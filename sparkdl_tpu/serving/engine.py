"""Continuous-batching generation engine — in-flight batching over the
slotted KV cache (ISSUE 8 tentpole).

The static ``models.llama.generate`` path is batch-job shaped: every row
of a batch prefills together, decodes in lockstep, and a new request
waits for the whole batch to drain. This module is the request-level
tier on top of the same two compiled programs' *slot* variants
(``models.llama.prefill_into_slot`` / ``slot_decode_step``): a request
queue with admission control feeds a fixed table of ``num_slots`` cache
slots, each independently holding one in-flight request. Every engine
iteration:

1. finished slots (EOS / max-tokens) are **retired** and their requests
   completed;
2. free slots are **admitted** from the queue and their prompts
   consumed — stall-free by default (``SPARKDL_SERVE_STALL_FREE=1``):
   at most ONE fixed-size chunk (``SPARKDL_SERVE_PREFILL_CHUNK``
   tokens) of at most one PREFILLING slot runs per iteration,
   interleaved with everyone else's decode, so a long prompt never
   preempts the decode batch for a whole O(L²) prefill (the blocking
   whole-prompt refill is the ``=0`` fallback); prompts that share a
   cached prefix copy those K/V rows device-side and chunk-prefill only
   the tail (``serving.prefix.PrefixCache``,
   ``SPARKDL_SERVE_PREFIX_CACHE_MB``);
3. one **decode step** advances every RUNNING slot one token at its own
   fill index — compiled once per (num_slots, max_len), never re-traced
   by refills or mid-prefill neighbors, so the batch never drains and
   aggregate tokens/s is bounded by compute, not by the longest request
   in a batch. ``serve_decode_stall`` accounting (engine stats,
   telemetry counter + histogram, and a flight-recorder span teed into
   ``StageAccountant``) records exactly how much wall time RUNNING
   slots spent not decoding while prefill work ran.

**Paged KV (ISSUE 11).** A backend with ``paged = True`` (the block-
table backends over one shared K/V pool) changes three scheduler
rules: admission additionally requires the pool to cover the prompt's
blocks + one decode block (a queue head it cannot cover WAITS, FIFO —
``admission_block_waits``); decode growth allocates blocks lazily at
each slot's write frontier, and a slot the pool cannot serve sits the
iteration out (``block_stall_events``) — only when EVERY running slot
stalls is the newest request preempted (released + requeued to resume
as ``prompt + tokens-so-far``; greedy output unchanged, nothing
re-emitted); and the per-iteration prefill pacing generalizes from one
chunk to a TOKEN budget (``SPARKDL_SERVE_PREFILL_BUDGET``) spent
round-robin oldest-first across every PREFILLING slot, so one
iteration can complete several refills — the admission-rate unlock
high-churn mixes need. Exhaustion is always backpressure:
``RequestRejected`` fires only for requests that can NEVER fit.

**Speculative decoding (ISSUE 12).** ``SPARKDL_SERVE_SPEC_K`` > 0
replaces each decode iteration with draft → verify → commit: a
jax-free ``serving.draft`` provider proposes up to k candidate tokens
per RUNNING slot (n-gram prompt-lookup by default; REST-style
retrieval over completed requests; or a registry-paired draft model),
ONE batched verify dispatch (``backend.verify`` — the fourth jitted
slot primitive) checks them all, and the engine commits the longest
draft prefix the target's greedy argmax agrees with plus the target's
own next token — always >= 1 token per slot per iteration, so
speculation can never emit below the k=0 baseline. Reject is a pure
frontier non-advance (misspeculated rows are garbage past the write
frontier — the chunked-prefill invariant), acceptance compares
argmaxes so the stream stays bit-identical to static ``generate()``
(greedy-only; sampling backends degrade to k=0 with a warning), and
k=0 is the EXACT pre-speculation engine.

Design split: this module is **jax-free** — the scheduler, queue, slot
table, request state machine, streaming callbacks, and failure policy
are all plain Python against a duck-typed backend (``prefill(slot,
prompt, bucket) -> first_token``, ``step(active_slots) -> tokens [num_
slots]``), so the whole scheduling layer unit-tests without a device
(``serving.paging`` — allocator, block manager, radix trie — is
jax-free too, and ``StubBackend`` mirrors the full paged protocol).
The jax half is ``serving.backend.LlamaSlotBackend`` (lazily imported
by :meth:`GenerationEngine.from_model`); :class:`StubBackend` here is
the deterministic jax-free stand-in the scheduler tests and the
backend-outage bench leg ride.

Failure semantics (the PR 4 posture, request-granular): a prompt that
fails admission is **rejected** synchronously (``RequestRejected`` /
``QueueFullError`` — backpressure, the caller owns retry); a request
whose prefill raises is retried ``SPARKDL_SERVE_RETRIES`` times and
then **quarantined** (request failed, engine keeps serving — the
poisoned request is evicted, not the gang); a decode-step failure is
retried, then the newest-admitted request (the state-change suspect) is
evicted and quarantined and the step retried again — down to an empty
slot table if need be, the engine staying alive for the queue (a
genuinely broken backend degrades per-request, each refill burning its
own retry budget, never gang-fatally). ``SPARKDL_SERVE_STALL_S`` arms a
wall-clock watchdog on every backend call — a wedged device surfaces as
a classified ``ServingStallError`` instead of an eternal hang.

**Failover (ISSUE 19).** A serving-fatal error (``SlotCacheLost`` — a
jitted slot call died after consuming its donated cache — or a stall-
watchdog fire) no longer kills the engine: every live request is
snapshotted host-side (prompt + tokens-so-far, all already jax-free
``Request`` state), the backend is torn down and rebuilt
(``backend.rebuild()`` — fresh slot cache / paged pool / prefix trie),
and the snapshots re-admit through the preemption-resume path with
exactly-once delivery: streamed tokens are never re-emitted (the
per-request ``delivered`` cursor survives the failover) and greedy
output is bit-identical to an uninterrupted run. Zero-progress
failovers in a row are bounded by ``SPARKDL_SERVE_FAILOVER_BUDGET``
(exponential backoff via ``SPARKDL_SERVE_FAILOVER_BACKOFF_S``); past
the engine budget the engine fails closed with the original cause, and
a single request that personally survives ``budget`` failovers without
gaining a token is quarantined individually instead of blocking the
fleet. Requests also carry **deadlines** (``deadline_s`` on
``submit()``, default ``SPARKDL_SERVE_DEADLINE_S``) and support
**cancellation** (``Request.cancel()``): both are honored at the next
iteration boundary — during prefill, decode, or mid-verify-window —
freeing the slot and its KV blocks (no radix entry is ever committed
for an aborted prefill). ``engine.drain()`` is the graceful-handoff
primitive: stop admission, preempt live requests into resumable
snapshots, and return them (``engine.resume(req)`` re-admits one); a
drain wedged past ``SPARKDL_SERVE_STALL_S`` degrades to
snapshot-and-stop instead of hanging the caller.

Observability: per-request ``serve_queue`` / ``serve_prefill`` /
``serve_decode`` spans through the flight recorder, and (when the
telemetry plane is armed) ``serving_queue_depth`` / ``serving_slots_
busy`` gauges, token/request counters, and request-latency + TTFT
histograms — the serving bench derives its latency percentiles from
those histograms via :func:`runner.telemetry.histogram_quantile`.
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import threading
import time

from ..runner import chaos as chaos_lib
from ..runner import events, telemetry
from ..runner import sentinel as sentinel_lib
from .introspect import register_engine
from .paging import BlockExhausted

__all__ = [
    "GenerationEngine", "Request", "StubBackend", "bucket_length",
    "ServingError", "RequestRejected", "QueueFullError",
    "RequestQuarantined", "ServingStallError", "EngineStopped",
    "RequestCancelled", "DeadlineExceeded",
    "PREFILLING", "BlockExhausted", "REQUEST_SCOPED_EVENTS",
    "ENGINE_SCOPED_EVENTS",
]

log = logging.getLogger("sparkdl_tpu.serving")

SLOTS_ENV = "SPARKDL_SERVE_SLOTS"
MAX_LEN_ENV = "SPARKDL_SERVE_MAX_LEN"
QUEUE_CAP_ENV = "SPARKDL_SERVE_QUEUE_CAP"
RETRIES_ENV = "SPARKDL_SERVE_RETRIES"
STALL_ENV = "SPARKDL_SERVE_STALL_S"
MIN_BUCKET_ENV = "SPARKDL_SERVE_MIN_BUCKET"
CHUNK_ENV = "SPARKDL_SERVE_PREFILL_CHUNK"
STALL_FREE_ENV = "SPARKDL_SERVE_STALL_FREE"
# ISSUE 11 — paged KV + multi-chunk prefill budgets. PREFILL_CHUNK
# stays the per-CHUNK size (one jitted call's token count);
# PREFILL_BUDGET owns admission pacing: tokens of prefill work per
# engine iteration, spread round-robin (oldest admitted first) across
# every PREFILLING slot. Default = one chunk — exact PR 9 behavior.
PREFILL_BUDGET_ENV = "SPARKDL_SERVE_PREFILL_BUDGET"
BLOCK_SIZE_ENV = "SPARKDL_SERVE_BLOCK_SIZE"
KV_POOL_MB_ENV = "SPARKDL_SERVE_KV_POOL_MB"
# ISSUE 12 — speculative decoding. SPEC_K is the draft window: 0 (the
# default) disables speculation entirely — the exact PR 11 decode
# path; k > 0 replaces each decode iteration with draft -> one batched
# verify -> greedy commit (always >= 1 token per slot per iteration).
# SPEC_DRAFT names the draft provider (serving.draft.make_provider).
SPEC_K_ENV = "SPARKDL_SERVE_SPEC_K"
# ISSUE 14 — tensor-parallel serving. TP is the mesh extent one engine
# spans: 1 (the default) constructs the EXACT single-device backends
# (no mesh, no wrapper, zero overhead); > 1 selects the head-sharded
# TensorParallel* backends whose weights/KV shard over Mesh(('tp',))
# while this scheduler stays byte-for-byte unchanged. The launcher's
# topology-aware placement gives each gang rank a disjoint device
# group (SPARKDL_TP_DEVICE_OFFSET / per-rank visibility).
TP_ENV = "SPARKDL_SERVE_TP"
# ISSUE 18 — quantized serving. KV_DTYPE selects the paged pool's K/V
# storage ("int8" / "fp8"): codes + a per-block [P, Hkv, 2] scale
# plane, dequantized inside the paged flash-decode kernel — no
# dequantized cache copy ever lands in HBM. Only meaningful with the
# paged backend (SPARKDL_SERVE_BLOCK_SIZE > 0); setting it without
# paging raises — a quantization request silently served at f32 is a
# 4x memory surprise. WEIGHT_DTYPE ("int8") quantizes the Megatron-
# sharded projection matmuls (absmax per-output-channel scales,
# dequant folded after the int8 dot); works on paged and un-paged,
# tp or single-device backends alike.
KV_DTYPE_ENV = "SPARKDL_SERVE_KV_DTYPE"
WEIGHT_DTYPE_ENV = "SPARKDL_SERVE_WEIGHT_DTYPE"
# ISSUE 19 — serving survivability. FAILOVER_BUDGET bounds CONSECUTIVE
# zero-progress failovers (any token emitted engine-wide resets the
# streak — supervise()'s restart-budget rule); past it the engine fails
# closed with the original cause. FAILOVER_BACKOFF_S is the base of the
# exponential sleep before each rebuild (0 = none, the test/CI
# default). DEADLINE_S is the default per-request deadline applied at
# submit() when the caller passes none (0/unset = no deadline).
FAILOVER_BUDGET_ENV = "SPARKDL_SERVE_FAILOVER_BUDGET"
FAILOVER_BACKOFF_ENV = "SPARKDL_SERVE_FAILOVER_BACKOFF_S"
DEADLINE_ENV = "SPARKDL_SERVE_DEADLINE_S"

_DEFAULT_SLOTS = 8
_DEFAULT_MAX_LEN = 2048
_DEFAULT_QUEUE_CAP = 128
_DEFAULT_RETRIES = 1
_DEFAULT_MIN_BUCKET = 16
_DEFAULT_CHUNK = 32
_DEFAULT_FAILOVER_BUDGET = 3
# Block-allocation-latency-shaped bounds (seconds): a free-list pop is
# microseconds; radix-eviction reclaims and CoW copies push into the
# ms range — the histogram's job is to show when allocation stops
# being free.
_ALLOC_BUCKETS = (1e-5, 5e-5, 2e-4, 1e-3, 5e-3, 0.02, 0.1, 0.5)

# Request-latency-shaped histogram bounds (seconds). The telemetry
# default buckets top out at 10s (span-duration-shaped) — a long-tail
# generation easily waits + decodes past that, and the quantile helper
# clamps +Inf-bucket ranks to the last finite bound, which would
# silently saturate the bench's p95/p99 at 10.0.
_LATENCY_BUCKETS = (0.005, 0.02, 0.05, 0.1, 0.15, 0.25, 0.35, 0.5,
                    0.75, 1.0, 1.5, 2.5, 5.0, 10.0, 30.0, 60.0, 180.0,
                    600.0)
# Decode-stall-shaped bounds: one stall event is one prefill (chunk or
# whole prompt) that ran while RUNNING slots waited — sub-ms on a stub,
# tens of ms per chunk on a real model, whole-prompt seconds on the
# blocking path. The histogram's job is exactly to show that shape
# difference between SPARKDL_SERVE_STALL_FREE=1 and =0.
_STALL_BUCKETS = (0.0005, 0.002, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5,
                  1.0, 2.5, 10.0)


def _env_num(name: str, default, cast=int):
    try:
        return cast(os.environ[name])
    except (KeyError, ValueError):
        return default


def scrub_serving_env(env: dict | None = None) -> dict:
    """Remove every serving knob (``SPARKDL_SERVE_*`` plus
    ``SPARKDL_TP_DEVICE_OFFSET``) from ``env`` — default the process
    environment — returning the removed entries so a caller can
    restore them. The ONE implementation of evidence hygiene for the
    tp bench leg, the MULTICHIP record script and the dryrun leg: an
    ambient ``SPARKDL_SERVE_KV_POOL_MB`` (a per-DEVICE budget) would
    size every tp degree's pool to ~equal device bytes and silently
    invert their 1/tp observable, and STALL_FREE/SPEC/PREFIX overrides
    would change which composition actually ran."""
    from ..runner.launcher import TP_OFFSET_ENV  # one shared definition
    target = os.environ if env is None else env
    removed = {}
    for k in list(target):
        if k.startswith("SPARKDL_SERVE_") or k == TP_OFFSET_ENV:
            removed[k] = target.pop(k)
    return removed


class ServingError(RuntimeError):
    """Base class for serving-tier failures."""


class RequestRejected(ServingError):
    """Admission control refused the request (invalid prompt, or the
    bucketed prompt + max_new_tokens cannot fit the slot cache)."""


class QueueFullError(ServingError):
    """Backpressure: the request queue is at capacity and the caller
    asked not to (or timed out waiting to) block."""


class RequestQuarantined(ServingError):
    """The request failed ``retries + 1`` attempts and was evicted; the
    engine keeps serving the other requests."""


class ServingStallError(ServingError):
    """A backend call exceeded ``SPARKDL_SERVE_STALL_S`` wall seconds."""


class EngineStopped(ServingError):
    """The engine stopped (or died) before this request completed."""


class RequestCancelled(ServingError):
    """The client cancelled the request (``Request.cancel()``); its
    slot and KV blocks were freed at the next iteration boundary."""


class DeadlineExceeded(ServingError):
    """The request's deadline (``deadline_s`` at submit, or the
    ``SPARKDL_SERVE_DEADLINE_S`` default) passed before completion."""


class SnapshotIncompatibleError(ServingError):
    """A resume snapshot failed validation (unknown version, missing
    fields, or an inconsistent delivery cursor) — rejected BEFORE it
    can corrupt a slot. Fatal by taxonomy: replaying it elsewhere
    reproduces the same rejection."""


# Version tag on resume snapshots (ISSUE 20): bump when the snapshot
# shape changes so a stale/foreign snapshot raises
# :class:`SnapshotIncompatibleError` instead of corrupting a slot.
SNAPSHOT_VERSION = 1


def bucket_length(prompt_len: int, min_bucket: int = _DEFAULT_MIN_BUCKET
                  ) -> int:
    """Prefill bucket for a prompt: the next power of two >=
    max(prompt_len, min_bucket). Every distinct bucket is one compiled
    prefill program, so the program count is bounded by
    log2(max_len / min_bucket) + 1 — a mixed-length request stream
    compiles a handful of prefills and then never re-traces."""
    if prompt_len < 1:
        raise ValueError("prompt must hold at least one token")
    b = max(1, min_bucket)
    while b < prompt_len:
        b <<= 1
    return b


# Every serve_* span/event the engine emits is classified here (ISSUE
# 13): REQUEST-scoped emissions carry ``request=<id>`` — the trace
# collector folds them into per-request records and SILENTLY degrades
# for any that drop the attribution, so a drift-guard test pins that
# (a) any serve_* name the engine emits appears in exactly one of
# these sets and (b) every REQUEST-scoped record carries ``request=``.
# ENGINE-scoped emissions describe the engine as a whole (a rejection
# happens before a Request exists; a step retry is not attributable to
# one request until eviction names a suspect; stall/draft spans cover
# all slots of an iteration).
REQUEST_SCOPED_EVENTS = frozenset({
    "serve_queue", "serve_prefill", "serve_decode",
    "serve_prefill_retry", "serve_prefill_chunk_retry",
    "serve_reserve_retry", "serve_prefix_seed_failed",
    "serve_request_quarantined", "serve_request_preempted",
    "serve_admission_block_wait", "serve_request",
    "serve_request_failover", "serve_request_cancelled",
})
ENGINE_SCOPED_EVENTS = frozenset({
    "serve_reject", "serve_step_retry", "serve_decode_stall",
    "serve_draft", "serve_engine_fatal", "serve_engine_failover",
    "serve_engine_drain",
})


def _req_trace(req: "Request") -> dict:
    """Causal-trace kwargs for a request-scoped emission (ISSUE 17):
    parent it under the request's admission (``serve_request``) span so
    the whole lifecycle — queue wait, prefill chunks, preemptions, the
    final decode span — chains to one node under the run root. {} when
    tracing is off, keeping untraced streams byte-identical."""
    sid = getattr(req, "span_id", None)
    return {"parent_id": sid} if sid else {}

# Request lifecycle states (plain strings — they serialize into events
# and stats as-is). PREFILLING is the stall-free scheduler's state: the
# request owns a slot and its prompt is being consumed chunk by chunk,
# interleaved with the other slots' decode steps.
QUEUED = "queued"
PREFILLING = "prefilling"
RUNNING = "running"
DONE = "done"
FAILED = "failed"


class Request:
    """One in-flight generation request: the handle ``submit`` returns.

    ``tokens`` grows as the engine emits (``stream_cb(request, token)``
    fires per token, in emission order, from the engine thread);
    ``result()`` blocks until retirement and returns the generated
    tokens (prompt excluded; the EOS token, when hit, is included —
    exactly ``generate()``'s contract).
    """

    def __init__(self, rid: int, prompt, max_new_tokens: int, bucket: int,
                 stream_cb=None):
        self.id = rid
        self.prompt = list(prompt)
        self.max_new_tokens = max_new_tokens
        self.bucket = bucket
        self.stream_cb = stream_cb
        self.tokens: list[int] = []
        self.state = QUEUED
        self.finish_reason: str | None = None   # eos | length | error
        self.error: BaseException | None = None
        self.failures = 0
        self.slot: int | None = None
        self.t_submit = time.time()
        self.t_admit: float | None = None
        self.t_first_token: float | None = None
        self.t_done: float | None = None
        # chunked (stall-free) prefill plan — filled at admission
        self.chunk_plan: list | None = None  # [(tokens[C], n_valid), ...]
        self.chunk_base = 0       # cache offset of chunk 0 (prefix reuse)
        self.next_chunk = 0       # committed chunks resume from here
        self.prefill_reused = 0   # prefix-cache tokens skipped
        self.prefill_spent_s = 0.0
        # paged mode: the slot's write frontier (next decode write
        # position — drives lazy block growth), preemption count, and
        # the length actually prefilled (prompt + already-generated
        # tokens after a preemption resume)
        self.write_pos = 0
        self.preemptions = 0
        self.served_len = len(self.prompt)
        self._block_stalled = False
        # Survivability (ISSUE 19): the exactly-once delivery cursor
        # (== len(tokens); host-side, so it survives a backend rebuild
        # — the failover audit's ground truth), consecutive failovers
        # this request survived WITHOUT gaining a token (progress
        # resets it; past the engine budget the request is quarantined
        # individually), and the deadline/cancel flags the engine
        # honors at the next iteration boundary.
        self.delivered = 0
        self.failovers = 0
        self._len_at_failover: int | None = None
        self.t_deadline: float | None = None
        self._cancel = False
        # request-scoped phase ledger (ISSUE 13): the trace collector
        # reads these off the serve_decode span at retirement —
        # t_enqueue starts the CURRENT queued stint (reset on requeue,
        # so a preempted request's serve_queue spans each measure their
        # own wait instead of everything since submit)
        self.t_enqueue = self.t_submit
        self.draft_s = 0.0
        self.block_stall_s = 0.0
        self.spec_windows = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self._block_stall_t0: float | None = None
        # Trace context (ISSUE 17): the admission span this request's
        # serve_* emissions parent under. Minted at submit time on the
        # CALLER's thread, so the captured parent is the submitter's
        # enclosing span (or the env-shipped gang-attempt span) — the
        # engine loop's ambient context would be wrong for every request
        # but the one it is currently stepping.
        self.span_id: str | None = None
        self.parent_span: str | None = None
        if events.trace_armed():
            self.span_id = events.new_span_id()
            self.parent_span = events.current_span_id()
        self._done = threading.Event()

    # -- caller-side API --------------------------------------------------
    @property
    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self):
        """Ask the engine to abort this request (the client-disconnect
        primitive). Honored at the next iteration boundary — queued,
        PREFILLING, RUNNING, or mid-verify-window — freeing the slot
        and its KV blocks; ``result()`` then raises
        :class:`RequestCancelled`. Idempotent; a no-op once done."""
        self._cancel = True

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def snapshot(self) -> dict:
        """Self-contained, version-tagged resume state (ISSUE 20):
        everything a DIFFERENT engine needs to continue this request —
        prompt, emitted tokens, the exactly-once delivery cursor, and
        the generation params. Plain ints/lists, so it survives a
        process hop (a router's shadow state for an uncleanly dead
        replica is exactly this dict rebuilt host-side)."""
        return {
            "version": SNAPSHOT_VERSION,
            "id": self.id,
            "prompt": list(self.prompt),
            "tokens": list(self.tokens),
            "delivered": self.delivered,
            "max_new_tokens": self.max_new_tokens,
            "failovers": self.failovers,
        }

    def result(self, timeout: float | None = None) -> list[int]:
        """Generated token ids (prompt excluded). Raises the request's
        failure (``RequestQuarantined`` / ``EngineStopped`` / the
        backend error) when it did not complete."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} not done after "
                               f"{timeout}s")
        if self.state != DONE:
            raise self.error if self.error is not None else \
                ServingError(f"request {self.id} ended in state "
                             f"{self.state}")
        return list(self.tokens)

    def __repr__(self):
        return (f"Request(id={self.id}, state={self.state}, "
                f"n_prompt={len(self.prompt)}, n_out={len(self.tokens)})")


class StubBackend:
    """Deterministic jax-free backend: scheduler tests and the
    backend-outage bench leg measure queue/slot mechanics (and raw
    scheduler throughput) without a device.

    Token stream per request: a fold over the SERVED sequence —
    ``v = sum(served) + len(served)`` after prefill, each emission
    ``tok = (seed + v·31) % vocab_size`` then ``v += tok + 1`` — so the
    stream is deterministic in the prompt alone AND resume-consistent:
    prefilling ``prompt + tokens-so-far`` (the preemption/failover
    resume) lands the chain on exactly the state an uninterrupted run
    would hold, so two runs of the same workload emit identical streams
    regardless of slot placement, chunking, prefix reuse, preemption or
    failover (the CPU llama tests carry the real equivalence proof).
    The mod-``vocab_size`` dynamics stay eventually periodic, so a
    small vocab still yields the repetitive, n-gram-predictable text
    the speculative legs ride. ``step_s``/``prefill_s``/``prefill_tok_s`` add
    synthetic per-call latency (bench shaping): a blocking prefill
    costs ``prefill_s + prefill_tok_s·bucket``, one chunk costs
    ``prefill_s + prefill_tok_s·C`` — per-token cost models the real
    O(tokens) device work, so prefix-cache reuse (fewer tail tokens)
    and bucket padding (blocking pads to the power-of-two bucket)
    show up in stub wall time exactly as they do on hardware.

    Mirrors the full chunked protocol (``begin_prefill`` /
    ``prefill_chunk`` / ``finish_prefill``) and the shared-prefix LRU
    (:class:`serving.prefix.PrefixCache` with synthetic
    ``prefix_bytes_per_token`` entry sizes) jax-free, so the scheduler
    logic — including hit/evict accounting — is tier-1-testable."""

    def __init__(self, num_slots: int, max_len: int, *,
                 vocab_size: int = 32000, step_s: float = 0.0,
                 prefill_s: float = 0.0, prefill_tok_s: float = 0.0,
                 seed: int = 0, prefix_cache_bytes: int | None = None,
                 prefix_bytes_per_token: int = 1024,
                 block_size: int | None = None,
                 pool_blocks: int | None = None,
                 spec_tok_s: float = 0.0):
        from .prefix import PrefixCache, prefix_cache_budget_bytes
        self.num_slots = num_slots
        self.max_len = max_len
        self.vocab_size = vocab_size
        self.step_s = step_s
        self.prefill_s = prefill_s
        self.prefill_tok_s = prefill_tok_s
        self.spec_tok_s = spec_tok_s
        self.seed = seed
        self.prefix_bytes_per_token = int(prefix_bytes_per_token)
        # (prompt_key, n_emitted, chain) — key is the served prompt's
        # sum+len (kept for test hooks), chain drives the token fold
        self._state = [(0, 0, 0)] * num_slots
        budget = prefix_cache_budget_bytes() if prefix_cache_bytes is None \
            else max(0, int(prefix_cache_bytes))
        # Paged mirror (ISSUE 11): block_size arms the SAME
        # PagedBlockManager the llama backend rides — slot block lists,
        # radix grafts, CoW and release bookkeeping are the one shared
        # implementation, only the K/V bytes are absent. The byte-
        # payload PrefixCache is replaced by the manager's radix trie.
        self.paged = bool(block_size)
        if self.paged:
            from .paging import PagedBlockManager
            self.mgr = PagedBlockManager(num_slots, max_len, block_size,
                                         pool_blocks, radix=budget > 0)
            self.block_size = self.mgr.block_size
            self.max_blocks = self.mgr.max_blocks
            self.max_len = self.mgr.max_len
            self.pool_blocks = self.mgr.pool_blocks
            self.allocator = self.mgr.allocator
            self.prefix_cache = None
        else:
            self.prefix_cache = PrefixCache(budget) if budget > 0 else None

    def _tok(self, key: int, n: int) -> int:
        """Emission hook: ``key`` is the fold-chain value at this
        position (== sum+len of everything served so far), ``n`` the
        emission index since the last prefill — the default ignores
        ``n`` so resumes (which reset it) stay stream-identical."""
        return (self.seed + key * 31) % self.vocab_size

    def _emit(self, slot: int):
        """Advance the slot's fold chain one token."""
        key, n, v = self._state[slot]
        tok = self._tok(v, n)
        self._state[slot] = (key, n + 1, v + tok + 1)
        return tok

    def prefill(self, slot: int, prompt, bucket: int) -> int:
        if self.paged:
            self.mgr.reserve_bucket(slot, bucket)  # BlockExhausted OK
        if self.prefill_s or self.prefill_tok_s:
            time.sleep(self.prefill_s + self.prefill_tok_s * bucket)
        key = sum(prompt) + len(prompt)
        self._state[slot] = (key, 0, key)
        return self._emit(slot)

    # -- chunked (stall-free) protocol, mirroring LlamaSlotBackend --------
    def begin_prefill(self, slot: int, prompt, chunk: int) -> int:
        from .prefix import usable_reuse
        self._state[slot] = (0, 0, 0)
        if self.paged:
            return self.mgr.reserve_prompt(slot, prompt, chunk)
        if self.prefix_cache is None:
            return 0
        key, n_cached, _payload = self.prefix_cache.lookup(prompt)
        reuse = usable_reuse(n_cached, len(prompt), chunk)
        if reuse <= 0:
            self.prefix_cache.note_miss()
            return 0
        self.prefix_cache.use(key, reuse)
        return reuse

    def prefill_chunk(self, slot: int, chunk_tokens, offset: int,
                      n_valid: int, window: int | None = None) -> int:
        if self.prefill_s or self.prefill_tok_s:
            time.sleep(self.prefill_s
                       + self.prefill_tok_s * len(chunk_tokens))
        return 0  # the engine reads the first token from finish_prefill

    def finish_prefill(self, slot: int, prompt, last_tok: int,
                       aligned_len: int, commit: bool = True) -> int:
        key = sum(prompt) + len(prompt)
        self._state[slot] = (key, 0, key)
        if commit:
            # Commit failures degrade (the entry just isn't cached) —
            # unless serving-fatal (injected cache_lost): that means
            # the slot state itself is gone and the engine must fail
            # over, exactly the llama backends' posture.
            try:
                chaos_lib.fire("serve_commit", batch=slot)
                if self.paged:
                    self.mgr.commit(slot, prompt)
                elif self.prefix_cache is not None:
                    self.prefix_cache.put(
                        tuple(prompt), tuple(prompt),
                        len(prompt) * self.prefix_bytes_per_token)
            except Exception as e:  # noqa: BLE001 — degrade, not fail
                if getattr(e, "serving_fatal", False):
                    raise
                log.warning("stub prefix commit failed (slot %s): %s",
                            slot, e)
        return self._emit(slot)

    def prefix_stats(self) -> dict | None:
        if self.paged:
            return self.mgr.prefix_stats()
        return None if self.prefix_cache is None else \
            self.prefix_cache.stats()

    # -- paged protocol (bookkeeping only — no K/V bytes) -----------------
    def can_reserve(self, n: int) -> bool:
        return self.mgr.can_reserve(n)

    def ensure_block_for(self, slot: int, pos: int) -> bool:
        return self.mgr.ensure_block_for(slot, pos)

    def pool_stats(self) -> dict:
        return self.mgr.pool_stats()

    def drain_alloc_samples(self) -> list[float]:
        return self.mgr.drain_alloc_samples()

    def release(self, slot: int):
        if self.paged:
            self.mgr.release(slot)
        self._state[slot] = (0, 0, 0)

    def rebuild(self):
        """Failover hook (ISSUE 19): discard every slot's chain state
        and rebuild the paged pool / prefix trie from scratch — the
        jax-free mirror of the llama backends' cache teardown."""
        self._state = [(0, 0, 0)] * self.num_slots
        if self.paged:
            from .paging import PagedBlockManager
            radix = self.mgr.radix is not None
            self.mgr = PagedBlockManager(self.num_slots, self.max_len,
                                         self.block_size,
                                         self.pool_blocks, radix=radix)
            self.allocator = self.mgr.allocator
        elif self.prefix_cache is not None:
            self.prefix_cache.clear()

    def step(self, active_slots) -> list[int]:
        if self.step_s:
            time.sleep(self.step_s)
        out = [0] * self.num_slots
        for s in active_slots:
            out[s] = self._emit(s)
        return out

    # -- speculative verify protocol (ISSUE 12), mirrored jax-free --------
    def verify(self, active_slots, drafts, k: int) -> list[list[int]]:
        """One verify window: proposal ``i`` of slot ``s`` is the token
        the stub's deterministic stream emits after ``i`` accepted
        drafts — position-determined, independent of the drafts
        themselves, exactly the greedy-target contract (a draft is
        accepted iff it equals the stream). Costs ONE step_s sleep
        (+ ``spec_tok_s`` per draft column — the marginal verify-width
        device time), so the k=0-vs-k speedup the bench measures is
        dispatch economics, the thing speculation actually buys."""
        if self.step_s or (self.spec_tok_s and k):
            time.sleep(self.step_s + self.spec_tok_s * k)
        out = [[0] * (k + 1) for _ in range(self.num_slots)]
        for s in active_slots:
            key, n, v = self._state[s]
            row = []
            for i in range(k + 1):
                tok = self._tok(v, n + i)
                row.append(tok)
                v += tok + 1
            out[s] = row
        return out

    def commit_spec(self, slot: int, n_tokens: int, last_tok: int):
        """Advance the slot's stream past ``n_tokens`` committed
        positions (reject = simply not advancing)."""
        for _ in range(int(n_tokens)):
            self._emit(slot)


class GenerationEngine:
    """Iteration-level scheduler over a slot backend (see module doc).

    Drive it inline (``step()`` / ``run_until_idle()`` — tests, batch
    drains) or as a background thread (``start()`` / ``stop()``, or the
    context manager). ``submit()`` is thread-safe and applies admission
    control synchronously.
    """

    def __init__(self, backend, *, eos_id: int | None = None,
                 queue_capacity: int | None = None,
                 retries: int | None = None,
                 stall_s: float | None = None,
                 min_bucket: int | None = None,
                 stall_free: bool | None = None,
                 prefill_chunk: int | None = None,
                 prefill_budget: int | None = None,
                 spec_k: int | None = None,
                 draft_provider=None,
                 failover_budget: int | None = None,
                 failover_backoff_s: float | None = None,
                 deadline_s: float | None = None):
        self.backend = backend
        self.eos_id = eos_id
        # Paged backend (ISSUE 11): admission additionally gates on KV-
        # pool blocks, decode growth allocates lazily, exhaustion
        # backpressures (the request waits) instead of crashing.
        self.paged = bool(getattr(backend, "paged", False))
        # Tensor-parallel degree + per-device KV-pool bytes (ISSUE 14):
        # both are engine-lifetime constants (the cache's shapes and
        # placement never change), so read them once here and export
        # them as gauges each iteration when the plane is armed.
        self.tp_degree = int(getattr(backend, "tp_degree", 1) or 1)
        kb = getattr(backend, "kv_pool_device_bytes", None)
        try:
            self.kv_pool_device_bytes = int(kb()) if callable(kb) else None
        except Exception:  # noqa: BLE001 — accounting, never fatal
            self.kv_pool_device_bytes = None
        # Stall-free scheduling (SPARKDL_SERVE_STALL_FREE, default on):
        # prompts are consumed in fixed-size chunks interleaved with the
        # decode step instead of blocking it for a whole O(L^2) prefill.
        # Requires the backend to speak the chunked protocol; otherwise
        # fall back to the blocking path with a warning.
        want_sf = (os.environ.get(STALL_FREE_ENV, "1").lower()
                   not in ("0", "false")) if stall_free is None \
            else bool(stall_free)
        self.stall_free = want_sf and hasattr(backend, "prefill_chunk")
        if want_sf and not self.stall_free:
            log.warning("backend %s lacks the chunked prefill protocol; "
                        "falling back to blocking refills",
                        type(backend).__name__)
        self.prefill_chunk = max(1, prefill_chunk
                                 if prefill_chunk is not None
                                 else _env_num(CHUNK_ENV, _DEFAULT_CHUNK))
        self.prefill_chunk = min(self.prefill_chunk, backend.max_len)
        if self.paged:
            # Radix grafts are whole blocks and chunk plans start at
            # chunk multiples: align the chunk to the block size so a
            # block-aligned reuse offset is always plan-legal.
            bs = int(backend.block_size)
            self.prefill_chunk = max(bs, (self.prefill_chunk // bs) * bs)
        # The per-iteration prefill TOKEN budget (ISSUE 11): how many
        # prompt tokens may be consumed per engine iteration, spread one
        # chunk at a time round-robin (oldest admitted first) over every
        # PREFILLING slot. Default = one chunk — the exact PR 9 pacing;
        # raising it lets one iteration refill several slots, removing
        # the ~1 admission/iteration cap high-churn mixes starve under.
        self.prefill_budget = max(
            self.prefill_chunk,
            prefill_budget if prefill_budget is not None
            else _env_num(PREFILL_BUDGET_ENV, self.prefill_chunk))
        # Floor 1: capacity 0 would make every blocking submit() spin
        # forever on `len(queue) >= 0` with no exit condition.
        self.queue_capacity = max(1, queue_capacity
                                  if queue_capacity is not None
                                  else _env_num(QUEUE_CAP_ENV,
                                                _DEFAULT_QUEUE_CAP))
        self.retries = max(0, retries if retries is not None
                           else _env_num(RETRIES_ENV, _DEFAULT_RETRIES))
        self.stall_s = stall_s if stall_s is not None \
            else _env_num(STALL_ENV, 0.0, float)
        self.min_bucket = min_bucket if min_bucket is not None \
            else _env_num(MIN_BUCKET_ENV, _DEFAULT_MIN_BUCKET)
        # Survivability knobs (ISSUE 19): see the env-constant comments.
        self.failover_budget = max(0, failover_budget
                                   if failover_budget is not None
                                   else _env_num(FAILOVER_BUDGET_ENV,
                                                 _DEFAULT_FAILOVER_BUDGET))
        self.failover_backoff_s = max(0.0, failover_backoff_s
                                      if failover_backoff_s is not None
                                      else _env_num(FAILOVER_BACKOFF_ENV,
                                                    0.0, float))
        self.default_deadline_s = max(0.0, deadline_s
                                      if deadline_s is not None
                                      else _env_num(DEADLINE_ENV, 0.0,
                                                    float))
        # Speculative decode (ISSUE 12): k = 0 (default) is the EXACT
        # PR 11 path — no draft provider, no verify program, nothing
        # speculation-shaped runs. k > 0 requires the backend's verify
        # protocol AND greedy sampling (acceptance compares argmaxes;
        # a sampling engine silently degrading to different draws
        # would break the determinism contract, so it degrades to
        # k = 0 with a warning instead).
        self.spec_k = max(0, spec_k if spec_k is not None
                          else _env_num(SPEC_K_ENV, 0))
        self._draft = None
        if self.spec_k > 0:
            greedy = float(getattr(backend, "temperature", 0.0)
                           or 0.0) <= 0.0
            if not hasattr(backend, "verify"):
                log.warning("backend %s lacks the speculative verify "
                            "protocol; running without speculation",
                            type(backend).__name__)
                self.spec_k = 0
            elif not greedy:
                log.warning("speculative decode is greedy-only "
                            "(acceptance = argmax agreement); backend "
                            "samples at temperature > 0 — running "
                            "without speculation")
                self.spec_k = 0
            else:
                from .draft import make_provider
                self._draft = draft_provider if draft_provider \
                    is not None else make_provider()
        # k+1 accept-length buckets (1..k+1 emitted per verify window)
        self._spec_buckets = tuple(
            float(i) for i in range(1, self.spec_k + 2)) or None
        self._queue: collections.deque[Request] = collections.deque()
        self._slots: list[Request | None] = [None] * backend.num_slots
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._ids = itertools.count()
        self._thread: threading.Thread | None = None
        self._stop_mode: str | None = None  # None | "drain" | "now"
        self._fatal: BaseException | None = None
        self._watch_pool = None  # lazy ThreadPoolExecutor(1) when stall_s
        # Failover supervisor state (ISSUE 19): re-entrancy latch,
        # consecutive zero-progress streak, chaos/watchdog call counter,
        # the note the fail-closed EngineStopped carries, and the
        # operator-facing ledger introspect/snapshot expose.
        self._failing_over = False
        # Router-side liveness (ISSUE 20): stamped at every iteration
        # (and every idle wait) — a fleet router reads this to tell a
        # busy-but-advancing replica from a wedged one.
        self.t_heartbeat = time.time()
        self._failover_streak = 0
        self._tokens_at_failover = -1
        self._backend_calls = 0
        self._fatal_note: str | None = None
        self._awaiting_recovery = False
        self._t_fault: float | None = None
        self._failover_info: dict = {
            "state": "healthy", "count": 0, "streak": 0,
            "last_cause": None, "last_t": None, "resumed_total": 0,
            "quarantined_total": 0, "last_backoff_s": 0.0,
            "last_recovery_s": None,
        }
        self.stats = {
            "submitted": 0, "rejected": 0, "completed": 0,
            "quarantined": 0, "failed": 0, "tokens_out": 0, "steps": 0,
            "prefills": 0, "prefill_retries": 0, "step_retries": 0,
            "peak_queue_depth": 0, "peak_slots_busy": 0,
            "callback_errors": 0, "prefill_chunks": 0,
            "decode_stall_s": 0.0, "decode_stall_events": 0,
            # paged-mode ledger: iterations where the queue head waited
            # for pool blocks (admission backpressure), decode steps a
            # RUNNING slot sat out waiting for a growth block, and
            # preemptions (the deadlock-breaking requeue of the newest
            # request when EVERY running slot is block-stalled)
            "admission_block_waits": 0, "block_stall_events": 0,
            "preemptions": 0,
            # survivability ledger (ISSUE 19): engine failovers
            # survived, requests re-admitted / individually quarantined
            # across them, and deadline/cancel aborts (never counted
            # quarantined)
            "failovers": 0, "failover_resumed": 0,
            "failover_quarantined": 0, "cancelled": 0,
            # speculative-decode ledger (ISSUE 12): verify iterations,
            # draft tokens the target agreed with (each one a decode
            # dispatch saved) vs rejected (wasted draft+verify columns)
            "spec_verifies": 0, "spec_tokens_accepted": 0,
            "spec_tokens_rejected": 0,
        }
        # Live inspector (ISSUE 13): one weak-set add per engine BUILD
        # (never per token); /serving on the telemetry HTTP server
        # snapshots every registered engine via debug_state().
        register_engine(self)

    # -- construction -----------------------------------------------------
    @classmethod
    def from_model(cls, model, variables, *, num_slots: int | None = None,
                   max_len: int | None = None, temperature: float = 0.0,
                   top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                   eos_id: int | None = None,
                   prefix_cache_mb: float | None = None,
                   block_size: int | None = None,
                   pool_blocks: int | None = None,
                   kv_pool_mb: float | None = None,
                   tp: int | None = None, mesh=None,
                   kv_dtype: str | None = None,
                   weight_dtype: str | None = None,
                   **kw) -> "GenerationEngine":
        """Build an engine over :class:`serving.backend.LlamaSlotBackend`
        (the jax import happens here, not at module import).
        ``prefix_cache_mb`` overrides ``SPARKDL_SERVE_PREFIX_CACHE_MB``
        (0 disables shared-prefix KV reuse).

        ``block_size`` > 0 (or ``SPARKDL_SERVE_BLOCK_SIZE``) selects the
        PAGED backend (ISSUE 11): one shared K/V pool of ``pool_blocks``
        blocks (or ``kv_pool_mb`` / ``SPARKDL_SERVE_KV_POOL_MB``
        converted; default = the un-paged footprint) addressed through
        per-slot block tables, with block-granular radix prefix sharing
        instead of the copy-based LRU.

        ``tp`` > 1 (or ``SPARKDL_SERVE_TP``) spans the engine over a
        tensor-parallel mesh (ISSUE 14): head-sharded weights + KV
        cache/pool over ``tp`` devices (``mesh`` to supply one;
        otherwise ``serving.backend.tp_mesh`` builds it from the
        visible devices at ``SPARKDL_TP_DEVICE_OFFSET``). tp <= 1 is
        exactly the single-device path — same classes, same compiled
        signatures. Paged + tp makes ``kv_pool_mb`` a PER-DEVICE
        budget (each device holds 1/tp of every block).

        ``kv_dtype`` ("int8"/"fp8", or ``SPARKDL_SERVE_KV_DTYPE``)
        block-quantizes the paged K/V pool (ISSUE 18; paged only —
        raises otherwise); ``weight_dtype`` ("int8", or
        ``SPARKDL_SERVE_WEIGHT_DTYPE``) quantizes the projection
        weights on any backend."""
        num_slots = num_slots if num_slots is not None \
            else _env_num(SLOTS_ENV, _DEFAULT_SLOTS)
        max_len = max_len if max_len is not None \
            else _env_num(MAX_LEN_ENV, _DEFAULT_MAX_LEN)
        block_size = block_size if block_size is not None \
            else _env_num(BLOCK_SIZE_ENV, 0)
        tp_explicit = tp is not None
        if tp is None:
            raw = os.environ.get(TP_ENV)
            if raw in (None, ""):
                tp = 1
            else:
                tp_explicit = True  # the operator pinned a degree
                try:
                    tp = int(raw)
                except ValueError:
                    # Losing tensor parallelism silently means a model
                    # sized for tp chips quietly not fitting (or 1/tp
                    # the KV) — a malformed knob raises as loudly as a
                    # wrong one (the SPARKDL_SERVE_SPEC_DRAFT rule).
                    raise ValueError(
                        f"{TP_ENV}={raw!r} is not an integer") from None
        if tp is not None and int(tp) < 0:
            # Checked BEFORE the mesh branch: a negative explicit tp
            # alongside a mesh must not be silently overwritten by the
            # mesh extent — a sign bug raises like every other bad tp.
            raise ValueError(f"tp={tp} is negative (0/1 = single-device)")
        if mesh is not None:
            try:
                extent = 1
                for v in mesh.shape.values():
                    extent *= int(v)
            except Exception as e:
                raise ValueError(
                    "mesh= was given but its extent could not be read; "
                    "pass tp= explicitly") from e
            if not tp_explicit and (not tp or tp <= 1):
                # An explicitly passed mesh IS the tensor-parallel
                # request: infer the degree from its total extent
                # instead of silently dropping the mesh and building a
                # single-device engine with the full unsharded KV.
                # Only a DEFAULTED tp infers — an explicit tp=1 (arg
                # or SPARKDL_SERVE_TP=1, the pinned single-device
                # baseline) disagreeing with a multi-device mesh
                # raises below like every other mismatch.
                tp = extent
            elif int(tp) != extent:
                # A disagreeing pair would validate heads against tp
                # but shard over the mesh: per-device budget math and
                # the tp observables all report the wrong degree.
                raise ValueError(
                    f"tp={tp} disagrees with the passed mesh's "
                    f"{extent} device(s)")
        pbytes = None if prefix_cache_mb is None \
            else int(prefix_cache_mb * 2 ** 20)
        if kv_dtype is None:
            kv_dtype = os.environ.get(KV_DTYPE_ENV) or None
        if weight_dtype is None:
            weight_dtype = os.environ.get(WEIGHT_DTYPE_ENV) or None
        if kv_dtype and not (block_size and block_size > 0):
            # A quantized-KV request silently served from the un-paged
            # f32 cache is a 4x memory surprise AND a wrong-bench — the
            # malformed-knob posture raises instead.
            raise ValueError(
                f"{KV_DTYPE_ENV}={kv_dtype!r} requires the paged "
                f"backend ({BLOCK_SIZE_ENV} > 0); the un-paged cache "
                "has no quantized mode")
        # tp_kw's truthiness SELECTS the TensorParallel class — keep it
        # tp-only and carry weight_dtype in its own dict.
        tp_kw = {"tp": int(tp), "mesh": mesh} if tp and tp > 1 else {}
        wq_kw = {"weight_dtype": weight_dtype} if weight_dtype else {}
        if block_size and block_size > 0:
            from .backend import (PagedLlamaSlotBackend,
                                  TensorParallelPagedLlamaSlotBackend)
            kv_pool_mb = kv_pool_mb if kv_pool_mb is not None \
                else _env_num(KV_POOL_MB_ENV, None, float)
            klass = TensorParallelPagedLlamaSlotBackend if tp_kw \
                else PagedLlamaSlotBackend
            backend = klass(
                model, variables, num_slots, max_len,
                block_size=int(block_size), pool_blocks=pool_blocks,
                kv_pool_mb=kv_pool_mb, kv_dtype=kv_dtype,
                temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed,
                prefix_cache_bytes=pbytes, **tp_kw, **wq_kw)
        else:
            from .backend import (LlamaSlotBackend,
                                  TensorParallelLlamaSlotBackend)
            klass = TensorParallelLlamaSlotBackend if tp_kw \
                else LlamaSlotBackend
            backend = klass(
                model, variables, num_slots, max_len,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=seed, prefix_cache_bytes=pbytes, **tp_kw, **wq_kw)
        return cls(backend, eos_id=eos_id, **kw)

    # -- telemetry helpers ------------------------------------------------
    def _metric(self, kind: str, name: str, *args, buckets=None):
        if not telemetry.enabled():
            return
        reg = telemetry.registry()
        if kind == "counter":
            reg.counter(name).inc(*args)
        elif kind == "gauge":
            reg.gauge(name).set(*args)
        else:
            reg.histogram(name, buckets or _LATENCY_BUCKETS).observe(*args)

    def _note_stall(self, dt: float, n_running: int):
        """Account one prefill-induced decode stall: a prefill (whole
        prompt on the blocking path, one chunk on the stall-free path)
        ran for ``dt`` wall seconds while ``n_running`` RUNNING slots
        sat idle instead of decoding. The ``serve_decode_stall`` span
        tees into ``StageAccountant``/``bottleneck_report`` like every
        other stage, so the scheduler's before/after is provable from
        the flight recorder, not just the bench."""
        if n_running <= 0 or dt <= 0:
            return
        self.stats["decode_stall_s"] += dt
        self.stats["decode_stall_events"] += 1
        events.completed_span("serve_decode_stall", dt,
                              slots_waiting=n_running)
        self._metric("counter", "serving_decode_stall_s_total", dt)
        self._metric("histogram", "serve_decode_stall_s", dt,
                     buckets=_STALL_BUCKETS)

    # -- admission --------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 16, *,
               stream_cb=None, block: bool = True,
               timeout: float | None = None,
               deadline_s: float | None = None) -> Request:
        """Queue one request; returns its :class:`Request` handle.

        Admission control is synchronous: an invalid prompt (empty, or
        out-of-vocab ids when the backend knows its vocab) or one whose
        ``bucket + max_new_tokens`` cannot fit the slot cache raises
        :class:`RequestRejected`; a full queue blocks (``block=True``,
        up to ``timeout``) or raises :class:`QueueFullError` — that is
        the backpressure contract, the caller owns retry/shedding.

        ``deadline_s`` caps the request's total wall time from submit
        (default ``SPARKDL_SERVE_DEADLINE_S``; 0/None = no deadline):
        past it the engine aborts the request at the next iteration
        boundary, freeing its slot and KV blocks, and ``result()``
        raises :class:`DeadlineExceeded`.
        """
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            self._reject("empty prompt (needs >= 1 token id)")
        if max_new_tokens < 1:
            self._reject("max_new_tokens < 1")
        vocab = getattr(self.backend, "vocab_size", None)
        if vocab is not None and any(t < 0 or t >= vocab for t in prompt):
            # the poisoned-request fast path: a corrupt id would index
            # the embedding out of range (silently clamped on-device) —
            # reject at the door, with the offending id named
            bad = next(t for t in prompt if t < 0 or t >= vocab)
            self._reject(f"token id {bad} outside vocab [0, {vocab})")
        if self.stall_free:
            # Chunked placement is zero-aligned: the prompt writes rows
            # [0, ceil(L/C)*C) (pad tail included) and decode continues
            # from L — both ends must fit the slot row.
            c = self.prefill_chunk
            bucket = -(-len(prompt) // c) * c
            if max(bucket, len(prompt) + max_new_tokens) > \
                    self.backend.max_len:
                self._reject(
                    f"chunk-aligned prompt ({bucket}) + max_new_tokens "
                    f"({max_new_tokens}) exceeds max_len "
                    f"{self.backend.max_len}")
        else:
            bucket = bucket_length(len(prompt), self.min_bucket)
            if bucket + max_new_tokens > self.backend.max_len:
                self._reject(
                    f"bucketed prompt ({bucket}) + max_new_tokens "
                    f"({max_new_tokens}) exceeds max_len "
                    f"{self.backend.max_len}")
        if self.paged:
            # Reject only what can NEVER fit — a request whose lifetime
            # block footprint exceeds the whole pool would wait forever;
            # anything smaller waits for blocks (backpressure, below).
            # Chunked mode: only real rows need blocks (pad writes go
            # to the trash block); blocking mode writes the whole
            # left-padded bucket.
            bs = self.backend.block_size
            rows = len(prompt) + max_new_tokens if self.stall_free \
                else max(bucket, len(prompt) + max_new_tokens)
            # the +1 decode block caps at the slot row (a request
            # spanning the whole row grows no further)
            need = min(-(-rows // bs) + 1,
                       -(-self.backend.max_len // bs))
            total = self.backend.allocator.usable_blocks
            if need > total:
                self._reject(
                    f"request needs {need} KV blocks (block_size {bs}); "
                    f"the whole pool holds {total} — can never fit")
        deadline = None if timeout is None else time.time() + timeout
        with self._work:
            if self._stop_mode is not None or self._fatal is not None:
                raise EngineStopped("engine is stopped")
            while len(self._queue) >= self.queue_capacity:
                if not block:
                    self._reject_locked("queue_full", QueueFullError)
                remain = None if deadline is None \
                    else deadline - time.time()
                if remain is not None and remain <= 0:
                    self._reject_locked("queue_full_timeout",
                                        QueueFullError)
                if not self._work.wait(timeout=remain if remain is not None
                                       else 0.5):
                    if deadline is not None:
                        self._reject_locked("queue_full_timeout",
                                            QueueFullError)
                if self._stop_mode is not None or self._fatal is not None:
                    raise EngineStopped("engine is stopped")
            req = Request(next(self._ids), prompt, int(max_new_tokens),
                          bucket, stream_cb)
            limit = deadline_s if deadline_s is not None \
                else self.default_deadline_s
            if limit and limit > 0:
                req.t_deadline = req.t_submit + float(limit)
            self._queue.append(req)
            self.stats["submitted"] += 1
            depth = len(self._queue)
            if depth > self.stats["peak_queue_depth"]:
                self.stats["peak_queue_depth"] = depth
            self._work.notify_all()
        self._metric("gauge", "serving_queue_depth", depth)
        sentinel_lib.observe("queue_depth", float(depth))
        return req

    def _reject(self, reason: str, exc_type=RequestRejected):
        with self._lock:
            self._reject_locked(reason, exc_type)

    def _reject_locked(self, reason: str, exc_type=RequestRejected):
        """Caller holds the lock; raises after recording the rejection."""
        self.stats["rejected"] += 1
        events.event("serve_reject", reason=reason[:200])
        self._metric("counter", "serving_requests_rejected_total")
        raise exc_type(reason)

    # -- scheduling loop --------------------------------------------------
    def step(self) -> bool:
        """One scheduler iteration. Stall-free (default): admit queued
        requests into free slots, advance AT MOST ONE chunk of at most
        one PREFILLING slot, then advance every RUNNING slot one decode
        step — a long prompt is consumed interleaved with everyone
        else's decode instead of monopolizing the device. Blocking
        fallback (``SPARKDL_SERVE_STALL_FREE=0``): retire/refill free
        slots with whole-prompt prefills, then decode. Returns True when
        any work happened; False when idle — the inline-drive loop
        condition.

        Failover seam (ISSUE 19): a serving-fatal error or stall
        surfacing from ANY backend call inside the iteration is caught
        HERE — the single supervisor point — and routed through
        :meth:`_handle_fatal`; when the failover succeeds (backend
        rebuilt, live requests re-admitted) the iteration reports
        worked=True and serving continues."""
        if self._fatal is not None:
            raise EngineStopped("engine died") from self._fatal
        self.t_heartbeat = time.time()
        try:
            return self._step_inner()
        except Exception as e:  # noqa: BLE001 — failover routing
            if not (getattr(e, "serving_fatal", False)
                    or isinstance(e, ServingStallError)):
                raise  # scheduler bug etc: the old fail-everything path
            self._handle_fatal(e)
            if self._fatal is None:
                return True  # failed over: rebuilt + re-admitted
            raise

    def _step_inner(self) -> bool:
        worked = self._reap_cancelled()
        if self.stall_free:
            worked = self._admit() > 0 or worked
            worked = self._prefill_tick() or worked
        else:
            worked = self._refill() > 0 or worked
        with self._lock:
            busy = sum(r is not None for r in self._slots)
            active = [(s, r) for s, r in enumerate(self._slots)
                      if r is not None and r.state == RUNNING]
        if busy > self.stats["peak_slots_busy"]:
            self.stats["peak_slots_busy"] = busy
        self._metric("gauge", "serving_slots_busy", busy)
        self._metric("gauge", "serving_tp_degree", self.tp_degree)
        if self.kv_pool_device_bytes is not None:
            self._metric("gauge", "serving_kv_pool_device_bytes",
                         self.kv_pool_device_bytes)
        if self.paged:
            self._export_pool_metrics()
        if not active:
            return worked
        if self.paged:
            # Lazy decode growth: every RUNNING slot needs a writable
            # block at its frontier before it may step; a slot the pool
            # cannot serve sits this iteration out (backpressure, not a
            # crash), and if NOBODY can step the newest request is
            # preempted to break the deadlock.
            active = self._filter_block_stalled(active)
            if not active:
                return True
        if self.spec_k > 0 and self._spec_step(active):
            return True
        # k = 0, or a speculative iteration where NO slot drafted
        # anything: the plain decode step (flash-decode economics, no
        # wasted k+1-wide verify window)
        t0 = time.perf_counter() if sentinel_lib.armed() else None
        toks = self._step_with_isolation()
        if t0 is not None and toks is not None:
            sentinel_lib.observe("decode_step", time.perf_counter() - t0)
        if toks is not None:
            self.stats["steps"] += 1
            for slot, req in active:
                if req.state == RUNNING:  # not evicted mid-isolation
                    self._deliver(req, int(toks[slot]))
                    req.write_pos += 1
        return True

    # -- deadlines / cancellation (ISSUE 19) ------------------------------
    @staticmethod
    def _should_cancel(req: Request, now: float) -> bool:
        if req.state in (DONE, FAILED):
            return False
        return req._cancel or (req.t_deadline is not None
                               and now >= req.t_deadline)

    def _reap_cancelled(self) -> bool:
        """Honor ``Request.cancel()`` and expired deadlines at the
        iteration boundary: pull the victims out of the queue and the
        slot table, release their slots (a paged release derefs every
        KV block; a mid-prefill abort never committed a radix/prefix
        entry, so there is nothing to roll back), and finish them
        FAILED with :class:`RequestCancelled` / :class:`DeadlineExceeded`
        — counted in ``cancelled``, never ``quarantined``."""
        now = time.time()
        victims = []
        with self._work:
            for r in list(self._queue):
                if self._should_cancel(r, now):
                    self._queue.remove(r)
                    victims.append(r)
            for s, r in enumerate(self._slots):
                if r is not None and self._should_cancel(r, now):
                    self._slots[s] = None
                    victims.append(r)
            if victims:
                self._work.notify_all()
        for r in victims:
            slot, r.slot = r.slot, None
            self._release_slot(slot)
            self._finish_cancelled(r, now)
        return bool(victims)

    def _finish_cancelled(self, req: Request, now: float):
        reason = "cancelled" if req._cancel else "deadline"
        req.state = FAILED
        req.finish_reason = reason
        if req._cancel:
            req.error = RequestCancelled(
                f"request {req.id} cancelled by the client "
                f"({len(req.tokens)} token(s) already streamed)")
        else:
            req.error = DeadlineExceeded(
                f"request {req.id} exceeded its deadline "
                f"({now - req.t_submit:.3f}s since submit)")
        req.t_done = now
        req.chunk_plan = None
        self._end_block_stall(req, time.perf_counter())
        self.stats["cancelled"] += 1
        events.event("serve_request_cancelled", request=req.id,
                     reason=reason, generated=len(req.tokens),
                     **_req_trace(req))
        self._metric("counter", "serving_requests_cancelled_total")
        self._close_request_span(req, reason)
        req._done.set()

    def run_until_idle(self):
        """Drive inline until the queue is empty and every slot idle."""
        while self.step():
            pass

    def start(self) -> "GenerationEngine":
        """Run the scheduling loop in a daemon thread."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop_mode = None
            self._thread = threading.Thread(
                target=self._loop, name="sparkdl-serve-engine", daemon=True)
            self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float | None = None
             ) -> list[Request]:
        """Stop the background loop. ``drain=True`` finishes queued and
        in-flight requests first; ``drain=False`` fails them with
        :class:`EngineStopped`. A drain wedged past
        ``SPARKDL_SERVE_STALL_S`` (or ``timeout``) degrades to
        snapshot-and-stop: the still-live requests are preempted into
        resumable snapshots and returned (empty list on a clean
        drain/stop)."""
        return self._shutdown("drain" if drain else "now", timeout)

    def drain(self, timeout: float | None = None) -> list[Request]:
        """Graceful handoff (ISSUE 19): stop admission, preempt every
        live request into a resumable snapshot (``prompt`` +
        ``tokens``-so-far on the returned :class:`Request` handles —
        the preemption-resume form), and return them. Feed each to
        :meth:`resume` on a fresh engine to continue exactly where it
        left off; already-streamed tokens are never re-emitted."""
        return self._shutdown("snapshot", timeout)

    def _shutdown(self, mode: str, timeout: float | None
                  ) -> list[Request]:
        """The ONE stop/drain implementation. ``mode``: "drain"
        (finish everything, degrade to snapshot past the stall budget),
        "snapshot" (immediate preempt-and-return), "now" (fail
        pending)."""
        with self._work:
            self._stop_mode = "drain" if mode == "drain" else "now"
            self._work.notify_all()
            t = self._thread
        snaps: list[Request] = []
        if mode == "drain" and t is not None:
            budget = timeout
            if self.stall_s and self.stall_s > 0:
                budget = self.stall_s if budget is None \
                    else min(budget, self.stall_s)
            t.join(budget)
            if t.is_alive():
                # Wedged drain: never hang the caller — degrade to
                # snapshot-and-stop, returning the resumable snapshots.
                log.warning("drain still running after %ss; degrading "
                            "to snapshot-and-stop", budget)
                with self._work:
                    self._stop_mode = "now"
                    self._work.notify_all()
                mode = "snapshot"
        if mode == "now" and t is not None:
            t.join(timeout)
        if mode == "snapshot":
            if t is not None:
                # Give the loop one beat to notice stop_mode="now" and
                # park between iterations; the in-flight guards make a
                # late backend return harmless either way.
                t.join(timeout if timeout is not None
                       else (self.stall_s or 1.0))
            snaps = self._detach_all()
            events.event("serve_engine_drain", requests=len(snaps))
        if t is not None:
            if t.is_alive():
                # The loop is wedged past the join timeout: leave
                # _thread set so a later start() cannot spawn a SECOND
                # loop over the same slot table.
                log.warning("serve engine loop still running after "
                            "stop(timeout=%s); not restartable until it "
                            "exits", timeout)
            else:
                with self._lock:
                    if self._thread is t:  # a concurrent start() may
                        self._thread = None  # already own the handle
        if mode == "now":
            self._fail_pending(EngineStopped("engine stopped"))
        pool, self._watch_pool = self._watch_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        return snaps

    def resume(self, req: "Request | dict", *, stream_cb=None) -> Request:
        """Re-admit a drained/preempted snapshot — on this engine or a
        DIFFERENT one (ISSUE 20). Accepts either the :class:`Request`
        handle :meth:`drain` returned, or a self-contained snapshot
        dict from :meth:`Request.snapshot` (the router's shadow-state
        path for an uncleanly dead replica; ``stream_cb`` attaches the
        continuation stream). The request keeps its id; its prefill
        consumes ``prompt + tokens-so-far`` and the stream continues
        exactly where it left off (greedy determinism), nothing
        re-emitted.

        Cross-engine safety: the request is RE-BUCKETED for THIS
        engine's config (chunk alignment / ``min_bucket`` may differ
        from the engine that drained it); a snapshot that cannot fit
        this engine's ``max_len`` raises :class:`RequestRejected`, and
        a stale/foreign snapshot (unknown version, missing fields, or
        a delivery cursor past the emitted tokens) raises
        :class:`SnapshotIncompatibleError` — both BEFORE the snapshot
        can touch a slot. Undelivered tail tokens (emitted but never
        streamed before the hop) are dropped back to the delivery
        cursor: greedy determinism regenerates them identically, so
        the client stream stays zero-dup / zero-loss."""
        if isinstance(req, dict):
            req = self._request_from_snapshot(req, stream_cb)
        elif stream_cb is not None:
            req.stream_cb = stream_cb
        if req.state in (DONE, FAILED):
            return req
        req.bucket = self._resume_bucket(req)
        with self._work:
            if self._stop_mode is not None or self._fatal is not None:
                raise EngineStopped("engine is stopped")
            req.state = QUEUED
            req.slot = None
            req.chunk_plan = None
            req._block_stalled = False
            req.t_enqueue = time.time()
            self._queue.append(req)
            self.stats["submitted"] += 1
            self._work.notify_all()
        return req

    def _request_from_snapshot(self, snap: dict, stream_cb) -> Request:
        """Rehydrate a :meth:`Request.snapshot` dict into a fresh
        handle (validation first — a foreign/corrupt snapshot must die
        here, not in a slot)."""
        version = snap.get("version")
        if version != SNAPSHOT_VERSION:
            raise SnapshotIncompatibleError(
                f"resume snapshot version {version!r} is not the "
                f"supported version {SNAPSHOT_VERSION}")
        try:
            rid = int(snap["id"])
            prompt = [int(t) for t in snap["prompt"]]
            tokens = [int(t) for t in snap["tokens"]]
            delivered = int(snap["delivered"])
            max_new = int(snap["max_new_tokens"])
        except (KeyError, TypeError, ValueError) as e:
            raise SnapshotIncompatibleError(
                f"resume snapshot is missing or malforms a required "
                f"field: {e!r}") from e
        if not prompt:
            raise SnapshotIncompatibleError(
                "resume snapshot has an empty prompt")
        if delivered < 0 or delivered > len(tokens):
            raise SnapshotIncompatibleError(
                f"resume snapshot delivery cursor {delivered} is "
                f"outside its emitted tokens [0, {len(tokens)}] — "
                f"re-admitting it could duplicate or lose streamed "
                f"tokens")
        req = Request(rid, prompt, max_new, 0, stream_cb)
        # Roll emitted-but-undelivered tokens back to the cursor: the
        # client never saw them, and the greedy continuation regrows
        # them bit-identically.
        req.tokens = tokens[:delivered]
        req.delivered = delivered
        req.failovers = int(snap.get("failovers", 0) or 0)
        return req

    def _resume_bucket(self, req: Request) -> int:
        """Re-bucket a resumed request for THIS engine (its stored
        bucket belongs to the engine that drained it). Same fit rules
        as :meth:`submit`, over the SERVED sequence (prompt + tokens
        already generated)."""
        served = len(req.prompt) + len(req.tokens)
        remaining = max(1, req.max_new_tokens - len(req.tokens))
        if self.stall_free:
            c = self.prefill_chunk
            bucket = -(-served // c) * c
            if max(bucket, served + remaining) > self.backend.max_len:
                self._reject(
                    f"resumed request {req.id}: chunk-aligned served "
                    f"length ({bucket}) + remaining tokens "
                    f"({remaining}) exceeds max_len "
                    f"{self.backend.max_len}")
        else:
            bucket = bucket_length(served, self.min_bucket)
            if bucket + remaining > self.backend.max_len:
                self._reject(
                    f"resumed request {req.id}: bucketed served length "
                    f"({bucket}) + remaining tokens ({remaining}) "
                    f"exceeds max_len {self.backend.max_len}")
        if self.paged:
            # Never-fit only — a coverable-but-currently-full pool
            # waits FIFO (the admission gate's backpressure), exactly
            # the submit() posture.
            bs = self.backend.block_size
            rows = served + remaining if self.stall_free \
                else max(bucket, served + remaining)
            need = min(-(-rows // bs) + 1,
                       -(-self.backend.max_len // bs))
            total = self.backend.allocator.usable_blocks
            if need > total:
                self._reject(
                    f"resumed request {req.id} needs {need} KV blocks "
                    f"(block_size {bs}); the whole pool holds {total} "
                    f"— can never fit")
        return bucket

    def residency_digest(self) -> dict | None:
        """Compact digest of the backend's resident prefix heads
        (ISSUE 20) — what a fleet router's radix-aware placement
        shadows. Duck-typed over both cache families: the paged
        backends' :class:`~sparkdl_tpu.serving.prefix.RadixPrefixCache`
        (via ``backend.radix`` / ``backend.mgr.radix``) or the unpaged
        byte-payload LRU (``backend.prefix_cache``). ``None`` when no
        prefix cache is enabled."""
        be = self.backend
        radix = getattr(be, "radix", None)
        if radix is None:
            radix = getattr(getattr(be, "mgr", None), "radix", None)
        if radix is not None:
            return radix.residency_digest()
        pc = getattr(be, "prefix_cache", None)
        if pc is not None:
            return pc.residency_digest()
        return None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=exc[0] is None)
        return False

    def _loop(self):
        # Online anomaly sentinel (ISSUE 17): env-armed at loop start,
        # same posture as fit() — TTFT / decode-step / queue-depth
        # baselines drift-checked while the engine serves.
        sentinel_lib.maybe_arm_from_env()
        try:
            while True:
                with self._work:
                    if self._fatal is not None or self._stop_mode == "now":
                        break
                    idle = not self._queue and all(
                        r is None for r in self._slots)
                    if idle:
                        if self._stop_mode == "drain":
                            break
                        self.t_heartbeat = time.time()
                        self._work.wait(0.05)
                        continue
                try:
                    self.step()
                except Exception as e:  # noqa: BLE001 — record, not die
                    # step() already routed failover-eligible errors; a
                    # raise here means failover was impossible/failed
                    # (or a scheduler bug) — record and die, unless a
                    # concurrent path somehow recovered.
                    self._handle_fatal(e)
                    if self._fatal is not None:
                        break
        finally:
            # A stop() whose join timed out leaves _thread set (so a
            # concurrent start() can't double-drive the slot table);
            # once the loop really exits, release the handle so start()
            # can re-arm the engine.
            with self._lock:
                if self._thread is threading.current_thread():
                    self._thread = None

    # -- refill -----------------------------------------------------------
    def _served_prompt(self, req: Request) -> list:
        """The token sequence this admission actually prefills: the
        prompt, plus any tokens already generated before a preemption
        (the recompute-resume — greedy K/V is deterministic, so the
        continuation picks up exactly where the preempted decode
        left off)."""
        return req.prompt + req.tokens if req.tokens else req.prompt

    def _blocks_needed(self, req: Request) -> int:
        """Worst-case NEW blocks an admission must be able to cover:
        the REAL prompt rows (chunk-pad writes route to the trash
        block, so alignment never inflates the footprint — in
        particular after a preemption resume) — or the blocking
        bucket, whose left-pad rows ARE written — plus one decode
        block. Radix grafts only reduce the real allocation, never the
        gate (conservative)."""
        served = len(self._served_prompt(req))
        rows = served if self.stall_free else \
            self._blocking_bucket(served, req)
        rows = min(rows, self.backend.max_len)
        bs = self.backend.block_size
        return min(-(-rows // bs) + 1,
                   -(-self.backend.max_len // bs))

    def _blocking_bucket(self, served: int, req: Request) -> int:
        """Blocking-path bucket for (possibly resumed) ``served``
        tokens: the power-of-two bucket, clamped so bucket + the
        remaining output still fits the slot row — a resume whose
        re-bucket overshoots ``max_len`` must degrade to a snug
        non-power-of-two bucket (one extra compiled prefill per resume
        length; preemption is rare), never quarantine. Always >=
        ``served``: admission guaranteed served + remaining <=
        max_len."""
        remaining = max(1, req.max_new_tokens - len(req.tokens))
        return min(bucket_length(served, self.min_bucket),
                   self.backend.max_len - remaining)

    def _pop_to_slot(self):
        """Move the queue head into the lowest free slot (admission
        bookkeeping shared by both scheduler modes); returns
        ``(req, slot)`` or ``(None, None)`` when there is nothing to
        do. Paged mode additionally gates on KV-pool capacity: a head
        the pool cannot cover WAITS (FIFO — later smaller requests do
        not jump it), counted in ``admission_block_waits``."""
        with self._work:
            free = [s for s, r in enumerate(self._slots) if r is None]
            if not free or not self._queue:
                return None, None
            if self.paged and not self.backend.can_reserve(
                    self._blocks_needed(self._queue[0])):
                self.stats["admission_block_waits"] += 1
                return None, None
            req = self._queue.popleft()
            slot = min(free)  # deterministic: lowest free slot, FIFO
            self._slots[slot] = req
            depth = len(self._queue)
            self._work.notify_all()  # queue space freed
        req.t_admit = time.time()
        req.slot = slot
        self._metric("gauge", "serving_queue_depth", depth)
        # Per-STINT wait: t_enqueue is reset on every requeue, so a
        # preempted request's second serve_queue span measures only its
        # re-queued wait — the trace collector sums stints, and phases
        # still total the end-to-end latency.
        wait_s = req.t_admit - req.t_enqueue
        events.completed_span("serve_queue", wait_s, request=req.id,
                              **_req_trace(req))
        self._metric("histogram", "serving_queue_wait_s", wait_s)
        return req, slot

    def _refill(self) -> int:
        """Blocking-mode refill: every free slot prefills its whole
        prompt inside this scheduler iteration (the pre-ISSUE-10
        head-of-line stall the stall-free path removes)."""
        admitted = 0
        while True:
            req, slot = self._pop_to_slot()
            if req is None:
                break
            try:
                ok = self._prefill_with_retries(req, slot)
            except BlockExhausted:
                # The admission gate was optimistic (an imminent graft
                # can pin blocks it counted evictable): requeue at the
                # FRONT and wait — exhaustion is backpressure, never a
                # quarantine.
                self._requeue_for_blocks(req, slot)
                break
            admitted += 1
            if not ok:
                with self._work:
                    self._slots[slot] = None
                    self._work.notify_all()
                # Same release as retirement/eviction/chunked
                # quarantine: a release()-ful backend must never leak a
                # slot's fill state on the blocking path either.
                self._release_slot(slot)
        return admitted

    def _requeue_for_blocks(self, req: Request, slot: int):
        with self._work:
            if self._slots[slot] is req:
                self._slots[slot] = None
            self._queue.appendleft(req)
            self._work.notify_all()
        self._release_slot(slot)
        req.slot = None
        req.t_enqueue = time.time()  # new queued stint begins
        self.stats["admission_block_waits"] += 1
        events.event("serve_admission_block_wait", request=req.id,
                     **_req_trace(req))

    # -- stall-free admission + chunked prefill ---------------------------
    def _admit(self) -> int:
        """Move queued requests into free slots as PREFILLING (prefix
        seed + chunk plan; no prompt compute happens here — chunks run
        one per iteration in :meth:`_prefill_tick`)."""
        admitted = 0
        while True:
            req, slot = self._pop_to_slot()
            if req is None:
                break
            if not self._arm_chunked_prefill(req, slot):
                break  # requeued on block exhaustion: wait, FIFO order
            admitted += 1
        return admitted

    def _arm_chunked_prefill(self, req: Request, slot: int) -> bool:
        c = self.prefill_chunk
        served = self._served_prompt(req)
        # Per-stint active-prefill ledger: a preemption-resume re-arms
        # here, and its serve_prefill span must report THIS stint's
        # compute, not re-bill the previous stint's (already landed on
        # the earlier span).
        req.prefill_spent_s = 0.0
        with self._lock:
            n_running = sum(1 for r in self._slots
                            if r is not None and r.state == RUNNING)
        start = 0
        t0 = time.perf_counter()
        try:
            # Under the same watchdog + stall ledger as every other
            # device call: a prefix-cache hit scatters K/V rows
            # device-side, which both stalls running decodes and can
            # wedge exactly like a chunk. (A paged backend's graft is a
            # pointer swap — cheap, but the ledger stays honest.)
            start = int(self._timed(
                lambda: self.backend.begin_prefill(slot, served, c),
                "prefix_seed"))
        except ServingStallError:
            raise  # a wedged device is never a per-request fault
        except BlockExhausted:
            # Optimistic-gate miss (see _refill): requeue and wait.
            self._requeue_for_blocks(req, slot)
            return False
        except Exception as e:  # noqa: BLE001 — reuse is an optimization
            if getattr(e, "serving_fatal", False):
                raise  # step()'s failover seam owns it
            if self.paged:
                # Paged begin_prefill is RESERVATION, not just reuse: a
                # cold fallback would chunk-write through an unreserved
                # (trash-parked) table — silently wrong tokens. Retry
                # the whole admission; quarantine past the budget.
                req.failures += 1
                if req.failures > self.retries:
                    with self._work:
                        if self._slots[slot] is req:
                            self._slots[slot] = None
                        self._work.notify_all()
                    self._release_slot(slot)
                    self._quarantine(req, e)
                    return True  # slot freed — keep admitting others
                events.event("serve_reserve_retry", request=req.id,
                             attempt=req.failures,
                             error=f"{type(e).__name__}: {e}"[:200],
                             **_req_trace(req))
                self._requeue_for_blocks(req, slot)
                return False
            events.event("serve_prefix_seed_failed", request=req.id,
                         error=f"{type(e).__name__}: {e}"[:200],
                         **_req_trace(req))
            start = 0
        dt = time.perf_counter() - t0
        self._note_stall(dt, n_running)
        req.prefill_spent_s += dt
        # Guard the contract (usable_reuse): a drifted backend must
        # degrade to a cold prefill, never hand the chunker an empty or
        # misaligned plan (a non-chunk-multiple start could make the
        # final chunk's scatter clamp at max_len and slide back over
        # committed rows).
        if not 0 <= start < len(served) or start % c:
            if start != 0:
                log.warning("backend.begin_prefill returned offset %s "
                            "for a %s-token prompt (chunk %s); ignoring "
                            "prefix reuse", start, len(served), c)
            start = 0
        tail = served[start:]
        plan = []
        for i in range(0, len(tail), c):
            part = list(tail[i:i + c])
            nv = len(part)
            if nv < c:  # final chunk right-pads; n_valid marks the reals
                part = part + [0] * (c - nv)
            plan.append((part, nv))
        req.chunk_plan = plan
        req.chunk_base = start
        req.next_chunk = 0
        req.prefill_reused = start
        req.served_len = len(served)
        req.state = PREFILLING
        return True

    def _prefill_tick(self) -> bool:
        """Spend this iteration's prefill TOKEN budget
        (``SPARKDL_SERVE_PREFILL_BUDGET``, default one chunk — the
        exact PR 9 pacing) one chunk at a time, round-robin oldest-
        admitted-first across every PREFILLING slot: with the default
        budget exactly one chunk of the oldest request runs per
        iteration; with a larger budget one iteration can advance —
        and complete — several refills, removing the ~1
        admission/iteration cap that starved high-churn mixes. Every
        RUNNING slot's decode still runs in the same iteration, so a
        long prompt costs running requests at most ``budget`` tokens of
        added latency per step, never a whole O(L²) prefill.
        Chunk-aware retry: a failed chunk stays current (the cache
        holds every committed chunk) and is re-attempted next tick;
        past the retry budget the REQUEST is quarantined and its slot
        freed — the gang keeps serving."""
        budget = self.prefill_budget
        worked = False
        while budget > 0:
            with self._lock:
                prefilling = sorted(
                    (r for r in self._slots
                     if r is not None and r.state == PREFILLING),
                    key=lambda r: (r.t_admit or 0.0, r.id))
            if not prefilling:
                break
            progressed = False
            for req in prefilling:
                if budget <= 0:
                    break
                if req.state != PREFILLING:
                    continue
                self._prefill_chunk_once(req)
                progressed = worked = True
                budget -= self.prefill_chunk
            if not progressed:
                break
        return worked

    def _prefill_chunk_once(self, req: Request) -> None:
        """Run exactly one chunk (or the final chunk + finish) of one
        PREFILLING request — the unit the budget loop spends."""
        with self._lock:
            n_running = sum(1 for r in self._slots
                            if r is not None and r.state == RUNNING)
        c = self.prefill_chunk
        chunk, n_valid = req.chunk_plan[req.next_chunk]
        offset = req.chunk_base + req.next_chunk * c
        final = req.next_chunk == len(req.chunk_plan) - 1
        window = req.chunk_base + len(req.chunk_plan) * c
        t0 = time.perf_counter()
        try:
            tok = self._timed(
                lambda: self.backend.prefill_chunk(req.slot, chunk,
                                                   offset, n_valid,
                                                   window),
                "prefill_chunk")
            if final:
                aligned = req.chunk_base + len(req.chunk_plan) * c
                # Commit policy: caching a one-chunk prompt can never
                # save a chunk on reuse, and a prompt the cache already
                # mostly served (a warm hit's distinct tail) adds no
                # reusable head — skip the commit copy for both. A
                # paged backend's radix commit is a zero-copy pointer
                # insert, so there is no copy economy to police:
                # commit whenever the prompt holds a full block.
                commit = True if self.paged else (
                    aligned > c and req.prefill_reused * 2 < aligned)
                tok = self._timed(
                    lambda: self.backend.finish_prefill(
                        req.slot, self._served_prompt(req), tok, aligned,
                        commit=commit),
                    "finish_prefill")
        except ServingStallError:
            raise  # a wedged device is never a per-request fault
        except Exception as e:  # noqa: BLE001 — per-request isolation
            if getattr(e, "serving_fatal", False):
                raise  # step()'s failover seam owns it
            dt_fail = time.perf_counter() - t0
            self._note_stall(dt_fail, n_running)
            req.prefill_spent_s += dt_fail  # failed-attempt compute is
            # still prefill-phase time — it must not leak into wait_s
            req.failures += 1
            if req.failures > self.retries:
                with self._work:
                    if req.slot is not None and \
                            self._slots[req.slot] is req:
                        self._slots[req.slot] = None
                    self._work.notify_all()
                self._release_slot(req.slot)
                self._quarantine(req, e)
            else:
                self.stats["prefill_retries"] += 1
                events.event("serve_prefill_chunk_retry", request=req.id,
                             chunk=req.next_chunk, offset=offset,
                             attempt=req.failures,
                             error=f"{type(e).__name__}: {e}"[:200],
                             **_req_trace(req))
            return
        dt = time.perf_counter() - t0
        self._note_stall(dt, n_running)
        req.prefill_spent_s += dt
        req.next_chunk += 1
        self.stats["prefill_chunks"] += 1
        if final:
            self.stats["prefills"] += 1
            if req.state != PREFILLING:
                # The engine failed, failed over, or drained while the
                # chunk was in flight: the request was already reported
                # failed — or detached into a resumable snapshot (state
                # QUEUED) awaiting re-admission. Never resurrect it to
                # RUNNING or stream a token from the dead stint.
                return
            req.state = RUNNING
            req.write_pos = req.served_len  # decode writes from L
            req.t_decode_start = time.time()
            # wait_s = the PREFILLING phase's wall minus its active
            # compute: time this request's chunks sat waiting for their
            # round-robin turn while other slots prefilled/decoded. The
            # trace collector needs it so queue + prefill + wait +
            # decode provably sums to the measured latency.
            phase_wall = req.t_decode_start - (req.t_admit
                                               or req.t_decode_start)
            wait_s = max(0.0, phase_wall - req.prefill_spent_s)
            events.completed_span(
                "serve_prefill", req.prefill_spent_s, request=req.id,
                slot=req.slot, bucket=req.bucket, rows=1,
                chunks=len(req.chunk_plan), reused=req.prefill_reused,
                wait_s=round(wait_s, 6), **_req_trace(req))
            self._deliver(req, int(tok))

    def _prefill_with_retries(self, req: Request, slot: int) -> bool:
        last: BaseException | None = None
        served = self._served_prompt(req)
        if req.tokens:  # preemption resume: re-bucket the longer prompt
            req.bucket = self._blocking_bucket(len(served), req)
        for attempt in range(self.retries + 1):
            with self._lock:
                n_running = sum(1 for r in self._slots
                                if r is not None and r.state == RUNNING)
            t0 = time.perf_counter()
            try:
                with events.span("serve_prefill", request=req.id, slot=slot,
                                 bucket=req.bucket, rows=1,
                                 **_req_trace(req)):
                    first = self._timed(
                        lambda: self.backend.prefill(slot, served,
                                                     req.bucket),
                        "prefill")
                # The head-of-line stall this whole prefill inflicted on
                # every already-RUNNING slot (the blocking-path number
                # the stall-free scheduler is measured against).
                self._note_stall(time.perf_counter() - t0, n_running)
                self.stats["prefills"] += 1
                if req.state == FAILED or self._slots[slot] is not req:
                    # The engine failed, failed over, or drained while
                    # this prefill was in flight: the request was
                    # already reported failed — or detached from the
                    # slot into a resumable snapshot. Never resurrect
                    # it to RUNNING or stream a token from the dead
                    # stint.
                    return False
                req.state = RUNNING
                req.served_len = len(served)
                req.write_pos = req.bucket  # blocking layout: cur=bucket
                req.t_decode_start = time.time()
                self._deliver(req, int(first))
                return True
            except ServingStallError:
                raise  # a wedged device is never a per-request fault
            except BlockExhausted:
                raise  # capacity, not a fault: _refill requeues + waits
            except Exception as e:  # noqa: BLE001 — per-request isolation
                if getattr(e, "serving_fatal", False):
                    # e.g. backend.SlotCacheLost: the donated cache was
                    # consumed by the failing call — retrying reads a
                    # deleted buffer, so let step()'s failover seam
                    # rebuild instead of evicting innocents one by one.
                    raise
                self._note_stall(time.perf_counter() - t0, n_running)
                last = e
                req.failures += 1
                if attempt < self.retries:
                    self.stats["prefill_retries"] += 1
                    events.event("serve_prefill_retry", request=req.id,
                                 attempt=attempt + 1,
                                 error=f"{type(e).__name__}: {e}"[:200],
                                 **_req_trace(req))
        self._quarantine(req, last)
        return False

    def _quarantine(self, req: Request, cause: BaseException | None):
        req.state = FAILED
        req.finish_reason = "error"
        req.error = RequestQuarantined(
            f"request {req.id} quarantined after {req.failures} "
            f"failure(s): {type(cause).__name__ if cause else '?'}: "
            f"{cause}")
        req.error.__cause__ = cause
        req.t_done = time.time()
        self.stats["quarantined"] += 1
        events.event("serve_request_quarantined", request=req.id,
                     failures=req.failures,
                     error=f"{type(cause).__name__}: {cause}"[:200]
                     if cause else "?", **_req_trace(req))
        self._metric("counter", "serving_requests_quarantined_total")
        self._close_request_span(req, "quarantined")
        req._done.set()

    # -- decode step ------------------------------------------------------
    def _step_with_isolation(self, call=None, stage: str = "decode_step"):
        """Run one backend decode/verify call with the PR 4 retry
        posture: transient failures retry; past the budget the
        newest-admitted request (the slot-table state that changed most
        recently — the suspect) is evicted + quarantined and the call
        retried, so a poisoned request takes itself out, not the gang.
        ``call(slots)`` defaults to the plain decode step; the
        speculative path passes the batched verify. Returns the
        backend's result, or None when every request was evicted."""
        if call is None:
            call = self.backend.step
        attempts = 0
        while True:
            with self._lock:
                slots = sorted(s for s, r in enumerate(self._slots)
                               if r is not None and r.state == RUNNING
                               and not r._block_stalled)
            if not slots:
                # Every running request was evicted (each already
                # quarantined with its cause): the engine stays alive
                # and keeps serving the queue — a sole poisoned
                # occupant must not take the gang down any more than a
                # co-resident one does. A genuinely broken backend
                # degrades per-request (each new refill burns its own
                # retry budget and quarantines), never engine-fatally.
                return None
            try:
                return self._timed(lambda: call(slots), stage)
            except ServingStallError:
                raise
            except Exception as e:  # noqa: BLE001 — retry taxonomy below
                if getattr(e, "serving_fatal", False):
                    raise  # step()'s failover seam owns it
                attempts += 1
                if attempts <= self.retries:
                    self.stats["step_retries"] += 1
                    events.event("serve_step_retry", attempt=attempts,
                                 error=f"{type(e).__name__}: {e}"[:200])
                    continue
                with self._lock:
                    running = [r for r in self._slots
                               if r is not None and r.state == RUNNING
                               and not r._block_stalled]
                    victim = max(running, key=lambda r: r.t_admit or 0.0) \
                        if running else None
                    if victim is not None:
                        self._slots[victim.slot] = None
                if victim is not None:
                    # Same release step as a normal retirement: the
                    # backend parks the evicted slot (a release()-ful
                    # backend must never leak one slot per eviction).
                    self._release_slot(victim.slot)
                    self._quarantine(victim, e)
                attempts = 0

    # -- speculative decode (ISSUE 12) ------------------------------------
    def _spec_step(self, active) -> bool:
        """One draft → verify → commit iteration: draft up to ``spec_k``
        candidates per RUNNING slot (jax-free provider, host-side),
        check them ALL in one batched target verify, and greedily
        commit the longest draft prefix the target's argmax agrees
        with plus the target's own next token — so every slot emits
        >= 1 token per iteration (a fully-rejected draft degrades to
        exactly the k=0 decode step's output, never below it). Reject
        is a pure frontier non-advance: the misspeculated rows sit
        past the slot's new write frontier and are garbage the next
        write overwrites before any attention reads them (the PR 9
        invariant — no rollback program exists). Paged mode allocates
        each slot's draft-window growth blocks UP FRONT
        (``ensure_block_for`` per draft position; a position the pool
        cannot serve just shortens that slot's window — backpressure,
        never a stall). Returns False — withOUT dispatching anything —
        when NO slot drafted a single token: the caller then runs the
        plain decode step, so draftless iterations keep the k=0
        economics (flash-decode HBM clamp included) instead of paying
        a wasted k+1-wide dense verify window."""
        k = self.spec_k
        drafts: dict[int, list[int]] = {}
        t0 = time.perf_counter()
        total_drafted = 0
        for slot, req in active:
            # Window caps: never draft past the request's remaining
            # output (the emission a+1 must not overshoot
            # max_new_tokens) nor the slot row's last writable position.
            cap = min(k, req.max_new_tokens - len(req.tokens) - 1,
                      self.backend.max_len - req.write_pos - 1)
            d: list[int] = []
            if cap > 0:
                t_d = time.perf_counter()
                try:
                    d = [int(t) for t in self._draft.propose(
                        req.prompt + req.tokens, cap)][:cap]
                except Exception:  # noqa: BLE001 — drafting is an
                    # optimization; a broken provider costs acceptance,
                    # never correctness or the loop
                    log.exception("draft provider failed (request %s)",
                                  req.id)
                    d = []
                req.draft_s += time.perf_counter() - t_d
            if self.paged and d:
                ok = 0
                for i in range(len(d)):
                    if self._ensure_block(slot, req.write_pos + 1 + i):
                        ok += 1
                    else:
                        break
                d = d[:ok]
            drafts[slot] = d
            total_drafted += len(d)
        if not total_drafted:
            return False  # nothing to verify — plain decode step
        # the drafting span tees into StageAccountant /
        # bottleneck_report like every other serving stage
        events.completed_span("serve_draft",
                              time.perf_counter() - t0,
                              rows=total_drafted)
        props = self._step_with_isolation(
            lambda slots: self.backend.verify(
                slots, {s: drafts.get(s, []) for s in slots}, k),
            stage="spec_verify")
        if props is None:
            return True  # every occupant evicted — nothing to fall to
        self.stats["steps"] += 1
        self.stats["spec_verifies"] += 1
        for slot, req in active:
            if req.state != RUNNING or req._block_stalled:
                continue  # evicted mid-isolation / sat this one out
            prop = [int(t) for t in props[slot]]
            d = drafts.get(slot, [])
            a = 0
            while a < len(d) and prop[a] == d[a]:
                a += 1
            self.stats["spec_tokens_accepted"] += a
            self.stats["spec_tokens_rejected"] += len(d) - a
            if d:
                req.spec_windows += 1
                req.spec_drafted += len(d)
                req.spec_accepted += a
                self._metric("counter", "serving_spec_tokens_accepted",
                             a)
                self._metric("counter", "serving_spec_tokens_rejected",
                             len(d) - a)
            emit = prop[:a + 1]
            self._metric("histogram", "serve_spec_accept_len",
                         float(len(emit)), buckets=self._spec_buckets)
            delivered, last = 0, None
            for t in emit:
                if req.state != RUNNING or \
                        self._should_cancel(req, time.time()):
                    # retired (EOS / length), cancelled, or past its
                    # deadline mid-verify-window: stop emitting — the
                    # reaper at the next iteration boundary finishes a
                    # cancel/deadline victim without streaming more
                    break
                self._deliver(req, t)
                req.write_pos += 1
                delivered += 1
                last = t
            if delivered and req.state == RUNNING:
                # Frontier advance past the committed rows; a retired
                # request's slot was already released (reset) by
                # _retire, so committing it would corrupt the next
                # occupant's fill state.
                self.backend.commit_spec(slot, delivered, last)
        return True

    # -- paged-mode block growth / backpressure ---------------------------
    def _ensure_block(self, slot: int, pos: int) -> bool:
        """``backend.ensure_block_for`` under the ``serve_alloc`` chaos
        site: an injected serving-fatal fault (``cache_lost``)
        propagates to step()'s failover seam; any other injected or
        organic allocator error degrades to False — the block-stall
        backpressure path, never a crash."""
        try:
            chaos_lib.fire("serve_alloc", batch=slot)
            return bool(self.backend.ensure_block_for(slot, pos))
        except Exception as e:  # noqa: BLE001 — alloc faults backpressure
            if getattr(e, "serving_fatal", False):
                raise
            log.warning("ensure_block_for(%s, %s) failed: %s: %s",
                        slot, pos, type(e).__name__, e)
            return False

    def _filter_block_stalled(self, active):
        """Secure a writable frontier block for every RUNNING slot
        (oldest admitted first — FIFO priority when blocks are scarce).
        Slots the pool cannot serve are flagged ``_block_stalled`` and
        sit the decode step out; if EVERY running slot stalls, the
        newest-admitted one is preempted (released + requeued for a
        recompute resume) so the others can make progress — exhaustion
        never evicts work, the worst case is a deferred request."""
        ordered = sorted(active,
                         key=lambda sr: (sr[1].t_admit or 0.0, sr[1].id))
        ok, stalled = [], []
        now = time.perf_counter()
        for slot, req in ordered:
            req._block_stalled = False
            if self._ensure_block(slot, req.write_pos):
                self._end_block_stall(req, now)
                ok.append((slot, req))
            else:
                req._block_stalled = True
                if req._block_stall_t0 is None:
                    req._block_stall_t0 = now  # stall interval opens
                stalled.append((slot, req))
                self.stats["block_stall_events"] += 1
        if stalled and not ok:
            victim = self._preempt_newest(stalled)
            # the victim's blocks are free now: give the survivors one
            # immediate retry instead of a wasted iteration
            for slot, req in stalled:
                if req is victim:
                    continue
                if self._ensure_block(slot, req.write_pos):
                    req._block_stalled = False
                    self._end_block_stall(req, time.perf_counter())
                    ok.append((slot, req))
        return sorted(ok)

    @staticmethod
    def _end_block_stall(req: Request, now: float):
        """Close an open block-stall interval into the request's phase
        ledger (the trace collector reads the total off the retirement
        span)."""
        if req._block_stall_t0 is not None:
            req.block_stall_s += max(0.0, now - req._block_stall_t0)
            req._block_stall_t0 = None

    def _preempt_newest(self, stalled) -> Request:
        """Deadlock breaker: requeue (front, FIFO-fair) the NEWEST
        stalled request. Its blocks free immediately; on re-admission
        it prefills ``prompt + tokens-so-far`` and continues — greedy
        output is unchanged (the recompute writes the identical K/V),
        already-streamed tokens are never re-emitted."""
        victim = max((r for _, r in stalled),
                     key=lambda r: (r.t_admit or 0.0, r.id))
        slot = victim.slot
        with self._work:
            if slot is not None and self._slots[slot] is victim:
                self._slots[slot] = None
            self._queue.appendleft(victim)
            self._work.notify_all()
        self._release_slot(slot)
        now = time.time()
        self._end_block_stall(victim, time.perf_counter())
        # the aborted stint's decode-phase wall: without it the trace
        # collector would book this time as unattributed (the final
        # serve_decode span only covers the LAST stint)
        stint_decode_s = max(0.0, now - getattr(victim, "t_decode_start",
                                                now))
        victim.slot = None
        victim.state = QUEUED
        victim.chunk_plan = None
        victim._block_stalled = False
        victim.preemptions += 1
        victim.t_enqueue = now  # new queued stint begins
        self.stats["preemptions"] += 1
        events.event("serve_request_preempted", request=victim.id,
                     generated=len(victim.tokens),
                     decode_s=round(stint_decode_s, 6),
                     **_req_trace(victim))
        self._metric("counter", "serving_requests_preempted_total")
        return victim

    def _export_pool_metrics(self):
        if not telemetry.enabled():
            return
        ps = self.backend.pool_stats()
        self._metric("gauge", "serving_kv_blocks_free",
                     ps.get("blocks_free", 0))
        self._metric("gauge", "serving_kv_blocks_shared",
                     ps.get("blocks_shared", 0))
        # ISSUE 18 — how many pool blocks the configured kv dtype
        # bought at this budget (pool_blocks incl. the trash block;
        # named so a dashboard can overlay int8 vs f32 runs at equal
        # SPARKDL_SERVE_KV_POOL_MB).
        self._metric("gauge", "kv_pool_effective_blocks",
                     ps.get("effective_blocks", ps.get("blocks_total", 0)))
        drain = getattr(self.backend, "drain_alloc_samples", None)
        if drain is not None:
            for dt in drain():
                self._metric("histogram", "serving_block_alloc_s", dt,
                             buckets=_ALLOC_BUCKETS)

    def _deliver(self, req: Request, tok: int):
        req.tokens.append(tok)
        self.stats["tokens_out"] += 1
        self._metric("counter", "serving_tokens_total")
        now = time.time()
        if self._awaiting_recovery and req.failovers:
            # recovery_s = fault-to-first-resumed-token (the ISSUE 19
            # survivability headline serve_bench reads off the snapshot)
            self._failover_info["last_recovery_s"] = max(
                0.0, now - (self._t_fault or now))
            self._awaiting_recovery = False
        if req.t_first_token is None:
            req.t_first_token = now
            self._metric("histogram", "serving_ttft_s",
                         now - req.t_submit)
            sentinel_lib.observe("ttft", now - req.t_submit)
        if req.stream_cb is not None:
            try:
                req.stream_cb(req, tok)
            except Exception:  # noqa: BLE001 — a client callback must
                self.stats["callback_errors"] += 1  # never kill the loop
                log.exception("serve stream callback failed (request %s)",
                              req.id)
        # Exactly-once delivery cursor: every token is appended +
        # streamed in this one place, so cursor == len(tokens) always —
        # a failover that re-emitted (or a resume that skipped) a token
        # would break the invariant, which is exactly what the chaos
        # smoke's cursor audit checks.
        req.delivered = len(req.tokens)
        if self.eos_id is not None and tok == self.eos_id:
            self._retire(req, "eos")
        elif len(req.tokens) >= req.max_new_tokens:
            self._retire(req, "length")

    def _close_request_span(self, req: Request, finish: str):
        """Land the request's causal-envelope span (ISSUE 17): one
        ``serve_request`` span covering submit→done, carrying the
        admission span id every other emission for this request parents
        under, itself parented at the submitter's context (or the
        env-shipped gang-attempt span). Only when tracing is armed — and
        deliberately WITHOUT an ``error`` attr even for quarantines:
        merge_timeline reads error-bearing records as failure evidence,
        and a per-request quarantine already narrates itself via
        ``serve_request_quarantined``."""
        if not req.span_id or req.t_done is None:
            return
        kw: dict = {"request": req.id, "finish": finish,
                    "span_id": req.span_id}
        if req.parent_span:
            kw["parent_id"] = req.parent_span
        events.completed_span("serve_request",
                              max(0.0, req.t_done - req.t_submit), **kw)

    def _release_slot(self, slot: int | None):
        if slot is None:
            return
        release = getattr(self.backend, "release", None)
        if release is not None:
            try:
                release(slot)
            except Exception:  # noqa: BLE001 — cleanup must not mask
                log.exception("backend.release(%s) failed", slot)

    def _retire(self, req: Request, reason: str):
        with self._work:
            if req.slot is not None and self._slots[req.slot] is req:
                self._slots[req.slot] = None
            self._work.notify_all()
        self._release_slot(req.slot)
        req.state = DONE
        req.finish_reason = reason
        req.t_done = time.time()
        self.stats["completed"] += 1
        decode_s = req.t_done - getattr(req, "t_decode_start", req.t_admit)
        # Retirement span = the request's decode-phase wall, carrying
        # the per-request sub-phase ledger (ISSUE 13): draft/block-stall
        # seconds are carved out of the decode wall by the trace
        # collector, the speculation counters yield its mean accept
        # length. Only nonzero fields ride, keeping the stream lean.
        attrs: dict = {"request": req.id, "rows": len(req.tokens),
                       "reason": reason}
        if req.prefill_reused:
            attrs["reused"] = req.prefill_reused
        if req.draft_s > 0:
            attrs["draft_s"] = round(req.draft_s, 6)
        if req.block_stall_s > 0:
            attrs["block_stall_s"] = round(req.block_stall_s, 6)
        if req.spec_windows:
            attrs["spec_windows"] = req.spec_windows
            attrs["spec_drafted"] = req.spec_drafted
            attrs["spec_accepted"] = req.spec_accepted
        if req.preemptions:
            attrs["preemptions"] = req.preemptions
        attrs.update(_req_trace(req))
        events.completed_span("serve_decode", decode_s, **attrs)
        self._close_request_span(req, reason)
        self._metric("counter", "serving_requests_completed_total")
        self._metric("histogram", "serving_request_latency_s",
                     req.t_done - req.t_submit)
        if self._draft is not None:
            # retrieval providers (HistoryDraft) learn from completed
            # traffic; a broken observer costs future acceptance only
            obs = getattr(self._draft, "observe", None)
            if obs is not None:
                try:
                    obs(req.prompt, req.tokens)
                except Exception:  # noqa: BLE001
                    log.exception("draft observe failed (request %s)",
                                  req.id)
        req._done.set()

    # -- failure plumbing -------------------------------------------------
    # Chaos sites (ISSUE 19): every jitted-call stage the watchdog
    # already names maps onto one of the serving fault-injection sites,
    # so the whole failover posture is provable on CPU. The rebuild
    # stage is deliberately absent — injecting into the recovery path
    # itself would recurse (the _failing_over latch guards regardless).
    _CHAOS_SITES = {
        "prefill": "serve_prefill", "prefill_chunk": "serve_prefill",
        "finish_prefill": "serve_prefill", "prefix_seed": "serve_alloc",
        "decode_step": "serve_decode", "spec_verify": "serve_decode",
    }

    def _timed(self, fn, stage: str):
        """Run one backend call under the optional stall watchdog (and
        the serving chaos sites — fired on the engine thread so an
        injected fault takes the organic error's exact control path)."""
        site = self._CHAOS_SITES.get(stage)
        if site is not None:
            self._backend_calls += 1
            chaos_lib.fire(site, step=self._backend_calls)
        if not self.stall_s or self.stall_s <= 0:
            return fn()
        if self._watch_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._watch_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="sparkdl-serve-backend")
        fut = self._watch_pool.submit(fn)
        from concurrent.futures import TimeoutError as FutTimeout
        try:
            return fut.result(timeout=self.stall_s)
        except FutTimeout:
            # step()'s failover seam owns the stall (rebuild or fail
            # closed); raising is all the watchdog does now.
            raise ServingStallError(
                f"serving {stage} exceeded SPARKDL_SERVE_STALL_S="
                f"{self.stall_s:g}s") from None

    def _handle_fatal(self, exc: BaseException):
        """The serving supervisor (ISSUE 19): try to fail over —
        snapshot live requests, rebuild the backend, re-admit — and
        only when that is impossible (no ``backend.rebuild``, an
        ineligible error class, budget exhausted, or the rebuild itself
        died) fall back to the fail-closed posture: record ONE
        ``serve_engine_fatal`` event and fail everything pending.
        Idempotent and latch-guarded — a failure surfacing through
        several paths runs one recovery."""
        with self._lock:
            if self._fatal is not None or self._failing_over:
                return
            self._failing_over = True
        ok = False
        try:
            ok = self._can_failover(exc) and self._failover(exc)
        finally:
            with self._lock:
                if not ok and self._fatal is None:
                    self._fatal = exc
                self._failing_over = False
        if ok:
            return
        note = f": {self._fatal_note}" if self._fatal_note else ""
        events.event("serve_engine_fatal",
                     error=f"{type(exc).__name__}: {exc}"[:300] + note)
        self._fail_pending(EngineStopped(
            f"engine died{note}: {type(exc).__name__}: {exc}"))

    def _can_failover(self, exc: BaseException) -> bool:
        """Failover eligibility: only errors that mean the BACKEND
        STATE is gone/wedged (``serving_fatal``-flagged, or a stall-
        watchdog fire) — an arbitrary scheduler exception keeps the
        conservative fail-everything posture — and only when the
        backend can actually be rebuilt."""
        if not (getattr(exc, "serving_fatal", False)
                or isinstance(exc, ServingStallError)):
            return False
        return callable(getattr(self.backend, "rebuild", None))

    def _failover(self, cause: BaseException) -> bool:
        """One failover: budget/backoff accounting, snapshot + detach
        every live request, rebuild the backend (fresh slot cache /
        paged pool / prefix trie), re-admit the snapshots through the
        preemption-resume path (FIFO seniority preserved), quarantining
        individually any request that has personally survived
        ``failover_budget`` failovers without gaining a token. Returns
        False to fail closed."""
        budget = self.failover_budget
        if self.stats["tokens_out"] > self._tokens_at_failover >= 0:
            self._failover_streak = 0  # progress resets the streak
        self._failover_streak += 1
        self._tokens_at_failover = self.stats["tokens_out"]
        if self._failover_streak > budget:
            self._fatal_note = (
                f"failover budget exhausted "
                f"({FAILOVER_BUDGET_ENV}={budget})")
            self._failover_info.update(
                state="exhausted", streak=self._failover_streak,
                last_cause=f"{type(cause).__name__}: {cause}"[:200])
            return False
        t_fault = time.time()
        backoff = self.failover_backoff_s * (
            2 ** (self._failover_streak - 1))
        if backoff > 0:
            time.sleep(backoff)
        live = self._detach_all()
        # A stall-triggered failover leaves the wedged call sleeping in
        # the 1-worker watchdog pool — the rebuild must not queue behind
        # it. Abandon the pool (daemon worker; the in-flight guards make
        # a late return harmless) and let _timed lazily build a fresh
        # one around the rebuild.
        pool, self._watch_pool = self._watch_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        try:
            self._timed(self.backend.rebuild, "failover_rebuild")
        except Exception as e:  # noqa: BLE001 — rebuild died: fail closed
            self._fatal_note = (f"backend rebuild failed: "
                                f"{type(e).__name__}: {e}")
            self._failover_info.update(
                state="rebuild_failed", streak=self._failover_streak,
                last_cause=f"{type(cause).__name__}: {cause}"[:200])
            with self._work:
                # Put the detached snapshots back so the fail-closed
                # path (_fail_pending) reports them — never strand a
                # request in QUEUED with no engine working it.
                self._queue.extendleft(reversed(live))
                self._work.notify_all()
            return False
        resumed, keep = 0, []
        for r in live:
            prev = r._len_at_failover
            if prev is not None and len(r.tokens) <= prev:
                r.failovers += 1  # zero progress since the last one
            else:
                r.failovers = 1
            r._len_at_failover = len(r.tokens)
            if r.failovers > budget:
                r.failures = max(r.failures, r.failovers)
                self.stats["failover_quarantined"] += 1
                self._quarantine(r, cause)
                continue
            events.event("serve_request_failover", request=r.id,
                         generated=len(r.tokens), failovers=r.failovers,
                         **_req_trace(r))
            keep.append(r)
            resumed += 1
        with self._work:
            self._queue.extendleft(reversed(keep))
            self._work.notify_all()
        self.stats["failovers"] += 1
        self.stats["failover_resumed"] += resumed
        self._failover_info.update(
            state="recovered", count=self.stats["failovers"],
            streak=self._failover_streak,
            last_cause=f"{type(cause).__name__}: {cause}"[:200],
            last_t=t_fault,
            resumed_total=self.stats["failover_resumed"],
            quarantined_total=self.stats["failover_quarantined"],
            last_backoff_s=backoff, last_recovery_s=None)
        self._awaiting_recovery = True
        self._t_fault = t_fault
        events.event("serve_engine_failover",
                     error=f"{type(cause).__name__}: {cause}"[:300],
                     resumed=resumed,
                     quarantined=self.stats["failover_quarantined"],
                     streak=self._failover_streak)
        self._metric("counter", "serving_failovers_total")
        if resumed:
            self._metric("counter", "serving_requests_resumed_total",
                         resumed)
        log.warning("serving failover %s (streak %s/%s): %s — %s "
                    "request(s) re-admitted", self.stats["failovers"],
                    self._failover_streak, budget, cause, resumed)
        return True

    def _detach_all(self) -> list[Request]:
        """Pull every live request out of the queue and the slot table
        into resumable snapshot form (state QUEUED, slot released,
        chunk plan dropped — exactly the preemption-resume shape),
        preserving FIFO seniority: slot occupants (admitted earliest)
        first, then the queue in order. Shared by failover and
        drain."""
        with self._work:
            queued = list(self._queue)
            self._queue.clear()
            occupants = []
            for s, r in enumerate(self._slots):
                if r is not None:
                    occupants.append(r)
                    self._slots[s] = None
            self._work.notify_all()
        live: list[Request] = []
        now = time.time()
        for r in sorted(occupants, key=lambda r: (r.t_admit or 0.0, r.id)):
            slot, r.slot = r.slot, None
            self._release_slot(slot)
            if r.state in (DONE, FAILED):
                continue
            r.state = QUEUED
            r.chunk_plan = None
            r._block_stalled = False
            self._end_block_stall(r, time.perf_counter())
            r.t_enqueue = now
            live.append(r)
        for r in queued:
            if r.state not in (DONE, FAILED):
                live.append(r)
        return live

    def _fail_pending(self, err: EngineStopped):
        with self._work:
            pending = list(self._queue)
            self._queue.clear()
            for s, r in enumerate(self._slots):
                if r is not None:
                    pending.append(r)
                    self._slots[s] = None
            self._work.notify_all()
        for req in pending:
            if req.state in (DONE, FAILED):
                continue
            req.state = FAILED
            req.finish_reason = "error"
            req.error = err
            req.t_done = time.time()
            self.stats["failed"] += 1
            req._done.set()

    # -- introspection ----------------------------------------------------
    def debug_state(self) -> dict:
        """Live operator view (ISSUE 13): the slot table (state /
        request / write frontier / age / per-slot KV block footprint),
        queue depth + head age, KV pool and radix residency, and
        speculation acceptance — what ``/serving`` on the telemetry
        HTTP server returns per engine. See
        :func:`serving.introspect.engine_debug_state`."""
        from .introspect import engine_debug_state
        return engine_debug_state(self)

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "queue_depth": len(self._queue),
                "slots_busy": sum(r is not None for r in self._slots),
                "num_slots": len(self._slots),
                "stall_free": self.stall_free,
                "prefill_chunk": self.prefill_chunk,
                "prefill_budget": self.prefill_budget,
                "paged": self.paged,
                "spec_k": self.spec_k,
                "tp_degree": self.tp_degree,
                "kv_pool_device_bytes": self.kv_pool_device_bytes,
                **dict(self.stats),
            }
            snap["failover"] = dict(self._failover_info)
        ps = getattr(self.backend, "prefix_stats", None)
        if callable(ps):
            st = ps()
            if st:
                snap["prefix_cache"] = st
        if self.paged:
            pool = getattr(self.backend, "pool_stats", None)
            if callable(pool):
                snap["kv_pool"] = pool()
        return snap
