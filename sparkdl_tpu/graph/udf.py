"""makeGraphUDF — register a graph as a named column function.

Reference surface: ``python/sparkdl/graph/tensorframes_udf.py`` —
``makeGraphUDF(graph, name, fetches)`` registered a TF graph as a Spark SQL
UDF executed by TensorFrames in the JVM (SURVEY.md §2.1/§3.3). Here the
registry lives in-process (``sparkdl_tpu.udf``) and the graph executes as a
jitted XLA program over Arrow batches.
"""

from __future__ import annotations

from typing import Sequence

from .builder import IsolatedSession
from .function import GraphFunction
from .input import XlaInputGraph


def makeGraphUDF(graph, name: str, fetches: Sequence[str] | None = None,
                 blocked: bool = True, batchSize: int = 64) -> None:
    """Register ``graph`` under ``name`` in the UDF registry.

    ``graph``: a GraphFunction, XlaInputGraph, IsolatedSession export, a
    jax-traceable callable, or serialized GraphFunction bytes/path.
    ``fetches`` picks the output (single fetch — column UDFs are one-column).
    ``blocked`` is reference-parity arity: execution here is always batched
    (blocked=False row-at-a-time would be a de-optimization on TPU).
    """
    from ..udf import registerUDF

    if isinstance(graph, XlaInputGraph):
        gfn = graph.translateToGraphFunction()
    elif isinstance(graph, GraphFunction):
        gfn = graph
    elif isinstance(graph, IsolatedSession):
        raise TypeError("Pass issn.asGraphFunction(inputs, outputs), not the "
                        "session itself")
    elif isinstance(graph, (bytes, bytearray)):
        gfn = GraphFunction.deserialize(bytes(graph))
    elif isinstance(graph, str):
        gfn = GraphFunction.load(graph)
    elif callable(graph):
        gfn = GraphFunction.fromJax(graph)
    else:
        raise TypeError(f"Cannot make a UDF from {type(graph).__name__}")

    del blocked
    if isinstance(fetches, str):
        fetches = [fetches]
    fetch = fetches[0] if fetches else None
    registerUDF(name, gfn.as_single_output_fn(fetch), batchSize=batchSize)
