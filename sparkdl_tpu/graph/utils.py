"""Feed/fetch name hygiene for the graph toolkit.

Reference surface: ``python/sparkdl/graph/utils.py`` — ``tensor_name``/
``op_name`` normalized TF-1.x's dual naming ("op" vs "op:0" tensor output),
and ``validated_input``/``validated_output`` checked feeds/fetches against a
graph (SURVEY.md §2.1). There is no op/tensor split in a jax program, but the
":0"-suffixed names still appear in TF-era artifacts (SavedModel signatures,
user code written against the reference), so the same normalization functions
are kept and every GraphFunction accepts either spelling.
"""

from __future__ import annotations

import re

_VALID_NAME = re.compile(r"^[A-Za-z0-9_.][A-Za-z0-9_.\-/]*$")


def op_name(name: str) -> str:
    """"x:0" → "x"; "x" → "x". The canonical slot name used internally."""
    if not isinstance(name, str) or not name:
        raise TypeError(f"Invalid graph slot name: {name!r}")
    base = name.split(":")[0]
    if not _VALID_NAME.match(base):
        raise ValueError(f"Invalid graph slot name: {name!r}")
    return base


def tensor_name(name: str) -> str:
    """"x" → "x:0"; "x:1" stays. TF-style spelling for compat output."""
    base = op_name(name)
    idx = name.split(":")[1] if ":" in name else "0"
    if not idx.isdigit():
        raise ValueError(f"Invalid tensor index in {name!r}")
    return f"{base}:{idx}"


def validated_input(name: str, input_names) -> str:
    """Normalize + check a feed name against a GraphFunction's inputs."""
    base = op_name(name)
    if base not in input_names:
        raise ValueError(
            f"Feed {name!r} is not an input of this graph; inputs: "
            f"{list(input_names)}")
    return base


def validated_output(name: str, output_names) -> str:
    """Normalize + check a fetch name against a GraphFunction's outputs."""
    base = op_name(name)
    if base not in output_names:
        raise ValueError(
            f"Fetch {name!r} is not an output of this graph; outputs: "
            f"{list(output_names)}")
    return base
