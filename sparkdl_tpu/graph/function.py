"""GraphFunction — the portable unit of compiled compute.

Reference surface: ``python/sparkdl/graph/builder.py``'s ``GraphFunction`` — a
serialized TF GraphDef plus input/output tensor names, buildable from Keras
models or by chaining pieces (``fromList``), spliced into sessions with
``importGraphFunction`` (SURVEY.md §2.1/§3.3).

TPU-native re-design: the portable artifact is **StableHLO via jax.export**,
not a GraphDef — a ``GraphFunction`` is a jit-traceable function with *named*
feeds and fetches (weights closed over as constants), which:

- executes as one XLA program (``.jit()``), so composed pieces fuse;
- composes functionally (``fromList`` chains fetches→feeds positionally, the
  reference's piece-chaining semantic) — composition happens before tracing,
  so XLA sees a single graph, where the reference spliced GraphDefs;
- serializes to bytes (``serialize``/``deserialize``, ``dump``/``load``) with
  a symbolic leading batch dimension, the analogue of the reference's
  portable GraphDef payloads.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Mapping, Sequence

import numpy as np

from .utils import op_name, validated_output

_MAGIC = b"SPARKDL-TPU-GFN1"


class GraphFunction:
    """A named-feeds/named-fetches jittable function.

    ``fn`` maps a dict ``{input_name: array}`` to a dict
    ``{output_name: array}`` and must be jax-traceable (any captured weights
    become XLA constants at compile/serialize time).
    """

    def __init__(self, fn: Callable[[dict], dict],
                 input_names: Sequence[str], output_names: Sequence[str],
                 input_specs: Mapping[str, tuple] | None = None):
        self.fn = fn
        self.input_names = [op_name(n) for n in input_names]
        self.output_names = [op_name(n) for n in output_names]
        # {name: (shape_with_None_batch, dtype_str)} — needed only to
        # serialize; calls infer shapes from the actual feeds.
        self.input_specs = dict(input_specs) if input_specs else None
        self._jitted = None

    # -- execution ---------------------------------------------------------

    def __call__(self, feeds: Mapping[str, object] | None = None, **kw):
        fetches = self.fn(self._normalize_feeds(feeds, kw))
        return {op_name(k): v for k, v in fetches.items()}

    def jit(self) -> Callable:
        """The compiled entry point: dict feeds → dict fetches, one XLA
        program per feed-shape signature."""
        if self._jitted is None:
            import jax
            self._jitted = jax.jit(
                lambda feeds: self.fn(feeds))
        jitted = self._jitted
        normalize = self._normalize_feeds
        return lambda feeds=None, **kw: jitted(normalize(feeds, kw))

    def as_single_output_fn(self, fetch: str | None = None) -> Callable:
        """batch → array adapter for single-input/single-output use (the
        shape the transformer/UDF layer consumes)."""
        if len(self.input_names) != 1:
            raise ValueError(
                f"as_single_output_fn needs exactly one input, have "
                f"{self.input_names}")
        out = (validated_output(fetch, self.output_names) if fetch
               else self.output_names[-1])
        name = self.input_names[0]
        fn = self.fn
        return lambda batch: fn({name: batch})[out]

    def _normalize_feeds(self, feeds, kw) -> dict:
        merged = dict(feeds or {})
        merged.update(kw)
        named = {op_name(k): v for k, v in merged.items()}
        missing = [n for n in self.input_names if n not in named]
        if missing:
            raise ValueError(f"Missing feeds {missing}; expected "
                             f"{self.input_names}")
        extra = [n for n in named if n not in self.input_names]
        if extra:
            raise ValueError(f"Unknown feeds {extra}; expected "
                             f"{self.input_names}")
        return named

    # -- construction ------------------------------------------------------

    @classmethod
    def fromJax(cls, fn: Callable, input_names: Sequence[str] | None = None,
                output_names: Sequence[str] | None = None,
                input_specs: Mapping[str, tuple] | None = None
                ) -> "GraphFunction":
        """Wrap a jax function taking positional arrays (one per input name)
        and returning an array, tuple of arrays, or dict of arrays."""
        inputs = [op_name(n) for n in (input_names or ["input"])]
        declared = [op_name(n) for n in output_names] if output_names else None

        def wrapped(feeds: dict) -> dict:
            out = fn(*[feeds[n] for n in inputs])
            return _name_outputs(out, declared)

        outputs = declared or _probe_output_names(fn, inputs, input_specs)
        return cls(wrapped, inputs, outputs, input_specs)

    @classmethod
    def fromKeras(cls, model_or_file, input_name: str = "input",
                  output_name: str = "output") -> "GraphFunction":
        """A Keras-3 (jax backend) model or saved .keras/.h5 file → one
        GraphFunction (weights captured). Reference: GraphFunction.fromKeras
        exported K.get_session()'s graph."""
        from ..transformers.keras_utils import (keras_model_to_fn,
                                                load_keras_model)
        model = (load_keras_model(model_or_file)
                 if isinstance(model_or_file, (str, os.PathLike))
                 else model_or_file)
        fn = keras_model_to_fn(model)
        spec = None
        try:
            shape = tuple(model.inputs[0].shape)
            spec = {op_name(input_name): (shape, "float32")}
        except Exception:
            pass
        return cls.fromJax(fn, [input_name], [output_name], spec)

    @classmethod
    def fromFlax(cls, module, variables, input_name: str = "input",
                 output_name: str = "output", **apply_kwargs
                 ) -> "GraphFunction":
        """A flax ``nn.Module`` + variables pytree → GraphFunction (weights
        captured as constants)."""
        def fn(batch):
            return module.apply(variables, batch, **apply_kwargs)
        return cls.fromJax(fn, [input_name], [output_name])

    @classmethod
    def fromList(cls, functions: Sequence["GraphFunction"]) -> "GraphFunction":
        """Chain pieces: stage i's fetches feed stage i+1's feeds
        positionally (the reference's piece-composition contract). The
        composite exposes the first stage's feeds and last stage's fetches —
        and compiles to ONE fused XLA program."""
        if not functions:
            raise ValueError("fromList needs at least one GraphFunction")
        for a, b in zip(functions, functions[1:]):
            if len(a.output_names) != len(b.input_names):
                raise ValueError(
                    f"Cannot chain: stage with outputs {a.output_names} into "
                    f"stage with inputs {b.input_names} (arity mismatch)")
        stages = list(functions)

        def chained(feeds: dict) -> dict:
            values = feeds
            for i, g in enumerate(stages):
                if i > 0:
                    prev = stages[i - 1]
                    values = {bn: values[an] for an, bn in
                              zip(prev.output_names, g.input_names)}
                values = g.fn(values)
                values = {op_name(k): v for k, v in values.items()}
            return values

        return cls(chained, stages[0].input_names, stages[-1].output_names,
                   stages[0].input_specs)

    def then(self, other: "GraphFunction") -> "GraphFunction":
        return GraphFunction.fromList([self, other])

    def rename(self, inputs: Mapping[str, str] | None = None,
               outputs: Mapping[str, str] | None = None) -> "GraphFunction":
        imap = {op_name(k): op_name(v) for k, v in (inputs or {}).items()}
        omap = {op_name(k): op_name(v) for k, v in (outputs or {}).items()}
        new_in = [imap.get(n, n) for n in self.input_names]
        new_out = [omap.get(n, n) for n in self.output_names]
        inv_in = dict(zip(new_in, self.input_names))
        fn = self.fn

        def renamed(feeds: dict) -> dict:
            out = fn({inv_in[k]: v for k, v in feeds.items()})
            return {omap.get(op_name(k), op_name(k)): v
                    for k, v in out.items()}

        specs = ({imap.get(k, k): v for k, v in self.input_specs.items()}
                 if self.input_specs else None)
        return GraphFunction(renamed, new_in, new_out, specs)

    # -- serialization (StableHLO via jax.export) --------------------------

    def serialize(self, input_specs: Mapping[str, tuple] | None = None
                  ) -> bytes:
        """→ portable bytes: json header (names/specs) + jax.export payload.

        ``input_specs``: {name: (shape, dtype)}; a ``None`` leading dim
        becomes a symbolic batch dimension so any batch size can be fed at
        load time. Falls back to specs captured at construction.
        """
        import jax
        from jax import export as jex

        specs = dict(input_specs or self.input_specs or {})
        missing = [n for n in self.input_names if n not in specs]
        if missing:
            raise ValueError(
                f"serialize needs input_specs for {missing} "
                f"(shape, dtype per input)")

        # One shared symbol for every leading None (batch — inputs batch
        # together); a distinct symbol per other variable dim. All symbols
        # must live in ONE scope, so name them first and mint them together.
        sym_names: dict = {}
        for n in self.input_names:
            for axis, d in enumerate(specs[n][0]):
                if d is None:
                    key = "batch" if axis == 0 else (n, axis)
                    sym_names.setdefault(key, f"d{len(sym_names) + 1}")
        symbols = (dict(zip(sym_names, jex.symbolic_shape(
            ", ".join(sym_names.values())))) if sym_names else {})

        def to_sds(name, shape, dtype):
            dims = [symbols["batch" if axis == 0 else (name, axis)]
                    if d is None else int(d)
                    for axis, d in enumerate(shape)]
            return jax.ShapeDtypeStruct(tuple(dims), np.dtype(dtype))

        sds = [to_sds(n, *specs[n]) for n in self.input_names]
        inputs, outputs, fn = self.input_names, self.output_names, self.fn

        def positional(*args):
            res = fn(dict(zip(inputs, args)))
            return tuple(res[n] for n in outputs)

        exported = jex.export(jax.jit(positional))(*sds)
        header = json.dumps({
            "inputs": inputs, "outputs": outputs,
            "specs": {n: [list(specs[n][0]), str(np.dtype(specs[n][1]))]
                      for n in inputs},
        }).encode()
        payload = exported.serialize()
        return (_MAGIC + len(header).to_bytes(8, "little") + header + payload)

    @classmethod
    def deserialize(cls, data: bytes) -> "GraphFunction":
        from jax import export as jex
        if data[:len(_MAGIC)] != _MAGIC:
            raise ValueError("Not a serialized GraphFunction")
        off = len(_MAGIC)
        hlen = int.from_bytes(data[off:off + 8], "little")
        header = json.loads(data[off + 8:off + 8 + hlen])
        exported = jex.deserialize(data[off + 8 + hlen:])
        inputs, outputs = header["inputs"], header["outputs"]

        def fn(feeds: dict) -> dict:
            res = exported.call(*[feeds[n] for n in inputs])
            return dict(zip(outputs, res))

        specs = {n: (tuple(s if s is None else int(s) for s in shape), dt)
                 for n, (shape, dt) in header.get("specs", {}).items()}
        return cls(fn, inputs, outputs, specs or None)

    def dump(self, path: str, input_specs: Mapping[str, tuple] | None = None):
        data = self.serialize(input_specs)
        with open(path, "wb") as f:
            f.write(data)

    @classmethod
    def load(cls, path: str) -> "GraphFunction":
        with open(path, "rb") as f:
            return cls.deserialize(f.read())

    def __repr__(self):
        return (f"GraphFunction(inputs={self.input_names}, "
                f"outputs={self.output_names})")


def _name_outputs(out, declared: Sequence[str] | None) -> dict:
    if isinstance(out, dict):
        named = {op_name(k): v for k, v in out.items()}
        if declared and sorted(named) != sorted(declared):
            raise ValueError(f"Function returned outputs {sorted(named)}, "
                             f"declared {sorted(declared)}")
        return named
    vals = out if isinstance(out, (tuple, list)) else (out,)
    if declared is None and len(vals) > 1:
        raise ValueError(
            "Multi-output functions must declare output_names or return a "
            "dict of named outputs")
    names = declared or ["output"]
    if len(names) != len(vals):
        raise ValueError(f"Function returned {len(vals)} outputs, declared "
                         f"{len(names)} names {names}")
    return dict(zip(names, vals))


def _probe_output_names(fn, inputs, input_specs) -> list[str]:
    """Infer output names at CONSTRUCTION time when possible.

    With ``input_specs`` the function is abstractly traced via
    ``jax.eval_shape`` (no compute, no compile): a dict return yields its
    keys, an undeclared multi-output raises here — at the definition —
    instead of as a confusing arity error at call time (round-2 verdict
    weak #8). Without specs tracing is impossible; the single-output
    default keeps the common case simple.
    """
    if not input_specs or any(n not in input_specs for n in inputs):
        return ["output"]
    import jax
    import jax.numpy as jnp

    structs = []
    for n in inputs:
        shape, dtype = input_specs[n]
        structs.append(jax.ShapeDtypeStruct(
            tuple(1 if d is None else int(d) for d in shape),
            jnp.dtype(dtype)))
    try:
        out = jax.eval_shape(fn, *structs)
    except Exception:
        # fn may not be abstractly traceable (host callbacks etc.); fall
        # back to the declared-or-default contract checked at call time.
        return ["output"]
    if isinstance(out, dict):
        return [op_name(k) for k in out]
    if isinstance(out, (tuple, list)) and len(out) > 1:
        raise ValueError(
            f"Function returns {len(out)} outputs; declare output_names or "
            f"return a dict of named outputs")
    return ["output"]
