"""IsolatedSession — imperative graph assembly over lazy jax nodes.

Reference surface: ``python/sparkdl/graph/builder.py``'s ``IsolatedSession`` —
a hermetic TF Graph + Session scope in which users placed placeholders, built
ops, spliced in GraphFunctions (``importGraphFunction``), and exported the
result (``asGraphFunction``) (SURVEY.md §2.1/§3.3).

TPU-native re-design: there is no session or mutable global graph in jax —
the equivalent scope is a **lazy expression DAG**. ``placeholder`` returns a
symbolic ``GraphNode``; arithmetic operators and ``apply(fn, *nodes)`` build
nodes; ``importGraphFunction`` splices a GraphFunction's body in as more
nodes. ``asGraphFunction(inputs, outputs)`` closes the DAG into a single
jit-traceable GraphFunction — so everything assembled in the session fuses
into ONE XLA program (the reference instead concatenated GraphDefs and ran
them through one Session).
"""

from __future__ import annotations

from typing import Callable, Sequence

from .function import GraphFunction
from .utils import op_name


class GraphNode:
    """A symbolic value in an IsolatedSession: either a placeholder (leaf)
    or a function of other nodes. Supports jnp-traceable operators."""

    def __init__(self, session: "IsolatedSession", name: str,
                 fn: Callable | None = None,
                 deps: Sequence["GraphNode"] = ()):
        self.session = session
        self.name = op_name(name)
        self.fn = fn            # None ⇒ placeholder
        self.deps = list(deps)

    def evaluate(self, env: dict, cache: dict):
        if self.name in cache:
            return cache[self.name]
        if self.fn is None:
            try:
                val = env[self.name]
            except KeyError:
                raise ValueError(
                    f"No feed provided for placeholder {self.name!r}"
                    ) from None
        else:
            val = self.fn(*[d.evaluate(env, cache) for d in self.deps])
        cache[self.name] = val
        return val

    # -- operator sugar (kept jax-traceable) --

    def _binop(self, other, f, name):
        import jax.numpy as jnp
        if isinstance(other, GraphNode):
            return self.session.apply(f, self, other, name=name)
        const = jnp.asarray(other) if not callable(other) else other
        return self.session.apply(lambda a: f(a, const), self, name=name)

    def __add__(self, o):
        return self._binop(o, lambda a, b: a + b, None)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, lambda a, b: a - b, None)

    def __rsub__(self, o):
        return self._binop(o, lambda a, b: b - a, None)

    def __mul__(self, o):
        return self._binop(o, lambda a, b: a * b, None)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, lambda a, b: a / b, None)

    def __rtruediv__(self, o):
        return self._binop(o, lambda a, b: b / a, None)

    def __matmul__(self, o):
        return self._binop(o, lambda a, b: a @ b, None)

    def __neg__(self):
        return self.session.apply(lambda a: -a, self)

    def __getitem__(self, idx):
        return self.session.apply(lambda a: a[idx], self)

    def __repr__(self):
        kind = "placeholder" if self.fn is None else "op"
        return f"GraphNode<{kind} {self.name}>"


class IsolatedSession:
    """``with IsolatedSession() as issn: ...`` — a scoped graph assembly.

    Unlike the reference there is no live Session to run: ``run(fetches,
    feed_dict)`` executes eagerly for debugging, and ``asGraphFunction``
    exports the compiled artifact.
    """

    def __init__(self):
        self._nodes: dict[str, GraphNode] = {}
        self._counter = 0

    # The with-statement is scoping sugar for reference-API familiarity;
    # all state lives on the session object itself (no global graph).

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    # -- graph building --

    def _fresh(self, prefix: str) -> str:
        self._counter += 1
        return f"{prefix}_{self._counter}"

    def _register(self, node: GraphNode) -> GraphNode:
        if node.name in self._nodes:
            raise ValueError(f"Duplicate node name {node.name!r}")
        self._nodes[node.name] = node
        return node

    def placeholder(self, shape: Sequence[int | None] | None = None,
                    dtype: str = "float32",
                    name: str | None = None) -> GraphNode:
        node = GraphNode(self, name or self._fresh("placeholder"))
        node.shape = tuple(shape) if shape is not None else None
        node.dtype = dtype
        return self._register(node)

    def apply(self, fn: Callable, *deps: GraphNode,
              name: str | None = None) -> GraphNode:
        """fn(*dep_values) → new node; fn must be jax-traceable."""
        for d in deps:
            if d.session is not self:
                raise ValueError(f"Node {d.name!r} belongs to another session")
        return self._register(
            GraphNode(self, name or self._fresh("op"), fn, deps))

    def constant(self, value, name: str | None = None) -> GraphNode:
        import jax.numpy as jnp
        arr = jnp.asarray(value)
        return self._register(GraphNode(
            self, name or self._fresh("const"), lambda: arr, ()))

    def importGraphFunction(self, gfn: GraphFunction,
                            inputs: Sequence[GraphNode],
                            prefix: str = "") -> list[GraphNode]:
        """Splice a GraphFunction into this session: its feeds are bound to
        ``inputs`` (positionally, the reference contract) and its fetches
        come back as nodes."""
        if len(inputs) != len(gfn.input_names):
            raise ValueError(
                f"GraphFunction expects {len(gfn.input_names)} inputs "
                f"{gfn.input_names}, got {len(inputs)}")
        p = f"{prefix}/" if prefix else ""

        def run_body(*vals):
            return gfn.fn(dict(zip(gfn.input_names, vals)))

        body = self.apply(run_body, *inputs,
                          name=f"{p}{self._fresh('import')}")
        outs = []
        for out_name in gfn.output_names:
            outs.append(self.apply(
                (lambda n: lambda d: d[n])(out_name), body,
                name=f"{p}{out_name}" if p else self._fresh(out_name)))
        return outs

    # -- execution / export --

    def run(self, fetches, feed_dict: dict | None = None):
        """Eager evaluation for debugging (the Session.run analogue)."""
        env = {op_name(k): v for k, v in (feed_dict or {}).items()}
        cache: dict = {}
        if isinstance(fetches, GraphNode):
            return fetches.evaluate(env, cache)
        return [f.evaluate(env, cache) for f in fetches]

    def asGraphFunction(self, inputs: Sequence[GraphNode],
                        outputs: Sequence[GraphNode]) -> GraphFunction:
        for n in inputs:
            if n.fn is not None:
                raise ValueError(f"Input {n.name!r} is not a placeholder")
        in_names = [n.name for n in inputs]
        out_nodes = list(outputs)
        # Export-time validation: every placeholder reachable from the
        # outputs must be declared an input — otherwise the omission only
        # surfaces as "No feed provided" when the exported function is
        # CALLED, far from the mistake (ADVICE r1 item 4).
        declared = set(in_names)
        reachable: dict[str, GraphNode] = {}
        stack = list(out_nodes)
        seen: set[str] = set()
        while stack:
            node = stack.pop()
            if node.name in seen:
                continue
            seen.add(node.name)
            if node.fn is None:
                reachable[node.name] = node
            stack.extend(node.deps)
        missing = sorted(set(reachable) - declared)
        if missing:
            raise ValueError(
                f"asGraphFunction: outputs depend on placeholder(s) "
                f"{missing} not declared in inputs {sorted(declared)}")

        def fn(feeds: dict) -> dict:
            cache: dict = {}
            return {n.name: n.evaluate(feeds, cache) for n in out_nodes}

        specs = {}
        for n in inputs:
            if getattr(n, "shape", None) is not None:
                specs[n.name] = (n.shape, getattr(n, "dtype", "float32"))
        return GraphFunction(fn, in_names, [n.name for n in out_nodes],
                             specs or None)


IsolatedGraph = IsolatedSession  # tpu-flavored alias
