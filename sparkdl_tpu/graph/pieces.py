"""Composable graph pieces.

Reference surface: ``python/sparkdl/graph/pieces.py`` —
``buildSpImageConverter`` (image-struct fields → float tensor, channel
reorder + rescale) and ``buildFlattener`` (tensor → per-row flat vector),
spliced in front of / behind model graphs (SURVEY.md §2.1/§3.3).

TPU-native deltas: struct *decode* happens once at the Arrow boundary
(``imageIO.imageColumnToNHWC``), so the converter piece here starts from a
uint8/float NHWC batch — dtype cast, BGR→RGB reorder, and model rescaling are
the parts that belong inside the XLA program, where they fuse with the model.
"""

from __future__ import annotations

from .function import GraphFunction


def buildSpImageConverter(channelOrder: str = "BGR",
                          img_dtype: str = "uint8",
                          scale: float | None = None,
                          offset: float | None = None) -> GraphFunction:
    """NHWC image batch (as stored: BGR, uint8) → float32 model-input batch.

    ``channelOrder``: order of the *incoming* batch ("BGR" = at-rest struct
    order, flipped to RGB here; "RGB" = passthrough). ``scale``/``offset``:
    optional affine rescale (e.g. scale=1/127.5, offset=-1 for the
    [-1, 1] preprocessing family).

    feeds: ``image``; fetches: ``converted``.
    """
    import jax.numpy as jnp

    flip = channelOrder.upper() == "BGR"
    del img_dtype  # cast is unconditional; kept for reference-parity arity

    def fn(feeds: dict) -> dict:
        x = jnp.asarray(feeds["image"])
        if x.ndim != 4:
            raise ValueError(f"Expected NHWC batch, got shape {x.shape}")
        x = x.astype(jnp.float32)
        if flip and x.shape[-1] >= 3:
            x = jnp.concatenate([x[..., 2::-1][..., :3], x[..., 3:]], axis=-1)
        if scale is not None:
            x = x * scale
        if offset is not None:
            x = x + offset
        return {"converted": x}

    return GraphFunction(fn, ["image"], ["converted"])


def buildFlattener(input_name: str = "input",
                   output_name: str = "flattened") -> GraphFunction:
    """(N, ...) batch → (N, prod(...)) float32 — the piece the reference
    appended so model outputs land as per-row vectors in the DataFrame."""
    import jax.numpy as jnp

    def fn(feeds: dict) -> dict:
        x = jnp.asarray(feeds[input_name])
        return {output_name: x.reshape(x.shape[0], -1).astype(jnp.float32)}

    return GraphFunction(fn, [input_name], [output_name])
