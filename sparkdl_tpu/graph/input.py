"""XlaInputGraph — normalize any model artifact into a GraphFunction.

Reference surface: ``python/sparkdl/graph/input.py``'s ``TFInputGraph`` with
``fromGraph``/``fromGraphDef``/``fromSavedModel``/``fromCheckpoint``
(+``WithSignature`` variants) — one constructor per TF-1.x artifact kind, all
normalizing to (graphdef, feeds, fetches) (SURVEY.md §2.1).

TPU-native re-design: the native artifact kinds are jax-world — functions,
flax modules + pytrees, Keras-3(jax) models, serialized StableHLO
(``GraphFunction.dump``), and weight checkpoints (orbax/safetensors/h5).
Legacy TF artifacts (SavedModel, frozen GraphDef, TF checkpoints) remain
loadable through a compat bridge: the TF graph is pruned to feeds/fetches and
embedded via ``jax2tf.call_tf`` — callable from jax, compiled by XLA — so
reference users' existing exported models still run. The bridge requires the
CPU backend (TF kernels); everything else compiles for TPU.
"""

from __future__ import annotations

import os
from typing import Callable, Mapping, Sequence

from .function import GraphFunction
from .utils import op_name, tensor_name


class XlaInputGraph:
    """A normalized (GraphFunction, feeds, fetches) triple."""

    def __init__(self, gfn: GraphFunction):
        self.gfn = gfn

    @property
    def input_names(self) -> list[str]:
        return self.gfn.input_names

    @property
    def output_names(self) -> list[str]:
        return self.gfn.output_names

    def translateToGraphFunction(self) -> GraphFunction:
        return self.gfn

    asGraphFunction = translateToGraphFunction

    # ---- native jax-world artifacts --------------------------------------

    @classmethod
    def fromGraph(cls, fn: Callable, feed_names: Sequence[str] | None = None,
                  fetch_names: Sequence[str] | None = None) -> "XlaInputGraph":
        """A jax-traceable function (the 'live graph' of this world)."""
        return cls(GraphFunction.fromJax(fn, feed_names, fetch_names))

    @classmethod
    def fromGraphFunction(cls, gfn: GraphFunction) -> "XlaInputGraph":
        return cls(gfn)

    @classmethod
    def fromSerialized(cls, path_or_bytes) -> "XlaInputGraph":
        """A ``GraphFunction.dump`` artifact (StableHLO) — the analogue of
        loading a frozen GraphDef file."""
        if isinstance(path_or_bytes, (bytes, bytearray)):
            return cls(GraphFunction.deserialize(bytes(path_or_bytes)))
        return cls(GraphFunction.load(os.fspath(path_or_bytes)))

    @classmethod
    def fromKeras(cls, model_or_file) -> "XlaInputGraph":
        return cls(GraphFunction.fromKeras(model_or_file))

    @classmethod
    def fromFlax(cls, module, variables, **apply_kwargs) -> "XlaInputGraph":
        return cls(GraphFunction.fromFlax(module, variables, **apply_kwargs))

    @classmethod
    def fromCheckpoint(cls, checkpoint_path: str, model_fn: Callable,
                       input_name: str = "input",
                       output_name: str = "output") -> "XlaInputGraph":
        """Weights-at-rest + a model function → GraphFunction.

        ``checkpoint_path``: an orbax checkpoint dir, a ``.safetensors``
        file, a Keras ``.h5``/``.weights.h5`` file, or a TF checkpoint
        prefix. ``model_fn(params, batch)`` binds them. (The reference's
        ``fromCheckpoint`` instead pulled the graph out of the colocated
        meta-graph — jax separates weights from program, so the program must
        be supplied.)
        """
        params = load_weights(checkpoint_path)
        return cls(GraphFunction.fromJax(
            lambda batch: model_fn(params, batch),
            [input_name], [output_name]))

    # ---- TF-era compat bridge (jax2tf.call_tf) ---------------------------

    @classmethod
    def fromSavedModel(cls, saved_model_dir: str,
                       signature: str = "serving_default",
                       feed_names: Sequence[str] | None = None,
                       fetch_names: Sequence[str] | None = None
                       ) -> "XlaInputGraph":
        """TF-2 SavedModel → GraphFunction via jax2tf.call_tf (CPU backend).

        Reference parity: ``TFInputGraph.fromSavedModel(WithSignature)`` —
        the signature's structured inputs/outputs become the feeds/fetches.
        """
        import tensorflow as tf
        from jax.experimental import jax2tf

        loaded = tf.saved_model.load(saved_model_dir)
        try:
            sig = loaded.signatures[signature]
        except KeyError:
            raise ValueError(
                f"SavedModel has no signature {signature!r}; available: "
                f"{list(loaded.signatures)}") from None
        in_keys = sorted(sig.structured_input_signature[1])
        out_keys = sorted(sig.structured_outputs)
        # feed/fetch names select BY NAME from the signature (never
        # positionally): they must be signature keys.
        feeds = [op_name(n) for n in feed_names] if feed_names else in_keys
        fetches = ([op_name(n) for n in fetch_names] if fetch_names
                   else out_keys)
        for n in feeds:
            if n not in in_keys:
                raise ValueError(f"Feed {n!r} is not a signature input; "
                                 f"inputs: {in_keys}")
        for n in fetches:
            if n not in out_keys:
                raise ValueError(f"Fetch {n!r} is not a signature output; "
                                 f"outputs: {out_keys}")
        if set(feeds) != set(in_keys):
            raise ValueError(
                f"All signature inputs must be fed; missing "
                f"{sorted(set(in_keys) - set(feeds))}")
        call = jax2tf.call_tf(
            lambda *args: sig(**dict(zip(in_keys, args))))
        # keep a reference to the loaded object alive in the closure
        def fn(feeds_dict: dict) -> dict:
            _ = loaded
            out = call(*[feeds_dict[n] for n in in_keys])
            return {f: out[f] for f in fetches}

        return cls(GraphFunction(fn, feeds, fetches))

    @classmethod
    def fromSavedModelWithSignature(cls, saved_model_dir: str,
                                    signature_def_key: str
                                    ) -> "XlaInputGraph":
        return cls.fromSavedModel(saved_model_dir,
                                  signature=signature_def_key)

    @classmethod
    def fromGraphDef(cls, graph_def, feed_names: Sequence[str],
                     fetch_names: Sequence[str]) -> "XlaInputGraph":
        """A frozen TF GraphDef (proto or serialized bytes) pruned to
        feeds/fetches, embedded via jax2tf.call_tf."""
        import tensorflow as tf
        from jax.experimental import jax2tf

        if isinstance(graph_def, (bytes, bytearray)):
            gd = tf.compat.v1.GraphDef()
            gd.ParseFromString(bytes(graph_def))
            graph_def = gd
        wrapped = tf.compat.v1.wrap_function(
            lambda: tf.graph_util.import_graph_def(graph_def, name=""), [])
        pruned = wrapped.prune(
            feeds=[wrapped.graph.get_tensor_by_name(tensor_name(n))
                   for n in feed_names],
            fetches=[wrapped.graph.get_tensor_by_name(tensor_name(n))
                     for n in fetch_names])
        call = jax2tf.call_tf(pruned)
        feeds = [op_name(n) for n in feed_names]
        fetches = [op_name(n) for n in fetch_names]

        def fn(feeds_dict: dict) -> dict:
            out = call(*[feeds_dict[n] for n in feeds])
            if not isinstance(out, (tuple, list)):
                out = (out,)
            return dict(zip(fetches, out))

        return cls(GraphFunction(fn, feeds, fetches))


TFInputGraph = XlaInputGraph  # reference-compat alias


# ---------------------------------------------------------------------------
# Weight loading (offline formats; SURVEY.md §7 "weight import offline")
# ---------------------------------------------------------------------------

def load_weights(path: str) -> Mapping:
    """Checkpoint file/dir → pytree (dict) of numpy arrays.

    Supports: orbax checkpoint dirs, .safetensors, Keras .h5 weight files,
    .npz, and TF2 checkpoints (prefix with .index beside it).
    """
    path = os.fspath(path)
    if os.path.isdir(path):
        if any(n.startswith("ocdbt") or n in ("_METADATA", "manifest.ocdbt")
               or n.endswith(".orbax-checkpoint") or n == "_CHECKPOINT_METADATA"
               for n in os.listdir(path)) or _looks_like_orbax(path):
            import orbax.checkpoint as ocp
            with ocp.PyTreeCheckpointer() as ckptr:
                return ckptr.restore(path)
        raise ValueError(f"Unrecognized checkpoint directory {path!r}")
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file
        return _unflatten(load_file(path))  # _unflatten splits "/" and "."
    if path.endswith((".h5", ".hdf5")):
        return _load_h5(path)
    if path.endswith(".npz"):
        import numpy as np
        with np.load(path, allow_pickle=False) as z:
            return _unflatten({k: z[k] for k in z.files})
    if os.path.exists(path + ".index"):
        return _load_tf_checkpoint(path)
    raise ValueError(f"Cannot determine checkpoint format of {path!r}")


def _looks_like_orbax(path: str) -> bool:
    try:
        import orbax.checkpoint as ocp
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.metadata(path)
        return True
    except Exception:
        return False


def _unflatten(flat: Mapping[str, object]) -> dict:
    # Both "/" and "." appear as path separators in the wild: this repo's
    # own safetensors writers join with "/", Keras h5 uses "/", TF
    # checkpoints use "/", npz conventions vary.
    tree: dict = {}
    for key, val in flat.items():
        parts = key.replace("/", ".").split(".")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return tree


def _load_h5(path: str) -> dict:
    import h5py
    out: dict = {}

    def visit(name, obj):
        if isinstance(obj, h5py.Dataset):
            node = out
            parts = [p for p in name.split("/") if p]
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = obj[()]

    with h5py.File(path, "r") as f:
        f.visititems(visit)
    return out


def _load_tf_checkpoint(prefix: str) -> dict:
    import tensorflow as tf
    reader = tf.train.load_checkpoint(prefix)
    flat = {name: reader.get_tensor(name)
            for name in reader.get_variable_to_shape_map()}
    return _unflatten(flat)
