"""Graph toolkit — the reference's L2 layer, rebuilt on jax.export/StableHLO.

Reference: ``python/sparkdl/graph/`` (builder, input, pieces, utils,
tensorframes_udf) — SURVEY.md §1-L2/§2.1.
"""

from .builder import GraphNode, IsolatedGraph, IsolatedSession
from .function import GraphFunction
from .input import TFInputGraph, XlaInputGraph, load_weights
from .pieces import buildFlattener, buildSpImageConverter
from .udf import makeGraphUDF
from .utils import op_name, tensor_name, validated_input, validated_output

__all__ = [
    "GraphFunction", "IsolatedSession", "IsolatedGraph", "GraphNode",
    "XlaInputGraph", "TFInputGraph", "load_weights",
    "buildSpImageConverter", "buildFlattener", "makeGraphUDF",
    "op_name", "tensor_name", "validated_input", "validated_output",
]
