"""DeepImageFeaturizer / DeepImagePredictor — named pretrained-model transformers.

The flagship transfer-learning surface (reference:
``python/sparkdl/transformers/named_image.py``, SURVEY.md §2.1/§3.1):
``DeepImageFeaturizer(modelName=...)`` emits the model's bottleneck features
for downstream shallow learners; ``DeepImagePredictor`` emits (optionally
decoded) class predictions.

TPU-native shape: model lookup in :mod:`sparkdl_tpu.models.registry`, weights
as a flax pytree, and the whole resize→preprocess→truncated-model graph
compiled as ONE ``jax.jit`` program (the reference stitched TF graph pieces
and ran them via TensorFrames JNI). Zero-egress environment: weights are
seeded-random by default; ``weightsPath`` loads locally-provided msgpack/
safetensors weights.
"""

from __future__ import annotations

import os

from ..core.params import (HasSeed, Param, Params, TypeConverters,
                           keyword_only)
from ..models import registry as model_registry
from .xla_image import XlaImageTransformer


class _NamedImageTransformer(XlaImageTransformer, HasSeed):
    """Shared machinery: resolve modelName → (module, params, apply fn)."""

    modelName = Param(Params, "modelName",
                      "named model from SUPPORTED_MODELS",
                      TypeConverters.toString)
    computeDtype = Param(Params, "computeDtype",
                         "activation dtype for the forward pass: float32 "
                         "(default, exact) or bfloat16 (MXU-native — ~2x "
                         "on TPU, features differ at ~1e-2 relative). "
                         "Params stay float32 either way.",
                         TypeConverters.toString)
    weightsPath = Param(Params, "weightsPath",
                        "local weights file: flax msgpack/safetensors, or a "
                        "Keras-applications .h5/.hdf5 (name-mapped import; "
                        "ResNets then run the keras v1 stride placement). "
                        "Random seeded init when unset (zero-egress "
                        "environment)", TypeConverters.toString)

    _features_only = True

    def __init__(self):
        super(XlaImageTransformer, self).__init__()
        self._setDefault(batchSize=32, channelOrder="RGB",
                         outputMode="vector", inputCol="image", seed=0,
                         computeDtype="float32")
        self._variables = None

    def _compute_dtype(self):
        import jax.numpy as jnp
        # isSet/hasDefault dance: instances revived by MLWritable.load from
        # an older save bypass __init__ and may lack the default.
        name = (self.getOrDefault(self.computeDtype)
                if self.isSet("computeDtype")
                or self.hasDefault("computeDtype") else "float32")
        try:
            return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[name]
        except KeyError:
            raise ValueError(
                f"computeDtype must be 'float32' or 'bfloat16', "
                f"got {name!r}") from None

    def getModelName(self) -> str:
        return self.getOrDefault(self.modelName)

    def _model(self) -> model_registry.NamedImageModel:
        return model_registry.get_model(self.getModelName())

    def _keras_semantics(self) -> bool:
        """True when the installed weights come from a Keras-applications
        ``.h5`` file, in which case ResNets must run the keras v1 stride
        placement (models/pretrained.py) for the weights to be faithful."""
        return (self.isDefined(self.weightsPath)
                and self.getOrDefault(self.weightsPath)
                        .endswith((".h5", ".hdf5")))

    def _build_kwargs(self) -> dict:
        if self._keras_semantics() \
                and self.getModelName().startswith("ResNet"):
            return {"stride_on_3x3": False}
        return {}

    def _load_variables(self):
        # getattr: instances revived by MLWritable.load bypass __init__.
        if getattr(self, "_variables", None) is None:
            m = self._model()
            variables = m.init_params(seed=self.getOrDefault(self.seed),
                                      **self._build_kwargs())
            if self.isDefined(self.weightsPath):
                path = self.getOrDefault(self.weightsPath)
                if path.endswith((".h5", ".hdf5")):
                    from ..models import pretrained
                    variables = pretrained.load_pretrained(
                        self.getModelName(), path, template=variables)
                elif path.endswith(".safetensors"):
                    variables = model_registry.load_safetensors(variables, path)
                else:
                    variables = model_registry.load_weights(variables, path)
            self._variables = variables
        return self._variables

    def setWeights(self, variables):
        """Directly install a flax variables pytree (e.g. a fine-tuned one)."""
        self._variables = variables
        return self

    def _make_fn(self):
        import jax.numpy as jnp
        m = self._model()
        variables = self._load_variables()
        dt = self._compute_dtype()
        if dt != jnp.float32:
            # Serve the conv/dense KERNELS in the compute dtype (a local
            # copy — self._variables stays f32 for setWeights/save
            # fidelity): numerically identical, since those are exactly
            # the leaves flax promote_dtype casts at use; BN stats/
            # scale/bias (1-D) stay f32 because flax BatchNorm runs its
            # normalization math in f32 WITHOUT casting them — see
            # cast_float_leaves. Halves weight HBM residency and drops
            # the per-dispatch kernel cast from every program call.
            from ..models.pretrained import cast_float_leaves
            variables = cast_float_leaves(variables, dt)
        apply = m.apply_fn(features_only=self._features_only,
                           dtype=dt, **self._build_kwargs())
        return lambda batch: apply(variables, batch)

    def _runner_key(self) -> tuple:
        return (self.getBatchSize(), self.getModelName(),
                self._features_only, str(self._compute_dtype()),
                id(self._load_variables()))

    def _transform(self, dataset):
        # Pin the static input size from the model registry before the
        # generic image path runs.
        m = self._model()
        self._set(inputSize=m.input_size)
        return super()._transform(dataset)

    def _save_payload(self, path: str):
        if getattr(self, "_variables", None) is not None:
            model_registry.save_weights(self._variables,
                                        os.path.join(path, "weights.msgpack"))

    def _load_payload(self, path: str, meta: dict):
        self._variables = None
        wpath = os.path.join(path, "weights.msgpack")
        if os.path.exists(wpath):
            template = self._model().init_params(
                seed=self.getOrDefault(self.seed))
            self._variables = model_registry.load_weights(template, wpath)


class DeepImageFeaturizer(_NamedImageTransformer):
    """Bottleneck-feature extractor for transfer learning (BASELINE config 1:
    ``Pipeline([DeepImageFeaturizer(InceptionV3), LogisticRegression])``)."""

    _features_only = True

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, modelName=None,
                 batchSize=None, weightsPath=None, seed=None,
                 computeDtype=None):
        super().__init__()
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, modelName=None,
                  batchSize=None, weightsPath=None, seed=None,
                  computeDtype=None):
        return self._set(**self._input_kwargs)

    def featureDim(self) -> int:
        return self._model().feature_dim


class DeepImagePredictor(_NamedImageTransformer):
    """Full-model classifier. ``decodePredictions=True`` emits a struct column
    of top-K {class, label, score} like the reference's decoded output."""

    _features_only = False

    decodePredictions = Param(Params, "decodePredictions",
                              "emit top-K decoded predictions instead of "
                              "raw logits", TypeConverters.toBoolean)
    topK = Param(Params, "topK", "K for decoded predictions",
                 TypeConverters.toInt)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, modelName=None,
                 batchSize=None, weightsPath=None, seed=None,
                 decodePredictions=None, topK=None, computeDtype=None):
        super().__init__()
        self._setDefault(decodePredictions=False, topK=5)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, modelName=None,
                  batchSize=None, weightsPath=None, seed=None,
                  decodePredictions=None, topK=None, computeDtype=None):
        return self._set(**self._input_kwargs)

    def _transform(self, dataset):
        out = super()._transform(dataset)
        if not self.getOrDefault(self.decodePredictions):
            return out
        import numpy as np
        import pyarrow as pa

        from ..core.frame import _length_preserving, _set_column
        out_col = self.getOutputCol()
        top = self.getOrDefault(self.topK)

        def decode_op(batch: pa.RecordBatch) -> pa.RecordBatch:
            if batch.num_rows == 0:
                typ = pa.list_(pa.struct([("class", pa.int32()),
                                          ("label", pa.string()),
                                          ("score", pa.float32())]))
                return _set_column(batch, out_col, pa.array([], type=typ))
            # zero-copy Arrow→ndarray off the packed logits column — the
            # to_pylist round-trip built 1000 Python floats per row on the
            # scoring hot path.
            from .tensor import columnToNdarray
            logits = columnToNdarray(batch.column(out_col), None,
                                     dtype=np.float32)
            decoded = model_registry.decodePredictions(logits, top=top)
            typ = pa.list_(pa.struct([("class", pa.int32()),
                                      ("label", pa.string()),
                                      ("score", pa.float32())]))
            return _set_column(batch, out_col, pa.array(decoded, type=typ))

        return out.mapBatches(_length_preserving(decode_op))
