from .keras_image import KerasImageFileTransformer, defaultImageLoader
from .named_image import DeepImageFeaturizer, DeepImagePredictor
from .tensor import KerasTransformer, XlaTransformer
from .xla_image import XlaImageTransformer

# Reference-name alias: the reference's TFImageTransformer applied an
# arbitrary compute graph to an image column; XlaImageTransformer is that
# role with jittable functions instead of TF graphs.
TFImageTransformer = XlaImageTransformer
TFTransformer = XlaTransformer

__all__ = [
    "XlaImageTransformer", "TFImageTransformer",
    "DeepImageFeaturizer", "DeepImagePredictor",
    "KerasImageFileTransformer", "defaultImageLoader",
    "XlaTransformer", "TFTransformer", "KerasTransformer",
]
