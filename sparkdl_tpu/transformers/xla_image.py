"""XlaImageTransformer — apply an arbitrary jittable function to an image column.

The TFImageTransformer of this framework (reference:
``python/sparkdl/transformers/tf_image.py``, SURVEY.md §2.1/§3.1): where the
reference accepted an arbitrary TF graph and executed it per partition through
TensorFrames, this transformer accepts an arbitrary **jittable function**
``fn(batch)`` over NHWC float batches and executes it as one XLA program on
the TPU, fed by the streaming scoring engine (``transformers/streaming.py``):
parallel host decode → pad/prefetch → one continuous cross-partition device
stream → overlap-worker Arrow encode.

The whole preprocessing+model chain lives inside one jit boundary, so XLA
fuses elementwise preprocessing into the model's first convolution — the
reference's graph-stitching (spImageConverter piece ∘ model graph) collapses
into compiler fusion.
"""

from __future__ import annotations

import threading

import numpy as np
import pyarrow as pa

from ..core import ingest
from ..core.frame import DataFrame
from ..core.params import (HasBatchSize, HasInputCol, HasOnError,
                           HasOutputCol, Param, Params, TypeConverters,
                           keyword_only)
from ..core.pipeline import Transformer
from ..core.runtime import BatchRunner
from ..image import imageIO
from .payloads import PicklesCallableParams
from .streaming import StreamScorer


def arrayColumnToArrow(result: np.ndarray) -> pa.Array:
    """N-d numpy → Arrow: 1-d as primitive array, N-d as list<primitive> rows.

    The nested case builds list<primitive> from the flat value buffer
    (zero-copy) instead of round-tripping through Python lists — the output
    column of a batch-scoring job can be hundreds of MB."""
    if result.ndim == 1:
        return pa.array(result)
    flat = np.ascontiguousarray(result).reshape(len(result), -1)
    offsets64 = np.arange(len(flat) + 1, dtype=np.int64) * flat.shape[1]
    values = pa.array(flat.reshape(-1))
    if offsets64[-1] > np.iinfo(np.int32).max:
        # >2**31 total elements only fits large_list offsets.
        return pa.LargeListArray.from_arrays(pa.array(offsets64), values)
    return pa.ListArray.from_arrays(
        pa.array(offsets64.astype(np.int32)), values)


def emptyVectorColumn() -> pa.Array:
    return pa.array([], type=pa.list_(pa.float32()))


class XlaImageTransformer(PicklesCallableParams, Transformer, HasInputCol,
                          HasOutputCol, HasBatchSize, HasOnError):
    """Applies ``fn`` (jittable, NHWC float32 in, array out) to an image column.

    ``inputSize=(H, W)`` resizes every image to a static shape (XLA needs
    static shapes; mixed-size columns are resized on the host feed path).
    ``onError='quarantine'`` dead-letters rows whose image payload fails
    to decode instead of killing the job (see README "Scoring failure
    semantics"; read them back via :meth:`deadLetters`).
    """

    fn = Param(Params, "fn", "jittable function applied to NHWC batches",
               TypeConverters.toCallable)
    inputSize = Param(Params, "inputSize", "static (H, W) every image is "
                      "resized to before entering the XLA program",
                      TypeConverters.toShape)
    channelOrder = Param(Params, "channelOrder",
                         "channel order fed to fn: RGB (default) or BGR",
                         TypeConverters.toString)
    outputMode = Param(Params, "outputMode",
                       "output column content: 'vector' (list<float>) or "
                       "'image' (uint8 image struct)", TypeConverters.toString)
    numDevices = Param(Params, "numDevices",
                       "devices to shard inference batches over: 1 (default) "
                       "single-device, -1 all visible — the reference's "
                       "partition-parallel executors become mesh devices",
                       TypeConverters.toInt)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, fn=None, inputSize=None,
                 batchSize=None, channelOrder=None, outputMode=None,
                 numDevices=None, onError=None):
        super().__init__()
        self._setDefault(batchSize=32, channelOrder="RGB", outputMode="vector",
                         inputCol="image", numDevices=1, onError="raise")
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, fn=None, inputSize=None,
                  batchSize=None, channelOrder=None, outputMode=None,
                  numDevices=None, onError=None):
        return self._set(**self._input_kwargs)

    def _make_fn(self):
        """Hook for subclasses that derive fn from other params."""
        return self.getOrDefault(self.fn)

    def _num_devices(self) -> int:
        # subclasses with their own __init__ may never have set the default
        return (self.getOrDefault(self.numDevices)
                if self.isSet("numDevices") or self.hasDefault("numDevices")
                else 1)

    def _runner_key(self) -> tuple:
        """Cache key for the compiled runner; subclasses add model identity."""
        return (self.getBatchSize(), self._num_devices(),
                id(self._paramMap.get(self.fn)) if self.hasParam("fn") else 0)

    def _mesh(self):
        from ..core import runtime
        n = self._num_devices()
        if n == 1:
            return None
        devs = runtime.devices()
        n = len(devs) if n == -1 else n
        if n > len(devs):
            raise ValueError(f"numDevices={n} but only {len(devs)} visible")
        return runtime.make_mesh({"data": n}, devices_=devs[:n])

    def _feed_key(self) -> tuple:
        """The feed-side configuration the compiled program depends on:
        fused mode changes the jitted prologue, size/order change what it
        does — a runner compiled for one must not serve another."""
        size = (tuple(self.getOrDefault(self.inputSize))
                if self.isDefined(self.inputSize) else None)
        return (ingest.fused_preprocess_default(), size,
                self.getOrDefault(self.channelOrder).upper())

    def _make_preprocess(self):
        """Fused on-device preprocess prologue (ISSUE 7): with
        ``SPARKDL_FUSED_PREPROCESS`` on (default), the host ships
        storage-dtype **BGR** batches (zero-copy views at native size
        when the column layout allows — see ``imageIO.imageColumnFeed``)
        and the compiled program does the rest: cast (the runner's
        ``input_cast``), BGR→RGB flip, and ``jax.image.resize`` to the
        static input size when the wire size differs — all fused by XLA
        into the model's first ops. Shapes are static at trace time, so
        each distinct wire size is one compilation (a ``recompile``
        event), and a wire size equal to the target skips the resize
        entirely (bit-identical to the host-resized feed).

        Fused mode requires a STATIC ``inputSize``: without one the target
        shape is pinned per partition at decode time, which this prologue
        (traced once per runner) cannot know — a native-size chunk would
        ship and never be resized. No ``inputSize`` → no prologue, and the
        feed stays on the legacy host pack path."""
        if not ingest.fused_preprocess_default() \
                or not self.isDefined(self.inputSize):
            return None
        size = self.getOrDefault(self.inputSize)
        h, w = int(size[0]), int(size[1])
        flip = self.getOrDefault(self.channelOrder).upper() == "RGB"
        import jax
        import jax.numpy as jnp

        def prologue(x):
            if flip and x.shape[-1] >= 3:
                x = jnp.concatenate([x[..., 2::-1], x[..., 3:]], axis=-1)
            if x.shape[1] != h or x.shape[2] != w:
                x = jax.image.resize(
                    x, (x.shape[0], h, w, x.shape[-1]), method="bilinear")
            return x

        return prologue

    def _get_runner(self) -> BatchRunner:
        """One BatchRunner (→ one XLA compilation) per param configuration.

        transform() is called repeatedly on the same stage (fit then
        transform, batch scoring jobs, ...); rebuilding the jit wrapper each
        time would recompile the model — the primary TPU perf failure mode."""
        key = (self._runner_key(), self._feed_key())
        cached = getattr(self, "_runner_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        import jax.numpy as jnp
        # Host batches are fed as uint8 (4x fewer bytes over the host→HBM
        # link); the runner casts to f32 inside the program, where XLA fuses
        # it into the first conv. ``fn`` still sees float32 NHWC (RGB when
        # channelOrder says so — in fused mode the prologue owns the flip
        # and the resize; see _make_preprocess).
        runner = BatchRunner(self._make_fn(), self.getBatchSize(),
                             mesh=self._mesh(), input_cast=jnp.float32,
                             preprocess=self._make_preprocess())
        self._runner_cache = (key, runner)
        return runner

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getInputCol()
        out_col = self.getOutputCol()
        size = (self.getOrDefault(self.inputSize)
                if self.isDefined(self.inputSize) else (None, None))
        order = self.getOrDefault(self.channelOrder)
        out_mode = self.getOrDefault(self.outputMode)
        batch_size = self.getBatchSize()
        runner = self._get_runner()

        # Fused feed only when the prologue exists to own flip/resize —
        # i.e. a static inputSize is defined (see _make_preprocess).
        fused = ingest.fused_preprocess_default() \
            and self.isDefined(self.inputSize)

        # Wire-shape budget: every distinct native size this stage ships
        # is one XLA compilation, so a many-sized dataset (per-directory
        # dumps, size-sorted scans) must not recompile unboundedly where
        # the host-pack feed compiled once. Shared by the thread decoder
        # and the process spec (evaluated in the parent — pool children
        # are stateless); metadata-only, no pixel work. The budget lives
        # WITH the compiled program (one set per runner, like
        # _runner_cache), not per transform() call: the jit cache it
        # bounds is cumulative across calls, so the budget must be too.
        if getattr(self, "_wire_budget_for", None) is not runner:
            self._wire_budget = set()
            self._wire_budget_lock = threading.Lock()
            self._wire_budget_for = runner
        wire_shapes = self._wire_budget
        wire_lock = self._wire_budget_lock
        max_wire = ingest.max_wire_shapes_default()

        def chunk_native_ok(chunk_col, length, h, w):
            """Wire-shape-budget verdict for one chunk: ``(native_ok,
            uniform_meta)`` — may the feed ship it zero-copy at its
            native size? A budget slot is consumed only for a chunk the
            view can ACTUALLY deliver (the view attempt below): metadata
            uniformity alone is not deliverability, and a slot burned for
            a chunk whose view then declines (truncated payloads, exotic
            storage) would strand that slot for the runner's lifetime on
            a shape that only ever packs."""
            if not fused or length <= 1:
                return True, None  # 1-row chunks pack (fallback parity)
            meta = imageIO.imageColumnUniformSize(chunk_col)
            if meta is None:
                return True, None  # not view-shippable; the feed packs
            mh, mw = meta[0], meta[1]
            if (mh, mw) == (h, w) or mh * mw > h * w:
                return True, meta  # target-shaped / packs anyway
            if imageIO.imageColumnNHWCView(chunk_col, uniform=meta) is None:
                return True, meta  # layout declines; the feed packs
            # Key on the FULL meta: the mode determines the view's
            # storage DTYPE, and each distinct (shape, dtype) signature
            # is its own XLA compilation — (h, w, c) alone would let a
            # u8/f32 mix compile 2x the budgeted programs.
            with wire_lock:
                if meta in wire_shapes:
                    return True, meta
                if len(wire_shapes) < max_wire:
                    wire_shapes.add(meta)
                    return True, meta
                return False, meta

        def chunk_verdicts(col, num_rows, h, w) -> dict:
            """native_ok per chunk start, evaluated HERE on the consumer
            thread in stream order BEFORE any chunk decodes: pool workers
            racing for the last budget slots would make native-vs-pack
            assignment — and therefore the resize path and output bits —
            depend on thread timing, and diverge between the thread and
            process backends. Mirrors StreamScorer's chunking
            (``chunk_rows=batch_size`` below); decode falls back to the
            pack path for any unaligned start (the quarantine
            row-fallback's 1-row decodes pack regardless)."""
            if not fused:
                return {}
            out = {}
            for s in range(0, num_rows, batch_size):
                length = min(batch_size, num_rows - s)
                out[s] = chunk_native_ok(col.slice(s, length), length, h, w)
            return out

        def feed_params(col: pa.Array) -> tuple:
            h, w = size
            if h is None or w is None:
                # No static inputSize: pin the partition-wide target shape
                # from row 0 BEFORE chunking, or mixed-size partitions would
                # produce per-chunk shapes (and recompiles/concat failures).
                h = int(col.field("height")[0].as_py()) if h is None else h
                w = int(col.field("width")[0].as_py()) if w is None else w
            # uint8 feed (the runner casts on-device — 4x fewer bytes over
            # the host→HBM link) when every row stores uint8 pixels; float-
            # mode (CV_32F*) columns keep a float32 feed, which the runner's
            # in-graph astype(f32) passes through untouched.
            modes = col.field("mode").to_numpy(zero_copy_only=False)
            feed_dtype = (np.uint8 if all(
                imageIO.ocvTypeByMode(int(m)).dtype == "uint8"
                for m in np.unique(modes)) else np.float32)
            return h, w, feed_dtype

        def make_decoder(batch: pa.RecordBatch):
            # One Arrow partition may exceed the device batch: decode AND
            # run per device-chunk, so peak host memory is O(batchSize)
            # decoded pixels, not O(partition) (round-1 verdict weak #4).
            # Each chunk decode runs on the parallel decode pool
            # (SPARKDL_DECODE_WORKERS) while earlier chunks execute; the
            # quarantine fallback calls the same decoder per row. In fused
            # mode (ISSUE 7) imageColumnFeed ships the cheapest batch the
            # policy allows (zero-copy native-size storage-dtype views
            # when the layout permits) and the runner's prologue does
            # flip/cast/resize on device.
            col = batch.column(in_col)
            h, w, feed_dtype = feed_params(col)
            native = chunk_verdicts(col, batch.num_rows, h, w)

            def decode(start: int, length: int) -> np.ndarray:
                ok, uniform = native.get(start, (False, None))
                return imageIO.imageColumnFeed(
                    col.slice(start, length), h, w, channelOrder=order,
                    dtype=feed_dtype, fused=fused, native_ok=ok,
                    uniform=uniform)

            return decode

        def decoder_spec(batch: pa.RecordBatch):
            # Process-backend eligibility (SPARKDL_DECODE_BACKEND=process):
            # per-chunk picklable tasks — the module-level factory plus a
            # COMPACTED Arrow slice (concat_arrays truncates the buffers;
            # a bare slice would pickle the whole partition per chunk).
            col = batch.column(in_col)
            h, w, feed_dtype = feed_params(col)
            dtype_name = np.dtype(feed_dtype).name
            native = chunk_verdicts(col, batch.num_rows, h, w)

            def spec(start: int, length: int) -> tuple:
                # the pool child re-derives the (cheap) uniform scan from
                # the compacted chunk; only the budget VERDICT — parent
                # state — ships in the payload
                chunk = pa.concat_arrays([col.slice(start, length)])
                return ingest.decode_image_chunk, \
                    (chunk, h, w, order, dtype_name, fused,
                     native.get(start, (False, None))[0])

            return spec

        # Each device chunk converts to its FINAL Arrow representation on
        # the scorer's overlap worker as it lands — the float32 model
        # output for a whole partition never materializes on the host, and
        # the device feed never waits on the conversion.
        if out_mode == "image":
            def encode(result: np.ndarray) -> pa.Array:
                structs = imageIO.nhwcToStructs(
                    np.clip(result, 0, 255).astype(np.uint8),
                    channelOrder=order)
                return pa.array(structs, type=imageIO.imageSchema)

            def empty_array() -> pa.Array:
                return pa.array([], type=imageIO.imageSchema)
        else:
            encode = arrayColumnToArrow
            empty_array = emptyVectorColumn

        on_error = self.getOnError()
        scorer = StreamScorer(runner, out_col, make_decoder, encode,
                              empty_array, chunk_rows=batch_size,
                              on_error=on_error, decoder_spec=decoder_spec)
        # Dead letters of the most recent materialized transform, read
        # back through HasOnError.deadLetters() after collect().
        self._quarantine_sink = scorer.sink
        return dataset.mapStream(scorer,
                                 changes_length=on_error == "quarantine")

    _pickled_params = ("fn",)
