"""XlaImageTransformer — apply an arbitrary jittable function to an image column.

The TFImageTransformer of this framework (reference:
``python/sparkdl/transformers/tf_image.py``, SURVEY.md §2.1/§3.1): where the
reference accepted an arbitrary TF graph and executed it per partition through
TensorFrames, this transformer accepts an arbitrary **jittable function**
``fn(batch)`` over NHWC float batches and executes it as one XLA program on
the TPU, fed by the streaming scoring engine (``transformers/streaming.py``):
parallel host decode → pad/prefetch → one continuous cross-partition device
stream → overlap-worker Arrow encode.

The whole preprocessing+model chain lives inside one jit boundary, so XLA
fuses elementwise preprocessing into the model's first convolution — the
reference's graph-stitching (spImageConverter piece ∘ model graph) collapses
into compiler fusion.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from ..core.frame import DataFrame
from ..core.params import (HasBatchSize, HasInputCol, HasOnError,
                           HasOutputCol, Param, Params, TypeConverters,
                           keyword_only)
from ..core.pipeline import Transformer
from ..core.runtime import BatchRunner
from ..image import imageIO
from .payloads import PicklesCallableParams
from .streaming import StreamScorer


def arrayColumnToArrow(result: np.ndarray) -> pa.Array:
    """N-d numpy → Arrow: 1-d as primitive array, N-d as list<primitive> rows.

    The nested case builds list<primitive> from the flat value buffer
    (zero-copy) instead of round-tripping through Python lists — the output
    column of a batch-scoring job can be hundreds of MB."""
    if result.ndim == 1:
        return pa.array(result)
    flat = np.ascontiguousarray(result).reshape(len(result), -1)
    offsets64 = np.arange(len(flat) + 1, dtype=np.int64) * flat.shape[1]
    values = pa.array(flat.reshape(-1))
    if offsets64[-1] > np.iinfo(np.int32).max:
        # >2**31 total elements only fits large_list offsets.
        return pa.LargeListArray.from_arrays(pa.array(offsets64), values)
    return pa.ListArray.from_arrays(
        pa.array(offsets64.astype(np.int32)), values)


def emptyVectorColumn() -> pa.Array:
    return pa.array([], type=pa.list_(pa.float32()))


class XlaImageTransformer(PicklesCallableParams, Transformer, HasInputCol,
                          HasOutputCol, HasBatchSize, HasOnError):
    """Applies ``fn`` (jittable, NHWC float32 in, array out) to an image column.

    ``inputSize=(H, W)`` resizes every image to a static shape (XLA needs
    static shapes; mixed-size columns are resized on the host feed path).
    ``onError='quarantine'`` dead-letters rows whose image payload fails
    to decode instead of killing the job (see README "Scoring failure
    semantics"; read them back via :meth:`deadLetters`).
    """

    fn = Param(Params, "fn", "jittable function applied to NHWC batches",
               TypeConverters.toCallable)
    inputSize = Param(Params, "inputSize", "static (H, W) every image is "
                      "resized to before entering the XLA program",
                      TypeConverters.toShape)
    channelOrder = Param(Params, "channelOrder",
                         "channel order fed to fn: RGB (default) or BGR",
                         TypeConverters.toString)
    outputMode = Param(Params, "outputMode",
                       "output column content: 'vector' (list<float>) or "
                       "'image' (uint8 image struct)", TypeConverters.toString)
    numDevices = Param(Params, "numDevices",
                       "devices to shard inference batches over: 1 (default) "
                       "single-device, -1 all visible — the reference's "
                       "partition-parallel executors become mesh devices",
                       TypeConverters.toInt)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, fn=None, inputSize=None,
                 batchSize=None, channelOrder=None, outputMode=None,
                 numDevices=None, onError=None):
        super().__init__()
        self._setDefault(batchSize=32, channelOrder="RGB", outputMode="vector",
                         inputCol="image", numDevices=1, onError="raise")
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, fn=None, inputSize=None,
                  batchSize=None, channelOrder=None, outputMode=None,
                  numDevices=None, onError=None):
        return self._set(**self._input_kwargs)

    def _make_fn(self):
        """Hook for subclasses that derive fn from other params."""
        return self.getOrDefault(self.fn)

    def _num_devices(self) -> int:
        # subclasses with their own __init__ may never have set the default
        return (self.getOrDefault(self.numDevices)
                if self.isSet("numDevices") or self.hasDefault("numDevices")
                else 1)

    def _runner_key(self) -> tuple:
        """Cache key for the compiled runner; subclasses add model identity."""
        return (self.getBatchSize(), self._num_devices(),
                id(self._paramMap.get(self.fn)) if self.hasParam("fn") else 0)

    def _mesh(self):
        from ..core import runtime
        n = self._num_devices()
        if n == 1:
            return None
        devs = runtime.devices()
        n = len(devs) if n == -1 else n
        if n > len(devs):
            raise ValueError(f"numDevices={n} but only {len(devs)} visible")
        return runtime.make_mesh({"data": n}, devices_=devs[:n])

    def _get_runner(self) -> BatchRunner:
        """One BatchRunner (→ one XLA compilation) per param configuration.

        transform() is called repeatedly on the same stage (fit then
        transform, batch scoring jobs, ...); rebuilding the jit wrapper each
        time would recompile the model — the primary TPU perf failure mode."""
        key = self._runner_key()
        cached = getattr(self, "_runner_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        import jax.numpy as jnp
        # Host batches are fed as uint8 (4x fewer bytes over the host→HBM
        # link); the runner casts to f32 inside the program, where XLA fuses
        # it into the first conv. ``fn`` still sees float32 NHWC.
        runner = BatchRunner(self._make_fn(), self.getBatchSize(),
                             mesh=self._mesh(), input_cast=jnp.float32)
        self._runner_cache = (key, runner)
        return runner

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getInputCol()
        out_col = self.getOutputCol()
        size = (self.getOrDefault(self.inputSize)
                if self.isDefined(self.inputSize) else (None, None))
        order = self.getOrDefault(self.channelOrder)
        out_mode = self.getOrDefault(self.outputMode)
        batch_size = self.getBatchSize()
        runner = self._get_runner()

        def make_decoder(batch: pa.RecordBatch):
            # One Arrow partition may exceed the device batch: decode AND
            # run per device-chunk, so peak host memory is O(batchSize)
            # decoded pixels, not O(partition) (round-1 verdict weak #4).
            # Each chunk decode runs on the parallel decode pool
            # (SPARKDL_DECODE_WORKERS) while earlier chunks execute; the
            # quarantine fallback calls the same decoder per row.
            col = batch.column(in_col)
            h, w = size
            if h is None or w is None:
                # No static inputSize: pin the partition-wide target shape
                # from row 0 BEFORE chunking, or mixed-size partitions would
                # produce per-chunk shapes (and recompiles/concat failures).
                h = int(col.field("height")[0].as_py()) if h is None else h
                w = int(col.field("width")[0].as_py()) if w is None else w
            # uint8 feed (the runner casts on-device — 4x fewer bytes over
            # the host→HBM link) when every row stores uint8 pixels; float-
            # mode (CV_32F*) columns keep a float32 feed, which the runner's
            # in-graph astype(f32) passes through untouched.
            modes = col.field("mode").to_numpy(zero_copy_only=False)
            feed_dtype = (np.uint8 if all(
                imageIO.ocvTypeByMode(int(m)).dtype == "uint8"
                for m in np.unique(modes)) else np.float32)

            def decode(start: int, length: int) -> np.ndarray:
                return imageIO.imageColumnToNHWC(
                    col.slice(start, length), h, w, channelOrder=order,
                    dtype=feed_dtype)

            return decode

        # Each device chunk converts to its FINAL Arrow representation on
        # the scorer's overlap worker as it lands — the float32 model
        # output for a whole partition never materializes on the host, and
        # the device feed never waits on the conversion.
        if out_mode == "image":
            def encode(result: np.ndarray) -> pa.Array:
                structs = imageIO.nhwcToStructs(
                    np.clip(result, 0, 255).astype(np.uint8),
                    channelOrder=order)
                return pa.array(structs, type=imageIO.imageSchema)

            def empty_array() -> pa.Array:
                return pa.array([], type=imageIO.imageSchema)
        else:
            encode = arrayColumnToArrow
            empty_array = emptyVectorColumn

        on_error = self.getOnError()
        scorer = StreamScorer(runner, out_col, make_decoder, encode,
                              empty_array, chunk_rows=batch_size,
                              on_error=on_error)
        # Dead letters of the most recent materialized transform, read
        # back through HasOnError.deadLetters() after collect().
        self._quarantine_sink = scorer.sink
        return dataset.mapStream(scorer,
                                 changes_length=on_error == "quarantine")

    _pickled_params = ("fn",)
