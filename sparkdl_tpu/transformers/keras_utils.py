"""Keras-3-on-JAX bridge: load a saved Keras model as a jittable pure function.

The reference executed Keras models by exporting the TF session graph
(``GraphFunction.fromKeras`` — SURVEY.md §2.1 graph builder). Here Keras 3
runs natively on the JAX backend: ``stateless_call`` gives a pure
``(variables, x) → y`` that jit-compiles for TPU like any flax apply.
"""

from __future__ import annotations

import os


def _keras():
    os.environ.setdefault("KERAS_BACKEND", "jax")
    import keras
    if keras.backend.backend() != "jax":
        raise RuntimeError(
            "Keras must run on the JAX backend for TPU execution; set "
            "KERAS_BACKEND=jax before importing keras (current: "
            f"{keras.backend.backend()!r})")
    return keras


def load_keras_model(model_file: str):
    return _keras().models.load_model(model_file, compile=False)


def keras_model_to_fn(model):
    """Keras model → jittable ``fn(batch)`` closing over its weights."""
    trainable = [v.value for v in model.trainable_variables]
    non_trainable = [v.value for v in model.non_trainable_variables]

    def fn(batch):
        out, _ = model.stateless_call(trainable, non_trainable, batch,
                                      training=False)
        return out

    return fn


def keras_file_to_fn(model_file: str):
    return keras_model_to_fn(load_keras_model(model_file))
