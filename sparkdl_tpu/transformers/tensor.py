"""XlaTransformer / KerasTransformer — jitted functions over numeric columns.

Reference: ``transformers/tf_tensor.py`` (``TFTransformer``) and
``transformers/keras_tensor.py`` (``KerasTransformer``) — SURVEY.md §2.1:
apply a TF graph / saved Keras model to array columns. Here the graph is any
jittable function (or a Keras-3-on-JAX model file) and execution is the same
pad/prefetch/jit BatchRunner pipeline the image transformers use.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from ..core import ingest
from ..core.frame import DataFrame
from ..core.ingest import columnToNdarray  # noqa: F401 — historical home;
# the implementation lives in core.ingest (no jax in that module's own
# imports) so process-pool decode children run it without device state
# (re-exported here for existing callers)
from ..core.params import (HasBatchSize, HasInputCol, HasOnError,
                           HasOutputCol, Param, Params, TypeConverters,
                           keyword_only)
from ..core.pipeline import Transformer
from ..core.runtime import BatchRunner
from .keras_utils import keras_file_to_fn
from .payloads import BundlesModelFile, PicklesCallableParams
from .xla_image import arrayColumnToArrow


class XlaTransformer(PicklesCallableParams, Transformer, HasInputCol,
                     HasOutputCol, HasBatchSize, HasOnError):
    """Applies a jittable ``fn(batch)`` to a numeric array column (the
    TFTransformer analogue). ``onError='quarantine'`` dead-letters rows
    whose payload fails to decode (ragged/mis-shaped arrays) instead of
    killing the job."""

    fn = Param(Params, "fn", "jittable function over (N, ...) float batches",
               TypeConverters.toCallable)
    inputShape = Param(Params, "inputShape",
                       "per-row shape to reshape flat list columns to "
                       "(optional; flat rows default to (N, D))",
                       TypeConverters.toShape)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, fn=None,
                 inputShape=None, batchSize=None, onError=None):
        super().__init__()
        self._setDefault(batchSize=64, onError="raise")
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, fn=None,
                  inputShape=None, batchSize=None, onError=None):
        return self._set(**self._input_kwargs)

    def _make_fn(self):
        return self.getOrDefault(self.fn)

    def _runner_key(self) -> tuple:
        return (self.getBatchSize(), id(self._paramMap.get(self.fn)))

    def _get_runner(self) -> BatchRunner:
        key = self._runner_key()
        cached = getattr(self, "_runner_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        runner = BatchRunner(self._make_fn(), self.getBatchSize())
        self._runner_cache = (key, runner)
        return runner

    def _transform(self, dataset: DataFrame) -> DataFrame:
        from .streaming import StreamScorer
        from .xla_image import emptyVectorColumn
        in_col = self.getInputCol()
        out_col = self.getOutputCol()
        batch_size = self.getBatchSize()
        shape = (self.getOrDefault(self.inputShape)
                 if self.isDefined(self.inputShape) else None)
        runner = self._get_runner()

        def make_decoder(batch: pa.RecordBatch):
            # Decode per device chunk on the pool (zero-copy Arrow→ndarray
            # per slice) — peak host memory O(batchSize), and the chunks
            # of every partition ride ONE device stream (no window drain
            # at partition boundaries). The same decoder serves the
            # quarantine fallback at row granularity.
            col = batch.column(in_col)

            def decode(start: int, length: int) -> np.ndarray:
                return columnToNdarray(col.slice(start, length), shape)

            return decode

        def decoder_spec(batch: pa.RecordBatch):
            # SPARKDL_DECODE_BACKEND=process eligibility: picklable
            # per-chunk tasks (module-level factory + compacted slice).
            col = batch.column(in_col)

            def spec(start: int, length: int) -> tuple:
                chunk = pa.concat_arrays([col.slice(start, length)])
                return ingest.decode_array_chunk, (chunk, shape)

            return spec

        on_error = self.getOnError()
        scorer = StreamScorer(runner, out_col, make_decoder,
                              arrayColumnToArrow, emptyVectorColumn,
                              chunk_rows=batch_size, on_error=on_error,
                              decoder_spec=decoder_spec)
        self._quarantine_sink = scorer.sink
        return dataset.mapStream(scorer,
                                 changes_length=on_error == "quarantine")

    _pickled_params = ("fn",)


class KerasTransformer(BundlesModelFile, XlaTransformer):
    """Applies a saved Keras model (Keras-3-on-JAX) to a 1-D array column —
    the reference's KerasTransformer (single input/output tensor contract).
    save() bundles the model file with the stage (BundlesModelFile)."""

    modelFile = Param(Params, "modelFile",
                      "path to a saved Keras model (.keras/.h5)",
                      TypeConverters.toString)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, modelFile=None,
                 inputShape=None, batchSize=None):
        super(XlaTransformer, self).__init__()
        self._setDefault(batchSize=64)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, modelFile=None,
                  inputShape=None, batchSize=None):
        return self._set(**self._input_kwargs)

    def _make_fn(self):
        return keras_file_to_fn(self.getOrDefault(self.modelFile))

    def _runner_key(self) -> tuple:
        return (self.getBatchSize(), self.getOrDefault(self.modelFile))

    _pickled_params = ()
