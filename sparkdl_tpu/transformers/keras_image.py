"""KerasImageFileTransformer — URI column → loaded image → Keras model output.

Reference: ``python/sparkdl/transformers/keras_image.py`` (SURVEY.md §2.1,
call stack §3.2): a DataFrame column of image URIs is loaded/preprocessed by a
user function and pushed through a saved Keras model. The reference's slow
path #1 (row-at-a-time pickled UDF between JVM and Python) does not exist
here: loading happens batched on the host while the previous batch computes
on the TPU (the BatchRunner prefetch overlap).
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from ..core.frame import DataFrame
from ..core.params import (HasBatchSize, HasInputCol, HasOnError,
                           HasOutputCol, Param, Params, TypeConverters,
                           keyword_only)
from ..core.pipeline import Transformer
from ..core.runtime import BatchRunner
from .keras_utils import keras_file_to_fn
from .payloads import BundlesModelFile, PicklesCallableParams
from .xla_image import arrayColumnToArrow


def defaultImageLoader(size: tuple[int, int]):
    """uri → float32 HWC RGB array resized to ``size`` (no model preprocess)."""
    def load(uri: str) -> np.ndarray:
        from PIL import Image
        img = Image.open(uri).convert("RGB").resize((size[1], size[0]),
                                                    Image.BILINEAR)
        return np.asarray(img, dtype=np.float32)

    return load


def loadImageBatch(loader, uris, workers: int = 0) -> np.ndarray:
    """Decode a URI batch through a thread pool → one stacked NHWC array.

    PIL decode/resize releases the GIL, so a pool of threads keeps every
    host core decoding (SURVEY.md §7.7 "streams via grain" — the capability
    is parallel host decode; one Python thread cannot feed a TPU).
    ``workers=0`` (auto) rides the process-wide shared decode executor
    (imageIO — no per-batch thread churn); an explicit N gets a dedicated
    N-thread pool for this batch (for loaders only N-thread-safe)."""
    uris = list(uris)
    if len(uris) <= 1 or workers == 1:
        return np.stack([loader(u) for u in uris])
    if workers <= 0:
        from ..image.imageIO import _decode_pool
        return np.stack(list(_decode_pool().map(loader, uris)))
    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(max_workers=workers) as pool:
        return np.stack(list(pool.map(loader, uris)))


class KerasImageFileTransformer(BundlesModelFile, PicklesCallableParams,
                                Transformer, HasInputCol, HasOutputCol,
                                HasBatchSize, HasOnError):
    """Loads images from a URI column via ``imageLoader`` and applies a saved
    Keras model (``modelFile``, Keras-3-on-JAX) as one jitted XLA program.
    save() bundles the model file with the stage (BundlesModelFile), so
    fitted transformers persist durably. ``onError='quarantine'``
    dead-letters rows whose URI fails to load/decode (missing file,
    truncated image) instead of killing the scoring job."""

    modelFile = Param(Params, "modelFile", "path to a saved Keras model "
                      "(.keras/.h5)", TypeConverters.toString)
    imageLoader = Param(Params, "imageLoader",
                        "callable uri -> float32 HWC array (loads AND "
                        "preprocesses, like the reference's loadImagesInternal)",
                        TypeConverters.toCallable)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, modelFile=None,
                 imageLoader=None, batchSize=None, onError=None):
        super().__init__()
        self._setDefault(batchSize=32, onError="raise")
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, modelFile=None,
                  imageLoader=None, batchSize=None, onError=None):
        return self._set(**self._input_kwargs)

    def _make_fn(self):
        return keras_file_to_fn(self.getOrDefault(self.modelFile))

    def _get_runner(self) -> BatchRunner:
        key = (self.getBatchSize(), self.getOrDefault(self.modelFile))
        cached = getattr(self, "_runner_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        runner = BatchRunner(self._make_fn(), self.getBatchSize())
        self._runner_cache = (key, runner)
        return runner

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col = self.getInputCol()
        out_col = self.getOutputCol()
        batch_size = self.getBatchSize()
        loader = self.getOrDefault(self.imageLoader)
        runner = self._get_runner()

        def make_decoder(batch: pa.RecordBatch):
            uris = batch.column(in_col).to_pylist()

            # Load lazily per device chunk: each decode fans its URI batch
            # over the shared decode executor (loadImageBatch) AND the
            # chunks themselves pipeline on the scorer's decode pool —
            # chunk k+1 loads while the TPU computes chunk k, across
            # partition boundaries. Peak host memory is one chunk x the
            # in-flight window, not the whole partition. The quarantine
            # fallback calls the same decoder per row (length=1), so a bad
            # URI dead-letters just its own row.
            def decode(start: int, length: int) -> np.ndarray:
                return loadImageBatch(loader, uris[start:start + length])

            return decode

        from .streaming import StreamScorer
        from .xla_image import emptyVectorColumn
        on_error = self.getOnError()
        scorer = StreamScorer(runner, out_col, make_decoder,
                              arrayColumnToArrow, emptyVectorColumn,
                              chunk_rows=batch_size, on_error=on_error)
        self._quarantine_sink = scorer.sink
        return dataset.mapStream(scorer,
                                 changes_length=on_error == "quarantine")

    _pickled_params = ("imageLoader",)
