"""Transformer helpers (reference: ``python/sparkdl/transformers/utils.py``
— ``imageInputPlaceholder`` and friends, SURVEY.md §2.1).

In TF-1.x the placeholder was a graph node; here it is a symbolic input in
an :class:`~sparkdl_tpu.graph.IsolatedSession` (or just a spec tuple for
``GraphFunction.serialize``)."""

from __future__ import annotations

from ..graph.builder import GraphNode, IsolatedSession

IMAGE_INPUT_PLACEHOLDER_NAME = "sparkdl_image_input"


def imageInputPlaceholder(nChannels: int | None = None,
                          height: int | None = None,
                          width: int | None = None,
                          session: IsolatedSession | None = None,
                          name: str = IMAGE_INPUT_PLACEHOLDER_NAME
                          ) -> GraphNode:
    """A batched NHWC float placeholder for image graphs.

    With ``session=None`` a fresh IsolatedSession is created and attached to
    the returned node (``node.session``), mirroring the reference pattern of
    building the input placeholder first and assembling around it.
    """
    issn = session or IsolatedSession()
    return issn.placeholder((None, height, width, nChannels), "float32",
                            name=name)


def imageInputSpec(height: int, width: int, nChannels: int = 3,
                   dtype: str = "float32") -> dict:
    """{name: (shape, dtype)} spec for ``GraphFunction.serialize``."""
    return {IMAGE_INPUT_PLACEHOLDER_NAME:
            ((None, height, width, nChannels), dtype)}
