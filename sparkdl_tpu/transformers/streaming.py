"""StreamScorer — the cross-partition streaming inference engine.

Every inference transformer used to lower to a per-partition ``mapBatches``
op that built its own decode thread + ``BatchRunner.run`` generator per
partition — so the device's in-flight window drained at EVERY partition
boundary, decode ran on ONE background thread, and Arrow output encoding
serialized between device fetches. On many-small-partition datasets the TPU
idled for most of the wall clock.

This module is the shared replacement (ISSUE 3 tentpole): one
:class:`StreamScorer` instance becomes a ``DataFrame.mapStream`` op that

- chunks every partition into device batches and decodes them on the
  parallel, order-preserving host pool (``runtime.parallel_map_iter``,
  ``SPARKDL_DECODE_WORKERS`` workers) — each decode wrapped in a ``decode``
  flight-recorder span;
- feeds the WHOLE dataset's chunk stream through one
  ``BatchRunner.run_stream`` call, partition identity and row counts riding
  host-side as the stream metadata — the pad/put/dispatch/fetch window
  never drains between partitions;
- encodes device outputs to their final Arrow form on an overlap worker
  (``encode`` spans), so the consumer loop goes straight back to fetching
  the next device result instead of blocking on ``nhwcToStructs`` /
  ``arrayColumnToArrow``;
- reassembles one output RecordBatch per input partition, in order, with
  the int32→large_list offset promotion handled once in
  :func:`concatChunkArrays`.

Peak host memory stays O(window · batchSize) decoded rows + the pending
partitions whose chunks are in flight — the same O(batchSize) contract the
per-partition design had, now without the per-boundary stalls.
"""

from __future__ import annotations

import collections
from typing import Callable, Iterator

import numpy as np
import pyarrow as pa

from ..core.frame import _set_column
from ..core.runtime import BatchRunner, parallel_map_iter


def concatChunkArrays(pieces: list[pa.Array]) -> pa.Array:
    """Concatenate per-chunk output arrays into one partition column.

    int32 list offsets overflow past 2**31 total values — every piece is
    promoted to large_list before concat when the total crosses that line
    (the single-piece path gets this inside ``arrayColumnToArrow``)."""
    if len(pieces) == 1:
        return pieces[0]
    total = sum(len(p.values) if isinstance(
        p, (pa.ListArray, pa.LargeListArray)) else 0 for p in pieces)
    if total > np.iinfo(np.int32).max:
        pieces = [p.cast(pa.large_list(p.type.value_type))
                  if isinstance(p, pa.ListArray) else p for p in pieces]
    return pa.concat_arrays(pieces)


class StreamScorer:
    """``DataFrame.mapStream`` op scoring a column through a BatchRunner.

    Per-transformer behavior plugs in via three callables:

    - ``chunk_thunks(batch) -> list[() -> host_array]``: split one
      partition into device-batch decode thunks (each runs on the decode
      pool and returns the host array for one ``BatchRunner`` batch);
    - ``encode(np.ndarray) -> pa.Array``: device output chunk → its final
      Arrow representation (runs on the overlap worker);
    - ``empty_array() -> pa.Array``: output column for a zero-row
      partition.
    """

    def __init__(self, runner: BatchRunner, out_col: str,
                 chunk_thunks: Callable, encode: Callable,
                 empty_array: Callable, decode_workers: int | None = None):
        self.runner = runner
        self.out_col = out_col
        self.chunk_thunks = chunk_thunks
        self.encode = encode
        self.empty_array = empty_array
        self.decode_workers = decode_workers

    # -- stages ------------------------------------------------------------
    def _decode(self, item):
        thunk, entry = item
        from ..core.runtime import _events
        with _events().span("decode"):
            return thunk(), entry

    def _encode(self, result: np.ndarray) -> pa.Array:
        from ..core.runtime import _events
        with _events().span("encode", rows=len(result)):
            return self.encode(result)

    def _finish(self, entry: dict) -> pa.RecordBatch:
        batch = entry["batch"]
        if not entry["n_chunks"]:
            return _set_column(batch, self.out_col, self.empty_array())
        pieces = [f.result() for f in entry["futs"]]
        return _set_column(batch, self.out_col, concatChunkArrays(pieces))

    # -- the stream op -----------------------------------------------------
    def __call__(self, parts: Iterator[pa.RecordBatch]
                 ) -> Iterator[pa.RecordBatch]:
        from concurrent.futures import ThreadPoolExecutor
        # Entries appear here in partition order as the chunk producer
        # (pulled on this thread through the decode pool / put window)
        # walks the input; each holds its RecordBatch and expected chunk
        # count host-side — the row-count bookkeeping the continuous
        # device stream does not carry.
        pending: collections.deque[dict] = collections.deque()

        def chunk_stream():
            for rb in parts:
                thunks = self.chunk_thunks(rb) if rb.num_rows else []
                entry = {"batch": rb, "n_chunks": len(thunks), "futs": []}
                pending.append(entry)
                for t in thunks:
                    yield t, entry

        decoded = parallel_map_iter(
            self._decode, chunk_stream(), workers=self.decode_workers,
            maxsize=max(self.runner.prefetch, 1))
        encode_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="sparkdl-encode")
        # Backpressure for the overlap worker: un-encoded RAW outputs are
        # full float32 chunks, so an encode slower than the device fetch
        # (image-mode nhwcToStructs on a huge partition) must throttle the
        # consumer loop before a partition's worth of raw output piles up
        # on the host — the O(window · batchSize) contract. Encoded
        # results are the compact final column form and may accumulate
        # per pending partition, exactly as the per-partition design did.
        backlog: collections.deque = collections.deque()
        max_backlog = max(2, int(getattr(self.runner, "prefetch", 2)))
        try:
            for out, entry in self.runner.run_stream(decoded):
                # Hand the Arrow encode to the overlap worker and go
                # straight back to the device stream — the feed waits on
                # encoding only past the bounded backlog.
                while backlog and backlog[0].done():
                    backlog.popleft()
                if len(backlog) >= max_backlog:
                    backlog.popleft().result()
                fut = encode_pool.submit(self._encode, np.asarray(out))
                backlog.append(fut)
                entry["futs"].append(fut)
                while pending and \
                        len(pending[0]["futs"]) == pending[0]["n_chunks"]:
                    yield self._finish(pending.popleft())
            while pending:
                yield self._finish(pending.popleft())
        finally:
            encode_pool.shutdown(wait=False, cancel_futures=True)
