"""StreamScorer — the cross-partition streaming inference engine.

Every inference transformer used to lower to a per-partition ``mapBatches``
op that built its own decode thread + ``BatchRunner.run`` generator per
partition — so the device's in-flight window drained at EVERY partition
boundary, decode ran on ONE background thread, and Arrow output encoding
serialized between device fetches. On many-small-partition datasets the TPU
idled for most of the wall clock.

This module is the shared replacement (ISSUE 3 tentpole): one
:class:`StreamScorer` instance becomes a ``DataFrame.mapStream`` op that

- chunks every partition into device batches and decodes them on the
  parallel, order-preserving host pool (``runtime.parallel_map_iter``,
  ``SPARKDL_DECODE_WORKERS`` workers) — each decode wrapped in a ``decode``
  flight-recorder span;
- feeds the WHOLE dataset's chunk stream through one
  ``BatchRunner.run_stream`` call, partition identity and row counts riding
  host-side as the stream metadata — the pad/put/dispatch/fetch window
  never drains between partitions;
- encodes device outputs to their final Arrow form on an overlap worker
  (``encode`` spans), so the consumer loop goes straight back to fetching
  the next device result instead of blocking on ``nhwcToStructs`` /
  ``arrayColumnToArrow``;
- reassembles one output RecordBatch per input partition, in order, with
  the int32→large_list offset promotion handled once in
  :func:`concatChunkArrays`.

Fault isolation (ISSUE 4 tentpole): with ``on_error='quarantine'`` a
host-side decode/payload failure no longer kills the whole job — the
failing chunk is re-decoded row by row, bad rows route to a **dead-letter
side output** (:class:`QuarantineSink`: the original row +
``error_class``/``error`` columns, Spark's task-isolation semantics at row
granularity) and the surviving rows continue through the device stream.
A circuit breaker (``SPARKDL_MAX_QUARANTINE_FRAC``, default 0.5) fails
the job with a fatal :class:`QuarantineOverflowError` when the bad-row
fraction says the *input* is broken, not the odd record. Device-side
dispatch/fetch faults are retried with backoff inside
``BatchRunner.run_stream`` (see ``core/runtime.py``).

Peak host memory stays O(window · batchSize) decoded rows + the pending
partitions whose chunks are in flight — the same O(batchSize) contract the
per-partition design had, now without the per-boundary stalls.
"""

from __future__ import annotations

import collections
import logging
import os
from typing import Callable, Iterator

import numpy as np
import pyarrow as pa

from ..core import ingest
from ..core.frame import _set_column
from ..core.runtime import (BatchRunner, _chaos, _events, _failures,
                            _run_stats, _telemetry, parallel_map_iter)

log = logging.getLogger("sparkdl_tpu.streaming")

ERROR_CLASS_COL = "error_class"
ERROR_COL = "error"


def max_quarantine_frac_default() -> float:
    """Dead-letter circuit-breaker threshold: the job fails (fatal) once
    quarantined_rows / seen_rows exceeds this fraction
    (``SPARKDL_MAX_QUARANTINE_FRAC``, default 0.5 — half the input bad
    means the pipeline, not the data, is broken)."""
    try:
        return float(os.environ.get("SPARKDL_MAX_QUARANTINE_FRAC", "0.5"))
    except ValueError:
        return 0.5


def quarantine_min_rows_default() -> int:
    """Minimum rows seen before the circuit breaker may trip MID-stream
    (``SPARKDL_QUARANTINE_MIN_ROWS``, default 100): one corrupt leading
    chunk must not read as "half the input is bad" and fatally kill a
    job whose overall bad fraction is tiny. At end of stream the breaker
    evaluates the TRUE whole-input fraction with no floor."""
    try:
        return max(1, int(
            os.environ.get("SPARKDL_QUARANTINE_MIN_ROWS", "100")))
    except ValueError:
        return 100


def concatChunkArrays(pieces: list[pa.Array]) -> pa.Array:
    """Concatenate per-chunk output arrays into one partition column.

    int32 list offsets overflow past 2**31 total values — every piece is
    promoted to large_list before concat when the total crosses that line
    (the single-piece path gets this inside ``arrayColumnToArrow``)."""
    if len(pieces) == 1:
        return pieces[0]
    total = sum(len(p.values) if isinstance(
        p, (pa.ListArray, pa.LargeListArray)) else 0 for p in pieces)
    if total > np.iinfo(np.int32).max:
        pieces = [p.cast(pa.large_list(p.type.value_type))
                  if isinstance(p, pa.ListArray) else p for p in pieces]
    return pa.concat_arrays(pieces)


class QuarantineSink:
    """Collects dead-letter rows: each quarantined input row rides with an
    ``error_class`` (exception type name) and ``error`` (message) column.

    Schema is pinned from the FIRST input partition (``ensure_schema``),
    so :meth:`to_table` returns a stably-typed table even when nothing was
    quarantined — the empty-quarantine and all-rows-quarantined edges
    round-trip through Arrow identically. Consumer-thread only (the
    scorer's reassembly loop); not thread-safe by design."""

    def __init__(self):
        self.batches: list[pa.RecordBatch] = []
        self.rows = 0
        self._schema: pa.Schema | None = None

    def ensure_schema(self, input_schema: pa.Schema):
        if self._schema is None:
            self._schema = pa.schema(
                list(input_schema)
                + [pa.field(ERROR_CLASS_COL, pa.string()),
                   pa.field(ERROR_COL, pa.string())])

    @property
    def schema(self) -> pa.Schema | None:
        return self._schema

    def add(self, batch: pa.RecordBatch, dead: list[tuple]):
        """``dead``: ``[(row_index, error_class, message), ...]`` into
        ``batch`` — appended as one dead-letter RecordBatch."""
        if not dead:
            return
        self.ensure_schema(batch.schema)
        src = batch.take(pa.array([r for r, _, _ in dead], type=pa.int64()))
        arrays = list(src.columns) + [
            pa.array([c for _, c, _ in dead], type=pa.string()),
            pa.array([m[:500] for _, _, m in dead], type=pa.string())]
        self.batches.append(pa.RecordBatch.from_arrays(
            arrays, schema=self._schema))
        self.rows += len(dead)

    def publish_to(self, dest: "QuarantineSink"):
        """Hand this run's collection to the transformer-visible sink.
        The schema pin always transfers; the dead-letter rows replace
        ``dest``'s only when this run actually quarantined something —
        so a 1-row schema probe (``DataFrame.schema`` re-invokes the
        stream op) or an early-closed ``take()`` pass cannot silently
        wipe the ledger of the last real materialization."""
        if dest._schema is None:
            dest._schema = self._schema
        if self.rows:
            dest.batches = self.batches
            dest.rows = self.rows
            dest._schema = self._schema

    def to_table(self) -> pa.Table:
        if self.batches:
            return pa.Table.from_batches(self.batches)
        if self._schema is not None:
            return self._schema.empty_table()
        return pa.table({})


class StreamScorer:
    """``DataFrame.mapStream`` op scoring a column through a BatchRunner.

    Per-transformer behavior plugs in via three callables:

    - ``make_decoder(batch) -> decode(start, length) -> host_array``:
      per-partition setup (pin the target shape, resolve the feed dtype)
      returning a slice decoder — the scorer chunks the partition into
      ``chunk_rows``-row device batches itself and calls ``decode`` per
      chunk on the decode pool (and per ROW on the quarantine fallback
      path);
    - ``encode(np.ndarray) -> pa.Array``: device output chunk → its final
      Arrow representation (runs on the overlap worker);
    - ``empty_array() -> pa.Array``: output column for a zero-row
      partition.

    ``on_error='quarantine'`` arms row-level fault isolation: a chunk
    whose decode raises is retried row by row; rows that still fail (or
    decode to a deviant shape) are dead-lettered into ``sink`` and the
    scored output batch simply omits them (length-changing — pair with
    ``mapStream(..., changes_length=True)``). ``max_quarantine_frac``
    bounds the damage (default: :func:`max_quarantine_frac_default`).

    ``decoder_spec`` (optional) makes the scorer eligible for the
    PROCESS decode backend (``SPARKDL_DECODE_BACKEND=process`` — GIL-
    bound decode scales past the ~1-core thread ceiling):
    ``decoder_spec(batch) -> spec`` where ``spec(start, length)`` returns
    a PICKLABLE ``(factory, payload)`` pair — ``factory`` a module-level
    callable decoding rows of that chunk from ``payload`` with
    chunk-local indices (see ``ingest.decode_image_chunk``). Without a
    spec, a process-backend request degrades to threads with one warning
    (entries/decoders close over Arrow batches and device state — not
    picklable). Chunk decode semantics — row-fallback quarantine, the
    chaos ``decode`` site — are the ONE shared implementation
    (``ingest.decode_chunk``) on either backend.
    """

    def __init__(self, runner: BatchRunner, out_col: str,
                 make_decoder: Callable, encode: Callable,
                 empty_array: Callable, chunk_rows: int | None = None,
                 decode_workers: int | None = None,
                 on_error: str = "raise",
                 max_quarantine_frac: float | None = None,
                 sink: QuarantineSink | None = None,
                 decoder_spec: Callable | None = None):
        if on_error not in ("raise", "quarantine"):
            raise ValueError(f"on_error must be 'raise' or 'quarantine', "
                             f"got {on_error!r}")
        self.runner = runner
        self.out_col = out_col
        self.make_decoder = make_decoder
        self.encode = encode
        self.empty_array = empty_array
        self.chunk_rows = int(chunk_rows or runner.batch_size)
        self.decode_workers = decode_workers
        self.on_error = on_error
        self.decoder_spec = decoder_spec
        self.max_quarantine_frac = (
            max_quarantine_frac if max_quarantine_frac is not None
            else max_quarantine_frac_default())
        self.sink = sink if sink is not None else (
            QuarantineSink() if on_error == "quarantine" else None)

    # -- stages ------------------------------------------------------------
    def _decode(self, item):
        """Decode one chunk (thread-pool path). Returns ``(array_or_None,
        info)`` — ``info`` is None in raise mode; in quarantine mode it
        carries the chunk length and the dead rows so ALL sink / counter
        mutation happens later on the consumer thread. The chunk/row-
        fallback protocol itself is the shared ``ingest.decode_chunk``."""
        decoder, start, length = item
        with _events().span("decode", rows=length):
            return ingest.decode_chunk(decoder, start, length,
                                       self.on_error == "quarantine")

    def _encode(self, result: np.ndarray) -> pa.Array:
        with _events().span("encode", rows=len(result)):
            return self.encode(result)

    def _finish(self, entry: dict, sink: QuarantineSink | None
                ) -> pa.RecordBatch:
        batch = entry["batch"]
        dead = entry["dead"]
        scored = batch
        if dead:
            if sink is not None:
                sink.add(batch, dead)
            dead_rows = {r for r, _, _ in dead}
            keep = [i for i in range(batch.num_rows) if i not in dead_rows]
            scored = (batch.take(pa.array(keep, type=pa.int64())) if keep
                      else batch.slice(0, 0))
        pieces = [f.result() for f in entry["futs"]]
        if not pieces:
            return _set_column(scored, self.out_col, self.empty_array())
        return _set_column(scored, self.out_col, concatChunkArrays(pieces))

    # -- the stream op -----------------------------------------------------
    def __call__(self, parts: Iterator[pa.RecordBatch]
                 ) -> Iterator[pa.RecordBatch]:
        from concurrent.futures import ThreadPoolExecutor
        ev = _events()
        tel = _telemetry()
        tel.maybe_start_from_env()
        pending_gauge = backlog_gauge = None
        if tel.enabled():
            # Live queue-depth gauges (ISSUE 6): `pending` = partitions
            # whose chunks are still in flight (reassembly latency),
            # `backlog` = fetched-but-unencoded raw outputs parked on the
            # overlap worker (encode falling behind the device).
            pending_gauge = tel.registry().gauge("scorer_pending_partitions")
            backlog_gauge = tel.registry().gauge("scorer_encode_backlog")
        # Entries appear here in partition order as the chunk producer
        # (pulled on this thread through the decode pool / put window)
        # walks the input; each holds its RecordBatch and expected chunk
        # count host-side — the row-count bookkeeping the continuous
        # device stream does not carry.
        pending: collections.deque[dict] = collections.deque()
        totals = {"seen": 0, "quarantined": 0}
        # Each invocation (one materialization of the lazy result)
        # collects into its OWN sink, published to the transformer-
        # visible one only at completion — see QuarantineSink.publish_to.
        run_sink = QuarantineSink() if self.sink is not None else None
        min_rows = quarantine_min_rows_default()

        def breaker_check(floor: int):
            if totals["seen"] >= floor and totals["quarantined"] > \
                    self.max_quarantine_frac * totals["seen"]:
                raise _failures().QuarantineOverflowError(
                    totals["quarantined"], totals["seen"],
                    self.max_quarantine_frac)

        # Decode backend resolution (ISSUE 7): the process pool needs
        # picklable tasks, which only scorers WITH a decoder_spec can
        # build; everything else rides threads exactly as before. The
        # chunk FIFO pairs each in-order decode result back with its
        # partition entry (entries hold RecordBatches and futures — they
        # never cross the process boundary).
        process_mode = ingest.decode_backend_default() == "process" \
            and (self.decode_workers is None or self.decode_workers > 0)
        if process_mode and self.decoder_spec is None:
            log.warning(
                "SPARKDL_DECODE_BACKEND=process but this scorer has no "
                "decoder_spec (its decoder closes over un-picklable "
                "state); decoding on threads instead")
            process_mode = False
        quarantine = self.on_error == "quarantine"
        chaos_json = None
        if process_mode:
            plan = _chaos().active_plan()
            chaos_json = plan.to_json() if plan is not None else None
        fifo: collections.deque[tuple] = collections.deque()

        def chunk_stream():
            for rb in parts:
                if run_sink is not None and rb.num_rows == 0 \
                        and run_sink.schema is None:
                    run_sink.ensure_schema(rb.schema)
                decoder = spec = None
                if rb.num_rows:
                    if process_mode:
                        spec = self.decoder_spec(rb)
                    else:
                        decoder = self.make_decoder(rb)
                starts = range(0, rb.num_rows, self.chunk_rows)
                entry = {"batch": rb, "n_chunks": len(starts), "futs": [],
                         "n_skipped": 0, "dead": []}
                pending.append(entry)
                for s in starts:
                    length = min(self.chunk_rows, rb.num_rows - s)
                    fifo.append((entry, s, length))
                    if process_mode:
                        factory, payload = spec(s, length)
                        yield (factory, payload, length, quarantine,
                               chaos_json)
                    else:
                        yield (decoder, s, length)

        def complete(entry: dict) -> bool:
            return len(entry["futs"]) + entry["n_skipped"] \
                == entry["n_chunks"]

        decoded = parallel_map_iter(
            ingest.run_decode_task if process_mode else self._decode,
            chunk_stream(), workers=self.decode_workers,
            maxsize=max(self.runner.prefetch, 1),
            backend="process" if process_mode else "thread")

        def device_stream():
            """Consumer-thread filter between the decode pool and the
            device window: records quarantine bookkeeping (sink schema,
            entry dead rows, counters, the circuit breaker) and drops
            chunks with no surviving rows."""
            for res in decoded:
                entry, start, length = fifo.popleft()
                if process_mode:
                    arr, info, dur_s = res
                    # The decode ran in a pool child whose recorder dies
                    # with it — land the span HERE so stage accounting /
                    # bottleneck reports still see decode time.
                    ev.completed_span("decode", dur_s, rows=length)
                    if info is not None and info["dead"]:
                        # child indices are chunk-local; re-base onto the
                        # partition batch
                        info = {"length": info["length"],
                                "dead": [(start + j, c, m)
                                         for j, c, m in info["dead"]]}
                else:
                    arr, info = res
                if info is not None:
                    totals["seen"] += info["length"]
                    if run_sink is not None and run_sink.schema is None:
                        run_sink.ensure_schema(entry["batch"].schema)
                    dead = info["dead"]
                    if dead:
                        entry["dead"].extend(dead)
                        totals["quarantined"] += len(dead)
                        _run_stats().record_quarantine(len(dead))
                        ev.event("quarantine", rows=len(dead),
                                 error_class=dead[0][1],
                                 total=totals["quarantined"])
                        # Mid-stream the breaker needs a sample-size
                        # floor — one corrupt leading chunk is not "half
                        # the input is bad".
                        breaker_check(min_rows)
                if arr is None or not len(arr):
                    entry["n_skipped"] += 1
                    continue
                yield arr, entry

        encode_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="sparkdl-encode")
        # Backpressure for the overlap worker: un-encoded RAW outputs are
        # full float32 chunks, so an encode slower than the device fetch
        # (image-mode nhwcToStructs on a huge partition) must throttle the
        # consumer loop before a partition's worth of raw output piles up
        # on the host — the O(window · batchSize) contract. Encoded
        # results are the compact final column form and may accumulate
        # per pending partition, exactly as the per-partition design did.
        backlog: collections.deque = collections.deque()
        max_backlog = max(2, int(getattr(self.runner, "prefetch", 2)))
        try:
            for out, entry in self.runner.run_stream(device_stream()):
                # Hand the Arrow encode to the overlap worker and go
                # straight back to the device stream — the feed waits on
                # encoding only past the bounded backlog.
                while backlog and backlog[0].done():
                    backlog.popleft()
                if len(backlog) >= max_backlog:
                    backlog.popleft().result()
                fut = encode_pool.submit(self._encode, np.asarray(out))
                backlog.append(fut)
                entry["futs"].append(fut)
                while pending and complete(pending[0]):
                    yield self._finish(pending.popleft(), run_sink)
                if pending_gauge is not None:
                    pending_gauge.set(len(pending))
                    backlog_gauge.set(len(backlog))
            # End of stream: the breaker now knows the TRUE whole-input
            # bad fraction — evaluate it with no sample-size floor.
            breaker_check(1)
            while pending:
                yield self._finish(pending.popleft(), run_sink)
            if run_sink is not None:
                run_sink.publish_to(self.sink)
        finally:
            encode_pool.shutdown(wait=False, cancel_futures=True)
