"""Feature-engineering stages: VectorAssembler, StringIndexer,
StandardScaler, IndexToString.

The reference's pipelines leaned on Spark MLlib feature stages around the
deep-learning transformers (StringIndexer for labels, VectorAssembler to
join feature columns before a shallow learner — e.g. the upstream README's
``Pipeline([featurizer, lr])`` flows; SURVEY.md §1-L3). There is no JVM
MLlib here, so the framework carries the stages those flows need,
with the same Params surface and fit/transform semantics.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from ..core.frame import DataFrame, _row_wise_op, _set_column
from ..core.params import (HasInputCol, HasOutputCol, Param, Params,
                           TypeConverters, keyword_only)
from ..core.pipeline import Estimator, Model, Transformer


def _check_no_nulls(arr, stage: str, col: str) -> None:
    """handleInvalid='error' guard. Top-level null_count misses a null
    *element inside* a list value (the list itself is non-null), which
    would silently become NaN through ``to_numpy(zero_copy_only=False)``
    — so list-typed columns are also checked flattened."""
    n = arr.null_count
    if not n and (pa.types.is_list(arr.type)
                  or pa.types.is_large_list(arr.type)
                  or pa.types.is_fixed_size_list(arr.type)):
        flat = (arr.combine_chunks() if isinstance(arr, pa.ChunkedArray)
                else arr).flatten()
        n = flat.null_count
    if n:
        raise ValueError(
            f"{stage}: column {col!r} contains null values; clean or "
            f"filter nulls first")


def _toHandleInvalid(value):
    """Param converter: config errors surface at set() time on the driver
    (the core/params.py contract), not at transform time on a worker."""
    value = TypeConverters.toString(value)
    if value not in ("error", "keep"):
        raise TypeError(
            f"handleInvalid must be 'error' or 'keep', got {value!r} "
            "('skip' is not supported: the data plane's indexing op is "
            "length-preserving)")
    return value


class VectorAssembler(Transformer, HasOutputCol):
    """Concatenate numeric / vector columns into one flat feature vector
    (Spark MLlib surface: inputCols → outputCol)."""

    inputCols = Param(Params, "inputCols", "columns to concatenate",
                      TypeConverters.toListString)

    @keyword_only
    def __init__(self, inputCols=None, outputCol=None):
        super().__init__()
        self._setDefault(outputCol="features")
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCols=None, outputCol=None):
        return self._set(**self._input_kwargs)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        cols = (self.getOrDefault(self.inputCols)
                if self.isDefined(self.inputCols) else None)
        if not cols:
            raise ValueError("VectorAssembler needs inputCols")
        out_col = self.getOutputCol()

        def op(batch: pa.RecordBatch) -> pa.RecordBatch:
            from .tensor import columnToNdarray
            pieces = []
            for c in cols:
                arr = batch.column(c)
                # Spark's handleInvalid='error' default: a null would
                # otherwise silently become NaN in the feature vector.
                # (No row index: this op sees streamed sub-batches, so
                # a local index would mislead.)
                _check_no_nulls(arr, "VectorAssembler", c)
                # zero-copy Arrow→ndarray (shared with the tensor
                # transformers); float64 end-to-end — the output column
                # type — so no silent float32 rounding; scalar columns
                # promote to (N, 1)
                pieces.append(columnToNdarray(arr, None, dtype=np.float64,
                                              atleast_2d=True))
            flat = np.concatenate(pieces, axis=1)
            # packed list<double> straight from the flat buffer (shared
            # with the scoring engine's output encode) — no per-row Python
            # list materialization on a column that may be the widest in
            # the pipeline
            from .xla_image import arrayColumnToArrow
            return _set_column(batch, out_col, arrayColumnToArrow(flat))

        # row-wise: each output row depends only on its own input row, so
        # the chain stays streamable (O(batchSize) host memory upstream)
        return dataset.mapBatches(_row_wise_op(op))


class StringIndexer(Estimator, HasInputCol, HasOutputCol):
    """Fit a label → index mapping over a string (or any hashable) column;
    indices are assigned by descending frequency, ties lexicographic —
    Spark's ``frequencyDesc`` order. Nulls are invalid values governed by
    ``handleInvalid`` (Spark semantics), never folded into a "None"
    label."""

    handleInvalid = Param(Params, "handleInvalid",
                          "'error' (default) or 'keep' (unseen/null → "
                          "n_labels)", _toHandleInvalid)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, handleInvalid=None):
        super().__init__()
        self._setDefault(handleInvalid="error")
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, handleInvalid=None):
        return self._set(**self._input_kwargs)

    def _fit(self, dataset: DataFrame) -> "StringIndexerModel":
        col = self.getInputCol()
        keep = self.getOrDefault(self.handleInvalid) == "keep"
        counts: dict = {}
        # non-null values coerce through str() on both fit and transform —
        # Spark casts the input column to string, and the labels Param
        # stores strings
        for batch in dataset.iterPartitions():
            for v in batch.column(col).to_pylist():
                if v is None:
                    if keep:
                        continue  # invalid value, excluded from the fit
                    raise ValueError(
                        f"StringIndexer: null in column {col!r} (set "
                        f"handleInvalid='keep' to bucket nulls with "
                        f"unseen labels)")
                counts[str(v)] = counts.get(str(v), 0) + 1
        labels = sorted(counts, key=lambda v: (-counts[v], v))
        model = StringIndexerModel(labels=labels)
        model._set(inputCol=col, outputCol=self.getOutputCol(),
                   handleInvalid=self.getOrDefault(self.handleInvalid))
        return model


class StringIndexerModel(Model, HasInputCol, HasOutputCol):
    handleInvalid = Param(Params, "handleInvalid",
                          "'error' (default) or 'keep' (unseen/null → "
                          "n_labels)", _toHandleInvalid)
    labels = Param(Params, "labels", "index → label mapping",
                   TypeConverters.toListString)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, handleInvalid=None,
                 labels=None):
        super().__init__()
        self._setDefault(handleInvalid="error")
        self._set(**self._input_kwargs)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        col = self.getInputCol()
        out_col = self.getOutputCol()
        labels = self.getOrDefault(self.labels)
        index = {v: i for i, v in enumerate(labels)}
        keep = self.getOrDefault(self.handleInvalid) == "keep"
        unseen = len(labels)

        def to_index(v):
            if v is None:  # invalid value, not a "None" label
                if keep:
                    return unseen
                raise ValueError(
                    f"StringIndexerModel: null in column {col!r} (set "
                    f"handleInvalid='keep' to map nulls to {unseen})")
            v = str(v)
            if v in index:
                return index[v]
            if keep:
                return unseen
            raise ValueError(
                f"StringIndexerModel: unseen label {v!r} (set "
                f"handleInvalid='keep' to map unseen labels to "
                f"{unseen})")

        return dataset.withColumn(out_col, to_index, [col])


class StandardScaler(Estimator, HasInputCol, HasOutputCol):
    """Fit per-dimension mean/std over a vector column; transform
    standardizes (Spark MLlib surface: withMean/withStd flags, std uses
    the unbiased N-1 denominator like Spark)."""

    withMean = Param(Params, "withMean", "subtract the mean",
                     TypeConverters.toBoolean)
    withStd = Param(Params, "withStd", "divide by the std",
                    TypeConverters.toBoolean)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, withMean=None,
                 withStd=None):
        super().__init__()
        self._setDefault(withMean=False, withStd=True)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, withMean=None,
                  withStd=None):
        return self._set(**self._input_kwargs)

    def _fit(self, dataset: DataFrame) -> "StandardScalerModel":
        from .tensor import columnToNdarray
        col = self.getInputCol()
        # single streaming pass, Welford/Chan parallel merge — a raw
        # sum-of-squares accumulator cancels catastrophically for
        # large-mean data (timestamp-scale values would fit std=0)
        n = 0
        mean = None
        m2 = None
        for batch in dataset.iterPartitions():
            if batch.num_rows == 0:
                continue
            arr = batch.column(col)
            _check_no_nulls(arr, "StandardScaler", col)
            x = columnToNdarray(arr, None, dtype=np.float64,
                                atleast_2d=True)
            bn = len(x)
            bmean = x.mean(0)
            bm2 = ((x - bmean) ** 2).sum(0)
            if n == 0:
                n, mean, m2 = bn, bmean, bm2
            else:
                delta = bmean - mean
                tot = n + bn
                mean = mean + delta * (bn / tot)
                m2 = m2 + bm2 + delta * delta * (n * bn / tot)
                n = tot
        if n == 0:
            raise ValueError("Cannot fit StandardScaler on an empty "
                             "DataFrame")
        var = m2 / max(n - 1, 1)  # unbiased (N-1), like Spark
        std = np.sqrt(np.maximum(var, 0.0))
        model = StandardScalerModel(mean=mean.tolist(), std=std.tolist())
        model._set(inputCol=col, outputCol=self.getOutputCol(),
                   withMean=self.getOrDefault(self.withMean),
                   withStd=self.getOrDefault(self.withStd))
        return model


class StandardScalerModel(Model, HasInputCol, HasOutputCol):
    withMean = Param(Params, "withMean", "subtract the mean",
                     TypeConverters.toBoolean)
    withStd = Param(Params, "withStd", "divide by the std",
                    TypeConverters.toBoolean)
    mean = Param(Params, "mean", "per-dimension mean",
                 TypeConverters.toListFloat)
    std = Param(Params, "std", "per-dimension std (N-1)",
                TypeConverters.toListFloat)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, withMean=None,
                 withStd=None, mean=None, std=None):
        super().__init__()
        self._setDefault(withMean=False, withStd=True)
        self._set(**self._input_kwargs)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        from .tensor import columnToNdarray
        col = self.getInputCol()
        out_col = self.getOutputCol()
        mean = np.asarray(self.getOrDefault(self.mean))
        std = np.asarray(self.getOrDefault(self.std))
        sub_mean = self.getOrDefault(self.withMean)
        div_std = self.getOrDefault(self.withStd)
        # Spark semantics: a zero-std dimension SCALES BY 0 (output 0.0),
        # it does not pass the raw value through.
        factor = np.divide(1.0, std, out=np.zeros_like(std),
                           where=std > 0)

        def op(batch: pa.RecordBatch) -> pa.RecordBatch:
            if batch.num_rows == 0:
                return _set_column(batch, out_col, pa.array(
                    [], type=pa.list_(pa.float64())))
            arr = batch.column(col)
            _check_no_nulls(arr, "StandardScalerModel", col)
            x = columnToNdarray(arr, None, dtype=np.float64,
                                atleast_2d=True)
            if x.shape[1:] != mean.shape:
                raise ValueError(
                    f"StandardScalerModel fitted on {mean.shape[0]} dims, "
                    f"got {x.shape[1:]} in column {col!r}")
            if sub_mean:
                x = x - mean
            if div_std:
                x = x * factor
            # packed list<double> from the flat buffer (see VectorAssembler)
            from .xla_image import arrayColumnToArrow
            return _set_column(batch, out_col, arrayColumnToArrow(x))

        return dataset.mapBatches(_row_wise_op(op))


class IndexToString(Transformer, HasInputCol, HasOutputCol):
    """Inverse of StringIndexer: index column → label strings."""

    labels = Param(Params, "labels", "index → label mapping",
                   TypeConverters.toListString)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, labels=None):
        super().__init__()
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, labels=None):
        return self._set(**self._input_kwargs)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        labels = self.getOrDefault(self.labels)

        def to_label(i):
            i = int(i)
            if not 0 <= i < len(labels):
                raise ValueError(f"index {i} out of range for "
                                 f"{len(labels)} labels")
            return labels[i]

        return dataset.withColumn(self.getOutputCol(), to_label,
                                  [self.getInputCol()])
