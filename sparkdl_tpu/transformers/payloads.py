"""Shared persistence helper: params whose values are callables.

Callable params (jittable fns, image loaders) can't go in metadata.json;
subclasses list them in ``_pickled_params`` and this mixin cloudpickles each
set value into ``<name>.pkl`` beside the stage metadata.
"""

from __future__ import annotations

import os


class PicklesCallableParams:
    _pickled_params: tuple[str, ...] = ()

    def _save_payload(self, path: str):
        import cloudpickle
        for name in self._pickled_params:
            if self.isSet(name):
                with open(os.path.join(path, f"{name}.pkl"), "wb") as f:
                    cloudpickle.dump(self.getOrDefault(name), f)

    def _load_payload(self, path: str, meta: dict):
        import cloudpickle
        for name in self._pickled_params:
            fpath = os.path.join(path, f"{name}.pkl")
            if os.path.exists(fpath):
                with open(fpath, "rb") as f:
                    self._set(**{name: cloudpickle.load(f)})
