"""Shared persistence helper: params whose values are callables.

Callable params (jittable fns, image loaders) can't go in metadata.json;
subclasses list them in ``_pickled_params`` and this mixin cloudpickles each
set value into ``<name>.pkl`` beside the stage metadata.
"""

from __future__ import annotations

import os


class PicklesCallableParams:
    _pickled_params: tuple[str, ...] = ()

    def _save_payload(self, path: str):
        import cloudpickle
        for name in self._pickled_params:
            if self.isSet(name):
                with open(os.path.join(path, f"{name}.pkl"), "wb") as f:
                    cloudpickle.dump(self.getOrDefault(name), f)

    def _load_payload(self, path: str, meta: dict):
        import cloudpickle
        for name in self._pickled_params:
            fpath = os.path.join(path, f"{name}.pkl")
            if os.path.exists(fpath):
                with open(fpath, "rb") as f:
                    self._set(**{name: cloudpickle.load(f)})


class BundlesModelFile:
    """Persistence mixin for stages with a ``modelFile`` path param: save()
    copies the model file INTO the stage directory and load() rebinds the
    param to the bundled copy — a fitted transformer whose modelFile points
    at a temp dir (KerasImageFileEstimator._fit) survives process exit,
    host moves, and temp-dir cleanup (SURVEY.md §5.4 durability).

    MRO note: place before PicklesCallableParams so both payload hooks run
    (each calls super())."""

    _MODEL_EXTS = (".keras", ".h5", ".hdf5")

    def _save_payload(self, path: str):
        super()._save_payload(path)
        if self.isDefined("modelFile"):
            import shutil
            src = self.getOrDefault("modelFile")
            ext = os.path.splitext(src)[1]
            # Fail LOUDLY here, not at load time on another host: a save()
            # that silently skips the model file is exactly the
            # non-durability this mixin exists to prevent.
            if not os.path.exists(src):
                raise FileNotFoundError(
                    f"save(): modelFile {src!r} no longer exists — the "
                    f"stage cannot be persisted durably")
            if ext not in self._MODEL_EXTS:
                raise ValueError(
                    f"save(): modelFile extension {ext!r} not one of "
                    f"{self._MODEL_EXTS}; load() would not find the bundle")
            shutil.copyfile(src, os.path.join(path, "model" + ext))

    def _load_payload(self, path: str, meta: dict):
        super()._load_payload(path, meta)
        for ext in self._MODEL_EXTS:
            bundled = os.path.join(path, "model" + ext)
            if os.path.exists(bundled):
                self._set(modelFile=bundled)
                break
