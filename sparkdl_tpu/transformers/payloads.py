"""Shared persistence helper: params whose values are callables.

Callable params (jittable fns, image loaders) can't go in metadata.json;
subclasses list them in ``_pickled_params`` and this mixin cloudpickles each
set value into ``<name>.pkl`` beside the stage metadata.
"""

from __future__ import annotations

import os


class PicklesCallableParams:
    _pickled_params: tuple[str, ...] = ()

    def _save_payload(self, path: str):
        import cloudpickle
        for name in self._pickled_params:
            if self.isSet(name):
                with open(os.path.join(path, f"{name}.pkl"), "wb") as f:
                    cloudpickle.dump(self.getOrDefault(name), f)

    def _load_payload(self, path: str, meta: dict):
        import cloudpickle
        for name in self._pickled_params:
            fpath = os.path.join(path, f"{name}.pkl")
            if os.path.exists(fpath):
                with open(fpath, "rb") as f:
                    self._set(**{name: cloudpickle.load(f)})


class BundlesModelFile:
    """Persistence mixin for stages with a ``modelFile`` path param: save()
    copies the model file INTO the stage directory and load() rebinds the
    param to the bundled copy — a fitted transformer whose modelFile points
    at a temp dir (KerasImageFileEstimator._fit) survives process exit,
    host moves, and temp-dir cleanup (SURVEY.md §5.4 durability).

    MRO note: place before PicklesCallableParams so both payload hooks run
    (each calls super())."""

    def _save_payload(self, path: str):
        super()._save_payload(path)
        if self.isDefined("modelFile"):
            import shutil
            src = self.getOrDefault("modelFile")
            if os.path.exists(src):
                shutil.copyfile(src, os.path.join(
                    path, "model" + os.path.splitext(src)[1]))

    def _load_payload(self, path: str, meta: dict):
        super()._load_payload(path, meta)
        for ext in (".keras", ".h5", ".hdf5"):
            bundled = os.path.join(path, "model" + ext)
            if os.path.exists(bundled):
                self._set(modelFile=bundled)
                break
