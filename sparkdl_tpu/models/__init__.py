from .bert import (BertConfig, BertEncoder, BertForSequenceClassification,
                   bert_finetune_loss, glue_loss_fn)
from .llama import (LlamaConfig, LlamaModel, causal_lm_loss_fn, lora_mask,
                    lora_optimizer)
from .pretrained import (CheckpointMismatch, cast_float_leaves,
                         import_hf_bert, import_hf_llama,
                         import_keras_inception, import_keras_resnet,
                         import_keras_vgg, import_keras_xception,
                         load_pretrained, merge_into_template, read_keras_h5)
from .tokenizer import ByteBPETokenizer
from .registry import (SUPPORTED_MODELS, NamedImageModel, decodePredictions,
                       get_model, load_safetensors, load_weights,
                       preprocess_caffe, preprocess_tf, preprocess_torch,
                       save_safetensors, save_weights)

__all__ = [
    "SUPPORTED_MODELS", "NamedImageModel", "get_model", "decodePredictions",
    "preprocess_tf", "preprocess_caffe", "preprocess_torch",
    "save_weights", "load_weights", "load_safetensors", "save_safetensors",
    "BertConfig", "BertEncoder", "BertForSequenceClassification",
    "glue_loss_fn", "bert_finetune_loss",
    "LlamaConfig", "LlamaModel", "causal_lm_loss_fn", "lora_mask",
    "lora_optimizer",
    "load_pretrained", "import_hf_llama", "import_hf_bert",
    "import_keras_resnet", "import_keras_vgg", "import_keras_inception",
    "import_keras_xception",
    "read_keras_h5", "merge_into_template", "CheckpointMismatch",
    "ByteBPETokenizer", "cast_float_leaves",
]
