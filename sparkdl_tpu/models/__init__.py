from .registry import (SUPPORTED_MODELS, NamedImageModel, decodePredictions,
                       get_model, load_safetensors, load_weights,
                       preprocess_caffe, preprocess_tf, preprocess_torch,
                       save_safetensors, save_weights)

__all__ = [
    "SUPPORTED_MODELS", "NamedImageModel", "get_model", "decodePredictions",
    "preprocess_tf", "preprocess_caffe", "preprocess_torch",
    "save_weights", "load_weights", "load_safetensors", "save_safetensors",
]
