"""ResNet v1 family in Flax linen, NHWC, TPU-first.

Provides the ResNet50 named model of the reference registry (expected upstream
``python/sparkdl/transformers/keras_applications.py`` — SURVEY.md §2.1) plus
the rest of the v1 family. Written for the MXU: NHWC layout (XLA:TPU's native
conv layout), a ``dtype`` knob for bfloat16 compute with float32 params, and
no data-dependent Python control flow — the whole forward pass is one traced
graph.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck with projection shortcut on shape change.

    ``stride_on_3x3=True`` (default) is the v1.5 variant (downsampling in
    the 3x3, as torchvision); ``False`` is the original v1 / keras-
    applications placement (stride on the first 1x1) — parameter shapes are
    identical, only the conv semantics differ, so set False when loading
    keras-trained weights (models/pretrained.py)."""
    filters: int
    strides: int
    dtype: Any = jnp.float32
    stride_on_3x3: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        s = (self.strides, self.strides)
        s1, s2 = ((1, 1), s) if self.stride_on_3x3 else (s, (1, 1))
        residual = x
        y = conv(self.filters, (1, 1), strides=s1, name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), strides=s2, name="conv2")(y)
        y = norm(name="bn2")(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = norm(name="bn3")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides),
                            name="proj_conv")(x)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class BasicBlock(nn.Module):
    """3x3 → 3x3 block (ResNet-18/34)."""
    filters: int
    strides: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), name="conv2")(y)
        y = norm(name="bn2")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            strides=(self.strides, self.strides),
                            name="proj_conv")(x)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet v1. ``__call__(x, features_only=True)`` yields the pooled
    bottleneck features — the featurizer output of DeepImageFeaturizer."""
    stage_sizes: Sequence[int]
    block: ModuleDef
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.float32
    stride_on_3x3: bool = True  # v1.5; False = keras-applications v1

    @nn.compact
    def __call__(self, x, train: bool = False, features_only: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype, name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                kw = ({"stride_on_3x3": self.stride_on_3x3}
                      if self.block is BottleneckBlock else {})
                x = self.block(self.width * 2 ** i, strides, dtype=self.dtype,
                               name=f"stage{i + 1}_block{j + 1}",
                               **kw)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool → (N, C)
        x = x.astype(jnp.float32)
        if features_only:
            return x
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block=BottleneckBlock)

