"""Foreign pretrained-checkpoint importers — name-mapping external layouts
onto this framework's flax trees.

The reference's model zoo loaded Keras-applications ``.h5`` files and TF
checkpoints directly (reference: ``python/sparkdl/transformers/
keras_applications.py``, SURVEY.md §7 hard-part #4: "h5/safetensors → Flax
pytrees for the model zoo"). Here the supported foreign layouts are:

- **HuggingFace-layout safetensors** for Llama (``model.layers.N.self_attn.
  q_proj.weight`` …) and BERT (``bert.encoder.layer.N.attention.self.query.
  weight`` …) → :mod:`sparkdl_tpu.models.llama` / ``bert`` trees. Linear
  weights are torch ``[out, in]`` and transpose to flax ``[in, out]``;
  Llama q/k projections additionally permute head dims from HF's
  half-split rotary convention to this repo's interleaved convention
  (see ``_rope_permutation``).
- **Keras-layout ``.h5``** (both the legacy ``layer_names`` topological
  format of the published keras-applications ImageNet files and the
  Keras-3 ``.weights.h5`` format) for the image zoo → ``models/resnet.py``
  / ``vgg.py`` / ``inception.py`` / ``xception.py`` trees. Conv biases
  present in keras ResNet files are folded into the following BatchNorm's
  moving mean (exact under eval-mode BN; a bias preceding train-mode BN
  is a no-op); separable convs transpose keras' (h,w,in,1) depthwise
  kernels to flax's (h,w,1,in).

Everything runs offline on locally-provided files (zero-egress
environment); tests generate foreign-named checkpoints with the installed
``transformers``/``keras`` packages and assert forward-pass equivalence.
"""

from __future__ import annotations

import re
from typing import Mapping

import numpy as np


class CheckpointMismatch(ValueError):
    """A foreign checkpoint doesn't match the target model/config."""


def _as_state_dict(path_or_state) -> dict[str, np.ndarray]:
    """Accept a safetensors path or an already-loaded {name: array} dict."""
    if isinstance(path_or_state, str):
        from safetensors.numpy import load_file
        return dict(load_file(path_or_state))
    return {k: np.asarray(v) for k, v in path_or_state.items()}


def _t(w: np.ndarray) -> np.ndarray:
    """torch Linear [out, in] → flax Dense kernel [in, out]."""
    return np.ascontiguousarray(np.asarray(w).T)


def _take(state: dict, key: str, shape=None) -> np.ndarray:
    try:
        w = state.pop(key)
    except KeyError:
        raise CheckpointMismatch(
            f"checkpoint is missing {key!r}; present keys start with "
            f"{sorted(state)[:3]}") from None
    if shape is not None and tuple(w.shape) != tuple(shape):
        raise CheckpointMismatch(
            f"{key}: checkpoint shape {tuple(w.shape)} != "
            f"model shape {tuple(shape)}")
    return np.asarray(w)


# ---------------------------------------------------------------------------
# HF Llama
# ---------------------------------------------------------------------------

def _rope_permutation(head_dim: int) -> np.ndarray:
    """Per-head output-dim permutation HF→interleaved.

    HF checkpoints pair rotary dims as (j, j+d/2) (``rotate_half``); this
    repo's :func:`models.llama.rope` pairs (2j, 2j+1). Both use frequency
    ``theta^(-2j/d)`` for pair j, so remapping dim ``2j ← j`` and
    ``2j+1 ← j+d/2`` makes attention outputs identical (q·k inner products
    are invariant under a shared per-head permutation of q and k).
    """
    half = head_dim // 2
    perm = np.empty(head_dim, dtype=np.int64)
    perm[0::2] = np.arange(half)
    perm[1::2] = np.arange(half, head_dim)
    return perm


def _permute_rope_rows(w: np.ndarray, n_heads: int) -> np.ndarray:
    """Apply the HF→interleaved permutation to a [heads*hd, in] torch
    weight's output rows, per head."""
    out, inner = w.shape
    hd = out // n_heads
    perm = _rope_permutation(hd)
    return w.reshape(n_heads, hd, inner)[:, perm, :].reshape(out, inner)


def import_hf_llama(path_or_state, cfg) -> dict:
    """HF-layout Llama safetensors → ``{"params": ...}`` for
    :class:`models.llama.LlamaModel` built with ``cfg``.

    Accepts both ``model.layers...``-prefixed (LlamaForCausalLM) and bare
    ``layers...`` (LlamaModel) key styles. A missing ``lm_head.weight``
    (tied-embedding checkpoints) falls back to the token embedding.
    LoRA adapter leaves (``cfg.lora_rank > 0``) are NOT expected in the
    file — import the base weights, then fine-tune adapters from zero
    (flax initializes them on first apply via ``init``; merge trees with
    :func:`merge_into_template`).
    """
    state = _as_state_dict(path_or_state)
    if any(k.startswith("model.") for k in state):
        state = {k[len("model."):] if k.startswith("model.") else k: v
                 for k, v in state.items()}

    hs, hd = cfg.hidden_size, cfg.head_dim
    q_out = cfg.num_heads * hd
    kv_out = cfg.num_kv_heads * hd
    params: dict = {}

    emb = _take(state, "embed_tokens.weight", (cfg.vocab_size, hs))
    params["embed_tokens"] = {"embedding": emb}

    for i in range(cfg.num_layers):
        p = f"layers.{i}."
        attn = {
            "q_proj": {"base": {"kernel": _t(_permute_rope_rows(
                _take(state, p + "self_attn.q_proj.weight", (q_out, hs)),
                cfg.num_heads))}},
            "k_proj": {"base": {"kernel": _t(_permute_rope_rows(
                _take(state, p + "self_attn.k_proj.weight", (kv_out, hs)),
                cfg.num_kv_heads))}},
            "v_proj": {"base": {"kernel": _t(
                _take(state, p + "self_attn.v_proj.weight", (kv_out, hs)))}},
            "o_proj": {"base": {"kernel": _t(
                _take(state, p + "self_attn.o_proj.weight", (hs, q_out)))}},
        }
        mlp = {
            "gate_proj": {"base": {"kernel": _t(_take(
                state, p + "mlp.gate_proj.weight",
                (cfg.intermediate_size, hs)))}},
            "up_proj": {"base": {"kernel": _t(_take(
                state, p + "mlp.up_proj.weight",
                (cfg.intermediate_size, hs)))}},
            "down_proj": {"base": {"kernel": _t(_take(
                state, p + "mlp.down_proj.weight",
                (hs, cfg.intermediate_size)))}},
        }
        params[f"layer_{i}"] = {
            "attn": attn,
            "mlp": mlp,
            "attn_norm": {"scale": _take(
                state, p + "input_layernorm.weight", (hs,))},
            "mlp_norm": {"scale": _take(
                state, p + "post_attention_layernorm.weight", (hs,))},
        }

    params["final_norm"] = {"scale": _take(state, "norm.weight", (hs,))}
    if "lm_head.weight" in state:
        params["lm_head"] = {"kernel": _t(_take(
            state, "lm_head.weight", (cfg.vocab_size, hs)))}
    else:  # tied embeddings
        params["lm_head"] = {"kernel": np.ascontiguousarray(emb.T)}

    leftovers = [k for k in state if not k.endswith("rotary_emb.inv_freq")]
    if leftovers:
        raise CheckpointMismatch(
            f"{len(leftovers)} unconsumed checkpoint keys, "
            f"e.g. {sorted(leftovers)[:3]} — config mismatch?")
    return {"params": params}


# ---------------------------------------------------------------------------
# HF BERT
# ---------------------------------------------------------------------------

def _hf_ln(state: dict, prefix: str, width: int) -> dict:
    """HF LayerNorm → flax {scale, bias}; tolerates old gamma/beta names."""
    if prefix + ".gamma" in state:
        return {"scale": _take(state, prefix + ".gamma", (width,)),
                "bias": _take(state, prefix + ".beta", (width,))}
    return {"scale": _take(state, prefix + ".weight", (width,)),
            "bias": _take(state, prefix + ".bias", (width,))}


def _hf_dense(state: dict, prefix: str, in_w: int, out_w: int) -> dict:
    return {"kernel": _t(_take(state, prefix + ".weight", (out_w, in_w))),
            "bias": _take(state, prefix + ".bias", (out_w,))}


_IGNORED_BERT = re.compile(r"(^|\.)(cls\.|seq_relationship|position_ids$)")


def _check_consumed(state: dict, ignore: re.Pattern = _IGNORED_BERT):
    leftovers = [k for k in state if not ignore.search(k)]
    if leftovers:
        raise CheckpointMismatch(
            f"{len(leftovers)} unconsumed checkpoint keys, "
            f"e.g. {sorted(leftovers)[:3]} — config mismatch?")


def import_hf_bert(path_or_state, cfg, num_classes: int | None = None) -> dict:
    """HF-layout BERT safetensors → ``{"params": ...}``.

    With ``num_classes`` the result fits
    :class:`models.bert.BertForSequenceClassification` (a matching
    ``classifier.weight`` in the file is used, otherwise the head is
    zero-initialized — the HF fine-tuning convention); without it, a bare
    :class:`models.bert.BertEncoder` tree is returned.
    """
    state = _as_state_dict(path_or_state)
    for pref in ("bert.", "model."):
        if any(k.startswith(pref + "embeddings.") for k in state):
            state = {(k[len(pref):] if k.startswith(pref) else k): v
                     for k, v in state.items()}
            break
    hs = cfg.hidden_size

    bert: dict = {
        "word_embeddings": {"embedding": _take(
            state, "embeddings.word_embeddings.weight",
            (cfg.vocab_size, hs))},
        "position_embeddings": {"embedding": _take(
            state, "embeddings.position_embeddings.weight",
            (cfg.max_position_embeddings, hs))},
        "token_type_embeddings": {"embedding": _take(
            state, "embeddings.token_type_embeddings.weight",
            (cfg.type_vocab_size, hs))},
        "embeddings_norm": _hf_ln(state, "embeddings.LayerNorm", hs),
    }
    for i in range(cfg.num_layers):
        p = f"encoder.layer.{i}."
        bert[f"layer_{i}"] = {
            "attention": {
                "query": _hf_dense(state, p + "attention.self.query", hs, hs),
                "key": _hf_dense(state, p + "attention.self.key", hs, hs),
                "value": _hf_dense(state, p + "attention.self.value", hs, hs),
                "attention_output": _hf_dense(
                    state, p + "attention.output.dense", hs, hs),
            },
            "attention_norm": _hf_ln(
                state, p + "attention.output.LayerNorm", hs),
            "intermediate": _hf_dense(
                state, p + "intermediate.dense", hs, cfg.intermediate_size),
            "output_dense": _hf_dense(
                state, p + "output.dense", cfg.intermediate_size, hs),
            "output_norm": _hf_ln(state, p + "output.LayerNorm", hs),
        }
    bert["pooler"] = _hf_dense(state, "pooler.dense", hs, hs)

    if num_classes is None:
        _check_consumed(state)
        return {"params": bert}

    if "classifier.weight" in state \
            and state["classifier.weight"].shape[0] == num_classes:
        head = _hf_dense(state, "classifier", hs, num_classes)
    else:
        state.pop("classifier.weight", None)
        state.pop("classifier.bias", None)
        head = {"kernel": np.zeros((hs, num_classes), np.float32),
                "bias": np.zeros((num_classes,), np.float32)}
    _check_consumed(state)
    return {"params": {"bert": bert, "classifier": head}}


# ---------------------------------------------------------------------------
# Keras .h5 reading (legacy topological + Keras-3 .weights.h5)
# ---------------------------------------------------------------------------

def read_keras_h5(path: str) -> dict[str, list[np.ndarray]]:
    """Read a Keras weights file → {layer_name: [arrays in save order]}.

    Handles the legacy topological format of the published
    keras-applications ImageNet files (root attr ``layer_names``, per-layer
    attr ``weight_names``) and the Keras-3 ``.weights.h5`` layout
    (``_layer_checkpoint_dependencies/<name>/vars/<i>``).
    """
    import h5py
    out: dict[str, list[np.ndarray]] = {}
    with h5py.File(path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f
        if "layer_names" in root.attrs:  # legacy topological format
            for lname in root.attrs["layer_names"]:
                lname = lname.decode() if isinstance(lname, bytes) else lname
                g = root[lname]
                weights = []
                for wname in g.attrs.get("weight_names", []):
                    wname = (wname.decode()
                             if isinstance(wname, bytes) else wname)
                    weights.append(np.asarray(g[wname]))
                if weights:
                    out[lname.split("/")[-1]] = weights
            return out
        deps = "_layer_checkpoint_dependencies"
        if deps in root:  # Keras-3 format
            def walk(group, name):
                for child, item in group.items():
                    if child == "vars" and len(item):
                        out[name] = [np.asarray(item[str(i)])
                                     for i in range(len(item))]
                    elif hasattr(item, "items"):
                        walk(item, child)
            walk(root[deps], "")
            return out
    raise CheckpointMismatch(f"{path}: unrecognized Keras weights layout")


def _keras_convbn(layers: Mapping[str, list], conv_name: str, bn_name: str):
    """One keras conv+bn pair → (conv_params, bn_params, bn_stats).

    A conv bias (keras-applications ResNet convs have one; this repo's
    conv-bn units don't) is folded into the BN moving mean — exact under
    eval-mode BN, and a bias feeding train-mode BN is mathematically inert.
    BN saved with ``scale=False`` (keras InceptionV3) gets scale=1.
    """
    if conv_name not in layers:
        raise CheckpointMismatch(f"Keras file has no layer {conv_name!r}")
    if bn_name not in layers:
        raise CheckpointMismatch(f"Keras file has no layer {bn_name!r}")
    cw = list(layers[conv_name])
    kernel = np.asarray(cw[0])  # keras HWIO == flax HWIO
    bias = np.asarray(cw[1]) if len(cw) > 1 else None
    bw = list(layers[bn_name])
    if len(bw) == 4:
        gamma, beta, mean, var = (np.asarray(a) for a in bw)
    elif len(bw) == 3:  # scale=False
        beta, mean, var = (np.asarray(a) for a in bw)
        gamma = np.ones_like(beta)
    else:
        raise CheckpointMismatch(
            f"{bn_name}: expected 3 or 4 BN arrays, got {len(bw)}")
    if bias is not None:
        mean = mean - bias
    return ({"kernel": kernel}, {"scale": gamma, "bias": beta},
            {"mean": mean, "var": var})


def _keras_dense(layers: Mapping[str, list], name: str) -> dict:
    if name not in layers:
        raise CheckpointMismatch(f"Keras file has no layer {name!r}")
    w = layers[name]
    leaf = {"kernel": np.asarray(w[0])}  # keras Dense kernel is [in, out]
    if len(w) > 1:
        leaf["bias"] = np.asarray(w[1])
    return leaf


def _check_tree_shapes(got: dict, template: dict, where: str = ""):
    """Every template leaf must exist in ``got`` with the same shape."""
    import jax
    gleaves = {tuple(str(k.key) for k in p): v.shape for p, v in
               jax.tree_util.tree_leaves_with_path(got)}
    for p, tv in jax.tree_util.tree_leaves_with_path(template):
        key = tuple(str(k.key) for k in p)
        if key not in gleaves:
            raise CheckpointMismatch(f"{where}: import missed {key}")
        if tuple(gleaves[key]) != tuple(tv.shape):
            raise CheckpointMismatch(
                f"{where}: {'/'.join(key)} imported shape {gleaves[key]} "
                f"!= model shape {tuple(tv.shape)}")


# ---------------------------------------------------------------------------
# Keras → image-zoo trees
# ---------------------------------------------------------------------------

_KERAS_RESNET_STAGES = {"ResNet50": (3, 4, 6, 3), "ResNet101": (3, 4, 23, 3),
                        "ResNet152": (3, 8, 36, 3)}


def import_keras_resnet(path: str, template: dict,
                        name: str = "ResNet50") -> dict:
    """Keras-layout ResNet{50,101,152} ``.h5`` → ``models/resnet.py`` tree.

    Name mapping: ``conv1_conv``/``conv1_bn`` → ``stem_conv``/``stem_bn``;
    ``conv{s+1}_block{b}_{k}_conv`` → ``stage{s}_block{b}/conv{k}``
    (``_0_conv``, the projection shortcut, → ``proj_conv``);
    ``predictions`` → ``head``.

    keras-applications ResNet is the v1 architecture (downsampling stride
    on the first 1x1 conv); this repo's default is v1.5 (stride on the
    3x3). Shapes are identical either way — build the model with
    ``stride_on_3x3=False`` for exact keras semantics.
    """
    if name not in _KERAS_RESNET_STAGES:
        raise CheckpointMismatch(
            f"No Keras .h5 layout exists for {name!r} — keras-applications "
            f"ships only {sorted(_KERAS_RESNET_STAGES)}")
    layers = read_keras_h5(path)
    params: dict = {}
    stats: dict = {}

    conv, bn, st = _keras_convbn(layers, "conv1_conv", "conv1_bn")
    params["stem_conv"], params["stem_bn"], stats["stem_bn"] = conv, bn, st

    for s, n_blocks in enumerate(_KERAS_RESNET_STAGES[name]):
        for b in range(n_blocks):
            kpre = f"conv{s + 2}_block{b + 1}"
            mine = f"stage{s + 1}_block{b + 1}"
            bp: dict = {}
            bs: dict = {}
            for k in (1, 2, 3):
                conv, bn, st = _keras_convbn(
                    layers, f"{kpre}_{k}_conv", f"{kpre}_{k}_bn")
                bp[f"conv{k}"], bp[f"bn{k}"], bs[f"bn{k}"] = conv, bn, st
            if f"{kpre}_0_conv" in layers:  # projection shortcut (block 1)
                conv, bn, st = _keras_convbn(
                    layers, f"{kpre}_0_conv", f"{kpre}_0_bn")
                bp["proj_conv"], bp["proj_bn"], bs["proj_bn"] = conv, bn, st
            params[mine], stats[mine] = bp, bs

    if "head" in template.get("params", {}):
        params["head"] = _keras_dense(
            layers, "predictions" if "predictions" in layers else "head")

    out = {"params": params, "batch_stats": stats}
    _check_tree_shapes(out, template, f"keras {name}")
    return out


def import_keras_vgg(path: str, template: dict) -> dict:
    """Keras-layout VGG16/19 ``.h5`` → ``models/vgg.py`` tree. Layer names
    (block1_conv1 … fc1, fc2, predictions→head) map 1:1; kernels are HWIO /
    [in, out] in both frameworks."""
    layers = read_keras_h5(path)
    params = {}
    for lname in template["params"]:
        src = lname
        if lname == "head" and "head" not in layers:
            src = "predictions"
        params[lname] = _keras_dense(layers, src)
    out = {"params": params}
    _check_tree_shapes(out, template, "keras VGG")
    return out


def _inception_conv_order() -> list[tuple[str, ...]]:
    """This repo's InceptionV3 ConvBN module paths in *creation order* —
    which matches keras-applications' conv2d_bn call order exactly (same
    branch order per mixed block, verified by the forward-equivalence
    test), so the file's auto-numbered conv2d_N/batch_normalization_N
    layers map by index."""
    order: list[tuple[str, ...]] = [(f"stem{i}",) for i in range(1, 6)]
    a = ["b1x1", "b5x5_1", "b5x5_2", "b3x3dbl_1", "b3x3dbl_2", "b3x3dbl_3",
         "bpool"]
    b = ["b3x3", "b3x3dbl_1", "b3x3dbl_2", "b3x3dbl_3"]
    c = ["b1x1", "b7x7_1", "b7x7_2", "b7x7_3", "b7x7dbl_1", "b7x7dbl_2",
         "b7x7dbl_3", "b7x7dbl_4", "b7x7dbl_5", "bpool"]
    d = ["b3x3_1", "b3x3_2", "b7x7x3_1", "b7x7x3_2", "b7x7x3_3", "b7x7x3_4"]
    e = ["b1x1", "b3x3_1", "b3x3_2a", "b3x3_2b", "b3x3dbl_1", "b3x3dbl_2",
         "b3x3dbl_3a", "b3x3dbl_3b", "bpool"]
    blocks = [a, a, a, b, c, c, c, c, d, e, e]
    for i, names in enumerate(blocks):
        order.extend((f"mixed{i}", n) for n in names)
    return order


def _numbered(layers: Mapping[str, list], stem: str) -> list[str]:
    """Layer names matching ``stem`` or ``stem_N``, sorted by N (creation
    order). The published InceptionV3 files number from 1; fresh keras
    sessions from 0/none — sorting by suffix handles both."""
    pat = re.compile(rf"^{re.escape(stem)}(?:_(\d+))?$")
    found = []
    for k in layers:
        m = pat.match(k)
        if m:
            found.append((int(m.group(1) or 0), k))
    return [k for _, k in sorted(found)]


def import_keras_inception(path: str, template: dict) -> dict:
    """Keras-layout InceptionV3 ``.h5`` → ``models/inception.py`` tree.

    The published file auto-numbers its conv/bn layers (conv2d_1, …); they
    are matched to this repo's ConvBN modules by creation order (see
    :func:`_inception_conv_order`). BN is saved with ``scale=False`` →
    scale=1.
    """
    layers = read_keras_h5(path)
    convs = _numbered(layers, "conv2d")
    bns = _numbered(layers, "batch_normalization")
    order = _inception_conv_order()
    if len(convs) != len(order) or len(bns) != len(order):
        raise CheckpointMismatch(
            f"InceptionV3 expects {len(order)} conv/bn pairs, file has "
            f"{len(convs)} convs / {len(bns)} bns")
    params: dict = {}
    stats: dict = {}

    def setd(root, p, leaf):
        for k in p[:-1]:
            root = root.setdefault(k, {})
        root[p[-1]] = leaf

    for path_, cname, bname in zip(order, convs, bns):
        conv, bn, st = _keras_convbn(layers, cname, bname)
        setd(params, path_ + ("conv",), conv)
        setd(params, path_ + ("bn",), bn)
        setd(stats, path_ + ("bn",), st)

    if "head" in template.get("params", {}):
        params["head"] = _keras_dense(
            layers, "predictions" if "predictions" in layers else "head")
    out = {"params": params, "batch_stats": stats}
    _check_tree_shapes(out, template, "keras InceptionV3")
    return out


def _keras_sepconv(layers: Mapping[str, list], sep_name: str,
                   bn_name: str):
    """One keras SeparableConv2D(+BN) → this repo's SeparableConvBN leaves.

    Keras stores [depthwise_kernel (h,w,in,1), pointwise_kernel] in ONE
    layer; flax's grouped-conv depthwise kernel is (h,w,1,in) — transpose
    the last two axes."""
    if sep_name not in layers:
        raise CheckpointMismatch(f"Keras file has no layer {sep_name!r}")
    w = layers[sep_name]
    if len(w) != 2:
        raise CheckpointMismatch(
            f"{sep_name}: expected [depthwise, pointwise], got {len(w)} "
            f"arrays (biased separable convs are not part of this layout)")
    dw = np.transpose(np.asarray(w[0]), (0, 1, 3, 2))
    pw = np.asarray(w[1])
    bw = list(layers.get(bn_name, ()))
    if len(bw) != 4:
        raise CheckpointMismatch(f"{bn_name}: expected 4 BN arrays")
    gamma, beta, mean, var = (np.asarray(a) for a in bw)
    return ({"depthwise": {"kernel": dw}, "pointwise": {"kernel": pw},
             "bn": {"scale": gamma, "bias": beta}},
            {"bn": {"mean": mean, "var": var}})


def import_keras_xception(path: str, template: dict) -> dict:
    """Keras-layout Xception ``.h5`` → ``models/xception.py`` tree.

    Named layers (block{i}_sepconv{j}, block1_conv{1,2}) map directly; the
    four residual 1x1 convs are auto-named (conv2d[_N]) and map by creation
    order: entry1, entry2, entry3, exit projections.
    """
    layers = read_keras_h5(path)
    params: dict = {}
    stats: dict = {}

    for i in (1, 2):
        conv, bn, st = _keras_convbn(layers, f"block1_conv{i}",
                                     f"block1_conv{i}_bn")
        params[f"stem_conv{i}"] = conv
        params[f"stem_bn{i}"] = bn
        stats[f"stem_bn{i}"] = st

    def sep_into(block: dict, bstats: dict, key: str, kname: str):
        p, s = _keras_sepconv(layers, kname, kname + "_bn")
        block[key] = p
        bstats[key] = s

    for i in (1, 2, 3):  # entry blocks ← keras block2..4
        bp: dict = {}
        bs: dict = {}
        for j in (1, 2):
            sep_into(bp, bs, f"sep{j}", f"block{i + 1}_sepconv{j}")
        params[f"entry{i}"], stats[f"entry{i}"] = bp, bs
    for i in range(1, 9):  # middle blocks ← keras block5..12
        for j in (1, 2, 3):
            p, s = _keras_sepconv(layers, f"block{i + 4}_sepconv{j}",
                                  f"block{i + 4}_sepconv{j}_bn")
            params[f"middle{i}_sep{j}"] = p
            stats[f"middle{i}_sep{j}"] = s
    for key, kname in (("exit_sep1", "block13_sepconv1"),
                       ("exit_sep2", "block13_sepconv2"),
                       ("exit_sep3", "block14_sepconv1"),
                       ("exit_sep4", "block14_sepconv2")):
        p, s = _keras_sepconv(layers, kname, kname + "_bn")
        params[key] = p
        stats[key] = s

    # residual projections: auto-named conv2d[_N]/batch_normalization[_N],
    # creation order = entry1, entry2, entry3, exit
    convs = _numbered(layers, "conv2d")
    bns = _numbered(layers, "batch_normalization")
    if len(convs) != 4 or len(bns) != 4:
        raise CheckpointMismatch(
            f"Xception expects 4 auto-named residual conv/bn pairs, file "
            f"has {len(convs)}/{len(bns)}")
    for block, cname, bname in zip(
            ["entry1", "entry2", "entry3", None], convs, bns):
        conv, bn, st = _keras_convbn(layers, cname, bname)
        if block is None:  # the exit-flow projection is flat-named
            params["exit_proj_conv"], params["exit_proj_bn"] = conv, bn
            stats["exit_proj_bn"] = st
        else:
            params[block]["proj_conv"] = conv
            params[block]["proj_bn"] = bn
            stats[block]["proj_bn"] = st

    if "head" in template.get("params", {}):
        params["head"] = _keras_dense(
            layers, "predictions" if "predictions" in layers else "head")
    out = {"params": params, "batch_stats": stats}
    _check_tree_shapes(out, template, "keras Xception")
    return out


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

def load_pretrained(model_name: str, path: str, *, cfg=None,
                    num_classes: int | None = None,
                    template: dict | None = None) -> dict:
    """One entry point: foreign checkpoint file → flax variables for a
    named model of this framework.

    - ``load_pretrained("llama", f, cfg=LlamaConfig(...))`` — HF safetensors
    - ``load_pretrained("bert", f, cfg=BertConfig(), num_classes=2)``
    - ``load_pretrained("ResNet50"|"VGG16"|"InceptionV3", f)`` — Keras .h5
      (``template`` defaults to the registry model's seeded init; pass the
      tree of an existing model instance to validate against it)
    - any registry name with a ``.msgpack``/flax-path ``.safetensors`` file
      falls through to the native loaders in :mod:`models.registry`.
    """
    lname = model_name.lower()
    if lname.startswith("llama"):
        from .llama import LlamaConfig
        return import_hf_llama(path, cfg or LlamaConfig())
    if lname.startswith("bert"):
        from .bert import BertConfig
        return import_hf_bert(path, cfg or BertConfig.base(),
                              num_classes=num_classes)

    from . import registry
    if path.endswith((".h5", ".hdf5", ".weights.h5")):
        if template is None:
            template = registry.get_model(model_name).init_params()
        if lname.startswith("resnet"):
            return import_keras_resnet(path, template, name=model_name)
        if lname.startswith("vgg"):
            return import_keras_vgg(path, template)
        if lname.startswith("inception"):
            return import_keras_inception(path, template)
        if lname.startswith("xception"):
            return import_keras_xception(path, template)
        raise CheckpointMismatch(
            f"No Keras .h5 importer for {model_name!r} (supported: "
            f"ResNet50/101/152, VGG16/19, InceptionV3, Xception)")
    if template is None:
        template = registry.get_model(model_name).init_params()
    if path.endswith(".safetensors"):
        return registry.load_safetensors(template, path)
    return registry.load_weights(template, path)


def merge_into_template(imported: dict, template: dict) -> dict:
    """Overlay imported leaves onto a full template tree (e.g. a LoRA model
    whose adapter leaves aren't in the base checkpoint): template leaves
    missing from ``imported`` are kept; shapes must match where present."""
    if not isinstance(template, dict):
        return imported if imported is not None else template
    out = {}
    for k, tv in template.items():
        iv = imported.get(k) if isinstance(imported, dict) else None
        if iv is None:
            out[k] = tv
        elif isinstance(tv, dict):
            out[k] = merge_into_template(iv, tv)
        else:
            if tuple(np.shape(iv)) != tuple(np.shape(tv)):
                raise CheckpointMismatch(
                    f"merge: {k} shape {np.shape(iv)} != {np.shape(tv)}")
            out[k] = iv
    return out


def cast_float_leaves(variables, dtype="bfloat16", *, min_ndim: int = 2):
    """Cast the MATRIX float leaves of a variables pytree to ``dtype`` —
    the serving-weights cast (industry-standard bf16 serving).

    Models here are dtype-parameterized for COMPUTE (flax ``dtype=``) but
    store params in flax's default float32 ``param_dtype``; every
    ``apply`` then re-casts the f32 weights down before each matmul, so a
    decode step's HBM traffic (and the resident footprint) is ~2x what
    the math needs. The cast is scoped to leaves with ``ndim >=
    min_ndim`` (default 2: conv/dense/embedding kernels — virtually all
    the bytes) because those are exactly the leaves flax's
    ``promote_dtype`` casts to the compute dtype at use anyway — for
    them, pre-casting is numerically IDENTICAL. 1-D leaves stay f32 on
    purpose: flax 0.12 BatchNorm/LayerNorm/RMSNorm do NOT cast their
    stats/scale/bias before the f32 normalization math, so casting them
    would silently shift bf16-mode outputs (and ``var + eps`` loses the
    epsilon in bf16). The one approximation that remains: a module that
    intentionally matmuls in f32 against a >=2-D kernel (e.g. a f32
    logits head) sees bf16-ROUNDED weights — the standard bf16-serving
    tradeoff; use the original tree wherever bit-exact f32 parity
    matters (training state, equivalence tests).

    Integer leaves (token ids, step counters) pass through untouched.
    """
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)

    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) \
                and x.dtype != dt and getattr(x, "ndim", 0) >= min_ndim:
            return x.astype(dt)
        return x

    return jax.tree_util.tree_map(cast, variables)
