"""VGG16/VGG19 in Flax linen (reference registry models — SURVEY.md §2.1).

The reference featurizer takes VGG's fc2 (4096-d) activations as the
bottleneck; we mirror that: ``features_only`` returns the post-fc2 ReLU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class VGG(nn.Module):
    cfg: Sequence[int]  # conv counts per block
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, features_only: bool = False):
        x = x.astype(self.dtype)
        widths = [64, 128, 256, 512, 512]
        for b, (n_convs, w) in enumerate(zip(self.cfg, widths)):
            for c in range(n_convs):
                x = nn.Conv(w, (3, 3), padding="SAME", dtype=self.dtype,
                            name=f"block{b + 1}_conv{c + 1}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc2")(x))
        x = x.astype(jnp.float32)
        if features_only:
            return x
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


VGG16 = partial(VGG, cfg=[2, 2, 3, 3, 3])
VGG19 = partial(VGG, cfg=[2, 2, 4, 4, 4])
