"""Xception in Flax linen (reference registry model — SURVEY.md §2.1).

Chollet 2017 (arXiv:1610.02357): depthwise-separable conv stacks with linear
residuals. Separable conv = depthwise (feature_group_count=channels) + 1x1
pointwise — both map cleanly onto XLA:TPU convolution; NHWC throughout.
Input 299x299, bottleneck = 2048-d global-average-pool features.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class SeparableConvBN(nn.Module):
    filters: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        x = nn.Conv(in_ch, (3, 3), padding="SAME", feature_group_count=in_ch,
                    use_bias=False, dtype=self.dtype, name="depthwise")(x)
        x = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype,
                    name="pointwise")(x)
        return nn.BatchNorm(use_running_average=not train, momentum=0.99,
                            epsilon=1e-3, dtype=self.dtype, name="bn")(x)


class XceptionBlock(nn.Module):
    filters: int
    strides: int = 1
    relu_first: bool = True
    grow_first: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = x
        for i in range(2):
            if self.relu_first or i > 0:
                y = nn.relu(y)
            y = SeparableConvBN(self.filters, dtype=self.dtype,
                                name=f"sep{i + 1}")(y, train)
        if self.strides > 1:
            y = nn.max_pool(y, (3, 3), strides=(self.strides, self.strides),
                            padding="SAME")
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False, dtype=self.dtype,
                               name="proj_conv")(x)
            residual = nn.BatchNorm(use_running_average=not train,
                                    momentum=0.99, epsilon=1e-3,
                                    dtype=self.dtype, name="proj_bn")(residual)
        return y + residual


class Xception(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, features_only: bool = False):
        x = x.astype(self.dtype)
        norm = lambda name: nn.BatchNorm(use_running_average=not train,
                                         momentum=0.99, epsilon=1e-3,
                                         dtype=self.dtype, name=name)
        # Entry flow. VALID stem padding — the paper's (and keras-
        # applications') convention, so imported keras weights see the
        # exact spatial grid they were trained on (models/pretrained.py).
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="VALID",
                    use_bias=False, dtype=self.dtype, name="stem_conv1")(x)
        x = nn.relu(norm("stem_bn1")(x))
        x = nn.Conv(64, (3, 3), padding="VALID", use_bias=False,
                    dtype=self.dtype, name="stem_conv2")(x)
        x = nn.relu(norm("stem_bn2")(x))
        x = XceptionBlock(128, strides=2, relu_first=False, dtype=self.dtype,
                          name="entry1")(x, train)
        x = XceptionBlock(256, strides=2, dtype=self.dtype, name="entry2")(x, train)
        x = XceptionBlock(728, strides=2, dtype=self.dtype, name="entry3")(x, train)
        # Middle flow: 8 identity blocks of 3 separable convs
        for i in range(8):
            residual = x
            y = x
            for j in range(3):
                y = nn.relu(y)
                y = SeparableConvBN(728, dtype=self.dtype,
                                    name=f"middle{i + 1}_sep{j + 1}")(y, train)
            x = y + residual
        # Exit flow
        residual = nn.Conv(1024, (1, 1), strides=(2, 2), use_bias=False,
                           dtype=self.dtype, name="exit_proj_conv")(x)
        residual = norm("exit_proj_bn")(residual)
        y = nn.relu(x)
        y = SeparableConvBN(728, dtype=self.dtype, name="exit_sep1")(y, train)
        y = nn.relu(y)
        y = SeparableConvBN(1024, dtype=self.dtype, name="exit_sep2")(y, train)
        y = nn.max_pool(y, (3, 3), strides=(2, 2), padding="SAME")
        x = y + residual
        x = SeparableConvBN(1536, dtype=self.dtype, name="exit_sep3")(x, train)
        x = nn.relu(x)
        x = SeparableConvBN(2048, dtype=self.dtype, name="exit_sep4")(x, train)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = x.astype(jnp.float32)
        if features_only:
            return x
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
