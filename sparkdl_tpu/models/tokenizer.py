"""Self-contained byte-level BPE tokenizer — the missing piece between
"registerTextGenerationUDF exists" and "the config-5 string-column path
runs end-to-end" (round-4 verdict Next #5): the reference era assumed a
downloadable tokenizer; this environment is zero-egress, so the framework
carries one that trains offline on any local text.

Design: GPT-2-style byte fallback without the download. Ids 0..255 are
the raw bytes (every string round-trips losslessly, trained or not);
PAD/BOS/EOS are fixed ids 256/257/258 so special-token ids never shift
as the learned vocabulary grows; merge tokens start at 259 in learned
order. Training is classic BPE — count adjacent-pair frequencies over
whitespace-attached pretoken chunks, greedily merge the most frequent —
which is exactly the published algorithm (Sennrich et al. 2016 / GPT-2's
byte variant), implemented from scratch.
"""

from __future__ import annotations

import json
import re
from collections import Counter
from typing import Iterable, Sequence

# Pretokens keep their LEADING whitespace attached (GPT-2 convention):
# merges then never straddle a word boundary, and " the" can become one
# token while the plain concatenation of decoded token bytes still
# reproduces the input exactly.
_PRETOKEN = re.compile(r"\s*\S+|\s+$")


class ByteBPETokenizer:
    """Byte-level BPE: ``encode`` str → ids, ``decode`` ids → str, with
    ``train``/``save``/``load``. Zero external assets; an UNtrained
    instance is already a valid (byte-only) tokenizer."""

    PAD, BOS, EOS = 256, 257, 258
    _N_SPECIAL_BASE = 259  # merge ids start here

    def __init__(self, merges: Sequence[Sequence[int]] = ()):  # noqa: D401
        self.merges: list[tuple[int, int]] = []
        self._ranks: dict[tuple[int, int], int] = {}
        # id → raw bytes, for O(1) decode of any id (merges expand to the
        # concatenation of their parts; built incrementally so each merge
        # may reference earlier merge ids)
        self._bytes: list[bytes] = [bytes([i]) for i in range(256)]
        self._bytes += [b"", b"", b""]  # PAD/BOS/EOS decode to nothing
        for pair in merges:
            self._add_merge((int(pair[0]), int(pair[1])))

    def _add_merge(self, pair: tuple[int, int]) -> int:
        a, b = pair
        if not (0 <= a < len(self._bytes) and 0 <= b < len(self._bytes)):
            raise ValueError(f"merge {pair} references unknown ids")
        if a in (self.PAD, self.BOS, self.EOS) or \
                b in (self.PAD, self.BOS, self.EOS):
            raise ValueError(f"merge {pair} references special ids")
        new_id = len(self._bytes)
        self.merges.append(pair)
        self._ranks[pair] = len(self.merges) - 1
        self._bytes.append(self._bytes[a] + self._bytes[b])
        return new_id

    @property
    def vocab_size(self) -> int:
        return len(self._bytes)

    # -- training ----------------------------------------------------------

    @classmethod
    def train(cls, texts: Iterable[str], vocab_size: int = 512,
              min_freq: int = 2) -> "ByteBPETokenizer":
        """Learn merges until ``vocab_size`` ids exist or no pair reaches
        ``min_freq``. Works on pretoken chunks so merges never cross
        whitespace boundaries.

        Pair statistics update INCREMENTALLY: each merge rewrites only
        the chunks that contain the merged pair (found via a pair→chunks
        index), so per-merge cost is proportional to affected chunks —
        not a full corpus recount, which would make a vocab_size=8192
        training quadratic. A merged pair can never reappear later (a
        merge only creates adjacencies involving its NEW id), so popping
        its index entry is safe."""
        from collections import defaultdict

        if vocab_size < cls._N_SPECIAL_BASE:
            raise ValueError(
                f"vocab_size must be >= {cls._N_SPECIAL_BASE} "
                f"(256 bytes + 3 specials), got {vocab_size}")
        tok = cls()
        # chunk (as tuple of ids) → corpus occurrence count
        chunks: Counter = Counter()
        for text in texts:
            for m in _PRETOKEN.finditer(text):
                chunks[tuple(m.group().encode("utf-8"))] += 1

        pair_counts: Counter = Counter()
        where: dict = defaultdict(set)  # pair → chunks that contain it

        def add_stats(seq, cnt):
            for p in zip(seq, seq[1:]):
                pair_counts[p] += cnt
                where[p].add(seq)

        def sub_stats(seq, cnt):
            for p in zip(seq, seq[1:]):
                pair_counts[p] -= cnt
                if pair_counts[p] <= 0:
                    del pair_counts[p]

        for seq, cnt in chunks.items():
            add_stats(seq, cnt)

        while tok.vocab_size < vocab_size and pair_counts:
            # deterministic: max count, ties by smallest pair ids
            best, cnt = min(pair_counts.items(),
                            key=lambda kv: (-kv[1], kv[0]))
            if cnt < min_freq:
                break
            new_id = tok._add_merge(best)
            # stale index entries (chunks rewritten by earlier merges)
            # filter out via the membership check
            affected = [s for s in where.pop(best, ()) if s in chunks]
            for seq in affected:
                c = chunks.pop(seq)
                sub_stats(seq, c)
                new_seq = cls._apply_one(seq, best, new_id)
                chunks[new_seq] += c
                add_stats(new_seq, c)
        return tok

    @staticmethod
    def _apply_one(seq: tuple, pair: tuple[int, int], new_id: int) -> tuple:
        out, i, n = [], 0, len(seq)
        while i < n:
            if i < n - 1 and seq[i] == pair[0] and seq[i + 1] == pair[1]:
                out.append(new_id)
                i += 2
            else:
                out.append(seq[i])
                i += 1
        return tuple(out)

    # -- encode / decode ---------------------------------------------------

    def _bpe(self, ids: list[int]) -> list[int]:
        """Apply learned merges lowest-rank-first (the standard BPE encode
        loop) until no adjacent pair has a rank."""
        while len(ids) > 1:
            ranked = [(self._ranks[p], i) for i, p in
                      enumerate(zip(ids, ids[1:])) if p in self._ranks]
            if not ranked:
                break
            rank, _ = min(ranked)
            pair = self.merges[rank]
            ids = list(self._apply_one(tuple(ids), pair,
                                       self._N_SPECIAL_BASE + rank))
        return ids

    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = False) -> list[int]:
        out: list[int] = [self.BOS] if add_bos else []
        for m in _PRETOKEN.finditer(text):
            out.extend(self._bpe(list(m.group().encode("utf-8"))))
        if add_eos:
            out.append(self.EOS)
        return out

    def decode(self, ids: Iterable[int]) -> str:
        buf = b"".join(
            self._bytes[i] for i in (int(x) for x in ids)
            if 0 <= i < len(self._bytes))
        return buf.decode("utf-8", errors="replace")

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"format": "sparkdl_tpu.byte_bpe.v1",
                       "merges": [list(m) for m in self.merges]}, f)

    @classmethod
    def load(cls, path: str) -> "ByteBPETokenizer":
        with open(path) as f:
            blob = json.load(f)
        if blob.get("format") != "sparkdl_tpu.byte_bpe.v1":
            raise ValueError(
                f"{path}: not a sparkdl_tpu byte-BPE file "
                f"(format={blob.get('format')!r})")
        return cls(blob["merges"])
