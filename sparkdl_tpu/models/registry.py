"""Named-model registry — per-model metadata for the image transformers.

Re-creates the reference's ``keras_applications.py`` registry (SURVEY.md §2.1):
for each supported named model — InceptionV3, Xception, ResNet50, VGG16, VGG19
(+ extra ResNet depths) — the constructor, expected input size, preprocessing
function, and bottleneck feature dimension. The preprocess fns are jnp-pure so
they fuse into the same XLA program as the model forward pass (the reference
ran preprocessing as a separate TF graph piece stitched in front — SURVEY.md
§3.1; here XLA fusion makes the stitch free).

Weights: zero-egress environment → models initialize randomly
(``init_params``); ``save_weights``/``load_weights`` use flax msgpack
serialization, and ``load_safetensors`` imports locally-provided safetensors
files by flattened param path.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from . import inception, resnet, vgg, xception

IMAGENET_CLASSES = 1000

_CAFFE_MEAN = (103.939, 116.779, 123.68)  # BGR order
_TORCH_MEAN = (0.485, 0.456, 0.406)
_TORCH_STD = (0.229, 0.224, 0.225)


def _as_float(x):
    """Integer image batches (the uint8 wire format — 4x fewer
    host→HBM bytes than f32) upcast IN-GRAPH before the arithmetic:
    without this, caffe's mean subtraction would run in uint8 and WRAP
    (103.94 → 103, 90-103 → 243+), and tf's ``x/127.5`` would rely on
    dtype promotion. XLA fuses the cast into the first op for free."""
    return x.astype(jnp.float32) if jnp.issubdtype(x.dtype, jnp.integer) \
        else x


def preprocess_tf(x):
    """Scale [0,255] → [-1,1] (InceptionV3 / Xception convention)."""
    return _as_float(x) / 127.5 - 1.0


def preprocess_caffe(x):
    """RGB→BGR + ImageNet mean subtraction (ResNet50/VGG convention)."""
    x = _as_float(x)[..., ::-1]
    return x - jnp.asarray(_CAFFE_MEAN, dtype=x.dtype)


def preprocess_torch(x):
    x = _as_float(x) / 255.0
    return (x - jnp.asarray(_TORCH_MEAN, dtype=x.dtype)) / jnp.asarray(
        _TORCH_STD, dtype=x.dtype)


@dataclass(frozen=True)
class NamedImageModel:
    """Metadata + builders for one named model."""
    name: str
    factory: Callable[..., Any]  # (num_classes, dtype) → flax Module
    input_size: tuple[int, int]  # (H, W)
    preprocess: Callable  # jnp [0,255] NHWC float → model input
    feature_dim: int
    num_classes: int = IMAGENET_CLASSES

    def build(self, dtype=jnp.float32, num_classes: int | None = None,
              **build_kwargs):
        """``build_kwargs`` pass through to the flax factory (e.g.
        ``stride_on_3x3=False`` for keras-v1 ResNet semantics when running
        keras-applications weights — models/pretrained.py)."""
        return self.factory(num_classes=num_classes or self.num_classes,
                            dtype=dtype, **build_kwargs)

    def init_params(self, seed: int = 0, dtype=jnp.float32,
                    num_classes: int | None = None, **build_kwargs):
        model = self.build(dtype, num_classes, **build_kwargs)
        h, w = self.input_size

        # jit the init: un-jitted flax init executes op-by-op, which on the
        # axon backend means one remote compile per op (~190s measured for
        # InceptionV3); as one compiled program it is a single compile.
        @jax.jit
        def init(key):
            return model.init(key, jnp.zeros((1, h, w, 3), jnp.float32),
                              train=False)

        return init(jax.random.PRNGKey(seed))

    def apply_fn(self, dtype=jnp.float32, features_only: bool = False,
                 with_preprocess: bool = True,
                 num_classes: int | None = None, **build_kwargs) -> Callable:
        """Returns jittable ``fn(variables, batch)``; batch is NHWC float32
        in [0,255] when ``with_preprocess`` (the image-struct convention)."""
        model = self.build(dtype, num_classes, **build_kwargs)

        def fn(variables, batch):
            x = self.preprocess(batch) if with_preprocess else batch
            return model.apply(variables, x, train=False,
                               features_only=features_only)

        return fn


SUPPORTED_MODELS: dict[str, NamedImageModel] = {}


def _register(m: NamedImageModel):
    SUPPORTED_MODELS[m.name] = m
    return m


_register(NamedImageModel("InceptionV3", inception.InceptionV3, (299, 299),
                          preprocess_tf, 2048))
_register(NamedImageModel("Xception", xception.Xception, (299, 299),
                          preprocess_tf, 2048))
_register(NamedImageModel("ResNet50", resnet.ResNet50, (224, 224),
                          preprocess_caffe, 2048))
_register(NamedImageModel("ResNet18", resnet.ResNet18, (224, 224),
                          preprocess_caffe, 512))
_register(NamedImageModel("ResNet34", resnet.ResNet34, (224, 224),
                          preprocess_caffe, 512))
_register(NamedImageModel("ResNet101", resnet.ResNet101, (224, 224),
                          preprocess_caffe, 2048))
_register(NamedImageModel("ResNet152", resnet.ResNet152, (224, 224),
                          preprocess_caffe, 2048))
_register(NamedImageModel("VGG16", vgg.VGG16, (224, 224),
                          preprocess_caffe, 4096))
_register(NamedImageModel("VGG19", vgg.VGG19, (224, 224),
                          preprocess_caffe, 4096))


def get_model(name: str) -> NamedImageModel:
    try:
        return SUPPORTED_MODELS[name]
    except KeyError:
        raise ValueError(
            f"Unknown model {name!r}; supported: {sorted(SUPPORTED_MODELS)}"
        ) from None


def decodePredictions(logits: np.ndarray, top: int = 5) -> list[list[dict]]:
    """Top-k decode of classifier logits (DeepImagePredictor's
    ``decodePredictions``). Offline environment → numeric class ids, not the
    ImageNet label text the reference downloaded."""
    logits = np.asarray(logits)
    probs = np.exp(logits - logits.max(axis=-1, keepdims=True))
    probs /= probs.sum(axis=-1, keepdims=True)
    out = []
    for row in probs:
        idx = np.argsort(row)[::-1][:top]
        out.append([{"class": int(i), "label": f"class_{int(i)}",
                     "score": float(row[i])} for i in idx])
    return out


# ---------------------------------------------------------------------------
# LLM family metadata — draft/target pairing for speculative serving
# ---------------------------------------------------------------------------
# The image registry above names vision models; the generation stack's
# families live in ``models.llama`` as config constructors. Speculative
# decoding (serving.draft.DraftModelProvider) needs a DRAFT model per
# target family — registry-driven so deployments swap pairings without
# touching engine code. Names: ``llama3_8b`` / ``llama_small``
# (TinyLlama-shaped ~1B) / ``llama_tiny`` (test scale).

# target family -> draft family (each one tier down: the draft must be
# cheap relative to its target or speculation cannot pay)
DRAFT_PAIRS: dict[str, str] = {
    "llama3_8b": "llama_small",
    "llama_small": "llama_tiny",
}


def register_draft_pair(target: str, draft: str) -> None:
    """Name ``draft`` as the speculative draft family for ``target``
    (overwrites an existing pairing — deployments tune this)."""
    if target == draft:
        raise ValueError(f"{target!r} cannot draft for itself — a draft "
                         f"model the size of its target saves nothing")
    DRAFT_PAIRS[str(target)] = str(draft)


def draft_for(model_name: str) -> str | None:
    """The registered draft family for ``model_name`` (None when the
    family has no pairing — the engine then uses n-gram
    self-drafting)."""
    return DRAFT_PAIRS.get(model_name)


def llm_config(name: str):
    """Named LLM config constructor (``models.llama.LlamaConfig``
    classmethods). Lazy import: the image-model paths never pay it."""
    from .llama import LlamaConfig
    factories = {"llama3_8b": LlamaConfig.llama3_8b,
                 "llama_small": LlamaConfig.small,
                 "llama_tiny": LlamaConfig.tiny}
    try:
        factory = factories[name]
    except KeyError:
        raise ValueError(f"Unknown LLM config {name!r}; supported: "
                         f"{sorted(factories)}") from None
    return factory()


# ---------------------------------------------------------------------------
# Weight persistence (flax msgpack + safetensors import)
# ---------------------------------------------------------------------------

def save_weights(variables, path: str):
    from flax import serialization
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(variables))


def load_weights(variables_template, path: str):
    from flax import serialization
    with open(path, "rb") as f:
        return serialization.from_bytes(variables_template, f.read())


def load_safetensors(variables_template, path: str):
    """Import a safetensors file whose keys are '/'-joined flax param paths."""
    from flax.traverse_util import flatten_dict, unflatten_dict
    from safetensors.numpy import load_file
    loaded = load_file(path)
    flat = flatten_dict(variables_template, sep="/")
    missing = [k for k in flat if k not in loaded]
    if missing:
        raise ValueError(f"safetensors file missing {len(missing)} keys, "
                         f"e.g. {missing[:3]}")
    out = {}
    for k, tmpl in flat.items():
        arr = jnp.asarray(loaded[k])
        if arr.shape != tmpl.shape:
            # No silent reshape: a same-size transposed tensor (e.g. a torch
            # OI export vs flax IO) would load as garbage.
            raise ValueError(f"Shape mismatch for {k}: file has {arr.shape}, "
                             f"model expects {tmpl.shape}")
        out[k] = arr
    return unflatten_dict({tuple(k.split("/")): v for k, v in out.items()})


def save_safetensors(variables, path: str):
    from flax.traverse_util import flatten_dict
    from safetensors.numpy import save_file
    flat = flatten_dict(variables, sep="/")
    save_file({k: np.asarray(v) for k, v in flat.items()}, path)
