"""Llama-style decoder-only transformer (flax) with LoRA — the stretch
family (BASELINE config 5: "Llama-3-8B LoRA fine-tune via XlaRunner +
registerUDF batch inference").

TPU-first design:

- module names (``q_proj``/``k_proj``/``v_proj``/``o_proj``,
  ``gate_proj``/``up_proj``/``down_proj``, ``embed_tokens``, ``lm_head``)
  match ``parallel.transformer_tp_rules`` — the 2-D mesh TP layout applies
  by pattern, no per-model sharding code;
- LoRA adapters are ``lora_a``/``lora_b`` Dense submodules inside each
  projection, so ``parallel.lora_rules`` inherits the base kernel's
  partitioning and ``lora_mask`` freezes everything else for optax;
- attention is pluggable: dense (default) or sequence-parallel ring/Ulysses
  from ``parallel.ring_attention`` via ``attn_fn`` — long context rides the
  ICI ring instead of blowing HBM;
- GQA via ``jnp.repeat`` of KV heads (static), RoPE precomputed per call
  (fuses), RMSNorm in f32, everything else dtype-parameterized for bf16.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    intermediate_size: int = 14336
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    # LoRA: rank 0 disables adapters entirely (no extra params).
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple = ("q_proj", "v_proj")

    @classmethod
    def llama3_8b(cls, lora_rank: int = 0) -> "LlamaConfig":
        return cls(lora_rank=lora_rank)

    @classmethod
    def tiny(cls, lora_rank: int = 0) -> "LlamaConfig":
        """For tests/dryruns: 2 layers, 128-wide, GQA 4:2."""
        return cls(vocab_size=512, hidden_size=128, num_layers=2,
                   num_heads=4, num_kv_heads=2, intermediate_size=256,
                   rope_theta=10000.0, lora_rank=lora_rank)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        xf = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + self.eps)
        return (y * scale).astype(x.dtype)


class LoRADense(nn.Module):
    """Dense with optional LoRA: y = xW + (alpha/r)·(xA)B.

    A is gaussian-init, B zero-init (adapter starts as identity). The base
    ``kernel`` and the adapters are separate leaves so the base can be frozen
    (``lora_mask``) while adapters train.
    """
    features: int
    rank: int = 0
    alpha: float = 16.0
    use_bias: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = nn.Dense(self.features, use_bias=self.use_bias, dtype=self.dtype,
                     name="base")(x)
        if self.rank > 0:
            a = nn.Dense(self.rank, use_bias=False, dtype=self.dtype,
                         kernel_init=nn.initializers.normal(0.02),
                         name="lora_a")(x)
            b = nn.Dense(self.features, use_bias=False, dtype=self.dtype,
                         kernel_init=nn.initializers.zeros,
                         name="lora_b")(a)
            y = y + (self.alpha / self.rank) * b
        return y


def rope(x, positions, theta: float):
    """Rotary position embedding. x: [B, H, S, D], positions: [S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # [S,D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    r1 = xf1 * cos - xf2 * sin
    r2 = xf2 * cos + xf1 * sin
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


class LlamaAttention(nn.Module):
    cfg: LlamaConfig
    dtype: Any = jnp.float32
    attn_fn: Optional[Callable] = None  # (q,k,v,causal=...) → o

    @nn.compact
    def __call__(self, x, positions):
        c, d = self.cfg, self.dtype
        B, S, _ = x.shape
        hd = c.head_dim

        def proj(name, heads, lora):
            dense = LoRADense(heads * hd, rank=c.lora_rank if lora else 0,
                              alpha=c.lora_alpha, dtype=d, name=name)
            out = dense(x)
            return out.reshape(B, S, heads, hd).transpose(0, 2, 1, 3)

        q = proj("q_proj", c.num_heads, "q_proj" in c.lora_targets)
        k = proj("k_proj", c.num_kv_heads, "k_proj" in c.lora_targets)
        v = proj("v_proj", c.num_kv_heads, "v_proj" in c.lora_targets)

        q = rope(q, positions, c.rope_theta)
        k = rope(k, positions, c.rope_theta)
        if c.num_kv_heads != c.num_heads:  # GQA: tile KV heads (static)
            rep = c.num_heads // c.num_kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)

        if self.attn_fn is not None:
            o = self.attn_fn(q, k, v, causal=True)
        else:
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd)
            mask = jnp.tril(jnp.ones((S, S), bool))
            s = jnp.where(mask, s.astype(jnp.float32), -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(d)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, v)

        o = o.transpose(0, 2, 1, 3).reshape(B, S, c.num_heads * hd)
        return LoRADense(c.hidden_size, rank=c.lora_rank if "o_proj" in
                         c.lora_targets else 0, alpha=c.lora_alpha,
                         dtype=d, name="o_proj")(o)


class LlamaMLP(nn.Module):
    cfg: LlamaConfig
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        c, d = self.cfg, self.dtype
        lr = c.lora_rank
        gate = LoRADense(c.intermediate_size, rank=lr if "gate_proj" in
                         c.lora_targets else 0, dtype=d, name="gate_proj")(x)
        up = LoRADense(c.intermediate_size, rank=lr if "up_proj" in
                       c.lora_targets else 0, dtype=d, name="up_proj")(x)
        h = nn.silu(gate) * up
        return LoRADense(c.hidden_size, rank=lr if "down_proj" in
                         c.lora_targets else 0, dtype=d, name="down_proj")(h)


class LlamaLayer(nn.Module):
    cfg: LlamaConfig
    dtype: Any = jnp.float32
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, positions):
        c = self.cfg
        x = x + LlamaAttention(c, self.dtype, self.attn_fn, name="attn")(
            RMSNorm(c.rms_norm_eps, name="attn_norm")(x), positions)
        x = x + LlamaMLP(c, self.dtype, name="mlp")(
            RMSNorm(c.rms_norm_eps, name="mlp_norm")(x))
        return x


class LlamaModel(nn.Module):
    """Token ids [B, S] → logits [B, S, vocab]."""
    cfg: LlamaConfig
    dtype: Any = jnp.float32
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, input_ids):
        c = self.cfg
        S = input_ids.shape[1]
        positions = jnp.arange(S)
        x = nn.Embed(c.vocab_size, c.hidden_size, dtype=self.dtype,
                     name="embed_tokens")(input_ids)
        for i in range(c.num_layers):
            x = LlamaLayer(c, self.dtype, self.attn_fn,
                           name=f"layer_{i}")(x, positions)
        x = RMSNorm(c.rms_norm_eps, name="final_norm")(x)
        return nn.Dense(c.vocab_size, use_bias=False, dtype=jnp.float32,
                        name="lm_head")(x)


# ---------------------------------------------------------------------------
# LoRA training utilities
# ---------------------------------------------------------------------------

def lora_mask(params) -> Any:
    """Boolean pytree: True for LoRA adapter leaves (trainable), False for
    base weights (frozen). Feed to ``optax.masked`` — the LoRA fine-tune
    trains ~0.1% of params, the rest stay untouched in HBM."""
    from ..parallel.sharding import path_str

    return jax.tree_util.tree_map_with_path(
        lambda path, _: ("lora_a" in path_str(path)
                         or "lora_b" in path_str(path)), params)


def lora_optimizer(learning_rate: float = 1e-4):
    """Adam on LoRA adapters only; base params get zero updates (frozen).

    Uses multi_transform, not optax.masked — masked passes non-masked
    updates through *unchanged* (i.e. raw gradients), it does not freeze.
    """
    import optax

    def labels(params):
        return jax.tree_util.tree_map(
            lambda m: "lora" if m else "frozen", lora_mask(params))

    return optax.multi_transform(
        {"lora": optax.adam(learning_rate), "frozen": optax.set_to_zero()},
        labels)


def causal_lm_loss_fn():
    """Next-token loss for RunnerContext.fit: batch = {input_ids} (labels =
    input_ids shifted left; last position dropped)."""
    import optax

    def loss_fn(params, apply_fn, batch):
        ids = batch["input_ids"]
        logits = apply_fn(params, ids)[:, :-1].astype(jnp.float32)
        targets = ids[:, 1:]
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()
        return loss, {"perplexity": jnp.exp(loss)}

    return loss_fn
