"""Llama-style decoder-only transformer (flax) with LoRA — the stretch
family (BASELINE config 5: "Llama-3-8B LoRA fine-tune via XlaRunner +
registerUDF batch inference").

TPU-first design:

- module names (``q_proj``/``k_proj``/``v_proj``/``o_proj``,
  ``gate_proj``/``up_proj``/``down_proj``, ``embed_tokens``, ``lm_head``)
  match ``parallel.transformer_tp_rules`` — the 2-D mesh TP layout applies
  by pattern, no per-model sharding code;
- LoRA adapters are ``lora_a``/``lora_b`` Dense submodules inside each
  projection, so ``parallel.lora_rules`` inherits the base kernel's
  partitioning and ``lora_mask`` freezes everything else for optax;
- attention is pluggable: dense (default) or sequence-parallel ring/Ulysses
  from ``parallel.ring_attention`` via ``attn_fn`` — long context rides the
  ICI ring instead of blowing HBM;
- GQA via ``jnp.repeat`` of KV heads (static), RoPE precomputed per call
  (fuses), RMSNorm in f32, everything else dtype-parameterized for bf16.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from collections.abc import Mapping
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    intermediate_size: int = 14336
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    # LoRA: rank 0 disables adapters entirely (no extra params).
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple = ("q_proj", "v_proj")

    @classmethod
    def llama3_8b(cls, lora_rank: int = 0) -> "LlamaConfig":
        return cls(lora_rank=lora_rank)

    @classmethod
    def tiny(cls, lora_rank: int = 0) -> "LlamaConfig":
        """For tests/dryruns: 2 layers, 128-wide, GQA 4:2."""
        return cls(vocab_size=512, hidden_size=128, num_layers=2,
                   num_heads=4, num_kv_heads=2, intermediate_size=256,
                   rope_theta=10000.0, lora_rank=lora_rank)

    @classmethod
    def small(cls, lora_rank: int = 0) -> "LlamaConfig":
        """~1B-class config (TinyLlama-shaped) — fits one v5e chip with KV
        cache; the single-chip serving-bench model."""
        return cls(vocab_size=32000, hidden_size=2048, num_layers=16,
                   num_heads=16, num_kv_heads=8, intermediate_size=5632,
                   rope_theta=10000.0, lora_rank=lora_rank)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        xf = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + self.eps)
        return (y * scale).astype(x.dtype)


class QuantDense(nn.Module):
    """Weight-quantized Dense (ISSUE 18): when the stored ``kernel`` is
    int8 the matmul runs against the codes and folds the absmax
    per-output-channel ``kernel_scale`` AFTER the contraction
    (``(x @ q)·s`` — the scale is constant down each output column), so
    no dequantized copy of the weight ever materializes. Param paths
    mirror ``nn.Dense`` (same ``kernel``/``bias`` names under the same
    module name), so :func:`quantize_params` converts a float
    checkpoint in place and the ``parallel.transformer_tp_rules``
    patterns keyed on ``.../kernel`` still apply; ``kernel_scale``
    rides alongside and shards with the kernel's output dim where that
    dim is column-parallel. A float kernel (an unconverted checkpoint)
    runs the plain dense path unchanged."""
    features: int
    use_bias: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features), jnp.float32)
        x = x.astype(self.dtype)
        if kernel.dtype == jnp.int8:
            scale = self.param("kernel_scale", nn.initializers.ones,
                               (self.features,))
            y = jnp.dot(x, kernel.astype(self.dtype))
            # f32 accumulate for the dequant multiply, back to dtype —
            # a bf16 scale would throw away most of the absmax's
            # precision for free.
            y = (y * scale.astype(jnp.float32)).astype(self.dtype)
        else:
            y = jnp.dot(x, kernel.astype(self.dtype))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,))
            y = y + bias.astype(self.dtype)
        return y


class LoRADense(nn.Module):
    """Dense with optional LoRA: y = xW + (alpha/r)·(xA)B.

    A is gaussian-init, B zero-init (adapter starts as identity). The base
    ``kernel`` and the adapters are separate leaves so the base can be frozen
    (``lora_mask``) while adapters train. ``quant`` ('int8') swaps the
    base for :class:`QuantDense` — same param paths, dequant folded
    into the matmul; adapters stay float (they are ~0.1% of params).
    """
    features: int
    rank: int = 0
    alpha: float = 16.0
    use_bias: bool = False
    dtype: Any = jnp.float32
    quant: Any = None

    @nn.compact
    def __call__(self, x):
        if self.quant is not None:
            y = QuantDense(self.features, use_bias=self.use_bias,
                           dtype=self.dtype, name="base")(x)
        else:
            y = nn.Dense(self.features, use_bias=self.use_bias,
                         dtype=self.dtype, name="base")(x)
        if self.rank > 0:
            a = nn.Dense(self.rank, use_bias=False, dtype=self.dtype,
                         kernel_init=nn.initializers.normal(0.02),
                         name="lora_a")(x)
            b = nn.Dense(self.features, use_bias=False, dtype=self.dtype,
                         kernel_init=nn.initializers.zeros,
                         name="lora_b")(a)
            y = y + (self.alpha / self.rank) * b
        return y


def rope(x, positions, theta: float):
    """Rotary position embedding. x: [B, H, S, D]; positions: [S] (shared)
    or [B, S] (per-row — left-padded serving, where row r's first real
    token sits at a different slot)."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [...,S,D/2]
    if angles.ndim == 3:
        angles = angles[:, None]  # [B, 1, S, D/2] broadcasts over heads
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    r1 = xf1 * cos - xf2 * sin
    r2 = xf2 * cos + xf1 * sin
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def _gather_leaf(leaf, tables):
    """One pool leaf ``[P, Hkv, bs, hd]`` through ``[B, MB]`` tables →
    the dense per-slot view ``[B, Hkv, MB·bs, hd]`` (the per-leaf body
    of :func:`_gather_view`, shared with the in-layer kernel
    fallback)."""
    v = leaf[tables]                       # [B, MB, Hkv, bs, hd]
    v = jnp.transpose(v, (0, 2, 1, 3, 4))  # [B, Hkv, MB, bs, hd]
    return v.reshape(v.shape[0], v.shape[1], -1, v.shape[4])


def _table_blocks(tables, bi, real):
    """Physical pool block for each logical block index ``bi``, with
    every position whose ``real`` flag is False routed to the trash
    block 0 — the trash-route-NEVER-clamp rule shared by the paged
    chunk prefill and the in-layer decode/verify writes (an
    out-of-table or pad position must land where nobody reads, never
    slide back over a committed block). ``tables`` is indexed along
    its last axis: a ``[MB]`` row (the chunk primitive) or
    ``[B, MB]`` slot tables (the slot-step paths); the ``min`` clamp
    only keeps the gather in-bounds — clamped positions are ~real and
    route to trash."""
    mb = tables.shape[-1]
    safe = jnp.minimum(bi, mb - 1)
    blk = tables[safe] if tables.ndim == 1 else \
        jnp.take_along_axis(tables, safe, axis=1)
    return jnp.where(real, blk, 0)


def _dense_slot_attention(q, k_all, v_all, qpos, pads, cfg, dtype):
    """Masked dense causal-vs-cache attention for the per-slot
    (``slot_cur``) serving paths — ONE definition shared by the paged
    and unpaged kernel fallbacks: query i of row r attends cache
    columns ``[pads[r], qpos[r, i]]``. This masking math is the
    token-identity contract the kernel-equivalence tests pin — keep it
    single-sourced. GQA runs against the untiled cache (group axis in
    the einsum, no ``jnp.repeat`` of K/V); masked columns get exactly
    zero probability (exp underflow of -1e30), so table-aliased
    garbage never perturbs live rows bitwise."""
    B, S = qpos.shape
    hd = cfg.head_dim
    rep = cfg.num_heads // cfg.num_kv_heads
    qg = q.reshape(B, cfg.num_kv_heads, rep, S, hd)
    s = jnp.einsum("bgrqd,bgkd->bgrqk", qg, k_all) / math.sqrt(hd)
    col = jnp.arange(k_all.shape[2])[None, None, :]
    valid = (col <= qpos[..., None]) & (col >= pads[:, None, None])
    s = jnp.where(valid[:, None, None], s.astype(jnp.float32), -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dtype)
    return jnp.einsum("bgrqk,bgkd->bgrqd", p, v_all).reshape(
        B, cfg.num_heads, S, hd)


# ---------------------------------------------------------------------------
# Block-quantized KV (ISSUE 18): the paged pool's K/V leaves store
# int8 (or fp8) CODES and a parallel ``kv_scale`` [pool_blocks, Hkv, 2]
# f32 plane holds one absmax scale per (physical block, kv head,
# K-or-V): dequant is codes·scale. The scale is a property of the
# PHYSICAL block, so radix grafts (table pointer copies) and
# copy-on-write (block row copies) move scales with their codes for
# free, and the flash-decode kernel dequantizes in-VMEM — no float
# copy of the cache ever exists in HBM.
# ---------------------------------------------------------------------------

KV_QUANT_DTYPES: dict = {"int8": (jnp.int8, 127.0)}
if hasattr(jnp, "float8_e4m3fn"):
    KV_QUANT_DTYPES["fp8"] = (jnp.float8_e4m3fn, 448.0)


def kv_quant_spec(name: str):
    """(storage dtype, qmax) for a KV quant mode name — raises with the
    available modes on a miss (e.g. ``fp8`` on a jax without
    ``float8_e4m3fn``), never silently falls back."""
    try:
        return KV_QUANT_DTYPES[name]
    except KeyError:
        raise ValueError(
            f"unknown KV quant dtype {name!r}; available: "
            f"{sorted(KV_QUANT_DTYPES)}") from None


def kv_quant_name(dtype) -> Optional[str]:
    """Quant mode name for a stored K/V dtype, or None for a float
    cache — quantization is detected from the POOL, not a model flag,
    so one compiled model serves both."""
    for name, (dt, _) in KV_QUANT_DTYPES.items():
        if jnp.dtype(dtype) == jnp.dtype(dt):
            return name
    return None


def _kv_qmax(dtype) -> float:
    for dt, qmax in KV_QUANT_DTYPES.values():
        if jnp.dtype(dtype) == jnp.dtype(dt):
            return qmax
    raise ValueError(f"not a KV quant storage dtype: {dtype}")


def _requant(x, qdt, qmax):
    """f32 values (already divided by scale) → codes: round+clip for
    int storage, clip+cast for fp8 (the cast itself rounds)."""
    if jnp.issubdtype(jnp.dtype(qdt), jnp.integer):
        x = jnp.round(x)
    return jnp.clip(x, -qmax, qmax).astype(qdt)


def _quant_insert_rows(codes, plane, ch, blk, off, rows):
    """Insert float ``rows`` [N, Hkv, hd] at pool positions
    ``(blk[n], :, off[n], :)`` of a quantized ``codes`` leaf,
    maintaining the shared per-(block, head) scale ``plane[..., ch]``
    (ch 0 = K, 1 = V). ONE routine serves the in-layer decode/verify
    writes, the chunk-prefill scatter and the blocking-prefill scatter.

    Scale discipline, in scatter order:
    1. an ``off == 0`` row is a block's FIRST write (positions fill
       sequentially under the write-frontier invariant), so its scale
       resets to 0 — a freed-then-reallocated block must not inherit
       the previous tenant's (possibly larger) scale forever;
    2. scatter-max of the incoming rows' absmax/qmax grows the scale
       (duplicate blocks in ``blk`` accumulate — a multi-row write into
       one block yields the block's true absmax);
    3. surviving rows of every touched block requantize by
       old_s/new_s — exact (round of an integer) when the scale did
       not grow, one ≤½-LSB rounding when it did; ratio 0 (fresh or
       virgin block) wipes stale codes;
    4. the new rows quantize at the final scale.
    Trash-routed rows (blk == 0) follow the same path — block 0 is
    never read live, and duplicate trash writes stay deterministic
    (identical content per duplicate). Returns ``(codes, plane)``."""
    qdt = codes.dtype
    qmax = _kv_qmax(qdt)
    rows = rows.astype(jnp.float32)
    first = off == 0
    plane = plane.at[jnp.where(first, blk, 0), :, ch].set(0.0)
    amax = jnp.max(jnp.abs(rows), axis=-1)          # [N, Hkv]
    old_s = plane[blk, :, ch]
    plane = plane.at[blk, :, ch].max(amax / qmax)
    new_s = plane[blk, :, ch]
    safe = jnp.maximum(new_s, 1e-30)
    ratio = jnp.where(new_s > 0, old_s / safe, 0.0)
    cur = codes[blk].astype(jnp.float32) * ratio[:, :, None, None]
    codes = codes.at[blk].set(_requant(cur, qdt, qmax))
    q = _requant(rows / safe[:, :, None], qdt, qmax)
    return codes.at[blk, :, off, :].set(q), plane


def _gather_dequant(leaf, plane, ch, tables, dtype):
    """Dense dequantized per-slot view of one quantized pool leaf —
    the quant twin of :func:`_gather_leaf`: gather codes through the
    tables, multiply by each block's per-head scale, cast to the
    compute dtype. Kernel-fallback and reference-view path only (the
    kernel itself dequantizes in-VMEM)."""
    v = _gather_leaf(leaf, tables).astype(jnp.float32)
    s = plane[tables][..., ch]                       # [B, MB, Hkv]
    s = jnp.repeat(jnp.transpose(s, (0, 2, 1)), leaf.shape[2], axis=2)
    return (v * s[..., None]).astype(dtype)


def _map_attn_dicts(fn, tree, *rest):
    """tree_map at the attention-DICT level: apply ``fn`` to every
    mapping holding both "k" and "v" (the per-layer cache dicts),
    recursing elsewhere; ``rest`` trees zip-walk by key. The quantized
    pool needs cross-leaf work (codes and ``kv_scale`` move together,
    and the scatter's dense twin LACKS the scale leaf), which
    leaf-level ``tree_map`` cannot express."""
    if isinstance(tree, Mapping):
        if "k" in tree and "v" in tree:
            return fn(dict(tree), *[dict(r) for r in rest])
        return {k: _map_attn_dicts(fn, v, *[r[k] for r in rest])
                for k, v in tree.items()}
    return tree


def _pool_quant(pool) -> Optional[str]:
    """KV quant mode of a pool ('int8'/'fp8'/None) from its stored K/V
    dtype."""
    for leaf in jax.tree_util.tree_leaves(pool):
        if getattr(leaf, "ndim", 0) == 4:
            return kv_quant_name(leaf.dtype)
    raise ValueError("pool holds no 4-D K/V leaves")


class LlamaAttention(nn.Module):
    cfg: LlamaConfig
    dtype: Any = jnp.float32
    # (q,k,v,causal=...) → o; "auto" (default) resolves to the Pallas flash
    # kernel on TPU and in-model dense attention elsewhere (ops.resolve_attn_fn)
    attn_fn: Any = "auto"
    # Mesh(('tp',)) of the tensor-parallel serving backends (ISSUE 15):
    # a pallas_call does not partition under GSPMD, so the decode
    # kernels dispatch under shard_map over this mesh's head axis
    # instead (parallel.sharding.head_sharded_kernel). None everywhere
    # else — the single-device paths are untouched.
    kernel_mesh: Any = None
    # 'int8' → projection base kernels run QuantDense (ISSUE 18); pair
    # with params converted by quantize_params.
    weight_quant: Any = None

    @nn.compact
    def __call__(self, x, positions, decode: bool = False, pad_lens=None,
                 first_chunk: bool = False, slot_cur=None,
                 block_tables=None):
        c, d = self.cfg, self.dtype
        B, S, _ = x.shape
        hd = c.head_dim

        def proj(name, heads, lora):
            dense = LoRADense(heads * hd, rank=c.lora_rank if lora else 0,
                              alpha=c.lora_alpha, dtype=d,
                              quant=self.weight_quant, name=name)
            out = dense(x)
            return out.reshape(B, S, heads, hd).transpose(0, 2, 1, 3)

        q = proj("q_proj", c.num_heads, "q_proj" in c.lora_targets)
        k = proj("k_proj", c.num_kv_heads, "k_proj" in c.lora_targets)
        v = proj("v_proj", c.num_kv_heads, "v_proj" in c.lora_targets)

        rep = c.num_heads // c.num_kv_heads  # GQA tiling factor (static)

        from ..ops.flash_attention import resolve_attn_fn
        resolved_attn = resolve_attn_fn(self.attn_fn)

        def prefill_attn_fn(need_mask: bool):
            """The attention to run at prefill: the resolved attn_fn when
            it can express the left-pad mask contract (flash can; ring/
            Ulysses cannot — they fall back to the dense cache path)."""
            fn = resolved_attn
            if fn is None or not need_mask:
                return fn
            import inspect
            try:
                params = inspect.signature(fn).parameters
            except (TypeError, ValueError):
                return None
            # Only an explicit kv_mask parameter proves support — a
            # **kwargs wrapper would swallow the mask and silently attend
            # to pad tokens.
            return fn if "kv_mask" in params else None

        if decode:
            # KV-cache serving path. The cache is sized by the *init* call's
            # sequence length (= max_len); apply() calls then write chunks —
            # the whole prompt at prefill, one token per decode step — at the
            # running index. See ``init_cache``/``generate``.
            # ``pad_lens`` [B] (left-padded serving): row r's first pad_lens[r]
            # cache slots are dead — masked out of attention, and rope
            # positions count from the first REAL token, so ONE compiled
            # prefill serves every prompt length (udf.registerGenerationUDF).
            # PREFILL (S > 1, cache index 0) runs through ``attn_fn`` when it
            # supports the mask contract: causal over the square S-slice +
            # kv_mask for pad slots — long prompts never materialize the
            # O(S·max_len) score matrix (flash is the TPU default), and a
            # ring/Ulysses attn_fn shards the prefill's S^2 compute over the
            # sp mesh axis (sequence-parallel serving; unpadded prompts).
            # Per-token DECODE steps (S == 1) pair with the flash prefill:
            # when the resolved attn_fn is the flash kernel, the step runs
            # through ops.flash_decode — HBM traffic O(cur), not
            # O(max_len), dead cache blocks are never fetched. Any other
            # attn_fn (dense, ring/Ulysses — sequence-sharding doesn't
            # apply to a replicated cache) keeps the dense cache path.
            k_cache = self.variable("cache", "k", jnp.zeros,
                                    (B, c.num_kv_heads, S, hd), d)
            v_cache = self.variable("cache", "v", jnp.zeros,
                                    (B, c.num_kv_heads, S, hd), d)
            idx = self.variable("cache", "idx",
                                lambda: jnp.zeros((), jnp.int32))
            if slot_cur is not None and not self.is_initializing():
                # Continuous-batching decode step / speculative verify
                # window (serving.engine): every cache row is an
                # INDEPENDENT in-flight request at its own fill index
                # ``slot_cur[r]``. S == 1 is the decode step — the token
                # writes at the frontier and attention masks per row to
                # [pad_lens[r], slot_cur[r]]. S == k+1 is the VERIFY
                # window (ISSUE 12): row r's S tokens (current token +
                # k drafts) write at [slot_cur[r], slot_cur[r]+S) and
                # query i attends [pad_lens[r], slot_cur[r]+i] — dense
                # causal-vs-cache attention under the chunked-prefill
                # write-frontier invariant: every row at/past the
                # frontier is (re)written before any attention can read
                # it, so rejected drafts leave inert garbage the next
                # real write overwrites. Writes past the row's end are
                # DROPPED (scatter mode="drop"), never clamped back
                # over committed rows. The shared ``idx`` variable is
                # NOT consulted or advanced (the engine owns per-slot
                # fill state host-side), so slot refills never disturb
                # the other rows' decode.
                pads = (jnp.zeros((B,), jnp.int32) if pad_lens is None
                        else pad_lens)
                qpos = slot_cur[:, None] + jnp.arange(S)[None, :]  # [B,S]
                pos = jnp.maximum(qpos - pads[:, None], 0)
                q = rope(q, pos, c.rope_theta)
                k = rope(k, pos, c.rope_theta)
                if block_tables is not None:
                    # PAGED slot step (ISSUE 15): the cache leaves are
                    # the SHARED pool [pool_blocks, Hkv, bs, hd] and
                    # ``block_tables`` [B, max_blocks] names each slot's
                    # blocks. Writes scatter through the table (the
                    # final-chunk trash-routing rule: a position past
                    # the table — an overhanging draft column — lands
                    # on trash block 0 where no live range reads);
                    # attention reads the pool THROUGH the table: via
                    # the paged flash-decode kernel when it engages (no
                    # gathered view exists in the program, per-step HBM
                    # traffic O(cur) per slot), else a per-layer dense
                    # gather view — the portable fallback, the exact
                    # PR 11 math.
                    bs_p = k_cache.value.shape[2]
                    mb = block_tables.shape[1]
                    bi = qpos // bs_p
                    blk = _table_blocks(block_tables, bi, bi < mb)
                    off = qpos % bs_p
                    quant = kv_quant_name(k_cache.value.dtype)
                    scl = None
                    if quant is None:
                        k_pool = k_cache.value.at[blk, :, off, :].set(
                            k.transpose(0, 2, 1, 3).astype(
                                k_cache.value.dtype))
                        v_pool = v_cache.value.at[blk, :, off, :].set(
                            v.transpose(0, 2, 1, 3).astype(
                                v_cache.value.dtype))
                    else:
                        # QUANTIZED pool (ISSUE 18): the leaves are
                        # codes and the ``kv_scale`` plane rides the
                        # same cache collection — declared here (only
                        # on the quantized paged path) so mut["cache"]
                        # carries it and float pools keep their exact
                        # pytree. Rows flatten to [B·S] for the shared
                        # insert primitive.
                        kv_scale = self.variable(
                            "cache", "kv_scale", jnp.zeros,
                            (k_cache.value.shape[0], c.num_kv_heads, 2),
                            jnp.float32)
                        fb, fo = blk.reshape(-1), off.reshape(-1)
                        kr = k.transpose(0, 2, 1, 3).reshape(
                            -1, c.num_kv_heads, hd)
                        vr = v.transpose(0, 2, 1, 3).reshape(
                            -1, c.num_kv_heads, hd)
                        scl = kv_scale.value
                        k_pool, scl = _quant_insert_rows(
                            k_cache.value, scl, 0, fb, fo, kr)
                        v_pool, scl = _quant_insert_rows(
                            v_cache.value, scl, 1, fb, fo, vr)
                        kv_scale.value = scl
                    k_cache.value, v_cache.value = k_pool, v_pool
                    from ..ops import paged_flash_decode as pfd
                    o = None
                    dec = pfd.paged_decode_fn_for(resolved_attn,
                                                  self.kernel_mesh)
                    if dec is not None:
                        reason = pfd.support_reason(bs_p, kv_dtype=quant)
                        if reason is None:
                            # the scale plane rides positionally so the
                            # head-sharded shard_map wrapper shards it
                            # with its heads (float pools pass nothing).
                            extra = () if scl is None else (scl,)
                            o = dec(q, k_pool, v_pool, block_tables,
                                    slot_cur, pads, *extra)
                        elif pfd.kernel_mode() == "force":
                            pfd.warn_fallback(reason)
                    if o is None:
                        if quant is None:
                            k_all = _gather_leaf(k_pool, block_tables)
                            v_all = _gather_leaf(v_pool, block_tables)
                        else:
                            k_all = _gather_dequant(k_pool, scl, 0,
                                                    block_tables, d)
                            v_all = _gather_dequant(v_pool, scl, 1,
                                                    block_tables, d)
                        o = _dense_slot_attention(q, k_all, v_all,
                                                  qpos, pads, c, d)
                else:
                    max_len = k_cache.value.shape[2]
                    rows_ix = jnp.arange(B)[:, None]
                    cols = jnp.where(qpos < max_len, qpos,
                                     max_len)  # OOB→drop
                    k_all = k_cache.value.at[rows_ix, :, cols, :].set(
                        k.transpose(0, 2, 1, 3), mode="drop")
                    v_all = v_cache.value.at[rows_ix, :, cols, :].set(
                        v.transpose(0, 2, 1, 3), mode="drop")
                    k_cache.value, v_cache.value = k_all, v_all
                    o = None
                    if S == 1:
                        from ..ops import flash_decode as fd
                        dec = fd.decode_fn_for(resolved_attn,
                                               self.kernel_mesh)
                        if dec is not None and fd.supports(max_len):
                            # per-row cur: each slot's HBM traffic
                            # scales with its own fill level (the
                            # kernel's dead-block clamp is per row).
                            o = dec(q, k_all, v_all, slot_cur + 1, pads)
                    if o is None:
                        o = _dense_slot_attention(q, k_all, v_all,
                                                  qpos, pads, c, d)
                # falls through to the shared o_proj tail below — the
                # serving path must ride the exact same output
                # projection as static generate() (token identity).
            elif not self.is_initializing():
                cur = idx.value
                if pad_lens is None:
                    pos = cur + jnp.arange(S)  # [S], shared across rows
                    valid_extra = None
                else:
                    # per-row positions relative to the first real token
                    pos = jnp.maximum(
                        cur + jnp.arange(S)[None, :]
                        - pad_lens[:, None], 0)  # [B, S]
                    valid_extra = pad_lens
                q = rope(q, pos, c.rope_theta)
                k = rope(k, pos, c.rope_theta)
                k_all = jax.lax.dynamic_update_slice(
                    k_cache.value, k, (0, 0, cur, 0))
                v_all = jax.lax.dynamic_update_slice(
                    v_cache.value, v, (0, 0, cur, 0))
                k_cache.value, v_cache.value = k_all, v_all
                idx.value = cur + S
                # Prefill through attn_fn over the square S-slice:
                # generate()'s contract writes the whole prompt at cache
                # index 0, where every slot past S is causally dead — so
                # attention over (q, k, v) with causal + a pad-slot
                # kv_mask equals the masked dense-vs-cache compute,
                # without materializing O(S·max_len) scores (flash), or
                # sharding the S^2 compute over the sp axis (ring).
                # Gated on the EXPLICIT first_chunk=True opt-in (only
                # _prefill passes it): a chunked multi-call prefill must
                # attend earlier cache too, so the default takes the
                # dense full-cache path.
                flash = (prefill_attn_fn(valid_extra is not None)
                         if S > 1 and first_chunk else None)
                o = None
                if flash is not None:
                    kf = jnp.repeat(k, rep, axis=1) if rep != 1 else k
                    vf = jnp.repeat(v, rep, axis=1) if rep != 1 else v
                    # Shape constraints (e.g. a ring attn_fn whose sp
                    # axis doesn't divide S) surface at TRACE time as
                    # ValueError/TypeError — fall back to the dense path
                    # instead of turning a previously working generate()
                    # into a crash. Other exception types (a genuinely
                    # broken attn_fn) propagate: silently densifying
                    # those would OOM the long-prompt case the fn was
                    # configured to avoid.
                    try:
                        if valid_extra is None:
                            o = flash(q, kf, vf, causal=True)
                        else:
                            kv_mask = (jnp.arange(S)[None, :]
                                       >= valid_extra[:, None]).astype(
                                           jnp.float32)
                            o = flash(q, kf, vf, causal=True,
                                      kv_mask=kv_mask)
                    except (TypeError, ValueError) as e:
                        _warn_prefill_fallback(flash, e)
                        o = None
                if o is None and S == 1:
                    from ..ops import flash_decode as fd
                    dec = fd.decode_fn_for(resolved_attn,
                                           self.kernel_mesh)
                    if dec is not None and fd.supports(k_all.shape[2]):
                        # slots < cur+1 are live (the step's own token
                        # attends to itself — the dense path's col <= row
                        # with row == cur); left-pad slots masked per row.
                        o = dec(q, k_all, v_all, cur + 1, pad_lens)
                if o is None:
                    # grouped-query attention against the UNtiled cache:
                    # fold the GQA tiling into the einsum group axis instead
                    # of jnp.repeat-copying the whole cache every step
                    max_len = k_all.shape[2]
                    qg = q.reshape(B, c.num_kv_heads, rep, S, hd)
                    s = jnp.einsum("bgrqd,bgkd->bgrqk", qg,
                                   k_all) / math.sqrt(hd)
                    col = jnp.arange(max_len)[None, :]
                    row = cur + jnp.arange(S)[:, None]
                    valid = (col <= row)  # [S, max_len] causal-vs-cache
                    if valid_extra is not None:
                        # [B, S, max_len]: also exclude each row's pad slots
                        valid = valid[None] & (
                            col[None] >= valid_extra[:, None, None])
                        valid = valid[:, None, None]  # [B,1,1,S,max_len]
                    s = jnp.where(valid, s.astype(jnp.float32), -1e30)
                    p = jax.nn.softmax(s, axis=-1).astype(d)
                    o = jnp.einsum("bgrqk,bgkd->bgrqd", p, v_all).reshape(
                        B, c.num_heads, S, hd)
            else:
                o = jnp.zeros((B, c.num_heads, S, hd), d)
        else:
            q = rope(q, positions, c.rope_theta)
            k = rope(k, positions, c.rope_theta)
            if rep != 1:
                k = jnp.repeat(k, rep, axis=1)
                v = jnp.repeat(v, rep, axis=1)
            if resolved_attn is not None:
                o = resolved_attn(q, k, v, causal=True)
            else:
                s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd)
                mask = jnp.tril(jnp.ones((S, S), bool))
                s = jnp.where(mask, s.astype(jnp.float32), -1e30)
                p = jax.nn.softmax(s, axis=-1).astype(d)
                o = jnp.einsum("bhqk,bhkd->bhqd", p, v)

        o = o.transpose(0, 2, 1, 3).reshape(B, S, c.num_heads * hd)
        return LoRADense(c.hidden_size, rank=c.lora_rank if "o_proj" in
                         c.lora_targets else 0, alpha=c.lora_alpha,
                         dtype=d, quant=self.weight_quant,
                         name="o_proj")(o)


class LlamaMLP(nn.Module):
    cfg: LlamaConfig
    dtype: Any = jnp.float32
    weight_quant: Any = None

    @nn.compact
    def __call__(self, x):
        c, d = self.cfg, self.dtype
        lr = c.lora_rank
        wq = self.weight_quant
        gate = LoRADense(c.intermediate_size, rank=lr if "gate_proj" in
                         c.lora_targets else 0, dtype=d, quant=wq,
                         name="gate_proj")(x)
        up = LoRADense(c.intermediate_size, rank=lr if "up_proj" in
                       c.lora_targets else 0, dtype=d, quant=wq,
                       name="up_proj")(x)
        h = nn.silu(gate) * up
        return LoRADense(c.hidden_size, rank=lr if "down_proj" in
                         c.lora_targets else 0, dtype=d, quant=wq,
                         name="down_proj")(h)


class LlamaLayer(nn.Module):
    cfg: LlamaConfig
    dtype: Any = jnp.float32
    attn_fn: Any = "auto"
    kernel_mesh: Any = None
    weight_quant: Any = None

    @nn.compact
    def __call__(self, x, positions, decode: bool = False, pad_lens=None,
                 first_chunk: bool = False, slot_cur=None,
                 block_tables=None):
        c = self.cfg
        x = x + LlamaAttention(c, self.dtype, self.attn_fn,
                               self.kernel_mesh,
                               weight_quant=self.weight_quant,
                               name="attn")(
            RMSNorm(c.rms_norm_eps, name="attn_norm")(x), positions, decode,
            pad_lens, first_chunk, slot_cur, block_tables)
        x = x + LlamaMLP(c, self.dtype, weight_quant=self.weight_quant,
                         name="mlp")(
            RMSNorm(c.rms_norm_eps, name="mlp_norm")(x))
        return x


class LlamaModel(nn.Module):
    """Token ids [B, S] → logits [B, S, vocab]."""
    cfg: LlamaConfig
    dtype: Any = jnp.float32
    attn_fn: Any = "auto"  # flash on TPU, dense elsewhere; or a callable
    kernel_mesh: Any = None  # Mesh(('tp',)) → shard_map decode kernels
    weight_quant: Any = None  # 'int8' + quantize_params → int8 matmuls

    @nn.compact
    def __call__(self, input_ids, decode: bool = False, pad_lens=None,
                 first_chunk: bool = False, slot_cur=None,
                 block_tables=None):
        """``first_chunk`` (decode mode, static): True ONLY when this
        apply() writes at cache index 0 — generate()'s single-call prefill
        passes it explicitly (``_prefill``). It enables the square flash
        fast path, which attends over the current chunk alone; at any
        other cache index that would silently ignore earlier cache, so
        the default is False and unaware multi-call chunked-prefill
        callers get the (correct) dense attention over the full cache.

        ``slot_cur`` (decode mode, ``[B]`` int32, traced): the
        continuous-batching step — row r writes its S tokens at its OWN
        cache fill index ``[slot_cur[r], slot_cur[r]+S)`` and query i
        attends ``[pad_lens[r], slot_cur[r]+i]`` of its row. S == 1 is
        the per-slot decode step; S == k+1 is the speculative VERIFY
        window (``slot_verify_step``). The shared ``idx`` cache
        variable is neither read nor advanced (the serving engine owns
        per-slot fill state).

        ``block_tables`` (decode mode with ``slot_cur``, ``[B,
        max_blocks]`` int32, traced): the PAGED slot step (ISSUE 15) —
        the provided cache leaves are the shared ``[pool_blocks, Hkv,
        block_size, hd]`` pool and row r's logical position p lives at
        pool position ``(block_tables[r, p // bs], p % bs)``. Writes
        scatter through the table (positions past it trash-route to
        block 0); attention reads the pool through the table — the
        paged flash-decode kernel when it engages
        (``ops.paged_flash_decode``), else a per-layer dense gather
        view."""
        c = self.cfg
        if pad_lens is not None and not decode:
            raise ValueError(
                "pad_lens is a KV-cache serving feature (decode=True); the "
                "training path has no left-pad masking — feed right-padded "
                "batches with a loss mask instead")
        S = input_ids.shape[1]
        if slot_cur is not None and not decode:
            raise ValueError(
                "slot_cur is the per-slot decode step / verify-window "
                f"feature (decode=True); got decode={decode} — prefill a "
                "slot via prefill_into_slot instead")
        if block_tables is not None and slot_cur is None:
            raise ValueError(
                "block_tables is the paged slot-step feature: the cache "
                "must be the shared block pool and every row needs its "
                "own fill index — pass slot_cur (see "
                "paged_slot_decode_step)")
        positions = jnp.arange(S)
        x = nn.Embed(c.vocab_size, c.hidden_size, dtype=self.dtype,
                     name="embed_tokens")(input_ids)
        for i in range(c.num_layers):
            x = LlamaLayer(c, self.dtype, self.attn_fn, self.kernel_mesh,
                           weight_quant=self.weight_quant,
                           name=f"layer_{i}")(x, positions, decode,
                                              pad_lens, first_chunk,
                                              slot_cur, block_tables)
        x = RMSNorm(c.rms_norm_eps, name="final_norm")(x)
        return nn.Dense(c.vocab_size, use_bias=False, dtype=jnp.float32,
                        name="lm_head")(x)


# ---------------------------------------------------------------------------
# Generation (KV-cache serving — the registerUDF inference path of
# BASELINE config 5)
# ---------------------------------------------------------------------------

def init_cache(model: LlamaModel, batch_size: int, max_len: int,
               kv_sharding=None, scalar_sharding=None):
    """Zeroed KV cache pytree sized (batch, kv_heads, max_len, head_dim) per
    layer. Built via ``jax.eval_shape`` over ``init`` — no parameter compute,
    just the variable-tree structure.

    ``kv_sharding`` (a ``jax.sharding.Sharding``) places the 4-D K/V
    leaves at creation — the tensor-parallel serving backend passes the
    head-sharded ``Mesh(('tp',))`` spec so a big cache is born
    distributed (each device allocates its ``1/tp`` shard) instead of
    materialized on one device and re-shuffled. ``scalar_sharding``
    places the scalar ``idx`` leaves (replicated under a mesh)."""
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((batch_size, max_len), jnp.int32),
                           decode=True))

    def make(s):
        sh = kv_sharding if len(s.shape) == 4 else scalar_sharding
        if sh is not None:
            return jax.make_array_from_callback(
                s.shape, sh, lambda idx: np.zeros(
                    tuple(len(range(*i.indices(d)))
                          for i, d in zip(idx, s.shape)), s.dtype))
        return jnp.zeros(s.shape, s.dtype)

    return jax.tree_util.tree_map(make, shapes["cache"])


def _sample(logits, key, temperature: float, top_k: int = 0,
            top_p: float = 1.0):
    """Greedy (temperature<=0) or temperature sampling with optional
    top-k / nucleus (top-p) truncation. All branches are static (compiled
    into the decode program); the filtering is rank-based so shapes stay
    fixed."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if (top_k and top_k > 0) or top_p < 1.0:
        # ONE sort serves both filters (this runs inside the per-token
        # decode scan — a second O(V log V) sort per step is pure waste).
        sl = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
        if top_k and top_k > 0:
            ranks = jnp.arange(sl.shape[-1])
            sl = jnp.where(ranks < top_k, sl, -jnp.inf)
        if top_p < 1.0:
            probs = jax.nn.softmax(sl, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # keep the smallest prefix with cumulative prob >= top_p
            # (rank 0 always kept: cum - probs is 0 there)
            sl = jnp.where(cum - probs < top_p, sl, -jnp.inf)
        # cutoff = smallest surviving logit; ties at the cutoff stay in
        cutoff = jnp.min(jnp.where(jnp.isfinite(sl), sl, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("model",))
def _prefill(model, params, prompt_ids, cache, pad_lens=None):
    """Whole prompt in one chunked cache write → (last-pos logits, cache).
    Compiled per (batch, prompt_len, max_len) signature. With left-padded
    prompts (``pad_lens``), ONE (batch, Lmax, max_len) program serves every
    prompt length — the newest real token is always the last position."""
    logits, mut = model.apply({"params": params, "cache": cache},
                              prompt_ids, decode=True, pad_lens=pad_lens,
                              first_chunk=True, mutable=["cache"])
    return logits[:, -1].astype(jnp.float32), mut["cache"]


@functools.partial(
    jax.jit, static_argnames=("model", "max_new_tokens", "temperature",
                              "top_k", "top_p", "eos_id"))
def _decode(model, params, cache, last_logits, rng, pad_lens=None, *,
            max_new_tokens: int, temperature: float, top_k: int = 0,
            top_p: float = 1.0, eos_id: int | None = None):
    """One token per step; compiled per (batch, max_len) signature —
    independent of the prompt length, so varying-length prompts with a
    shared cache size reuse ONE decode program.

    Without ``eos_id``: a ``lax.scan`` of exactly max_new_tokens steps.
    With ``eos_id``: a ``lax.while_loop`` that STOPS as soon as every row
    has emitted eos — an all-done batch pays only the steps it used, not
    max_new_tokens (round-3 verdict Next #6: compute-side early exit, not
    just host-side tail trimming). Unwritten output slots hold eos_id,
    which is exactly what the fixed-length scan emitted for done rows.

    Returns ``(tokens [B, max_new_tokens], n_steps)`` where n_steps is the
    number of decode-loop iterations actually executed (== max_new_tokens
    for the scan path)."""
    rng, key = jax.random.split(rng)
    tok = _sample(last_logits, key, temperature, top_k, top_p)

    def model_step(cache, tok, rng):
        logits, mut = model.apply({"params": params, "cache": cache},
                                  tok[:, None], decode=True,
                                  pad_lens=pad_lens, mutable=["cache"])
        rng, key = jax.random.split(rng)
        nxt = _sample(logits[:, -1].astype(jnp.float32), key, temperature,
                      top_k, top_p)
        return mut["cache"], nxt, rng

    if eos_id is None:
        # each step emits the already-sampled token and samples the next;
        # after n steps the emitted sequence is exactly the n new tokens
        def step(carry, _):
            cache, nxt, rng = model_step(*carry)
            return (cache, nxt, rng), carry[1]

        _, toks = jax.lax.scan(step, (cache, tok, rng), None,
                               length=max_new_tokens)
        return jnp.moveaxis(toks, 0, 1), jnp.asarray(max_new_tokens)

    out0 = jnp.full((tok.shape[0], max_new_tokens), eos_id, jnp.int32)

    def cond(carry):
        _, _, _, done, i, _ = carry
        return (i < max_new_tokens) & ~jnp.all(done)

    def body(carry):
        cache, tok, rng, done, i, out = carry
        out = out.at[:, i].set(tok)
        cache, nxt, rng = model_step(cache, tok, rng)
        nxt = jnp.where(done, eos_id, nxt)
        return (cache, nxt, rng, done | (nxt == eos_id), i + 1, out)

    carry = jax.lax.while_loop(
        cond, body,
        (cache, tok, rng, tok == eos_id, jnp.asarray(0), out0))
    return carry[5], carry[4]


def left_pad_prompts(prompts, pad_id: int = 0, pad_to: int | None = None):
    """Variable-length prompt lists → (ids [B, Lmax] left-padded, pad_lens
    [B]). Left padding keeps every row's newest token at the last position,
    so one prefill program + one decode program serve mixed lengths.

    ``pad_to`` pins Lmax externally — chunked callers (the streaming
    generation UDF) pass the column-wide max so every chunk shares one
    compiled (rows, Lmax) signature."""
    import numpy as np
    lens = [len(p) for p in prompts]
    if min(lens, default=0) < 1:
        raise ValueError("every prompt needs at least one token id")
    lmax = max(lens)
    if pad_to is not None:
        if pad_to < lmax:
            raise ValueError(f"pad_to={pad_to} < longest prompt {lmax}")
        lmax = pad_to
    ids = np.full((len(prompts), lmax), pad_id, dtype=np.int32)
    for r, p in enumerate(prompts):
        ids[r, lmax - len(p):] = np.asarray(p, dtype=np.int32)
    return ids, np.asarray([lmax - n for n in lens], dtype=np.int32)


_warned_attn_fn_ignored = False
_warned_prefill_fallback: set = set()


def _warn_prefill_fallback(fn, err) -> None:
    """Once per (fn, error) pair host-side — not once per layer per trace
    (a 32-layer model would otherwise emit 32 identical warnings)."""
    key = (repr(fn), f"{type(err).__name__}: {err}")
    if key not in _warned_prefill_fallback:
        import logging
        logging.getLogger(__name__).warning(
            "prefill attn_fn %s failed at trace time (%s); using dense "
            "cache attention", key[0], key[1])
        _warned_prefill_fallback.add(key)


def generate(model: LlamaModel, variables, prompt_ids, max_new_tokens: int,
             temperature: float = 0.0, rng=None, pad_to: int | None = None,
             pad_lens=None, top_k: int = 0, top_p: float = 1.0,
             eos_id: int | None = None, return_steps: bool = False):
    """Greedy / temperature sampling with a KV cache.

    Two jitted programs: a prefill pass writes the prompt's cache in a
    single chunked update, then a decode loop emits one token per step
    (compiled per (batch, cache-size) only). For mixed-length columns,
    left-pad with :func:`left_pad_prompts` and pass ``pad_lens`` — the
    prefill then also compiles ONCE for the whole column (positions count
    from each row's first real token; pad slots are masked out of
    attention). With ``eos_id`` the decode is a ``lax.while_loop`` that
    exits as soon as every row has finished — the compute-side early stop.

    ``prompt_ids``: [B, Lp] int32, Lp >= 1. Returns [B, Lp+max_new_tokens]
    (left-pad slots included when ``pad_lens`` is used — strip
    ``pad_lens[r]`` leading ids per row). With ``return_steps=True``
    returns ``(ids, n_decode_steps)`` — the observable for early-exit
    tests and serving telemetry.
    """
    global _warned_attn_fn_ignored
    # Warn only for an EXPLICITLY configured attn_fn — the "auto" default
    # resolving to flash for prefill is not a user setting being ignored.
    if callable(model.attn_fn) and not _warned_attn_fn_ignored:
        # Host-side, once — not inside the traced apply (fires per trace).
        import logging
        logging.getLogger(__name__).warning(
            "LlamaModel.attn_fn applies to the PREFILL pass during "
            "generation (flash/ring/Ulysses; left-padded prefill "
            "additionally needs kv_mask support, which only flash has); "
            "per-token decode runs the cache-aware flash decode kernel "
            "when attn_fn is the flash kernel (ops.flash_decode), and "
            "dense cache attention for every other attn_fn")
        _warned_attn_fn_ignored = True
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p} — 0 would "
                         f"mask every token and degenerate to id 0")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0 (0 disables), got {top_k}")
    if eos_id is not None and (isinstance(eos_id, bool)
                               or not isinstance(eos_id, (int, np.integer))):
        raise TypeError(f"eos_id must be an int token id or None, "
                        f"got {eos_id!r}")
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    b, lp = prompt_ids.shape
    if lp < 1:
        raise ValueError("prompt_ids must contain at least one token")
    max_len = pad_to or (lp + max_new_tokens)
    if max_len < lp + max_new_tokens:
        raise ValueError(f"pad_to={pad_to} < prompt+new ="
                         f" {lp + max_new_tokens}")
    from ..ops import flash_decode as _fd
    from ..ops.flash_attention import resolve_attn_fn as _resolve_attn
    if (pad_to is None
            and _fd.decode_fn_for(_resolve_attn(model.attn_fn)) is not None
            and not _fd.supports(max_len)):
        # Round the DEFAULT cache size up to the decode kernel's KV-block
        # multiple so the flash decode path engages without an explicit
        # pad_to; a few spare KV slots cost far less than every step
        # reading the cache dense. An EXPLICIT pad_to is honored verbatim
        # — callers sizing the cache to an HBM budget must get exactly
        # what they asked for (a non-multiple then takes the dense path,
        # by supports()).
        max_len = ((max_len + _fd.KV_BLOCK - 1)
                   // _fd.KV_BLOCK) * _fd.KV_BLOCK
    params = variables["params"] if "params" in variables else variables
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if pad_lens is not None:
        pad_lens = jnp.asarray(pad_lens, jnp.int32)
    cache = init_cache(model, b, int(max_len))
    last_logits, cache = _prefill(model, params, prompt_ids, cache, pad_lens)
    toks, n_steps = _decode(model, params, cache, last_logits, rng, pad_lens,
                            max_new_tokens=int(max_new_tokens),
                            temperature=float(temperature), top_k=int(top_k),
                            top_p=float(top_p),
                            eos_id=None if eos_id is None else int(eos_id))
    ids = jnp.concatenate([prompt_ids, toks], axis=1)
    return (ids, int(n_steps)) if return_steps else ids


# ---------------------------------------------------------------------------
# Slot-level serving primitives (continuous batching — serving.engine)
# ---------------------------------------------------------------------------
# The static generate() path above runs whole batches in lockstep: every
# row prefills together and the decode loop drains together. The two
# functions below are the per-SLOT halves the in-flight batching engine
# composes instead: ``prefill_into_slot`` writes one new request's cache
# into one row of a shared slot cache (the other rows' in-flight state
# untouched), and ``slot_decode_step`` advances EVERY slot one token at
# its own fill index. Both are jitted with donated caches; the decode
# step compiles once per (num_slots, max_len) and never re-traces across
# refills — slot/cur/pad all ride as traced operands.


@functools.partial(
    jax.jit, static_argnames=("model", "temperature", "top_k", "top_p"),
    donate_argnames=("cache",))
def prefill_into_slot(model, params, prompt_ids, pad_len, cache, slot, rng,
                      *, temperature: float = 0.0, top_k: int = 0,
                      top_p: float = 1.0):
    """Prefill ONE request into row ``slot`` of the engine's slot cache.

    ``prompt_ids``: ``[1, Lb]`` int32, left-padded to the engine's bucket
    length (``pad_len``: ``[1]`` int32 — same contract as
    :func:`left_pad_prompts`); ``cache``: the ``[num_slots, ...]`` slot
    cache (donated); ``slot``: traced int32 row index. The prompt runs
    through the standard first-chunk prefill against a private
    ``[1, Lb]``-length scratch cache (so compute is O(Lb²), never
    O(Lb·max_len)), and the written K/V rows are scattered into the slot
    row — positions count from the first real token, exactly the
    ``generate()`` left-pad contract, so a refilled slot's logits are
    bit-identical to a fresh static run of the same prompt.

    Compiled once per bucket length ``Lb``; ``slot``/``pad_len`` are
    traced, so refills into different slots share one program. Returns
    ``(first_token [1] int32, cache)``.
    """
    lb = prompt_ids.shape[1]
    small_shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, lb), jnp.int32), decode=True))
    small = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), small_shapes["cache"])
    logits, mut = model.apply({"params": params, "cache": small},
                              prompt_ids, decode=True, pad_lens=pad_len,
                              first_chunk=True, mutable=["cache"])

    def scatter(big, sm):
        # K/V leaves are [slots, Hkv, L, hd] vs [1, Hkv, Lb, hd]; the
        # scalar ``idx`` leaf is the static path's shared fill index —
        # the engine tracks per-slot fill host-side, so it stays as-is.
        if getattr(sm, "ndim", 0) == 4:
            return jax.lax.dynamic_update_slice(
                big, sm.astype(big.dtype), (slot, 0, 0, 0))
        return big

    cache = jax.tree_util.tree_map(scatter, cache, mut["cache"])
    tok = _sample(logits[:, -1].astype(jnp.float32), rng, temperature,
                  top_k, top_p)
    return tok, cache


@functools.partial(
    jax.jit,
    static_argnames=("model", "window", "temperature", "top_k", "top_p"),
    donate_argnames=("cache",))
def prefill_chunk_into_slot(model, params, chunk_ids, cache, slot, offset,
                            n_valid, rng, *, window: int | None = None,
                            temperature: float = 0.0,
                            top_k: int = 0, top_p: float = 1.0):
    """Consume ``C`` prompt tokens of ONE request into row ``slot`` at
    cache positions ``[offset, offset + C)`` — the stall-free serving
    engine's chunk primitive: a long prompt is fed through this in
    fixed-size chunks *interleaved with* ``slot_decode_step``, so a
    refill never monopolizes the device for a whole O(L²) prefill.

    ``chunk_ids``: ``[1, C]`` int32 — the chunked-prefill contract is
    **zero-aligned** (no left padding: the prompt's token ``i`` lives at
    cache position ``i``, rope position ``i``), the FINAL chunk
    right-pads with zeros and ``n_valid`` (traced int32 scalar) names
    how many of this chunk's tokens are real. The pad tail's K/V rows
    are written but harmless: causality bounds every real query at or
    left of itself, and the decode step overwrites position ``L`` first
    (each write lands before the attention that could read it).
    ``cache``: the ``[num_slots, ...]`` slot cache (donated); ``slot``/
    ``offset`` traced, so chunked prefill compiles one program per
    (C, window) where the bucketed whole-prompt path compiles one per
    bucket. ``window`` (static, default the full row) bounds how many
    of the slot's rows the chunk touches: the caller passes the
    request's chunk-aligned total prompt length, so a 48-token prompt's
    chunks gather/attend/scatter a 48-row window instead of paying
    O(C·max_len) attention and full-row copies per chunk — window
    values are chunk multiples, so the program count stays bounded by
    max_len/C. Every row the chunk may attend ([0, offset+C)) is inside
    the window by construction.

    The chunk runs through the model's standard multi-call decode path
    (write at the fill index, dense attention over the window with the
    causal-vs-cache mask) against the slot's own row gathered as a B=1
    cache — attending only to that slot's rows, never the neighbors'.
    Returns ``(tok [1] int32, cache)`` where ``tok`` is sampled from the
    logits at the last REAL position — meaningful only on the final
    chunk (the engine delivers it as the request's first token).
    """
    def gather(leaf):
        # K/V leaves are [slots, Hkv, L, hd]; scalar leaves are the
        # per-layer ``idx`` fill index — pinned to ``offset`` so the
        # multi-call decode path writes this chunk at the right rows.
        if getattr(leaf, "ndim", 0) == 4:
            w = leaf.shape[2] if window is None \
                else min(int(window), leaf.shape[2])
            return jax.lax.dynamic_slice(
                leaf, (slot, 0, 0, 0),
                (1, leaf.shape[1], w, leaf.shape[3]))
        return jnp.asarray(offset, jnp.int32)

    row = jax.tree_util.tree_map(gather, cache)
    logits, mut = model.apply({"params": params, "cache": row},
                              chunk_ids, decode=True, mutable=["cache"])

    def scatter(big, sm):
        if getattr(sm, "ndim", 0) == 4:
            return jax.lax.dynamic_update_slice(
                big, sm.astype(big.dtype), (slot, 0, 0, 0))
        return big  # the shared static-path idx leaf stays as-is

    cache = jax.tree_util.tree_map(scatter, cache, mut["cache"])
    # Logits at the last REAL token of the chunk (a padded final chunk's
    # tail logits are garbage); traced index -> one program.
    last = jax.lax.dynamic_slice(
        logits, (0, jnp.maximum(n_valid - 1, 0), 0),
        (1, 1, logits.shape[2]))[:, 0]
    tok = _sample(last.astype(jnp.float32), rng, temperature, top_k, top_p)
    return tok, cache


@functools.partial(
    jax.jit, static_argnames=("model", "temperature", "top_k", "top_p"),
    donate_argnames=("cache",))
def slot_decode_step(model, params, cache, tokens, slot_cur, pad_lens, rng,
                     *, temperature: float = 0.0, top_k: int = 0,
                     top_p: float = 1.0):
    """One in-flight batching decode iteration: every slot advances one
    token at its OWN fill index.

    ``tokens``: ``[num_slots]`` int32 (each slot's current token — for
    idle slots the value is irrelevant, their output is discarded
    host-side); ``slot_cur``: ``[num_slots]`` int32 per-slot fill
    indices (the token writes there; attention masks to
    ``[pad_lens[r], slot_cur[r]]``); ``cache`` donated. Compiled ONCE
    per (num_slots, max_len) signature — the engine's steady-state hot
    program; slot refills and retirements never re-trace it. Returns
    ``(next_tokens [num_slots] int32, cache)``.
    """
    logits, mut = model.apply({"params": params, "cache": cache},
                              tokens[:, None], decode=True,
                              pad_lens=pad_lens, slot_cur=slot_cur,
                              mutable=["cache"])
    nxt = _sample(logits[:, -1].astype(jnp.float32), rng, temperature,
                  top_k, top_p)
    return nxt, mut["cache"]


@functools.partial(jax.jit, static_argnames=("model",),
                   donate_argnames=("cache",))
def slot_verify_step(model, params, cache, tokens, slot_cur, pad_lens):
    """Speculative VERIFY window — the fourth jitted donated-cache slot
    primitive (ISSUE 12): one batched target forward checks k drafted
    tokens per slot in a single program dispatch.

    ``tokens``: ``[num_slots, k+1]`` int32 — column 0 is each slot's
    current token (exactly what ``slot_decode_step`` would consume),
    columns 1..k its draft candidates (pad freely: a slot drafting
    fewer than k just computes discarded columns). Row r writes its
    k+1 K/V rows at ``[slot_cur[r], slot_cur[r]+k]`` and query i
    attends dense causal-vs-cache to ``[pad_lens[r], slot_cur[r]+i]``
    — the chunked-prefill write-frontier invariant makes the
    misspeculated tail inert: rejected rows sit at/past the new
    frontier and are overwritten before any attention can read them,
    so **reject is a pure host-side ``cur`` non-advance** — no cache
    rollback program exists or is needed. Writes past ``max_len`` are
    dropped in-graph (never clamped back over committed rows); the
    engine separately caps how many proposals it COMMITS to rows that
    were really written.

    Returns ``(proposals [num_slots, k+1] int32, cache)`` where
    ``proposals[r, i]`` is the greedy argmax of the logits at position
    ``slot_cur[r] + i`` — the token the target emits after consuming
    ``tokens[r, :i+1]``. Greedy-only by construction (argmax IS the
    acceptance rule); the engine gates speculation on
    ``temperature <= 0``. Compiled ONCE per (num_slots, k+1, max_len)
    — drafting, acceptance and rejection are host-side and never
    re-trace it.

    Arithmetic note: the window's logits come from the dense
    causal-vs-cache attention path (S > 1 never rides the
    flash-decode kernel), so on a backend whose flash and dense
    reductions round differently an exact logit TIE could argmax-flip
    a token relative to a flash-decoded ``generate()`` stream; the
    pinned backends (CPU dense + stub) are exact, and the serve
    bench's ``spec_token_identical`` gate is the on-chip check."""
    logits, mut = model.apply({"params": params, "cache": cache},
                              tokens, decode=True, pad_lens=pad_lens,
                              slot_cur=slot_cur, mutable=["cache"])
    props = jnp.argmax(logits.astype(jnp.float32), axis=-1)
    return props.astype(jnp.int32), mut["cache"]


# ---------------------------------------------------------------------------
# Paged slot primitives (block-table serving — ISSUE 11)
# ---------------------------------------------------------------------------
# The three slot primitives above address a PRIVATE [num_slots, ...,
# max_len, ...] cache row per slot: HBM is reserved at num_slots x
# max_len whatever requests actually use. The paged variants below
# address ONE shared pool of [pool_blocks, Hkv, block_size, hd] K/V
# blocks per layer through a per-slot block TABLE ([max_blocks] int32,
# traced): logical cache position p of a slot lives at pool position
# (table[p // block_size], p % block_size). The decode / verify
# primitives route the pool + tables straight into apply(): each layer
# writes only the newly produced positions through the table (a shared
# prefix block is written once and read by every slot whose table
# names it) and attends the pool THROUGH the table — the paged
# flash-decode kernel (ops.paged_flash_decode, ISSUE 15) fuses the
# block gather into its BlockSpec index map, so no dense per-slot view
# exists and per-step HBM traffic is O(cur) per slot; where the kernel
# stands down, a per-layer dense gather view keeps the portable PR 11
# math. The chunk / whole-prompt prefill primitives keep their
# window-bounded gather (already O(window), and prefill is
# compute-bound, not cache-bandwidth-bound).
# Program signatures depend on (num_slots, max_blocks, pool_blocks)
# and the static chunk/window sizes only — tables, slots, offsets and
# fill indices are traced, so refills, grafts and block allocation
# never re-trace (the same no-re-trace property the per-slot
# primitives pin).


def paged_pool_spec(model: LlamaModel, pool_blocks: int, block_size: int,
                    kv_quant: Optional[str] = None):
    """``ShapeDtypeStruct`` pytree of the paged pool — the single
    source of truth for allocation (:func:`init_paged_pool`) AND byte
    accounting (``serving.backend.pool_bytes_per_block``). With
    ``kv_quant`` ('int8'/'fp8') the K/V leaves store codes in the
    quant dtype and every attention dict gains a ``kv_scale``
    ``[pool_blocks, Hkv, 2]`` f32 plane (``[..., 0]`` = K scales,
    ``[..., 1]`` = V — one absmax scale per physical block per kv
    head)."""
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((int(pool_blocks), int(block_size)),
                                     jnp.int32), decode=True))["cache"]
    if kv_quant is None:
        return shapes
    qdt, _ = kv_quant_spec(kv_quant)

    def q(attn):
        for name in ("k", "v"):
            attn[name] = jax.ShapeDtypeStruct(attn[name].shape, qdt)
        p, hkv = attn["k"].shape[:2]
        attn["kv_scale"] = jax.ShapeDtypeStruct((p, hkv, 2), jnp.float32)
        return attn

    return _map_attn_dicts(q, shapes)


def init_paged_pool(model: LlamaModel, pool_blocks: int, block_size: int,
                    kv_sharding=None, scalar_sharding=None,
                    kv_quant: Optional[str] = None, scale_sharding=None):
    """Zeroed shared K/V pool: per layer ``[pool_blocks, kv_heads,
    block_size, head_dim]`` — structurally a ``init_cache`` with
    batch=pool_blocks and max_len=block_size, which is exactly the
    block-major paged layout. Block 0 is conventionally the trash block
    (``serving.paging.BlockAllocator``): idle slots' tables point at
    it, so masked garbage writes land where no request reads.
    ``kv_sharding`` places every block's ``Hkv`` axis over a tp mesh —
    block ids stay logical/device-count-agnostic, each device holds
    ``1/tp`` of every block (see :func:`init_cache`).

    ``kv_quant`` stores K/V as codes with a per-block ``kv_scale``
    plane (:func:`paged_pool_spec`); ``scale_sharding`` places the 3-D
    plane leaves — the tp backends shard them over the same head axis
    as their codes."""
    spec = paged_pool_spec(model, pool_blocks, block_size, kv_quant)

    def make(s):
        nd = len(s.shape)
        sh = {4: kv_sharding, 3: scale_sharding}.get(nd, scalar_sharding)
        if sh is not None:
            return jax.make_array_from_callback(
                s.shape, sh, lambda idx: np.zeros(
                    tuple(len(range(*i.indices(d)))
                          for i, d in zip(idx, s.shape)), s.dtype))
        return jnp.zeros(s.shape, s.dtype)

    return jax.tree_util.tree_map(make, spec)


def _pool_block_size(pool) -> int:
    """Static block size from the pool's K/V leaf shapes."""
    for leaf in jax.tree_util.tree_leaves(pool):
        if getattr(leaf, "ndim", 0) == 4:
            return leaf.shape[2]
    raise ValueError("pool holds no 4-D K/V leaves")


def _gather_view(pool, tables):
    """Dense per-slot cache view through the block tables:
    ``[P, Hkv, bs, hd]`` pool leaves + ``[S, MB]`` tables →
    ``[S, Hkv, MB*bs, hd]`` rows (scalar leaves → zeros placeholders,
    keeping the cache pytree structure apply() expects). Since ISSUE
    15 the decode/verify primitives route the pool straight into
    ``apply()`` (writes and reads go through the table in-layer, the
    kernel fuses the gather away); this tree-level view remains the
    REFERENCE the equivalence tests compare against. A quantized pool
    yields the DEQUANTIZED f32 view (codes·per-block scale) — the
    reference the interpret-mode kernel pins run against."""
    def g_attn(attn):
        plane = attn.get("kv_scale")
        out = {}
        for name, leaf in attn.items():
            if getattr(leaf, "ndim", 0) != 4:
                out[name] = jnp.zeros((), jnp.int32)
            elif plane is None:
                out[name] = _gather_leaf(leaf, tables)
            else:
                out[name] = _gather_dequant(
                    leaf, plane, 0 if name == "k" else 1, tables,
                    jnp.float32)
        return out

    return _map_attn_dicts(g_attn, pool)


@functools.partial(
    jax.jit, static_argnames=("model", "temperature", "top_k", "top_p"),
    donate_argnames=("pool",))
def paged_slot_decode_step(model, params, pool, tables, tokens, slot_cur,
                           pad_lens, rng, *, temperature: float = 0.0,
                           top_k: int = 0, top_p: float = 1.0):
    """One in-flight decode iteration over the BLOCK-TABLE cache: every
    slot advances one token at its own fill index, reading its cache
    through ``tables`` (``[num_slots, max_blocks]`` int32, traced) and
    writing exactly its one new position back into the pool.

    Compiled ONCE per (num_slots, max_blocks, pool_blocks) — block
    allocation, frees, grafts and refills mutate the (traced) tables,
    never the program. Idle or block-stalled slots' writes land at
    whatever their table names at the frontier — the engine parks those
    entries on the trash block, so the masked garbage is contained.
    Returns ``(next_tokens [num_slots] int32, pool)``.

    Since ISSUE 15 the pool rides into ``apply()`` DIRECTLY with the
    block tables (no tree-level ``_gather_view`` / scatter-back): each
    layer writes its one new position through the table and attends the
    pool through the table — via the paged flash-decode kernel when it
    engages (``ops.paged_flash_decode``: the program holds NO
    ``[S, Hkv, max_blocks·bs, hd]`` gather and per-step HBM traffic is
    O(cur) per slot), else a per-layer dense gather view with the exact
    PR 11 math (masked garbage contributes exactly-zero probability, so
    committed tokens are unchanged either way).
    """
    logits, mut = model.apply({"params": params, "cache": pool},
                              tokens[:, None], decode=True,
                              pad_lens=pad_lens, slot_cur=slot_cur,
                              block_tables=tables, mutable=["cache"])
    nxt = _sample(logits[:, -1].astype(jnp.float32), rng, temperature,
                  top_k, top_p)
    return nxt, mut["cache"]


@functools.partial(jax.jit, static_argnames=("model",),
                   donate_argnames=("pool",))
def paged_slot_verify_step(model, params, pool, tables, tokens, slot_cur,
                           pad_lens):
    """``slot_verify_step`` through the block tables — the paged
    speculative verify window (ISSUE 12): row r's k+1 positions
    ``[slot_cur[r], slot_cur[r]+k]`` write through ``tables`` into the
    shared pool, with the draft window's growth blocks allocated UP
    FRONT by the engine (``ensure_block_for`` per draft position — a
    position whose block the pool could not serve routes to the trash
    block 0 and its proposal is never committed). The k+1 writes go
    through the tables in-layer (overhanging positions trash-route —
    same rule as the chunk primitive: never clamp onto live blocks) and
    attention reads the pool through the tables exactly like
    ``paged_slot_decode_step`` — the paged flash-decode kernel covers
    this S = k+1 window too (query i attends ``[pads[r],
    slot_cur[r]+i]``), with the per-layer gather view as the fallback.
    Reject is the same pure host-side ``cur`` non-advance — the
    misspeculated rows are garbage past the frontier, overwritten
    (or trash-routed) before any attention reads them. Compiled ONCE
    per (num_slots, max_blocks, pool_blocks, k+1); tables/fill indices
    traced, so allocation, grafts and refills never re-trace it.
    Returns ``(proposals [num_slots, k+1] int32, pool)``."""
    logits, mut = model.apply({"params": params, "cache": pool},
                              tokens, decode=True, pad_lens=pad_lens,
                              slot_cur=slot_cur, block_tables=tables,
                              mutable=["cache"])
    props = jnp.argmax(logits.astype(jnp.float32), axis=-1)
    return props.astype(jnp.int32), mut["cache"]


@functools.partial(
    jax.jit,
    static_argnames=("model", "window", "temperature", "top_k", "top_p"),
    donate_argnames=("pool",))
def paged_prefill_chunk_into_slot(model, params, chunk_ids, pool,
                                  table_row, offset, n_valid, rng, *,
                                  window: int,
                                  temperature: float = 0.0,
                                  top_k: int = 0, top_p: float = 1.0):
    """``prefill_chunk_into_slot`` through a block table: consume ``C``
    zero-aligned prompt tokens at logical positions
    ``[offset, offset + C)`` of the slot whose table is ``table_row``
    (``[max_blocks]`` int32, traced). The chunk attends a dense view of
    the table's first ``ceil(window / block_size)`` blocks — ``window``
    (static, a chunk multiple covering the request's aligned prompt
    length) bounds the gather exactly like the per-slot variant's
    window bounds its slice — and scatters only its own C written
    positions back through the table, so a grafted shared-prefix block
    is READ here, never written. One compiled program per
    (C, window-blocks); slot identity rides entirely in the table.
    Returns ``(tok [1] int32, pool)`` — the last-real-position sample,
    meaningful on the final chunk."""
    bs = _pool_block_size(pool)
    c = chunk_ids.shape[1]
    # The VIEW must span the whole window (>= offset + C for every
    # chunk of the plan): the multi-call decode path writes the chunk
    # at [offset, offset+C) with dynamic_update_slice, which CLAMPS a
    # write extending past the view — sliding it back over committed
    # prompt rows. A window past the table (a resume whose chunk-
    # aligned length exceeds max_len) gathers every table block and
    # pads the view with scratch rows instead: writes land in-place,
    # and only real positions scatter back to the pool.
    wb = -(-int(window) // bs)

    def gather_attn(attn):
        plane = attn.get("kv_scale")
        out = {}
        for name, leaf in attn.items():
            if name == "kv_scale":
                # the dense window view is FLOAT — the model's
                # non-paged branch (this apply carries no
                # block_tables) declares only k/v/idx, so the view
                # must not grow a scale leaf.
                continue
            if getattr(leaf, "ndim", 0) != 4:
                # scalar idx leaves: pin the multi-call decode path's
                # write index at the chunk's offset (same contract as
                # the un-paged chunk primitive)
                out[name] = jnp.asarray(offset, jnp.int32)
                continue
            mbv = min(wb, table_row.shape[0])
            v = leaf[table_row[:mbv]]              # [mbv, Hkv, bs, hd]
            if plane is not None:
                # dequantize the window into the model's compute
                # dtype; scratch pad rows (below) stay zero — never
                # read live.
                s = plane[table_row[:mbv], :, 0 if name == "k" else 1]
                v = (v.astype(jnp.float32)
                     * s[:, :, None, None]).astype(model.dtype)
            v = jnp.transpose(v, (1, 0, 2, 3))
            v = v.reshape(1, leaf.shape[1], mbv * bs, leaf.shape[3])
            if wb > mbv:
                v = jnp.concatenate(
                    [v, jnp.zeros((1, leaf.shape[1], (wb - mbv) * bs,
                                   leaf.shape[3]), v.dtype)], axis=2)
            out[name] = v
        return out

    row = _map_attn_dicts(gather_attn, pool)
    logits, mut = model.apply({"params": params, "cache": row},
                              chunk_ids, decode=True, mutable=["cache"])
    pos = offset + jnp.arange(c)                   # [C] logical
    bi = pos // bs
    mb = table_row.shape[0]
    # Only REAL tokens' rows are persisted: the final chunk's pad tail
    # (pos >= offset + n_valid) and anything past the table route to
    # the trash block 0 — never clamp onto a live block (a resume
    # whose chunk-aligned length pads past max_len would otherwise
    # scatter garbage over committed rows), and pad-only blocks then
    # need no allocation at all (the reservation covers real rows +
    # one decode block; decode's first write lands at the frontier
    # before any attention can read it — the PR 9 invariant).
    real = (pos < offset + n_valid) & (bi < mb)
    blk = _table_blocks(table_row, bi, real)
    off = pos % bs

    def scatter_attn(attn, dense):
        # zip-walk: the dense twin came from the FLOAT window apply, so
        # it lacks the kv_scale leaf a quantized pool carries — a
        # leaf-level tree_map would reject the structure mismatch.
        plane = attn.get("kv_scale")
        out = dict(attn)
        for ch, name in enumerate(("k", "v")):
            new = jnp.take_along_axis(
                dense[name], pos[None, None, :, None], axis=2)[0]
            new = jnp.moveaxis(new, 1, 0)          # [C, Hkv, hd]
            if plane is None:
                out[name] = attn[name].at[blk, :, off, :].set(
                    new.astype(attn[name].dtype))
            else:
                out[name], plane = _quant_insert_rows(
                    attn[name], plane, ch, blk, off, new)
        if plane is not None:
            out["kv_scale"] = plane
        return out

    pool = _map_attn_dicts(scatter_attn, pool, mut["cache"])
    last = jax.lax.dynamic_slice(
        logits, (0, jnp.maximum(n_valid - 1, 0), 0),
        (1, 1, logits.shape[2]))[:, 0]
    tok = _sample(last.astype(jnp.float32), rng, temperature, top_k, top_p)
    return tok, pool


@functools.partial(
    jax.jit, static_argnames=("model", "temperature", "top_k", "top_p"),
    donate_argnames=("pool",))
def paged_prefill_into_slot(model, params, prompt_ids, pad_len, pool,
                            table_row, rng, *, temperature: float = 0.0,
                            top_k: int = 0, top_p: float = 1.0):
    """``prefill_into_slot`` through a block table — the blocking
    (whole-prompt, left-padded bucket) refill for paged backends: the
    prompt runs the standard first-chunk prefill against a private
    ``[1, Lb]`` scratch cache, then every one of its ``Lb`` rows
    scatters to the pool position the table names (left-pad rows
    included — they carry the same masked-garbage contract as the
    per-slot variant). Compiled once per bucket length; returns
    ``(first_token [1] int32, pool)``."""
    bs = _pool_block_size(pool)
    lb = prompt_ids.shape[1]
    small_shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, lb), jnp.int32), decode=True))
    small = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), small_shapes["cache"])
    logits, mut = model.apply({"params": params, "cache": small},
                              prompt_ids, decode=True, pad_lens=pad_len,
                              first_chunk=True, mutable=["cache"])
    pos = jnp.arange(lb)
    blk = table_row[pos // bs]
    off = pos % bs

    def scatter_attn(attn, sm):
        plane = attn.get("kv_scale")
        out = dict(attn)
        for ch, name in enumerate(("k", "v")):
            new = jnp.transpose(sm[name][0], (1, 0, 2))  # [Lb, Hkv, hd]
            if plane is None:
                out[name] = attn[name].at[blk, :, off, :].set(
                    new.astype(attn[name].dtype))
            else:
                out[name], plane = _quant_insert_rows(
                    attn[name], plane, ch, blk, off, new)
        if plane is not None:
            out["kv_scale"] = plane
        return out

    pool = _map_attn_dicts(scatter_attn, pool, mut["cache"])
    tok = _sample(logits[:, -1].astype(jnp.float32), rng, temperature,
                  top_k, top_p)
    return tok, pool


@functools.partial(jax.jit, donate_argnames=("pool",))
def copy_pool_block(pool, src, dst):
    """Copy one physical block's K/V (every layer) — the paged
    copy-on-write primitive: a write that would land in a SHARED block
    (refcount >= 2 after a radix graft) first duplicates it so the
    other holders keep reading the original. ``src``/``dst`` traced —
    one tiny compiled program per pool signature. The 3-D ``kv_scale``
    planes of a quantized pool copy with their codes (both are indexed
    by physical block), so copy-on-write stays EXACT — the duplicate
    dequantizes bit-identically to the original."""
    def cp(leaf):
        nd = getattr(leaf, "ndim", 0)
        if nd not in (3, 4):
            return leaf
        row = jax.lax.dynamic_slice(
            leaf, (src,) + (0,) * (nd - 1),
            (1,) + leaf.shape[1:])
        return jax.lax.dynamic_update_slice(
            leaf, row, (dst,) + (0,) * (nd - 1))

    return jax.tree_util.tree_map(cp, pool)


# int8 weight serving (ISSUE 18): the Megatron-sharded projection
# matmuls — attention q/k/v/o and MLP gate/up/down. lm_head, embed,
# norms and LoRA adapters stay float (logits keep full precision;
# adapters are ~0.1% of params).
WEIGHT_QUANT_TARGETS = frozenset(
    ("q_proj", "k_proj", "v_proj", "o_proj",
     "gate_proj", "up_proj", "down_proj"))


def quantize_params(params, name: str = "int8"):
    """Host-side weight quantization: every projection base kernel in
    ``WEIGHT_QUANT_TARGETS`` → int8 codes + an absmax per-OUTPUT-channel
    f32 ``kernel_scale`` (``s = max|col| / 127``; an all-zero column
    gets scale 1 so dequant stays finite). Pair with
    ``model.clone(weight_quant='int8')`` — :class:`QuantDense` engages
    on the stored dtype and folds the dequant after each matmul.
    Returns a new params pytree; everything outside the targets is
    passed through untouched."""
    if name != "int8":
        raise ValueError(
            f"unsupported weight quant dtype {name!r} (int8 only)")

    def convert(base):
        kern = jnp.asarray(base["kernel"], jnp.float32)
        s = jnp.max(jnp.abs(kern), axis=0) / 127.0
        s = jnp.where(s > 0, s, 1.0)
        out = dict(base)
        out["kernel"] = jnp.clip(
            jnp.round(kern / s), -127, 127).astype(jnp.int8)
        out["kernel_scale"] = s.astype(jnp.float32)
        return out

    def walk(tree, parent):
        if not isinstance(tree, Mapping):
            return tree
        return {
            k: (convert(v) if k == "base"
                and parent in WEIGHT_QUANT_TARGETS
                and isinstance(v, Mapping) and "kernel" in v
                else walk(v, k))
            for k, v in tree.items()}

    return walk(params, "")


# ---------------------------------------------------------------------------
# LoRA training utilities
# ---------------------------------------------------------------------------

def lora_mask(params) -> Any:
    """Boolean pytree: True for LoRA adapter leaves (trainable), False for
    base weights (frozen). Feed to ``optax.masked`` — the LoRA fine-tune
    trains ~0.1% of params, the rest stay untouched in HBM."""
    from ..parallel.sharding import path_str

    return jax.tree_util.tree_map_with_path(
        lambda path, _: ("lora_a" in path_str(path)
                         or "lora_b" in path_str(path)), params)


def lora_optimizer(learning_rate: float = 1e-4):
    """Adam on LoRA adapters only; base params get zero updates (frozen).

    Uses multi_transform, not optax.masked — masked passes non-masked
    updates through *unchanged* (i.e. raw gradients), it does not freeze.
    """
    import optax

    def labels(params):
        return jax.tree_util.tree_map(
            lambda m: "lora" if m else "frozen", lora_mask(params))

    return optax.multi_transform(
        {"lora": optax.adam(learning_rate), "frozen": optax.set_to_zero()},
        labels)


def causal_lm_loss_fn():
    """Next-token loss for RunnerContext.fit: batch = {input_ids} (labels =
    input_ids shifted left; last position dropped)."""
    import optax

    def loss_fn(params, apply_fn, batch):
        ids = batch["input_ids"]
        logits = apply_fn(params, ids)[:, :-1].astype(jnp.float32)
        targets = ids[:, 1:]
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()
        return loss, {"perplexity": jnp.exp(loss)}

    return loss_fn
