"""InceptionV3 in Flax linen — the flagship DeepImageFeaturizer model.

Reference: the named-model registry's InceptionV3 entry (input 299x299,
bottleneck = 2048-d global-average-pool features — SURVEY.md §2.1, BASELINE
config 1). Architecture follows Szegedy et al. 2015 ("Rethinking the Inception
Architecture", arXiv:1512.00567): factorized 7x7 branches, grid reductions,
expanded-filter-bank mixed9/10 blocks. Implemented NHWC with fused
conv+bn+relu units, single traced graph, dtype knob for bf16 MXU compute.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    filters: int
    kernel: tuple[int, int]
    strides: tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.filters, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype,
                    name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9997,
                         epsilon=1e-3, dtype=self.dtype, name="bn")(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME",
                       count_include_pad=False)


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cbn = lambda f, k, name: ConvBN(f, k, dtype=self.dtype, name=name)
        b1 = cbn(64, (1, 1), "b1x1")(x, train)
        b5 = cbn(48, (1, 1), "b5x5_1")(x, train)
        b5 = cbn(64, (5, 5), "b5x5_2")(b5, train)
        b3 = cbn(64, (1, 1), "b3x3dbl_1")(x, train)
        b3 = cbn(96, (3, 3), "b3x3dbl_2")(b3, train)
        b3 = cbn(96, (3, 3), "b3x3dbl_3")(b3, train)
        bp = _avg_pool_same(x)
        bp = cbn(self.pool_features, (1, 1), "bpool")(bp, train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35→17."""
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cbn = lambda f, k, name, s=(1, 1), p="SAME": ConvBN(
            f, k, strides=s, padding=p, dtype=self.dtype, name=name)
        b3 = cbn(384, (3, 3), "b3x3", s=(2, 2), p="VALID")(x, train)
        bd = cbn(64, (1, 1), "b3x3dbl_1")(x, train)
        bd = cbn(96, (3, 3), "b3x3dbl_2")(bd, train)
        bd = cbn(96, (3, 3), "b3x3dbl_3", s=(2, 2), p="VALID")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7x7 branches."""
    c7: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cbn = lambda f, k, name: ConvBN(f, k, dtype=self.dtype, name=name)
        c7 = self.c7
        b1 = cbn(192, (1, 1), "b1x1")(x, train)
        b7 = cbn(c7, (1, 1), "b7x7_1")(x, train)
        b7 = cbn(c7, (1, 7), "b7x7_2")(b7, train)
        b7 = cbn(192, (7, 1), "b7x7_3")(b7, train)
        bd = cbn(c7, (1, 1), "b7x7dbl_1")(x, train)
        bd = cbn(c7, (7, 1), "b7x7dbl_2")(bd, train)
        bd = cbn(c7, (1, 7), "b7x7dbl_3")(bd, train)
        bd = cbn(c7, (7, 1), "b7x7dbl_4")(bd, train)
        bd = cbn(192, (1, 7), "b7x7dbl_5")(bd, train)
        bp = _avg_pool_same(x)
        bp = cbn(192, (1, 1), "bpool")(bp, train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17→8."""
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cbn = lambda f, k, name, s=(1, 1), p="SAME": ConvBN(
            f, k, strides=s, padding=p, dtype=self.dtype, name=name)
        b3 = cbn(192, (1, 1), "b3x3_1")(x, train)
        b3 = cbn(320, (3, 3), "b3x3_2", s=(2, 2), p="VALID")(b3, train)
        b7 = cbn(192, (1, 1), "b7x7x3_1")(x, train)
        b7 = cbn(192, (1, 7), "b7x7x3_2")(b7, train)
        b7 = cbn(192, (7, 1), "b7x7x3_3")(b7, train)
        b7 = cbn(192, (3, 3), "b7x7x3_4", s=(2, 2), p="VALID")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """Expanded filter bank (split 3x3 into 1x3 + 3x1)."""
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cbn = lambda f, k, name: ConvBN(f, k, dtype=self.dtype, name=name)
        b1 = cbn(320, (1, 1), "b1x1")(x, train)
        b3 = cbn(384, (1, 1), "b3x3_1")(x, train)
        b3 = jnp.concatenate([cbn(384, (1, 3), "b3x3_2a")(b3, train),
                              cbn(384, (3, 1), "b3x3_2b")(b3, train)], axis=-1)
        bd = cbn(448, (1, 1), "b3x3dbl_1")(x, train)
        bd = cbn(384, (3, 3), "b3x3dbl_2")(bd, train)
        bd = jnp.concatenate([cbn(384, (1, 3), "b3x3dbl_3a")(bd, train),
                              cbn(384, (3, 1), "b3x3dbl_3b")(bd, train)],
                             axis=-1)
        bp = _avg_pool_same(x)
        bp = cbn(192, (1, 1), "bpool")(bp, train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, features_only: bool = False):
        x = x.astype(self.dtype)
        cbn = lambda f, k, name, s=(1, 1), p="VALID": ConvBN(
            f, k, strides=s, padding=p, dtype=self.dtype, name=name)
        # Stem: 299x299x3 → 35x35x192
        x = cbn(32, (3, 3), "stem1", s=(2, 2))(x, train)
        x = cbn(32, (3, 3), "stem2")(x, train)
        x = cbn(64, (3, 3), "stem3", p="SAME")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = cbn(80, (1, 1), "stem4")(x, train)
        x = cbn(192, (3, 3), "stem5")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # Mixed blocks
        x = InceptionA(32, dtype=self.dtype, name="mixed0")(x, train)
        x = InceptionA(64, dtype=self.dtype, name="mixed1")(x, train)
        x = InceptionA(64, dtype=self.dtype, name="mixed2")(x, train)
        x = InceptionB(dtype=self.dtype, name="mixed3")(x, train)
        x = InceptionC(128, dtype=self.dtype, name="mixed4")(x, train)
        x = InceptionC(160, dtype=self.dtype, name="mixed5")(x, train)
        x = InceptionC(160, dtype=self.dtype, name="mixed6")(x, train)
        x = InceptionC(192, dtype=self.dtype, name="mixed7")(x, train)
        x = InceptionD(dtype=self.dtype, name="mixed8")(x, train)
        x = InceptionE(dtype=self.dtype, name="mixed9")(x, train)
        x = InceptionE(dtype=self.dtype, name="mixed10")(x, train)
        x = jnp.mean(x, axis=(1, 2))  # 8x8x2048 → 2048 (the bottleneck)
        x = x.astype(jnp.float32)
        if features_only:
            return x
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
