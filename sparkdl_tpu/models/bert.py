"""BERT-base encoder (flax) — the XlaRunner GLUE fine-tune family.

The reference predates BERT entirely; this family exists for BASELINE
config 4 ("XlaRunner: BERT-base fine-tune on GLUE with Spark DataFrame
reader"). TPU-first choices:

- static [B, S] shapes, attention mask as an additive bias (no dynamic
  slicing) so XLA compiles one program per sequence length;
- module names (``query``/``key``/``value``/``attention_output``/
  ``intermediate``/``output_dense``/``word_embeddings``) line up with
  ``parallel.transformer_tp_rules`` so the same checkpoint runs replicated
  (DP) or tensor-parallel without renaming;
- dtype-parameterized (bfloat16 compute on the MXU, f32 layernorm/softmax).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dropout_rate: float = 0.1

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "BertConfig":
        """For tests/dryruns: 2 layers, 128-wide."""
        return cls(vocab_size=1000, hidden_size=128, num_layers=2,
                   num_heads=4, intermediate_size=256,
                   max_position_embeddings=128)


class BertSelfAttention(nn.Module):
    cfg: BertConfig
    dtype: Any = jnp.float32
    # (q, k, v, causal=..., kv_mask=...) → o. "auto" (default) resolves to
    # the Pallas flash kernel on TPU and in-model dense attention elsewhere
    # (ops.resolve_attn_fn); the S·S score matrix then never materializes
    # and the padding mask rides as kv_mask. NB: attention-prob dropout is
    # skipped under attn_fn (streaming softmax has no prob matrix to drop —
    # the standard flash trade-off); hidden dropout elsewhere is unaffected.
    attn_fn: Any = "auto"

    @nn.compact
    def __call__(self, x, bias=None, deterministic: bool = True, mask=None):
        c, d = self.cfg, self.dtype
        head_dim = c.hidden_size // c.num_heads
        dense = lambda name: nn.Dense(c.hidden_size, dtype=d, name=name)
        # [B, S, H*D] → [B, H, S, D]
        split = lambda t: t.reshape(t.shape[0], t.shape[1], c.num_heads,
                                    head_dim).transpose(0, 2, 1, 3)
        q = split(dense("query")(x))
        k = split(dense("key")(x))
        v = split(dense("value")(x))
        from ..ops.flash_attention import resolve_attn_fn
        attn_fn = resolve_attn_fn(self.attn_fn)
        # attn_fn runs only when the padding state is EXPRESSIBLE to it:
        # either an explicit [B, S] mask (→ kv_mask), or provably no
        # padding (bias is None too — the encoder passes bias=None when no
        # attention_mask was given). A caller supplying only an additive
        # bias keeps the dense path: the bias is never silently dropped.
        if attn_fn is not None and mask is None and bias is None:
            # no padding declared: plain (q, k, v, causal=...) contract —
            # ring/Ulysses/dense drop in unchanged
            o = attn_fn(q, k, v, causal=False)
        elif attn_fn is not None and mask is not None:
            import inspect
            try:
                params = inspect.signature(attn_fn).parameters
                accepts_mask = ("kv_mask" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values()))
            except (TypeError, ValueError):
                accepts_mask = True
            if not accepts_mask:
                raise TypeError(
                    f"BertSelfAttention.attn_fn {attn_fn} does not accept "
                    f"kv_mask — padded encoder batches need a mask-capable "
                    f"attention (e.g. ops.flash_attention); for unpadded "
                    f"batches call without an attention_mask")
            o = attn_fn(q, k, v, causal=False, kv_mask=mask)
        else:
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(head_dim)
            s = s.astype(jnp.float32)
            if bias is not None:
                s = s + bias  # mask as additive bias, f32 softmax
            p = jax.nn.softmax(s, axis=-1).astype(d)
            p = nn.Dropout(c.dropout_rate)(p, deterministic=deterministic)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        o = o.transpose(0, 2, 1, 3).reshape(x.shape[0], x.shape[1],
                                            c.hidden_size)
        return nn.Dense(c.hidden_size, dtype=d, name="attention_output")(o)


class BertLayer(nn.Module):
    cfg: BertConfig
    dtype: Any = jnp.float32
    attn_fn: Any = "auto"

    @nn.compact
    def __call__(self, x, bias=None, deterministic: bool = True, mask=None):
        c, d = self.cfg, self.dtype
        a = BertSelfAttention(c, d, self.attn_fn, name="attention")(
            x, bias, deterministic, mask)
        a = nn.Dropout(c.dropout_rate)(a, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=jnp.float32,
                         name="attention_norm")(x + a)
        h = nn.Dense(c.intermediate_size, dtype=d, name="intermediate")(x)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(c.hidden_size, dtype=d, name="output_dense")(h)
        h = nn.Dropout(c.dropout_rate)(h, deterministic=deterministic)
        return nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=jnp.float32,
                            name="output_norm")(x + h)


class BertEncoder(nn.Module):
    """Token ids (+mask, +segments) → (sequence_output, pooled_output).

    ``attn_fn``: pluggable attention (see BertSelfAttention) — pass
    ``ops.flash_attention`` (or ``ops.auto_attn_fn()``) for the Pallas
    kernel on TPU; padding masks ride through as ``kv_mask``."""
    cfg: BertConfig
    dtype: Any = jnp.float32
    attn_fn: Any = "auto"

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        c, d = self.cfg, self.dtype
        B, S = input_ids.shape
        # Track None-ness: an absent mask means "no padding", which lets a
        # mask-less attn_fn (ring/Ulysses) run; a ones-mask would force the
        # kv_mask contract for nothing.
        user_mask = attention_mask
        if attention_mask is None:
            attention_mask = jnp.ones((B, S), jnp.int32)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((B, S), jnp.int32)

        emb = nn.Embed(c.vocab_size, c.hidden_size, dtype=d,
                       name="word_embeddings")(input_ids)
        pos = nn.Embed(c.max_position_embeddings, c.hidden_size, dtype=d,
                       name="position_embeddings")(jnp.arange(S)[None, :])
        seg = nn.Embed(c.type_vocab_size, c.hidden_size, dtype=d,
                       name="token_type_embeddings")(token_type_ids)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=jnp.float32,
                         name="embeddings_norm")(emb + pos + seg)
        x = nn.Dropout(c.dropout_rate)(x, deterministic=deterministic)
        x = x.astype(d)

        # [B, S] mask → additive bias [B, 1, 1, S]; None when no mask was
        # given, so the attention layer KNOWS there is no padding (and a
        # maskless attn_fn is admissible)
        bias = None if user_mask is None else (
            (1.0 - attention_mask[:, None, None, :].astype(jnp.float32))
            * -1e30)
        for i in range(c.num_layers):
            x = BertLayer(c, d, self.attn_fn, name=f"layer_{i}")(
                x, bias, deterministic, user_mask)

        pooled = nn.tanh(nn.Dense(c.hidden_size, dtype=d,
                                  name="pooler")(x[:, 0]))
        return x, pooled


class BertForSequenceClassification(nn.Module):
    """The GLUE head: encoder + dropout + linear over pooled [CLS]."""
    cfg: BertConfig
    num_classes: int = 2
    dtype: Any = jnp.float32
    attn_fn: Any = "auto"

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        _, pooled = BertEncoder(self.cfg, self.dtype, self.attn_fn,
                                name="bert")(
            input_ids, attention_mask, token_type_ids, deterministic)
        pooled = nn.Dropout(self.cfg.dropout_rate)(
            pooled, deterministic=deterministic)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="classifier")(pooled)


def glue_loss_fn():
    """loss_fn for RunnerContext.fit: batch = {input_ids, attention_mask,
    token_type_ids?, label}. ``apply_fn(params, batch)`` runs deterministic
    (no dropout); for dropout-regularized fine-tuning use
    ``bert_finetune_loss`` with ``with_rng=True`` steps."""
    import optax

    def loss_fn(params, apply_fn, batch):
        logits = apply_fn(params, batch).astype(jnp.float32)
        onehot = jax.nn.one_hot(batch["label"], logits.shape[-1])
        loss = optax.softmax_cross_entropy(logits, onehot).mean()
        acc = (logits.argmax(-1) == batch["label"]).mean()
        return loss, {"accuracy": acc.astype(jnp.float32)}

    return loss_fn


def bert_finetune_loss(model: BertForSequenceClassification):
    """Dropout-active GLUE fine-tune loss: pair with a ``with_rng=True``
    train step (RunnerContext.fit(with_rng=True)) so each step gets fresh
    dropout noise; falls back to deterministic when no rng is plumbed."""
    import optax

    def loss_fn(params, apply_fn, batch, rng=None):
        det = rng is None
        logits = model.apply(
            params, batch["input_ids"], batch.get("attention_mask"),
            batch.get("token_type_ids"), deterministic=det,
            rngs=None if det else {"dropout": rng})
        logits = logits.astype(jnp.float32)
        onehot = jax.nn.one_hot(batch["label"], logits.shape[-1])
        loss = optax.softmax_cross_entropy(logits, onehot).mean()
        acc = (logits.argmax(-1) == batch["label"]).mean()
        return loss, {"accuracy": acc.astype(jnp.float32)}

    return loss_fn
