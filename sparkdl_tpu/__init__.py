"""sparkdl_tpu — a TPU-native deep-learning-pipelines framework.

A ground-up re-design of the capability surface of Databricks' Deep Learning
Pipelines (``sparkdl``, reference fork ``smurching/spark-deep-learning``) for
TPU: the Spark-ML-shaped Pipeline API (``fit``/``transform``, Params,
persistence) over an Arrow columnar data plane, with inference as
``jax.jit``-compiled XLA programs fed by a double-buffered HBM pipeline, and
distributed training via ``XlaRunner`` — SPMD over a ``jax.sharding.Mesh``
with ICI collectives — replacing the reference's Horovod MPI+NCCL stack.

See SURVEY.md for the blueprint (the reference mount was empty at build time;
the survey + BASELINE.json are the spec).
"""

__version__ = "0.1.0"

from .core import (CrossValidator, CrossValidatorModel, DataFrame, Estimator,
                   Evaluator, HasBatchSize, HasInputCol, HasLabelCol,
                   HasOutputCol, HasPredictionCol, HasSeed, MLWritable, Model,
                   Param, ParamGridBuilder, Params, Pipeline, PipelineModel,
                   Row, TrainValidationSplit, TrainValidationSplitModel,
                   Transformer, TypeConverters, keyword_only, load)
from .estimators import (BinaryClassificationEvaluator,
                         KerasImageFileEstimator, LogisticRegression,
                         LogisticRegressionModel,
                         MulticlassClassificationEvaluator,
                         RegressionEvaluator)
from .graph import (GraphFunction, IsolatedSession, TFInputGraph,
                    XlaInputGraph, buildFlattener, buildSpImageConverter,
                    makeGraphUDF)
from .ops import flash_attention
from .image.imageIO import (createResizeImageUDF, imageSchema,
                            nhwcToImageColumn, readImages,
                            readImagesWithCustomFn)
from .models import ByteBPETokenizer, load_pretrained
from .transformers import (DeepImageFeaturizer, DeepImagePredictor,
                           KerasImageFileTransformer, KerasTransformer,
                           TFImageTransformer, TFTransformer,
                           XlaImageTransformer, XlaTransformer)
from .runner import (CheckpointManager, RunnerContext, TrainState, XlaRunner,
                     make_shard_map_step, make_train_step)
from .serving import GenerationEngine
from .transformers.feature import (IndexToString, StandardScaler,
                                   StandardScalerModel, StringIndexer,
                                   StringIndexerModel, VectorAssembler)
from .udf import (applyUDF, listUDFs, registerGenerationUDF,
                  registerImageUDF, registerKerasImageUDF,
                  registerSequenceClassificationUDF,
                  registerTextGenerationUDF, registerUDF)

__all__ = [
    "DataFrame", "Row",
    "Param", "Params", "TypeConverters", "keyword_only",
    "HasInputCol", "HasOutputCol", "HasLabelCol", "HasPredictionCol",
    "HasBatchSize", "HasSeed",
    "Transformer", "Estimator", "Model", "Evaluator",
    "Pipeline", "PipelineModel", "MLWritable", "load",
    "imageSchema", "readImages", "readImagesWithCustomFn",
    "createResizeImageUDF", "nhwcToImageColumn",
    "load_pretrained", "ByteBPETokenizer",
    "XlaImageTransformer", "TFImageTransformer",
    "DeepImageFeaturizer", "DeepImagePredictor",
    "KerasImageFileTransformer", "XlaTransformer", "TFTransformer",
    "KerasTransformer",
    "LogisticRegression", "LogisticRegressionModel",
    "VectorAssembler", "StringIndexer", "StringIndexerModel",
    "IndexToString", "StandardScaler", "StandardScalerModel",
    "ParamGridBuilder", "CrossValidator", "CrossValidatorModel",
    "TrainValidationSplit", "TrainValidationSplitModel",
    "MulticlassClassificationEvaluator", "RegressionEvaluator",
    "BinaryClassificationEvaluator",
    "KerasImageFileEstimator",
    "registerUDF", "registerImageUDF", "registerKerasImageUDF",
    "registerGenerationUDF", "registerTextGenerationUDF",
    "registerSequenceClassificationUDF", "applyUDF",
    "listUDFs",
    "GraphFunction", "IsolatedSession", "XlaInputGraph", "TFInputGraph",
    "buildSpImageConverter", "buildFlattener", "makeGraphUDF",
    "flash_attention",
    "XlaRunner", "RunnerContext", "TrainState", "CheckpointManager",
    "make_train_step", "make_shard_map_step",
    "GenerationEngine",
    "__version__",
]
