"""UDF registry — named, reusable column functions over DataFrames.

Reference surface: ``registerKerasImageUDF(name, model, preprocessor)``
(``python/sparkdl/udf/keras_image_model.py``) + ``makeGraphUDF``
(``graph/tensorframes_udf.py``) registered TF graphs as Spark SQL UDFs
executed by TensorFrames in the JVM (SURVEY.md §2.1/§3.3). There is no JVM
and no SQL parser here; the equivalent contract is a process-global registry
of named batch functions applicable to any DataFrame column via
``applyUDF(df, name, inputCol, outputCol)`` — the same "register once, score
anywhere by name" workflow, executing as jitted XLA programs.
"""

from __future__ import annotations

from typing import Callable

from ..core.frame import DataFrame

_UDF_REGISTRY: dict[str, Callable[[DataFrame, str, str], DataFrame]] = {}


def registerUDF(name: str, fn: Callable, batchSize: int = 64,
                inputShape: tuple | None = None) -> None:
    """Register a jittable ``fn(batch)`` over numeric array columns."""
    from ..transformers.tensor import XlaTransformer

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        t = XlaTransformer(inputCol=inputCol, outputCol=outputCol, fn=fn,
                           batchSize=batchSize,
                           **({"inputShape": inputShape} if inputShape else {}))
        return t.transform(df)

    _UDF_REGISTRY[name] = apply


def registerImageUDF(name: str, fn: Callable, inputSize: tuple[int, int],
                     batchSize: int = 32, channelOrder: str = "RGB") -> None:
    """Register a jittable ``fn(nhwc_batch)`` over image-struct columns."""
    from ..transformers.xla_image import XlaImageTransformer

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        t = XlaImageTransformer(inputCol=inputCol, outputCol=outputCol, fn=fn,
                                inputSize=inputSize, batchSize=batchSize,
                                channelOrder=channelOrder)
        return t.transform(df)

    _UDF_REGISTRY[name] = apply


def registerKerasImageUDF(udf_name: str, keras_model_or_file,
                          preprocessor: Callable | None = None,
                          batchSize: int = 32) -> None:
    """The reference's flagship UDF: compose image-decode ∘ (preprocessor) ∘
    Keras model and register under ``udf_name``.

    ``keras_model_or_file``: a Keras-3 model object, a saved-model path, or a
    named model from SUPPORTED_MODELS (e.g. "InceptionV3" — random-init in
    this zero-egress environment). ``preprocessor`` is a jittable NHWC→NHWC
    function fused in front of the model inside the same XLA program.
    """
    from ..transformers.keras_utils import keras_model_to_fn

    if isinstance(keras_model_or_file, str):
        from ..models import SUPPORTED_MODELS, get_model
        if keras_model_or_file in SUPPORTED_MODELS:
            m = get_model(keras_model_or_file)
            variables = m.init_params()
            apply_model = m.apply_fn(features_only=False)
            base_fn = lambda b: apply_model(variables, b)
            input_hw = m.input_size
        else:
            from ..transformers.keras_utils import load_keras_model
            model = load_keras_model(keras_model_or_file)
            base_fn = keras_model_to_fn(model)
            shape = model.inputs[0].shape
            input_hw = (int(shape[1]), int(shape[2]))
    else:
        base_fn = keras_model_to_fn(keras_model_or_file)
        shape = keras_model_or_file.inputs[0].shape
        input_hw = (int(shape[1]), int(shape[2]))

    fn = (lambda b: base_fn(preprocessor(b))) if preprocessor else base_fn
    registerImageUDF(udf_name, fn, inputSize=input_hw, batchSize=batchSize)



def registerGenerationUDF(name: str, model, variables,
                          max_new_tokens: int = 32,
                          temperature: float = 0.0, seed: int = 0,
                          batchRows: int = 64, top_k: int = 0,
                          top_p: float = 1.0,
                          eos_id: int | None = None) -> None:
    """Register a text-generation UDF over token-id columns — the
    ``registerUDF`` batch-inference half of BASELINE config 5 ("Llama LoRA
    fine-tune via XlaRunner + registerUDF batch inference").

    The column holds int token-id lists (prompts). The whole column is
    LEFT-padded to one length (``models.llama.left_pad_prompts``) and runs
    as exactly TWO compiled XLA programs however many distinct prompt
    lengths appear: one masked prefill (positions count from each row's
    first real token) + one while_loop/scan decode (EOS early exit). No
    duplicate-row fill, no per-length recompiles. Rows are chunked to
    ``batchRows`` so a huge column doesn't build one giant cache (chunks
    of equal row count reuse the same programs).
    """
    _UDF_REGISTRY[name] = _make_generation_apply(
        model, variables, max_new_tokens=max_new_tokens,
        temperature=temperature, seed=seed, batchRows=batchRows,
        top_k=top_k, top_p=top_p, eos_id=eos_id)


def _make_generation_apply(model, variables, *, max_new_tokens: int = 32,
                           temperature: float = 0.0, seed: int = 0,
                           batchRows: int = 64, top_k: int = 0,
                           top_p: float = 1.0,
                           eos_id: int | None = None) -> Callable:
    """Build (and validate) the apply closure behind
    :func:`registerGenerationUDF` — shared with
    :func:`registerTextGenerationUDF` so the padding/chunking/EOS
    semantics have one source of truth."""
    import jax
    import numpy as np

    from ..models.llama import generate, left_pad_prompts

    # fail at REGISTRATION, not on the first applyUDF call
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0 (0 disables), got {top_k}")
    if eos_id is not None and (isinstance(eos_id, bool)
                               or not isinstance(eos_id, (int, np.integer))):
        raise TypeError(f"eos_id must be an int token id or None, "
                        f"got {eos_id!r}")

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        import pyarrow as pa
        import pyarrow.compute as pc

        from ..core.frame import _set_column

        # Streaming data plane (round-3 verdict Next #5): the prompt column
        # never materializes whole on the host. Pass 1 walks the column in
        # ``batchRows`` Arrow chunks reading LENGTHS only, to pin the
        # column-wide max prompt length — the one value that must be global
        # for every chunk to share a single compiled (rows, lmax) prefill/
        # decode signature. Pass 2 re-streams the same chunks through
        # generate(). Host memory is O(batchRows) input rows + the output
        # column itself.
        if df._ops:
            # Two passes would execute pending upstream ops (tokenizers,
            # mapBatches, ...) twice; materialize once instead. Token-id
            # columns are small — the memory tradeoff only bites on frames
            # that are already op-free (the common fromPandas/fromArrow
            # case), which skip this.
            df = df.cache()
        lmax = 0
        n_rows = 0
        for batch in df.iterBatches(batchRows):
            lens = pc.list_value_length(batch.column(inputCol)) \
                .to_numpy(zero_copy_only=False)
            if len(lens) and int(lens.min()) == 0:
                bad = n_rows + int(np.argmin(lens))
                raise ValueError(
                    f"{inputCol!r} row {bad} is an empty prompt; every row "
                    f"needs at least one token id")
            n_rows += len(lens)
            if len(lens):
                lmax = max(lmax, int(lens.max()))

        if n_rows == 0:  # keep the schema contract on an empty column
            tbl = df.toArrow()
            empty = pa.array([], type=pa.list_(pa.int64()))
            if outputCol in tbl.column_names:  # replace, like _set_column
                tbl = tbl.set_column(tbl.column_names.index(outputCol),
                                     outputCol, empty)
            else:
                tbl = tbl.append_column(outputCol, empty)
            return DataFrame.fromArrow(
                tbl, numPartitions=max(1, df.numPartitions))

        rng = jax.random.PRNGKey(seed)
        out_parts: list[pa.RecordBatch] = []
        for chunk_idx, batch in enumerate(df.iterBatches(batchRows)):
            prompts = batch.column(inputCol).to_pylist()
            ids, pads = left_pad_prompts(prompts, pad_to=lmax)
            # pad a trailing partial chunk's ROWS up to batchRows so every
            # chunk hits the same compiled (rows, lmax) signature; fill
            # rows are duplicates sliced off below. (A lone first chunk
            # compiles at its own row count — no fill needed.)
            n = len(ids)
            if n < batchRows and chunk_idx > 0:
                fill = batchRows - n
                ids = np.concatenate(
                    [ids, np.repeat(ids[:1], fill, axis=0)])
                pads = np.concatenate(
                    [pads, np.repeat(pads[:1], fill, axis=0)])
            rng, key = jax.random.split(rng)
            gen = np.asarray(generate(
                model, variables, ids, max_new_tokens,
                temperature=temperature, rng=key,
                pad_to=lmax + max_new_tokens, pad_lens=pads,
                top_k=top_k, top_p=top_p, eos_id=eos_id))
            out: list = []
            for row in range(n):
                # strip this row's left pads: real prompt + new tokens
                toks = gen[row, pads[row]:].tolist()
                if eos_id is not None:
                    # trim the repeated-eos tail, keep one eos
                    plen = len(prompts[row])
                    gen_part = toks[plen:]
                    if eos_id in gen_part:
                        gen_part = gen_part[:gen_part.index(eos_id) + 1]
                    toks = toks[:plen] + gen_part
                out.append(toks)
            out_parts.append(_set_column(
                batch, outputCol, pa.array(out, type=pa.list_(pa.int64()))))
        # Restore the input's partition count (the pre-streaming contract;
        # the chunk layout above is a generation detail, not an API).
        return DataFrame(out_parts).repartition(df.numPartitions)

    return apply


def registerTextGenerationUDF(name: str, model, variables,
                              encode: Callable[[str], list],
                              decode: Callable[[list], str],
                              **gen_kwargs) -> None:
    """String-column twin of :func:`registerGenerationUDF`: the column
    holds TEXT prompts; ``encode``/``decode`` are the tokenizer halves
    (e.g. a HF tokenizer's ``tok.encode`` / ``tok.decode``). Tokenize →
    the streamed left-padded two-program generation → detokenize, all per
    ``batchRows`` chunk. Accepts every registerGenerationUDF keyword.
    """
    if not callable(encode) or not callable(decode):
        raise TypeError("encode and decode must be callables "
                        f"(got {encode!r}, {decode!r})")
    inner_apply = _make_generation_apply(model, variables, **gen_kwargs)

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        ids_col = f"__{name}_ids"
        out_ids = f"__{name}_out_ids"
        with_ids = df.withColumn(
            ids_col, lambda s: [int(t) for t in encode(s)], [inputCol])
        try:
            gen = inner_apply(with_ids, ids_col, out_ids)
        except ValueError as e:
            # surface the USER's column name, not the hidden ids column
            raise ValueError(
                str(e).replace(repr(ids_col), repr(inputCol))) from None
        # strip the prompt ids from each completion before decoding
        def detok(prompt_ids, completion_ids):
            return decode([int(t) for t in
                           completion_ids[len(prompt_ids):]])
        return gen.withColumn(outputCol, detok, [ids_col, out_ids]) \
                  .drop(ids_col, out_ids)

    _UDF_REGISTRY[name] = apply


def applyUDF(df: DataFrame, name: str, inputCol: str,
             outputCol: str) -> DataFrame:
    try:
        apply = _UDF_REGISTRY[name]
    except KeyError:
        raise ValueError(f"UDF {name!r} is not registered; available: "
                         f"{sorted(_UDF_REGISTRY)}") from None
    return apply(df, inputCol, outputCol)


def listUDFs() -> list[str]:
    return sorted(_UDF_REGISTRY)


def unregisterUDF(name: str) -> None:
    _UDF_REGISTRY.pop(name, None)
