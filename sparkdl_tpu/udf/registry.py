"""UDF registry — named, reusable column functions over DataFrames.

Reference surface: ``registerKerasImageUDF(name, model, preprocessor)``
(``python/sparkdl/udf/keras_image_model.py``) + ``makeGraphUDF``
(``graph/tensorframes_udf.py``) registered TF graphs as Spark SQL UDFs
executed by TensorFrames in the JVM (SURVEY.md §2.1/§3.3). There is no JVM
and no SQL parser here; the equivalent contract is a process-global registry
of named batch functions applicable to any DataFrame column via
``applyUDF(df, name, inputCol, outputCol)`` — the same "register once, score
anywhere by name" workflow, executing as jitted XLA programs.
"""

from __future__ import annotations

from typing import Callable

from ..core.frame import DataFrame

_UDF_REGISTRY: dict[str, Callable[[DataFrame, str, str], DataFrame]] = {}


def registerUDF(name: str, fn: Callable, batchSize: int = 64,
                inputShape: tuple | None = None) -> None:
    """Register a jittable ``fn(batch)`` over numeric array columns."""
    from ..transformers.tensor import XlaTransformer

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        t = XlaTransformer(inputCol=inputCol, outputCol=outputCol, fn=fn,
                           batchSize=batchSize,
                           **({"inputShape": inputShape} if inputShape else {}))
        return t.transform(df)

    _UDF_REGISTRY[name] = apply


def registerImageUDF(name: str, fn: Callable, inputSize: tuple[int, int],
                     batchSize: int = 32, channelOrder: str = "RGB") -> None:
    """Register a jittable ``fn(nhwc_batch)`` over image-struct columns."""
    from ..transformers.xla_image import XlaImageTransformer

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        t = XlaImageTransformer(inputCol=inputCol, outputCol=outputCol, fn=fn,
                                inputSize=inputSize, batchSize=batchSize,
                                channelOrder=channelOrder)
        return t.transform(df)

    _UDF_REGISTRY[name] = apply


def registerKerasImageUDF(udf_name: str, keras_model_or_file,
                          preprocessor: Callable | None = None,
                          batchSize: int = 32) -> None:
    """The reference's flagship UDF: compose image-decode ∘ (preprocessor) ∘
    Keras model and register under ``udf_name``.

    ``keras_model_or_file``: a Keras-3 model object, a saved-model path, or a
    named model from SUPPORTED_MODELS (e.g. "InceptionV3" — random-init in
    this zero-egress environment). ``preprocessor`` is a jittable NHWC→NHWC
    function fused in front of the model inside the same XLA program.
    """
    from ..transformers.keras_utils import keras_model_to_fn

    if isinstance(keras_model_or_file, str):
        from ..models import SUPPORTED_MODELS, get_model
        if keras_model_or_file in SUPPORTED_MODELS:
            m = get_model(keras_model_or_file)
            variables = m.init_params()
            apply_model = m.apply_fn(features_only=False)
            base_fn = lambda b: apply_model(variables, b)
            input_hw = m.input_size
        else:
            from ..transformers.keras_utils import load_keras_model
            model = load_keras_model(keras_model_or_file)
            base_fn = keras_model_to_fn(model)
            shape = model.inputs[0].shape
            input_hw = (int(shape[1]), int(shape[2]))
    else:
        base_fn = keras_model_to_fn(keras_model_or_file)
        shape = keras_model_or_file.inputs[0].shape
        input_hw = (int(shape[1]), int(shape[2]))

    fn = (lambda b: base_fn(preprocessor(b))) if preprocessor else base_fn
    registerImageUDF(udf_name, fn, inputSize=input_hw, batchSize=batchSize)



def registerGenerationUDF(name: str, model, variables,
                          max_new_tokens: int = 32,
                          temperature: float = 0.0, seed: int = 0) -> None:
    """Register a text-generation UDF over token-id columns — the
    ``registerUDF`` batch-inference half of BASELINE config 5 ("Llama LoRA
    fine-tune via XlaRunner + registerUDF batch inference").

    The column holds int token-id lists (prompts). Rows are grouped by
    prompt length and each group decodes as ONE compiled KV-cache program
    (prefill + lax.scan) — two XLA programs per distinct prompt length.
    """
    import jax
    import numpy as np

    from ..models.llama import generate

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        import pandas as pd
        pdf = df.toPandas()
        prompts = [np.asarray(p, dtype=np.int32)
                   for p in pdf[inputCol].to_list()]
        out: list = [None] * len(prompts)
        by_len: dict[int, list[int]] = {}
        for i, p in enumerate(prompts):
            if len(p) == 0:
                raise ValueError(
                    f"{inputCol!r} row {i} is an empty prompt; every row "
                    f"needs at least one token id")
            by_len.setdefault(len(p), []).append(i)
        # One compiled decode program for ALL groups: fix the cache size
        # (pad_to) and pad each group's batch to a common row count with
        # repeated rows (discarded after). Prefill still compiles once per
        # distinct prompt length — inherent without attention masks.
        pad_to = max(by_len) + max_new_tokens if by_len else 0
        batch_rows = max(len(v) for v in by_len.values()) if by_len else 0
        rng = jax.random.PRNGKey(seed)
        for _, idxs in sorted(by_len.items()):
            batch = np.stack([prompts[i] for i in idxs])
            if len(idxs) < batch_rows:
                fill = np.repeat(batch[:1], batch_rows - len(idxs), axis=0)
                batch = np.concatenate([batch, fill], axis=0)
            rng, key = jax.random.split(rng)
            gen = np.asarray(generate(model, variables, batch,
                                      max_new_tokens,
                                      temperature=temperature, rng=key,
                                      pad_to=pad_to))
            for row, i in enumerate(idxs):
                out[i] = gen[row].tolist()
        pdf = pdf.copy()
        pdf[outputCol] = pd.Series(out, index=pdf.index)
        return DataFrame.fromPandas(pdf, numPartitions=df.numPartitions)

    _UDF_REGISTRY[name] = apply


def applyUDF(df: DataFrame, name: str, inputCol: str,
             outputCol: str) -> DataFrame:
    try:
        apply = _UDF_REGISTRY[name]
    except KeyError:
        raise ValueError(f"UDF {name!r} is not registered; available: "
                         f"{sorted(_UDF_REGISTRY)}") from None
    return apply(df, inputCol, outputCol)


def listUDFs() -> list[str]:
    return sorted(_UDF_REGISTRY)


def unregisterUDF(name: str) -> None:
    _UDF_REGISTRY.pop(name, None)
