"""UDF registry — named, reusable column functions over DataFrames.

Reference surface: ``registerKerasImageUDF(name, model, preprocessor)``
(``python/sparkdl/udf/keras_image_model.py``) + ``makeGraphUDF``
(``graph/tensorframes_udf.py``) registered TF graphs as Spark SQL UDFs
executed by TensorFrames in the JVM (SURVEY.md §2.1/§3.3). There is no JVM
and no SQL parser here; the equivalent contract is a process-global registry
of named batch functions applicable to any DataFrame column via
``applyUDF(df, name, inputCol, outputCol)`` — the same "register once, score
anywhere by name" workflow, executing as jitted XLA programs.
"""

from __future__ import annotations

from typing import Callable

from ..core.frame import DataFrame

_UDF_REGISTRY: dict[str, Callable[[DataFrame, str, str], DataFrame]] = {}


def registerUDF(name: str, fn: Callable, batchSize: int = 64,
                inputShape: tuple | None = None) -> None:
    """Register a jittable ``fn(batch)`` over numeric array columns."""
    from ..transformers.tensor import XlaTransformer

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        t = XlaTransformer(inputCol=inputCol, outputCol=outputCol, fn=fn,
                           batchSize=batchSize,
                           **({"inputShape": inputShape} if inputShape else {}))
        return t.transform(df)

    _UDF_REGISTRY[name] = apply


def registerImageUDF(name: str, fn: Callable, inputSize: tuple[int, int],
                     batchSize: int = 32, channelOrder: str = "RGB") -> None:
    """Register a jittable ``fn(nhwc_batch)`` over image-struct columns."""
    from ..transformers.xla_image import XlaImageTransformer

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        t = XlaImageTransformer(inputCol=inputCol, outputCol=outputCol, fn=fn,
                                inputSize=inputSize, batchSize=batchSize,
                                channelOrder=channelOrder)
        return t.transform(df)

    _UDF_REGISTRY[name] = apply


def registerKerasImageUDF(udf_name: str, keras_model_or_file,
                          preprocessor: Callable | None = None,
                          batchSize: int = 32) -> None:
    """The reference's flagship UDF: compose image-decode ∘ (preprocessor) ∘
    Keras model and register under ``udf_name``.

    ``keras_model_or_file``: a Keras-3 model object, a saved-model path, or a
    named model from SUPPORTED_MODELS (e.g. "InceptionV3" — random-init in
    this zero-egress environment). ``preprocessor`` is a jittable NHWC→NHWC
    function fused in front of the model inside the same XLA program.
    """
    from ..transformers.keras_utils import keras_model_to_fn

    if isinstance(keras_model_or_file, str):
        from ..models import SUPPORTED_MODELS, get_model
        if keras_model_or_file in SUPPORTED_MODELS:
            m = get_model(keras_model_or_file)
            variables = m.init_params()
            apply_model = m.apply_fn(features_only=False)
            base_fn = lambda b: apply_model(variables, b)
            input_hw = m.input_size
        else:
            from ..transformers.keras_utils import load_keras_model
            model = load_keras_model(keras_model_or_file)
            base_fn = keras_model_to_fn(model)
            shape = model.inputs[0].shape
            input_hw = (int(shape[1]), int(shape[2]))
    else:
        base_fn = keras_model_to_fn(keras_model_or_file)
        shape = keras_model_or_file.inputs[0].shape
        input_hw = (int(shape[1]), int(shape[2]))

    fn = (lambda b: base_fn(preprocessor(b))) if preprocessor else base_fn
    registerImageUDF(udf_name, fn, inputSize=input_hw, batchSize=batchSize)



def registerGenerationUDF(name: str, model, variables,
                          max_new_tokens: int = 32,
                          temperature: float = 0.0, seed: int = 0,
                          batchRows: int = 64, top_k: int = 0,
                          top_p: float = 1.0,
                          eos_id: int | None = None,
                          params_dtype: str | None = None) -> None:
    """Register a text-generation UDF over token-id columns — the
    ``registerUDF`` batch-inference half of BASELINE config 5 ("Llama LoRA
    fine-tune via XlaRunner + registerUDF batch inference").

    The column holds int token-id lists (prompts). The whole column is
    LEFT-padded to one length (``models.llama.left_pad_prompts``) and runs
    as exactly TWO compiled XLA programs however many distinct prompt
    lengths appear: one masked prefill (positions count from each row's
    first real token) + one while_loop/scan decode (EOS early exit) — no
    per-length recompiles. Rows are chunked to ``batchRows`` so a huge
    column doesn't build one giant cache; a short trailing chunk fills
    with duplicate rows (dropped from the output) so every chunk reuses
    the same two programs.

    ``params_dtype="bfloat16"`` casts the weight MATRICES to the serving
    dtype up front (``models.pretrained.cast_float_leaves``): decode is
    weight-HBM-bandwidth-bound, so halving the stored weight bytes is a
    direct decode-rate/footprint lever — numerically identical for the
    dense/embedding kernels (flax casts them at use anyway; 1-D norm
    scales stay f32 untouched); only the intentionally-f32 logits head
    sees bf16-rounded weights, the standard bf16-serving tradeoff.
    Default None keeps the caller's weights bit-exact.
    """
    _UDF_REGISTRY[name] = _make_generation_apply(
        model, variables, max_new_tokens=max_new_tokens,
        temperature=temperature, seed=seed, batchRows=batchRows,
        top_k=top_k, top_p=top_p, eos_id=eos_id,
        params_dtype=params_dtype)


def _make_generation_apply(model, variables, *, max_new_tokens: int = 32,
                           temperature: float = 0.0, seed: int = 0,
                           batchRows: int = 64, top_k: int = 0,
                           top_p: float = 1.0,
                           eos_id: int | None = None,
                           params_dtype: str | None = None) -> Callable:
    """Build (and validate) the apply closure behind
    :func:`registerGenerationUDF` — shared with
    :func:`registerTextGenerationUDF` so the padding/chunking/EOS
    semantics have one source of truth."""
    import jax
    import numpy as np

    from ..models.llama import generate, left_pad_prompts

    # fail at REGISTRATION, not on the first applyUDF call
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0 (0 disables), got {top_k}")
    if eos_id is not None and (isinstance(eos_id, bool)
                               or not isinstance(eos_id, (int, np.integer))):
        raise TypeError(f"eos_id must be an int token id or None, "
                        f"got {eos_id!r}")
    if params_dtype:
        from ..models.pretrained import cast_float_leaves
        variables = cast_float_leaves(variables, params_dtype)

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        import pyarrow as pa

        # per-call key stream: deterministic for a given seed, and no
        # state shared between concurrent applyUDF calls (reentrant)
        rng_box = [jax.random.PRNGKey(seed)]

        def compute(prompts, lmax, n_fill):
            ids, pads = left_pad_prompts(prompts, pad_to=lmax)
            n = len(ids)
            if n_fill:
                ids = np.concatenate(
                    [ids, np.repeat(ids[:1], n_fill, axis=0)])
                pads = np.concatenate(
                    [pads, np.repeat(pads[:1], n_fill, axis=0)])
            rng_box[0], key = jax.random.split(rng_box[0])
            gen = np.asarray(generate(
                model, variables, ids, max_new_tokens,
                temperature=temperature, rng=key,
                pad_to=lmax + max_new_tokens, pad_lens=pads,
                top_k=top_k, top_p=top_p, eos_id=eos_id))
            out: list = []
            for row in range(n):
                # strip this row's left pads: real prompt + new tokens
                toks = gen[row, pads[row]:].tolist()
                if eos_id is not None:
                    # trim the repeated-eos tail, keep one eos
                    plen = len(prompts[row])
                    gen_part = toks[plen:]
                    if eos_id in gen_part:
                        gen_part = gen_part[:gen_part.index(eos_id) + 1]
                    toks = toks[:plen] + gen_part
                out.append(toks)
            return pa.array(out, type=pa.list_(pa.int64()))

        return _streamed_token_apply(df, inputCol, outputCol, batchRows,
                                     compute, pa.list_(pa.int64()))

    return apply


def _streamed_token_apply(df: DataFrame, inputCol: str, outputCol: str,
                          batchRows: int, compute: Callable,
                          out_type) -> DataFrame:
    """Shared streamed data plane for token-id-column UDFs (generation,
    sequence classification) — round-3 verdict Next #5, one source of
    truth. The win is CHUNKED DEVICE COMPUTE — one compiled
    (batchRows, max_len) program signature and one batchRows-sized KV
    cache however large the column — not host-memory residency: the
    ``cache()`` below materializes pending-op output (the token column)
    in full on the host, and the final ``repartition`` assembles the
    whole output table once. Host-side the column is token ids (small);
    device-side nothing beyond one chunk is ever live.

    - pending upstream ops are cached ONCE (two passes must not run a
      tokenizer twice);
    - pass 1 walks the column in ``batchRows`` Arrow chunks reading
      LENGTHS only (validating every row is non-null and non-empty with
      its GLOBAL row index) to pin the column-wide max length — the one
      value every chunk must share for a single compiled signature;
    - pass 2 re-streams the chunks through ``compute(rows, max_len,
      n_fill) -> pa.Array`` (length == len(rows)); ``n_fill`` dummy
      duplicate rows keep a short chunk on the same compiled
      (batchRows, max_len) signature — compute appends and drops them.
      ``iterBatches`` erases partition boundaries, so only the FINAL
      chunk can be short; a column that fits in one sub-batchRows chunk
      is left unfilled (its single smaller signature is the only one
      compiled, and filling would pay batchRows of compute for n rows);
    - an empty column keeps the schema contract; the output restores the
      input's partition count (chunk layout is an implementation detail).
    """
    import numpy as np
    import pyarrow as pa
    import pyarrow.compute as pc

    from ..core.frame import _set_column

    if df._ops:
        df = df.cache()
    max_len = 0
    n_rows = 0
    for batch in df.iterBatches(batchRows):
        col = batch.column(inputCol)
        if col.null_count:
            bad = n_rows + next(i for i, v in enumerate(col.to_pylist())
                                if v is None)
            raise ValueError(
                f"{inputCol!r} row {bad} is null; every row needs at "
                f"least one token id")
        lens = pc.list_value_length(col).to_numpy(zero_copy_only=False)
        if len(lens) and int(lens.min()) == 0:
            bad = n_rows + int(np.argmin(lens))
            raise ValueError(
                f"{inputCol!r} row {bad} is an empty prompt; every row "
                f"needs at least one token id")
        n_rows += len(lens)
        if len(lens):
            max_len = max(max_len, int(lens.max()))

    if n_rows == 0:  # keep the schema contract on an empty column
        tbl = df.toArrow()
        empty = pa.array([], type=out_type)
        if outputCol in tbl.column_names:  # replace, like _set_column
            tbl = tbl.set_column(tbl.column_names.index(outputCol),
                                 outputCol, empty)
        else:
            tbl = tbl.append_column(outputCol, empty)
        return DataFrame.fromArrow(
            tbl, numPartitions=max(1, df.numPartitions))

    out_parts: list[pa.RecordBatch] = []
    for batch in df.iterBatches(batchRows):
        rows = batch.column(inputCol).to_pylist()
        n = len(rows)
        # fill ANY short chunk of a multi-chunk column (iterBatches: only
        # the last can be short) so every chunk shares one signature
        n_fill = batchRows - n if (n < batchRows
                                   and n_rows > batchRows) else 0
        out = compute(rows, max_len, n_fill)
        assert len(out) == n, f"compute returned {len(out)} for {n} rows"
        out_parts.append(_set_column(batch, outputCol, out))
    return DataFrame(out_parts).repartition(df.numPartitions)


def registerTextGenerationUDF(name: str, model, variables,
                              encode: Callable[[str], list],
                              decode: Callable[[list], str],
                              **gen_kwargs) -> None:
    """String-column twin of :func:`registerGenerationUDF`: the column
    holds TEXT prompts; ``encode``/``decode`` are the tokenizer halves
    (e.g. a HF tokenizer's ``tok.encode`` / ``tok.decode``). Tokenize →
    the streamed left-padded two-program generation → detokenize, all per
    ``batchRows`` chunk. Accepts every registerGenerationUDF keyword.
    """
    if not callable(encode) or not callable(decode):
        raise TypeError("encode and decode must be callables "
                        f"(got {encode!r}, {decode!r})")
    inner_apply = _make_generation_apply(model, variables, **gen_kwargs)

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        ids_col = f"__{name}_ids"
        out_ids = f"__{name}_out_ids"
        with_ids = df.withColumn(
            ids_col, lambda s: [int(t) for t in encode(s)], [inputCol])
        try:
            gen = inner_apply(with_ids, ids_col, out_ids)
        except ValueError as e:
            # surface the USER's column name, not the hidden ids column
            raise ValueError(
                str(e).replace(repr(ids_col), repr(inputCol))) from None
        # strip the prompt ids from each completion before decoding
        def detok(prompt_ids, completion_ids):
            return decode([int(t) for t in
                           completion_ids[len(prompt_ids):]])
        return gen.withColumn(outputCol, detok, [ids_col, out_ids]) \
                  .drop(ids_col, out_ids)

    _UDF_REGISTRY[name] = apply


def registerSequenceClassificationUDF(name: str, model, variables,
                                      batchRows: int = 64,
                                      pad_id: int = 0,
                                      params_dtype: str | None = None
                                      ) -> None:
    """Register an encoder-classifier UDF over token-id columns — the
    serving half of BASELINE config 4 (BERT fine-tune), mirroring the
    generation UDF's streamed data plane for the encoder family.

    The column holds int token-id lists. Rows stream in ``batchRows``
    Arrow chunks, RIGHT-padded to the column-wide max length with an
    attention mask (pad positions excluded from attention — the flash
    kv_mask contract on TPU), through ONE compiled program per
    (rows, maxLen) signature. Output: predicted class index per row.

    ``model``: a flax module whose ``apply(variables, input_ids,
    attention_mask)`` returns ``[B, num_classes]`` logits
    (``models.bert.BertForSequenceClassification`` is the shipped shape).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    if params_dtype:
        # serving-dtype weight cast — see registerGenerationUDF
        from ..models.pretrained import cast_float_leaves
        variables = cast_float_leaves(variables, params_dtype)

    @jax.jit
    def classify(ids, mask):
        return model.apply(variables, ids, mask).astype(jnp.float32)

    def compute(rows, max_len, n_fill):
        import pyarrow as pa
        n = len(rows)
        ids = np.full((n + n_fill, max_len), pad_id, np.int32)
        mask = np.zeros((n + n_fill, max_len), np.int32)
        for r, toks in enumerate(rows):
            ids[r, :len(toks)] = np.asarray(toks, np.int32)
            mask[r, :len(toks)] = 1
        if n_fill:
            ids[n:] = ids[0]
            mask[n:] = mask[0]
        logits = np.asarray(classify(ids, mask))[:n]
        return pa.array(logits.argmax(-1).astype("int64"))

    def apply(df: DataFrame, inputCol: str, outputCol: str) -> DataFrame:
        import pyarrow as pa
        return _streamed_token_apply(df, inputCol, outputCol, batchRows,
                                     compute, pa.int64())

    _UDF_REGISTRY[name] = apply


def applyUDF(df: DataFrame, name: str, inputCol: str,
             outputCol: str) -> DataFrame:
    try:
        apply = _UDF_REGISTRY[name]
    except KeyError:
        raise ValueError(f"UDF {name!r} is not registered; available: "
                         f"{sorted(_UDF_REGISTRY)}") from None
    return apply(df, inputCol, outputCol)


def listUDFs() -> list[str]:
    return sorted(_UDF_REGISTRY)


def unregisterUDF(name: str) -> None:
    _UDF_REGISTRY.pop(name, None)
