from .registry import (applyUDF, listUDFs, registerGenerationUDF,
                       registerImageUDF, registerKerasImageUDF,
                       registerTextGenerationUDF, registerUDF,
                       unregisterUDF)

__all__ = ["registerUDF", "registerImageUDF", "registerKerasImageUDF",
           "registerGenerationUDF", "registerTextGenerationUDF",
           "applyUDF", "listUDFs", "unregisterUDF"]
