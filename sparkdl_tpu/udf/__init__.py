from .registry import (applyUDF, listUDFs, registerGenerationUDF,
                       registerImageUDF, registerKerasImageUDF, registerUDF,
                       unregisterUDF)

__all__ = ["registerUDF", "registerImageUDF", "registerKerasImageUDF",
           "registerGenerationUDF", "applyUDF", "listUDFs", "unregisterUDF"]
