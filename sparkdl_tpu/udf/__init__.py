from .registry import (applyUDF, listUDFs, registerGenerationUDF,
                       registerImageUDF, registerKerasImageUDF,
                       registerSequenceClassificationUDF,
                       registerTextGenerationUDF, registerUDF,
                       unregisterUDF)

__all__ = ["registerUDF", "registerImageUDF", "registerKerasImageUDF",
           "registerGenerationUDF", "registerTextGenerationUDF",
           "registerSequenceClassificationUDF",
           "applyUDF", "listUDFs", "unregisterUDF"]
