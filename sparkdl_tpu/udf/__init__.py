from .registry import (applyUDF, listUDFs, registerImageUDF,
                       registerKerasImageUDF, registerUDF, unregisterUDF)

__all__ = ["registerUDF", "registerImageUDF", "registerKerasImageUDF",
           "applyUDF", "listUDFs", "unregisterUDF"]
