"""Image I/O: the image-struct schema and numpy converters.

Re-creates the behavior of the reference's image layer (expected upstream file
``python/sparkdl/image/imageIO.py`` + Scala ``ImageUtils.scala`` — SURVEY.md
§1-L1/§2.1: image struct schema ``(height, width, nChannels, mode, data)``,
bytes→struct decode, struct↔numpy conversion, resize, ``readImages*``).

TPU-first deltas from the reference design:
- The struct's ``data`` stays raw bytes in Arrow (one contiguous buffer per
  image); batch assembly goes straight from the Arrow binary column into one
  NHWC numpy array (``structsToNHWC``) that is handed to ``jax.device_put`` —
  the per-row Python object churn of the reference's UDF path never happens.
- At-rest layout matches Spark's ImageSchema: OpenCV mode codes AND OpenCV
  channel order — 3/4-channel image data is stored **BGR(A)**, so structs are
  interchangeable with Spark/reference-written data. The NHWC batch builders
  emit RGB by default (the convention every model preprocess here expects)
  and flip at the single batch-assembly point.
"""

from __future__ import annotations

import io
import os
from collections import namedtuple
from typing import Callable, Sequence

import numpy as np
import pyarrow as pa

ImageFields = ["origin", "height", "width", "nChannels", "mode", "data"]

# OpenCV type codes, as used by Spark's ImageSchema (and the reference's
# OCV-type mapping). dtype + channel count → code.
_OcvType = namedtuple("_OcvType", ["name", "ord", "nChannels", "dtype"])

_SUPPORTED_OCV_TYPES = (
    _OcvType(name="CV_8UC1", ord=0, nChannels=1, dtype="uint8"),
    _OcvType(name="CV_8UC3", ord=16, nChannels=3, dtype="uint8"),
    _OcvType(name="CV_8UC4", ord=24, nChannels=4, dtype="uint8"),
    _OcvType(name="CV_32FC1", ord=5, nChannels=1, dtype="float32"),
    _OcvType(name="CV_32FC3", ord=21, nChannels=3, dtype="float32"),
    _OcvType(name="CV_32FC4", ord=29, nChannels=4, dtype="float32"),
)
_OCV_BY_ORD = {t.ord: t for t in _SUPPORTED_OCV_TYPES}
_OCV_BY_KEY = {(t.dtype, t.nChannels): t for t in _SUPPORTED_OCV_TYPES}

imageSchema = pa.struct([
    ("origin", pa.string()),
    ("height", pa.int32()),
    ("width", pa.int32()),
    ("nChannels", pa.int32()),
    ("mode", pa.int32()),
    ("data", pa.binary()),
])


def _swapRB(arr: np.ndarray) -> np.ndarray:
    """Swap channels 0<->2 (BGR(A)<->RGB(A)), preserving alpha — the one
    channel-reorder convention used on every path (incl. the native packer),
    so results don't depend on which path ran."""
    if arr.shape[-1] < 3:
        return arr
    return np.concatenate([arr[..., 2::-1], arr[..., 3:]], axis=-1)


def ocvTypeByMode(mode: int) -> _OcvType:
    try:
        return _OCV_BY_ORD[mode]
    except KeyError:
        raise ValueError(f"Unsupported OpenCV image mode {mode}; supported: "
                         f"{sorted(_OCV_BY_ORD)}") from None


def imageArrayToStruct(array: np.ndarray, origin: str = "") -> dict:
    """HWC numpy array → image struct dict (Arrow-storable)."""
    if array.ndim == 2:
        array = array[:, :, None]
    if array.ndim != 3:
        raise ValueError(f"Expected HW or HWC array, got shape {array.shape}")
    h, w, c = array.shape
    key = (str(array.dtype), c)
    if key not in _OCV_BY_KEY:
        raise ValueError(f"Unsupported dtype/channels {key}; supported: "
                         f"{sorted(_OCV_BY_KEY)}")
    t = _OCV_BY_KEY[key]
    return {
        "origin": origin,
        "height": int(h),
        "width": int(w),
        "nChannels": int(c),
        "mode": t.ord,
        "data": np.ascontiguousarray(array).tobytes(),
    }


def imageStructToArray(struct: dict) -> np.ndarray:
    """Image struct dict → HWC numpy array (dtype per the mode's OCV type)."""
    t = ocvTypeByMode(struct["mode"])
    arr = np.frombuffer(struct["data"], dtype=t.dtype)
    expected = struct["height"] * struct["width"] * struct["nChannels"]
    if arr.size != expected:
        raise ValueError(
            f"Image data has {arr.size} elements, expected {expected} "
            f"({struct['height']}x{struct['width']}x{struct['nChannels']})")
    return arr.reshape(struct["height"], struct["width"], struct["nChannels"])


def decodeImage(data: bytes, origin: str = "") -> dict | None:
    """Compressed image bytes (PNG/JPEG/...) → image struct; None if undecodable
    (matching the reference's drop-bad-images behavior). Stored channel order
    is BGR(A), per the Spark/OpenCV at-rest convention."""
    from PIL import Image
    try:
        img = Image.open(io.BytesIO(data))
        img = _normalize_pil_mode(img)
        arr = np.asarray(img, dtype=np.uint8)
    except Exception:
        return None
    if arr.ndim == 3 and arr.shape[2] >= 3:
        arr = np.ascontiguousarray(_swapRB(arr))  # RGB(A) → BGR(A)
    return imageArrayToStruct(arr, origin=origin)


def _normalize_pil_mode(img):
    if img.mode in ("L",):
        return img
    if img.mode in ("RGBA", "P", "CMYK"):
        return img.convert("RGBA") if img.mode == "RGBA" else img.convert("RGB")
    if img.mode != "RGB":
        return img.convert("RGB")
    return img


def encodePng(struct: dict) -> bytes:
    from PIL import Image
    arr = imageStructToArray(struct)
    if arr.dtype != np.uint8:
        raise ValueError("encodePng requires uint8 image structs")
    if arr.shape[2] >= 3:
        arr = _swapRB(arr)  # stored BGR(A) → RGB(A) for PIL
    buf = io.BytesIO()
    Image.fromarray(arr.squeeze() if arr.shape[2] == 1 else arr).save(
        buf, format="PNG")
    return buf.getvalue()


def resizeImage(struct: dict, height: int, width: int) -> dict:
    """Bilinear resize of one image struct (PIL, uint8 path)."""
    from PIL import Image
    arr = imageStructToArray(struct)
    if arr.dtype != np.uint8:
        raise ValueError("resizeImage supports uint8 structs")
    img = Image.fromarray(arr.squeeze() if arr.shape[2] == 1 else arr)
    resized = np.asarray(img.resize((width, height), Image.BILINEAR),
                         dtype=np.uint8)
    if resized.ndim == 2:
        resized = resized[:, :, None]
    return imageArrayToStruct(resized, origin=struct.get("origin", ""))


def createResizeImageUDF(height: int, width: int):
    """Row-wise resize fn for ``DataFrame.withColumn`` — the reference's
    ``createResizeImageUDF(size)`` surface: register once, apply to any
    image-struct column. (Batch hot paths resize inside the packer /
    ``imageColumnToNHWC`` instead.)"""

    def resize(struct: dict) -> dict:
        return resizeImage(struct, height, width)

    return resize


def resizeImageBatchNHWC(batch: np.ndarray, height: int, width: int,
                         device: bool = False) -> np.ndarray:
    """Vectorized NHWC resize on device-bound data.

    Uses ``jax.image.resize`` (XLA gather-based bilinear) so resize fuses into
    the same compiled program as preprocessing — the reference instead resized
    row-at-a-time in a Spark UDF (SURVEY.md §3.1 step 2).

    The resize is jitted and shape-cached (``runtime.jit_resize_nhwc``):
    one compilation per (input shape, target), where the old bare
    ``jax.image.resize`` call re-traced its gather chain on EVERY call.
    ``device=True`` returns the device array as-is — callers feeding
    ``jax.device_put``/another jitted program skip the forced
    ``np.asarray`` host sync entirely.
    """
    from ..core.runtime import jit_resize_nhwc
    out = jit_resize_nhwc(height, width)(batch)
    return out if device else np.asarray(out)


def _narrowing_safe(img: np.ndarray, out_dtype) -> np.ndarray:
    """Guard float pixels entering a uint8 batch: numpy's unsafe cast would
    truncate-and-wrap silently (0.9→0, -1→255, 300→44); round+clip instead.
    Requesting uint8 output for float-mode images is still lossy — callers
    that must preserve float data should request dtype=float32."""
    if (np.dtype(out_dtype) == np.uint8
            and np.issubdtype(img.dtype, np.floating)):
        return np.clip(np.round(img), 0, 255)
    return img


def structsToNHWC(structs: Sequence[dict], height: int | None = None,
                  width: int | None = None, dtype=np.float32,
                  channelOrder: str = "RGB") -> np.ndarray:
    """Column of image structs → one contiguous NHWC batch array.

    Structs store BGR(A) at rest; ``channelOrder="RGB"`` (default) flips to
    the model convention here, at the single batch-assembly point. Mixed sizes
    are resized (PIL) to (height, width); if not given, all images must share
    one shape.
    """
    if not structs:
        raise ValueError("empty image column")
    first = structs[0]
    h = height if height is not None else first["height"]
    w = width if width is not None else first["width"]
    c = first["nChannels"]
    flip = channelOrder.upper() == "RGB" and c >= 3
    if all(s["nChannels"] == c for s in structs):
        packed = _native_pack_or_none(
            lambda: [s["data"] for s in structs],
            [s["height"] for s in structs], [s["width"] for s in structs],
            [s["mode"] for s in structs], c, h, w, flip, dtype)
        if packed is not None:
            return packed
    out = np.empty((len(structs), h, w, c), dtype=dtype)
    for i, s in enumerate(structs):
        if s["nChannels"] != c:
            raise ValueError(f"Row {i}: channel mismatch {s['nChannels']} != {c}")
        if s["height"] != h or s["width"] != w:
            s = resizeImage(s, h, w)
        arr = imageStructToArray(s)
        out[i] = _narrowing_safe(_swapRB(arr) if flip else arr, out.dtype)
    return out


def imageColumnToNHWC(column: pa.Array, height: int | None = None,
                      width: int | None = None, dtype=np.float32,
                      channelOrder: str = "RGB") -> np.ndarray:
    """Arrow struct column → NHWC batch, reading the struct's child arrays
    directly (no per-row Python dict materialization on this hot boundary).

    Uniform-size rows are filled via zero-copy ``np.frombuffer`` views of the
    Arrow binary buffers; only rows needing a resize detour through PIL.
    """
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    n = len(column)
    if n == 0:
        raise ValueError("empty image column")
    heights = column.field("height").to_numpy(zero_copy_only=False)
    widths = column.field("width").to_numpy(zero_copy_only=False)
    chans = column.field("nChannels").to_numpy(zero_copy_only=False)
    modes = column.field("mode").to_numpy(zero_copy_only=False)
    data = column.field("data")
    h = int(height) if height is not None else int(heights[0])
    w = int(width) if width is not None else int(widths[0])
    c = int(chans[0])
    if not (chans == c).all():
        raise ValueError(f"Mixed channel counts in image column: "
                         f"{sorted(set(chans.tolist()))}")
    flip = channelOrder.upper() == "RGB" and c >= 3
    if _pack_gate(modes, dtype):
        from .. import native
        packed = _arrow_ptr_pack_or_none(data, heights, widths, c, h, w,
                                         flip, dtype)
        if packed is None:  # exotic layout — per-row buffer path
            packed = native.pack_images(
                [data[i].as_buffer() for i in range(n)], heights, widths,
                c, h, w, flip_bgr=flip, dtype=dtype)
        if packed is not None:
            return packed
    out = np.empty((n, h, w, c), dtype=dtype)
    for i in range(n):
        src_dtype = ocvTypeByMode(int(modes[i])).dtype
        view = np.frombuffer(data[i].as_buffer(), dtype=src_dtype)
        if heights[i] == h and widths[i] == w:
            img = view.reshape(h, w, c)
        else:
            struct = {"height": int(heights[i]), "width": int(widths[i]),
                      "nChannels": c, "mode": int(modes[i]),
                      "data": view.tobytes()}
            img = imageStructToArray(resizeImage(struct, h, w))
        out[i] = _narrowing_safe(_swapRB(img) if flip else img, out.dtype)
    return out


def imageColumnUniformSize(column: pa.Array) -> tuple | None:
    """``(height, width, nChannels, mode)`` when EVERY row of the
    image-struct column stores the same values and no row is null — the
    METADATA-ONLY precondition of :func:`imageColumnNHWCView` (int-field
    reads, no buffer-layout inspection, no pixel work). Callers use it to
    decide a feed policy for a chunk without decoding it (the wire-shape
    cap in ``XlaImageTransformer``)."""
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    n = len(column)
    if n == 0 or column.null_count:
        return None
    try:
        heights = column.field("height").to_numpy(zero_copy_only=False)
        widths = column.field("width").to_numpy(zero_copy_only=False)
        chans = column.field("nChannels").to_numpy(zero_copy_only=False)
        modes = column.field("mode").to_numpy(zero_copy_only=False)
    except (KeyError, pa.ArrowInvalid):
        return None
    h, w, c, mode = (int(heights[0]), int(widths[0]), int(chans[0]),
                     int(modes[0]))
    if not ((heights == h).all() and (widths == w).all()
            and (chans == c).all() and (modes == mode).all()):
        return None
    return h, w, c, mode


def imageColumnNHWCView(column: pa.Array,
                        uniform: tuple | None = None) -> np.ndarray | None:
    """ZERO-COPY NHWC view over a uniform image-struct column.

    When every row stores the same (height, width, nChannels, mode) and
    the binary child's rows sit back-to-back (no nulls, uniform lengths —
    the layout every writer here produces), the Arrow values buffer IS an
    NHWC batch: one ``np.frombuffer`` reshape, no per-row work, no copy.
    Returns the **storage-dtype, at-rest BGR(A)** view (read-only — it
    aliases the immutable Arrow buffer), or ``None`` whenever any layout
    precondition fails, in which case the caller takes a packing path.

    ``uniform``: a precomputed :func:`imageColumnUniformSize` result for
    this exact column — skips the metadata re-scan on the hot path (the
    wire-shape budget in ``XlaImageTransformer`` already ran it).

    This is the host-ingest fast path (ISSUE 7): decode cost for a
    uniform uint8 column drops to ~zero, and the view flows straight into
    ``device_put`` with channel-flip/cast/resize fused into the jitted
    program (``BatchRunner(preprocess=...)``).
    """
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    meta = uniform if uniform is not None else imageColumnUniformSize(column)
    if meta is None:
        return None
    h, w, c, mode = meta
    n = len(column)
    try:
        data = column.field("data")
    except (KeyError, pa.ArrowInvalid):
        return None
    if mode not in _OCV_BY_ORD:
        return None  # let the packing path raise its informative error
    dt = np.dtype(_OCV_BY_ORD[mode].dtype)
    if pa.types.is_binary(data.type):
        off_dtype = np.dtype(np.int32)
    elif pa.types.is_large_binary(data.type):
        off_dtype = np.dtype(np.int64)
    else:
        return None
    if data.null_count:
        return None
    bufs = data.buffers()
    offsets = np.frombuffer(
        bufs[1], dtype=off_dtype, count=n + 1,
        offset=data.offset * off_dtype.itemsize)
    row_bytes = h * w * c * dt.itemsize
    if not (np.diff(offsets) == row_bytes).all():
        return None
    view = np.frombuffer(
        bufs[2], dtype=dt, count=n * h * w * c,
        offset=int(offsets[0])).reshape(n, h, w, c)
    view.flags.writeable = False  # aliases Arrow memory — never mutate
    return view


def imageColumnFeed(column: pa.Array, height: int, width: int,
                    dtype=np.float32, channelOrder: str = "RGB",
                    fused: bool = True, native_ok: bool = True,
                    uniform: tuple | None = None) -> np.ndarray:
    """Feed-side decode policy for the streaming scorer (ISSUE 7).

    ``fused=True`` (the ``SPARKDL_FUSED_PREPROCESS`` default) pairs with a
    jitted preprocess prologue that does flip/cast/resize on device, so
    the host ships the cheapest batch that policy allows:

    - a uniform column whose stored size is ≤ the target's pixel count
      returns the ZERO-COPY storage-dtype **BGR** view at its native size
      (fewer or equal bytes over the wire than a target-size batch, zero
      host pixel math; the device upsamples);
    - anything else (mixed sizes, nulls, stored > target — downsampling
      on device would INFLATE wire bytes, fatal on a ~40 MB/s tunnel)
      packs to the target size in ``dtype``, still **BGR** — the prologue
      owns the flip either way, so every chunk of a stream agrees.

    ``fused=False`` is the legacy host path: pack to target size in
    ``dtype`` with ``channelOrder`` applied on the host.

    Single-row columns always pack: the quarantine row-fallback re-decodes
    a failed chunk one row at a time, and a 1-row slice is trivially
    "uniform" — shipping it at native size would make every mixed-size
    row's shape deviate from the fallback's modal shape and dead-letter
    valid rows (the chunk view path cannot raise, so a fallback only ever
    follows a failed PACK — packing the rows matches it). Costs at most
    one extra wire shape for a legitimate 1-row tail chunk.

    ``native_ok=False`` forces the fused PACK path even for a shippable
    uniform column — the caller's wire-shape budget said no (every
    distinct native size is one XLA compilation; ``XlaImageTransformer``
    caps how many a stage may introduce, ``SPARKDL_MAX_WIRE_SHAPES``).
    ``uniform``: precomputed metadata for this column, forwarded to
    :func:`imageColumnNHWCView` so the uniform-size scan runs once.
    """
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    if fused:
        if native_ok and len(column) > 1:
            view = imageColumnNHWCView(column, uniform=uniform)
            if view is not None and \
                    view.shape[1] * view.shape[2] <= int(height) * int(width):
                return view
        return imageColumnToNHWC(column, height, width, dtype=dtype,
                                 channelOrder="BGR")
    return imageColumnToNHWC(column, height, width, dtype=dtype,
                             channelOrder=channelOrder)


def _pack_gate(modes, dtype) -> bool:
    """THE native-packer eligibility gate (one copy: both the struct-list
    and Arrow column paths consult it): supported output dtype, not
    disabled by env, all rows uint8-moded. NB: the pure-python fallback
    resizes through uint8 (PIL), so resized values can differ from the
    native float path by <1 level — native.py logs once when the library
    is unavailable."""
    if (np.dtype(dtype) not in (np.dtype(np.float32), np.dtype(np.uint8))
            or os.environ.get("SPARKDL_TPU_NATIVE", "1") == "0"
            or not all(ocvTypeByMode(int(m)).dtype == "uint8"
                       for m in modes)):
        return False
    from .. import native
    return native.available()


def _native_pack_or_none(buffers_fn, heights, widths, modes, c, h, w, flip,
                         dtype):
    """Struct-list entry to the native packer (C++: threaded resize +
    channel flip + u8→f32/u8 in one pass; the TensorFrames-JNI-equivalent
    role, SURVEY.md §2.3). None ⇒ caller takes the pure-python path.
    ``buffers_fn`` defers per-row buffer materialization until the gate
    has passed."""
    if not _pack_gate(modes, dtype):
        return None
    from .. import native
    return native.pack_images(buffers_fn(), heights, widths, c, h, w,
                              flip_bgr=flip, dtype=dtype)


def _arrow_ptr_pack_or_none(data: pa.Array, heights, widths, c, h, w,
                            flip, dtype):
    """Zero-copy Arrow fast path: source addresses come straight from the
    binary child's values buffer + offsets — no per-row buffer objects
    and no per-row ctypes casts, which cost ~30% of wall time on the
    per-row path at 299x299. Caller has already passed ``_pack_gate``;
    this adds only LAYOUT checks, returning None for layouts it doesn't
    cover (nulls, non-binary storage); size mismatches raise, matching
    pack_images' contract."""
    from .. import native

    if pa.types.is_binary(data.type):
        off_dtype = np.dtype(np.int32)
    elif pa.types.is_large_binary(data.type):
        off_dtype = np.dtype(np.int64)
    else:
        return None
    if data.null_count:
        return None
    bufs = data.buffers()
    offsets = np.frombuffer(
        bufs[1], dtype=off_dtype, count=len(data) + 1,
        offset=data.offset * off_dtype.itemsize).astype(np.int64)
    lens = np.diff(offsets)
    expected = (np.asarray(heights, np.int64)
                * np.asarray(widths, np.int64) * c)
    if not (lens == expected).all():
        i = int(np.argmax(lens != expected))
        raise ValueError(
            f"Image {i}: buffer has {lens[i]} bytes, expected "
            f"{heights[i]}x{widths[i]}x{c}")
    ptrs = np.uint64(bufs[2].address) + offsets[:-1].astype(np.uint64)
    return native.pack_images_ptrs(ptrs, heights, widths, c, h, w,
                                   flip_bgr=flip, dtype=dtype)


def nhwcToImageColumn(batch: np.ndarray,
                      origins: Sequence[str] | None = None,
                      channelOrder: str = "RGB",
                      copy: bool = True) -> pa.StructArray:
    """Vectorized NHWC batch → image struct COLUMN (the write-side twin
    of :func:`imageColumnToNHWC`): the whole batch becomes one contiguous
    Arrow values buffer with arithmetic offsets — no per-row dict/bytes
    objects, whose GIL-bound assembly caps out around 4k rows/s however
    many host cores exist. Same conventions as :func:`nhwcToStructs`:
    input is RGB by default, stored structs are BGR at rest.

    ``copy=False`` skips the defensive copy when no channel swap is
    needed, zero-copy-wrapping the CALLER'S buffer — only for callers
    that never mutate ``batch`` afterwards (mutating it would silently
    corrupt the column's supposedly immutable data)."""
    src = np.asarray(batch)
    if src.ndim != 4:
        raise ValueError(f"Expected NHWC batch, got shape {src.shape}")
    n, h, w, c = src.shape
    key = (str(src.dtype), c)
    if key not in _OCV_BY_KEY:
        raise ValueError(f"Unsupported dtype/channels {key}; supported: "
                         f"{sorted(_OCV_BY_KEY)}")
    t = _OCV_BY_KEY[key]
    if channelOrder.upper() == "RGB" and c >= 3:
        batch = np.ascontiguousarray(_swapRB(src))  # new owned array
    else:
        batch = np.ascontiguousarray(src)
        if copy and batch is src:
            # ascontiguousarray was a no-op: without this copy the Arrow
            # column would alias the caller's mutable buffer
            batch = batch.copy()
    row_nbytes = h * w * c * batch.itemsize
    total = n * row_nbytes
    if total > 2**31 - 1:
        raise ValueError(
            f"batch is {total} bytes — exceeds the int32 offsets of the "
            f"image column's binary storage; convert in chunks")
    offsets = (np.arange(n + 1, dtype=np.int32) * row_nbytes)
    data = pa.Array.from_buffers(
        pa.binary(), n,
        [None, pa.py_buffer(offsets), pa.py_buffer(batch)], null_count=0)
    const = lambda v: pa.array(np.full(n, v, dtype=np.int32))
    origin_arr = pa.array(
        [""] * n if origins is None else list(origins), type=pa.string())
    if len(origin_arr) != n:
        raise ValueError(f"{len(origin_arr)} origins for {n} rows")
    return pa.StructArray.from_arrays(
        [origin_arr, const(h), const(w), const(c), const(t.ord), data],
        fields=list(imageSchema))


def nhwcToStructs(batch: np.ndarray, origins: Sequence[str] | None = None,
                  channelOrder: str = "RGB") -> list[dict]:
    """NHWC batch → image structs. Input is RGB by default (the model
    convention); stored structs are BGR per the at-rest convention."""
    origins = origins or [""] * len(batch)
    flip = channelOrder.upper() == "RGB" and batch.shape[-1] >= 3
    return [imageArrayToStruct(
        np.ascontiguousarray(_swapRB(np.asarray(img))) if flip
        else np.asarray(img), origin=o)
        for img, o in zip(batch, origins)]


# ---------------------------------------------------------------------------
# Readers (reference: readImages / readImagesWithCustomFn)
# ---------------------------------------------------------------------------

_IMAGE_EXTENSIONS = {".jpg", ".jpeg", ".png", ".gif", ".bmp", ".webp"}

_POOL = None
_POOL_LOCK = __import__("threading").Lock()


def _decode_pool():
    """ONE process-wide decode executor shared by every reader DataFrame —
    a per-reader pool would pin its threads for the reader's lifetime and
    accumulate across many readImages calls in a long-lived driver."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            from concurrent.futures import ThreadPoolExecutor
            _POOL = ThreadPoolExecutor(
                max_workers=min(os.cpu_count() or 1, 16),
                thread_name_prefix="sparkdl-decode")
        return _POOL


def _list_image_files(path: str, recursive: bool = True) -> list[str]:
    if os.path.isfile(path):
        return [path]
    files = []
    for root, dirs, names in os.walk(path):
        dirs.sort()  # deterministic walk order — seeded sampleRatio
        # draws the same files on every filesystem
        for n in sorted(names):
            if os.path.splitext(n)[1].lower() in _IMAGE_EXTENSIONS:
                files.append(os.path.join(root, n))
        if not recursive:
            break
    return files


def readImages(path: str, numPartitions: int = 1,
               dropImageFailures: bool = True, sampleRatio: float = 1.0,
               seed: int = 42):
    """Directory/file of images → DataFrame[image: imageSchema].

    Reference behavior: ``readImages`` returns a DataFrame with an ``image``
    struct column, silently dropping undecodable files when asked;
    ``sampleRatio`` takes a seeded random fraction of the file listing
    (the reference's large-directory sampling knob).

    LAZY: only file *URIs* are enumerated here; decode runs inside a
    row-wise DataFrame op at materialization time, so scoring N images
    through a downstream transformer holds O(batchSize) decoded pixels in
    host memory, never the whole dataset (the BASELINE "batch-scores 1M
    images" north star; round-1 verdict item 4).
    """
    # decodeImage (PIL) is thread-safe → pooled decode (decodeWorkers=0).
    return readImagesWithCustomFn(path, decode_fn=decodeImage,
                                  numPartitions=numPartitions,
                                  dropImageFailures=dropImageFailures,
                                  decodeWorkers=0,
                                  sampleRatio=sampleRatio, seed=seed)


def readImagesWithCustomFn(path: str, decode_fn: Callable[[bytes, str], dict | None],
                           numPartitions: int = 1,
                           dropImageFailures: bool = True,
                           decodeWorkers: int = 1,
                           sampleRatio: float = 1.0, seed: int = 42):
    """``decodeWorkers``: 1 (default) keeps the historical SEQUENTIAL
    contract — a custom ``decode_fn`` may use shared mutable state. Pass 0
    (auto: min(cpu_count, 16)) or N>1 to fan decode over a thread pool;
    ``decode_fn`` must then be thread-safe (the built-in PIL decoder is —
    ``readImages`` uses the pooled path)."""
    from ..core.frame import DataFrame
    if not 0.0 < sampleRatio <= 1.0:
        raise ValueError(f"sampleRatio must be in (0, 1], got {sampleRatio}")
    files = _list_image_files(path)
    if not files:
        raise FileNotFoundError(f"No image files under {path!r}")
    if sampleRatio < 1.0:
        # seeded per-file Bernoulli over the sorted listing — stable for a
        # fixed seed regardless of numPartitions
        rng = np.random.RandomState(seed)
        keep = rng.random_sample(len(files)) < sampleRatio
        files = [f for f, k in zip(files, keep) if k]
        if not files:
            raise ValueError(
                f"sampleRatio={sampleRatio} over {int(keep.size)} files "
                f"sampled zero rows (seed={seed}); raise the ratio or "
                f"change the seed")
    workers = (min(os.cpu_count() or 1, 16) if decodeWorkers == 0
               else max(1, decodeWorkers))

    # Closure counters: the single-process data plane applies ops
    # sequentially, so once every listed file has been seen with zero
    # successful decodes we can reproduce the eager reader's loud
    # "all files failed" error instead of silently yielding 0 rows.
    progress = {"seen": 0, "ok": 0}

    def read_one(uri: str):
        """Runs on a pool thread (file IO + PIL decode release the GIL);
        OSError is carried back as a value so ordering/error policy stays
        on the consumer side."""
        try:
            with open(uri, "rb") as fh:
                return decode_fn(fh.read(), uri)
        except OSError as e:
            return e

    def decode_wave(uris):
        """Decode URIs in bounded waves so dropImageFailures=False still
        fails fast — a bad first file can't trigger the decode of a whole
        512-row batch before the error surfaces.

        decodeWorkers=0 (auto, the readImages default — thread-safe PIL
        decode) rides the process-wide shared executor. An EXPLICIT
        decodeWorkers=N gets a dedicated pool of exactly N threads for
        this batch (the caller's concurrency contract for decode fns that
        are only N-thread-safe or memory-budgeted), shut down after.
        """
        if workers == 1 or len(uris) <= 1:
            for u in uris:
                yield u, read_one(u)
            return
        if decodeWorkers == 0:
            pool = _decode_pool()  # shared, min(cpu_count, 16) threads
            wave = 2 * (os.cpu_count() or 1)
            for start in range(0, len(uris), wave):
                chunk = uris[start:start + wave]
                yield from zip(chunk, pool.map(read_one, chunk))
            return
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=workers) as pool:
            wave = 2 * workers
            for start in range(0, len(uris), wave):
                chunk = uris[start:start + wave]
                yield from zip(chunk, pool.map(read_one, chunk))

    def decode_op(batch: pa.RecordBatch) -> pa.RecordBatch:
        uris = batch.column("_uri").to_pylist()
        structs = []
        for uri, s in decode_wave(uris):
            progress["seen"] += 1
            if isinstance(s, OSError):
                if dropImageFailures:
                    s = None
                else:
                    # dropImageFailures=False exists to surface problems:
                    # an unreadable file raises, it does not become a
                    # placeholder row.
                    raise s
            if s is None:
                if dropImageFailures:
                    continue
                s = {"origin": uri, "height": -1, "width": -1,
                     "nChannels": -1, "mode": -1, "data": b""}
            else:
                progress["ok"] += 1
            structs.append(s)
        if (dropImageFailures and progress["seen"] >= len(files)
                and progress["ok"] == 0):
            raise ValueError(f"All {len(files)} image files failed to decode")
        return pa.RecordBatch.from_arrays(
            [pa.array(structs, type=imageSchema)], names=["image"])

    # Row-wise: each output row depends only on its own input row, so the
    # streaming materializer may apply it per sub-partition chunk.
    decode_op._row_wise = True
    decode_op._changes_length = dropImageFailures

    uris = DataFrame.fromPydict({"_uri": files},
                                numPartitions=numPartitions)
    return uris.mapBatches(decode_op)
