from .imageIO import (decodeImage, encodePng, imageArrayToStruct,
                      imageColumnToNHWC, imageSchema, imageStructToArray,
                      nhwcToStructs, readImages, readImagesWithCustomFn,
                      resizeImage, resizeImageBatchNHWC, structsToNHWC)

__all__ = [
    "imageSchema", "imageArrayToStruct", "imageStructToArray", "decodeImage",
    "encodePng", "resizeImage", "resizeImageBatchNHWC", "structsToNHWC",
    "imageColumnToNHWC", "nhwcToStructs", "readImages",
    "readImagesWithCustomFn",
]
