"""ctypes binding for the native batch packer (native/packing.cpp).

The reference's partition-batch data path ran through TensorFrames' JNI
bridge into TF C++ (SURVEY.md §2.3); here the in-tree native component is
``libsparkdl_native.so``: multithreaded resize + channel-reorder + uint8→f32
NHWC packing, producing the host batch that ``jax.device_put`` ships to HBM.

``pack_images``/``pack_batch`` transparently fall back to numpy/PIL when the
shared library hasn't been built (``ensure_built`` compiles it with g++ on
first use; pybind11 is unavailable in this image, hence the C ABI).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Sequence

import numpy as np

_log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libsparkdl_native.so")

_lock = threading.RLock()  # reentrant: _load holds it while calling ensure_built
_lib = None
_build_failed = False


def ensure_built() -> bool:
    """Compile the .so if missing/stale. Returns availability.

    Thread-safe: the build runs under ``_lock`` so concurrent first-use from
    multiple threads cannot race two ``make`` processes, and success is only
    reported after re-checking that the .so actually exists (make exiting 0
    with no artifact — e.g. a stale Makefile target — must not be trusted)."""
    global _build_failed
    src = os.path.join(_NATIVE_DIR, "packing.cpp")
    if not os.path.exists(src):
        return os.path.exists(_SO_PATH)

    def fresh() -> bool:
        return (os.path.exists(_SO_PATH)
                and os.path.getmtime(_SO_PATH) >= os.path.getmtime(src))

    if fresh():
        return True
    with _lock:
        if fresh():          # another thread built it while we waited
            return True
        if _build_failed:
            return False
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
            if not fresh():
                raise OSError("make succeeded but produced no "
                              f"{os.path.basename(_SO_PATH)}")
            return True
        except (subprocess.SubprocessError, OSError) as e:
            _build_failed = True
            # Loud once: the PIL fallback resizes through uint8, so resized
            # batches differ (<1 level per value) from native-built hosts.
            _log.warning(
                "sparkdl_tpu native packer build failed (%s); using the "
                "pure-python fallback — resized image batches will differ "
                "slightly from native-enabled hosts", e)
            return False


_lib_failed = False  # loaded but unusable (ABI mismatch) — don't re-dlopen


def _load():
    global _lib, _lib_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            return None
        if not ensure_built():
            return None
        lib = ctypes.CDLL(_SO_PATH)
        lib.sdl_abi_version.restype = ctypes.c_int
        if lib.sdl_abi_version() != 2:
            # Cache the mismatch: without this every pack call would redo
            # dlopen+probe on the hot path, silently, forever.
            _lib_failed = True
            _log.warning(
                "libsparkdl_native.so has ABI %d (want 2) — prebuilt "
                "library is stale; using the pure-python fallback",
                lib.sdl_abi_version())
            return None
        _common = [
            ctypes.POINTER(ctypes.c_void_p),           # srcs
            ctypes.POINTER(ctypes.c_int32),            # heights
            ctypes.POINTER(ctypes.c_int32),            # widths
            ctypes.c_int32, ctypes.c_int32,            # n, c
        ]
        _tail = [
            ctypes.c_int32, ctypes.c_int32,            # out_h, out_w
            ctypes.c_int32,                            # flip_bgr
            ctypes.c_float, ctypes.c_float,            # scale, offset
            ctypes.c_int32,                            # n_threads
        ]
        lib.sdl_pack_images.restype = ctypes.c_int
        lib.sdl_pack_images.argtypes = (
            _common + [ctypes.POINTER(ctypes.c_float)] + _tail)
        lib.sdl_pack_images_u8.restype = ctypes.c_int
        lib.sdl_pack_images_u8.argtypes = (
            _common + [ctypes.POINTER(ctypes.c_uint8)] + _tail)
        lib.sdl_pack_batch.restype = ctypes.c_int
        lib.sdl_pack_batch.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_float, ctypes.c_float, ctypes.c_int32,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def pack_images(buffers: Sequence, heights: Sequence[int],
                widths: Sequence[int], channels: int, out_h: int, out_w: int,
                flip_bgr: bool = True, scale: float = 1.0,
                offset: float = 0.0, n_threads: int = 0,
                dtype=np.float32) -> np.ndarray:
    """Variable-size uint8 HWC image buffers → (N, out_h, out_w, C) batch.

    ``buffers``: per-image bytes-like objects (Arrow binary buffers, bytes,
    or uint8 arrays) each holding heights[i]*widths[i]*channels bytes.

    ``dtype``: float32 (default) or uint8. The uint8 output keeps the batch
    at 1 byte/sample so ``jax.device_put`` ships 4x fewer bytes over the
    host→HBM link; the on-device program casts to float (fused by XLA into
    its first consumer). Resize math still runs in float either way.
    """
    dtype = np.dtype(dtype)
    if dtype not in (np.dtype(np.float32), np.dtype(np.uint8)):
        raise TypeError(f"pack_images output dtype must be float32 or "
                        f"uint8, got {dtype}")
    n = len(buffers)
    out = np.empty((n, out_h, out_w, channels), dtype=dtype)
    if n == 0:
        return out
    for b in buffers:
        if isinstance(b, np.ndarray) and b.dtype != np.uint8:
            raise TypeError(
                f"pack_images takes raw uint8 buffers, got ndarray dtype "
                f"{b.dtype} (value-casting would silently truncate)")
    lib = _load()
    if lib is None:
        return _pack_images_numpy(buffers, heights, widths, channels, out,
                                  flip_bgr, scale, offset)
    arrays = [np.frombuffer(b, dtype=np.uint8) if not isinstance(b, np.ndarray)
              else np.ascontiguousarray(b).reshape(-1)
              for b in buffers]
    for i, a in enumerate(arrays):
        if a.size != heights[i] * widths[i] * channels:
            raise ValueError(
                f"Image {i}: buffer has {a.size} bytes, expected "
                f"{heights[i]}x{widths[i]}x{channels}")
    ptrs = np.fromiter((a.ctypes.data for a in arrays), dtype=np.uint64,
                       count=n)
    # `arrays` stays alive past the native call — the addresses in `ptrs`
    # borrow its buffers
    result = _dispatch_pack(lib, ptrs, heights, widths, channels, out,
                            out_h, out_w, flip_bgr, scale, offset,
                            n_threads)
    del arrays
    return result


def pack_images_ptrs(ptrs: np.ndarray, heights: Sequence[int],
                     widths: Sequence[int], channels: int, out_h: int,
                     out_w: int, flip_bgr: bool = True, scale: float = 1.0,
                     offset: float = 0.0, n_threads: int = 0,
                     dtype=np.float32):
    """Zero-copy twin of :func:`pack_images`: ``ptrs`` is a uint64 array
    of source ADDRESSES (e.g. an Arrow binary values-buffer base +
    offsets), passed to C as the ``const uint8_t**`` directly — no
    per-row buffer objects or ctypes casts on the hot path. The caller
    owns both the address validity and the per-row size check (the
    addresses carry no length). Returns None when the native library is
    unavailable (the caller holds the real buffers and picks its own
    fallback)."""
    dtype = np.dtype(dtype)
    if dtype not in (np.dtype(np.float32), np.dtype(np.uint8)):
        raise TypeError(f"pack_images_ptrs output dtype must be float32 "
                        f"or uint8, got {dtype}")
    lib = _load()
    if lib is None:
        return None
    out = np.empty((len(ptrs), out_h, out_w, channels), dtype=dtype)
    return _dispatch_pack(lib, ptrs, heights, widths, channels, out,
                          out_h, out_w, flip_bgr, scale, offset, n_threads)


def _dispatch_pack(lib, ptrs, heights, widths, channels, out, out_h, out_w,
                   flip_bgr, scale, offset, n_threads) -> np.ndarray:
    """One marshalling point for the sdl_pack_images* C ABI — both the
    buffer-list and address-array entries go through here, so ABI changes
    can't drift between them. ``out.dtype`` selects the u8/f32 entry."""
    n = len(ptrs)
    if n == 0:
        return out
    ptrs = np.ascontiguousarray(ptrs, dtype=np.uint64)
    hs = np.ascontiguousarray(heights, dtype=np.int32)
    ws = np.ascontiguousarray(widths, dtype=np.int32)
    if out.dtype == np.uint8:
        entry, ctype = lib.sdl_pack_images_u8, ctypes.c_uint8
    else:
        entry, ctype = lib.sdl_pack_images, ctypes.c_float
    rc = entry(
        ptrs.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)),
        hs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ws.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, channels, out.ctypes.data_as(ctypes.POINTER(ctype)),
        out_h, out_w, int(flip_bgr), float(scale), float(offset), n_threads)
    if rc != 0:
        raise ValueError(f"sdl_pack_images failed with code {rc}")
    return out


def pack_batch(batch: np.ndarray, out_h: int | None = None,
               out_w: int | None = None, flip_bgr: bool = False,
               scale: float = 1.0, offset: float = 0.0,
               n_threads: int = 0) -> np.ndarray:
    """(N, H, W, C) uint8 → (N, out_h, out_w, C) float32 in one native call."""
    batch = np.ascontiguousarray(batch, dtype=np.uint8)
    n, h, w, c = batch.shape
    oh, ow = out_h or h, out_w or w
    lib = _load()
    if lib is None:
        bufs = [batch[i] for i in range(n)]
        out = np.empty((n, oh, ow, c), dtype=np.float32)
        return _pack_images_numpy(bufs, [h] * n, [w] * n, c, out, flip_bgr,
                                  scale, offset)
    out = np.empty((n, oh, ow, c), dtype=np.float32)
    rc = lib.sdl_pack_batch(
        batch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n, h, w, c,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), oh, ow,
        int(flip_bgr), float(scale), float(offset), n_threads)
    if rc != 0:
        raise ValueError(f"sdl_pack_batch failed with code {rc}")
    return out


def _pack_images_numpy(buffers, heights, widths, channels, out, flip_bgr,
                       scale, offset) -> np.ndarray:
    """Pure-python fallback; PIL handles the resizes."""
    from PIL import Image
    n, oh, ow, c = out.shape
    for i in range(n):
        arr = np.frombuffer(buffers[i], dtype=np.uint8).reshape(
            heights[i], widths[i], channels)
        if flip_bgr and c >= 3:
            arr = np.concatenate([arr[..., 2::-1][..., :3], arr[..., 3:]],
                                 axis=-1)
        if (heights[i], widths[i]) != (oh, ow):
            img = Image.fromarray(arr.squeeze() if c == 1 else arr)
            arr = np.asarray(img.resize((ow, oh), Image.BILINEAR),
                             dtype=np.uint8)
            if arr.ndim == 2:
                arr = arr[:, :, None]
        vals = arr.astype(np.float32) * scale + offset
        if out.dtype == np.uint8:
            vals = np.clip(np.round(vals), 0, 255)
        out[i] = vals
    return out
