"""KerasImageFileEstimator — train a Keras model on an image-URI DataFrame.

Reference: ``python/sparkdl/estimators/keras_image_file_estimator.py``
(SURVEY.md §2.1, call stack §3.4): ``_getNumpyFeaturesAndLabels`` collected
*all* image URIs to the driver, materialized the full dataset as numpy, and
ran driver-side ``model.fit`` — a single-node bottleneck by design.

TPU-native inversion (SURVEY.md §7.7): the dataset is **streamed** — images
decode host-side per batch while the previous batch trains on the TPU
(prefetch overlap), through the same compiled SPMD step machinery as
XlaRunner (gradient allreduce inside the program, DP across all visible
chips). Keras 3 on the JAX backend provides ``stateless_call`` so the Keras
model trains as a pure jitted function; its weights never round-trip through
Python during the loop. ``fitMultiple`` (hyperparameter parallelism) comes
from the Estimator base class.
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterator

import numpy as np

from ..core.frame import DataFrame
from ..core.params import (HasBatchSize, HasInputCol, HasLabelCol,
                           HasOutputCol, HasSeed, Param, Params,
                           TypeConverters, keyword_only)
from ..core.pipeline import Estimator
from ..transformers.keras_image import KerasImageFileTransformer
from ..transformers.payloads import PicklesCallableParams


class KerasImageFileEstimator(PicklesCallableParams, Estimator, HasInputCol,
                              HasOutputCol, HasLabelCol, HasBatchSize,
                              HasSeed):
    """Fits ``modelFile`` on (URI, label) rows; returns a
    :class:`KerasImageFileTransformer` bound to the trained weights."""

    modelFile = Param(Params, "modelFile",
                      "path to a saved Keras model (.keras/.h5) to fine-tune",
                      TypeConverters.toString)
    imageLoader = Param(Params, "imageLoader",
                        "callable uri -> float32 array (loads AND "
                        "preprocesses)", TypeConverters.toCallable)
    epochs = Param(Params, "epochs", "passes over the dataset",
                   TypeConverters.toInt)
    learningRate = Param(Params, "learningRate", "optimizer learning rate",
                         TypeConverters.toFloat)
    optimizer = Param(Params, "optimizer", "optax optimizer name "
                      "(adam|sgd|adamw|rmsprop)", TypeConverters.toString)
    loss = Param(Params, "loss", "loss: sparse_categorical_crossentropy | "
                 "categorical_crossentropy | mse", TypeConverters.toString)
    dropLastBatch = Param(Params, "dropLastBatch",
                          "drop the trailing partial batch (keeps shapes "
                          "static; set False to pad-and-mask it)",
                          TypeConverters.toBoolean)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, labelCol=None,
                 modelFile=None, imageLoader=None, batchSize=None,
                 epochs=None, learningRate=None, optimizer=None, loss=None,
                 dropLastBatch=None, seed=None):
        super().__init__()
        self._setDefault(batchSize=32, epochs=1, learningRate=1e-3,
                         optimizer="adam",
                         loss="sparse_categorical_crossentropy",
                         dropLastBatch=False, seed=0, labelCol="label")
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, labelCol=None,
                  modelFile=None, imageLoader=None, batchSize=None,
                  epochs=None, learningRate=None, optimizer=None, loss=None,
                  dropLastBatch=None, seed=None):
        return self._set(**self._input_kwargs)

    # -- data plane --------------------------------------------------------

    def _batches(self, dataset: DataFrame, epochs: int) -> Iterator[dict]:
        """Stream (image, label, weight) batches; images decoded lazily per
        batch. The trailing partial batch is padded to the static batch size
        with zero-weight rows (or dropped when ``dropLastBatch``)."""
        in_col = self.getInputCol()
        label_col = self.getLabelCol()
        bs = self.getBatchSize()
        loader = self.getOrDefault(self.imageLoader)
        drop_last = self.getOrDefault(self.dropLastBatch)

        from ..transformers.keras_image import loadImageBatch

        for _ in range(epochs):
            for rb in dataset.iterBatches(bs):
                n = rb.num_rows
                if n == 0 or (drop_last and n < bs):
                    continue
                uris = rb.column(in_col).to_pylist()
                labels = np.asarray(rb.column(label_col).to_pylist())
                # thread-pool decode: every host core loads in parallel
                imgs = loadImageBatch(loader, uris).astype(np.float32)
                weight = np.ones((n,), np.float32)
                if n < bs:
                    pad = bs - n
                    imgs = np.concatenate(
                        [imgs, np.broadcast_to(imgs[:1],
                                               (pad,) + imgs.shape[1:])])
                    labels = np.concatenate(
                        [labels, np.broadcast_to(labels[:1],
                                                 (pad,) + labels.shape[1:])])
                    weight = np.concatenate([weight, np.zeros((pad,),
                                                              np.float32)])
                yield {"image": imgs, "label": labels, "weight": weight}

    # -- training ----------------------------------------------------------

    def _make_tx(self):
        import optax
        lr = self.getOrDefault(self.learningRate)
        name = self.getOrDefault(self.optimizer).lower()
        makers = {"adam": optax.adam, "sgd": optax.sgd, "adamw": optax.adamw,
                  "rmsprop": optax.rmsprop}
        if name not in makers:
            raise ValueError(f"Unknown optimizer {name!r}; "
                             f"one of {sorted(makers)}")
        return makers[name](lr)

    def _make_loss(self, model):
        """Weighted loss over keras stateless_call — the ``mutable=True``
        step contract (non-trainable vars = model_state)."""
        import jax.numpy as jnp
        name = self.getOrDefault(self.loss).lower()

        def per_example(y, logits):
            import optax
            if name == "sparse_categorical_crossentropy":
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), y.astype(jnp.int32))
            if name == "categorical_crossentropy":
                return optax.softmax_cross_entropy(
                    logits.astype(jnp.float32), y.astype(jnp.float32))
            if name == "mse":
                d = logits.astype(jnp.float32) - y.astype(jnp.float32)
                return d.reshape(d.shape[0], -1).mean(-1)
            raise ValueError(f"Unknown loss {name!r}")

        def loss_fn(params, model_state, _apply, batch):
            out, new_nt = model.stateless_call(
                params["trainable"], model_state["non_trainable"],
                batch["image"], training=True)
            le = per_example(batch["label"], out)
            w = batch["weight"]
            loss = (le * w).sum() / jnp.maximum(w.sum(), 1.0)
            return loss, {}, {"non_trainable": new_nt}

        return loss_fn

    def _fit(self, dataset: DataFrame) -> KerasImageFileTransformer:
        from ..runner import XlaRunner
        from ..transformers.keras_utils import load_keras_model

        model_file = self.getOrDefault(self.modelFile)
        model = load_keras_model(model_file)
        epochs = self.getOrDefault(self.epochs)
        bs = self.getBatchSize()
        n_rows = dataset.count()
        if n_rows == 0:
            raise ValueError("Cannot fit on an empty DataFrame")
        per_epoch = (n_rows // bs if self.getOrDefault(self.dropLastBatch)
                     else -(-n_rows // bs))
        num_steps = max(per_epoch, 1) * epochs

        params = {"trainable": [np.asarray(v.value)
                                for v in model.trainable_variables]}
        model_state = {"non_trainable": [np.asarray(v.value) for v in
                                         model.non_trainable_variables]}

        # background_iter: batch k+1 decodes on a feeder thread while the
        # compiled step runs batch k — the fit loop never blocks on decode.
        from ..core.runtime import background_iter
        res = XlaRunner(np=-1).run(lambda ctx: ctx.fit(
            loss_fn=self._make_loss(model), params=params,
            tx=self._make_tx(),
            data=background_iter(self._batches(dataset, epochs), maxsize=2),
            num_steps=num_steps, model_state=model_state, mutable=True,
            log_every=max(num_steps // 4, 1)))

        # Write trained weights back into the Keras model and persist it —
        # the returned transformer is self-contained (reference semantics:
        # the fitted transformer carries the trained model).
        for var, val in zip(model.trainable_variables,
                            res["state"].params["trainable"]):
            var.assign(np.asarray(val))
        for var, val in zip(model.non_trainable_variables,
                            res["state"].model_state["non_trainable"]):
            var.assign(np.asarray(val))
        out_dir = tempfile.mkdtemp(prefix="sparkdl_keras_fit_")
        trained_path = os.path.join(out_dir, "trained.keras")
        model.save(trained_path)

        return KerasImageFileTransformer(
            inputCol=self.getInputCol(), outputCol=self.getOutputCol(),
            modelFile=trained_path,
            imageLoader=self.getOrDefault(self.imageLoader),
            batchSize=bs)

    _pickled_params = ("imageLoader",)
