from .evaluation import (BinaryClassificationEvaluator,
                         MulticlassClassificationEvaluator,
                         RegressionEvaluator)
from .keras_image_file_estimator import KerasImageFileEstimator
from .logistic_regression import LogisticRegression, LogisticRegressionModel

__all__ = ["LogisticRegression", "LogisticRegressionModel",
           "KerasImageFileEstimator", "MulticlassClassificationEvaluator",
           "RegressionEvaluator", "BinaryClassificationEvaluator"]
