"""Concrete evaluators for tuning (Spark ML ``pyspark.ml.evaluation``
surface — the metric side of the reference's param-grid workflows).
Metrics compute on-host over collected columns: evaluation is
O(rows), not a device-bound op."""

from __future__ import annotations

import numpy as np

from ..core.params import (HasLabelCol, HasPredictionCol, Param, Params,
                           TypeConverters, keyword_only)
from ..core.pipeline import Evaluator


def _col(dataset, name) -> np.ndarray:
    return np.asarray(
        [r[name] for r in dataset.select(name).collect()])


class MulticlassClassificationEvaluator(Evaluator, HasLabelCol,
                                        HasPredictionCol):
    metricName = Param(Params, "metricName",
                       "accuracy | f1 | weightedPrecision | weightedRecall",
                       TypeConverters.toString)

    @keyword_only
    def __init__(self, labelCol=None, predictionCol=None, metricName=None):
        super().__init__()
        self._setDefault(labelCol="label", predictionCol="prediction",
                         metricName="accuracy")
        self._set(labelCol=labelCol, predictionCol=predictionCol,
                  metricName=metricName)

    def _evaluate(self, dataset) -> float:
        y = _col(dataset, self.getLabelCol()).astype(np.int64)
        p = _col(dataset, self.getPredictionCol()).astype(np.int64)
        metric = self.getOrDefault(self.metricName)
        if metric == "accuracy":
            return float((y == p).mean())
        classes = np.unique(np.concatenate([y, p]))
        stats = []
        for c in classes:
            tp = float(((p == c) & (y == c)).sum())
            fp = float(((p == c) & (y != c)).sum())
            fn = float(((p != c) & (y == c)).sum())
            prec = tp / (tp + fp) if tp + fp else 0.0
            rec = tp / (tp + fn) if tp + fn else 0.0
            f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
            stats.append((float((y == c).mean()), prec, rec, f1))
        if metric == "weightedPrecision":
            return sum(w * s for w, s, _, _ in stats)
        if metric == "weightedRecall":
            return sum(w * s for w, _, s, _ in stats)
        if metric == "f1":
            return sum(w * s for w, _, _, s in stats)
        raise ValueError(f"Unknown metricName {metric!r}")


class RegressionEvaluator(Evaluator, HasLabelCol, HasPredictionCol):
    metricName = Param(Params, "metricName", "rmse | mse | mae | r2",
                       TypeConverters.toString)

    @keyword_only
    def __init__(self, labelCol=None, predictionCol=None, metricName=None):
        super().__init__()
        self._setDefault(labelCol="label", predictionCol="prediction",
                         metricName="rmse")
        self._set(labelCol=labelCol, predictionCol=predictionCol,
                  metricName=metricName)

    def _evaluate(self, dataset) -> float:
        y = _col(dataset, self.getLabelCol()).astype(np.float64)
        p = _col(dataset, self.getPredictionCol()).astype(np.float64)
        err = y - p
        metric = self.getOrDefault(self.metricName)
        if metric == "mse":
            return float((err ** 2).mean())
        if metric == "rmse":
            return float(np.sqrt((err ** 2).mean()))
        if metric == "mae":
            return float(np.abs(err).mean())
        if metric == "r2":
            ss_res = float((err ** 2).sum())
            ss_tot = float(((y - y.mean()) ** 2).sum())
            return 1.0 - ss_res / ss_tot if ss_tot else 0.0
        raise ValueError(f"Unknown metricName {metric!r}")

    def isLargerBetter(self) -> bool:
        return self.getOrDefault(self.metricName) == "r2"


class BinaryClassificationEvaluator(Evaluator, HasLabelCol):
    """areaUnderROC via the rank statistic (equivalent to the
    Mann-Whitney U), over a probability/score column."""

    rawPredictionCol = Param(Params, "rawPredictionCol",
                             "score/probability column",
                             TypeConverters.toString)
    metricName = Param(Params, "metricName", "areaUnderROC",
                       TypeConverters.toString)

    @keyword_only
    def __init__(self, labelCol=None, rawPredictionCol=None,
                 metricName=None):
        super().__init__()
        self._setDefault(labelCol="label", rawPredictionCol="probability",
                         metricName="areaUnderROC")
        self._set(labelCol=labelCol, rawPredictionCol=rawPredictionCol,
                  metricName=metricName)

    def _evaluate(self, dataset) -> float:
        if self.getOrDefault(self.metricName) != "areaUnderROC":
            raise ValueError("Only areaUnderROC is supported")
        y = _col(dataset, self.getLabelCol()).astype(np.int64)
        raw = _col(dataset,
                   self.getOrDefault(self.rawPredictionCol))
        # accept scalar scores or per-class probability vectors (take P[1])
        score = (raw[:, -1] if raw.ndim == 2 else raw).astype(np.float64)
        pos, neg = score[y == 1], score[y != 1]
        if len(pos) == 0 or len(neg) == 0:
            return 0.5
        # tie-averaged ranks, vectorized: O(n log n)
        uniq, inv, counts = np.unique(score, return_inverse=True,
                                      return_counts=True)
        ends = np.cumsum(counts)                       # rank after each tie
        starts = ends - counts + 1                     # rank before each tie
        ranks = ((starts + ends) / 2.0)[inv]
        u = ranks[y == 1].sum() - len(pos) * (len(pos) + 1) / 2
        return float(u / (len(pos) * len(neg)))
