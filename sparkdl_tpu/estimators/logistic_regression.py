"""LogisticRegression — the shallow learner closing the transfer-learning loop.

BASELINE config 1 is ``Pipeline([DeepImageFeaturizer, LogisticRegression])``
on the featurizer's bottleneck vectors. The reference used Spark MLlib's
LogisticRegression (JVM L-BFGS); this one is a jitted optax training loop on
the TPU — full-batch softmax regression with L2, ``lax.scan`` over epochs so
the whole optimization is a single XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pyarrow as pa

from ..core.frame import DataFrame, _length_preserving, _set_column
from ..core.params import (HasLabelCol, HasPredictionCol, Param, Params,
                           TypeConverters, keyword_only)
from ..core.pipeline import Estimator, Model
from ..transformers.tensor import columnToNdarray


class _LRParams(Params):
    featuresCol = Param(Params, "featuresCol", "input feature-vector column",
                        TypeConverters.toString)
    maxIter = Param(Params, "maxIter", "training steps (full-batch)",
                    TypeConverters.toInt)
    stepSize = Param(Params, "stepSize", "learning rate",
                     TypeConverters.toFloat)
    regParam = Param(Params, "regParam", "L2 regularization",
                     TypeConverters.toFloat)
    probabilityCol = Param(Params, "probabilityCol",
                           "optional output column of class probabilities",
                           TypeConverters.toString)
    standardization = Param(Params, "standardization",
                            "standardize features before fitting (Spark MLlib "
                            "default; scaling is folded back into the coefs)",
                            TypeConverters.toBoolean)


class LogisticRegression(Estimator, _LRParams, HasLabelCol, HasPredictionCol):
    @keyword_only
    def __init__(self, featuresCol=None, labelCol=None, predictionCol=None,
                 probabilityCol=None, maxIter=None, stepSize=None,
                 regParam=None, standardization=None):
        super().__init__()
        self._setDefault(featuresCol="features", labelCol="label",
                         predictionCol="prediction", maxIter=100,
                         stepSize=0.1, regParam=0.0, standardization=True)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, featuresCol=None, labelCol=None, predictionCol=None,
                  probabilityCol=None, maxIter=None, stepSize=None,
                  regParam=None, standardization=None):
        return self._set(**self._input_kwargs)

    def _fit(self, dataset: DataFrame) -> "LogisticRegressionModel":
        feats_col = self.getOrDefault(self.featuresCol)
        label_col = self.getLabelCol()
        X_parts, y_parts = [], []
        for part in dataset.iterPartitions():
            if part.num_rows == 0:
                continue
            X_parts.append(columnToNdarray(part.column(feats_col), None))
            y_parts.append(np.asarray(part.column(label_col).to_pylist(),
                                      dtype=np.int32))
        if not X_parts:
            raise ValueError("Cannot fit LogisticRegression on an empty "
                             "DataFrame")
        X = np.concatenate(X_parts)
        y = np.concatenate(y_parts)
        n_classes = int(y.max()) + 1
        if n_classes < 2:
            raise ValueError("Need at least 2 classes to fit")
        lr = self.getOrDefault(self.stepSize)
        reg = self.getOrDefault(self.regParam)
        steps = self.getOrDefault(self.maxIter)
        d = X.shape[1]

        if self.getOrDefault(self.standardization):
            mu = X.mean(axis=0)
            sigma = X.std(axis=0)
            sigma = np.where(sigma < 1e-8, 1.0, sigma)
        else:
            mu = np.zeros((d,), np.float32)
            sigma = np.ones((d,), np.float32)
        Xs = (X - mu) / sigma

        tx = optax.adam(lr)
        init = {"w": jnp.zeros((d, n_classes), jnp.float32),
                "b": jnp.zeros((n_classes,), jnp.float32)}

        def loss_fn(p, xb, yb):
            logits = xb @ p["w"] + p["b"]
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()
            return ce + reg * (p["w"] ** 2).sum()

        @jax.jit
        def train(x, yb):
            # lax.scan over steps: the entire optimization is one XLA program.
            def step(carry, _):
                p, opt_state = carry
                g = jax.grad(loss_fn)(p, x, yb)
                updates, opt_state = tx.update(g, opt_state, p)
                return (optax.apply_updates(p, updates), opt_state), None

            (p, _), _ = jax.lax.scan(step, (init, tx.init(init)), None,
                                     length=steps)
            return p

        params = jax.tree_util.tree_map(np.asarray, train(Xs, y))
        # Fold the standardization back into the coefficients so the model
        # scores raw features: w' = w/sigma, b' = b - mu·(w/sigma).
        w = params["w"] / sigma[:, None]
        b = params["b"] - mu @ w
        return LogisticRegressionModel(
            weights=w, bias=b,
            featuresCol=feats_col,
            predictionCol=self.getPredictionCol(),
            probabilityCol=(self.getOrDefault(self.probabilityCol)
                            if self.isDefined(self.probabilityCol) else None))


class LogisticRegressionModel(Model, _LRParams, HasLabelCol, HasPredictionCol):
    def __init__(self, weights=None, bias=None, featuresCol="features",
                 predictionCol="prediction", probabilityCol=None):
        super().__init__()
        self.weights = np.asarray(weights) if weights is not None else None
        self.bias = np.asarray(bias) if bias is not None else None
        self._set(featuresCol=featuresCol, predictionCol=predictionCol,
                  probabilityCol=probabilityCol)

    @property
    def numClasses(self) -> int:
        return int(self.weights.shape[1])

    def _transform(self, dataset: DataFrame) -> DataFrame:
        feats_col = self.getOrDefault(self.featuresCol)
        pred_col = self.getPredictionCol()
        prob_col = (self.getOrDefault(self.probabilityCol)
                    if self.isDefined(self.probabilityCol) else None)
        w = jnp.asarray(self.weights)
        b = jnp.asarray(self.bias)

        @jax.jit
        def infer(x):
            logits = x @ w + b
            return jnp.argmax(logits, -1), jax.nn.softmax(logits, -1)

        def op(batch: pa.RecordBatch) -> pa.RecordBatch:
            x = columnToNdarray(batch.column(feats_col), None)
            pred, prob = infer(x)
            batch = _set_column(batch, pred_col,
                                pa.array(np.asarray(pred, dtype=np.int32)))
            if prob_col:
                batch = _set_column(
                    batch, prob_col,
                    pa.array(np.asarray(prob).tolist(),
                             type=pa.list_(pa.float32())))
            return batch

        return dataset.mapBatches(_length_preserving(op))

    def _save_payload(self, path: str):
        import os
        np.savez(os.path.join(path, "coef.npz"), w=self.weights, b=self.bias)

    def _load_payload(self, path: str, meta: dict):
        import os
        z = np.load(os.path.join(path, "coef.npz"))
        self.weights, self.bias = z["w"], z["b"]
