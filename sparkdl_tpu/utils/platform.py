"""Backend identification — the single place that decides "are we on TPU?".

Three features key off the platform (flash-attention default, Pallas
interpret-mode auto-select, torus-aware mesh construction). The axon PJRT
plugin may register its platform as ``"axon"`` rather than ``"tpu"`` while
the devices themselves report ``device_kind`` like ``"TPU v5 lite"`` —
gating on the literal backend string alone would silently disable every
TPU-only fast path on the real chip (round-3 verdict, Missing #2). So the
check accepts any of: default backend ``"tpu"``, device platform ``"tpu"``
or ``"axon"``, or a device kind containing ``"tpu"``.
"""

from __future__ import annotations

from typing import Any


def is_tpu_device(dev: Any) -> bool:
    """True if one jax Device is TPU silicon (incl. the axon plugin)."""
    plat = (getattr(dev, "platform", "") or "").lower()
    kind = (getattr(dev, "device_kind", "") or "").lower()
    return plat in ("tpu", "axon") or "tpu" in kind


def is_tpu_backend() -> bool:
    """True if jax's default backend runs on TPU silicon.

    Initializes the backend on first call (callers are all paths that are
    about to run on the backend anyway)."""
    import jax

    if jax.default_backend() == "tpu":
        return True
    try:
        devs = jax.devices()
    except RuntimeError:
        return False
    return bool(devs) and is_tpu_device(devs[0])


def backend_info() -> dict:
    """Observability record for the bench: what backend actually resolved.

    Settles per-round whether the platform gates fire on the real chip
    (round-3 verdict asked for exactly this in bench ``extra``)."""
    import jax

    info: dict = {"default_backend": jax.default_backend()}
    try:
        devs = jax.devices()
    except RuntimeError as e:
        info["devices_error"] = f"{type(e).__name__}: {e}"[:200]
        return info
    info["n_devices"] = len(devs)
    if devs:
        info["device_platform"] = getattr(devs[0], "platform", None)
        info["device_kind"] = getattr(devs[0], "device_kind", None)
    info["is_tpu"] = bool(devs) and is_tpu_device(devs[0])
    return info
