"""Shared utilities: pytree path helpers and device-aware timing."""

from .trees import flatten_with_paths, path_str, tree_size_bytes

__all__ = ["flatten_with_paths", "path_str", "tree_size_bytes", "Timer"]


def __getattr__(name):
    # Lazy: Timer is the flight recorder's span base (runner.events). The
    # laziness is for import-cycle safety, not cost — an eager import here
    # would re-enter the runner package while sparkdl_tpu/__init__ is
    # mid-initialization for any consumer that reaches utils first.
    if name == "Timer":
        from .timing import Timer
        return Timer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
