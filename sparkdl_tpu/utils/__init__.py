"""Shared utilities: pytree path helpers and device-aware timing."""

from .trees import flatten_with_paths, path_str, tree_size_bytes
from .timing import Timer

__all__ = ["flatten_with_paths", "path_str", "tree_size_bytes", "Timer"]
