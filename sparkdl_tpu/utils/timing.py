"""Wall-clock timing with device-completion awareness.

Thin alias (ISSUE 2 satellite): the timing primitive now lives in the
flight-recorder span API — ``sparkdl_tpu.runner.events.Timer`` is the base
class of ``events.span``, so there is exactly one timing implementation in
the codebase. The import is lazy (module ``__getattr__``) for import-cycle
safety — resolving it eagerly would re-enter the runner package while the
top-level ``sparkdl_tpu`` init is still running.
"""

from __future__ import annotations

__all__ = ["Timer"]


def __getattr__(name):
    if name == "Timer":
        from ..runner.events import Timer
        return Timer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
