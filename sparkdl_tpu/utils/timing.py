"""Wall-clock timing with device-completion awareness."""

from __future__ import annotations

import time


class Timer:
    """``with Timer() as t: ...`` — blocks on ``block_on`` (a jax pytree)
    before stopping, so device work is actually counted."""

    def __init__(self, block_on=None):
        self._block_on = block_on
        self.seconds = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._block_on is not None:
            import jax
            jax.block_until_ready(self._block_on)
        self.seconds = time.perf_counter() - self._t0
        return False
