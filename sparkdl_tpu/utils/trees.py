"""Pytree path helpers (the canonical "a/b/c" path spelling lives in
parallel.sharding; these wrap it for generic use)."""

from __future__ import annotations

import jax
import numpy as np

from ..parallel.sharding import path_str  # canonical "a/b/c" spelling


def flatten_with_paths(tree) -> list[tuple[str, object]]:
    """[(\"a/b/c\", leaf), ...] in deterministic traversal order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(path_str(path), leaf) for path, leaf in flat]


def tree_size_bytes(tree) -> int:
    """Total bytes across array leaves (params/cache accounting)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
        elif hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += int(leaf.size) * np.dtype(leaf.dtype).itemsize
    return total
