"""Transfer learning, reference-style (SURVEY.md §3.1 / BASELINE config 1).

DeepImageFeaturizer (truncated named model → bottleneck features) feeding
LogisticRegression inside a Pipeline, on a synthetic two-class image set.

Run: python examples/transfer_learning.py
Env: JAX_PLATFORMS=cpu for a quick CPU run; N_IMAGES / MODEL_NAME to scale.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

import sparkdl_tpu as sdl
from sparkdl_tpu.image import imageIO


def main():
    n = int(os.environ.get("N_IMAGES", "16"))
    model_name = os.environ.get("MODEL_NAME", "ResNet18")

    # Two synthetic classes: dark images (label 0) vs bright images (1).
    rng = np.random.RandomState(0)
    structs, labels = [], []
    for i in range(n):
        label = i % 2
        base = 40 if label == 0 else 200
        img = np.clip(rng.randint(-30, 30, (64, 64, 3)) + base,
                      0, 255).astype(np.uint8)
        structs.append(imageIO.imageArrayToStruct(img))
        labels.append(label)
    df = sdl.DataFrame.fromPydict({"image": structs, "label": labels},
                                  numPartitions=2)

    featurizer = sdl.DeepImageFeaturizer(
        inputCol="image", outputCol="features", modelName=model_name,
        batchSize=8)
    lr = sdl.LogisticRegression(featuresCol="features", labelCol="label",
                                maxIter=60)
    model = sdl.Pipeline([featurizer, lr]).fit(df)

    preds = model.transform(df).collect()
    acc = np.mean([int(r["prediction"]) == r["label"] for r in preds])
    print(f"{model_name} features -> LogisticRegression: "
          f"train accuracy {acc:.2f} on {n} images")
    assert acc >= 0.75, "separable synthetic classes should fit"


if __name__ == "__main__":
    main()
