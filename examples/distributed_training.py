"""Distributed data-parallel training — the HorovodRunner → XlaRunner
inversion (SURVEY.md §3.5 / BASELINE config 3).

The gradient allreduce is jax.lax.psum over the mesh's data axis, compiled
INTO the step function by XLA's SPMD partitioner — not a framework hook
outside the graph.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python examples/distributed_training.py
On a TPU slice, drop both env vars: the runner uses every local chip.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np
import optax

import sparkdl_tpu as sdl
from sparkdl_tpu.models.registry import get_model
from sparkdl_tpu.runner import softmax_cross_entropy_loss


def main():
    steps = int(os.environ.get("STEPS", "6"))
    per_chip = int(os.environ.get("BATCH_PER_CHIP", "4"))

    runner = sdl.XlaRunner(np=-1)  # every visible device

    def train(ctx):
        import jax.numpy as jnp

        spec = get_model("ResNet18")
        model = spec.build(num_classes=10)
        variables = jax.tree_util.tree_map(np.asarray, model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False))

        def apply_fn(params, x):
            return model.apply(params, x, train=False)

        def data():
            rng = np.random.RandomState(0)
            n = per_chip * ctx.size
            while True:
                yield {"image": rng.randint(0, 256, (n, 32, 32, 3))
                       .astype(np.float32),
                       "label": rng.randint(0, 10, (n,))}

        return ctx.fit(loss_fn=softmax_cross_entropy_loss(),
                       params=variables, tx=optax.adam(1e-3),
                       apply_fn=apply_fn, data=data(), num_steps=steps,
                       log_every=max(1, steps // 3))

    res = runner.run(train)
    losses = [h["loss"] for h in res["history"]]
    print(f"{len(runner.devices)}-device DP: "
          f"loss {losses[0]:.3f} -> {losses[-1]:.3f} over {steps} steps")


if __name__ == "__main__":
    main()
