"""Long-context serving: sequence-parallel prefill over a device mesh.

The prefill of a long prompt is O(S^2) attention compute — the part of
serving that actually needs more than one chip. Configuring the model's
``attn_fn`` with ring attention shards that compute over the ``sp`` mesh
axis (KV blocks hop the ICI ring via ``ppermute``) while the KV cache and
the per-token decode stay exactly as in single-chip serving. Tokens are
bit-identical to the dense single-device run — parallelism is layout,
not math.

On real hardware the mesh spans TPU chips; here the same code runs on a
virtual 8-device CPU mesh (XLA_FLAGS=--xla_force_host_platform_device_count=8).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
         python examples/long_context_serving.py
"""

import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from sparkdl_tpu.core import runtime
from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel, generate
from sparkdl_tpu.parallel.ring_attention import ring_attention


def main():
    n_dev = len(jax.devices())
    cfg = LlamaConfig.tiny()  # random init — swap in load_pretrained(...)
    dense = LlamaModel(cfg)

    # One knob turns on sequence parallelism: attn_fn=ring over an sp mesh.
    mesh = runtime.make_mesh({"sp": n_dev})
    sp_model = LlamaModel(cfg, attn_fn=functools.partial(
        ring_attention, mesh=mesh, axis="sp"))

    # "Long" prompt at example scale: S = 64 tokens = 8 tokens per device.
    # The same code serves 128k-token prompts on a real slice — S just has
    # to divide the sp axis.
    S, new = 64, 8
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(2, S)).astype(np.int32)
    variables = dense.init(jax.random.PRNGKey(0), ids[:1])

    ref = np.asarray(generate(dense, variables, ids, new))
    out = np.asarray(generate(sp_model, variables, ids, new))
    np.testing.assert_array_equal(out, ref)
    print(f"prefill of {S}-token prompts sharded over {n_dev} devices "
          f"({S // n_dev} tokens/device), decode unchanged")
    print("sequence-parallel tokens == single-device tokens, "
          "bit-identical.")


if __name__ == "__main__":
    main()
