"""Batch text generation through the UDF registry — the registerUDF
inference half of BASELINE config 5.

Part 1 (token columns): mixed-length prompts run as exactly two compiled
programs (left-padded prefill + while_loop decode with EOS early exit),
streamed from the DataFrame in batchRows chunks.

Part 2 (STRING columns, zero external assets): train the in-repo
ByteBPETokenizer on a local corpus, then drive a text column through
registerTextGenerationUDF — string → tokens → generate → string without
downloading anything.

Part 3 (online serving): the same prompts through the
continuous-batching engine (sparkdl_tpu.serving) — mixed lengths stream
through a 2-slot table with in-flight refill, tokens stream per request
via callback, and greedy output is token-identical to the static
two-program path of Part 1.

Run: JAX_PLATFORMS=cpu python examples/generation_serving.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

import sparkdl_tpu as sdl
from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel
from sparkdl_tpu.models.tokenizer import ByteBPETokenizer
from sparkdl_tpu.udf import (applyUDF, registerGenerationUDF,
                             registerTextGenerationUDF)


def token_column_serving(model, variables, cfg):
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
               for n in (5, 2, 7, 3, 6)]
    df = sdl.DataFrame.fromPydict({"prompt": prompts}, numPartitions=2)

    registerGenerationUDF("complete", model, variables,
                          max_new_tokens=8, temperature=0.7, top_p=0.9,
                          seed=0, batchRows=4)
    out = applyUDF(df, "complete", "prompt", "completion").toPandas()
    for p, c in zip(out["prompt"], out["completion"]):
        p, c = list(map(int, p)), list(map(int, c))
        print(f"  {p} -> {c[len(p):]}")
    assert all(len(c) == len(p) + 8 for p, c in
               zip(out["prompt"], out["completion"]))
    print("5 prompts, 3 lengths, ONE prefill + ONE decode program.")


def string_column_serving(model, variables):
    # Train the tokenizer on any local text — here, this very script.
    # (A real deployment would train on its domain corpus and .save()
    # the merges next to the model checkpoint.)
    with open(os.path.abspath(__file__)) as f:
        corpus = f.read().splitlines()
    tok = ByteBPETokenizer.train(corpus, vocab_size=400)
    print(f"tokenizer: {tok.vocab_size} ids "
          f"({len(tok.merges)} learned merges)")

    df = sdl.DataFrame.fromPydict({"text": [
        "batch text generation",
        "the DataFrame streams prompts",
        "left-padded prefill",
    ]})
    registerTextGenerationUDF(
        "continue", model, variables, encode=tok.encode, decode=tok.decode,
        max_new_tokens=6, seed=0, batchRows=2,
        eos_id=ByteBPETokenizer.EOS)
    out = applyUDF(df, "continue", "text", "completion").toPandas()
    for t, c in zip(out["text"], out["completion"]):
        print(f"  {t!r} -> {c!r}")
    assert all(isinstance(c, str) for c in out["completion"])
    print("string column -> tokenize -> generate -> detokenize, "
          "in-repo tokenizer only.")


def continuous_batching_serving(model, variables, cfg):
    """Part 3: the static path waits for the whole batch; the engine
    retires and refills each slot independently. Greedy decoding makes
    the two paths exactly comparable — token-identical per request."""
    from sparkdl_tpu.models.llama import generate, left_pad_prompts
    from sparkdl_tpu.serving import GenerationEngine

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
               for n in (5, 2, 7, 3, 6)]  # Part 1's prompts
    engine = GenerationEngine.from_model(model, variables, num_slots=2,
                                         max_len=64, min_bucket=8)
    streamed: dict = {}
    handles = [
        engine.submit(p, max_new_tokens=8,
                      stream_cb=lambda r, t:
                      streamed.setdefault(r.id, []).append(t))
        for p in prompts]
    engine.run_until_idle()
    for p, h in zip(prompts, handles):
        ids, lens = left_pad_prompts([p])
        ref = np.asarray(generate(model, variables, ids, 8,
                                  pad_lens=lens, pad_to=64))[0]
        want = ref[int(lens[0]) + len(p):].tolist()
        got = h.result()
        assert got == want, (p, got, want)
        # the stream callback saw every token, in emission order
        assert streamed[h.id] == got
        print(f"  {p} -> {got}")
    snap = engine.snapshot()
    assert snap["completed"] == len(prompts)
    assert snap["peak_slots_busy"] == 2  # requests genuinely overlapped
    print(f"5 requests over 2 slots ({snap['steps']} decode iterations, "
          f"{snap['prefills']} slot prefills): continuous batching is "
          f"token-identical to the static two-program path.")


def main():
    cfg = LlamaConfig.tiny()  # random init — swap in load_pretrained(...)
    model = LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    token_column_serving(model, variables, cfg)
    string_column_serving(model, variables)
    continuous_batching_serving(model, variables, cfg)


if __name__ == "__main__":
    main()
