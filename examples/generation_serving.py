"""Batch text generation through the UDF registry — the registerUDF
inference half of BASELINE config 5.

Part 1 (token columns): mixed-length prompts run as exactly two compiled
programs (left-padded prefill + while_loop decode with EOS early exit),
streamed from the DataFrame in batchRows chunks.

Part 2 (STRING columns, zero external assets): train the in-repo
ByteBPETokenizer on a local corpus, then drive a text column through
registerTextGenerationUDF — string → tokens → generate → string without
downloading anything.

Run: JAX_PLATFORMS=cpu python examples/generation_serving.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

import sparkdl_tpu as sdl
from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel
from sparkdl_tpu.models.tokenizer import ByteBPETokenizer
from sparkdl_tpu.udf import (applyUDF, registerGenerationUDF,
                             registerTextGenerationUDF)


def token_column_serving(model, variables, cfg):
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
               for n in (5, 2, 7, 3, 6)]
    df = sdl.DataFrame.fromPydict({"prompt": prompts}, numPartitions=2)

    registerGenerationUDF("complete", model, variables,
                          max_new_tokens=8, temperature=0.7, top_p=0.9,
                          seed=0, batchRows=4)
    out = applyUDF(df, "complete", "prompt", "completion").toPandas()
    for p, c in zip(out["prompt"], out["completion"]):
        p, c = list(map(int, p)), list(map(int, c))
        print(f"  {p} -> {c[len(p):]}")
    assert all(len(c) == len(p) + 8 for p, c in
               zip(out["prompt"], out["completion"]))
    print("5 prompts, 3 lengths, ONE prefill + ONE decode program.")


def string_column_serving(model, variables):
    # Train the tokenizer on any local text — here, this very script.
    # (A real deployment would train on its domain corpus and .save()
    # the merges next to the model checkpoint.)
    with open(os.path.abspath(__file__)) as f:
        corpus = f.read().splitlines()
    tok = ByteBPETokenizer.train(corpus, vocab_size=400)
    print(f"tokenizer: {tok.vocab_size} ids "
          f"({len(tok.merges)} learned merges)")

    df = sdl.DataFrame.fromPydict({"text": [
        "batch text generation",
        "the DataFrame streams prompts",
        "left-padded prefill",
    ]})
    registerTextGenerationUDF(
        "continue", model, variables, encode=tok.encode, decode=tok.decode,
        max_new_tokens=6, seed=0, batchRows=2,
        eos_id=ByteBPETokenizer.EOS)
    out = applyUDF(df, "continue", "text", "completion").toPandas()
    for t, c in zip(out["text"], out["completion"]):
        print(f"  {t!r} -> {c!r}")
    assert all(isinstance(c, str) for c in out["completion"])
    print("string column -> tokenize -> generate -> detokenize, "
          "in-repo tokenizer only.")


def main():
    cfg = LlamaConfig.tiny()  # random init — swap in load_pretrained(...)
    model = LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    token_column_serving(model, variables, cfg)
    string_column_serving(model, variables)


if __name__ == "__main__":
    main()
