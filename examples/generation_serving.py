"""Batch text generation through the UDF registry — the registerUDF
inference half of BASELINE config 5.

Mixed-length prompts run as exactly two compiled programs (left-padded
prefill + while_loop decode with EOS early exit), streamed from the
DataFrame in batchRows chunks.

Run: JAX_PLATFORMS=cpu python examples/generation_serving.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

import sparkdl_tpu as sdl
from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel
from sparkdl_tpu.udf import applyUDF, registerGenerationUDF


def main():
    cfg = LlamaConfig.tiny()  # random init — swap in load_pretrained(...)
    model = LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
               for n in (5, 2, 7, 3, 6)]
    df = sdl.DataFrame.fromPydict({"prompt": prompts}, numPartitions=2)

    registerGenerationUDF("complete", model, variables,
                          max_new_tokens=8, temperature=0.7, top_p=0.9,
                          seed=0, batchRows=4)
    out = applyUDF(df, "complete", "prompt", "completion").toPandas()
    for p, c in zip(out["prompt"], out["completion"]):
        p, c = list(map(int, p)), list(map(int, c))
        print(f"  {p} -> {c[len(p):]}")
    assert all(len(c) == len(p) + 8 for p, c in
               zip(out["prompt"], out["completion"]))
    print("5 prompts, 3 lengths, ONE prefill + ONE decode program.")


if __name__ == "__main__":
    main()
