"""XlaRunner tests on the virtual 8-device CPU mesh.

Strategy mirrors the reference's (SURVEY.md §4): a local-mode engine exercises
the full distributed machinery in-process, and correctness is equivalence —
the sharded SPMD step must match a single-device numpy/jax reference step
bit-for-bit (same inputs, same update math).
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from sparkdl_tpu.runner import (CheckpointManager, TrainState, ThroughputMeter,
                                XlaRunner, make_shard_map_step,
                                make_train_step, softmax_cross_entropy_loss)
from sparkdl_tpu.runner import api as hvd
from sparkdl_tpu.core import runtime


def _linear_apply(params, x):
    return x @ params["w"] + params["b"]


def _make_problem(seed=0, dim=4, classes=3):
    rng = np.random.RandomState(seed)
    # Host numpy (not jnp): donated train steps delete their input device
    # buffers, so each TrainState gets its own device copy of these.
    params = {"w": rng.randn(dim, classes).astype(np.float32),
              "b": np.zeros((classes,), np.float32)}
    x = rng.randn(16, dim).astype(np.float32)
    y = rng.randint(0, classes, size=(16,))
    return params, {"image": x, "label": y}


def _reference_step(params, batch, lr=0.1):
    """Plain single-device step for equivalence checking."""
    def loss(p):
        logits = _linear_apply(p, jnp.asarray(batch["image"]))
        onehot = jax.nn.one_hot(batch["label"], logits.shape[-1])
        return optax.softmax_cross_entropy(logits, onehot).mean()

    grads = jax.grad(loss)(params)
    return jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)


@pytest.fixture(scope="module")
def runner():
    return XlaRunner(np=8)


class TestTrainStep:
    @pytest.mark.parametrize("explicit", [False, True])
    def test_matches_single_device_reference(self, runner, explicit):
        """The SPMD step (implicit XLA collective or explicit shard_map
        pmean) must equal the plain single-device SGD step."""
        ctx = runner.make_context()
        params, batch = _make_problem()
        loss_fn = softmax_cross_entropy_loss()
        state = TrainState.create(_linear_apply, params,
                                  optax.sgd(0.1))
        step = ctx.make_train_step(loss_fn, explicit_collectives=explicit)
        with ctx.mesh:
            new_state, metrics = step(state, ctx.shard_batch(batch))
        expected = _reference_step(params, batch)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(new_state.params[k]),
                                       np.asarray(expected[k]),
                                       rtol=2e-5, atol=2e-6)
        assert float(metrics["loss"]) > 0
        assert int(new_state.step) == 1

    def test_explicit_and_implicit_agree(self, runner):
        ctx = runner.make_context()
        params, batch = _make_problem(seed=1)
        loss_fn = softmax_cross_entropy_loss()
        tx = optax.adam(1e-2)
        with ctx.mesh:
            s1, _ = make_train_step(loss_fn, ctx.mesh)(
                TrainState.create(_linear_apply, params, tx),
                ctx.shard_batch(batch))
            s2, _ = make_shard_map_step(loss_fn, ctx.mesh)(
                TrainState.create(_linear_apply, params, tx),
                ctx.shard_batch(batch))
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(s1.params[k]),
                                       np.asarray(s2.params[k]),
                                       rtol=1e-5, atol=1e-6)

    def test_remat_same_gradients(self, runner):
        """remat=True recomputes activations in the backward pass — a
        scheduling change, not a math change: updated params must equal
        the non-remat step's."""
        ctx = runner.make_context()
        params, batch = _make_problem(seed=2)
        loss_fn = softmax_cross_entropy_loss()
        tx = optax.sgd(0.1)
        with ctx.mesh:
            s1, _ = ctx.make_train_step(loss_fn)(
                TrainState.create(_linear_apply, params, tx),
                ctx.shard_batch(batch))
            s2, _ = ctx.make_train_step(loss_fn, remat=True)(
                TrainState.create(_linear_apply, params, tx),
                ctx.shard_batch(batch))
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(s1.params[k]),
                                       np.asarray(s2.params[k]),
                                       rtol=1e-6, atol=1e-7)

    def test_gradient_accumulation_equals_full_batch(self, runner):
        """accum_steps=k microbatch scan must produce the SAME update as
        one full-batch step (mean-reduced loss ⇒ averaged microbatch
        grads == full grad), also composed with remat."""
        ctx = runner.make_context()
        params, batch = _make_problem(seed=3)
        loss_fn = softmax_cross_entropy_loss()
        tx = optax.sgd(0.1)
        with ctx.mesh:
            full, _ = ctx.make_train_step(loss_fn)(
                TrainState.create(_linear_apply, params, tx),
                ctx.shard_batch(batch))
            acc, m = ctx.make_train_step(loss_fn, accum_steps=4)(
                TrainState.create(_linear_apply, params, tx),
                ctx.shard_batch(batch))
            accr, _ = ctx.make_train_step(loss_fn, accum_steps=4,
                                          remat=True)(
                TrainState.create(_linear_apply, params, tx),
                ctx.shard_batch(batch))
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(acc.params[k]),
                                       np.asarray(full.params[k]),
                                       rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(np.asarray(accr.params[k]),
                                       np.asarray(full.params[k]),
                                       rtol=1e-5, atol=1e-6)
        assert np.isfinite(float(m["loss"]))
        # shard-aligned split path: batch divisible by k x shards (the
        # zero-reshard fast path; the 16-row case above exercises the
        # contiguous fallback)
        rng = np.random.RandomState(9)
        big = {"image": rng.randn(64, 4).astype(np.float32),
               "label": rng.randint(0, 3, (64,))}
        bparams = {"w": rng.randn(4, 3).astype(np.float32) * 0.1,
                   "b": np.zeros(3, np.float32)}
        with ctx.mesh:
            bf, _ = ctx.make_train_step(loss_fn)(
                TrainState.create(_linear_apply, bparams, tx),
                ctx.shard_batch(big))
            ba, _ = ctx.make_train_step(loss_fn, accum_steps=4)(
                TrainState.create(_linear_apply, bparams, tx),
                ctx.shard_batch(big))
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(ba.params[k]),
                                       np.asarray(bf.params[k]),
                                       rtol=1e-5, atol=1e-6)
        with pytest.raises(ValueError, match="mutable"):
            make_train_step(loss_fn, ctx.mesh, mutable=True, accum_steps=2)
        with pytest.raises(ValueError, match="accum_steps"):
            make_train_step(loss_fn, ctx.mesh, accum_steps=0)
        # explicit-collective path: remat composes, accum raises clearly
        with ctx.mesh:
            er, _ = ctx.make_train_step(loss_fn, explicit_collectives=True,
                                        remat=True)(
                TrainState.create(_linear_apply, params, tx),
                ctx.shard_batch(batch))
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(er.params[k]),
                                       np.asarray(full.params[k]),
                                       rtol=1e-5, atol=1e-6)
        with pytest.raises(ValueError, match="explicit_collectives"):
            ctx.make_train_step(loss_fn, explicit_collectives=True,
                                accum_steps=2)

    def test_fit_accum_crops_ragged_tail(self, runner):
        """fit(accum_steps=k) must survive a data iterator whose tail
        batches are not divisible by k x local devices: crop (and skip
        tiny leftovers), never abort at the run's last step."""
        def apply_fn(p, x):
            return x @ p["w"]

        rng = np.random.RandomState(4)
        params = {"w": rng.randn(4, 3).astype(np.float32) * 0.1}

        def data():
            for nrows in (32, 20, 3):  # full, ragged (crop), tiny (skip)
                yield {"image": rng.randn(nrows, 4).astype(np.float32),
                       "label": rng.randint(0, 3, (nrows,))}

        res = runner.run(lambda ctx: ctx.fit(
            loss_fn=softmax_cross_entropy_loss(), params=params,
            tx=optax.sgd(0.1), apply_fn=apply_fn, data=data(),
            num_steps=3, log_every=1, accum_steps=2))
        steps = [h["step"] for h in res["history"]]
        # 32 runs whole; 20 crops to 16 (accum 2 x data-axis 8 = 16);
        # 3 is skipped entirely -> two optimizer steps happened
        assert steps == [1, 2]
        assert all(np.isfinite(h["loss"]) for h in res["history"])

    def test_batch_actually_sharded(self, runner):
        """The input batch must land split over the data axis — 8 shards."""
        ctx = runner.make_context()
        _, batch = _make_problem()
        sharded = ctx.shard_batch(batch)
        assert len(sharded["image"].sharding.device_set) == 8
        shard_shapes = {s.data.shape for s in sharded["image"].addressable_shards}
        assert shard_shapes == {(2, 4)}  # 16 rows / 8 devices


class TestRunnerApi:
    def test_run_passes_context(self):
        out = XlaRunner(np=8).run(lambda ctx, k: (ctx.size, k), k=42)
        assert out == (8, 42)

    def test_np_subset(self):
        assert XlaRunner(np=4).run(lambda ctx: ctx.mesh.devices.size) == 4

    def test_np_too_large(self):
        with pytest.raises(ValueError):
            XlaRunner(np=99)

    def test_init_shutdown_init_cycle(self):
        """Regression (ISSUE 1 satellite): shutdown() popped the context
        stack but left _default_runner cached, so a second init() could
        ride a stale runner. The cycle must yield a FRESH context honoring
        the new np."""
        from sparkdl_tpu.runner.xla_runner import current_context
        ctx1 = hvd.init(np=4)
        assert ctx1.size == 4
        hvd.shutdown()
        assert current_context() is None
        assert hvd._default_runner is None
        ctx2 = hvd.init(np=8)
        try:
            assert ctx2 is not ctx1
            assert ctx2.size == 8
            assert hvd.size() == 8
        finally:
            hvd.shutdown()
        assert current_context() is None

    def test_hvd_compat_shim(self):
        def main(ctx):
            assert hvd.size() == 8
            assert hvd.rank() == 0
            s = hvd.allreduce(jnp.ones((3,)), average=False)
            np.testing.assert_allclose(np.asarray(s), 8 * np.ones(3))
            m = hvd.allreduce(jnp.full((3,), 2.0), average=True)
            np.testing.assert_allclose(np.asarray(m), 2 * np.ones(3))
            return True

        assert XlaRunner(np=8).run(lambda ctx: main(ctx))


class TestFitLoop:
    def _data(self, n_batches=12, bs=16, seed=0):
        rng = np.random.RandomState(seed)
        w_true = rng.randn(4, 3).astype(np.float32)
        for _ in range(n_batches):
            x = rng.randn(bs, 4).astype(np.float32)
            y = (x @ w_true).argmax(-1)
            yield {"image": x, "label": y}

    def test_fit_learns_and_meters(self, tmp_path):
        runner = XlaRunner(np=8, checkpoint_dir=str(tmp_path / "ckpt"))
        params, _ = _make_problem(seed=3)

        def main(ctx):
            return ctx.fit(loss_fn=softmax_cross_entropy_loss(),
                           params=params, tx=optax.adam(5e-2),
                           apply_fn=_linear_apply,
                           data=self._data(), num_steps=12,
                           checkpoint_every=5, log_every=4)

        res = runner.run(main)
        assert int(res["state"].step) == 12
        losses = [h["loss"] for h in res["history"]]
        assert losses[-1] < losses[0]
        assert res["meter"].steps == 12

    def test_fit_feed_lookahead_matches_inline(self):
        """feed_lookahead=2 (threaded shard-ahead) must consume the same
        batches in the same order and land on bitwise-identical params as
        the inline feed — including with accum cropping active (a skipped
        tail batch must not desync the step count)."""
        params, _ = _make_problem(seed=6)
        kw = dict(loss_fn=softmax_cross_entropy_loss(), params=params,
                  tx=optax.sgd(0.1), apply_fn=_linear_apply, log_every=100,
                  accum_steps=2)

        def ragged(seed):
            # batch sizes 16,16,...,3 — the 3-row tail gets skipped by crop
            for i, b in enumerate(self._data(n_batches=6, seed=seed)):
                yield b
            yield {"image": np.ones((3, 4), np.float32),
                   "label": np.zeros((3,), np.int64)}

        r_inline = XlaRunner(np=8).run(lambda ctx: ctx.fit(
            data=ragged(7), num_steps=10, feed_lookahead=0, **kw))
        r_ahead = XlaRunner(np=8).run(lambda ctx: ctx.fit(
            data=ragged(7), num_steps=10, feed_lookahead=2, **kw))
        assert int(r_inline["state"].step) == int(r_ahead["state"].step) == 6
        for a, b in zip(jax.tree_util.tree_leaves(r_inline["state"].params),
                        jax.tree_util.tree_leaves(r_ahead["state"].params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_fit_lookahead_never_overconsumes_iterator(self):
        """A reused data iterator must sit exactly where the inline feed
        would leave it: the lookahead may not draw batches the step loop
        won't run (epoch-style sequential fit() calls on one iterator)."""
        params, _ = _make_problem(seed=8)
        it = self._data(n_batches=10)
        XlaRunner(np=8).run(lambda ctx: ctx.fit(
            loss_fn=softmax_cross_entropy_loss(), params=params,
            tx=optax.sgd(0.1), apply_fn=_linear_apply, data=it,
            num_steps=4, feed_lookahead=3, log_every=100))
        assert sum(1 for _ in it) == 6  # 10 - exactly num_steps consumed

    def test_checkpoint_resume(self, tmp_path):
        """Kill-and-restart: a second fit with the same checkpoint_dir must
        resume from the saved step, not from scratch (SURVEY.md §5.3)."""
        ckpt = str(tmp_path / "ckpt")
        params, _ = _make_problem(seed=4)
        kw = dict(loss_fn=softmax_cross_entropy_loss(), params=params,
                  tx=optax.sgd(0.1), apply_fn=_linear_apply,
                  checkpoint_every=3, log_every=100)

        r1 = XlaRunner(np=8, checkpoint_dir=ckpt).run(
            lambda ctx: ctx.fit(data=self._data(), num_steps=6, **kw))
        assert int(r1["state"].step) == 6

        seen = []

        def main2(ctx):
            res = ctx.fit(data=self._data(), num_steps=9, **kw)
            seen.append(res)
            return res

        r2 = XlaRunner(np=8, checkpoint_dir=ckpt).run(main2)
        # resumed at 6 → only 3 more steps ran
        assert int(r2["state"].step) == 9
        assert r2["meter"].steps == 3

    def test_run_with_restarts_fault_injection(self, tmp_path):
        """Fault injection (SURVEY.md §5.3): main_fn dies mid-training once;
        supervision restarts it and it resumes from the checkpoint."""
        ckpt = str(tmp_path / "ckpt")
        params, _ = _make_problem(seed=5)
        attempts = []

        def main(ctx):
            attempts.append(1)
            res = ctx.fit(loss_fn=softmax_cross_entropy_loss(), params=params,
                          tx=optax.sgd(0.1), apply_fn=_linear_apply,
                          data=self._data(), num_steps=4,
                          checkpoint_every=2, log_every=100)
            if len(attempts) == 1:
                raise RuntimeError("injected chip failure")
            return res

        res = XlaRunner(np=8, checkpoint_dir=ckpt).run_with_restarts(
            main, max_restarts=2, backoff_s=0.0)
        assert len(attempts) == 2
        assert int(res["state"].step) == 4


class TestCheckpointManager:
    def test_roundtrip(self, tmp_path):
        params, _ = _make_problem()
        state = TrainState.create(_linear_apply, params, optax.adam(1e-3))
        mngr = CheckpointManager(str(tmp_path), async_save=False)
        mngr.save(7, state, wait=True)
        assert mngr.latest_step() == 7

        fresh = TrainState.create(_linear_apply,
                                  jax.tree_util.tree_map(jnp.zeros_like,
                                                         params),
                                  optax.adam(1e-3))
        restored = mngr.restore(fresh)
        np.testing.assert_allclose(np.asarray(restored.params["w"]),
                                   np.asarray(params["w"]))
        mngr.close()


class TestMutableAndRng:
    """BatchNorm model_state + dropout RNG plumbing through the steps."""

    def _bn_model(self):
        import flax.linen as nn

        class TinyBN(nn.Module):
            @nn.compact
            def __call__(self, x, train=False):
                x = nn.Dense(8)(x)
                x = nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9)(x)
                return nn.Dense(3)(x)

        return TinyBN()

    def test_mutable_step_updates_batch_stats(self, runner):
        from sparkdl_tpu.runner import bn_classifier_loss
        ctx = runner.make_context()
        model = self._bn_model()
        rng = np.random.RandomState(0)
        x = rng.randn(16, 4).astype(np.float32) * 3 + 1
        variables = jax.tree_util.tree_map(np.asarray, model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4))))
        state = TrainState.create(
            None, variables["params"], optax.sgd(0.01),
            model_state={"batch_stats": variables["batch_stats"]})
        step = ctx.make_train_step(bn_classifier_loss(model), mutable=True)
        with ctx.mesh:
            new_state, m = step(state, ctx.shard_batch(
                {"image": x, "label": rng.randint(0, 3, size=(16,))}))
        old_mean = variables["batch_stats"]["BatchNorm_0"]["mean"]
        new_mean = new_state.model_state["batch_stats"]["BatchNorm_0"]["mean"]
        assert not np.allclose(np.asarray(old_mean), np.asarray(new_mean))
        assert np.isfinite(float(m["loss"]))

    def test_mutable_checkpoint_roundtrip_and_legacy(self, tmp_path):
        """model_state survives save/restore; restoring a checkpoint saved
        WITHOUT model_state into a template WITH it keeps the fresh stats
        (the upgrade path) instead of crashing."""
        params = {"w": np.ones((2, 2), np.float32)}
        ms = {"batch_stats": {"mean": np.full((2,), 5.0, np.float32)}}
        mngr = CheckpointManager(str(tmp_path / "a"), async_save=False)
        state = TrainState.create(None, params, optax.sgd(0.1),
                                  model_state=ms)
        mngr.save(1, state, wait=True)
        fresh = TrainState.create(
            None, jax.tree_util.tree_map(np.zeros_like, params),
            optax.sgd(0.1),
            model_state=jax.tree_util.tree_map(np.zeros_like, ms))
        restored = mngr.restore(fresh)
        np.testing.assert_allclose(
            np.asarray(restored.model_state["batch_stats"]["mean"]),
            5.0 * np.ones(2))
        mngr.close()

        # legacy: checkpoint without model_state, template with it
        mngr2 = CheckpointManager(str(tmp_path / "b"), async_save=False)
        mngr2.save(1, TrainState.create(None, params, optax.sgd(0.1)),
                   wait=True)
        restored2 = mngr2.restore(fresh)
        np.testing.assert_allclose(np.asarray(restored2.params["w"]),
                                   np.ones((2, 2)))
        # template's fresh stats kept
        np.testing.assert_allclose(
            np.asarray(restored2.model_state["batch_stats"]["mean"]),
            np.zeros(2))
        mngr2.close()

    def test_with_rng_dropout_plumbing(self, runner):
        """with_rng steps feed fresh per-step dropout noise; without it the
        model runs deterministic. A minimal flax dropout model, not BERT:
        the contract under test is the RUNNER's rng threading into
        ``apply(..., rngs={'dropout': ...})``, and four tiny-BERT
        train-step compiles cost ~13s of tier-1 budget for the same
        proof (ISSUE 10 headroom satellite; BERT's own dropout behavior
        is covered in test_transformer_models)."""
        import flax.linen as nn

        class DropNet(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = True):
                h = nn.Dense(8)(x)
                h = nn.Dropout(0.5, deterministic=not train)(h)
                return nn.Dense(2)(h)

        ctx = runner.make_context()
        model = DropNet()
        rng = np.random.RandomState(0)
        batch = {"input_ids": rng.uniform(size=(8, 16)).astype(np.float32),
                 "label": rng.randint(0, 2, size=(8,))}
        variables = jax.tree_util.tree_map(np.asarray, model.init(
            jax.random.PRNGKey(0), jnp.asarray(batch["input_ids"]),
            train=False))

        def loss_fn(params, apply_fn, batch, rng=None):
            det = rng is None
            logits = model.apply(
                params, batch["input_ids"], train=not det,
                rngs=None if det else {"dropout": rng})
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["label"]).mean()
            return loss, {}

        def one(with_rng, seed):
            state = TrainState.create(None, variables, optax.sgd(0.0))
            step = ctx.make_train_step(loss_fn, with_rng=with_rng)
            if with_rng:
                from sparkdl_tpu.runner import make_train_step
                step = make_train_step(loss_fn, ctx.mesh, with_rng=True,
                                       rng_seed=seed)
            with ctx.mesh:
                _, m = step(state, ctx.shard_batch(batch))
            return float(m["loss"])

        det1, det2 = one(False, 0), one(False, 1)
        assert det1 == det2  # deterministic path ignores seed
        s0, s1 = one(True, 0), one(True, 1)
        assert s0 != s1  # different dropout noise → different loss


def test_throughput_meter_warmup():
    m = ThroughputMeter(n_chips=8, warmup_steps=1)
    m.update(64)  # warmup (compile) step — excluded
    for _ in range(5):
        m.update(64)
    s = m.summary()
    assert s["examples"] == 5 * 64
    assert s["n_chips"] == 8
    assert s["examples_per_sec"] > 0
