"""Flight recorder tests (ISSUE 2 tentpole): structured event tracing
through the real training machinery — ring-buffer bounds, JSONL streaming,
crash postmortems, merged gang timelines, step-time percentiles, and MFU —
plus the observability satellites (atomic heartbeats, robust trace(),
MetricsLogger hardening).
"""

import json
import os
import sys
import time
import types

import jax
import numpy as np
import optax
import pytest

from sparkdl_tpu.runner import (Fault, FaultPlan, GangFailure, StepTimeStats,
                                ThroughputMeter, XlaRunner, chaos, events,
                                launcher, run_stats,
                                softmax_cross_entropy_loss, supervise)
from sparkdl_tpu.runner import metrics as metrics_lib
from sparkdl_tpu.runner.metrics import MetricsLogger

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Every test starts with a fresh recorder, no stream dir, and zeroed
    process-wide stats."""
    monkeypatch.delenv("SPARKDL_EVENT_DIR", raising=False)
    monkeypatch.delenv("SPARKDL_EVENT_RING", raising=False)
    monkeypatch.delenv("SPARKDL_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("SPARKDL_MFU_ESTIMATE", raising=False)
    monkeypatch.delenv(chaos.CHAOS_ENV, raising=False)
    chaos.uninstall()
    events.reset()
    metrics_lib.global_step_stats.reset()
    run_stats.reset()
    yield
    chaos.uninstall()
    events.reset()
    metrics_lib.global_step_stats.reset()
    run_stats.reset()


def _linear_apply(params, x):
    return x @ params["w"]


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": rng.randn(4, 3).astype(np.float32)}


def _data(n_batches=64, seed=1):
    rng = np.random.RandomState(seed)
    for _ in range(n_batches):
        x = rng.randn(16, 4).astype(np.float32)
        yield {"image": x, "label": rng.randint(0, 3, (16,))}


def _fit(ctx, **kw):
    kw.setdefault("num_steps", 4)
    kw.setdefault("log_every", 100)
    return ctx.fit(loss_fn=softmax_cross_entropy_loss(), params=_params(),
                   tx=optax.sgd(0.1), apply_fn=_linear_apply, data=_data(),
                   **kw)


class TestRecorder:
    def test_ring_is_bounded(self):
        rec = events.reset(ring_size=16)
        for i in range(100):
            rec.event("e", i=i)
        tail = rec.tail()
        assert len(tail) == 16
        assert tail[0]["i"] == 84 and tail[-1]["i"] == 99

    def test_span_records_duration_and_error(self):
        rec = events.reset()
        with events.span("ok", step=3):
            time.sleep(0.002)
        with pytest.raises(ValueError, match="boom"):
            with events.span("bad"):
                raise ValueError("boom")
        ok_end = [e for e in rec.tail() if e["name"] == "ok"
                  and e["ph"] == "E"][0]
        assert ok_end["dur_s"] >= 0.002 and ok_end["step"] == 3
        bad_end = [e for e in rec.tail() if e["name"] == "bad"
                   and e["ph"] == "E"][0]
        assert bad_end["error"] == "ValueError: boom"

    def test_data_exhaustion_is_not_an_error(self):
        """A span closed by StopIteration (fit's data_fetch around next())
        marks end_of_data — NOT error — so a rank that merely finished its
        data can never be named the gang's first failure."""
        rec = events.reset()
        it = iter([])
        try:
            with events.span("data_fetch", step=0):
                next(it)
        except StopIteration:
            pass
        end = rec.tail()[-1]
        assert end["ph"] == "E" and end.get("end_of_data") is True
        assert "error" not in end

    def test_block_on_error_does_not_mask_region_error(self, monkeypatch):
        """When the region raised AND block_until_ready also fails, the
        region's exception is the story — the block error is recorded in
        the end event, never raised over it (classification depends on
        the right exception propagating)."""
        rec = events.reset()
        monkeypatch.setattr(jax, "block_until_ready", lambda t: (_ for _ in
                            ()).throw(RuntimeError("UNAVAILABLE: device")))
        with pytest.raises(ValueError, match="diverged-ish"):
            with events.span("step", block_on=object()):
                raise ValueError("diverged-ish user error")
        end = rec.tail()[-1]
        assert end["error"].startswith("ValueError")
        assert end["block_error"].startswith("RuntimeError")
        # clean region: the device error DOES surface
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            with events.span("step", block_on=object()):
                pass
        assert rec.tail()[-1]["error"].startswith("RuntimeError")

    def test_no_dir_means_no_io(self, tmp_path):
        rec = events.reset()
        for i in range(50):
            rec.event("e", i=i)
        assert rec._file is None  # never opened a stream
        assert list(tmp_path.iterdir()) == []

    def test_streams_jsonl_per_rank(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SPARKDL_EVENT_DIR", str(tmp_path))
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "3")
        rec = events.reset()
        rec.event("alpha", step=1)
        with rec.span("beta"):
            pass
        path = tmp_path / "events_rank3.jsonl"
        recs = [json.loads(ln) for ln in path.read_text().splitlines()]
        assert [r["name"] for r in recs] == ["alpha", "beta", "beta"]
        assert [r["ph"] for r in recs] == ["P", "B", "E"]
        assert all(r["rank"] == 3 for r in recs)
        assert recs[0]["step"] == 1

    def test_stream_cap_bounds_file_ring_keeps_recording(self, tmp_path,
                                                         monkeypatch):
        monkeypatch.setenv("SPARKDL_EVENT_DIR", str(tmp_path))
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "0")
        monkeypatch.setenv("SPARKDL_EVENT_MAX_MB", "0.0005")  # ~520 bytes
        rec = events.reset()
        for i in range(100):
            rec.event("e", i=i)
        lines = (tmp_path / "events_rank0.jsonl").read_text().splitlines()
        recs = [json.loads(ln) for ln in lines]
        assert recs[-1]["name"] == "event_stream_truncated"
        assert len(recs) < 100  # file bounded...
        assert len(rec.tail()) > len(recs)  # ...ring kept recording
        size = (tmp_path / "events_rank0.jsonl").stat().st_size
        rec.event("after")  # no further growth past the marker
        assert (tmp_path / "events_rank0.jsonl").stat().st_size == size

    def test_stream_cap_survives_recorder_reset(self, tmp_path,
                                                monkeypatch):
        """The cap budget is seeded from the file already on disk: a
        reset()-per-attempt retry loop must not grow the stream
        N_attempts x cap."""
        monkeypatch.setenv("SPARKDL_EVENT_DIR", str(tmp_path))
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "0")
        monkeypatch.setenv("SPARKDL_EVENT_MAX_MB", "0.0005")
        rec = events.reset()
        for i in range(100):
            rec.event("e", i=i)
        size = (tmp_path / "events_rank0.jsonl").stat().st_size
        rec2 = events.reset()  # fresh recorder, same dir, same file
        for i in range(100):
            rec2.event("e", i=i)
        assert (tmp_path / "events_rank0.jsonl").stat().st_size == size

    def test_enable_flight_recorder(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "0")
        # setenv first so monkeypatch restores the pre-test absence even
        # though enable_flight_recorder writes os.environ directly
        monkeypatch.setenv("SPARKDL_EVENT_DIR", "overwritten")
        monkeypatch.setenv("SPARKDL_EVENT_RING", "overwritten")
        from sparkdl_tpu.runner.api import enable_flight_recorder
        rec = enable_flight_recorder(str(tmp_path), ring_size=32)
        assert os.environ["SPARKDL_EVENT_DIR"] == str(tmp_path)
        rec.event("hello")
        assert (tmp_path / "events_rank0.jsonl").exists()
        assert rec.ring.maxlen == 32

    def test_timer_is_the_span_primitive(self):
        from sparkdl_tpu.utils import Timer
        assert Timer is events.Timer
        with Timer() as t:
            time.sleep(0.002)
        assert t.seconds >= 0.002
        # spans ARE timers — one timing primitive in the codebase
        assert issubclass(type(events.span("x")), Timer)


class TestStepTimeStats:
    def test_percentiles_on_synthetic_sequence(self):
        st = StepTimeStats()
        for ms in range(1, 101):  # 1..100 ms
            st.record(ms / 1000.0)
        s = st.summary()
        assert s["n"] == 100
        assert s["p50_s"] == pytest.approx(0.050)
        assert s["p95_s"] == pytest.approx(0.095)
        assert s["p99_s"] == pytest.approx(0.099)
        assert s["max_s"] == pytest.approx(0.100)
        assert s["mean_s"] == pytest.approx(0.0505)

    def test_reservoir_bounds_memory_keeps_max_exact(self):
        st = StepTimeStats(capacity=50)
        for i in range(1000):
            st.record(0.001 * (i % 97 + 1))
        assert len(st._sample) == 50
        assert st.count == 1000
        assert st.summary()["max_s"] == pytest.approx(0.097)
        assert 0.001 <= st.percentile(50) <= 0.097

    def test_meter_summary_carries_percentiles_and_mfu(self, monkeypatch):
        m = ThroughputMeter(n_chips=4, warmup_steps=0)
        for _ in range(10):
            m.step_stats.record(0.1)
        # FLOPs unknown -> MFU is null, not zero
        assert m.summary()["mfu"] is None
        monkeypatch.setenv("SPARKDL_PEAK_FLOPS", "1e12")
        m.flops_per_step = 4e10  # global step over 4 chips at 1e12 peak
        s = m.summary()
        # 4e10 / 0.1s / (1e12 * 4 chips) = 0.1
        assert s["mfu"] == pytest.approx(0.1)
        assert s["step_time"]["p50_s"] == pytest.approx(0.1)

    def test_fit_populates_step_time(self):
        res = XlaRunner(np=8).run(_fit)
        s = res["meter"].summary()
        assert s["step_time"]["n"] == 3  # 4 steps - 1 warmup
        assert s["step_time"]["p99_s"] >= s["step_time"]["p50_s"] > 0
        assert s["mfu"] is None  # no FLOP count supplied
        # the process-wide reservoir (bench's source) saw the same steps
        assert metrics_lib.global_step_stats.count == 3

    def test_fit_mfu_estimate_via_cost_analysis(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_MFU_ESTIMATE", "1")
        monkeypatch.setenv("SPARKDL_PEAK_FLOPS", "1e12")
        res = XlaRunner(np=8).run(_fit)
        m = res["meter"]
        assert m.flops_per_step is not None and m.flops_per_step > 0
        assert m.summary()["mfu"] is not None


class TestPostmortem:
    def test_fit_failure_writes_postmortem(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SPARKDL_EVENT_DIR", str(tmp_path))
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "0")
        events.reset()
        chaos.install(FaultPlan([Fault("step_start", "preempt", at_step=2)]))
        with pytest.raises(Exception, match="UNAVAILABLE"):
            XlaRunner(np=8).run(_fit)
        pm = json.loads((tmp_path / "postmortem_rank0.json").read_text())
        assert pm["error"]["type"] == "InjectedPreemption"
        assert pm["error"]["kind"] == "retryable"
        assert pm["site"] == "fit" and pm["step"] == 2
        names = [e["name"] for e in pm["events"]]
        assert "fit_start" in names and "chaos" in names
        assert "step_compute" in names and "compile" in names
        # the stream holds the same trail (flushed line-by-line)
        lines = (tmp_path / "events_rank0.jsonl").read_text().splitlines()
        assert any(json.loads(ln)["name"] == "chaos" for ln in lines)

    def test_chaos_fire_lands_in_trace(self):
        rec = events.reset()
        chaos.install(FaultPlan([Fault("batch_fetch", "nan", at_step=0)]))
        chaos.fire("batch_fetch", step=0,
                   batch={"x": np.ones(3, np.float32)})
        ev = [e for e in rec.tail() if e["name"] == "chaos"]
        assert ev and ev[0]["site"] == "batch_fetch" \
            and ev[0]["kind"] == "nan" and ev[0]["step"] == 0


class TestOverheadBounded:
    def test_recorder_off_is_ring_only_no_sync(self, tmp_path, monkeypatch):
        """Acceptance: with SPARKDL_EVENT_DIR unset, a recorded fit() does
        no event I/O and introduces no extra host syncs — exactly the one
        pre-existing block_until_ready at the end of fit()."""
        rec = events.reset()
        calls = []
        orig = jax.block_until_ready
        monkeypatch.setattr(
            jax, "block_until_ready",
            lambda tree: (calls.append(1), orig(tree))[1])
        res = XlaRunner(np=8).run(_fit)
        assert int(res["state"].step) == 4
        assert len(calls) == 1  # fit()'s final sync only
        assert rec._file is None  # no stream was ever opened
        assert list(tmp_path.iterdir()) == []
        assert any(e["name"] == "step_compute" for e in rec.tail())


class TestMergeTimeline:
    def _write(self, d, rank, recs):
        with open(os.path.join(d, f"events_rank{rank}.jsonl"), "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    def test_merged_order_and_first_failure(self, tmp_path):
        d = str(tmp_path)
        self._write(d, 0, [
            {"t": 100.0, "name": "step_compute", "ph": "B", "rank": 0,
             "step": 0},
            {"t": 101.0, "name": "step_compute", "ph": "E", "rank": 0,
             "step": 1},
            {"t": 102.0, "name": "step_compute", "ph": "E", "rank": 0,
             "step": 2},
        ])
        self._write(d, 1, [
            {"t": 100.1, "name": "step_compute", "ph": "E", "rank": 1,
             "step": 0},
            {"t": 100.6, "name": "chaos", "ph": "P", "rank": 1,
             "site": "step_start", "kind": "preempt", "step": 1},
        ])
        with open(os.path.join(d, "postmortem_rank1.json"), "w") as f:
            json.dump({"t": 100.7, "rank": 1, "site": "fit", "step": 1,
                       "error": {"type": "InjectedPreemption",
                                 "kind": "retryable",
                                 "message": "UNAVAILABLE: injected"}}, f)
        hb = tmp_path / "hb"
        hb.mkdir()
        (hb / "rank0.hb").write_text(json.dumps({"step": 2, "time": 102.0}))
        tl = events.merge_timeline(d, heartbeat_dir=str(hb))
        assert tl["first_failing_rank"] == 1
        assert tl["first_failure"]["site"] == "step_start"
        assert tl["first_failure"]["step"] == 1
        assert tl["ranks"]["1"]["last_step"] == 1
        assert tl["ranks"]["0"]["last_step"] == 2
        assert tl["ranks"]["0"]["heartbeat"]["step"] == 2
        assert tl["first_stalled_rank"] == 1  # its trace ends earliest
        ts = [e["t"] for e in tl["events"]]
        assert ts == sorted(ts)  # one merged, time-ordered stream
        text = events.format_timeline(tl)
        assert "rank 1" in text and "step_start" in text

    def test_finished_rank_does_not_mask_real_failure(self, tmp_path):
        """Regression: rank 0 exhausts its data (end_of_data) BEFORE rank 1
        faults — the later, real fault must still be the first failure."""
        d = str(tmp_path)
        self._write(d, 0, [
            {"t": 100.0, "name": "data_fetch", "ph": "E", "rank": 0,
             "step": 5, "end_of_data": True, "dur_s": 0.001},
        ])
        self._write(d, 1, [
            {"t": 101.0, "name": "chaos", "ph": "P", "rank": 1,
             "site": "step_start", "kind": "preempt", "step": 4},
        ])
        tl = events.merge_timeline(d)
        assert tl["first_failing_rank"] == 1
        assert tl["first_failure"]["site"] == "step_start"

    def test_recovered_restart_does_not_outrank_terminal_fault(
            self, tmp_path):
        """An in-process restart RECOVERED from its error — the later
        fault that actually killed the gang must be the first failure."""
        d = str(tmp_path)
        self._write(d, 0, [
            {"t": 100.0, "name": "restart", "ph": "P", "rank": 0,
             "attempt": 1, "kind": "retryable",
             "error": "XlaRuntimeError: UNAVAILABLE (recovered)"},
            {"t": 150.0, "name": "step_compute", "ph": "E", "rank": 0,
             "step": 40, "dur_s": 0.01},
        ])
        self._write(d, 1, [
            {"t": 140.0, "name": "chaos", "ph": "P", "rank": 1,
             "site": "step_start", "kind": "fatal", "step": 30},
        ])
        tl = events.merge_timeline(d)
        assert tl["first_failing_rank"] == 1
        assert tl["first_failure"]["site"] == "step_start"
        # ...but with no terminal evidence, the recovered error is named
        os.unlink(os.path.join(d, "events_rank1.jsonl"))
        tl = events.merge_timeline(d)
        assert tl["first_failing_rank"] == 0
        assert tl["first_failure"].get("recovered") is True

    def test_recovered_attempts_chaos_evidence_is_demoted_too(
            self, tmp_path):
        """Not just the restart event: the recovered attempt's own chaos/
        span-error evidence precedes its restart and must rank below the
        fault that killed the gang."""
        d = str(tmp_path)
        self._write(d, 0, [
            {"t": 100.0, "name": "chaos", "ph": "P", "rank": 0,
             "site": "step_start", "kind": "preempt", "step": 3},
            {"t": 100.5, "name": "step_compute", "ph": "E", "rank": 0,
             "step": 3, "dur_s": 0.01,
             "error": "InjectedPreemption: UNAVAILABLE"},
            {"t": 101.0, "name": "restart", "ph": "P", "rank": 0,
             "attempt": 1, "kind": "retryable",
             "error": "InjectedPreemption: UNAVAILABLE"},
        ])
        self._write(d, 1, [
            {"t": 140.0, "name": "chaos", "ph": "P", "rank": 1,
             "site": "step_start", "kind": "fatal", "step": 30},
        ])
        tl = events.merge_timeline(d)
        assert tl["first_failing_rank"] == 1
        assert tl["first_failure"]["step"] == 30
        assert "recovered" not in tl["first_failure"]

    def test_hang_outranks_recovered_error_for_attribution(self, tmp_path):
        """A rank that RECOVERED its error must not be blamed for a later
        hang on another rank: with no terminal evidence, the stall
        heuristic names the rank that went quiet."""
        d = str(tmp_path)
        self._write(d, 0, [  # hangs after step 5 — goes quiet at t=150
            {"t": 150.0, "name": "step_compute", "ph": "E", "rank": 0,
             "step": 5, "dur_s": 0.01},
        ])
        self._write(d, 1, [  # recovered at t=100, kept training to t=190
            {"t": 100.0, "name": "restart", "ph": "P", "rank": 1,
             "attempt": 1, "kind": "retryable",
             "error": "XlaRuntimeError: UNAVAILABLE (recovered)"},
            {"t": 190.0, "name": "step_compute", "ph": "E", "rank": 1,
             "step": 30, "dur_s": 0.01},
        ])
        tl = events.merge_timeline(d)
        assert tl["first_failing_rank"] == 0  # the hung rank, not rank 1
        assert tl["first_stalled_rank"] == 0
        text = events.format_timeline(tl)
        assert "rank 0 stalled first" in text
        assert "recovered in-process" in text  # narrative, not blame

    def test_stall_pick_consults_heartbeats(self, tmp_path):
        """A rank whose event stream froze (size cap / never streamed) but
        whose heartbeat is fresh must not be blamed as first-stalled."""
        d = str(tmp_path)
        self._write(d, 0, [{"t": 100.0, "name": "step_compute", "ph": "E",
                            "rank": 0, "step": 1, "dur_s": 0.01}])
        self._write(d, 1, [{"t": 200.0, "name": "step_compute", "ph": "E",
                            "rank": 1, "step": 50, "dur_s": 0.01}])
        hb = tmp_path / "hb"
        hb.mkdir()
        # rank 0 kept beating long after its trace froze; rank 1 went
        # silent at t=200 with no heartbeat at all
        (hb / "rank0.hb").write_text(
            json.dumps({"step": 300, "time": 500.0}))
        tl = events.merge_timeline(d, heartbeat_dir=str(hb))
        assert tl["first_stalled_rank"] == 1

    def test_empty_dir_yields_no_ranks(self, tmp_path):
        tl = events.merge_timeline(str(tmp_path))
        assert tl["ranks"] == {} and tl["first_failing_rank"] is None

    def test_clear_rank_files_globs_all_ranks(self, tmp_path):
        """A reused event dir from an earlier, LARGER gang must not leak a
        stale high-rank trace into the next attempt's timeline."""
        d = str(tmp_path)
        self._write(d, 7, [{"t": 1.0, "name": "chaos", "ph": "P",
                            "rank": 7, "site": "worker",
                            "kind": "fatal"}])
        (tmp_path / "postmortem_rank7.json").write_text("{}")
        events.clear_rank_files(d)  # rank 7 cleared (glob, not 0..np-1)
        assert list(tmp_path.iterdir()) == []

    def test_last_step_ignores_prefetch_feed_events(self, tmp_path):
        """feed_lookahead: data_fetch spans run steps AHEAD of compute —
        the timeline must report the last step the rank actually computed,
        not the feed position."""
        d = str(tmp_path)
        self._write(d, 0, [
            {"t": 1.0, "name": "step_compute", "ph": "E", "rank": 0,
             "step": 10, "dur_s": 0.01},
            {"t": 1.1, "name": "data_fetch", "ph": "E", "rank": 0,
             "step": 14, "dur_s": 0.001},  # prefetcher, 4 steps ahead
        ])
        tl = events.merge_timeline(d)
        assert tl["ranks"]["0"]["last_step"] == 10

    def test_clear_rank_files_removes_stale_gang_timeline(self, tmp_path):
        (tmp_path / events.GANG_TIMELINE_FILE).write_text("{}")
        events.clear_rank_files(str(tmp_path))
        assert list(tmp_path.iterdir()) == []

    def test_torn_tail_line_is_skipped(self, tmp_path):
        d = str(tmp_path)
        with open(os.path.join(d, "events_rank0.jsonl"), "w") as f:
            f.write(json.dumps({"t": 1.0, "name": "a", "ph": "P",
                                "rank": 0, "step": 5}) + "\n")
            f.write('{"t": 2.0, "name": "tru')  # SIGKILL mid-write
        tl = events.merge_timeline(d)
        assert tl["ranks"]["0"]["n_events"] == 1
        assert tl["ranks"]["0"]["last_step"] == 5


_TIMELINE_WORKER = """
import os, sys, time
sys.path.insert(0, {repo!r})
from sparkdl_tpu.runner import chaos, events
rank = int(os.environ["SPARKDL_PROCESS_ID"])
for step in range(4):
    with events.span("step_compute", step=step):
        try:
            chaos.fire("step_start", step=step)
        except Exception as e:
            events.postmortem(e, site="step_start", step=step)
            raise
        time.sleep(0.05)
time.sleep(60)  # survivor: wait for the gang kill
"""


class TestGangTimeline:
    def test_supervise_failure_carries_merged_timeline(self, tmp_path):
        """Acceptance: a chaos-injected gang failure under supervise()
        produces a merged, time-ordered gang-timeline postmortem naming
        the first-failing rank, its last step, and the fault site."""
        script = tmp_path / "w.py"
        script.write_text(_TIMELINE_WORKER.format(repo=_REPO))
        event_dir = tmp_path / "events"
        plan = FaultPlan([Fault("step_start", "preempt", at_step=2,
                                rank=1)])
        with pytest.raises(GangFailure) as ei:
            supervise(str(script), np=2, timeout_s=120.0, max_restarts=0,
                      backoff_s=0.05, poll_s=0.25, plan=plan,
                      event_dir=str(event_dir))
        err = ei.value
        assert err.timeline is not None
        assert err.timeline["first_failing_rank"] == 1
        assert err.timeline["first_failure"]["site"] == "step_start"
        assert err.timeline["first_failure"]["step"] == 2
        assert err.timeline["ranks"]["1"]["last_step"] == 2
        ts = [e["t"] for e in err.timeline["events"]]
        assert ts == sorted(ts)
        # written next to the salvaged stderr, and named in the message
        merged = event_dir / events.GANG_TIMELINE_FILE
        assert merged.exists()
        assert json.loads(merged.read_text())["first_failing_rank"] == 1
        assert "gang timeline" in str(err)
        assert "first failure on rank 1" in str(err)


class TestGangEventDirIsolation:
    def test_supervise_does_not_clobber_driver_event_stream(
            self, tmp_path, monkeypatch):
        """A driver with its own recorder streaming to SPARKDL_EVENT_DIR
        must keep its events_rank0.jsonl across supervise(): the gang gets
        a subdir, so per-attempt clearing can't unlink the driver's live
        file or conflate driver events with worker rank 0's."""
        monkeypatch.setenv("SPARKDL_EVENT_DIR", str(tmp_path))
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "0")
        rec = events.reset()
        rec.event("driver_alive")
        script = tmp_path / "w.py"
        script.write_text("import sys; sys.exit(1)\n")
        with pytest.raises(GangFailure):
            supervise(str(script), np=1, timeout_s=30.0, max_restarts=0,
                      backoff_s=0.05, poll_s=0.25)
        rec.event("driver_still_alive")
        lines = (tmp_path / "events_rank0.jsonl").read_text().splitlines()
        assert [json.loads(ln)["name"] for ln in lines] == \
            ["driver_alive", "driver_still_alive"]
        # the gang ran in its own unique subdir namespace — and since the
        # jax-free worker streamed nothing, the empty dir was pruned on
        # the give-up path rather than left as clutter
        assert not any(p.name.startswith("gang-")
                       for p in tmp_path.iterdir() if p.is_dir())


class TestHeartbeatSatellite:
    def test_touch_heartbeat_is_atomic_json(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SPARKDL_HEARTBEAT_DIR", str(tmp_path))
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "2")
        t0 = time.time()
        metrics_lib.touch_heartbeat(7)
        body = json.loads((tmp_path / "rank2.hb").read_text())
        assert body["step"] == 7
        assert t0 - 1 <= body["time"] <= time.time() + 1
        # no tmp droppings left behind (the os.replace committed)
        assert [p.name for p in tmp_path.iterdir()] == ["rank2.hb"]

    def test_watchdog_parses_json_and_legacy_bodies(self, tmp_path):
        (tmp_path / "rank0.hb").write_text(
            json.dumps({"step": 12, "time": 1.0}))
        (tmp_path / "rank1.hb").write_text("34")  # pre-PR-2 bare body
        ages = launcher._heartbeat_ages(str(tmp_path), 2, time.time())
        assert ages[0][1] == "12"
        assert ages[1][1] == "34"


class TestMetricsLoggerSatellite:
    def test_tb_unavailable_falls_back_to_log(self, tmp_path, monkeypatch,
                                              caplog):
        monkeypatch.setitem(sys.modules, "tensorboardX", None)
        logger = MetricsLogger(str(tmp_path / "tb"))
        assert logger._tb is None  # fell back without raising
        with caplog.at_level("INFO", logger="sparkdl_tpu.runner"):
            logger.log(1, {"loss": 0.5})
        assert "loss" in caplog.text
        logger.close()

    def test_non_numeric_values_do_not_crash(self, caplog):
        logger = MetricsLogger(None)
        with caplog.at_level("INFO", logger="sparkdl_tpu.runner"):
            logger.log(2, {"loss": np.float32(1.5), "note": "warmup",
                           "arr": np.ones(3)})  # .item-bearing, not scalar
        assert "warmup" in caplog.text
        logger.close()

    def test_close_is_idempotent(self, tmp_path, monkeypatch):
        # A fake tensorboardX: importing the real one costs ~20s of the
        # tier-1 budget (ISSUE 10 headroom satellite) and the close
        # contract is about MetricsLogger's state machine, not the
        # writer. The fallback path has its own test above.
        closes = []

        class _FakeWriter:
            def __init__(self, log_dir):
                self.log_dir = log_dir

            def add_scalar(self, *a):
                pass

            def close(self):
                closes.append(1)

        fake = types.ModuleType("tensorboardX")
        fake.SummaryWriter = _FakeWriter
        monkeypatch.setitem(sys.modules, "tensorboardX", fake)
        logger = MetricsLogger(str(tmp_path / "tb"))
        assert logger._tb is not None
        logger.close()
        logger.close()  # second close must be a no-op
        assert closes == [1]  # the writer closed exactly once
        assert logger._tb is None
        logger.log(1, {"loss": 1.0})  # and logging still works (text path)

    def test_log_summary_flattens_nested_blocks(self, caplog):
        logger = MetricsLogger(None)
        with caplog.at_level("INFO", logger="sparkdl_tpu.runner"):
            logger.log_summary(10, {"examples_per_sec": 5.0, "mfu": None,
                                    "step_time": {"p50_s": 0.1}})
        assert "step_time_p50_s" in caplog.text
        assert "mfu" not in caplog.text  # None dropped, not logged as null
        logger.close()


class TestTraceSatellite:
    def test_region_failure_still_stops_profiler(self, monkeypatch):
        calls = []
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda d: calls.append("start"))

        def stop():
            calls.append("stop")
            raise RuntimeError("No profiler session running")

        monkeypatch.setattr(jax.profiler, "stop_trace", stop)
        # failed region: stop IS attempted, its error does not mask ours
        with pytest.raises(ValueError, match="user bug"):
            with metrics_lib.trace("/tmp/x"):
                raise ValueError("user bug")
        assert calls == ["start", "stop"]

    def test_stop_error_propagates_when_region_succeeded(self, monkeypatch):
        monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)

        def stop():
            raise RuntimeError("profiler broke")

        monkeypatch.setattr(jax.profiler, "stop_trace", stop)
        with pytest.raises(RuntimeError, match="profiler broke"):
            with metrics_lib.trace("/tmp/x"):
                pass

    def test_trace_emits_event_with_dir(self, monkeypatch):
        rec = events.reset()
        monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
        monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
        ctx = XlaRunner(np=8).make_context()
        with ctx.trace("/tmp/sparkdl_trace_test"):
            pass
        ev = [e for e in rec.tail() if e["name"] == "profile_trace"]
        assert ev and ev[0]["trace_dir"] == "/tmp/sparkdl_trace_test"


class TestDegradations:
    """ISSUE 4: survived-fault events (retry / quarantine / rollback) are
    timeline NARRATIVE — collected, rendered, never failure evidence."""

    def _write(self, d, rank, recs):
        with open(os.path.join(d, f"events_rank{rank}.jsonl"), "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    def _recs(self):
        return [
            {"t": 100.0, "name": "retry", "ph": "P", "rank": 0,
             "stage": "dispatch", "attempt": 1,
             "error": "InjectedPreemption: UNAVAILABLE"},
            {"t": 100.5, "name": "quarantine", "ph": "P", "rank": 0,
             "rows": 3, "error_class": "ValueError", "total": 3},
            {"t": 101.0, "name": "checkpoint_rollback", "ph": "P",
             "rank": 0, "from_step": 4, "to_step": 2},
            {"t": 102.0, "name": "step_compute", "ph": "E", "rank": 0,
             "step": 3},
        ]

    def test_merge_timeline_collects_degradations(self, tmp_path):
        d = str(tmp_path)
        self._write(d, 0, self._recs() + [
            {"t": 103.0, "name": "chaos", "ph": "P", "rank": 0,
             "site": "step_start", "kind": "preempt", "step": 4}])
        tl = events.merge_timeline(d)
        kinds = [dg["kind"] for dg in tl["degradations"]]
        assert kinds == ["retry", "quarantine", "checkpoint_rollback"]
        # the retry's error text did NOT become failure evidence: the
        # later chaos fire is still the first failure
        assert tl["first_failure"]["site"] == "step_start"
        assert tl["first_failure"]["t"] == 103.0
        rendered = events.format_timeline(tl)
        assert "survived degradations" in rendered
        assert "checkpoint_rollback x1" in rendered

    def test_collect_degradations_success_path(self, tmp_path):
        d = str(tmp_path)
        self._write(d, 0, self._recs())
        self._write(d, 1, [{"t": 99.0, "name": "retry", "ph": "P",
                            "rank": 1, "stage": "fetch", "attempt": 1}])
        out = events.collect_degradations(d)
        assert [r["name"] for r in out] == [
            "retry", "retry", "quarantine", "checkpoint_rollback"]
        assert out[0]["rank"] == 1  # time-ordered across ranks
        assert events.collect_degradations(str(tmp_path / "missing")) == []
