"""ByteBPETokenizer: the self-contained tokenizer that makes the
config-5 STRING-column serving path runnable with zero external assets
(round-4 verdict Next #5). Round-trip is guaranteed by the byte base;
training must actually compress; save/load must reproduce encodings;
and the registerTextGenerationUDF wiring must run string → tokens →
generate → string end-to-end."""

import jax
import numpy as np
import pytest

from sparkdl_tpu.models.tokenizer import ByteBPETokenizer

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the lazy dog sleeps while the quick fox runs",
    "a quick brown dog and a lazy fox",
    "the the the quick quick lazy lazy fox dog",
]


def test_untrained_round_trip_any_text():
    tok = ByteBPETokenizer()
    for text in ["hello world", "", "  spaces  and\nnewlines\t",
                 "unicode: héllo wörld — ≠ 🦊", "a"]:
        assert tok.decode(tok.encode(text)) == text
    # untrained = pure bytes + specials
    assert tok.vocab_size == 259
    assert tok.encode("ab") == [97, 98]


def test_training_learns_merges_and_compresses():
    tok = ByteBPETokenizer.train(CORPUS, vocab_size=320)
    assert 259 < tok.vocab_size <= 320
    text = "the quick lazy fox"
    ids = tok.encode(text)
    assert len(ids) < len(text.encode())  # actually compresses
    assert tok.decode(ids) == text
    # unseen text (even unseen bytes) still round-trips via byte fallback
    assert tok.decode(tok.encode("zebra ≠ fox!")) == "zebra ≠ fox!"


def test_specials_and_flags():
    tok = ByteBPETokenizer.train(CORPUS, vocab_size=280)
    ids = tok.encode("the fox", add_bos=True, add_eos=True)
    assert ids[0] == ByteBPETokenizer.BOS
    assert ids[-1] == ByteBPETokenizer.EOS
    # specials decode to nothing — generation output with a trailing EOS
    # detokenizes cleanly
    assert tok.decode(ids) == "the fox"
    assert tok.decode([ByteBPETokenizer.PAD] * 3) == ""


def test_save_load_reproduces_encoding(tmp_path):
    tok = ByteBPETokenizer.train(CORPUS, vocab_size=300)
    p = str(tmp_path / "bpe.json")
    tok.save(p)
    tok2 = ByteBPETokenizer.load(p)
    assert tok2.vocab_size == tok.vocab_size
    for text in CORPUS + ["held-out the lazy zebra"]:
        assert tok2.encode(text) == tok.encode(text)
    with pytest.raises(ValueError, match="format"):
        import json
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            json.dump({"merges": []}, f)
        ByteBPETokenizer.load(bad)


def test_deterministic_training():
    a = ByteBPETokenizer.train(CORPUS, vocab_size=300)
    b = ByteBPETokenizer.train(CORPUS, vocab_size=300)
    assert a.merges == b.merges


def test_fuzz_round_trip_random_unicode():
    """Byte-base invariant under fuzz: ANY string round-trips through a
    TRAINED tokenizer — ascii, multi-byte code points, random unicode,
    whitespace runs, control chars."""
    import random

    tok = ByteBPETokenizer.train(CORPUS, vocab_size=320)
    rnd = random.Random(0)
    pool = (
        [chr(c) for c in range(32, 127)]
        + list("äöüßéè日本語中文한국어🦊🎉∑≠  ")
        + list("\t\n\r ") * 5
    )
    for _ in range(300):
        s = "".join(rnd.choice(pool) for _ in range(rnd.randint(0, 60)))
        assert tok.decode(tok.encode(s)) == s


def test_text_generation_udf_end_to_end_with_in_repo_tokenizer():
    """BASELINE config-5 string serving with ZERO external assets: train
    the tokenizer in-process, size the model's vocab off it, and drive a
    string column through registerTextGenerationUDF."""
    import sparkdl_tpu as sdl
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel
    from sparkdl_tpu.udf import registerTextGenerationUDF, unregisterUDF

    tok = ByteBPETokenizer.train(CORPUS, vocab_size=300)
    cfg = LlamaConfig.tiny()  # vocab 512 covers the 300 tokenizer ids
    assert cfg.vocab_size >= tok.vocab_size
    model = LlamaModel(cfg)
    v = model.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.int32))

    df = sdl.DataFrame.fromPydict(
        {"prompt": ["the quick fox", "a lazy dog", "the the the"]})
    registerTextGenerationUDF(
        "txt", model, v, encode=tok.encode, decode=tok.decode,
        max_new_tokens=4, batchRows=2, eos_id=ByteBPETokenizer.EOS)
    try:
        out = sdl.applyUDF(df, "txt", "prompt", "completion").collect()
    finally:
        unregisterUDF("txt")
    assert len(out) == 3
    for r in out:
        assert isinstance(r["completion"], str)
    # prompts survive untouched alongside the completion column
    assert [r["prompt"] for r in out] == \
        ["the quick fox", "a lazy dog", "the the the"]
