"""Bench orchestrator contract tests (round-3 verdict Next #1).

The hard requirement: bench.py ALWAYS prints exactly one parsed JSON
record, fast, whatever the backend does — a hung backend (the r01/r03
outage) must produce a machine-readable error within the probe timeout,
and an exhausted wall budget must surface as budget_exhausted, never as
silence or a SIGKILL with no record.

These run bench.py as a real subprocess with the test env inherited
(conftest pins JAX_PLATFORMS=cpu), so the probe worker exercises the same
code path the driver does.
"""

import json
import os
import subprocess
import sys
import time

import pytest

_BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _run(env_extra: dict, timeout: float = 120) -> dict:
    env = dict(os.environ)
    env.update(env_extra)
    proc = subprocess.run([sys.executable, _BENCH], capture_output=True,
                          text=True, timeout=timeout, env=env)
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.strip().startswith("{")]
    assert lines, f"no JSON record printed; stdout={proc.stdout[-400:]!r} " \
                  f"stderr={proc.stderr[-400:]!r}"
    return json.loads(lines[-1])


def test_hung_backend_yields_error_record_fast():
    """Simulated hang (every worker sleeps): the record must print within
    roughly the probe timeout, with the outage machine-readable."""
    t0 = time.monotonic()
    rec = _run({"BENCH_FAKE_HANG_S": "300", "BENCH_PROBE_TIMEOUT_S": "5",
                "BENCH_WALL_S": "60"})
    wall = time.monotonic() - t0
    assert rec["value"] == 0.0
    assert rec["vs_baseline"] == 0.0
    assert rec["error"]["kind"] == "backend_unavailable"
    assert rec["extra"]["probe_error"]["kind"] == "timeout"
    assert wall < 30, f"error record took {wall:.0f}s"


def test_exhausted_budget_yields_error_record():
    """A wall budget too small for even the probe must still produce the
    record, flagged budget_exhausted."""
    rec = _run({"BENCH_WALL_S": "1"})
    assert rec["value"] == 0.0
    assert rec["error"]["kind"] == "backend_unavailable"
    assert rec["extra"]["probe_error"]["kind"] == "budget_exhausted"


@pytest.mark.slow
def test_probe_worker_records_backend_identity():
    """The probe leg must report what the backend registers as — the
    artifact that settles the axon-vs-tpu platform-gate question each
    round (round-3 verdict Missing #2)."""
    proc = subprocess.run(
        [sys.executable, _BENCH, "--worker", "probe"],
        capture_output=True, text=True, timeout=180, env=dict(os.environ))
    assert proc.returncode == 0, proc.stderr[-400:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    for key in ("default_backend", "device_kind", "is_tpu", "compiled_ok",
                "flash_attention_default"):
        assert key in rec, f"probe record missing {key}: {rec}"
    assert rec["compiled_ok"] is True
