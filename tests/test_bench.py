"""Bench orchestrator contract tests (round-3 verdict Next #1).

The hard requirement: bench.py ALWAYS prints exactly one parsed JSON
record, fast, whatever the backend does — a hung backend (the r01/r03
outage) must produce a machine-readable error within the probe timeout,
and an exhausted wall budget must surface as budget_exhausted, never as
silence or a SIGKILL with no record.

These run bench.py as a real subprocess with the test env inherited
(conftest pins JAX_PLATFORMS=cpu), so the probe worker exercises the same
code path the driver does.
"""

import json
import os
import subprocess
import sys
import time

import pytest

_BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _run(env_extra: dict, timeout: float = 120) -> dict:
    env = dict(os.environ)
    env.update(env_extra)
    proc = subprocess.run([sys.executable, _BENCH], capture_output=True,
                          text=True, timeout=timeout, env=env)
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.strip().startswith("{")]
    assert lines, f"no JSON record printed; stdout={proc.stdout[-400:]!r} " \
                  f"stderr={proc.stderr[-400:]!r}"
    return json.loads(lines[-1])


def test_hung_backend_yields_error_record_fast(tmp_path):
    """Simulated hang (every worker sleeps): the record must print within
    roughly the probe timeout, with the outage machine-readable — and it
    must embed the newest on-chip evidence (fixture-fed: the test does
    not depend on which artifacts a checkout happens to carry)."""
    for idx, val in (("", 111.0), ("2", 222.0)):
        (tmp_path / f"BENCH_TPU_MEASURED{idx}.json").write_text(json.dumps(
            {"metric": "resnet50_dp_train_throughput", "value": val,
             "unit": "img/s/chip",
             "extra": {"backend": {"is_tpu": True}, "mfu": 0.3,
                       "git_rev": "abc123"}}))
    (tmp_path / "PROBE_LOG").write_text(
        "2026-07-30T16:21:58Z down 120s probe-hung\n"
        "2026-07-30T20:55:00Z up 5s 1 tpu TPU v5 lite\n")
    t0 = time.monotonic()
    rec = _run({"BENCH_FAKE_HANG_S": "300", "BENCH_PROBE_TIMEOUT_S": "5",
                "BENCH_WALL_S": "60",
                "BENCH_MEASURED_DIR": str(tmp_path),
                "BENCH_PROBE_LOG_PATH": str(tmp_path / "PROBE_LOG")})
    wall = time.monotonic() - t0
    assert rec["value"] == 0.0
    assert rec["vs_baseline"] == 0.0
    assert rec["error"]["kind"] == "backend_unavailable"
    assert rec["extra"]["probe_error"]["kind"] == "timeout"
    assert wall < 30, f"error record took {wall:.0f}s"
    # Self-contained outage evidence: highest filename index wins (git
    # checkouts do not preserve mtimes), probe history summarized.
    lm = rec["extra"]["last_measured"]
    assert lm["file"] == "BENCH_TPU_MEASURED2.json" and lm["value"] == 222.0
    assert rec["extra"]["probe_log"] == {
        "attempts": 2, "ups": 1, "first": "2026-07-30T16:21:58Z",
        "last": "2026-07-30T20:55:00Z"}


def test_exhausted_budget_yields_error_record():
    """A wall budget too small for even the probe must still produce the
    record, flagged budget_exhausted."""
    rec = _run({"BENCH_WALL_S": "1"})
    assert rec["value"] == 0.0
    assert rec["error"]["kind"] == "backend_unavailable"
    assert rec["extra"]["probe_error"]["kind"] == "budget_exhausted"


def _load_serve_bench():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(os.path.dirname(_BENCH), "scripts",
                                    "serve_bench.py"))
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    return sb


def _retry_once(run, ok):
    """Wall-clock stub comparisons ride time.sleep() on a shared CI
    host: one retry absorbs a loaded-host scheduling hiccup without
    weakening the floors (both attempts must run the SAME deterministic
    workload — flakiness here is timer noise, never workload noise)."""
    rec = run()
    if ok(rec):
        return rec
    return run()


def test_stub_scheduler_stall_free_beats_blocking():
    """ISSUE 10 regression pin without hardware: on the long-prompt mix
    with deterministic synthetic device costs (jax-free StubBackend),
    the stall-free scheduler (chunked prefill + shared-prefix reuse)
    must beat the PR 8 blocking engine on aggregate tokens/s (floor
    1.2x — bench-record target 1.3x), cut prefill-induced decode-stall
    wall time (floor 2.5x — record target 5x), and improve TTFT p99
    (floor 1.2x — record target 2x)."""
    sb = _load_serve_bench()
    rec = _retry_once(
        lambda: sb.run_stub_scheduler_comparison(n_requests=96),
        lambda r: (r["speedup_vs_blocking"] >= 1.2
                   and r["decode_stall_ratio"] >= 2.5
                   and r["ttft_p99_ratio"] >= 1.2))
    assert rec["speedup_vs_blocking"] >= 1.2, rec
    assert rec["decode_stall_ratio"] >= 2.5, rec
    assert rec["ttft_p99_ratio"] >= 1.2, rec
    # the win comes from the prefix cache + chunking, and the record
    # proves it: warm traffic hits the cache
    assert rec["prefix_cache"]["hit_rate"] >= 0.5, rec["prefix_cache"]


def test_paged_engine_beats_per_slot_on_high_churn():
    """ISSUE 11 regression pin without hardware: at FIXED pool bytes on
    the short-output high-churn mix, the paged 32-slot engine must beat
    the PR 9 per-slot 8-slot engine on tokens/s (floor 1.3x), run the
    pool hot (peak utilization >= 0.8 — throughput is bounded by pool
    bytes, not max_len x slots), and hold the shared preamble as ONE
    physical block set (blocks_shared_frac > 0)."""
    sb = _load_serve_bench()
    rec = _retry_once(
        lambda: sb.run_paged_churn_comparison(n_requests=192),
        lambda r: (r.get("paged_speedup", 0) >= 1.3
                   and (r.get("kv_pool_utilization") or 0) >= 0.8))
    assert rec["paged_speedup"] >= 1.3, rec
    assert rec["kv_pool_utilization"] >= 0.8, rec
    assert rec["blocks_shared_frac"] > 0, rec
    assert rec["paged"]["completed"] == rec["paged"]["requests"], rec
    # the admission-wait stats ride the record (healthy pool: ~0; a
    # too-small pool shows up here instead of as a crash)
    assert "admission_block_waits" in rec and "preemptions" in rec


def test_stub_spec_leg_beats_k0_engine():
    """ISSUE 12 regression pin without hardware: on the repetitive-text
    mix (small-vocab stub streams are periodic, so the request's own
    output is self-predictive — the default n-gram provider's home
    turf), the k=4 speculative engine must beat the k=0 engine >= 1.5x
    single-stream tokens/s (bench-record target 2x on the CPU-llama
    leg), with a sane draft-acceptance floor and token-identical
    output."""
    sb = _load_serve_bench()
    rec = _retry_once(
        lambda: sb.run_spec_comparison_stub(
            n_requests=16, ks=(0, 4), concurrencies=(1,),
            step_s=0.0015, n_new=32),
        lambda r: r.get("spec_speedup", 0) >= 1.5)
    assert rec["spec_speedup"] >= 1.5, rec
    assert rec["spec_accept_rate"] >= 0.3, rec  # acceptance sanity floor
    assert rec["spec_token_identical"] is True, rec


def test_serve_headline_carries_tp_fields():
    """ISSUE 14: the tp leg's identity / per-device-pool-bytes /
    re-trace evidence must ride ``_serve_headline`` into BOTH the
    healthy and backend_unavailable records (never-host-blind rule) —
    jax-free mapping pin on a synthetic serve record."""
    import bench

    serve = {
        "engine": {"8": {"tokens_s": 100.0}},
        "tp": {
            "tp_identical": True,
            "kv_pool_device_bytes": {"1": 1000, "2": 500, "4": 250},
            "kv_pool_device_frac": {"1": 1.0, "2": 0.5, "4": 0.25},
            "degrees": {
                "1": {"decode_retrace_after_warmup": 0,
                      "verify_retrace_after_warmup": 0},
                "2": {"decode_retrace_after_warmup": 0,
                      "verify_retrace_after_warmup": 0},
            },
        },
    }
    out = bench._serve_headline(serve)
    assert out["serve_tp_identical"] is True
    assert out["serve_tp_kv_pool_device_bytes"]["4"] == 250
    assert out["serve_tp_kv_pool_device_frac"]["2"] == 0.5
    assert out["serve_tp_retraces_after_warmup"] == 0
    # a tp-less record (BENCH_SKIP_TP / subprocess failure) adds none
    assert "serve_tp_identical" not in bench._serve_headline(
        {"engine": {}})


def test_multi_chunk_budget_admits_multiple_slots_per_iteration():
    """The ISSUE 11 budget pin: where the one-chunk PR 9 budget fills 1
    slot per iteration, SPARKDL_SERVE_PREFILL_BUDGET = 2 chunks fills
    2 — jax-free, deterministic (no sleeps)."""
    from sparkdl_tpu.serving import GenerationEngine, StubBackend

    def refills_completed_after_one_iteration(budget):
        eng = GenerationEngine(
            StubBackend(4, 64, vocab_size=100, block_size=4,
                        pool_blocks=80),
            prefill_chunk=4, prefill_budget=budget)
        for b in (1, 20, 40):  # one-chunk prompts: 1 chunk = 1 refill
            eng.submit(list(range(b, b + 4)), max_new_tokens=1)
        eng.step()
        done = eng.snapshot()["prefills"]
        eng.run_until_idle()
        return done

    assert refills_completed_after_one_iteration(None) == 1  # PR 9 cap
    assert refills_completed_after_one_iteration(8) == 2     # 2 slots
    assert refills_completed_after_one_iteration(12) == 3    # 3 slots


@pytest.mark.slow
def test_all_metric_legs_run_end_to_end_tiny_cpu():
    """Every metric leg's BODY executes end-to-end at tiny config on CPU
    (round-4 verdict Next #2): a leg regression must turn the suite red,
    never be discovered on chip time. Asserts the one-record contract,
    every leg's keys present, no *_error fields, an honest null
    vs_baseline when no baseline exists (BENCH_BASELINE_PATH pointed at
    a nonexistent temp path, so a real chip baseline in the repo never
    leaks into this CPU run), and the EOS leg proving a MID-STREAM
    while_loop exit (0 < steps < new)."""
    import tempfile
    _tmp = tempfile.mkdtemp()
    rec = _run({"BENCH_BASELINE_PATH": os.path.join(_tmp, "none.json"),
                "BENCH_MODEL": "ResNet18", "BENCH_IMAGE_SIZE": "64",
                "BENCH_BATCH_PER_CHIP": "8", "BENCH_STEPS": "3",
                "BENCH_FEAT_ROWS": "16", "BENCH_FEAT_BATCH": "8",
                "BENCH_BERT_CONFIG": "tiny", "BENCH_BERT_BATCH": "4",
                "BENCH_BERT_SEQ": "64", "BENCH_GEN_CONFIG": "tiny",
                "BENCH_GEN_BATCH": "2", "BENCH_GEN_PROMPT": "16",
                "BENCH_GEN_NEW": "8", "BENCH_FLASH_SEQS": "256",
                "BENCH_GEN_LC_PROMPT": "8", "BENCH_GEN_LC_CACHE": "256",
                "BENCH_GEN_LC_NEW": "4",
                # serve leg (ISSUE 8) at smoke scale: the leg BODY must
                # run, compile-free steady state and token identity are
                # asserted below; the >=3x speedup is a bench-record
                # criterion, not a tiny-CPU one
                "BENCH_SERVE_REQUESTS": "32", "BENCH_SERVE_SLOTS": "4",
                "BENCH_SERVE_CONCURRENCY": "1,8",
                # tp leg (ISSUE 14) at smoke scale: tp in {1,2} keeps
                # the 8-virtual-device subprocess inside the budget
                # while still proving identity + the 1/2 pool shrink
                "BENCH_TP_REQUESTS": "12", "BENCH_TP_DEGREES": "1,2",
                # the train leg compiles TWO signatures per swept batch
                # size since the uint8-streamed variant landed — the old
                # 480s/900s budgets left it no headroom on a loaded host
                "BENCH_TIMEOUT_S": "900",
                "BENCH_WALL_S": "1800"}, timeout=1800)
    assert rec["value"] > 0, rec
    assert rec["vs_baseline"] is None  # no baseline file -> null, not 1.0
    assert rec["extra"]["baseline"] == "none"
    assert "error" not in rec
    extra = rec["extra"]
    errs = [k for k in extra if k.endswith("_error")]
    assert not errs, {k: extra[k] for k in errs}
    for key in ("mfu", "featurizer_rows_per_sec", "featurizer_breakdown",
                "inference", "bert_tokens_s_chip", "gen_e2e_tokens_s",
                "flash", "host_ingest", "serving", "serve_tokens_s"):
        assert key in extra, f"leg output missing {key}: {sorted(extra)}"
    # serving leg (ISSUE 8): engine legs + static comparator recorded,
    # the decode step never re-traced after warmup, greedy continuous
    # batching token-identical to the static path
    sv = extra["serving"]
    assert extra["serve_tokens_s"] and extra["serve_tokens_s"] > 0
    assert sv["static"]["tokens_s"] > 0
    assert sv["decode_retrace_after_warmup"] == 0, sv
    assert sv["token_identical_spot_check"] is True
    assert all(leg["completed"] == leg["requests"]
               for leg in sv["engine"].values()), sv["engine"]
    # speculative leg (ISSUE 12): rides the serve record — greedy
    # identity + zero verify re-traces even at smoke scale, headline
    # mirrored next to serve_tokens_s
    spq = sv["spec"]
    assert spq["spec_token_identical"] is True, spq
    assert spq["verify_retrace_after_warmup"] == 0, spq
    assert extra["serve_spec_speedup"] == spq["spec_speedup"]
    assert extra["serve_spec_accept_rate"] == spq["spec_accept_rate"]
    # tensor-parallel leg (ISSUE 14): greedy identity across degrees,
    # per-device pool bytes halved at tp=2, zero re-traces — mirrored
    # into the headline next to serve_tokens_s
    tpq = sv["tp"]
    assert tpq["tp_identical"] is True, tpq
    assert extra["serve_tp_identical"] is True
    assert extra["serve_tp_kv_pool_device_frac"]["2"] == 0.5, tpq
    assert extra["serve_tp_retraces_after_warmup"] == 0, tpq
    # backend-free ingest leg (ISSUE 7): a real host-side number with
    # before/after deltas — the record that survives TPU outages
    hi = extra["host_ingest"]
    assert hi["value"] > 0 and hi["legs"]["f32_host"]["rows_per_sec"] > 0
    assert hi["deltas"]["rows_per_sec_vs_f32_host"] >= 2.0, hi["deltas"]
    assert hi["deltas"]["wire_bytes_ratio_f32_over_u8"] >= 4.0, hi["deltas"]
    # the inference-throughput record (ISSUE 3): rate + per-stage spans
    assert extra["inference"]["rows_per_sec"] > 0
    assert {"decode", "dispatch", "fetch", "encode"} <= \
        set(extra["inference"]["stage_seconds"]), extra["inference"]
    # bottleneck evidence per revision (ISSUE 6): overlap-aware busy
    # fractions + the named dominant stage ride next to stage_seconds
    su = extra["inference"]["stage_utilization"]
    assert su and su["dominant_stage"] in su["stages"], extra["inference"]
    assert all(0.0 <= s["busy_frac"] <= 1.0 for s in su["stages"].values())
    assert "gen_eos_error" not in extra
    # mid-stream EOS exit: the loop iterated, then stopped early
    assert 0 < extra["gen_eos_steps"] < extra["gen_new_tokens"], extra
    assert extra["gen_eos_steps"] == extra["gen_eos_expected_step"]
    assert extra["gen_eos_early_exit"] is True


@pytest.mark.slow
def test_northstar_leg_streams_in_o_batch_memory():
    """The north-star-scale leg (round-4 verdict Next #6) at reduced N:
    the streamed featurize→parquet run's peak-RSS growth must stay FAR
    below the materialized input size — the in-suite pin of the
    O(batch)-at-scale claim (measured 36 MB vs 226 MB materialized on
    CPU; bound set at 3x headroom)."""
    env = dict(os.environ)
    env.update({"BENCH_NORTHSTAR_ROWS": "1500",
                "BENCH_NORTHSTAR_BATCH": "64",
                "BENCH_NORTHSTAR_MODEL": "ResNet18",
                # single device, like the real single-chip deployment:
                # the 8-virtual-device test mesh multiplies XLA's
                # per-device allocator overhead into the RSS reading,
                # which is runtime noise, not data-plane residency
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    proc = subprocess.run(
        [sys.executable, _BENCH, "--worker", "northstar"],
        capture_output=True, text=True, timeout=540, env=env)
    assert proc.returncode == 0, proc.stderr[-600:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["northstar_rows"] == 1500
    assert rec["northstar_rows_per_sec"] > 0
    materialized = rec["northstar_input_mb_if_materialized"]
    assert materialized > 200  # the leg is actually at a meaningful N
    assert rec["northstar_peak_rss_delta_mb"] < min(materialized / 2, 120)


@pytest.mark.slow
def test_probe_worker_records_backend_identity():
    """The probe leg must report what the backend registers as — the
    artifact that settles the axon-vs-tpu platform-gate question each
    round (round-3 verdict Missing #2)."""
    proc = subprocess.run(
        [sys.executable, _BENCH, "--worker", "probe"],
        capture_output=True, text=True, timeout=180, env=dict(os.environ))
    assert proc.returncode == 0, proc.stderr[-400:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    for key in ("default_backend", "device_kind", "is_tpu", "compiled_ok",
                "flash_attention_default"):
        assert key in rec, f"probe record missing {key}: {rec}"
    assert rec["compiled_ok"] is True
