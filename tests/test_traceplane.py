"""Causal trace plane tests (ISSUE 17 tentpole): span-id/parent-id
propagation through the flight recorder, supervisor-minted trace ids
shipped to ranks via env, the merged Chrome-trace export
(``runner/traceview.py`` + ``scripts/trace_export.py``), the
``gang_resized`` never-failure-evidence rule under elastic resizes, the
engine's request-span parentage, and the BENCH trajectory gate
(``scripts/bench_trend.py``).

Fast and jax-free where possible: synthetic streams feed traceview and
merge_timeline; the one subprocess test launches hand-rolled stdlib
workers. The end-to-end proof (2-rank supervised gang + serving requests
→ one validated Perfetto trace) rides the slow obs_smoke leg in
test_chaos.py.
"""

import importlib.util
import json
import os
import sys
import threading

import pytest

from sparkdl_tpu.runner import events, launcher, telemetry, traceview

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Fresh recorder, no stream dir, no trace env — arming is per-test."""
    for v in ("SPARKDL_EVENT_DIR", events.TRACE_ID_ENV,
              events.TRACE_PARENT_ENV):
        monkeypatch.delenv(v, raising=False)
    events.reset()
    telemetry.reset()
    yield
    events.reset()
    telemetry.reset()


def _arm(monkeypatch, trace_id="t" * 16, parent=None):
    monkeypatch.setenv(events.TRACE_ID_ENV, trace_id)
    if parent:
        monkeypatch.setenv(events.TRACE_PARENT_ENV, parent)
    return trace_id


class TestTraceContext:
    def test_untraced_records_are_byte_identical(self):
        """With no SPARKDL_TRACE_ID the machinery must be invisible: no
        span_id/parent_id/trace_id keys anywhere — PR 2's record shape,
        unchanged."""
        rec = events.reset()
        with events.span("step_compute", step=1):
            events.event("chaos", site="step_start")
        for r in rec.tail():
            assert "span_id" not in r
            assert "parent_id" not in r
            assert "trace_id" not in r

    def test_armed_spans_chain_and_carry_trace_id(self, monkeypatch):
        tid = _arm(monkeypatch)
        rec = events.reset()
        with events.span("outer"):
            with events.span("inner"):
                events.event("chaos", site="x")
        by = {}
        for r in rec.tail():
            by.setdefault((r["name"], r["ph"]), r)
        outer = by[("outer", "B")]
        inner = by[("inner", "B")]
        point = by[("chaos", "P")]
        assert all(r["trace_id"] == tid for r in (outer, inner, point))
        assert outer["span_id"] and "parent_id" not in outer
        assert inner["parent_id"] == outer["span_id"]
        # a bare point event inside the region parents to the innermost
        # open span
        assert point["parent_id"] == inner["span_id"]
        # B and E of one span carry the SAME span_id
        assert by[("inner", "E")]["span_id"] == inner["span_id"]

    def test_sibling_after_exit_parents_to_enclosing(self, monkeypatch):
        _arm(monkeypatch)
        rec = events.reset()
        with events.span("outer"):
            with events.span("first"):
                pass
            with events.span("second"):
                pass
        by = {(r["name"], r["ph"]): r for r in rec.tail()}
        outer_id = by[("outer", "B")]["span_id"]
        assert by[("first", "B")]["parent_id"] == outer_id
        # the closed first span did NOT stay on the stack
        assert by[("second", "B")]["parent_id"] == outer_id

    def test_env_parent_is_the_outermost_fallback(self, monkeypatch):
        """A rank's outermost span — and a point event outside any span —
        chain to the supervise() attempt span shipped via env."""
        _arm(monkeypatch, parent="driver-span-7")
        rec = events.reset()
        events.event("restart", attempt=1)
        with events.span("step_compute", step=0):
            pass
        by = {(r["name"], r["ph"]): r for r in rec.tail()}
        assert by[("restart", "P")]["parent_id"] == "driver-span-7"
        assert by[("step_compute", "B")]["parent_id"] == "driver-span-7"

    def test_completed_span_mints_ids(self, monkeypatch):
        _arm(monkeypatch, parent="root-1")
        rec = events.reset()
        events.completed_span("serve_decode", 0.5, request=3)
        (r,) = [x for x in rec.tail()
                if x["name"] == "serve_decode" and x["ph"] == "E"]
        assert r["span_id"] and r["parent_id"] == "root-1"
        # explicit ids win over ambient context (the engine's
        # request-parented emission path)
        events.completed_span("serve_decode", 0.1, request=4,
                              span_id="S", parent_id="P")
        (r2,) = [x for x in rec.tail()
                 if x.get("request") == 4 and x["ph"] == "E"]
        assert r2["span_id"] == "S" and r2["parent_id"] == "P"

    def test_span_stack_is_thread_local(self, monkeypatch):
        """A feed thread's spans must never parent under the training
        loop's open span — each thread has its own stack."""
        _arm(monkeypatch)
        rec = events.reset()

        def feeder():
            with events.span("data_fetch"):
                pass

        with events.span("step_compute"):
            t = threading.Thread(target=feeder)
            t.start()
            t.join()
        by = {(r["name"], r["ph"]): r for r in rec.tail()}
        assert "parent_id" not in by[("data_fetch", "B")]

    def test_exception_exit_still_pops(self, monkeypatch):
        _arm(monkeypatch)
        rec = events.reset()
        with pytest.raises(RuntimeError):
            with events.span("outer"):
                with events.span("boom"):
                    raise RuntimeError("x")
        # the stack fully unwound: a new span is a root again
        with events.span("after"):
            pass
        by = {(r["name"], r["ph"]): r for r in rec.tail()}
        assert "parent_id" not in by[("after", "B")]


class TestLauncherPropagation:
    _WORKER = """
import json, os, sys
rank = int(os.environ["SPARKDL_PROCESS_ID"])
d = os.environ["SPARKDL_EVENT_DIR"]
rec = {"t": 100.0 + rank, "name": "worker_span", "ph": "E", "rank": rank,
       "dur_s": 0.5, "trace_id": os.environ.get("SPARKDL_TRACE_ID"),
       "span_id": f"w{rank}",
       "parent_id": os.environ.get("SPARKDL_TRACE_PARENT")}
with open(os.path.join(d, f"events_rank{rank}.jsonl"), "w") as f:
    f.write(json.dumps(rec) + "\\n")
"""

    def test_supervise_ships_trace_context_and_writes_manifest(
            self, tmp_path):
        """Both ranks inherit ONE trace id and a parent span id that
        resolves to the attempt span in the supervisor's manifest — the
        whole chain ends at the run root."""
        script = tmp_path / "w.py"
        script.write_text(self._WORKER)
        event_dir = str(tmp_path / "ev")
        launcher.supervise(str(script), np=2, timeout_s=60.0,
                           max_restarts=0, backoff_s=0.1, poll_s=0.1,
                           event_dir=event_dir)
        manifest = traceview.find_trace_manifest(event_dir)
        assert manifest and manifest["trace_id"]
        spans = {s["span_id"]: s for s in manifest["spans"]}
        root = manifest["root_span_id"]
        assert spans[root]["parent_id"] is None
        attempt = [s for s in manifest["spans"]
                   if s["name"] == "gang_attempt"]
        assert attempt and attempt[0]["parent_id"] == root
        for rank in (0, 1):
            with open(os.path.join(event_dir,
                                   f"events_rank{rank}.jsonl")) as f:
                (rec,) = [json.loads(ln) for ln in f]
            assert rec["trace_id"] == manifest["trace_id"]
            # the shipped parent IS the newest attempt span
            assert rec["parent_id"] == attempt[-1]["span_id"]

    def test_trace_env_of_caller_is_respected(self, tmp_path):
        """An outer orchestrator's trace id (env=) is adopted, not
        replaced — nested supervision joins the existing trace."""
        script = tmp_path / "w.py"
        script.write_text(self._WORKER)
        event_dir = str(tmp_path / "ev")
        launcher.supervise(str(script), np=1, timeout_s=60.0,
                           max_restarts=0, backoff_s=0.1, poll_s=0.1,
                           event_dir=event_dir,
                           env={events.TRACE_ID_ENV: "feedcafe01234567"})
        manifest = traceview.find_trace_manifest(event_dir)
        assert manifest["trace_id"] == "feedcafe01234567"


class TestMergeTimelineResize:
    def _write(self, d, rank, recs):
        with open(os.path.join(d, f"events_rank{rank}.jsonl"), "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    def test_gang_resized_is_narrative_never_failure_evidence(
            self, tmp_path):
        """ISSUE 17 satellite: under an elastic resize the timeline must
        show `gang_resized` in the degradation narrative — and even when
        the resize record carries error text (the dead rank's reason), it
        must never be promoted to failure evidence."""
        d = str(tmp_path)
        self._write(d, 0, [
            {"t": 100.0, "name": "gang_resized", "ph": "P", "rank": 0,
             "from_np": 4, "to_np": 3, "reason": "rank_died",
             "error": "rank 2 exited 137 (permanent)"},
            {"t": 101.0, "name": "step_compute", "ph": "E", "rank": 0,
             "step": 10, "dur_s": 0.01},
        ])
        tl = events.merge_timeline(d)
        assert tl["first_failure"] is None  # resize is not a fault
        kinds = [dg["kind"] for dg in tl["degradations"]]
        assert "gang_resized" in kinds
        assert "gang_resized" in events.format_timeline(tl)

    def test_resize_then_real_fault_attributes_to_the_fault(
            self, tmp_path):
        d = str(tmp_path)
        self._write(d, 0, [
            {"t": 100.0, "name": "gang_resized", "ph": "P", "rank": 0,
             "from_np": 2, "to_np": 1, "reason": "rank_died",
             "error": "rank 1 exited 137"},
            {"t": 105.0, "name": "chaos", "ph": "P", "rank": 0,
             "site": "step_start", "kind": "fatal", "step": 7},
        ])
        tl = events.merge_timeline(d)
        assert tl["first_failure"]["site"] == "step_start"
        assert tl["first_failure"]["step"] == 7
        assert any(dg["kind"] == "gang_resized"
                   for dg in tl["degradations"])


class TestTraceview:
    def _seed(self, tmp_path, with_manifest=True):
        ev = tmp_path / "ev"
        ev.mkdir()
        if with_manifest:
            (ev / "trace_manifest.json").write_text(json.dumps({
                "trace_id": "abc123", "root_span_id": "root",
                "spans": [{"span_id": "root", "parent_id": None,
                           "name": "supervise", "t": 100.0},
                          {"span_id": "a1", "parent_id": "root",
                           "name": "gang_attempt", "t": 100.2,
                           "attempt": 1}]}))
        recs0 = [
            {"t": 101.0, "name": "step_compute", "ph": "E", "rank": 0,
             "dur_s": 0.5, "trace_id": "abc123", "span_id": "s0",
             "parent_id": "a1", "step": 1},
            {"t": 101.2, "name": "chaos", "ph": "P", "rank": 0,
             "site": "step_start", "trace_id": "abc123",
             "parent_id": "s0"},
        ]
        recs1 = [
            {"t": 101.1, "name": "step_compute", "ph": "E", "rank": 1,
             "dur_s": 0.4, "trace_id": "abc123", "span_id": "s1",
             "parent_id": "a1", "step": 1},
        ]
        for rank, recs in ((0, recs0), (1, recs1)):
            with open(ev / f"events_rank{rank}.jsonl", "w") as f:
                for r in recs:
                    f.write(json.dumps(r) + "\n")
        return str(ev)

    def test_chrome_trace_shape(self, tmp_path):
        ev = self._seed(tmp_path)
        tr = traceview.chrome_trace(ev)
        assert tr["displayTimeUnit"] == "ms"
        evs = tr["traceEvents"]
        x = [e for e in evs if e["ph"] == "X"]
        i = [e for e in evs if e["ph"] == "i"]
        m = [e for e in evs if e["ph"] == "M"]
        # rank spans: ts back-dated by dur, µs scale
        s0 = next(e for e in x if e["args"].get("span_id") == "s0")
        assert s0["pid"] == 0
        assert s0["ts"] == pytest.approx((101.0 - 0.5) * 1e6)
        assert s0["dur"] == pytest.approx(0.5 * 1e6)
        # instants carry a scope
        assert all(e["s"] == "t" for e in i)
        # driver manifest spans on the synthetic driver pid
        driver = [e for e in x if e["pid"] == traceview.DRIVER_PID]
        assert {e["name"] for e in driver} == {"supervise",
                                               "gang_attempt"}
        # process/thread naming metadata present
        assert any(e["name"] == "process_name"
                   and e["args"]["name"] == "driver" for e in m)
        assert any(e["name"] == "process_name"
                   and e["args"]["name"] == "rank 1" for e in m)
        # skew is annotated even with no heartbeat dir — never silent
        skew = tr["otherData"]["clock_skew"]
        assert skew["measured"] is False and "unmeasured" in skew["note"]

    def test_counter_tracks_from_metrics_history(self, tmp_path):
        ev = self._seed(tmp_path)
        mdir = tmp_path / "m"
        mdir.mkdir()
        with open(mdir / "metrics_rank0.jsonl", "w") as f:
            for t, depth in ((101.0, 2), (101.5, 5)):
                f.write(json.dumps(
                    {"t": t, "rank": 0,
                     "gauges": {"serving_queue_depth":
                                {"value": depth, "max": 5}},
                     "counters": {"steps_total": t - 100.0}}) + "\n")
        tr = traceview.chrome_trace(ev, metrics_dir=str(mdir))
        c = [e for e in tr["traceEvents"] if e["ph"] == "C"]
        depths = [e["args"]["value"] for e in c
                  if e["name"] == "serving_queue_depth"]
        assert depths == [2, 5]
        assert any(e["name"] == "steps_total" for e in c)

    def test_validate_accepts_good_and_flags_broken_chains(
            self, tmp_path):
        ev = self._seed(tmp_path)
        tr = traceview.chrome_trace(ev)
        good = traceview.validate_chrome_trace(tr, require_ranks=2)
        assert good["ok"], good["problems"]
        assert good["ranks"] == [0, 1]
        # break a parent chain: an id that resolves nowhere
        tr["traceEvents"].append(
            {"ph": "X", "name": "orphan", "pid": 0, "tid": 9,
             "ts": 0, "dur": 1,
             "args": {"span_id": "zz", "parent_id": "missing"}})
        bad = traceview.validate_chrome_trace(tr)
        assert not bad["ok"]
        assert any("resolves to no known span" in p
                   for p in bad["problems"])

    def test_validate_flags_foreign_trace_id(self, tmp_path):
        ev = self._seed(tmp_path)
        tr = traceview.chrome_trace(ev)
        tr["traceEvents"].append(
            {"ph": "X", "name": "alien", "pid": 1, "tid": 9,
             "ts": 0, "dur": 1,
             "args": {"span_id": "zz", "trace_id": "OTHER"}})
        bad = traceview.validate_chrome_trace(tr)
        assert any("FOREIGN trace_id" in p for p in bad["problems"])

    def test_manifest_found_in_newest_gang_subdir(self, tmp_path):
        """Supervised runs write the manifest into the adopted gang-*
        subdir; the exporter must find it by the same newest-only rule
        the analysis reader uses."""
        ev = tmp_path / "ev"
        old = ev / "gang-1111-aaaa"
        new = ev / "gang-2222-bbbb"
        for d, tid in ((old, "oldtrace"), (new, "newtrace")):
            d.mkdir(parents=True)
            (d / "trace_manifest.json").write_text(json.dumps(
                {"trace_id": tid, "root_span_id": "r",
                 "spans": [{"span_id": "r", "parent_id": None,
                            "name": "supervise", "t": 1.0}]}))
            (d / "events_rank0.jsonl").write_text(json.dumps(
                {"t": 2.0, "name": "s", "ph": "E", "rank": 0,
                 "dur_s": 0.1}) + "\n")
        os.utime(old, (1, 1))
        m = traceview.find_trace_manifest(str(ev))
        assert m["trace_id"] == "newtrace"

    def test_clock_skew_measured_from_heartbeats(self, tmp_path):
        ev = self._seed(tmp_path)
        hb = tmp_path / "hb"
        hb.mkdir()
        p = hb / "rank0.hb"
        p.write_text(json.dumps({"step": 3, "time": 500.0}))
        os.utime(p, (500.0, 500.25))  # mtime (host) 0.25s after body
        skew = traceview.measure_clock_skew(str(hb))
        assert skew["measured"] is True
        assert skew["per_rank_s"]["0"] == pytest.approx(-0.25)
        tr = traceview.chrome_trace(ev, heartbeat_dir=str(hb))
        assert tr["otherData"]["clock_skew"]["measured"] is True

    def test_request_summary_track(self, tmp_path):
        """Completed serve_* folds become one summary span per request on
        the owning rank's `requests` lane."""
        ev = tmp_path / "ev"
        ev.mkdir()
        recs = [
            {"t": 10.2, "name": "serve_queue", "ph": "E", "rank": 0,
             "request": 1, "dur_s": 0.2},
            {"t": 10.5, "name": "serve_prefill", "ph": "E", "rank": 0,
             "request": 1, "dur_s": 0.3, "tokens": 3},
            {"t": 11.0, "name": "serve_decode", "ph": "E", "rank": 0,
             "request": 1, "dur_s": 0.5, "reason": "stop",
             "new_tokens": 4},
        ]
        with open(ev / "events_rank0.jsonl", "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        tr = traceview.chrome_trace(str(ev))
        assert tr["otherData"]["requests"] == 1
        req = next(e for e in tr["traceEvents"]
                   if e["ph"] == "X" and e["name"] == "request 1")
        assert req["pid"] == 0
        assert req["args"]["finish"] == "stop"


class TestTraceExportScript:
    def test_cli_roundtrip_and_validation_gate(self, tmp_path):
        mod = _load_script("trace_export")
        ev = tmp_path / "ev"
        ev.mkdir()
        (ev / "trace_manifest.json").write_text(json.dumps(
            {"trace_id": "abc", "root_span_id": "r",
             "spans": [{"span_id": "r", "parent_id": None,
                        "name": "supervise", "t": 1.0}]}))
        (ev / "events_rank0.jsonl").write_text(json.dumps(
            {"t": 2.0, "name": "s", "ph": "E", "rank": 0, "dur_s": 0.1,
             "trace_id": "abc", "span_id": "x", "parent_id": "r"}) + "\n")
        out = tmp_path / "t.json"
        rc = mod.main([str(ev), "--out", str(out), "--validate"])
        assert rc == 0
        trace = json.load(open(out))
        assert trace["otherData"]["trace_id"] == "abc"
        # demanding a second rank must flip the gate
        rc = mod.main([str(ev), "--out", str(out), "--validate",
                       "--require-ranks", "2"])
        assert rc == 1
        # an empty dir is its own exit code
        empty = tmp_path / "empty"
        empty.mkdir()
        assert mod.main([str(empty)]) == 2


class TestEngineParentage:
    def _engine(self):
        from sparkdl_tpu.serving import GenerationEngine, StubBackend
        return GenerationEngine(StubBackend(2, 64, step_s=0.0),
                                prefill_chunk=8)

    def test_serve_spans_parent_under_request_envelope(self, monkeypatch):
        """Every request-scoped serve_* record parents (transitively) to
        the request's admission span; the serve_request envelope closes
        the chain to the submitter's context."""
        _arm(monkeypatch, parent="attempt-9")
        rec = events.reset()
        eng = self._engine()
        h = eng.submit([1, 2, 3], max_new_tokens=4)
        eng.run_until_idle()
        assert h.wait(30) and h.finish_reason == "length"
        recs = [r for r in rec.tail() if r["name"].startswith("serve_")]
        env_rec = next(r for r in recs if r["name"] == "serve_request")
        assert env_rec["span_id"]  # the admission span
        assert env_rec["parent_id"] == "attempt-9"
        assert env_rec["finish"] == "length"
        scoped = [r for r in recs if r["name"] != "serve_request"
                  and r.get("request") is not None and r["ph"] != "B"]
        assert scoped  # queue/prefill/decode all present
        for r in scoped:
            assert r["parent_id"] == env_rec["span_id"], r["name"]
            assert r["trace_id"] == env_rec["trace_id"]

    def test_untraced_engine_emits_no_ids(self):
        rec = events.reset()
        eng = self._engine()
        eng.submit([1, 2, 3], max_new_tokens=4)
        eng.run_until_idle()
        recs = [r for r in rec.tail() if r["name"].startswith("serve_")]
        assert recs
        assert not any(r["name"] == "serve_request" for r in recs)
        for r in recs:
            assert "span_id" not in r and "parent_id" not in r


class TestBenchTrend:
    def _rec(self, n, value, metric="tput", extra=None, error=None,
             parsed=True):
        p = None
        if parsed:
            p = {"metric": metric, "value": value, "extra": extra or {}}
            if error:
                p["error"] = error
        return {"n": n, "rc": 0, "parsed": p}

    def test_improvement_and_flat_pass(self):
        mod = _load_script("bench_trend")
        rep = mod.trend([self._rec(1, 100.0), self._rec(2, 110.0),
                         self._rec(3, 109.0)], threshold=0.15)
        assert rep["ok"]
        (m,) = [x for x in rep["metrics"] if x["metric"] == "tput"]
        assert m["best_prior"] == 110.0
        assert m["regressed"] is False

    def test_regression_past_threshold_fails(self):
        mod = _load_script("bench_trend")
        rep = mod.trend([self._rec(1, 100.0), self._rec(2, 70.0)],
                        threshold=0.15)
        assert not rep["ok"]
        assert rep["regressions"] == ["tput"]
        # ...but within threshold passes
        rep2 = mod.trend([self._rec(1, 100.0), self._rec(2, 90.0)],
                         threshold=0.15)
        assert rep2["ok"]

    def test_lower_is_better_metrics_invert(self):
        mod = _load_script("bench_trend")
        recs = [self._rec(1, 1.0, extra={"step_time_s": 0.010}),
                self._rec(2, 1.0, extra={"step_time_s": 0.030})]
        rep = mod.trend(recs, threshold=0.15)
        (m,) = [x for x in rep["metrics"]
                if x["metric"] == "step_time_s"]
        assert m["direction"] == "lower"
        assert m["regressed"] is True

    def test_unmeasured_rounds_are_annotated_not_regressions(self):
        """A backend_unavailable round scoring 0.0 must not read as a
        100% regression — it is excluded and named in `skipped`."""
        mod = _load_script("bench_trend")
        recs = [self._rec(1, 100.0),
                self._rec(2, 0.0,
                          error={"kind": "backend_unavailable"}),
                {"n": 3, "rc": 124, "parsed": None},
                self._rec(4, 98.0)]
        rep = mod.trend(recs, threshold=0.15)
        assert rep["ok"]
        assert [s["n"] for s in rep["skipped"]] == [2, 3]
        assert [s["reason"] for s in rep["skipped"]] == [
            "backend_unavailable", "no parse"]
        (m,) = [x for x in rep["metrics"] if x["metric"] == "tput"]
        assert m["points"] == 2  # only the measured rounds

    def test_cli_exit_codes(self, tmp_path):
        mod = _load_script("bench_trend")
        for rec in [self._rec(1, 100.0), self._rec(2, 50.0)]:
            with open(tmp_path / f"BENCH_r{rec['n']:02d}.json",
                      "w") as f:
                json.dump(rec, f)
        assert mod.main(["--dir", str(tmp_path)]) == 1  # regression
        assert mod.main(["--dir", str(tmp_path),
                         "--threshold", "0.9"]) == 0
        solo = tmp_path / "one"
        solo.mkdir()
        with open(solo / "BENCH_r01.json", "w") as f:
            json.dump(self._rec(1, 100.0), f)
        assert mod.main(["--dir", str(solo)]) == 2  # no trend yet
