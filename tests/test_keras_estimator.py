"""KerasImageFileEstimator: streaming fit through the SPMD step machinery,
trained-transformer round trip, fitMultiple hyperparameter parallelism."""

import os

import numpy as np
import pytest

from sparkdl_tpu import DataFrame, KerasImageFileEstimator


@pytest.fixture(scope="module")
def keras_model_file(tmp_path_factory):
    os.environ.setdefault("KERAS_BACKEND", "jax")
    import keras
    model = keras.Sequential([
        keras.Input((8, 8, 3)),
        keras.layers.Flatten(),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(2),
    ])
    path = str(tmp_path_factory.mktemp("km") / "tiny.keras")
    model.save(path)
    return path


def synthetic_loader(uri: str) -> np.ndarray:
    """'img_<label>_<i>' → image whose pixel values encode the label
    (linearly separable, so a couple of epochs suffice)."""
    label = int(uri.split("_")[1])
    rng = np.random.RandomState(abs(hash(uri)) % (2 ** 31))
    return (np.full((8, 8, 3), float(label)) +
            rng.randn(8, 8, 3) * 0.1).astype(np.float32)


def _df(n=48, partitions=3):
    rows = [{"uri": f"img_{i % 2}_{i}", "label": i % 2} for i in range(n)]
    return DataFrame.fromRows(rows, numPartitions=partitions)


class TestKerasImageFileEstimator:
    def test_fit_learns_and_returns_transformer(self, keras_model_file):
        est = KerasImageFileEstimator(
            inputCol="uri", outputCol="scores", labelCol="label",
            modelFile=keras_model_file, imageLoader=synthetic_loader,
            batchSize=16, epochs=4, learningRate=5e-2)
        df = _df()
        model = est.fit(df)

        out = model.transform(df).toPandas()
        scores = np.stack(out["scores"].to_numpy())
        preds = scores.argmax(-1)
        labels = out["label"].to_numpy()
        acc = (preds == labels).mean()
        assert acc >= 0.9, f"accuracy {acc} — training did not learn"

    def test_partial_batch_padding_matches_drop(self, keras_model_file):
        """48 rows with batchSize=20: padded partial batches must still
        train without shape errors (static shapes preserved)."""
        est = KerasImageFileEstimator(
            inputCol="uri", outputCol="scores", labelCol="label",
            modelFile=keras_model_file, imageLoader=synthetic_loader,
            batchSize=16, epochs=1)
        # 40 rows → batches of 16,16,8(padded)
        model = est.fit(_df(n=40))
        assert model is not None

    def test_fit_empty_raises(self, keras_model_file):
        est = KerasImageFileEstimator(
            inputCol="uri", outputCol="scores", labelCol="label",
            modelFile=keras_model_file, imageLoader=synthetic_loader)
        with pytest.raises(ValueError):
            est.fit(DataFrame.fromRows([], numPartitions=1))

    def test_fit_multiple_order(self, keras_model_file):
        """fit(df, [maps]) returns models in paramMaps order even though
        fitMultiple completes out of order."""
        est = KerasImageFileEstimator(
            inputCol="uri", outputCol="scores", labelCol="label",
            modelFile=keras_model_file, imageLoader=synthetic_loader,
            batchSize=16, epochs=1)
        df = _df(n=32)
        maps = [{est.epochs: 1}, {est.epochs: 2}]
        models = est.fit(df, maps)
        assert len(models) == 2
        for m in models:
            assert m.transform(df).count() == 32

    def test_bad_optimizer_raises(self, keras_model_file):
        est = KerasImageFileEstimator(
            inputCol="uri", outputCol="s", labelCol="label",
            modelFile=keras_model_file, imageLoader=synthetic_loader,
            optimizer="lion9000")
        with pytest.raises(ValueError):
            est.fit(_df(n=16))


def test_fitted_transformer_survives_model_file_deletion(
        keras_model_file, tmp_path):
    """Durable persistence of the FITTED estimator output (round-1 task 5 /
    round-2 verdict missing #3): save() must bundle the trained weights with
    the transformer, so the temp file _fit wrote can vanish and a fresh
    load still reproduces predictions."""
    import sparkdl_tpu as sdl
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="pred", labelCol="label",
        modelFile=keras_model_file, imageLoader=synthetic_loader,
        batchSize=8, epochs=1, learningRate=0.05)
    df = _df(16, 2)
    fitted = est.fit(df)
    before = np.stack([np.asarray(r.pred, np.float32)
                       for r in fitted.transform(df).collect()])

    p = str(tmp_path / "fitted")
    fitted.save(p)
    # simulate process exit / tmp cleanup: remove the temp trained file
    tmp_model = fitted.getOrDefault(fitted.modelFile)
    os.remove(tmp_model)

    loaded = sdl.load(p)
    assert loaded.getOrDefault(loaded.modelFile) != tmp_model
    after = np.stack([np.asarray(r.pred, np.float32)
                      for r in loaded.transform(df).collect()])
    np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)


def test_keras_transformer_save_bundles_model(tmp_path):
    """KerasTransformer.save copies the model file into the stage dir."""
    os.environ.setdefault("KERAS_BACKEND", "jax")
    import keras
    import sparkdl_tpu as sdl
    m = keras.Sequential([keras.Input((3,)), keras.layers.Dense(2)])
    src = str(tmp_path / "m.keras")
    m.save(src)
    t = sdl.KerasTransformer(inputCol="x", outputCol="y", modelFile=src,
                             batchSize=2)
    df = sdl.DataFrame.fromPydict({"x": [[1.0, 2.0, 3.0], [0.0, 1.0, 0.0]]})
    want = [r.y for r in t.transform(df).collect()]
    p = str(tmp_path / "stage")
    t.save(p)
    os.remove(src)
    loaded = sdl.load(p)
    got = [r.y for r in loaded.transform(df).collect()]
    np.testing.assert_allclose(got, want, rtol=1e-6)
