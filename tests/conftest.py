"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI/dev; collective semantics
(psum over ICI, shard_map sharding rules) are validated on XLA's host platform
with 8 virtual devices, exactly as the driver's multichip dryrun does.

Note: the axon sitecustomize pre-imports jax in every interpreter, so plain
env-var JAX_PLATFORMS is already latched — we must go through jax.config
before the backend initializes (conftest runs before any test imports).
"""

import os
import sys

# SPARKDL_TEST_PLATFORM=axon (or tpu) runs the suite against the real
# backend instead of the virtual CPU mesh — the only way the TPU-gated
# compiled-kernel tests (tests/test_ops.py) can ever unskip. Round-3
# verdict weak #2: the unconditional cpu force made them structurally
# dead code in every environment.
_platform = os.environ.get("SPARKDL_TEST_PLATFORM", "cpu")

_flags = os.environ.get("XLA_FLAGS", "")
if _platform == "cpu" and \
        "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = _platform
os.environ.setdefault("KERAS_BACKEND", "jax")

import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)

if _platform == "cpu":
    assert len(jax.devices()) == 8, (
        f"expected 8 virtual CPU devices for sharding tests, "
        f"got {jax.devices()}")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
