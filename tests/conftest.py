"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI/dev; collective semantics
(psum over ICI, shard_map sharding rules) are validated on XLA's host platform
with 8 virtual devices, exactly as the driver's multichip dryrun does.

Note: the axon sitecustomize pre-imports jax in every interpreter, so plain
env-var JAX_PLATFORMS is already latched — we must go through jax.config
before the backend initializes (conftest runs before any test imports).
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("KERAS_BACKEND", "jax")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices for sharding tests, got {jax.devices()}")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
