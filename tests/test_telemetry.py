"""Live telemetry plane tests (ISSUE 6): registry, stage accountant math,
exporter lifecycle, Prometheus endpoint, gang aggregation, bottleneck
attribution, doc-drift lint — and the overhead pin that the disabled
plane stays ≈ free (PR 2's rule: observability must cost nothing when
off).

Fast and jax-free where possible: the registry/accountant/analysis tests
feed synthetic records; only the meter-summary and fit-integration tests
touch jax (already resident via conftest). The end-to-end smoke
(scripts/obs_smoke.py: live snapshot mid-run + bottleneck report naming
the decode stage) is slow-marked in test_chaos.py.
"""

import json
import os
import re
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from sparkdl_tpu.runner import analysis, events, telemetry
from sparkdl_tpu.runner.telemetry import (MetricsRegistry, StageAccountant,
                                          render_prometheus)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_plane():
    """Every test gets a stopped, fresh plane and a clean recorder; env
    arming from one test must not leak into the next."""
    telemetry.reset()
    yield
    telemetry.reset()
    events.reset()


def _span_records(stage, pairs, rank=0, **attrs):
    """Synthetic B/E record pairs: pairs = [(t0, t1), ...]."""
    recs = []
    for t0, t1 in pairs:
        recs.append({"t": t0, "name": stage, "ph": "B", "rank": rank})
        recs.append({"t": t1, "name": stage, "ph": "E", "rank": rank,
                     "dur_s": round(t1 - t0, 6), **attrs})
    return recs


class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(2.5)
        reg.gauge("g").set(3)
        reg.gauge("g").set(1)  # value drops, max holds
        reg.histogram("h", buckets=(0.1, 1.0)).observe(0.05)
        reg.histogram("h").observe(0.5)
        reg.histogram("h").observe(5.0)
        snap = reg.snapshot()
        assert snap["counters"]["c"] == 3.5
        assert snap["gauges"]["g"] == {"value": 1, "max": 3}
        h = snap["histograms"]["h"]
        assert h["count"] == 3 and abs(h["sum"] - 5.55) < 1e-9
        # cumulative buckets: le=0.1 -> 1, le=1.0 -> 2 (+Inf implicit = 3)
        assert h["buckets"] == [1, 2]

    def test_counter_inc_is_thread_safe(self):
        reg = MetricsRegistry()
        c = reg.counter("n")

        def work():
            for _ in range(1000):
                c.inc()

        ts = [threading.Thread(target=work) for _ in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert c.value == 4000

    def test_prometheus_rendering(self):
        reg = MetricsRegistry()
        reg.counter("rows").inc(7)
        reg.gauge("depth").set(2)
        reg.histogram("lat", buckets=(0.5,)).observe(0.3)
        snap = {"rank": 3, "elapsed_s": 1.5,
                "stages": {"decode": {"busy_s": 0.5, "wall_busy_s": 0.4,
                                      "busy_frac": 0.27, "count": 9,
                                      "rows": 36, "bytes": 1024,
                                      "errors": 0, "active": 1,
                                      "max_concurrency": 2}}}
        snap.update(reg.snapshot())
        txt = render_prometheus(snap)
        assert '# TYPE sparkdl_stage_busy_seconds counter' in txt
        assert 'sparkdl_stage_busy_seconds{rank="3",stage="decode"} 0.5' \
            in txt
        assert 'sparkdl_stage_busy_frac{rank="3",stage="decode"} 0.27' \
            in txt
        assert 'sparkdl_rows_total{rank="3"} 7' in txt
        assert 'sparkdl_depth{rank="3"} 2' in txt
        # histogram label values quoted too — one unquoted rank= fails
        # the WHOLE scrape, not just the histogram family
        assert 'sparkdl_lat_bucket{le="0.5",rank="3"} 1' in txt
        assert 'sparkdl_lat_bucket{le="+Inf",rank="3"} 1' in txt
        assert 'sparkdl_lat_count{rank="3"} 1' in txt
        assert re.search(r'rank=(?!")', txt) is None  # no unquoted rank


class TestStageAccountant:
    def test_busy_books_on_synthetic_spans(self):
        """Two overlapping decode spans: busy_s sums both (slot-seconds),
        wall_busy_s is the union — the wall is counted once."""
        acc = StageAccountant()
        # decode A [0, 2], decode B [1, 3] -> busy 4.0, union 3.0
        for r in [{"t": 0.0, "name": "decode", "ph": "B"},
                  {"t": 1.0, "name": "decode", "ph": "B"},
                  {"t": 2.0, "name": "decode", "ph": "E", "dur_s": 2.0,
                   "rows": 8, "bytes": 100},
                  {"t": 3.0, "name": "decode", "ph": "E", "dur_s": 2.0,
                   "rows": 8, "bytes": 100},
                  # dispatch [3, 4]: closes the elapsed window at 4.0
                  {"t": 3.0, "name": "dispatch", "ph": "B"},
                  {"t": 4.0, "name": "dispatch", "ph": "E", "dur_s": 1.0,
                   "error": "boom"}]:
            acc.on_event(r)
        snap = acc.snapshot(now=4.0)
        assert snap["elapsed_s"] == 4.0
        d = snap["stages"]["decode"]
        assert d["busy_s"] == 4.0
        assert d["wall_busy_s"] == 3.0
        assert d["busy_frac"] == 0.75
        assert d["rows"] == 16 and d["bytes"] == 200
        assert d["max_concurrency"] == 2 and d["active"] == 0
        dis = snap["stages"]["dispatch"]
        assert dis["errors"] == 1 and dis["busy_frac"] == 0.25
        # all fractions in [0, 1] — the acceptance-criteria invariant
        assert all(0.0 <= s["busy_frac"] <= 1.0
                   for s in snap["stages"].values())

    def test_open_span_counts_as_busy_in_live_snapshot(self):
        """A wedged stage with an open span must read busy mid-run, not
        idle — the live view is the whole point of the plane."""
        acc = StageAccountant()
        acc.on_event({"t": 10.0, "name": "dispatch", "ph": "B"})
        snap = acc.snapshot(now=40.0)
        st = snap["stages"]["dispatch"]
        assert st["active"] == 1
        assert st["wall_busy_s"] == 30.0
        assert snap["elapsed_s"] == 30.0
        assert st["busy_frac"] == 1.0

    def test_point_events_tallied(self):
        acc = StageAccountant()
        acc.on_event({"t": 1.0, "name": "quarantine", "ph": "P", "rows": 3})
        acc.on_event({"t": 2.0, "name": "quarantine", "ph": "P", "rows": 2})
        acc.on_event({"t": 2.5, "name": "retry", "ph": "P"})
        snap = acc.snapshot(now=3.0)
        assert snap["events"] == {"quarantine": 2, "retry": 1}
        assert snap["event_rows"] == {"quarantine": 5}

    def test_tee_feeds_accountant_through_recorder(self):
        telemetry.start()  # no dir/port: tee only
        rec = events.reset()  # fresh ring; module-level tee survives reset
        with events.span("pad", rows=4):
            pass
        with events.span("pad", rows=4):
            pass
        snap = telemetry.accountant().snapshot()
        assert snap["stages"]["pad"]["count"] == 2
        assert snap["stages"]["pad"]["rows"] == 8
        assert rec.tail()  # the ring saw them too


class TestExporterLifecycle:
    def test_snapshot_files_appear_and_survive_stop(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("SPARKDL_METRICS_INTERVAL_S", "0.05")
        d = str(tmp_path / "m")
        telemetry.start(metrics_dir=d)
        with events.span("decode", rows=2):
            pass
        deadline = time.time() + 5.0
        path = os.path.join(d, "metrics_rank0.json")
        while not os.path.exists(path) and time.time() < deadline:
            time.sleep(0.02)
        assert os.path.exists(path), "exporter never wrote a snapshot"
        snap = json.load(open(path))
        assert snap["stages"]["decode"]["count"] == 1
        # SIGKILL-survivability proxy: the latest file is always a
        # COMPLETE atomic write — no .tmp leftovers, parseable JSON
        # (the writer is tmp+os.replace; a kill between ticks leaves the
        # previous complete snapshot).
        telemetry.stop()
        final = json.load(open(path))
        assert final["stages"]["decode"]["count"] == 1
        hist = open(os.path.join(d, "metrics_rank0.jsonl")).readlines()
        assert all(json.loads(ln) for ln in hist)

    def test_start_and_stop_are_idempotent(self, tmp_path):
        d = str(tmp_path / "m")
        p1 = telemetry.start(metrics_dir=d)
        p2 = telemetry.start(metrics_dir=str(tmp_path / "other"))
        assert p1 is p2
        assert p2.metrics_dir == d  # second start did not rewire
        assert telemetry.enabled()
        telemetry.stop()
        telemetry.stop()  # no-op
        assert not telemetry.enabled()
        # tee removed: new spans no longer account
        before = telemetry.accountant().snapshot()["stages"].get(
            "pad", {}).get("count", 0)
        with events.span("pad"):
            pass
        after = telemetry.accountant().snapshot()["stages"].get(
            "pad", {}).get("count", 0)
        assert after == before

    def test_http_endpoint_serves_prometheus_and_json(self):
        telemetry.start(port=0)  # ephemeral
        port = telemetry.server_port()
        assert port
        with events.span("fetch", rows=4):
            pass
        txt = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert 'sparkdl_stage_count{rank="0",stage="fetch"} 1' in txt
        js = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json", timeout=10).read())
        assert js["stages"]["fetch"]["rows"] == 4
        telemetry.stop()

    def test_healthz_endpoint(self):
        """ISSUE 17: /healthz answers 200 with pid + uptime next to
        /metrics — the cheap liveness probe orchestrators can poll at a
        rate the full snapshot endpoint shouldn't pay."""
        telemetry.start(port=0)
        port = telemetry.server_port()
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert resp.status == 200
        body = json.loads(resp.read())
        assert body["status"] == "ok"
        assert body["pid"] == os.getpid()
        assert body["rank"] == 0
        assert isinstance(body["uptime_s"], (int, float))
        assert body["uptime_s"] >= 0
        # unknown paths still 404 — /healthz did not become a catch-all
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10)
        telemetry.stop()

    def test_healthz_bind_failure_degrades(self, tmp_path):
        """A taken port must degrade to no-endpoint (port=None) while the
        rest of the plane — exporter, registry, tee — keeps running; the
        same never-kill rule the /metrics endpoint pins."""
        import socket
        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        sock.listen(1)
        taken = sock.getsockname()[1]
        try:
            telemetry.start(metrics_dir=str(tmp_path / "m"), port=taken)
            assert telemetry.server_port() is None  # degraded, not dead
            assert telemetry.enabled()
            with events.span("pad"):
                pass
            telemetry.flush_snapshot()
            snap = json.load(
                open(os.path.join(str(tmp_path / "m"),
                                  "metrics_rank0.json")))
            assert snap["stages"]["pad"]["count"] == 1
        finally:
            sock.close()
            telemetry.stop()

    def test_maybe_start_from_env(self, tmp_path, monkeypatch):
        assert telemetry.maybe_start_from_env() is False  # nothing set
        assert not telemetry.enabled()
        monkeypatch.setenv("SPARKDL_METRICS_DIR", str(tmp_path / "m"))
        assert telemetry.maybe_start_from_env() is True
        assert telemetry.enabled()

    def test_unparseable_port_alone_does_not_arm(self, monkeypatch):
        """SPARKDL_METRICS_PORT=abc with no metrics dir: arming would pay
        the tee + accountant with no exporter and no endpoint — all
        overhead, no telemetry. Stay off."""
        monkeypatch.delenv("SPARKDL_METRICS_DIR", raising=False)
        monkeypatch.setenv("SPARKDL_METRICS_PORT", "abc")
        assert telemetry.maybe_start_from_env() is False
        assert not telemetry.enabled()
        assert events._TEES == []

    def test_history_capped_latest_keeps_updating(self, tmp_path,
                                                  monkeypatch):
        """SPARKDL_METRICS_MAX_MB bounds the .jsonl history (same rule as
        SPARKDL_EVENT_MAX_MB): one truncation marker, no further growth —
        while the atomic latest-snapshot file keeps updating."""
        monkeypatch.setenv("SPARKDL_METRICS_MAX_MB", "0.0002")  # ~200 B
        monkeypatch.setenv("SPARKDL_METRICS_INTERVAL_S", "60")
        d = str(tmp_path / "m")
        telemetry.start(metrics_dir=d)
        for _ in range(20):
            telemetry.flush_snapshot()
        hpath = os.path.join(d, "metrics_rank0.jsonl")
        lines = open(hpath).read().splitlines()
        marker = json.loads(lines[-1])
        assert marker["name"] == "metrics_history_truncated"
        assert sum(1 for ln in lines
                   if '"metrics_history_truncated"' in ln) == 1
        n = len(lines)
        telemetry.flush_snapshot()
        telemetry.flush_snapshot()
        assert len(open(hpath).read().splitlines()) == n  # capped
        # the latest file is still a live, parseable snapshot
        with events.span("decode"):
            pass
        telemetry.flush_snapshot()
        latest = json.load(open(os.path.join(d, "metrics_rank0.json")))
        assert latest["stages"]["decode"]["count"] == 1
        telemetry.stop()

    def test_concurrent_flush_and_tick_never_tear_snapshot(self, tmp_path,
                                                           monkeypatch):
        """flush_snapshot (fit_end/postmortem/atexit) races the exporter
        tick in the same process; the snapshot lock must keep the latest
        file and every history line parseable."""
        monkeypatch.setenv("SPARKDL_METRICS_INTERVAL_S", "0.05")
        d = str(tmp_path / "m")
        telemetry.start(metrics_dir=d)
        with events.span("pad"):
            pass

        def flusher():
            for _ in range(25):
                telemetry.flush_snapshot()

        threads = [threading.Thread(target=flusher) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        telemetry.stop()
        snap = json.load(open(os.path.join(d, "metrics_rank0.json")))
        assert snap["stages"]["pad"]["count"] == 1
        for ln in open(os.path.join(d, "metrics_rank0.jsonl")):
            json.loads(ln)  # no torn/interleaved line


class TestOverheadBounded:
    def test_disabled_plane_is_free(self, tmp_path, monkeypatch):
        """ISSUE 6 acceptance: with SPARKDL_METRICS_DIR unset the plane
        adds no hot-path work — no tee registered, no exporter thread, no
        registry traffic, no files; mirrors PR 2's recorder-off pin."""
        monkeypatch.delenv("SPARKDL_METRICS_DIR", raising=False)
        monkeypatch.delenv("SPARKDL_METRICS_PORT", raising=False)
        assert telemetry.maybe_start_from_env() is False
        assert events._TEES == []  # emit()'s per-event check is one falsy
        n_threads = threading.active_count()
        rec = events.reset()
        for i in range(200):
            with events.span("pad", rows=1):
                pass
        assert threading.active_count() == n_threads
        assert list(tmp_path.iterdir()) == []
        # plane never armed: a later snapshot shows nothing recorded
        assert telemetry.accountant().snapshot()["stages"] == {}
        assert rec.tail()  # recording itself still worked

    def test_broken_tee_never_breaks_the_hot_path(self):
        def bad(rec):
            raise RuntimeError("telemetry bug")

        events.add_tee(bad)
        try:
            with events.span("pad"):
                pass  # must not raise
            events.event("x")
        finally:
            events.remove_tee(bad)


class TestGangAggregation:
    def _write_snap(self, d, rank, stages, elapsed=10.0, events_=None):
        os.makedirs(d, exist_ok=True)
        snap = {"t": 100.0 + rank, "rank": rank, "pid": 1,
                "elapsed_s": elapsed, "stages": stages}
        if events_:
            snap["events"] = events_
        with open(os.path.join(d, f"metrics_rank{rank}.json"), "w") as f:
            json.dump(snap, f)

    def test_aggregate_sums_stages_across_ranks(self, tmp_path):
        d = str(tmp_path)
        st = {"count": 5, "busy_s": 4.0, "wall_busy_s": 4.0,
              "busy_frac": 0.4, "rows": 50, "bytes": 1000, "errors": 0,
              "active": 0, "max_concurrency": 2}
        self._write_snap(d, 0, {"decode": dict(st)},
                         events_={"quarantine": 1})
        self._write_snap(d, 1, {"decode": dict(st, busy_s=6.0,
                                               wall_busy_s=6.0, rows=70)},
                         events_={"quarantine": 2})
        agg = telemetry.aggregate_snapshots(d)
        assert agg["n_ranks"] == 2
        dec = agg["stages"]["decode"]
        assert dec["busy_s"] == 10.0 and dec["rows"] == 120
        assert dec["count"] == 10 and dec["max_concurrency"] == 2
        # gang busy fraction: 10s wall-busy over 2 ranks x 10s elapsed
        assert dec["busy_frac"] == 0.5
        assert agg["events"] == {"quarantine": 3}

    def test_aggregate_empty_dir_is_none(self, tmp_path):
        assert telemetry.aggregate_snapshots(str(tmp_path)) is None
        assert telemetry.aggregate_snapshots(
            str(tmp_path / "missing")) is None

    def test_clear_rank_files(self, tmp_path):
        d = str(tmp_path)
        self._write_snap(d, 0, {})
        (tmp_path / "metrics_rank0.jsonl").write_text("{}\n")
        (tmp_path / "keep.txt").write_text("x")
        telemetry.clear_rank_files(d)
        assert sorted(os.listdir(d)) == ["keep.txt"]

    def test_supervise_attaches_gang_metrics(self, tmp_path):
        """Jax-free supervisor e2e: a worker that exports a telemetry
        snapshot → SuperviseResult.metrics carries the aggregated gang
        view (the ISSUE 6 supervise() contract)."""
        from sparkdl_tpu.runner.launcher import supervise
        mdir = tmp_path / "metrics"
        script = tmp_path / "w.py"
        script.write_text("""
import json, os, sys
d = os.environ["SPARKDL_METRICS_DIR"]
os.makedirs(d, exist_ok=True)
rank = os.environ.get("SPARKDL_PROCESS_ID", "0")
snap = {"t": 1.0, "rank": int(rank), "pid": os.getpid(), "elapsed_s": 2.0,
        "stages": {"step_compute": {"count": 4, "busy_s": 1.5,
                                    "wall_busy_s": 1.5, "busy_frac": 0.75,
                                    "rows": 32, "bytes": 0, "errors": 0,
                                    "active": 0, "max_concurrency": 1}}}
tmp = os.path.join(d, f"metrics_rank{rank}.json.tmp")
open(tmp, "w").write(json.dumps(snap))
os.replace(tmp, os.path.join(d, f"metrics_rank{rank}.json"))
""")
        res = supervise(str(script), np=2, timeout_s=30.0, max_restarts=0,
                        poll_s=0.2,
                        env={"SPARKDL_METRICS_DIR": str(mdir)})
        assert res.metrics is not None
        assert res.metrics["n_ranks"] == 2
        assert res.metrics["stages"]["step_compute"]["rows"] == 64

    def test_launch_failure_metrics_ignore_stale_rank_files(self, tmp_path):
        """A reused SPARKDL_METRICS_DIR holding a dead earlier gang's
        high-rank snapshots must not be aggregated as THIS gang's failure
        evidence: launch() gives the gang a fresh gang-* subdir, same
        isolation supervise() has."""
        from sparkdl_tpu.runner.launcher import GangFailure, launch
        mdir = tmp_path / "metrics"
        st = {"count": 9, "busy_s": 9.0, "wall_busy_s": 9.0,
              "busy_frac": 0.9, "rows": 999, "bytes": 0, "errors": 0,
              "active": 0, "max_concurrency": 1}
        for r in (2, 3):  # earlier 4-rank run's leftovers
            self._write_snap(str(mdir), r, {"stale_stage": dict(st)})
        edir = tmp_path / "events"
        script = tmp_path / "w.py"
        script.write_text("""
import json, os, sys, time
rank = os.environ.get("SPARKDL_PROCESS_ID", "0")
d = os.environ["SPARKDL_METRICS_DIR"]
os.makedirs(d, exist_ok=True)
snap = {"t": 1.0, "rank": int(rank), "pid": os.getpid(), "elapsed_s": 2.0,
        "stages": {"step_compute": {"count": 4, "busy_s": 1.5,
                                    "wall_busy_s": 1.5, "busy_frac": 0.75,
                                    "rows": 32, "bytes": 0, "errors": 0,
                                    "active": 0, "max_concurrency": 1}}}
tmp = os.path.join(d, f"metrics_rank{rank}.json.tmp")
open(tmp, "w").write(json.dumps(snap))
os.replace(tmp, os.path.join(d, f"metrics_rank{rank}.json"))
with open(os.path.join(os.environ["SPARKDL_EVENT_DIR"],
                       f"events_rank{rank}.jsonl"), "w") as f:
    f.write(json.dumps({"t": time.time(), "name": "step_compute",
                        "ph": "E", "dur_s": 0.1, "rank": int(rank)}) + "\\n")
if rank == "0":
    time.sleep(0.5)  # let rank 1 land its files before the gang dies
    sys.exit(1)
""")
        with pytest.raises(GangFailure) as ei:
            launch(str(script), np=2, timeout_s=30.0, poll_s=0.2,
                   capture=True, event_dir=str(edir),
                   env={"SPARKDL_METRICS_DIR": str(mdir)})
        tl = ei.value.timeline
        assert tl is not None and tl.get("metrics") is not None
        assert tl["metrics"]["n_ranks"] == 2  # not 4
        assert "stale_stage" not in tl["metrics"]["stages"]
        assert tl["metrics"]["stages"]["step_compute"]["rows"] == 64
        # the workers exported into a gang-* subdir; the stale parent
        # files are untouched
        assert any(fn.startswith("gang-") for fn in os.listdir(mdir))
        assert (mdir / "metrics_rank3.json").exists()


class TestAnalysis:
    def test_union_seconds(self):
        assert analysis.union_seconds([]) == 0.0
        assert analysis.union_seconds([(0, 2), (1, 3), (5, 6)]) == 4.0

    def test_attribution_on_synthetic_spans(self):
        """decode saturates [0,10] on two workers; dispatch covers [2,5];
        the report must name decode, keep every fraction in [0,1], and
        project the Amdahl bound off decode's busy fraction."""
        recs = []
        recs += _span_records("decode",
                              [(0.0, 5.0), (0.5, 5.5), (5.0, 10.0)],
                              rows=4)
        recs += _span_records("dispatch", [(2.0, 5.0)], rows=4)
        rep = analysis.analyze(events=recs)
        assert rep["dominant_stage"] == "decode"
        d = rep["stages"]["decode"]
        assert d["busy_frac"] == 1.0          # union covers the whole wall
        assert d["busy_s"] == 15.0            # slot-seconds sum
        assert d["avg_concurrency"] == 1.5
        # decode exclusive = wall minus dispatch's [2,5] overlap
        assert abs(d["exclusive_s"] - 7.0) < 1e-6
        assert rep["stages"]["dispatch"]["busy_frac"] == 0.3
        assert rep["stages"]["dispatch"]["exclusive_s"] == 0.0
        assert rep["max_speedup_fixing_others"] == 1.0
        assert rep["idle_s"] == 0.0
        assert all(0.0 <= s["busy_frac"] <= 1.0
                   for s in rep["stages"].values())

    def test_idle_gap_reported(self):
        recs = _span_records("fetch", [(0.0, 1.0), (3.0, 4.0)])
        rep = analysis.analyze(events=recs)
        assert rep["wall_s"] == 4.0
        assert rep["idle_s"] == 2.0
        assert rep["idle_frac"] == 0.5

    def test_no_spans_is_none(self):
        assert analysis.analyze(events=[{"name": "x", "ph": "P",
                                         "t": 1.0}]) is None
        assert analysis.analyze(events=[]) is None

    def test_format_report_names_dominant(self):
        recs = _span_records("decode", [(0.0, 9.4)], rows=100) \
            + _span_records("fetch", [(9.4, 10.0)])
        rep = analysis.analyze(events=recs)
        txt = analysis.format_report(rep)
        assert "dominant stage: decode (94.0% busy)" in txt
        assert "<= 1.06x" in txt  # 1 / 0.94

    def test_event_dir_loader_includes_gang_subdirs(self, tmp_path):
        (tmp_path / "gang-x").mkdir()
        with open(tmp_path / "events_rank0.jsonl", "w") as f:
            for r in _span_records("pad", [(0.0, 1.0)]):
                f.write(json.dumps(r) + "\n")
        with open(tmp_path / "gang-x" / "events_rank1.jsonl", "w") as f:
            for r in _span_records("pad", [(1.0, 2.0)], rank=1):
                f.write(json.dumps(r) + "\n")
        rep = analysis.analyze(event_dir=str(tmp_path))
        assert rep["stages"]["pad"]["count"] == 2

    def test_event_dir_loader_merges_only_newest_gang_subdir(self, tmp_path):
        """A reused event dir accumulates one kept gang-* subdir per
        supervise() run; splicing two runs into one timeline would turn
        the gap between them into fictitious idle. Newest non-empty gang
        wins (empty ones are skipped), same rule as aggregate_snapshots."""
        old = tmp_path / "gang-old"
        new = tmp_path / "gang-new"
        empty = tmp_path / "gang-zzz-empty"
        for d in (old, new, empty):
            d.mkdir()
        with open(old / "events_rank0.jsonl", "w") as f:
            for r in _span_records("pad", [(0.0, 1.0)]):
                f.write(json.dumps(r) + "\n")
        with open(new / "events_rank0.jsonl", "w") as f:
            for r in _span_records("pad", [(1000.0, 1001.0)]):
                f.write(json.dumps(r) + "\n")
        os.utime(old, (1, 1))        # oldest
        os.utime(new, (100, 100))    # newest non-empty
        os.utime(empty, (200, 200))  # newest overall but no streams
        rep = analysis.analyze(event_dir=str(tmp_path))
        assert rep["stages"]["pad"]["count"] == 1
        # wall is the newest run's 1s, not 1001s of spliced runs
        assert rep["wall_s"] == 1.0
        assert rep["idle_s"] == 0.0

    def test_bottleneck_report_cli(self, tmp_path, capsys):
        """In-process main() call — no fresh jax-importing interpreters
        in a tier-1 test (the slow obs smoke runs the script as a real
        subprocess); same import route as the env-docs lint tests."""
        sys.path.insert(0, os.path.join(_REPO, "scripts"))
        try:
            import bottleneck_report
        finally:
            sys.path.pop(0)
        d = tmp_path / "ev"
        d.mkdir()
        with open(d / "events_rank0.jsonl", "w") as f:
            for r in _span_records("decode", [(0.0, 2.0)], rows=8) \
                    + _span_records("dispatch", [(2.0, 2.5)]):
                f.write(json.dumps(r) + "\n")
        assert bottleneck_report.main([str(d), "--json"]) == 0
        rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rec["report"]["dominant_stage"] == "decode"
        # empty dir → exit 2, not a crash
        empty = tmp_path / "empty"
        empty.mkdir()
        assert bottleneck_report.main([str(empty)]) == 2


class TestMeterIntegration:
    def test_summary_carries_stage_utilization_when_armed(self):
        from sparkdl_tpu.runner.metrics import ThroughputMeter
        telemetry.start()
        events.reset()
        with events.span("decode", rows=4):
            time.sleep(0.002)
        with events.span("dispatch", rows=4):
            pass
        s = ThroughputMeter().summary()
        su = s["stage_utilization"]
        assert su is not None
        assert su["dominant_stage"] == "decode"
        assert set(su["stages"]) == {"decode", "dispatch"}
        telemetry.stop()

    def test_summary_block_is_none_when_off(self):
        from sparkdl_tpu.runner.metrics import ThroughputMeter
        assert ThroughputMeter().summary()["stage_utilization"] is None

    def test_log_summary_flattens_doubly_nested_blocks(self, caplog):
        """ISSUE 6 satellite: nested summary blocks (compile_cache's
        persistent sub-dict, stage_utilization's stages) flatten to
        scalar keys recursively — no stringified dicts in TB/CSV."""
        from sparkdl_tpu.runner.metrics import MetricsLogger
        logger = MetricsLogger(None)
        with caplog.at_level("INFO", logger="sparkdl_tpu.runner"):
            logger.log_summary(10, {
                "examples_per_sec": 5.0,
                "compile_cache": {"hits": 2,
                                  "persistent": {"hits": 1, "misses": 0}},
                "stage_utilization": {
                    "dominant_stage": "decode",
                    "stages": {"decode": {"busy_frac": 0.9}}},
            })
        assert "compile_cache_persistent_hits" in caplog.text
        assert "stage_utilization_stages_decode_busy_frac" in caplog.text
        assert "{'hits'" not in caplog.text  # nothing stringified
        logger.close()


class TestEnvDocsLint:
    def test_repo_has_no_drift(self):
        """The lint itself, as a tier-1 gate: every SPARKDL_* var in the
        package is documented in README.md."""
        sys.path.insert(0, os.path.join(_REPO, "scripts"))
        try:
            import check_env_docs
        finally:
            sys.path.pop(0)
        missing = check_env_docs.missing_vars()
        assert missing == [], \
            f"undocumented SPARKDL_* env vars: {missing}"
        # sanity: the scanner actually sees known vars on both sides
        assert "SPARKDL_EVENT_DIR" in check_env_docs.code_env_vars()
        assert "SPARKDL_EVENT_DIR" in check_env_docs.documented_env_vars()

    def test_lint_catches_synthetic_drift(self, tmp_path):
        """The mechanism, not just the current state: an undocumented var
        in a synthetic tree is reported."""
        sys.path.insert(0, os.path.join(_REPO, "scripts"))
        try:
            import check_env_docs
        finally:
            sys.path.pop(0)
        pkg = tmp_path / "sparkdl_tpu"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            'import os\nX = os.environ.get("SPARKDL_TOTALLY_NEW_KNOB")\n')
        (tmp_path / "scripts").mkdir()
        (tmp_path / "bench.py").write_text("")
        (tmp_path / "README.md").write_text("docs say nothing")
        missing = check_env_docs.missing_vars(
            root=str(tmp_path), readme=str(tmp_path / "README.md"))
        assert missing == ["SPARKDL_TOTALLY_NEW_KNOB"]


class TestScorerGauges:
    def test_stream_scorer_sets_queue_gauges(self):
        """The pending/backlog deque depths land as gauges when armed."""
        import numpy as np
        import pyarrow as pa

        from sparkdl_tpu.transformers.streaming import StreamScorer

        class StubRunner:
            prefetch = 2
            batch_size = 2

            def run_stream(self, stream):
                for arr, entry in stream:
                    yield np.asarray(arr) * 2.0, entry

        telemetry.start()
        events.reset()
        scorer = StreamScorer(
            StubRunner(), "y",
            make_decoder=lambda rb: (
                lambda start, length:
                np.full((length, 1), 1.0, np.float32)),
            encode=lambda r: pa.array([float(v) for v in r[:, 0]],
                                      type=pa.float64()),
            empty_array=lambda: pa.array([], type=pa.float64()),
            chunk_rows=2, decode_workers=0)
        batch = pa.RecordBatch.from_arrays(
            [pa.array([1.0, 2.0, 3.0, 4.0])], ["x"])
        out = list(scorer(iter([batch])))
        assert len(out) == 1
        snap = telemetry.registry().snapshot()
        assert "scorer_pending_partitions" in snap["gauges"]
        assert "scorer_encode_backlog" in snap["gauges"]
        assert snap["gauges"]["scorer_encode_backlog"]["max"] >= 1
        # decode spans accounted too (rows attr rides the span)
        acc = telemetry.accountant().snapshot()
        assert acc["stages"]["decode"]["rows"] == 4
        telemetry.stop()

    def test_run_stream_occupancy_gauge_is_a_fraction(self):
        """Slot occupancy is read AFTER the window pop: a keeping-up feed
        reads 1.0 — never a perpetual (prefetch+1)/prefetch > 1."""
        import numpy as np

        from sparkdl_tpu.core import runtime
        telemetry.start()
        events.reset()
        runner = runtime.BatchRunner(lambda x: x + 1.0, batch_size=4,
                                     prefetch=2)
        batches = [np.ones((3, 2), np.float32) for _ in range(8)]
        out = list(runner.run_stream((b, i) for i, b in enumerate(batches)))
        assert len(out) == 8
        g = telemetry.registry().snapshot()["gauges"]
        assert 0.0 < g["run_stream_slot_occupancy"]["max"] <= 1.0
        assert g["run_stream_window_depth"]["max"] <= 2
        telemetry.stop()
