"""DataFrame (Arrow data plane) tests — mapBatches is the load-bearing primitive."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from sparkdl_tpu.core.frame import DataFrame


def make_df(n=10, parts=3):
    return DataFrame.fromPydict(
        {"x": list(range(n)), "y": [float(i) * 2 for i in range(n)]},
        numPartitions=parts)


def test_constructors_roundtrip():
    df = make_df()
    assert df.count() == 10
    assert df.numPartitions == 3
    assert df.columns == ["x", "y"]
    pdf = df.toPandas()
    assert list(pdf["x"]) == list(range(10))

    df2 = DataFrame.fromPandas(pd.DataFrame({"a": [1, 2, 3]}), numPartitions=2)
    assert df2.count() == 3 and df2.numPartitions == 2

    df3 = DataFrame.fromRows([{"a": 1}, {"a": 2}])
    assert [r.a for r in df3.collect()] == [1, 2]


def test_select_drop_rename():
    df = make_df()
    assert df.select("y").columns == ["y"]
    assert df.drop("y").columns == ["x"]
    assert df.withColumnRenamed("x", "z").columns == ["z", "y"]


def test_with_column_rowwise_and_batch():
    df = make_df(6, parts=2)
    out = df.withColumn("s", lambda x, y: x + y, inputCols=["x", "y"])
    rows = out.collect()
    assert all(r.s == r.x + r.y for r in rows)

    out2 = df.withColumnBatch(
        "z", lambda x: np.asarray(x) * 10, inputCols=["x"])
    assert [r.z for r in out2.collect()] == [i * 10 for i in range(6)]


def test_filter_and_count():
    df = make_df(10, parts=4)
    f = df.filter(lambda r: r.x % 2 == 0)
    assert f.count() == 5
    assert all(r.x % 2 == 0 for r in f.collect())


def test_iter_batches_rechunks_across_partitions():
    df = make_df(10, parts=3)  # partitions of 4,4,2
    sizes = [b.num_rows for b in df.iterBatches(3)]
    assert sizes == [3, 3, 3, 1]
    seen = []
    for b in df.iterBatches(4):
        seen.extend(b.column("x").to_pylist())
    assert seen == list(range(10))


def test_lazy_ops_compose_single_pass():
    calls = []
    df = make_df(4, parts=1)

    def op(b):
        calls.append(b.num_rows)
        return b

    chained = df.mapBatches(op).select("x")
    assert calls == []  # nothing ran yet
    chained.collect()
    assert calls == [4]


def test_nested_tensor_column():
    imgs = np.arange(2 * 2 * 3, dtype=np.float32).reshape(2, 2, 3)
    df = DataFrame.fromPydict({"img": imgs, "label": [0, 1]})
    rows = df.collect()
    assert np.allclose(np.asarray(rows[0].img), imgs[0])


def test_take_limit_first_cache_repartition():
    df = make_df(10, parts=3)
    assert [r.x for r in df.take(5)] == [0, 1, 2, 3, 4]
    assert df.limit(5).count() == 5
    assert df.first().x == 0
    cached = df.withColumn("z", lambda x: x + 1, inputCols=["x"]).cache()
    assert cached._ops == ()
    assert cached.count() == 10
    rp = df.repartition(5)
    assert rp.numPartitions == 5 and rp.count() == 10
    with pytest.raises(ValueError):
        DataFrame.fromPydict({"x": []}).first()


def test_limit_after_filter_applies_post_filter():
    # Regression: limit must see the filtered stream, not raw partitions.
    df = DataFrame.fromPydict({"x": list(range(10))}, numPartitions=3)
    out = df.filter(lambda r: r.x % 2 == 0).limit(3)
    assert [r.x for r in out.collect()] == [0, 2, 4]


def test_with_column_batch_preserves_tensor_shape():
    df = DataFrame.fromPydict({"x": list(range(4))})
    out = df.withColumnBatch("t", lambda x: np.ones((4, 2, 3), np.float32),
                             inputCols=["x"])
    assert np.asarray(out.first().t).shape == (2, 3)


def test_count_fast_path_does_not_materialize():
    calls = []
    df = make_df(6, parts=2)

    def probe(x):
        calls.append(1)
        return np.asarray(x)

    chained = df.select("x").withColumnBatch("y", probe, inputCols=["x"])
    assert chained.count() == 6
    assert calls == []  # length-preserving chain → no materialization


def test_streaming_only_for_row_wise_ops():
    """iterBatches may slice raw partitions ahead of ROW-WISE ops, but a
    withColumnBatch fn that aggregates across its batch (mean-centering)
    must keep partition granularity — collect() and iterBatches() must
    agree (code-review regression, round 2)."""
    df = DataFrame.fromPydict({"x": [float(i) for i in range(16)]},
                              numPartitions=1)
    centered = df.withColumnBatch(
        "z", lambda x: np.asarray(x) - np.asarray(x).mean(), ["x"])
    via_collect = [r.z for r in centered.collect()]
    via_batches = [z for b in centered.iterBatches(4)
                   for z in b.column("z").to_pylist()]
    assert via_collect == via_batches

    # row-wise chain (withColumn + filter + select) IS streamed: chunks of
    # at most the batch size reach the ops
    seen = []
    probe = df.withColumn("w", lambda x: x + 1, ["x"]) \
              .filter(lambda r: r.x != 3.0)

    def spy(b):
        seen.append(b.num_rows)
        return b

    spy._changes_length = False
    spy._row_wise = True
    out = [r for b in probe.mapBatches(spy).iterBatches(4)
           for r in b.to_pylist()]
    assert len(out) == 15
    assert max(seen) <= 4


def test_parquet_round_trip(tmp_path):
    """toParquet/fromParquet: the durable interchange format — schema,
    values (incl. list columns), and partitioning survive the round trip."""
    import numpy as np

    import sparkdl_tpu as sdl

    df = sdl.DataFrame.fromPydict(
        {"x": list(range(10)),
         "vec": [np.arange(3, dtype=np.float32) + i for i in range(10)]},
        numPartitions=3)
    p = str(tmp_path / "t.parquet")
    df.toParquet(p)

    back = sdl.DataFrame.fromParquet(p)
    assert back.numPartitions == df.numPartitions  # row groups = partitions
    assert back.columns == ["x", "vec"]
    rows = back.collect()
    assert [r["x"] for r in rows] == list(range(10))
    np.testing.assert_allclose(rows[4]["vec"], [4.0, 5.0, 6.0])

    # forced re-split
    re = sdl.DataFrame.fromParquet(p, numPartitions=2)
    assert re.numPartitions == 2 and re.count() == 10

    # lazy ops stream through toParquet (written post-op)
    df2 = df.withColumn("y", lambda x: x * 2, ["x"])
    p2 = str(tmp_path / "t2.parquet")
    df2.toParquet(p2)
    assert [r["y"] for r in sdl.DataFrame.fromParquet(p2).collect()] == \
        [2 * i for i in range(10)]


def test_parquet_empty_partitions_and_directories(tmp_path):
    import pyarrow.parquet as pq

    import sparkdl_tpu as sdl

    # a filter emptying partition 0 leaves a degenerate null-typed op
    # column there — the writer schema must come from a NON-empty batch
    df = sdl.DataFrame.fromPydict({"x": [1, 2, 3, 4]}, numPartitions=2) \
        .filter(lambda r: r["x"] > 2) \
        .withColumn("y", lambda x: x * 2, ["x"])
    p = str(tmp_path / "filtered.parquet")
    df.toParquet(p)
    back = sdl.DataFrame.fromParquet(p)
    assert [(r["x"], r["y"]) for r in back.collect()] == [(3, 6), (4, 8)]

    # dataset DIRECTORY: row groups across all member files = partitions
    d = tmp_path / "dataset"
    d.mkdir()
    sdl.DataFrame.fromPydict({"x": [0, 1]}).toParquet(str(d / "a.parquet"))
    sdl.DataFrame.fromPydict({"x": [2, 3]}, numPartitions=2) \
        .toParquet(str(d / "b.parquet"))
    dd = sdl.DataFrame.fromParquet(str(d))
    assert dd.numPartitions == 3  # 1 row group + 2 row groups
    assert sorted(r["x"] for r in dd.collect()) == [0, 1, 2, 3]

    # an all-empty frame still writes a valid (0-row) file
    empty = sdl.DataFrame.fromPydict({"x": [1]}).filter(lambda r: False)
    pe = str(tmp_path / "empty.parquet")
    empty.toParquet(pe)
    assert pq.read_table(pe).num_rows == 0


def test_show(capsys):
    import sparkdl_tpu as sdl

    df = sdl.DataFrame.fromPydict(
        {"name": ["a-very-long-string-that-overflows", "b"],
         "x": [1, 22]})
    df.show(truncate=10)
    out = capsys.readouterr().out
    lines = out.strip().splitlines()
    assert lines[1].count("|") == 3  # header row: | name | x |
    assert "a-very-..." in out  # truncated to 10 chars
    assert "22" in out
    # n limits the rows shown
    df.show(n=1)
    out2 = capsys.readouterr().out
    assert "22" not in out2
    # the ubiquitous Spark idiom: truncate=True means the default 20,
    # not the bool-as-int s[:True] one-char cut; False disables
    df.show(truncate=True)
    out3 = capsys.readouterr().out
    assert "a-very-long-strin..." in out3
    df.show(truncate=False)
    out4 = capsys.readouterr().out
    assert "a-very-long-string-that-overflows" in out4


def test_iter_batches_many_tiny_partitions_linear():
    """Satellite regression (ISSUE 3): the deque-of-batches carry re-chunks
    many tiny partitions correctly — every row exactly once, in order,
    exact batch sizes — and never calls pa.concat_tables (the old
    table-carry whose repeated remainder concat was quadratic)."""
    import pyarrow as _pa
    from unittest import mock

    n = 501
    df = DataFrame.fromPydict({"x": list(range(n))}, numPartitions=n)
    assert df.numPartitions == n  # one row per partition
    with mock.patch.object(_pa, "concat_tables",
                           side_effect=AssertionError("table-carry used")):
        sizes, seen = [], []
        for b in df.iterBatches(64):
            sizes.append(b.num_rows)
            seen.extend(b.column("x").to_pylist())
    assert sizes == [64] * (n // 64) + [n % 64]
    assert seen == list(range(n))

    # big-partition → small batches direction too (zero-copy head slicing)
    df2 = DataFrame.fromPydict({"x": list(range(100))}, numPartitions=2)
    got = [b.column("x").to_pylist() for b in df2.iterBatches(7)]
    assert [len(g) for g in got] == [7] * 14 + [2]
    assert [x for g in got for x in g] == list(range(100))


def test_map_stream_op_chains_and_probes():
    """mapStream: the fn sees all partition batches in one iterator per
    materialization, composes with per-batch ops, and the 1-row schema
    probe works through it."""
    calls = []

    def stream_fn(parts):
        calls.append("open")
        for b in parts:
            yield b.set_column(
                b.schema.get_field_index("x") if "x" in b.schema.names
                else 0, "x",
                pa.array([v * 2 for v in b.column("x").to_pylist()]))

    df = make_df(9, parts=3).select("x").mapStream(stream_fn)
    assert df.columns == ["x"]  # schema probe ran the stream op on 1 row
    rows = [r.x for r in df.collect()]
    assert rows == [i * 2 for i in range(9)]
    # ONE stream-fn invocation per materialization (collect), not one per
    # partition — the property the streaming scorer needs to keep its
    # device window alive across partition boundaries.
    assert calls.count("open") >= 1
    calls.clear()
    df.collect()
    assert calls.count("open") == 1
    # length-preserving contract keeps the lazy count/limit fast paths
    assert df.count() == 9
    assert [r.x for r in df.limit(4).collect()] == [0, 2, 4, 6]
