"""Chaos subsystem tests (ISSUE 1 tentpole): deterministic fault injection
through the real training machinery, proving the recovery paths the seed
only *declared* — checkpoint-resume under injected preemption, fail-fast on
divergence, heartbeat plumbing — actually execute in tier-1.
"""

import json
import os
import subprocess
import sys

import numpy as np
import optax
import pytest

from sparkdl_tpu.runner import (CheckpointManager, Fault, FaultPlan,
                                InjectedFatal, InjectedPreemption,
                                TrainingDivergedError, XlaRunner,
                                classify_exception, run_stats,
                                softmax_cross_entropy_loss, touch_heartbeat)
from sparkdl_tpu.runner import chaos

pytestmark = pytest.mark.chaos

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos(monkeypatch):
    """Every test starts and ends with no plan installed, no env plan, and
    zeroed process-wide failure counters."""
    monkeypatch.delenv(chaos.CHAOS_ENV, raising=False)
    chaos.uninstall()
    run_stats.reset()
    yield
    chaos.uninstall()
    run_stats.reset()


def _linear_apply(params, x):
    return x @ params["w"]


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": rng.randn(4, 3).astype(np.float32)}


def _data(n_batches=64, seed=1):
    rng = np.random.RandomState(seed)
    for _ in range(n_batches):
        x = rng.randn(16, 4).astype(np.float32)
        yield {"image": x, "label": rng.randint(0, 3, (16,))}


class TestFaultPlan:
    def test_env_roundtrip(self):
        plan = FaultPlan([Fault("step_start", "preempt", at_step=3),
                          Fault("batch_fetch", "nan", at_step=1, rank=1,
                                once=False)],
                         seed=42, state_dir="/tmp/x")
        env = plan.to_env()
        back = FaultPlan.from_env(env)
        assert back.faults == plan.faults
        assert back.seed == 42 and back.state_dir == "/tmp/x"
        assert FaultPlan.from_env({}) is None

    def test_validation(self):
        with pytest.raises(ValueError, match="site"):
            Fault("nowhere", "preempt", at_step=0)
        with pytest.raises(ValueError, match="kind"):
            Fault("step_start", "explode", at_step=0)
        with pytest.raises(ValueError, match="batch_fetch"):
            Fault("step_start", "nan", at_step=0)
        with pytest.raises(ValueError, match="trigger"):
            Fault("step_start", "preempt")  # no at_step, no prob

    def test_at_step_fires_once_and_counts(self):
        plan = chaos.install(FaultPlan([Fault("step_start", "preempt",
                                              at_step=2)]))
        chaos.fire("step_start", step=0)
        chaos.fire("step_start", step=1)
        with pytest.raises(InjectedPreemption, match="UNAVAILABLE"):
            chaos.fire("step_start", step=2)
        # once=True: same step again does NOT re-fire (restart passed it)
        chaos.fire("step_start", step=2)
        assert plan._fired[0] == 1
        assert run_stats.faults_injected == 1
        assert run_stats.fault_sites == ["step_start:preempt"]

    def test_prob_trigger_is_seed_deterministic(self):
        def pattern(seed):
            plan = FaultPlan([Fault("collective", "hang", prob=0.3,
                                    once=False, hang_s=0.0)], seed=seed)
            fired = []
            for _ in range(64):
                before = plan._fired[0]
                plan.fire("collective")
                fired.append(plan._fired[0] > before)
            return fired

        a, b = pattern(7), pattern(7)
        assert a == b
        assert any(a) and not all(a)  # a real coin, not a constant
        assert pattern(8) != a

    def test_rank_filter(self, monkeypatch):
        plan = chaos.install(FaultPlan([Fault("step_start", "preempt",
                                              at_step=0, rank=1)]))
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "0")
        chaos.fire("step_start", step=0)  # wrong rank: no fire
        assert plan._fired[0] == 0
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "1")
        with pytest.raises(InjectedPreemption):
            chaos.fire("step_start", step=0)

    def test_once_persists_across_plan_instances_via_state_dir(self, tmp_path):
        plan1 = FaultPlan([Fault("step_start", "preempt", at_step=1)],
                          state_dir=str(tmp_path))
        with pytest.raises(InjectedPreemption):
            plan1.fire("step_start", step=1)
        # A "restarted process": fresh plan parsed from the same env JSON
        plan2 = FaultPlan.from_json(plan1.to_json())
        plan2.fire("step_start", step=1)  # marker file suppresses re-fire
        assert plan2._fired[0] == 0

    def test_nan_poisons_float_leaves_only(self):
        chaos.install(FaultPlan([Fault("batch_fetch", "nan", at_step=0)]))
        batch = {"image": np.ones((4, 2), np.float32),
                 "label": np.arange(4)}
        out = chaos.fire("batch_fetch", step=0, batch=batch)
        assert np.isnan(out["image"]).all()
        assert (out["label"] == np.arange(4)).all()
        assert run_stats.faults_injected == 1

    def test_env_autoinstall(self, monkeypatch):
        plan = FaultPlan([Fault("worker", "fatal", prob=1.0)])
        monkeypatch.setenv(chaos.CHAOS_ENV, plan.to_json())
        chaos.uninstall()  # forget the fixture's "env checked" latch
        with pytest.raises(InjectedFatal, match="INVALID_ARGUMENT"):
            chaos.fire("worker")

    def test_no_plan_is_noop(self):
        batch = {"x": np.ones(3)}
        assert chaos.fire("step_start", step=0, batch=batch) is batch

    def test_injected_errors_classify_correctly(self):
        assert classify_exception(
            InjectedPreemption("UNAVAILABLE: injected")) == "retryable"
        assert classify_exception(
            InjectedFatal("INVALID_ARGUMENT: injected")) == "fatal"
        assert classify_exception(TrainingDivergedError(7, float("nan"))) \
            == "fatal"


class TestChaosThroughFit:
    """The two tier-1 acceptance paths: injected preemption -> one restart,
    resume from checkpoint, exact stats; injected NaN -> fatal fail-fast,
    zero restarts consumed."""

    def test_preempt_at_step_k_restarts_once_and_resumes(self, tmp_path):
        chaos.install(FaultPlan([Fault("step_start", "preempt", at_step=3)]))
        ckpt = str(tmp_path / "ckpt")
        params = _params()
        attempts = []

        def main(ctx):
            attempts.append(1)
            return ctx.fit(loss_fn=softmax_cross_entropy_loss(),
                           params=params, tx=optax.sgd(0.1),
                           apply_fn=_linear_apply, data=_data(),
                           num_steps=6, checkpoint_every=2, log_every=100)

        res = XlaRunner(np=8, checkpoint_dir=ckpt).run_with_restarts(
            main, max_restarts=2, backoff_s=0.0)
        assert len(attempts) == 2
        assert int(res["state"].step) == 6
        # Resume proof: attempt 1 checkpointed at step 2 and died at step 3;
        # attempt 2 ran steps 2..5 only.
        assert res["meter"].steps == 4
        snap = run_stats.snapshot()
        assert snap["restarts"] == 1
        assert snap["faults_injected"] == 1
        assert snap["last_failure_kind"] == "retryable"
        assert "UNAVAILABLE" in snap["last_failure"]

    def test_nan_batch_fails_fast_fatal_no_restart(self, tmp_path):
        chaos.install(FaultPlan([Fault("batch_fetch", "nan", at_step=1)]))
        ckpt = str(tmp_path / "ckpt")
        attempts = []

        def main(ctx):
            attempts.append(1)
            return ctx.fit(loss_fn=softmax_cross_entropy_loss(),
                           params=_params(), tx=optax.sgd(0.1),
                           apply_fn=_linear_apply, data=_data(),
                           num_steps=4, checkpoint_every=2, log_every=1)

        with pytest.raises(TrainingDivergedError) as ei:
            XlaRunner(np=8, checkpoint_dir=ckpt).run_with_restarts(
                main, max_restarts=3, backoff_s=0.0)
        assert ei.value.step == 2  # NaN batch fed step index 1 -> step 2
        assert len(attempts) == 1  # fatal: no restart consumed
        snap = run_stats.snapshot()
        assert snap["restarts"] == 0
        assert snap["last_failure_kind"] == "fatal"
        # The guard beat the step-2 checkpoint: nothing garbage on disk.
        mngr = CheckpointManager(ckpt, async_save=False)
        assert mngr.latest_step() is None
        mngr.close()

    def test_fatal_injection_does_not_retry(self):
        chaos.install(FaultPlan([Fault("step_start", "fatal", at_step=1)]))
        attempts = []

        def main(ctx):
            attempts.append(1)
            return ctx.fit(loss_fn=softmax_cross_entropy_loss(),
                           params=_params(), tx=optax.sgd(0.1),
                           apply_fn=_linear_apply, data=_data(),
                           num_steps=3, log_every=100)

        with pytest.raises(InjectedFatal):
            XlaRunner(np=8).run_with_restarts(main, max_restarts=3,
                                              backoff_s=0.0)
        assert len(attempts) == 1

    def test_fit_touches_heartbeat(self, tmp_path, monkeypatch):
        hb = tmp_path / "hb"
        monkeypatch.setenv("SPARKDL_HEARTBEAT_DIR", str(hb))
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "0")
        XlaRunner(np=8).run(lambda ctx: ctx.fit(
            loss_fn=softmax_cross_entropy_loss(), params=_params(),
            tx=optax.sgd(0.1), apply_fn=_linear_apply, data=_data(),
            num_steps=3, log_every=100))
        beat = hb / "rank0.hb"
        assert beat.exists()
        body = json.loads(beat.read_text())
        assert body["step"] == 2  # last step index the loop reached
        assert body["time"] > 0  # wall clock rides alongside (ISSUE 2)

    def test_touch_heartbeat_noop_without_env(self, monkeypatch, tmp_path):
        monkeypatch.delenv("SPARKDL_HEARTBEAT_DIR", raising=False)
        touch_heartbeat(5)  # must not raise or create anything
        monkeypatch.setenv("SPARKDL_HEARTBEAT_DIR", str(tmp_path / "hb2"))
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "3")
        touch_heartbeat(5)
        body = json.loads((tmp_path / "hb2" / "rank3.hb").read_text())
        assert body["step"] == 5


@pytest.mark.slow
def test_chaos_smoke_script(tmp_path):
    """scripts/chaos_smoke.py end-to-end: supervisor + injected preemption
    + checkpoint resume in real subprocesses on CPU."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "chaos_smoke.py")],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert '"ok": true' in proc.stdout, proc.stdout[-2000:]


@pytest.mark.slow
@pytest.mark.chaos
def test_train_resume_smoke_script(tmp_path):
    """scripts/train_resume_smoke.py end-to-end (ISSUE 5 acceptance): a
    supervised run with one injected SIGKILL and one deterministic poison
    batch finishes; the batch-id ledger proves exactly-once consumption
    (deterministic replay, only the quarantined batch skipped); the final
    loss equals a clean run on the same skip-list; and the identical job
    without the skip-list death-loops through its restart budget."""
    import json
    proc = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "scripts", "train_resume_smoke.py")],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, \
        f"stdout={proc.stdout[-2000:]}\nstderr={proc.stderr[-2000:]}"
    rec = json.loads([ln for ln in proc.stdout.strip().splitlines()
                      if ln.startswith("{")][-1])
    assert rec["ok"] is True
    assert rec["ledger_exactly_once"] is True
    assert rec["ledger_replay_deterministic"] is True
    assert rec["final_loss_matches_clean_run"] is True
    assert rec["counterfactual_death_loops"] is True
    assert rec["degradations_narrate_resume_and_quarantine"] is True


@pytest.mark.slow
def test_obs_smoke_script(tmp_path):
    """scripts/obs_smoke.py end-to-end (ISSUE 2 + ISSUE 6 + ISSUE 7
    satellites): a real CPU fit under the supervisor with the flight
    recorder on and one injected preemption — the merged gang-timeline
    postmortem must name the faulted rank and site; then a
    streamed-scoring run with the live telemetry plane armed — a
    snapshot file must appear MID-run and the bottleneck report must
    name the expected host-side stage (decode) with internally
    consistent busy fractions; then a REAL image-scoring run whose
    Arrow decode was the pre-ISSUE-7 bottleneck — post-PR the report
    must NOT name decode dominant (the fused zero-copy feed collapsed
    it); finally the ISSUE 13 serving leg — a stub engine under load
    with the plane armed: /serving answers with a live slot map
    MID-run, request_report.py names the slowest request's dominant
    phase, healthy SLO compliance >= 0.99, and an injected-slowness
    leg flips the burn-rate gauge."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "obs_smoke.py")],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads([ln for ln in proc.stdout.strip().splitlines()
                      if ln.startswith("{")][-1])
    assert rec["ok"] is True
    assert rec["postmortem_ok"] is True
    tele = rec["telemetry"]
    assert tele["snapshot_mid_run"] is True
    assert tele["dominant_stage"] == "decode"
    assert tele["busy_fracs_consistent"] is True
    assert tele["max_speedup_fixing_others"] >= 1.0
    serving = rec["serving"]
    assert serving["serving_endpoint_live_mid_run"] is True
    assert serving["healthy_ttft_compliance"] >= 0.99
    assert serving["chaos_breaching"] is True
    assert serving["burn_gauge_value"] > 1.0
    assert serving["slowest_dominant_phase"] in ("prefill",
                                                 "prefill_wait")
    assert serving["max_unattributed_frac"] <= 0.05


class TestCorruptKind:
    """ISSUE 4: the `corrupt` fault kind + the new decode/dispatch/
    checkpoint_restore sites (their behavioral coverage lives in
    test_runtime.py / test_streaming.py / test_checkpoint.py)."""

    def test_new_sites_and_kind_validate(self):
        # corrupt is checkpoint_restore-only
        with pytest.raises(ValueError, match="corrupt"):
            Fault("step_start", "corrupt", prob=1.0)
        for site in ("decode", "dispatch", "checkpoint_restore"):
            assert Fault(site, "preempt", prob=1.0).site == site
        f = Fault("checkpoint_restore", "corrupt", prob=1.0)
        # env transport round-trips the new site/kind
        back = FaultPlan.from_env(FaultPlan([f]).to_env())
        assert back.faults == [f]

    def test_poison_kind_and_data_fetch_site_validate(self):
        """ISSUE 5: `poison` is a drawn-batch kind (data_fetch /
        batch_fetch only); the data_fetch site's step is the dataset's
        global batch index."""
        with pytest.raises(ValueError, match="poison"):
            Fault("step_start", "poison", prob=1.0)
        f = Fault("data_fetch", "poison", at_step=8, once=False)
        back = FaultPlan.from_env(FaultPlan([f]).to_env())
        assert back.faults == [f]
        assert Fault("batch_fetch", "poison", at_step=1).site == \
            "batch_fetch"

    def test_poison_nans_floats_or_raises_without_them(self):
        import numpy as np
        plan = chaos.install(FaultPlan(
            [Fault("data_fetch", "poison", at_step=2, once=False)]))
        try:
            clean = {"x": np.ones(3, np.float32), "y": np.arange(3)}
            out = plan.fire("data_fetch", step=1, batch=clean)
            assert out is clean  # wrong batch index: untouched
            out = plan.fire("data_fetch", step=2, batch=clean)
            assert np.isnan(out["x"]).all()
            np.testing.assert_array_equal(out["y"], np.arange(3))
            # refires on the SAME index every time (once=False): the
            # deterministic poison record the quarantine correlates on
            out2 = plan.fire("data_fetch", step=2, batch=clean)
            assert np.isnan(out2["x"]).all()
            with pytest.raises(chaos.InjectedFatal, match="poison"):
                plan.fire("data_fetch", step=2,
                          batch={"ids": np.arange(3)})  # no float leaves
        finally:
            chaos.uninstall()

    def test_corrupt_damages_newest_step_only(self, tmp_path):
        for step, size in ((1, 64), (2, 64)):
            d = tmp_path / str(step)
            d.mkdir()
            (d / "data.bin").write_bytes(b"\x00" * size)
        damaged = chaos.corrupt_latest_checkpoint(str(tmp_path))
        assert damaged and "/2/" in damaged[0]
        assert (tmp_path / "2" / "data.bin").stat().st_size < 64  # truncated
        assert (tmp_path / "1" / "data.bin").stat().st_size == 64  # untouched
        # robust no-ops: empty dir / missing dir / None
        assert chaos.corrupt_latest_checkpoint(str(tmp_path / "none")) == []
        assert chaos.corrupt_latest_checkpoint(None) == []

    def test_corrupt_fires_through_restore_site(self, tmp_path):
        """fire('checkpoint_restore', path=...) with a corrupt fault
        damages the newest step under path and records the injection."""
        d = tmp_path / "3"
        d.mkdir()
        (d / "leaf.bin").write_bytes(b"\x11" * 32)
        chaos.install(FaultPlan([Fault("checkpoint_restore", "corrupt",
                                       prob=1.0)]))
        chaos.fire("checkpoint_restore", path=str(tmp_path))
        assert (d / "leaf.bin").stat().st_size < 32
        assert run_stats.fault_sites == ["checkpoint_restore:corrupt"]


@pytest.mark.slow
def test_supervised_gang_rolls_back_corrupt_checkpoint(tmp_path):
    """ISSUE 4 acceptance, gang level: attempt 1 checkpoints steps 2 and 4
    then dies on an injected preemption; before attempt 2's restore an
    injected `corrupt` fault damages step 4 on disk. The restore must
    quarantine it, roll back to verified step 2, and finish within the
    restart budget — with the rollback visible on the SuperviseResult's
    degradation ledger (no death loop)."""
    from sparkdl_tpu.runner.launcher import supervise

    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {_REPO!r})
import numpy as np
import optax
from sparkdl_tpu.runner import XlaRunner, softmax_cross_entropy_loss

out_dir = sys.argv[1]
runner = XlaRunner(checkpoint_dir=os.path.join(out_dir, "ckpt"))
rng = np.random.RandomState(0)
params = {{"w": rng.randn(4, 3).astype(np.float32)}}

def data():
    r = np.random.RandomState(1)
    while True:
        yield {{"image": r.randn(8, 4).astype(np.float32),
               "label": r.randint(0, 3, (8,))}}

res = runner.run(lambda ctx: ctx.fit(
    loss_fn=softmax_cross_entropy_loss(), params=params, tx=optax.sgd(0.1),
    apply_fn=lambda p, x: x @ p["w"], data=data(), num_steps=6,
    checkpoint_every=2, log_every=100))
with open(os.path.join(out_dir, "attempts.jsonl"), "a") as f:
    f.write(json.dumps({{"final_step": int(res["state"].step),
                        "steps_this_attempt": res["meter"].steps}}) + "\\n")
""")
    plan = FaultPlan([
        Fault("step_start", "preempt", at_step=5),
        Fault("checkpoint_restore", "corrupt", prob=1.0),
    ])
    res = supervise(str(worker), np=1, args=[str(tmp_path)],
                    timeout_s=300.0, max_restarts=2, backoff_s=0.1,
                    poll_s=0.25, plan=plan)
    attempts = [json.loads(ln)
                for ln in open(tmp_path / "attempts.jsonl")]
    assert res.restarts == 1  # one relaunch, within budget — no death loop
    assert res.failure_kinds == ["retryable"]
    # rolled back to step 2 (not 4): the resumed attempt ran 4 steps
    assert attempts == [{"final_step": 6, "steps_this_attempt": 4}]
    assert res.rolled_back
    kinds = {d.get("name") for d in res.degradations}
    assert "checkpoint_rollback" in kinds
    assert "checkpoint_quarantine" in kinds
    rb = [d for d in res.degradations
          if d.get("name") == "checkpoint_rollback"][0]
    assert (rb["from_step"], rb["to_step"]) == (4, 2)
    # forensics: the corrupt step dir is quarantined on disk
    import glob as glob_mod
    assert glob_mod.glob(str(tmp_path / "ckpt" / "4.corrupt*"))


class TestDecimateKind:
    """ISSUE 16: `decimate` — a rank death whose SLOT stays dead. Unlike
    every once=True kind (whose state_dir marker SUPPRESSES a re-fire so
    the restarted gang can make progress), decimate's marker makes the
    fault KEEP firing for the same rank at the same world size, modeling
    permanently lost capacity. The kill path itself is subprocess-only
    (SIGKILL of the calling process) — covered by the supervision tests
    in test_multiprocess.py and scripts/elastic_smoke.py; here we pin
    validation, env transport, and the marker semantics."""

    def test_kind_validates_anywhere_and_roundtrips(self):
        f = Fault("step_start", "decimate", at_step=5, rank=2)
        back = FaultPlan.from_env(FaultPlan([f]).to_env())
        assert back.faults == [f]
        # any site: entry-point re-kill means site is just the first kill
        assert Fault("worker", "decimate", prob=1.0).kind == "decimate"
        with pytest.raises(ValueError, match="kind"):
            Fault("step_start", "decimated", at_step=1)

    def test_marker_is_rank_and_world_scoped(self, tmp_path, monkeypatch):
        """The marker names (rank, world): after the supervisor shrinks,
        the new gang's rank 2 is a DIFFERENT slot and must not inherit
        the old world's death."""
        plan = FaultPlan([Fault("step_start", "decimate", at_step=5,
                                rank=2)], state_dir=str(tmp_path))
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "2")
        monkeypatch.setenv("SPARKDL_NUM_PROCESSES", "4")
        marker = plan.decimate_marker(2)
        assert marker.endswith("chaos_decimated_rank2_np4")
        assert not plan._slot_decimated()
        plan._mark_decimated()
        assert plan._slot_decimated()
        # same rank id, shrunken world: alive
        monkeypatch.setenv("SPARKDL_NUM_PROCESSES", "3")
        assert not plan._slot_decimated()
        # other ranks of the original world: alive
        monkeypatch.setenv("SPARKDL_NUM_PROCESSES", "4")
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "1")
        assert not plan._slot_decimated()

    def test_no_state_dir_degrades_to_plain_sigkill(self, monkeypatch):
        """Without a state_dir there is nowhere to persist the dead slot:
        decimate degrades to a one-shot sigkill (documented), and the
        re-kill probe reports 'not decimated' instead of crashing."""
        plan = FaultPlan([Fault("step_start", "decimate", at_step=5,
                                rank=2)])
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "2")
        assert plan.decimate_marker(2) is None
        assert not plan._slot_decimated()
        plan._mark_decimated()  # no-op, must not raise
        assert not plan._slot_decimated()


class TestServingSites:
    """ISSUE 19: chaos grows serving sites (serve_prefill / serve_decode /
    serve_alloc / serve_commit) + the `cache_lost` kind, so the engine's
    failover seam is exercised by the same deterministic plan machinery
    that drives training chaos. Engine-level behavioral coverage lives in
    test_serving.py and scripts/serve_chaos_smoke.py."""

    def test_serving_sites_and_cache_lost_validate(self):
        for site in chaos.SERVING_SITES:
            assert site in chaos.SITES
            assert Fault(site, "cache_lost", at_step=1).site == site
        # cache_lost models a donated-slot-cache loss: serving-only
        with pytest.raises(ValueError, match="cache_lost"):
            Fault("step_start", "cache_lost", at_step=1)
        # env transport round-trips the new site/kind
        f = Fault("serve_decode", "cache_lost", at_step=3, once=False)
        back = FaultPlan.from_env(FaultPlan([f]).to_env())
        assert back.faults == [f]

    def test_injected_cache_lost_is_serving_fatal_and_retryable(self):
        """The injected error carries the `serving_fatal` routing attr
        (engine fails over instead of retrying the slot call) but
        classifies retryable for the cluster supervisor — lost backend
        state is recoverable by a rebuild, same verdict as the organic
        SlotCacheLost."""
        exc = chaos.InjectedCacheLost("injected slot-cache loss")
        assert getattr(exc, "serving_fatal", False)
        assert isinstance(exc, chaos.InjectedFault)
        assert classify_exception(exc) == "retryable"

    def test_engine_fails_over_under_cache_lost_plan(self):
        """An installed plan firing cache_lost at a serve_decode call
        must push the engine through a full failover (backend rebuild +
        re-admission) and still complete every request with exactly-once
        delivery."""
        from sparkdl_tpu.serving import GenerationEngine, StubBackend

        # prob=1.0 + once: fire on the FIRST decode call, whatever the
        # global backend-call index it lands on (the step counter is
        # shared across serving sites).
        chaos.install(FaultPlan([Fault("serve_decode", "cache_lost",
                                       prob=1.0)]))
        eng = GenerationEngine(StubBackend(2, 64, vocab_size=997),
                               retries=1)
        reqs = [eng.submit([7 * (i + 1)], max_new_tokens=5)
                for i in range(2)]
        for _ in range(200):
            if not eng.step():
                break
        assert all(r.state == "done" for r in reqs)
        assert eng.stats["failovers"] == 1
        assert eng.stats["failover_resumed"] == 2
        assert eng._failover_info["state"] == "recovered"
        assert eng._failover_info["last_cause"].startswith(
            "InjectedCacheLost")
        for r in reqs:
            assert r.delivered == len(r.tokens) == 5
        # token identity vs an uninjected run: exactly-once resume means
        # chaos must be invisible in the output stream
        chaos.uninstall()
        clean = GenerationEngine(StubBackend(2, 64, vocab_size=997),
                                 retries=1)
        creqs = [clean.submit([7 * (i + 1)], max_new_tokens=5)
                 for i in range(2)]
        for _ in range(200):
            if not clean.step():
                break
        for r, c in zip(reqs, creqs):
            assert r.tokens == c.tokens
