"""Exactly-once training data plane (ISSUE 5): cursor round-trips,
skip-lists, adapters, manifest-persisted cursors, and fit() threading —
the lookahead-replay and legacy-manifest degradation cases pinned fast.

All CPU-only; the supervised end-to-end (SIGKILL + poison batch +
quarantine) lives in scripts/train_resume_smoke.py (slow, test_chaos.py);
the supervisor's correlation logic is pinned fast in test_multiprocess.py.
"""

import json
import os

import numpy as np
import optax
import pytest

from sparkdl_tpu.runner import (CheckpointManager, ListDataset, XlaRunner,
                                softmax_cross_entropy_loss)
from sparkdl_tpu.runner import chaos, events
from sparkdl_tpu.runner import data as data_lib
from sparkdl_tpu.runner.chaos import Fault, FaultPlan, InjectedPreemption
from sparkdl_tpu.runner.data import (ArrowDataset, FactoryDataset,
                                     as_dataset, env_skip_list, read_ledger)


def _batches(n, rows=8):
    return [{"image": np.random.RandomState(i).randn(rows, 4)
                 .astype(np.float32),
             "label": np.random.RandomState(i).randint(0, 3, (rows,))}
            for i in range(n)]


def _ids(pairs):
    """[(epoch, batch_index), ...] drawn from indexed() pairs."""
    return [(c["epoch"], c["batch_index"] - 1) for c, _ in pairs]


class TestCursorRoundTrip:
    def test_state_restore_resumes_at_exact_batch(self):
        ds = ListDataset(_batches(6))
        it = ds.indexed()
        first = [next(it) for _ in range(3)]
        cursor = first[-1][0]  # after batch 2
        ds2 = ListDataset(_batches(6))
        ds2.restore(cursor)
        rest = list(ds2.indexed())
        assert _ids(rest) == [(0, 3), (0, 4), (0, 5)]
        # and the replayed batches are the SAME arrays, not re-generated
        np.testing.assert_array_equal(rest[0][1]["image"],
                                      _batches(6)[3]["image"])

    def test_restore_records_shuffle_seed_mismatch(self):
        """Review regression: a CRC-valid cursor from a run with a
        different shuffle_seed maps positions to different batches —
        restore() must put that on record, not silently replay wrong."""
        rec = events.reset()
        src = ListDataset(_batches(4), shuffle_seed=7)
        next(src.indexed())
        ds = ListDataset(_batches(4), shuffle_seed=3)
        ds.restore(src.state())
        evs = [e for e in rec.tail()
               if e["name"] == "unverified_data_cursor"]
        assert evs and "shuffle_seed mismatch" in evs[0]["reason"]
        # same seed: no spurious degradation
        rec = events.reset()
        ListDataset(_batches(4), shuffle_seed=7).restore(src.state())
        assert not [e for e in rec.tail()
                    if e["name"] == "unverified_data_cursor"]

    def test_cursor_is_jsonable_and_round_trips(self):
        ds = ListDataset(_batches(3), shuffle_seed=7)
        next(ds.indexed())
        state = json.loads(json.dumps(ds.state()))
        ds2 = ListDataset(_batches(3), shuffle_seed=7)
        ds2.restore(state)
        assert ds2.state()["batch_index"] == state["batch_index"]
        assert state["shuffle_seed"] == 7

    def test_skip_list_honored_and_recorded(self):
        rec = events.reset()
        ds = ListDataset(_batches(5), skip_list=[1, 3])
        out = _ids(ds.indexed())
        assert out == [(0, 0), (0, 2), (0, 4)]
        skipped = [e for e in rec.tail()
                   if e["name"] == "train_batch_skipped"]
        assert [e["batch_index"] for e in skipped] == [1, 3]
        # the cursor carries the skip-list forward
        assert ds.state()["skip_list"] == [1, 3]

    def test_epochs_advance_and_restore_mid_epoch(self):
        ds = ListDataset(_batches(3), epochs=2)
        assert _ids(ds.indexed()) == [(0, 0), (0, 1), (0, 2),
                                      (1, 0), (1, 1), (1, 2)]
        ds2 = ListDataset(_batches(3), epochs=2)
        ds2.restore({"epoch": 1, "batch_index": 1, "skip_list": []})
        assert _ids(ds2.indexed()) == [(1, 1), (1, 2)]

    def test_shuffle_is_deterministic_per_epoch(self):
        def content(ds):
            return [float(b["image"][0, 0]) for _, b in ds.indexed()]

        a = content(ListDataset(_batches(8), epochs=2, shuffle_seed=3))
        b = content(ListDataset(_batches(8), epochs=2, shuffle_seed=3))
        assert a == b  # identically seeded -> identical order (replayable)
        assert a[:8] != a[8:]  # permutation re-seeded per epoch
        assert sorted(a[:8]) == sorted(a[8:])  # same batches, new order


class TestAdapters:
    def test_factory_dataset_fresh_iterator_per_epoch(self):
        calls = []

        def factory():
            calls.append(1)
            return iter(_batches(2))

        ds = FactoryDataset(factory, epochs=2)
        assert _ids(ds.indexed()) == [(0, 0), (0, 1), (1, 0), (1, 1)]
        assert len(calls) == 2

    def test_epoch_aware_factory_gets_the_epoch(self):
        seen = []

        def factory(epoch):
            seen.append(epoch)
            return iter(_batches(1))

        list(FactoryDataset(factory, epochs=3).indexed())
        assert seen == [0, 1, 2]

    def test_defaulted_factory_param_is_not_epoch_aware(self):
        """Review regression: `lambda n=2: ...` is configuration, not an
        epoch slot — passing epoch 0 as n would yield an empty epoch and
        silently end the dataset at step 0."""
        ds = FactoryDataset(lambda n=2: iter(_batches(n)), epochs=1)
        assert len(list(ds.indexed())) == 2

    def test_arrow_skipped_indices_never_converted(self):
        """Review regression: a record whose DECODE is the poison must be
        skippable — skip-listed indices yield raw, unconverted."""
        import pyarrow as pa

        import sparkdl_tpu as sdl
        df = sdl.DataFrame.fromArrow(
            pa.table({"x": np.arange(12, dtype=np.float32)}),
            numPartitions=2)

        def convert(rb):
            out = {"x": rb.column("x").to_numpy(zero_copy_only=False)}
            if out["x"][0] == 4.0:  # batch index 1 is the poison
                raise RuntimeError("decode poison")
            return out

        poisoned = ArrowDataset(df, batch_size=4, convert=convert)
        with pytest.raises(RuntimeError, match="decode poison"):
            list(poisoned.indexed())
        skipping = ArrowDataset(df, batch_size=4, convert=convert,
                                skip_list=[1])
        got = [b["x"][0] for _, b in skipping.indexed()]
        assert got == [0.0, 8.0]  # batch 1 skipped without decoding

    def test_arrow_dataset_round_trip(self):
        import pyarrow as pa

        import sparkdl_tpu as sdl
        df = sdl.DataFrame.fromArrow(
            pa.table({"x": np.arange(10, dtype=np.float32),
                      "label": np.arange(10) % 3}), numPartitions=3)
        ds = ArrowDataset(df, batch_size=4)
        got = list(ds.indexed())
        assert [len(b["x"]) for _, b in got] == [4, 4, 2]
        np.testing.assert_array_equal(got[1][1]["x"],
                                      np.arange(4, 8, dtype=np.float32))
        # restore replays the tail exactly
        ds2 = ArrowDataset(df, batch_size=4)
        ds2.restore(got[0][0])
        np.testing.assert_array_equal(
            next(ds2.indexed())[1]["x"], got[1][1]["x"])

    def test_as_dataset_coercions(self):
        assert isinstance(as_dataset(_batches(2)), ListDataset)
        assert isinstance(as_dataset(lambda: iter(_batches(2))),
                          FactoryDataset)
        ds = ListDataset(_batches(1))
        assert as_dataset(ds) is ds
        # a bare generator is consumable-once: no cursor, legacy path
        assert as_dataset(iter(_batches(2))) is None

    def test_env_skip_list_parsing(self, monkeypatch):
        monkeypatch.setenv(data_lib.SKIP_ENV, "[3, 5]")
        assert env_skip_list() == [3, 5]
        monkeypatch.setenv(data_lib.SKIP_ENV, "not json")
        assert env_skip_list() == []
        monkeypatch.delenv(data_lib.SKIP_ENV)
        assert env_skip_list() == []

    def test_rank_sharding_is_opt_in(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_NUM_PROCESSES", "2")
        monkeypatch.setenv("SPARKDL_PROCESS_ID", "1")
        # default: fit's gang contract — data is ALREADY the local shard;
        # the dataset must not silently re-slice it (review finding)
        _, untouched = next(ListDataset(_batches(2)).indexed())
        assert len(untouched["image"]) == 8
        # shard=True: global stream, rank slices its contiguous share
        ds = ListDataset(_batches(2), shard=True)
        cur, local = next(ds.indexed())
        assert len(local["image"]) == 4  # 8 global rows -> 4 local
        np.testing.assert_array_equal(local["image"],
                                      _batches(2)[0]["image"][4:])
        # cursor stays GLOBAL: rank 1's cursor == rank 0's
        assert cur["batch_index"] == 1
        # non-sliceable leaves replicate instead of crashing
        ds2 = ListDataset([{"x": np.ones((8, 2), np.float32),
                            "frac": 0.5}], shard=True)
        _, b = next(ds2.indexed())
        assert b["frac"] == 0.5 and len(b["x"]) == 4


class TestManifestCursor:
    def _state(self):
        from sparkdl_tpu.runner import TrainState
        return TrainState.create(
            None, {"w": np.ones((4, 3), np.float32)}, optax.sgd(0.1))

    def test_cursor_persists_and_verifies(self, tmp_path):
        m = CheckpointManager(str(tmp_path / "c"), async_save=False)
        cur = {"epoch": 0, "batch_index": 4, "skip_list": [2]}
        m.save(4, self._state(), wait=True, data_cursor=cur)
        assert m.data_cursor(4) == cur
        m.close()

    def test_tampered_cursor_is_rejected_with_degradation(self, tmp_path):
        m = CheckpointManager(str(tmp_path / "c"), async_save=False)
        m.save(2, self._state(), wait=True,
               data_cursor={"epoch": 0, "batch_index": 2, "skip_list": []})
        path = str(tmp_path / "c" / "manifest_step_2.json")
        man = json.load(open(path))
        man["data_cursor"]["batch_index"] = 7  # bit-rot / hand edit
        json.dump(man, open(path, "w"))
        rec = events.reset()
        assert m.data_cursor(2) is None
        evs = [e for e in rec.tail()
               if e["name"] == "unverified_data_cursor"]
        assert evs and "checksum" in evs[0]["reason"]
        m.close()

    def test_legacy_manifest_without_cursor_degrades(self, tmp_path):
        """A pre-ISSUE-5 manifest (no data_cursor key) restores with a
        recorded unverified_data_cursor degradation, not a crash."""
        m = CheckpointManager(str(tmp_path / "c"), async_save=False)
        m.save(1, self._state(), wait=True)  # no cursor (legacy shape)
        rec = events.reset()
        assert m.data_cursor(1) is None
        evs = [e for e in rec.tail()
               if e["name"] == "unverified_data_cursor"]
        assert evs and "pre-cursor" in evs[0]["reason"]
        m.close()


def _fit(ckpt_dir, data, num_steps, **kw):
    kw.setdefault("log_every", 100)
    runner = XlaRunner(checkpoint_dir=str(ckpt_dir))
    params = {"w": np.random.RandomState(0).randn(4, 3).astype(np.float32)}
    return runner.run(lambda ctx: ctx.fit(
        loss_fn=softmax_cross_entropy_loss(), params=params,
        tx=optax.sgd(0.1), apply_fn=lambda p, x: x @ p["w"], data=data,
        num_steps=num_steps, checkpoint_every=2, **kw))


class TestFitCursorThreading:
    def test_resume_continues_at_exact_batch(self, tmp_path, monkeypatch):
        """Two fits over one checkpoint dir: the second must resume the
        DATA at batch 4, not replay 0..3 (pinned via the batch ledger)."""
        monkeypatch.setenv(data_lib.LEDGER_ENV, str(tmp_path / "led"))
        batches = _batches(8)
        _fit(tmp_path / "ck", ListDataset(batches), 4)
        _fit(tmp_path / "ck", ListDataset(batches), 8)
        led = read_ledger(str(tmp_path / "led"))
        assert [(e["step"], e["batch_index"]) for e in led] == \
            [(i, i) for i in range(8)]

    def test_lookahead_batches_replayed_not_dropped(self, tmp_path,
                                                    monkeypatch):
        """THE documented-caveat fix: a mid-loop failure with
        feed_lookahead > 0 used to silently drop the prefetched batches;
        with a dataset they replay from the cursor on resume."""
        monkeypatch.setenv(data_lib.LEDGER_ENV, str(tmp_path / "led"))
        batches = _batches(8)
        chaos.install(FaultPlan(
            [Fault("step_start", "preempt", at_step=3)]))
        try:
            with pytest.raises(InjectedPreemption):
                _fit(tmp_path / "ck", ListDataset(batches), 8,
                     feed_lookahead=2)
        finally:
            chaos.uninstall()
        # steps 0..2 completed; lookahead had drawn batches ~3..5 which
        # died with the attempt. Resume must replay them.
        _fit(tmp_path / "ck", ListDataset(batches), 8, feed_lookahead=2)
        led = read_ledger(str(tmp_path / "led"))
        by_step = {}
        for e in led:
            assert by_step.setdefault(e["step"], e["batch_index"]) \
                == e["batch_index"], "replay diverged"
        assert sorted(by_step.items()) == [(i, i) for i in range(8)]

    def test_fit_honors_env_skip_list(self, tmp_path, monkeypatch):
        monkeypatch.setenv(data_lib.LEDGER_ENV, str(tmp_path / "led"))
        monkeypatch.setenv(data_lib.SKIP_ENV, "[1]")
        _fit(tmp_path / "ck", ListDataset(_batches(5)), 4)
        led = read_ledger(str(tmp_path / "led"))
        assert [e["batch_index"] for e in led] == [0, 2, 3, 4]

    def test_draw_failure_attributed_to_failing_batch(self, tmp_path,
                                                      monkeypatch):
        """Review finding: a failure raised while DRAWING batch X must
        postmortem as batch X, not as the previous step's batch — a wrong
        index would make the supervisor quarantine good data."""
        monkeypatch.setenv(events.RECORDER_DIR_ENV, str(tmp_path / "ev"))
        events.reset()
        chaos.install(FaultPlan(
            [Fault("data_fetch", "fatal", at_step=3, once=False)]))
        try:
            with pytest.raises(chaos.InjectedFatal):
                _fit(tmp_path / "ck", ListDataset(_batches(8)), 8,
                     feed_lookahead=2)
        finally:
            chaos.uninstall()
            monkeypatch.delenv(events.RECORDER_DIR_ENV)
            events.reset()
        pm = json.load(open(tmp_path / "ev" / "postmortem_rank0.json"))
        assert pm["batch_index"] == 3 and pm["epoch"] == 0
        # the data_fetch SPAN error event — usually the timeline's
        # earliest evidence, hence what the supervisor's signature reads
        # — must carry the tag too (verify-drive regression: without it
        # first_failure had no batch_index and quarantine never fired)
        evs = [json.loads(ln) for ln in
               open(tmp_path / "ev" / "events_rank0.jsonl")]
        span_err = [e for e in evs if e["name"] == "data_fetch"
                    and e.get("error")]
        assert span_err and span_err[0]["batch_index"] == 3

    def test_step_start_failure_not_attributed_to_previous_batch(
            self, tmp_path, monkeypatch):
        """Review regression: a failure at the step_start hook (before
        this step's batch is drawn) must carry NO batch attribution —
        cur_cursor still holding the previous step's batch would make
        the supervisor quarantine innocent data."""
        monkeypatch.setenv(events.RECORDER_DIR_ENV, str(tmp_path / "ev"))
        events.reset()
        chaos.install(FaultPlan(
            [Fault("step_start", "fatal", at_step=2, once=False)]))
        try:
            with pytest.raises(chaos.InjectedFatal):
                _fit(tmp_path / "ck", ListDataset(_batches(8)), 8)
        finally:
            chaos.uninstall()
            monkeypatch.delenv(events.RECORDER_DIR_ENV)
            events.reset()
        pm = json.load(open(tmp_path / "ev" / "postmortem_rank0.json"))
        assert pm["batch_index"] is None

    def test_diverged_attribution_suppressed_unless_log_every_1(
            self, tmp_path, monkeypatch):
        """Review finding: with log_every > 1 the NaN producer is
        anywhere in the window — the postmortem must carry NO
        batch_index (no quarantine) rather than name the detection
        step's innocent batch."""
        from sparkdl_tpu.runner.failures import TrainingDivergedError
        monkeypatch.setenv(events.RECORDER_DIR_ENV, str(tmp_path / "ev"))
        events.reset()
        chaos.install(FaultPlan(
            [Fault("data_fetch", "poison", at_step=2, once=False)]))
        try:
            with pytest.raises(TrainingDivergedError):
                _fit(tmp_path / "ck", ListDataset(_batches(8)), 8,
                     log_every=3)
        finally:
            chaos.uninstall()
            monkeypatch.delenv(events.RECORDER_DIR_ENV)
            events.reset()
        pm = json.load(open(tmp_path / "ev" / "postmortem_rank0.json"))
        assert pm["batch_index"] is None
        # ...while log_every=1 attributes exactly (train_resume_smoke
        # relies on this): pinned in-process too
        monkeypatch.setenv(events.RECORDER_DIR_ENV, str(tmp_path / "ev2"))
        events.reset()
        chaos.install(FaultPlan(
            [Fault("data_fetch", "poison", at_step=2, once=False)]))
        try:
            with pytest.raises(TrainingDivergedError):
                _fit(tmp_path / "ck2", ListDataset(_batches(8)), 8,
                     log_every=1)
        finally:
            chaos.uninstall()
            monkeypatch.delenv(events.RECORDER_DIR_ENV)
            events.reset()
        pm = json.load(open(tmp_path / "ev2" / "postmortem_rank0.json"))
        assert pm["batch_index"] == 2

    def test_bare_iterator_keeps_legacy_path(self, tmp_path, monkeypatch):
        """A generator (not replayable) must train exactly as before —
        no cursor in the manifest, no ledger entries."""
        monkeypatch.setenv(data_lib.LEDGER_ENV, str(tmp_path / "led"))
        res = _fit(tmp_path / "ck", iter(_batches(4)), 4)
        assert int(res["state"].step) == 4
        assert read_ledger(str(tmp_path / "led")) == []
        man = json.load(open(tmp_path / "ck" / "manifest_step_4.json"))
        assert "data_cursor" not in man
