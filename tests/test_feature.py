"""Feature stages: VectorAssembler / StringIndexer / IndexToString —
the MLlib stages the reference's pipelines composed around the deep
transformers."""

import numpy as np
import pytest

import sparkdl_tpu as sdl


def test_vector_assembler_scalars_and_vectors():
    df = sdl.DataFrame.fromPydict(
        {"a": [1.0, 2.0], "b": [10, 20],
         "v": [np.asarray([0.5, 0.6], np.float32),
               np.asarray([0.7, 0.8], np.float32)]},
        numPartitions=2)
    va = sdl.VectorAssembler(inputCols=["a", "v", "b"], outputCol="feat")
    rows = va.transform(df).collect()
    np.testing.assert_allclose(rows[0]["feat"], [1.0, 0.5, 0.6, 10.0])
    np.testing.assert_allclose(rows[1]["feat"], [2.0, 0.7, 0.8, 20.0])
    with pytest.raises(ValueError, match="inputCols"):
        sdl.VectorAssembler(outputCol="f").transform(df)


def test_string_indexer_frequency_order_and_inverse():
    df = sdl.DataFrame.fromPydict(
        {"fruit": ["b", "a", "b", "c", "b", "a"]}, numPartitions=3)
    model = sdl.StringIndexer(inputCol="fruit", outputCol="idx").fit(df)
    # frequencyDesc: b(3)=0, a(2)=1, c(1)=2
    assert model.getOrDefault(model.labels) == ["b", "a", "c"]
    out = model.transform(df)
    assert [r["idx"] for r in out.collect()] == [0, 1, 0, 2, 0, 1]

    inv = sdl.IndexToString(inputCol="idx", outputCol="fruit2",
                            labels=model.getOrDefault(model.labels))
    back = inv.transform(out)
    assert [r["fruit2"] for r in back.collect()] == \
        ["b", "a", "b", "c", "b", "a"]


def test_vector_assembler_rejects_nulls_and_handles_fixed_size_list():
    import pyarrow as pa

    df = sdl.DataFrame.fromArrow(pa.table({"a": pa.array([1.0, None])}))
    with pytest.raises(ValueError, match="contains null"):
        sdl.VectorAssembler(inputCols=["a"], outputCol="f").transform(df) \
            .collect()

    # a null ELEMENT inside a non-null list value must error too — the
    # top-level null_count is 0 and conversion would silently emit NaN
    nested = pa.array([[1.0, None], [2.0, 3.0]],
                      type=pa.list_(pa.float64()))
    dfn = sdl.DataFrame.fromArrow(pa.table({"v": nested}))
    with pytest.raises(ValueError, match="contains null"):
        sdl.VectorAssembler(inputCols=["v"], outputCol="f") \
            .transform(dfn).collect()
    with pytest.raises(ValueError, match="contains null"):
        sdl.StandardScaler(inputCol="v", outputCol="s").fit(dfn)
    # fixed_size_list hides nested nulls the same way
    fsln = pa.FixedSizeListArray.from_arrays(
        pa.array([1.0, None, 2.0, 3.0], pa.float64()), 2)
    dff = sdl.DataFrame.fromArrow(pa.table({"v": fsln}))
    with pytest.raises(ValueError, match="contains null"):
        sdl.VectorAssembler(inputCols=["v"], outputCol="f") \
            .transform(dff).collect()

    # float64 survives end-to-end (no silent float32 squeeze) and
    # large_list columns work
    exact = 16777217.0  # 2**24 + 1: not representable in float32
    ll = pa.array([[exact]], type=pa.large_list(pa.float64()))
    dfp = sdl.DataFrame.fromArrow(pa.table({"v": ll}))
    row = sdl.VectorAssembler(inputCols=["v"], outputCol="f") \
        .transform(dfp).first()
    assert row["f"][0] == exact

    fsl = pa.FixedSizeListArray.from_arrays(
        pa.array([1.0, 2.0, 3.0, 4.0], pa.float32()), 2)
    df2 = sdl.DataFrame.fromArrow(pa.table({"v": fsl, "s": [7.0, 8.0]}))
    rows = sdl.VectorAssembler(inputCols=["v", "s"], outputCol="f") \
        .transform(df2).collect()
    np.testing.assert_allclose(rows[0]["f"], [1.0, 2.0, 7.0])
    np.testing.assert_allclose(rows[1]["f"], [3.0, 4.0, 8.0])


def test_vector_assembler_keeps_chain_streamable():
    """Row-wise op tag: an assembler in the chain must not force whole-
    partition materialization (the O(batchSize) host-memory contract)."""
    df = sdl.DataFrame.fromPydict(
        {"x": [float(i) for i in range(12)]}, numPartitions=1)
    out = sdl.VectorAssembler(inputCols=["x"], outputCol="f").transform(df)
    assert out._streamable()
    sizes = [b.num_rows for b in out.iterBatches(4)]
    assert sizes == [4, 4, 4]


def test_string_indexer_handle_invalid_validated_at_set_time():
    with pytest.raises(TypeError, match="handleInvalid"):
        sdl.StringIndexer(inputCol="s", outputCol="i",
                          handleInvalid="skip")


def test_string_indexer_nulls_are_invalid_not_labels():
    df = sdl.DataFrame.fromPydict({"s": ["a", None, "a"]})
    with pytest.raises(ValueError, match="null in column 's'"):
        sdl.StringIndexer(inputCol="s", outputCol="i").fit(df)
    m = sdl.StringIndexer(inputCol="s", outputCol="i",
                          handleInvalid="keep").fit(df)
    assert m.getOrDefault(m.labels) == ["a"]  # null excluded from fit
    assert [r["i"] for r in m.transform(df).collect()] == [0, 1, 0]


def test_string_indexer_unseen_labels():
    train = sdl.DataFrame.fromPydict({"s": ["x", "y"]})
    test = sdl.DataFrame.fromPydict({"s": ["x", "z"]})
    model = sdl.StringIndexer(inputCol="s", outputCol="i").fit(train)
    with pytest.raises(ValueError, match="unseen label 'z'"):
        model.transform(test).collect()
    keep = sdl.StringIndexer(inputCol="s", outputCol="i",
                             handleInvalid="keep").fit(train)
    assert [r["i"] for r in keep.transform(test).collect()] == [0, 2]


def test_feature_stages_persist(tmp_path):
    df = sdl.DataFrame.fromPydict({"s": ["a", "b", "a"]})
    model = sdl.StringIndexer(inputCol="s", outputCol="i").fit(df)
    p = str(tmp_path / "sim")
    model.save(p)
    back = sdl.load(p)
    assert back.getOrDefault(back.labels) == \
        model.getOrDefault(model.labels)
    assert [r["i"] for r in back.transform(df).collect()] == [0, 1, 0]

    va = sdl.VectorAssembler(inputCols=["x", "y"], outputCol="f")
    pv = str(tmp_path / "va")
    va.save(pv)
    va2 = sdl.load(pv)
    d2 = sdl.DataFrame.fromPydict({"x": [1.0], "y": [2.0]})
    np.testing.assert_allclose(va2.transform(d2).first()["f"], [1.0, 2.0])


def test_standard_scaler():
    rng = np.random.RandomState(0)
    X = rng.randn(50, 3) * [2.0, 5.0, 0.0] + [1.0, -3.0, 7.0]  # dim 2 const
    df = sdl.DataFrame.fromPydict(
        {"v": [np.asarray(x, np.float64) for x in X]}, numPartitions=4)

    m = sdl.StandardScaler(inputCol="v", outputCol="s", withMean=True,
                           withStd=True).fit(df)
    np.testing.assert_allclose(m.getOrDefault(m.mean), X.mean(0),
                               atol=1e-9)
    np.testing.assert_allclose(m.getOrDefault(m.std), X.std(0, ddof=1),
                               atol=1e-9)
    out = np.stack([np.asarray(r["s"]) for r in m.transform(df).collect()])
    np.testing.assert_allclose(out.mean(0), [0, 0, 0], atol=1e-9)
    np.testing.assert_allclose(out.std(0, ddof=1)[:2], [1, 1], atol=1e-9)
    # constant dimension: centered but NOT divided by zero
    assert np.isfinite(out).all() and np.allclose(out[:, 2], 0.0)

    # default flags match Spark: std only, no centering; zero-std dims
    # SCALE BY 0 (Spark semantics), not pass-through
    m2 = sdl.StandardScaler(inputCol="v", outputCol="s").fit(df)
    out2 = np.stack([np.asarray(r["s"])
                     for r in m2.transform(df).collect()])
    np.testing.assert_allclose(out2.mean(0)[:2],
                               X.mean(0)[:2] / X.std(0, ddof=1)[:2],
                               atol=1e-9)
    np.testing.assert_allclose(out2[:, 2], 0.0)

    # numerically stable at large means (a sum-of-squares accumulator
    # would cancel to std=0 here)
    big = 1.7e12 + rng.randn(100) * 987.5
    bdf = sdl.DataFrame.fromPydict(
        {"v": [np.asarray([x], np.float64) for x in big]},
        numPartitions=5)
    mb = sdl.StandardScaler(inputCol="v", outputCol="s").fit(bdf)
    np.testing.assert_allclose(mb.getOrDefault(mb.std),
                               [big.std(ddof=1)], rtol=1e-6)

    # empty partitions stream through transform; nulls error clearly
    import pyarrow as pa
    empty_part = m.transform(df.filter(lambda r: False))
    assert empty_part.count() == 0
    ndf = sdl.DataFrame.fromArrow(pa.table(
        {"v": pa.array([[1.0, 2.0, 3.0], None],
                       type=pa.list_(pa.float64()))}))
    with pytest.raises(ValueError, match="contains null"):
        m.transform(ndf).collect()

    with pytest.raises(ValueError, match="empty"):
        sdl.StandardScaler(inputCol="v", outputCol="s").fit(
            df.filter(lambda r: False))
    with pytest.raises(ValueError, match="dims"):
        bad = sdl.DataFrame.fromPydict(
            {"v": [np.zeros(5, np.float64)]})
        m.transform(bad).collect()


def test_standard_scaler_scalar_column():
    """Plain numeric columns work as 1-dim vectors (VectorAssembler in the
    same flow accepts scalars, so the scaler must too)."""
    df = sdl.DataFrame.fromPydict({"x": [1.0, 2.0, 3.0, 4.0]})
    m = sdl.StandardScaler(inputCol="x", outputCol="s",
                           withMean=True).fit(df)
    out = np.asarray([r["s"] for r in m.transform(df).collect()])
    np.testing.assert_allclose(out.mean(), 0.0, atol=1e-12)
    np.testing.assert_allclose(out.std(ddof=1), 1.0, atol=1e-12)


def test_standard_scaler_persists(tmp_path):
    df = sdl.DataFrame.fromPydict(
        {"v": [np.asarray([1.0, 2.0]), np.asarray([3.0, 6.0])]})
    m = sdl.StandardScaler(inputCol="v", outputCol="s",
                           withMean=True).fit(df)
    p = str(tmp_path / "scaler")
    m.save(p)
    back = sdl.load(p)
    a = [r["s"] for r in m.transform(df).collect()]
    b = [r["s"] for r in back.transform(df).collect()]
    np.testing.assert_allclose(a, b)


def test_indexer_in_pipeline_with_assembler():
    """The reference-era flow: StringIndexer labels + VectorAssembler
    features → LogisticRegression, all inside one Pipeline."""
    rng = np.random.RandomState(0)
    n = 40
    cls = ["cat" if i % 2 else "dog" for i in range(n)]
    feats = [rng.randn(3) + (2.0 if c == "cat" else -2.0) for c in cls]
    df = sdl.DataFrame.fromPydict(
        {"name": cls,
         "f": [np.asarray(f, np.float32) for f in feats]})
    pipe = sdl.Pipeline([
        sdl.StringIndexer(inputCol="name", outputCol="label"),
        sdl.VectorAssembler(inputCols=["f"], outputCol="features"),
        sdl.LogisticRegression(maxIter=80),
    ])
    model = pipe.fit(df)
    preds = model.transform(df).collect()
    idx = {r["name"]: r["label"] for r in
           sdl.StringIndexer(inputCol="name", outputCol="label")
           .fit(df).transform(df).collect()}
    acc = np.mean([int(r["prediction"]) == idx[r["name"]] for r in preds])
    assert acc >= 0.95
