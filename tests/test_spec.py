"""Speculative decoding on the slot cache (ISSUE 12).

Three layers, leanest first: jax-free draft-provider semantics (the
prompt-lookup run/periodicity corners, retrieval replay, LRU bounds,
env resolution, registry pairing), jax-free speculative scheduling
over the ``StubBackend`` verify mirror (k=0 bypasses everything
speculation-shaped, identity + acceptance on both cache layouts,
degrade gates, EOS mid-window, telemetry on/off), then lean CPU-llama
classes proving greedy output BIT-IDENTICAL to static ``generate()``
through speculation × chunked prefill × prefix reuse × paging × radix
grafts × preemption-resume, with zero verify/decode re-traces (the
compile-signature pin).
"""

import numpy as np
import pytest

from sparkdl_tpu.serving import (GenerationEngine, HistoryDraft,
                                 NGramDraft, StubBackend, make_provider)
from sparkdl_tpu.serving.draft import _NullDraft

# ---------------------------------------------------------------------------
# draft providers (jax-free)
# ---------------------------------------------------------------------------


class TestNGramDraft:
    def test_empty_and_degenerate_inputs(self):
        p = NGramDraft()
        assert p.propose([], 4) == []
        assert p.propose([1], 4) == []  # nothing before the suffix
        assert p.propose([1, 2, 3], 0) == []
        assert p.propose([1, 2, 3, 4], 4) == []  # no repeat, no match

    def test_run_match_prefers_full_k_continuation(self):
        # the newest occurrence of [7,7,7] inside a run overlaps the
        # suffix and has only 1 token after it — the provider must back
        # off to an occurrence with a full-k continuation
        hist = [1, 2] + [7] * 8
        assert NGramDraft().propose(hist, 4) == [7, 7, 7, 7]

    def test_periodic_pattern_predicts_cycle(self):
        hist = [5, 6, 7, 8] * 3
        assert NGramDraft().propose(hist, 4) == [5, 6, 7, 8]

    def test_shorter_continuation_when_nothing_longer_exists(self):
        # one earlier occurrence, history ends before k tokens follow
        hist = [5, 6, 9, 5, 6]
        assert NGramDraft().propose(hist, 4) == [9, 5, 6]

    def test_validation(self):
        with pytest.raises(ValueError):
            NGramDraft(max_ngram=2, min_ngram=3)


class TestHistoryDraft:
    def test_exact_replay_beats_ngram_misalignment(self):
        # a REPETITIVE cached stream mis-aligns a short n-gram match;
        # the prefix-replay path must return the exact continuation
        p = HistoryDraft()
        prompt, out = [1, 2, 3], [7, 7, 7, 9, 7, 7, 7, 4]
        p.observe(prompt, out)
        hist = prompt + out[:3]  # ...7,7,7 — ambiguous for 3-grams
        assert p.propose(hist, 4) == [9, 7, 7, 7]

    def test_falls_back_to_own_history_then_corpus_ngram(self):
        p = HistoryDraft()
        # no corpus: behaves like prompt-lookup
        assert p.propose([5, 6, 5, 6, 5], 2) == [6, 5]
        # corpus n-gram (not a prefix replay): shared tail pattern
        p.observe([40, 41, 42], [43, 44, 45, 46])
        assert p.propose([9, 41, 42, 43], 3) == [44, 45, 46]

    def test_lru_bound_and_newest_entry_wins(self):
        p = HistoryDraft(max_entries=2)
        p.observe([1], [10, 11])
        p.observe([2], [20, 21])
        p.observe([3], [30, 31])  # evicts prompt [1]
        assert len(p._corpus) == 2
        assert p.propose([1], 2) == []  # evicted
        assert p.propose([3], 2) == [30, 31]
        # re-observing a prompt replaces its completion
        p.observe([3], [33, 34])
        assert p.propose([3], 2) == [33, 34]


class TestMakeProvider:
    def test_env_resolution(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_SERVE_SPEC_DRAFT", raising=False)
        assert isinstance(make_provider(), NGramDraft)
        assert isinstance(make_provider("history"), HistoryDraft)
        assert isinstance(make_provider("none"), _NullDraft)
        assert make_provider("ngram:5").max_ngram == 5
        assert make_provider("history:7").max_entries == 7
        monkeypatch.setenv("SPARKDL_SERVE_SPEC_DRAFT", "history")
        assert isinstance(make_provider(), HistoryDraft)
        with pytest.raises(ValueError, match="SPARKDL_SERVE_SPEC_DRAFT"):
            make_provider("medusa")
        # a malformed tuning suffix fails as loudly as a bad name
        with pytest.raises(ValueError, match="bad SPARKDL_SERVE_SPEC"):
            make_provider("ngram:fve")
        with pytest.raises(ValueError, match="bad SPARKDL_SERVE_SPEC"):
            make_provider("history:0")

    def test_null_provider_proposes_nothing(self):
        assert _NullDraft().propose([1, 2, 3, 1, 2, 3], 4) == []


class TestRegistryPairing:
    def test_draft_for_and_register(self):
        from sparkdl_tpu.models import registry
        assert registry.draft_for("llama3_8b") == "llama_small"
        assert registry.draft_for("llama_small") == "llama_tiny"
        assert registry.draft_for("unknown-family") is None
        registry.register_draft_pair("my_target", "llama_tiny")
        try:
            assert registry.draft_for("my_target") == "llama_tiny"
        finally:
            registry.DRAFT_PAIRS.pop("my_target", None)
        with pytest.raises(ValueError, match="itself"):
            registry.register_draft_pair("x", "x")

    def test_llm_config_names(self):
        from sparkdl_tpu.models import registry
        cfg = registry.llm_config("llama_tiny")
        assert cfg.num_layers == 2
        assert registry.llm_config("llama_small").hidden_size == 2048
        with pytest.raises(ValueError, match="Unknown LLM config"):
            registry.llm_config("gpt5")


# ---------------------------------------------------------------------------
# speculative scheduling over the stub mirror (jax-free)
# ---------------------------------------------------------------------------


def _spec_workload():
    # small vocab -> the stub's arithmetic stream is periodic -> the
    # request's own output is n-gram-predictable after one period
    return [([1, 2, 3], 24), ([4, 5], 24), ([1, 2, 3, 4, 5, 6], 24)]


def _run_stub(spec_k, *, eos_id=None, provider=None, vocab=8, **bkw):
    eng = GenerationEngine(
        StubBackend(4, 64, vocab_size=vocab, **bkw), spec_k=spec_k,
        eos_id=eos_id, draft_provider=provider)
    hs = [eng.submit(p, max_new_tokens=n) for p, n in _spec_workload()]
    eng.run_until_idle()
    return [h.result(1) for h in hs], eng.snapshot()


class TestSpecStubEngine:
    def test_identity_acceptance_and_fewer_steps_both_layouts(self):
        base, s0 = _run_stub(0)
        spec, s4 = _run_stub(4)
        assert spec == base  # bit-identical stream
        assert s4["spec_k"] == 4 and s4["spec_tokens_accepted"] > 0
        assert s4["steps"] < s0["steps"]  # fewer program dispatches
        # paged layout: same stream, same win, through the block tables
        base_p, p0 = _run_stub(0, block_size=4, pool_blocks=80)
        spec_p, p4 = _run_stub(4, block_size=4, pool_blocks=80)
        assert base_p == spec_p == base
        assert p4["spec_tokens_accepted"] > 0
        assert p4["steps"] < p0["steps"]

    def test_k0_is_exactly_the_pr11_path(self):
        class VerifyPoison(StubBackend):
            def verify(self, active_slots, drafts, k):
                raise AssertionError("k=0 must never touch verify")

        eng = GenerationEngine(VerifyPoison(2, 64, vocab_size=8),
                               spec_k=0)
        h = eng.submit([1, 2, 3], max_new_tokens=6)
        eng.run_until_idle()
        assert len(h.result(1)) == 6
        snap = eng.snapshot()
        assert snap["spec_k"] == 0 and snap["spec_verifies"] == 0
        assert eng._draft is None  # nothing speculation-shaped armed

    def test_backend_without_verify_degrades_to_k0(self):
        class OldBackend:
            num_slots, max_len = 2, 64

            def __init__(self):
                self._n = {}

            def prefill(self, slot, prompt, bucket):
                self._n[slot] = 1
                return 7

            def step(self, active):
                out = [0] * self.num_slots
                for s in active:
                    out[s] = (7 + self._n[s]) % 97
                    self._n[s] += 1
                return out

        eng = GenerationEngine(OldBackend(), spec_k=4)
        assert eng.spec_k == 0  # degraded, warned, still serving
        h = eng.submit([1, 2], max_new_tokens=3)
        eng.run_until_idle()
        assert len(h.result(1)) == 3

    def test_sampling_backend_degrades_to_k0(self):
        class Sampling(StubBackend):
            temperature = 0.7

        eng = GenerationEngine(Sampling(2, 64, vocab_size=8), spec_k=4)
        assert eng.spec_k == 0  # greedy-only: acceptance is argmax

    def test_draftless_iterations_fall_through_to_plain_step(self):
        """A null provider must cost NOTHING over k=0: no verify
        dispatch runs (draftless iterations take the plain decode
        step — flash-decode economics preserved), and the output is
        the k=0 stream exactly."""
        class VerifyPoison(StubBackend):
            def verify(self, active_slots, drafts, k):
                raise AssertionError("draftless iteration ran verify")

        base, s0 = _run_stub(0)
        eng = GenerationEngine(VerifyPoison(4, 64, vocab_size=8),
                               spec_k=4, draft_provider=_NullDraft())
        hs = [eng.submit(p, max_new_tokens=n)
              for p, n in _spec_workload()]
        eng.run_until_idle()
        assert [h.result(1) for h in hs] == base
        snap = eng.snapshot()
        assert snap["spec_verifies"] == 0
        assert snap["steps"] == s0["steps"]  # exact k=0 economics

    def test_broken_draft_provider_never_kills_the_loop(self):
        class Broken:
            def propose(self, history, k):
                raise RuntimeError("draft meltdown")

        base, _ = _run_stub(0)
        out, snap = _run_stub(4, provider=Broken())
        assert out == base
        assert snap["completed"] == len(_spec_workload())

    def test_eos_mid_window_matches_k0(self):
        # pick an eos value the deterministic stream emits mid-request
        base, _ = _run_stub(0)
        eos = base[0][3]
        ref, s0 = _run_stub(0, eos_id=eos)
        out, s4 = _run_stub(4, eos_id=eos)
        assert out == ref  # truncated at the same token
        assert all(t.count(eos) <= 1 for t in out)
        assert s4["completed"] == s0["completed"]

    def test_history_provider_observe_learns_completed_traffic(self):
        prov = HistoryDraft()
        eng = GenerationEngine(StubBackend(2, 64, vocab_size=8),
                               spec_k=4, draft_provider=prov)
        h1 = eng.submit([1, 2, 3], max_new_tokens=12)
        eng.run_until_idle()
        assert len(prov._corpus) == 1  # retirement fed the corpus
        snap1 = dict(eng.snapshot())
        h2 = eng.submit([1, 2, 3], max_new_tokens=12)  # retry storm
        eng.run_until_idle()
        assert h2.result(1) == h1.result(1)
        warm_acc = eng.snapshot()["spec_tokens_accepted"] \
            - snap1["spec_tokens_accepted"]
        assert warm_acc >= 8  # the replay predicts nearly everything

    def test_preemption_resume_with_speculation_on(self):
        # the PR 11 total-stall preemption corner with spec enabled:
        # both layouts' streams must stay identical to the k=0 run and
        # every block must come home
        def run(k):
            be = StubBackend(2, 64, vocab_size=8, block_size=4,
                             pool_blocks=6, prefix_cache_bytes=0)
            eng = GenerationEngine(be, prefill_chunk=4, spec_k=k)
            a = eng.submit([1, 2, 3, 4], max_new_tokens=12)
            b = eng.submit([5, 6, 7, 0], max_new_tokens=12)
            eng.run_until_idle()
            return [a.result(1), b.result(1)], eng.snapshot(), be

        ref, snap0, _ = run(0)
        out, snap4, be = run(4)
        assert out == ref
        assert snap4["preemptions"] >= 1  # the corner actually fired
        assert snap4["completed"] == 2 and snap4["quarantined"] == 0
        assert be.allocator.used_count() == 0

    def test_spec_metrics_when_plane_armed_and_zero_when_off(self):
        from sparkdl_tpu.runner import telemetry
        telemetry.reset()
        telemetry.start()
        try:
            eng = GenerationEngine(StubBackend(2, 64, vocab_size=8),
                                   spec_k=4)
            h = eng.submit([1, 2, 3], max_new_tokens=16)
            eng.run_until_idle()
            assert h.result(1)
            snap = telemetry.registry().snapshot()
            assert snap["counters"].get(
                "serving_spec_tokens_accepted", 0) > 0
            assert "serving_spec_tokens_rejected" in snap["counters"]
            hist = snap["histograms"]["serve_spec_accept_len"]
            # k+1 accept-length buckets: 1..k+1 committed per window
            assert hist["bounds"] == [1.0, 2.0, 3.0, 4.0, 5.0]
            assert hist["count"] == eng.snapshot()["spec_verifies"]
        finally:
            telemetry.reset()
        # plane off: zero registration (the PR 8-11 rule)
        telemetry.reset()
        eng = GenerationEngine(StubBackend(2, 64, vocab_size=8),
                               spec_k=4)
        eng.submit([1, 2, 3], max_new_tokens=8)
        eng.run_until_idle()
        assert telemetry.registry().snapshot() == {
            "counters": {}, "gauges": {}, "histograms": {}}

    def test_draft_span_reaches_flight_recorder(self):
        from sparkdl_tpu.runner import events
        rec = events.reset()
        eng = GenerationEngine(StubBackend(2, 64, vocab_size=8),
                               spec_k=4)
        eng.submit([1, 2, 3], max_new_tokens=16)
        eng.run_until_idle()
        names = [e["name"] for e in rec.ring]
        assert "serve_draft" in names

    def test_bottleneck_report_prints_mean_accepted_length(
            self, tmp_path, capsys):
        import importlib.util
        import json
        import os
        snap = {"t": 1.0, "rank": 0, "elapsed_s": 1.0, "stages": {},
                "histograms": {"serve_spec_accept_len": {
                    "bounds": [1.0, 2.0, 3.0], "buckets": [4, 6, 10],
                    "count": 10, "sum": 21.0}}}
        (tmp_path / "metrics_rank0.json").write_text(json.dumps(snap))
        spec = importlib.util.spec_from_file_location(
            "bottleneck_report",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "scripts",
                "bottleneck_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main([str(tmp_path / "no-events"), "--metrics-dir",
                       str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "mean accepted length 2.10 tokens/verify" in out


# ---------------------------------------------------------------------------
# speculative engine on CPU over the tiny model (lean: shapes shared
# with the test_serving / test_paging CPU classes, so the only NEW
# compiles are the verify programs)
# ---------------------------------------------------------------------------


class TestSpecOnCpu:
    def _model(self):
        import jax

        from sparkdl_tpu.models import llama as L
        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        return cfg, model, variables

    def _refs(self, model, variables, prompts, new, max_len=64):
        from sparkdl_tpu.models import llama as L
        ids, lens = L.left_pad_prompts(prompts)
        out = np.asarray(L.generate(model, variables, np.asarray(ids),
                                    new, pad_lens=np.asarray(lens),
                                    pad_to=max_len))
        return [out[i][int(lens[i]) + len(p):].tolist()
                for i, p in enumerate(prompts)]

    def test_spec_identity_chunked_prefill_and_prefix_reuse(self):
        """Unpaged: 1/2/3-chunk prompts decode speculatively (k=3,
        n-gram self-drafting) bit-identical to static generate();
        shared-head prompts ride a prefix-cache hit and stay
        identical; ONE verify signature for the engine's lifetime and
        zero decode re-traces."""
        from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE

        cfg, model, variables = self._model()
        rng = np.random.RandomState(5)
        max_len, new = 64, 6
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (5, 17)]
        head = rng.randint(0, cfg.vocab_size, 12).tolist()
        pa = head + rng.randint(0, cfg.vocab_size, 4).tolist()
        pb = head + rng.randint(0, cfg.vocab_size, 7).tolist()
        refs = self._refs(model, variables, prompts + [pa, pb], new)

        eng = GenerationEngine.from_model(model, variables, num_slots=2,
                                          max_len=max_len,
                                          prefill_chunk=8, spec_k=3)
        assert eng.spec_k == 3
        hs = [eng.submit(p, max_new_tokens=new) for p in prompts]
        eng.run_until_idle()
        for p, h, want in zip(prompts, hs, refs):
            assert h.result(1) == want, len(p)
        sig_v = GLOBAL_COMPILE_CACHE.signatures("serve_verify_step")
        sig_d = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")
        assert sig_v >= 1

        ha = eng.submit(pa, max_new_tokens=new)
        eng.run_until_idle()  # commits pa's head to the prefix cache
        hb = eng.submit(pb, max_new_tokens=new)
        eng.run_until_idle()
        assert ha.result(1) == refs[2] and hb.result(1) == refs[3]
        assert eng.snapshot()["prefix_cache"]["hits"] >= 1
        snap = eng.snapshot()
        assert snap["spec_verifies"] > 0
        # acceptance/rejection never re-trace verify or decode
        assert GLOBAL_COMPILE_CACHE.signatures(
            "serve_verify_step") == sig_v
        assert GLOBAL_COMPILE_CACHE.signatures(
            "serve_decode_step") == sig_d

    def test_paged_spec_preemption_resume_fast_twin(self):
        """Lean twin of the slow static-anchored test below (the
        tier-1 headroom rule — ISSUE 15 added the interpret-mode
        kernel suite, this buys the seconds back): same contract — a
        mid-decode preemption-resume plus a radix graft under
        speculation must not change the streams — but the reference is
        the SAME engine config run without the preemption (whose
        static-generate() identity the other fast spec/paging tests
        pin), so the twin skips the two extra generate() programs, and
        a 1-layer model halves the compile cost. The slow test keeps
        the static anchor on the full tiny model."""
        import dataclasses

        import jax

        from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
        from sparkdl_tpu.models import llama as L

        cfg = dataclasses.replace(L.LlamaConfig.tiny(), num_layers=1)
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(7)
        new = 12
        head = rng.randint(0, cfg.vocab_size, 16).tolist()  # 2 blocks
        pa = head + rng.randint(0, cfg.vocab_size, 3).tolist()
        pb = head + rng.randint(0, cfg.vocab_size, 6).tolist()

        def make_engine(prov):
            return GenerationEngine.from_model(
                model, variables, num_slots=2, max_len=64,
                prefill_chunk=8, block_size=8, prefill_budget=16,
                spec_k=3, draft_provider=prov)

        ref_eng = make_engine(HistoryDraft())  # clean drained streams
        refs = []
        for p in (pa, pb):
            h = ref_eng.submit(p, max_new_tokens=new)
            ref_eng.run_until_idle()
            refs.append(h.result(1))

        prov = HistoryDraft()
        prov.observe(pa, refs[0])
        prov.observe(pb, refs[1])
        eng = make_engine(prov)
        ha = eng.submit(pa, max_new_tokens=new)
        eng.step()  # 2 of pa's 3 chunks (budget 16)
        eng.step()  # final chunk + first token (+ a verify window)
        sig_v = GLOBAL_COMPILE_CACHE.signatures("serve_verify_step")
        eng.step()
        assert eng.snapshot()["spec_verifies"] >= 1
        assert ha.state == "running" and 0 < len(ha.tokens) < new
        eng._preempt_newest([(ha.slot, ha)])
        hb = eng.submit(pb, max_new_tokens=new)  # grafts pa's head
        eng.run_until_idle()
        assert ha.result(1) == refs[0]
        assert hb.result(1) == refs[1]
        snap = eng.snapshot()
        assert snap["preemptions"] == 1
        assert GLOBAL_COMPILE_CACHE.signatures(
            "serve_verify_step") == sig_v

    @pytest.mark.slow
    def test_paged_spec_identity_graft_and_preemption_resume(self):
        """Paged: speculative decode through the block tables with a
        radix graft AND a mid-decode preemption-resume — the resumed
        stream and the grafted stream must both stay bit-identical to
        static generate(), with zero verify re-traces through
        allocation, graft, preempt and resume. (Slow: the fast twin
        above pins the same contract engine-vs-engine; this keeps the
        static anchor.)"""
        from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE

        cfg, model, variables = self._model()
        rng = np.random.RandomState(7)
        # 12 output tokens: with near-full acceptance a verify window
        # commits ~4/iteration, so the request is still RUNNING at the
        # preemption point below
        max_len, new = 64, 12
        head = rng.randint(0, cfg.vocab_size, 16).tolist()  # 2 blocks
        pa = head + rng.randint(0, cfg.vocab_size, 3).tolist()
        pb = head + rng.randint(0, cfg.vocab_size, 6).tolist()
        refs = self._refs(model, variables, [pa, pb], new)

        # warm retrieval corpus (the retry-storm steady state): every
        # decode iteration drafts deterministically — including the
        # resumed request, whose history is a prefix of its cached
        # completion — so the paged verify path is exercised on every
        # step, with high acceptance driving multi-token commits
        # through the block tables.
        prov = HistoryDraft()
        prov.observe(pa, refs[0])
        prov.observe(pb, refs[1])
        eng = GenerationEngine.from_model(
            model, variables, num_slots=2, max_len=max_len,
            prefill_chunk=8, block_size=8, prefill_budget=16, spec_k=3,
            draft_provider=prov)
        assert eng.paged and eng.spec_k == 3
        ha = eng.submit(pa, max_new_tokens=new)
        eng.step()  # 2 of pa's 3 chunks (budget 16)
        eng.step()  # final chunk + first token (+ a verify window)
        assert ha.state == "running"
        sig_v = GLOBAL_COMPILE_CACHE.signatures("serve_verify_step")
        eng.step()  # >= 1 speculative verify ran
        assert eng.snapshot()["spec_verifies"] >= 1
        # preempt pa mid-decode (still RUNNING — the production caller
        # only ever preempts running slots): resume must re-prefill
        # prompt+tokens and keep decoding speculatively, bit-identically
        assert ha.state == "running" and 0 < len(ha.tokens) < new
        eng._preempt_newest([(ha.slot, ha)])
        hb = eng.submit(pb, max_new_tokens=new)  # grafts pa's... head
        eng.run_until_idle()
        assert ha.result(1) == refs[0]
        assert hb.result(1) == refs[1]
        snap = eng.snapshot()
        assert snap["preemptions"] == 1
        assert snap["spec_verifies"] >= 2
        assert GLOBAL_COMPILE_CACHE.signatures(
            "serve_verify_step") == sig_v  # one paged verify program
        assert eng.backend.allocator.used_count() == \
            len(eng.backend.mgr.radix or [])

    def test_blocking_path_spec_identity_with_left_pad(self):
        """Blocking (left-padded) layout + speculation: the verify
        window's rope positions and attention mask are pad-RELATIVE
        (prompts of 5 and 7 tokens in the 8-bucket carry pads 3 and
        1), and the stream must still equal static generate()."""
        cfg, model, variables = self._model()
        rng = np.random.RandomState(3)
        # repetitive prompts: prompt-lookup drafts from iteration one,
        # so the left-pad verify math actually runs (draftless
        # iterations fall through to the plain step)
        pieces = [rng.randint(0, cfg.vocab_size, 3).tolist()
                  for _ in range(2)]
        prompts = [(pieces[0] * 2)[:5], (pieces[1] * 3)[:7]]
        refs = self._refs(model, variables, prompts, 6)
        eng = GenerationEngine.from_model(
            model, variables, num_slots=2, max_len=64, min_bucket=8,
            stall_free=False, spec_k=3)
        hs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        for h, want in zip(hs, refs):
            assert h.result(1) == want
        assert eng.snapshot()["spec_verifies"] > 0

    def test_draft_model_provider_registry_pairing(self):
        """The registry-paired draft model drafts k tokens through the
        static generate() path (mechanics + pairing; acceptance
        quality needs trained weights, which the zero-egress container
        does not have)."""
        from sparkdl_tpu.serving.draft import DraftModelProvider

        with pytest.raises(ValueError, match="no draft pairing"):
            DraftModelProvider.from_registry("not-a-family")
        prov = DraftModelProvider.from_registry("llama_small",
                                                min_bucket=8)
        assert prov.model.cfg.num_layers == 2  # llama_tiny, per pairing
        d = prov.propose([1, 2, 3, 4, 5], 3)
        assert len(d) == 3
        assert all(0 <= t < prov.model.cfg.vocab_size for t in d)
        # deterministic (greedy draft)
        assert prov.propose([1, 2, 3, 4, 5], 3) == d
        # history outside the draft vocab: stand down, never crash
        assert prov.propose([10 ** 6], 3) == []
