"""Params system contract tests (SURVEY.md §5.6: must match Spark ML semantics)."""

import pytest

from sparkdl_tpu.core.params import (HasBatchSize, HasInputCol, HasOutputCol,
                                     Param, Params, TypeConverters,
                                     keyword_only)


class Stage(HasInputCol, HasOutputCol, HasBatchSize):
    threshold = Param(Params, "threshold", "a float knob", TypeConverters.toFloat)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, batchSize=None,
                 threshold=None):
        super().__init__()
        self._setDefault(batchSize=32, threshold=0.5)
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, inputCol=None, outputCol=None, batchSize=None,
                  threshold=None):
        return self._set(**self._input_kwargs)


def test_defaults_and_set():
    s = Stage(inputCol="image")
    assert s.getInputCol() == "image"
    assert s.getBatchSize() == 32
    assert s.getOrDefault("threshold") == 0.5
    s.setParams(threshold=0.9, outputCol="features")
    assert s.getOrDefault(s.threshold) == 0.9
    assert s.getOutputCol() == "features"
    assert s.isSet(s.threshold) and not s.isSet(s.batchSize)
    assert s.isDefined(s.batchSize) and s.hasDefault("batchSize")


def test_type_converters_validate_eagerly():
    s = Stage()
    s.set("threshold", 1)  # int → float coercion
    assert isinstance(s.getOrDefault("threshold"), float)
    with pytest.raises(TypeError):
        s.set("threshold", "hot")
    with pytest.raises(TypeError):
        s.set("batchSize", 3.5)
    with pytest.raises(TypeError):
        TypeConverters.toShape([4, -1])
    assert TypeConverters.toShape([4, 224, 224, 3]) == (4, 224, 224, 3)
    with pytest.raises(TypeError):
        TypeConverters.toInt(True)


def test_keyword_only_rejects_positional():
    with pytest.raises(TypeError):
        Stage("image")


def test_copy_preserves_uid_and_isolates_maps():
    s = Stage(inputCol="a", threshold=0.7)
    c = s.copy({s.threshold: 0.1})
    assert c.uid == s.uid
    assert c.getOrDefault("threshold") == 0.1
    assert s.getOrDefault("threshold") == 0.7
    c.set("inputCol", "b")
    assert s.getInputCol() == "a"


def test_params_listing_and_explain():
    s = Stage(inputCol="x")
    names = [p.name for p in s.params]
    assert names == sorted(names)
    assert {"inputCol", "outputCol", "batchSize", "threshold"} <= set(names)
    text = s.explainParams()
    assert "threshold" in text and "default: 0.5" in text
    assert "current: x" in s.explainParam("inputCol")


def test_extract_param_map_with_extra():
    s = Stage(inputCol="a")
    m = s.extractParamMap({s.threshold: 0.3})
    assert m[s.threshold] == 0.3
    assert m[s.inputCol] == "a"
    assert m[s.batchSize] == 32


def test_foreign_param_rejected():
    s1, s2 = Stage(), Stage()
    with pytest.raises(ValueError):
        s1.set(s2.threshold, 0.2)


def test_param_uids_unique():
    assert Stage().uid != Stage().uid
