"""Worker program for the 2-process XlaRunner test (launched by
runner.launcher — NOT collected by pytest).

Each process: rendezvous via the launcher's SPARKDL_* env, train a linear
classifier for 3 steps with its OWN local data shard (HorovodRunner
semantics), then assert the result matches a single-device reference
computed over the full global batch — proving the cross-process gradient
allreduce actually averaged over both shards. Also exercises the hvd-compat
module collectives (real cross-process allreduce/broadcast).

Usage: mp_worker.py <out_dir>
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402


def main():
    out_dir = sys.argv[1]
    import numpy as np
    import optax

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from sparkdl_tpu.runner import (TrainState, XlaRunner,
                                    softmax_cross_entropy_loss)
    from sparkdl_tpu.runner import api as hvd

    runner = XlaRunner(np=2)  # env rendezvous: 2 procs x 1 local CPU device
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()

    # Global problem, identical on both ranks (seeded); each rank feeds
    # only its own half of every batch.
    rng = np.random.RandomState(0)
    dim, classes, gbs = 4, 3, 8
    params = {"w": rng.randn(dim, classes).astype(np.float32),
              "b": np.zeros((classes,), np.float32)}
    batches = []
    for _ in range(3):
        x = rng.randn(gbs, dim).astype(np.float32)
        y = rng.randint(0, classes, size=(gbs,))
        batches.append({"image": x, "label": y})

    def apply_fn(p, x):
        return x @ p["w"] + p["b"]

    def reference():
        import jax.numpy as jnp
        p = {k: jnp.asarray(v) for k, v in params.items()}
        for b in batches:
            def loss(q):
                logits = apply_fn(q, jnp.asarray(b["image"]))
                onehot = jax.nn.one_hot(b["label"], classes)
                return optax.softmax_cross_entropy(logits, onehot).mean()
            g = jax.grad(loss)(p)
            p = jax.tree_util.tree_map(lambda a, d: a - 0.1 * d, p, g)
        return p

    def train(ctx):
        assert ctx.size == 2 and ctx.num_processes == 2
        state = TrainState.create(apply_fn, params, optax.sgd(0.1))
        state = ctx.put_replicated(state)
        step = ctx.make_train_step(softmax_cross_entropy_loss())
        half = gbs // 2
        for b in batches:
            local = {k: v[rank * half:(rank + 1) * half] for k, v in b.items()}
            state, metrics = step(state, ctx.shard_batch(local))
        jax.block_until_ready(state.params)
        return state

    state = runner.run(train)
    want = reference()
    for k in ("w", "b"):
        got = np.asarray(jax.device_get(
            state.params[k].addressable_data(0)))
        np.testing.assert_allclose(got, np.asarray(want[k]),
                                   rtol=2e-5, atol=2e-6)

    # hvd-compat module API: real cross-process collectives.
    ctx = runner.make_context()
    from sparkdl_tpu.runner import xla_runner as xr
    xr._CURRENT_CONTEXT.append(ctx)
    s = hvd.allreduce(np.float32(rank + 1), average=False)
    assert float(s) == 3.0, float(s)  # 1 + 2
    m = hvd.allreduce(np.float32(rank + 1), average=True)
    assert float(m) == 1.5, float(m)
    b = hvd.broadcast(np.float32(rank * 10 + 7), root_rank=1)
    assert float(b) == 17.0, float(b)
    xr._CURRENT_CONTEXT.pop()

    with open(os.path.join(out_dir, f"rank{rank}.ok"), "w") as f:
        f.write("ok")
    print(f"rank {rank} ok", flush=True)


if __name__ == "__main__":
    main()
