"""Paged flash-decode kernel (ISSUE 15): block-table attention without
the gather.

Reference = ``ops.flash_decode`` over the block-GATHERED dense view at
``block_k = block_size``: both kernels then execute the identical
online-softmax block walk — the paged kernel merely addresses each
block through the table instead of through a materialized copy — so
equivalence is asserted BITWISE (interpret mode, the same kernel the
chip compiles). Covered: ragged per-slot fills, slots parked entirely
on trash block 0, tables whose live blocks are non-contiguous pool
ids, the S = k+1 verify window, the engagement resolver + env knob,
the forced-fallback warning, the no-gather jaxpr pin, and the
kernel-on engine's token identity to static ``generate()`` with zero
decode/verify re-traces.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu.ops import paged_flash_decode as pfd
from sparkdl_tpu.ops.flash_decode import flash_decode


def _pool_and_tables(seed=0, *, b=4, h_kv=2, bs=8, mb=4, pool=13, d=16):
    """A deliberately adversarial layout: non-contiguous live pool ids,
    one slot parked entirely on trash block 0, mixed fill levels."""
    rng = np.random.RandomState(seed)
    k_pool = jnp.asarray(rng.randn(pool, h_kv, bs, d), jnp.float32)
    v_pool = jnp.asarray(rng.randn(pool, h_kv, bs, d), jnp.float32)
    tables = np.zeros((b, mb), np.int32)
    tables[0] = [7, 3, 11, 0]    # non-contiguous, trailing unallocated
    tables[1] = [2, 9, 0, 0]
    tables[2] = [5, 1, 10, 4]    # fully allocated
    tables[3] = 0                # parked on the trash block (idle slot)
    cur = jnp.asarray([17, 9, 31, 0], jnp.int32)
    pads = jnp.asarray([0, 3, 5, 0], jnp.int32)
    return k_pool, v_pool, jnp.asarray(tables), cur, pads


def _gather(pool, tables):
    """The dense per-slot view the pre-kernel primitives materialized
    (models.llama._gather_view, one leaf)."""
    v = pool[tables]                       # [B, MB, Hkv, bs, d]
    v = jnp.transpose(v, (0, 2, 1, 3, 4))
    return v.reshape(v.shape[0], v.shape[1], -1, v.shape[4])


@pytest.mark.parametrize("rep", [1, 2, 4])
def test_decode_step_bitwise_equals_flash_on_gather_view(rep):
    k_pool, v_pool, tables, cur, pads = _pool_and_tables(rep)
    b, h_kv, bs, d = 4, 2, 8, 16
    q = jnp.asarray(np.random.RandomState(rep + 50).randn(
        b, h_kv * rep, 1, d), jnp.float32)
    got = pfd.paged_flash_decode(q, k_pool, v_pool, tables, cur, pads,
                                 interpret=True)
    want = flash_decode(q, _gather(k_pool, tables),
                        _gather(v_pool, tables), cur + 1, pads,
                        block_k=bs, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # the trash-parked slot's output is finite garbage, never NaN (the
    # engine discards it, but a NaN would poison the o_proj matmul)
    assert np.isfinite(np.asarray(got[3])).all()


def test_verify_window_bitwise_equals_per_query_flash():
    """S = k+1 (the speculative verify window): query i of slot r must
    attend [pads[r], cur[r]+i] — bitwise the dense-flash run of each
    query column at its own fill level."""
    k_pool, v_pool, tables, cur, pads = _pool_and_tables(9)
    b, h_kv, rep, bs, d, s_q = 4, 2, 2, 8, 16, 4
    q = jnp.asarray(np.random.RandomState(77).randn(
        b, h_kv * rep, s_q, d), jnp.float32)
    got = pfd.paged_flash_decode(q, k_pool, v_pool, tables, cur, pads,
                                 interpret=True)
    kg, vg = _gather(k_pool, tables), _gather(v_pool, tables)
    want = jnp.concatenate(
        [flash_decode(q[:, :, i:i + 1], kg, vg, cur + i + 1, pads,
                      block_k=bs, interpret=True) for i in range(s_q)],
        axis=2)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # the per-query causal offset is real: reversing the window's
    # queries changes the answer (each query sees a different prefix)
    flipped = pfd.paged_flash_decode(q[:, :, ::-1], k_pool, v_pool,
                                     tables, cur, pads, interpret=True)
    assert not np.allclose(np.asarray(flipped[2, :, -1]),
                           np.asarray(got[2, :, -1]), atol=1e-3)


def test_one_signature_serves_every_table_and_fill(monkeypatch):
    """Tables / fill indices / pads are traced operands: block
    allocation, grafts and refills must reuse ONE compiled program
    (the no-re-trace contract the slot primitives pin)."""
    k_pool, v_pool, tables, cur, pads = _pool_and_tables(3)
    q = jnp.asarray(np.random.RandomState(5).randn(4, 4, 1, 16),
                    jnp.float32)
    traces = []

    @jax.jit
    def step(tables, cur, pads):
        traces.append(1)
        return pfd.paged_flash_decode(q, k_pool, v_pool, tables, cur,
                                      pads, interpret=True)

    kg, vg = None, None
    for roll in range(3):
        t = jnp.roll(tables, roll, axis=0)
        c = jnp.roll(cur, roll)
        p = jnp.roll(pads, roll)
        got = step(t, c, p)
        want = flash_decode(q, _gather(k_pool, t), _gather(v_pool, t),
                            c + 1, p, block_k=8, interpret=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert len(traces) == 1


def test_supports_contract_and_shape_validation():
    assert pfd.supports(8) and pfd.supports(16) and pfd.supports(32)
    assert not pfd.supports(4)    # sublane misalignment
    assert not pfd.supports(12)   # not an 8-multiple
    k_pool, v_pool, tables, cur, pads = _pool_and_tables(1)
    q = jnp.zeros((4, 4, 1, 16), jnp.float32)
    with pytest.raises(ValueError, match="block_size"):
        pfd.paged_flash_decode(q, k_pool[:, :, :4], v_pool[:, :, :4],
                               tables, cur, pads, interpret=True)
    with pytest.raises(ValueError, match="tables"):
        pfd.paged_flash_decode(q, k_pool, v_pool, tables[:2], cur, pads,
                               interpret=True)
    with pytest.raises(ValueError, match="multiple"):
        pfd.paged_flash_decode(jnp.zeros((4, 3, 1, 16)), k_pool, v_pool,
                               tables, cur, pads, interpret=True)


class TestResolverAndKnob:
    def test_auto_mode_mirrors_dense_flash_resolution(self, monkeypatch):
        from sparkdl_tpu.ops.flash_attention import flash_attention
        monkeypatch.delenv(pfd.PAGED_KERNEL_ENV, raising=False)
        assert pfd.paged_decode_fn_for(flash_attention) is \
            pfd.paged_flash_decode
        assert pfd.paged_decode_fn_for(None) is None
        # the global flash-decode ablation lever gates auto mode too
        monkeypatch.setenv("SPARKDL_FLASH_DECODE", "0")
        assert pfd.paged_decode_fn_for(flash_attention) is None

    def test_force_and_off(self, monkeypatch):
        monkeypatch.setenv(pfd.PAGED_KERNEL_ENV, "1")
        assert pfd.paged_decode_fn_for(None) is pfd.paged_flash_decode
        monkeypatch.setenv(pfd.PAGED_KERNEL_ENV, "0")
        from sparkdl_tpu.ops.flash_attention import flash_attention
        assert pfd.paged_decode_fn_for(flash_attention) is None
        assert pfd.kernel_mode() == "off"

    def test_mesh_routes_through_shard_map_gate(self, monkeypatch):
        from sparkdl_tpu.serving.backend import tp_mesh
        mesh = tp_mesh(2)
        # auto on CPU: the sharded dispatch is off (TPU-only default)
        monkeypatch.delenv(pfd.PAGED_KERNEL_ENV, raising=False)
        monkeypatch.setenv("SPARKDL_SERVE_TP_KERNEL", "0")
        assert pfd.paged_decode_fn_for(None, mesh) is None
        # the tp ablation beats force: a leftover forced paged knob
        # must not contaminate the dense-attention tp baseline leg
        # (explicit =0 is the documented override — no warning)
        monkeypatch.setenv(pfd.PAGED_KERNEL_ENV, "1")
        monkeypatch.setattr(pfd, "_warned_fallback", set())
        assert pfd.paged_decode_fn_for(None, mesh) is None
        assert not pfd._warned_fallback
        # but force + tp with the dispatch merely DEFAULTED off (auto
        # on CPU) must warn — a forced knob never densifies silently
        monkeypatch.delenv("SPARKDL_SERVE_TP_KERNEL")
        assert pfd.paged_decode_fn_for(None, mesh) is None
        assert any("sharded tp dispatch" in r for r in pfd._warned_fallback)
        monkeypatch.delenv(pfd.PAGED_KERNEL_ENV)
        # forced on: a head-sharded wrapper around the kernel
        monkeypatch.setenv("SPARKDL_SERVE_TP_KERNEL", "1")
        fn = pfd.paged_decode_fn_for(None, mesh)
        assert fn is not None and fn.__wrapped__ is pfd.paged_flash_decode

    def test_dense_decode_fn_for_mesh_gating(self, monkeypatch):
        from sparkdl_tpu.ops import flash_decode as fd
        from sparkdl_tpu.serving.backend import tp_mesh
        mesh = tp_mesh(2)
        monkeypatch.setenv(fd.TP_KERNEL_ENV, "0")
        assert fd.decode_fn_for(None, mesh) is None
        monkeypatch.setenv(fd.TP_KERNEL_ENV, "1")
        fn = fd.decode_fn_for(None, mesh)
        assert fn is not None and fn.__wrapped__ is fd.flash_decode
        # the global ablation lever still wins under a mesh
        monkeypatch.setenv("SPARKDL_FLASH_DECODE", "0")
        assert fd.decode_fn_for(None, mesh) is None


def test_head_sharded_kernel_matches_unsharded():
    """shard_map over the tp head axis must be a pure layout change:
    per-head attention needs no collective, so the sharded dispatch is
    bitwise the single-device kernel."""
    from sparkdl_tpu.parallel.sharding import head_sharded_kernel
    from sparkdl_tpu.serving.backend import tp_mesh
    k_pool, v_pool, tables, cur, pads = _pool_and_tables(13)
    q = jnp.asarray(np.random.RandomState(29).randn(4, 4, 1, 16),
                    jnp.float32)
    want = pfd.paged_flash_decode(q, k_pool, v_pool, tables, cur, pads,
                                  interpret=True)
    sharded = head_sharded_kernel(pfd.paged_flash_decode, tp_mesh(2))
    got = jax.jit(lambda *a: sharded(*a, interpret=True))(
        q, k_pool, v_pool, tables, cur, pads)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_forced_fallback_warns_once(monkeypatch, caplog):
    """SPARKDL_SERVE_PAGED_KERNEL=1 with an unsupported block size must
    stand down to the gather view with ONE warning — silently changing
    the HBM profile the knob pinned is the hazard (ISSUE 15
    satellite)."""
    import logging

    from sparkdl_tpu.models import llama as L
    monkeypatch.setenv(pfd.PAGED_KERNEL_ENV, "1")
    monkeypatch.setattr(pfd, "_warned_fallback", set())
    cfg = L.LlamaConfig.tiny()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    pool = L.init_paged_pool(model, 7, 4)  # block_size 4: unsupported
    tables = jnp.zeros((2, 3), jnp.int32)
    zeros = jnp.zeros((2,), jnp.int32)
    with caplog.at_level(logging.WARNING,
                         logger="sparkdl_tpu.ops.paged_flash_decode"):
        tok, pool = L.paged_slot_decode_step(
            model, variables["params"], pool, tables, zeros, zeros,
            zeros, jax.random.PRNGKey(0))
        warns = [r for r in caplog.records
                 if "paged flash-decode" in r.getMessage()]
        # once per reason host-side, not once per layer per trace
        assert len(warns) == 1
        assert "block_size 4" in warns[0].getMessage()
        # a second step (same signature, no re-trace; and even a fresh
        # trace of the same reason) stays silent
        tok, pool = L.paged_slot_decode_step(
            model, variables["params"], pool, tables, zeros, zeros,
            zeros, jax.random.PRNGKey(1))
        assert len([r for r in caplog.records
                    if "paged flash-decode" in r.getMessage()]) == 1


def test_kernel_engagement_drops_the_gather(monkeypatch):
    """The acceptance jaxpr pin: with the kernel engaged the lowered
    decode step holds NO materialized [S, Hkv, max_blocks·bs, hd]
    view; with it off, the per-layer gather view is exactly there.
    (Distinct slot counts per leg — the jit cache keys on traced
    shapes, not the env knob, so same-signature relowers would reuse
    the first trace.)"""
    from sparkdl_tpu.models import llama as L
    cfg = L.LlamaConfig.tiny()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    mb, bs = 3, 8
    pool = L.init_paged_pool(model, 9, bs)
    key = jax.random.PRNGKey(0)
    for env_val, slots, expect_gather in (("1", 3, False), ("0", 5, True)):
        monkeypatch.setenv(pfd.PAGED_KERNEL_ENV, env_val)
        tables = jnp.zeros((slots, mb), jnp.int32)
        zeros = jnp.zeros((slots,), jnp.int32)
        view = (f"tensor<{slots}x{cfg.num_kv_heads}x{mb * bs}x"
                f"{cfg.head_dim}xf32>")
        txt = L.paged_slot_decode_step.lower(
            model, variables["params"], pool, tables, zeros, zeros,
            zeros, key).as_text()
        assert (view in txt) == expect_gather, (env_val, view)
        # the verify window composes with the same dispatch
        toks = jnp.zeros((slots, 3), jnp.int32)
        txt = L.paged_slot_verify_step.lower(
            model, variables["params"], pool, tables, toks, zeros,
            zeros).as_text()
        assert (view in txt) == expect_gather, (env_val, "verify")


class TestKernelOnEngine:
    def test_token_identity_and_zero_retraces(self, monkeypatch):
        """The kernel-engaged paged engine (forced — CPU runs the same
        kernel interpreted) through chunked prefill × radix grafts ×
        speculation: greedy streams bit-identical to static
        ``generate()``, zero decode/verify re-traces after warmup.
        Odd slot count / max_len keep the signatures private to this
        test — the process-global jit cache would otherwise hand the
        engine a program traced with the kernel off."""
        from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
        from sparkdl_tpu.models import llama as L
        from sparkdl_tpu.serving import GenerationEngine
        from sparkdl_tpu.serving.draft import HistoryDraft

        monkeypatch.setenv(pfd.PAGED_KERNEL_ENV, "1")
        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(23)
        max_len, new = 40, 6
        head = rng.randint(0, cfg.vocab_size, 16).tolist()  # 2 blocks
        prompts = [head + rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (3, 7)]
        ids, lens = L.left_pad_prompts(prompts)
        out = np.asarray(L.generate(model, variables, np.asarray(ids),
                                    new, pad_lens=np.asarray(lens),
                                    pad_to=max_len))
        refs = [out[i][int(lens[i]) + len(p):].tolist()
                for i, p in enumerate(prompts)]

        prov = HistoryDraft()
        for p, r in zip(prompts, refs):
            prov.observe(p, r)  # high-acceptance verify windows
        eng = GenerationEngine.from_model(
            model, variables, num_slots=3, max_len=max_len,
            block_size=8, prefill_chunk=8, spec_k=3,
            draft_provider=prov)
        hs = [eng.submit(p, max_new_tokens=new) for p in prompts]
        eng.run_until_idle()
        assert [h.result(1) for h in hs] == refs
        assert eng.snapshot()["spec_verifies"] >= 1
        sig_d = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")
        sig_v = GLOBAL_COMPILE_CACHE.signatures("serve_verify_step")
        # second wave: grafts the shared head, refills other slots —
        # and must not re-trace the kernel-engaged programs
        hs = [eng.submit(p, max_new_tokens=new) for p in prompts]
        eng.run_until_idle()
        assert [h.result(1) for h in hs] == refs
        assert GLOBAL_COMPILE_CACHE.signatures(
            "serve_decode_step") == sig_d
        assert GLOBAL_COMPILE_CACHE.signatures(
            "serve_verify_step") == sig_v
