"""Host-ingest layer unit tests (ISSUE 7): zero-copy NHWC column views,
the fused feed policy, pooled staging buffers, and the shared
chunk-decode protocol — all jax-free (`core/ingest.py` must stay
importable and benchmarkable without a backend).

The scorer-level integration (process decode backend, quarantine
equivalence, chaos across the pool boundary) lives in test_streaming.py;
this file pins the building blocks.
"""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.core import ingest
from sparkdl_tpu.image import imageIO


def image_column(n=6, h=4, w=5, seed=0):
    rng = np.random.default_rng(seed)
    imgs = [rng.integers(0, 256, (h, w, 3), np.uint8) for _ in range(n)]
    structs = [imageIO.imageArrayToStruct(im, origin=f"m{i}")
               for i, im in enumerate(imgs)]
    return pa.array(structs, type=imageIO.imageSchema), imgs


# ---------------------------------------------------------------------------
# imageColumnNHWCView — the zero-copy fast path
# ---------------------------------------------------------------------------

def test_nhwc_view_matches_packed_and_is_zero_copy():
    col, _ = image_column()
    view = imageIO.imageColumnNHWCView(col)
    assert view is not None and view.dtype == np.uint8
    # at-rest layout is BGR: the packed BGR batch is the ground truth
    packed = imageIO.imageColumnToNHWC(col, 4, 5, dtype=np.uint8,
                                       channelOrder="BGR")
    np.testing.assert_array_equal(view, packed)
    # genuinely a view: read-only, aliasing the Arrow values buffer
    assert not view.flags.writeable
    assert view.base is not None


def test_nhwc_view_respects_slices():
    col, _ = image_column(n=8)
    full = imageIO.imageColumnNHWCView(col)
    part = imageIO.imageColumnNHWCView(col.slice(3, 4))
    np.testing.assert_array_equal(part, full[3:7])


def test_nhwc_view_declines_nonuniform_columns():
    rng = np.random.default_rng(1)
    structs = [imageIO.imageArrayToStruct(
        rng.integers(0, 256, (h, 4, 3), np.uint8)) for h in (4, 4, 6)]
    col = pa.array(structs, type=imageIO.imageSchema)
    assert imageIO.imageColumnNHWCView(col) is None      # mixed heights
    col2, _ = image_column(n=3)
    with_null = pa.concat_arrays(
        [col2, pa.array([None], type=imageIO.imageSchema)])
    assert imageIO.imageColumnNHWCView(with_null) is None  # null row


# ---------------------------------------------------------------------------
# imageColumnFeed — the fused feed policy
# ---------------------------------------------------------------------------

def test_feed_fused_ships_native_u8_view_when_upscaling():
    col, _ = image_column(h=4, w=5)
    out = imageIO.imageColumnFeed(col, 8, 8, fused=True)
    assert out.dtype == np.uint8 and out.shape == (6, 4, 5, 3)
    np.testing.assert_array_equal(out, imageIO.imageColumnNHWCView(col))


def test_feed_fused_packs_when_stored_exceeds_target():
    # downsampling on device would INFLATE wire bytes — pack at target,
    # still BGR (the device prologue owns the flip in fused mode)
    col, _ = image_column(h=8, w=8)
    out = imageIO.imageColumnFeed(col, 4, 4, dtype=np.float32, fused=True)
    assert out.dtype == np.float32 and out.shape == (6, 4, 4, 3)
    np.testing.assert_array_equal(
        out, imageIO.imageColumnToNHWC(col, 4, 4, dtype=np.float32,
                                       channelOrder="BGR"))


def test_feed_legacy_path_packs_on_host():
    col, _ = image_column(h=4, w=5)
    out = imageIO.imageColumnFeed(col, 8, 8, dtype=np.float32,
                                  channelOrder="RGB", fused=False)
    np.testing.assert_array_equal(
        out, imageIO.imageColumnToNHWC(col, 8, 8, dtype=np.float32,
                                       channelOrder="RGB"))


def test_fused_preprocess_env_gate(monkeypatch):
    assert ingest.fused_preprocess_default() is True
    monkeypatch.setenv("SPARKDL_FUSED_PREPROCESS", "0")
    assert ingest.fused_preprocess_default() is False


# ---------------------------------------------------------------------------
# StagingPool + stage_batch — reused pad/put host buffers
# ---------------------------------------------------------------------------

def test_stage_batch_full_batch_passes_through():
    pool = ingest.StagingPool()
    arr = np.arange(12, dtype=np.float32).reshape(4, 3)
    staged, n, lease, copied = ingest.stage_batch(arr, 4, pool)
    assert staged is arr and n == 4 and lease is None and copied == 0
    assert pool.stats() == {"allocs": 0, "reuses": 0}


def test_stage_batch_pads_and_reuses_buffers():
    pool = ingest.StagingPool()
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    staged, n, lease, copied = ingest.stage_batch(a, 4, pool)
    assert n == 2 and copied == staged.nbytes
    np.testing.assert_array_equal(staged[:2], a)
    np.testing.assert_array_equal(staged[2:], np.broadcast_to(a[:1], (2, 3)))
    pool.release(lease)
    # same (shape, dtype) → the SAME buffer comes back, no new alloc
    staged2, _, lease2, _ = ingest.stage_batch(
        np.ones((3, 3), np.float32), 4, pool)
    assert staged2 is staged
    assert pool.stats() == {"allocs": 1, "reuses": 1}
    pool.release(lease2)


def test_stage_batch_dict_batches_and_oversize():
    pool = ingest.StagingPool()
    batch = {"a": np.zeros((2, 3), np.float32),
             "b": np.ones((2, 2), np.int32)}
    staged, n, lease, copied = ingest.stage_batch(batch, 4, pool)
    assert n == 2 and len(lease) == 2
    assert copied == sum(v.nbytes for v in staged.values())
    assert staged["a"].shape == (4, 3) and staged["b"].shape == (4, 2)
    pool.release(lease)
    with pytest.raises(ValueError, match="exceeds"):
        ingest.stage_batch(np.zeros((5, 3), np.float32), 4, pool)


def test_stage_buffers_env_gate(monkeypatch):
    assert ingest.stage_buffers_default() is True
    monkeypatch.setenv("SPARKDL_STAGE_BUFFERS", "0")
    assert ingest.stage_buffers_default() is False


# ---------------------------------------------------------------------------
# decode_chunk — the ONE copy of chunk-then-row-fallback semantics
# ---------------------------------------------------------------------------

def _flaky_decoder(bad):
    def decode(start, length):
        rows = range(start, start + length)
        if any(r in bad for r in rows):
            raise ValueError(f"bad row in {list(rows)}")
        return np.full((length, 2), float(start), np.float32)
    return decode


def test_decode_chunk_clean_and_raise_modes():
    arr, info = ingest.decode_chunk(_flaky_decoder(set()), 0, 4, True)
    assert arr.shape == (4, 2) and info == {"length": 4, "dead": []}
    with pytest.raises(ValueError):
        ingest.decode_chunk(_flaky_decoder({1}), 0, 4, False)


def test_decode_chunk_row_fallback_dead_letters():
    arr, info = ingest.decode_chunk(_flaky_decoder({1, 3}), 0, 4, True)
    assert arr.shape == (2, 2)
    assert [d[0] for d in info["dead"]] == [1, 3]
    assert all(d[1] == "ValueError" for d in info["dead"])


def test_decode_backend_env_resolution(monkeypatch):
    monkeypatch.delenv("SPARKDL_DECODE_BACKEND", raising=False)
    assert ingest.decode_backend_default() == "thread"
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", "process")
    assert ingest.decode_backend_default() == "process"
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", "bogus")
    assert ingest.decode_backend_default() == "thread"


def test_pool_not_rebuilt_while_held():
    """A concurrent stream's mismatched worker request must ride the
    HELD pool, never tear it down (cancelling the holder's in-flight
    futures outside the quarantine protocol); the rebuild happens at the
    next unheld request."""
    ingest.shutdown_decode_executor()
    try:
        a = ingest.acquire_decode_executor(1)
        assert ingest.get_decode_executor(2) is a      # held: no rebuild
        assert ingest.acquire_decode_executor(2) is a  # even acquiring
        ingest.release_decode_executor()
        ingest.release_decode_executor()
        b = ingest.get_decode_executor(2)              # unheld: rebuilt
        assert b is not a
    finally:
        ingest.shutdown_decode_executor()


def test_broken_pool_is_replaced():
    """A BrokenProcessPool executor is poisoned permanently — caching it
    would fail every later process-backend stream until the interpreter
    restarts. A broken pool must be replaced on the next request, even
    while nominally held."""
    ingest.shutdown_decode_executor()
    try:
        a = ingest.get_decode_executor(1)
        a._broken = "child died"
        b = ingest.get_decode_executor(1)  # same key, but broken → new
        assert b is not a
        c = ingest.acquire_decode_executor(1)
        assert c is b
        c._broken = "child died"
        d = ingest.acquire_decode_executor(1)  # held AND broken → new
        assert d is not c
        ingest.release_decode_executor()
        ingest.release_decode_executor()
    finally:
        ingest.shutdown_decode_executor()


def test_stalled_pool_is_evicted_even_while_held():
    """A stall means a wedged-but-ALIVE child: it never sets _broken, so
    without explicit eviction the pool would keep its lost worker slot
    until interpreter restart and every retry would re-stall. After
    invalidate_decode_executor the next request — even from the same
    holder — gets a fresh pool; invalidating a pool no longer in the
    slot is a no-op."""
    ingest.shutdown_decode_executor()
    try:
        a = ingest.acquire_decode_executor(1)
        ingest.invalidate_decode_executor(a)
        b = ingest.acquire_decode_executor(1)
        assert b is not a
        ingest.invalidate_decode_executor(a)  # stale handle: no-op
        assert ingest.get_decode_executor(1) is b
        ingest.release_decode_executor()
        ingest.release_decode_executor()
    finally:
        ingest.shutdown_decode_executor()


def test_decode_stall_resolution_precedence(monkeypatch):
    """SPARKDL_DISPATCH_TIMEOUT_S takes precedence whenever SET —
    including an explicit 0, that knob's documented off value, which
    must actually disable the decode watchdog instead of falling
    through a falsy-or to the 600s default."""
    monkeypatch.delenv("SPARKDL_DISPATCH_TIMEOUT_S", raising=False)
    monkeypatch.delenv("SPARKDL_DECODE_TIMEOUT_S", raising=False)
    assert ingest.decode_stall_resolved() == 600.0
    monkeypatch.setenv("SPARKDL_DECODE_TIMEOUT_S", "120")
    assert ingest.decode_stall_resolved() == 120.0
    monkeypatch.setenv("SPARKDL_DISPATCH_TIMEOUT_S", "30")
    assert ingest.decode_stall_resolved() == 30.0
    monkeypatch.setenv("SPARKDL_DISPATCH_TIMEOUT_S", "0")
    assert ingest.decode_stall_resolved() == 0.0
    monkeypatch.setenv("SPARKDL_DISPATCH_TIMEOUT_S", "bogus")
    assert ingest.decode_stall_resolved() == 120.0


def test_windowed_apply_stall_watchdog():
    """stall_s arms a decode-future watchdog: a worker that never
    completes (the fork-deadlock hazard) raises a classified
    ScoringStallError instead of hanging the stream forever."""
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from sparkdl_tpu.runner.failures import ScoringStallError
    release = threading.Event()
    ex = ThreadPoolExecutor(max_workers=1)
    try:
        g = ingest.windowed_apply(lambda x: release.wait(30), [1], 1, 1,
                                  executor=ex, stall_s=0.2,
                                  stall_stage="decode")
        with pytest.raises(ScoringStallError, match="decode"):
            next(g)
    finally:
        release.set()
        ex.shutdown(wait=False)
