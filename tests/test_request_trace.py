"""Request-scoped tracing, live engine inspector, and SLO burn-rate
monitoring (ISSUE 13): trace assembly (live tee + offline fold), the
phases-sum-to-latency invariant, the serve_* attribution drift guard,
the /serving endpoint, the SLO monitor's multi-window burn math and
breach flip, the request_report / bottleneck_report CLIs (in-process,
per the tier-1 lean rule), the check_metric_docs lint, serve_bench's
new record fields — and the off-plane overhead pins (zero registration,
no tee, no per-token event growth; the PR 6 rule).

Fast and jax-free throughout: everything rides StubBackend and
synthetic records.
"""

import json
import os
import re
import sys
import time
import urllib.request

import pytest

from sparkdl_tpu.runner import analysis, events, slo, telemetry
from sparkdl_tpu.serving import (ENGINE_SCOPED_EVENTS,
                                 REQUEST_SCOPED_EVENTS, GenerationEngine,
                                 StubBackend, introspect)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_plane(monkeypatch):
    """Fresh plane/recorder/SLO monitor per test; SLO env never leaks."""
    for v in ("SPARKDL_SLO_TTFT_S", "SPARKDL_SLO_LATENCY_S",
              "SPARKDL_SLO_ERROR_RATE", "SPARKDL_SLO_TARGET",
              "SPARKDL_SLO_WINDOWS_S", "SPARKDL_SLO_BURN_THRESHOLD",
              "SPARKDL_TRACE_RING", "SPARKDL_TRACE_SLOWEST"):
        monkeypatch.delenv(v, raising=False)
    telemetry.reset()
    slo.reset()
    events.reset()
    yield
    telemetry.reset()
    slo.reset()
    events.reset()


def _drain(eng, handles, timeout=30):
    eng.run_until_idle()
    for h in handles:
        assert h.wait(timeout)


# ---------------------------------------------------------------------------
# Trace assembly
# ---------------------------------------------------------------------------

class TestTraceCollector:
    def test_engine_run_assembles_traces_summing_to_latency(self):
        """The acceptance invariant: every completed request has a trace
        whose phases sum to its measured latency within 5%
        (unattributed_s bounded)."""
        telemetry.start()
        eng = GenerationEngine(StubBackend(4, 128, step_s=0.001),
                               prefill_chunk=8)
        hs = [eng.submit([1 + i, 2, 3], max_new_tokens=12)
              for i in range(10)]
        _drain(eng, hs)
        traces = telemetry.request_traces().traces()
        assert len(traces) == 10
        for t in traces:
            assert t["finish"] == "length"
            assert t["tokens_out"] == 12
            assert t["latency_s"] > 0
            assert abs(t["unattributed_s"]) <= 0.05 * t["latency_s"]
            total = (t["queue_s"] + t["prefill_s"] + t["prefill_wait_s"]
                     + t["decode_s"] + t["unattributed_s"])
            assert total == pytest.approx(t["latency_s"], abs=1e-4)
            assert t["ttft_s"] is not None
            assert t["dominant_phase"] in t["phases"]

    def test_slowest_and_ring_bounds(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_TRACE_RING", "8")
        monkeypatch.setenv("SPARKDL_TRACE_SLOWEST", "3")
        telemetry.start()
        eng = GenerationEngine(StubBackend(2, 64, step_s=0.0002),
                               prefill_chunk=8)
        hs = [eng.submit([1 + i, 2], max_new_tokens=4)
              for i in range(20)]
        _drain(eng, hs)
        col = telemetry.request_traces()
        assert len(col.traces()) == 8          # ring bound
        slowest = col.slowest()
        assert len(slowest) == 3               # slowest-N bound
        lats = [t["latency_s"] for t in slowest]
        assert lats == sorted(lats, reverse=True)
        summ = col.summary()
        assert summ["completed"] == 20
        assert summ["in_ring"] == 8
        assert len(summ["slowest"]) == 3

    def test_quarantined_request_finalizes_as_error(self):
        class FailingPrefill(StubBackend):
            def prefill_chunk(self, *a, **kw):
                raise RuntimeError("poisoned prompt")

        telemetry.start()
        eng = GenerationEngine(FailingPrefill(2, 64), retries=1,
                               prefill_chunk=8)
        h = eng.submit([1, 2, 3], max_new_tokens=4)
        eng.run_until_idle()
        assert h.state == "failed"
        traces = telemetry.request_traces().traces()
        assert len(traces) == 1
        assert traces[0]["finish"] == "error"
        assert traces[0]["retries"] >= 1

    def test_spec_and_preemption_fields(self):
        """Paged + speculative run: traces carry the spec ledger (mean
        accept length) and preemption/block-stall evidence when the
        pool is tight."""
        telemetry.start()
        eng = GenerationEngine(
            StubBackend(4, 128, vocab_size=8, block_size=8,
                        pool_blocks=12), prefill_chunk=8, spec_k=2)
        hs = [eng.submit([1, 2, 3], max_new_tokens=20)
              for _ in range(6)]
        _drain(eng, hs)
        traces = telemetry.request_traces().traces()
        assert len(traces) == 6
        spec = [t for t in traces if t["spec_windows"] > 0]
        assert spec, "speculation ran but no trace carries its ledger"
        for t in spec:
            assert 1.0 <= t["spec_mean_accept_len"] <= 3.0
        assert eng.stats["preemptions"] == sum(
            t["preemptions"] for t in traces)

    def test_offline_assembly_matches_live(self, tmp_path, monkeypatch):
        """request_report's offline fold and the live tee are the same
        implementation: traces assembled from the streamed JSONL equal
        the live collector's."""
        monkeypatch.setenv("SPARKDL_EVENT_DIR", str(tmp_path))
        events.reset()
        telemetry.start()
        eng = GenerationEngine(StubBackend(2, 64, step_s=0.0005),
                               prefill_chunk=8)
        hs = [eng.submit([1 + i, 2], max_new_tokens=6)
              for i in range(5)]
        _drain(eng, hs)
        live = {t["request"]: t
                for t in telemetry.request_traces().traces()}
        telemetry.stop()
        events.reset()  # close the stream
        recs = analysis.load_event_dir(str(tmp_path))
        offline = {t["request"]: t for t in
                   telemetry.assemble_request_traces(recs).traces()}
        assert live.keys() == offline.keys()
        for rid, t in live.items():
            assert offline[rid] == t


# ---------------------------------------------------------------------------
# Drift guard: serve_* attribution (satellite 4)
# ---------------------------------------------------------------------------

class TestAttributionDriftGuard:
    def test_every_emitted_serve_event_is_classified_and_attributed(
            self):
        """Drive every scheduler path (chunked, blocking, paged +
        preemption, speculation, retry + quarantine, reject) with a tee
        capturing records: every serve_* name must be classified in
        exactly one scope set, and every REQUEST-scoped record must
        carry request= — the trace collector silently degrades without
        it."""
        seen: list = []
        events.add_tee(
            lambda rec: seen.append(dict(rec))
            if str(rec.get("name", "")).startswith("serve_") else None)
        try:
            # chunked + spec
            eng = GenerationEngine(StubBackend(2, 64, vocab_size=8),
                                   prefill_chunk=8, spec_k=2)
            hs = [eng.submit([1, 2, 3], max_new_tokens=8)
                  for _ in range(3)]
            _drain(eng, hs)
            # blocking
            engb = GenerationEngine(StubBackend(2, 64),
                                    stall_free=False)
            hb = engb.submit([1, 2, 3], max_new_tokens=4)
            _drain(engb, [hb])
            # paged, pool tight enough to preempt and admission-wait
            engp = GenerationEngine(
                StubBackend(4, 128, block_size=8, pool_blocks=10),
                prefill_chunk=8)
            hp = [engp.submit([1, 2, 3], max_new_tokens=24)
                  for _ in range(6)]
            _drain(engp, hp)
            assert engp.stats["preemptions"] > 0 \
                or engp.stats["block_stall_events"] > 0

            # prefill failure: retry then quarantine
            class Flaky(StubBackend):
                def prefill_chunk(self, *a, **kw):
                    raise RuntimeError("boom")

            engf = GenerationEngine(Flaky(1, 64), retries=1,
                                    prefill_chunk=8)
            hf = engf.submit([1, 2], max_new_tokens=2)
            engf.run_until_idle()
            assert hf.state == "failed"

            # blocking-path prefill failure (serve_prefill_retry)
            class FlakyBlocking(StubBackend):
                def prefill(self, *a, **kw):
                    raise RuntimeError("boom")

            engfb = GenerationEngine(FlakyBlocking(1, 64), retries=1,
                                     stall_free=False)
            hfb = engfb.submit([1, 2], max_new_tokens=2)
            engfb.run_until_idle()
            assert hfb.state == "failed"

            # decode-step failure: step retry + suspect eviction
            class FlakyStep(StubBackend):
                def step(self, active):
                    raise RuntimeError("step boom")

            engs = GenerationEngine(FlakyStep(1, 64), retries=1,
                                    prefill_chunk=8)
            hs2 = engs.submit([1, 2], max_new_tokens=4)
            engs.run_until_idle()
            assert hs2.state == "failed"
            # rejection (pre-admission — engine-scoped by design)
            with pytest.raises(Exception):
                eng.submit([], max_new_tokens=2)
        finally:
            events._TEES.clear()
        names = {r["name"] for r in seen}
        unclassified = names - REQUEST_SCOPED_EVENTS \
            - ENGINE_SCOPED_EVENTS
        assert not unclassified, (
            f"new serve_* emissions must be classified request- or "
            f"engine-scoped: {sorted(unclassified)}")
        for r in seen:
            if r["name"] in REQUEST_SCOPED_EVENTS:
                assert "request" in r, \
                    f"{r['name']} dropped request= attribution: {r}"
        # the paths above must actually exercise the interesting names
        assert {"serve_queue", "serve_prefill", "serve_decode",
                "serve_request_quarantined",
                "serve_prefill_chunk_retry", "serve_prefill_retry",
                "serve_step_retry", "serve_reject"} <= names

    def test_engine_source_emissions_all_classified(self):
        """Static completeness: every serve_* literal passed to
        events.event/span/completed_span in engine.py appears in one of
        the scope sets — adding an emission without classifying it
        fails here even if no runtime path above reaches it."""
        src = open(os.path.join(
            _REPO, "sparkdl_tpu", "serving", "engine.py")).read()
        emitted = set(re.findall(
            r"events\.(?:event|span|completed_span)\(\s*\n?\s*"
            r"['\"](serve_[a-z_]+)['\"]", src))
        assert emitted, "expected serve_* emissions in engine.py"
        unclassified = emitted - REQUEST_SCOPED_EVENTS \
            - ENGINE_SCOPED_EVENTS
        assert not unclassified, sorted(unclassified)


# ---------------------------------------------------------------------------
# Off-plane overhead pins (satellite 4)
# ---------------------------------------------------------------------------

class TestOffPlaneOverhead:
    def test_zero_registration_and_no_tee_when_plane_off(self):
        """Plane off: no tee (collector included), zero metric
        registration from a full engine run (slo gauges included), no
        traces collected."""
        assert events._TEES == []
        eng = GenerationEngine(StubBackend(2, 64, vocab_size=8),
                               prefill_chunk=8, spec_k=2)
        hs = [eng.submit([1, 2, 3], max_new_tokens=8)
              for _ in range(3)]
        _drain(eng, hs)
        assert events._TEES == []
        assert telemetry.registry().snapshot() == {
            "counters": {}, "gauges": {}, "histograms": {}}
        assert telemetry.request_traces().traces() == []
        assert telemetry.request_traces().summary() is None
        # and the snapshot carries neither a traces nor an slo block
        snap = telemetry.snapshot()
        assert "request_traces" not in snap
        assert "slo" not in snap

    def test_no_per_token_event_cost(self):
        """The per-request emission count is independent of output
        length: tracing attribution rides the three lifecycle spans,
        never per-token events."""
        def count_serve_records(max_new):
            rec = events.reset()
            eng = GenerationEngine(StubBackend(1, 256),
                                   prefill_chunk=8)
            h = eng.submit([1, 2, 3], max_new_tokens=max_new)
            _drain(eng, [h])
            return sum(1 for r in rec.tail()
                       if str(r.get("name", "")).startswith("serve_"))

        assert count_serve_records(4) == count_serve_records(64)

    def test_slo_monitor_off_without_env(self):
        assert slo.monitor() is None
        assert slo.evaluate({"t": time.time()}) is None


# ---------------------------------------------------------------------------
# Live engine inspector (/serving)
# ---------------------------------------------------------------------------

class TestIntrospect:
    def test_debug_state_paged_engine(self):
        eng = GenerationEngine(
            StubBackend(3, 64, block_size=8, pool_blocks=30),
            prefill_chunk=8)
        h = eng.submit([1, 2, 3, 4, 5], max_new_tokens=4)
        st = eng.debug_state()
        assert st["num_slots"] == 3
        assert st["queue"]["depth"] == 1
        assert st["queue"]["head"]["request"] == h.id
        assert st["queue"]["head"]["age_s"] >= 0
        assert [s["slot"] for s in st["slots"]] == [0, 1, 2]
        assert all(s["state"] == "idle" for s in st["slots"])
        assert all("kv_blocks" in s for s in st["slots"])
        assert "blocks_free" in st["kv_pool"]
        eng.run_until_idle()
        st = eng.debug_state()
        assert st["slots_busy"] == 0
        assert st["stats"]["completed"] == 1
        assert st["fatal"] is None

    def test_debug_state_mid_run_slot_map(self):
        eng = GenerationEngine(StubBackend(2, 64), prefill_chunk=8)
        eng.submit([1, 2, 3], max_new_tokens=4)
        eng.submit([4, 5, 6], max_new_tokens=4)
        eng._admit()
        st = eng.debug_state()
        busy = [s for s in st["slots"] if s["state"] != "idle"]
        assert len(busy) == 2
        for s in busy:
            assert s["state"] == "prefilling"
            assert s["chunks_total"] == 1
            assert s["tokens_out"] == 0
        eng.run_until_idle()

    def test_serving_endpoint_live(self):
        """/serving on the telemetry HTTP server returns every live
        engine's state as JSON."""
        telemetry.start(port=0)
        port = telemetry.server_port()
        assert port is not None
        eng = GenerationEngine(StubBackend(2, 64), prefill_chunk=8)
        eng.submit([1, 2, 3], max_new_tokens=4)
        eng._admit()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/serving", timeout=10) as resp:
            body = json.loads(resp.read().decode())
        ours = [e for e in body["engines"]
                if e.get("backend") == "StubBackend"
                and e.get("slots_busy", 0) > 0]
        assert ours, body
        assert ours[0]["slots"][0]["state"] == "prefilling"
        eng.run_until_idle()


# ---------------------------------------------------------------------------
# SLO monitor
# ---------------------------------------------------------------------------

def _hist(bounds, buckets, count=None, s=0.0):
    return {"bounds": list(bounds), "buckets": list(buckets),
            "count": count if count is not None else buckets[-1],
            "sum": s}


class TestSloMonitor:
    def test_fraction_below(self):
        h = _hist((0.1, 1.0, 10.0), [50, 90, 100])
        assert telemetry.histogram_fraction_below(h, 0.1) == 0.5
        # interpolated inside (0.1, 1.0]: 50 + 40*(0.55-0.1)/0.9 = 70
        assert telemetry.histogram_fraction_below(h, 0.55) == \
            pytest.approx(0.7, abs=1e-6)
        assert telemetry.histogram_fraction_below(h, 10.0) == 1.0
        assert telemetry.histogram_fraction_below(h, 100.0) == 1.0
        assert telemetry.histogram_fraction_below({}, 1.0) is None
        # +Inf-bucket observations count as above any finite threshold
        h2 = _hist((0.1,), [5], count=10)
        assert telemetry.histogram_fraction_below(h2, 0.5) == 0.5

    def test_burn_rate_windows_and_breach_flip(self, monkeypatch):
        """Synthetic history: compliant traffic, then a burst of
        violations — burn must exceed the threshold in every window and
        the breach event fire exactly once per transition."""
        monkeypatch.setenv("SPARKDL_SLO_TTFT_S", "1.0")
        mon = slo.SloMonitor(slo.objectives_from_env(),
                             windows_s=(10.0, 60.0))
        rec = events.reset()

        def snap_at(t, good, bad):
            return {"t": t, "histograms": {"serving_ttft_s": _hist(
                (1.0, 5.0), [good, good + bad])}}

        b0 = mon.evaluate(snap_at(1000.0, 100, 0))
        ob = b0["objectives"]["ttft"]
        assert ob["compliance"] == 1.0 and not ob["breaching"]
        # 30s later: 100 new requests, 10 violations — burn 10x in both
        # the 10s and 60s windows (window diffs vs history)
        b1 = mon.evaluate(snap_at(1030.0, 190, 10))
        ob = b1["objectives"]["ttft"]
        assert ob["breaching"] is True
        assert ob["burn_rate"] == pytest.approx(10.0, rel=0.01)
        names = [e["name"] for e in rec.tail()]
        assert names.count("slo_breach") == 1
        # recovery: clean traffic, short window clean -> not breaching
        b2 = mon.evaluate(snap_at(1045.0, 290, 10))
        assert b2["objectives"]["ttft"]["breaching"] is False
        names = [e["name"] for e in rec.tail()]
        assert names.count("slo_recovered") == 1

    def test_error_rate_objective(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_SLO_ERROR_RATE", "0.1")
        mon = slo.SloMonitor(slo.objectives_from_env(),
                             windows_s=(10.0,))
        c0 = {"t": 0.0, "counters": {
            "serving_requests_completed_total": 90.0,
            "serving_requests_quarantined_total": 0.0}}
        mon.evaluate(c0)
        c1 = {"t": 20.0, "counters": {
            "serving_requests_completed_total": 140.0,
            "serving_requests_quarantined_total": 50.0}}
        ob = mon.evaluate(c1)["objectives"]["errors"]
        # window: 50 completed + 50 errors -> error rate 0.5, burn 5x
        assert ob["breaching"] is True
        assert ob["burn_rate"] == pytest.approx(5.0, rel=0.01)

    def test_plane_snapshot_carries_slo_block_and_gauges(
            self, monkeypatch):
        monkeypatch.setenv("SPARKDL_SLO_TTFT_S", "0.001")
        monkeypatch.setenv("SPARKDL_SLO_WINDOWS_S", "5,30")
        slo.reset()
        telemetry.start()
        eng = GenerationEngine(StubBackend(2, 64, step_s=0.002),
                               prefill_chunk=8)
        hs = [eng.submit([1 + i, 2], max_new_tokens=4)
              for i in range(4)]
        _drain(eng, hs)
        snap = telemetry.snapshot()  # every TTFT > 1ms: burning
        ob = snap["slo"]["objectives"]["ttft"]
        assert ob["breaching"] is True
        telemetry.snapshot()  # gauges land for the NEXT read
        gauges = telemetry.registry().snapshot()["gauges"]
        assert gauges["slo_ttft_burn_rate"]["value"] > 1.0
        assert gauges["slo_ttft_compliance"]["value"] < 0.99

    def test_armed_objective_without_traffic_registers_no_gauges(
            self, monkeypatch):
        """An armed objective that has seen NO traffic must export
        nothing — a default-0.0 compliance gauge would read as a total
        SLO failure when the truth is 'no data'."""
        monkeypatch.setenv("SPARKDL_SLO_TTFT_S", "1.0")
        slo.reset()
        telemetry.start()
        telemetry.snapshot()
        telemetry.snapshot()
        assert telemetry.registry().snapshot()["gauges"] == {}

    def test_compliance_from_traces(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_SLO_TTFT_S", "0.5")
        monkeypatch.setenv("SPARKDL_SLO_LATENCY_S", "2.0")
        monkeypatch.setenv("SPARKDL_SLO_ERROR_RATE", "0.3")
        traces = [
            {"ttft_s": 0.1, "latency_s": 1.0, "finish": "length"},
            {"ttft_s": 0.9, "latency_s": 3.0, "finish": "length"},
            {"ttft_s": None, "latency_s": 0.2, "finish": "error"},
        ]
        out = slo.compliance_from_traces(traces)
        assert out["ttft"]["compliance"] == 0.5
        # latency population mirrors the live histogram: COMPLETED
        # requests only (the engine observes serving_request_latency_s
        # at _retire) — the 0.2s error trace is excluded, so 1 of the
        # 2 completed traces is under the 2.0s threshold
        assert out["latency"]["compliance"] == 0.5
        assert out["errors"]["compliance"] == pytest.approx(2 / 3)
        assert out["errors"]["met"] is False
        # a partial trace (fabricated attributed-sum latency) is
        # excluded from the latency population too
        traces.append({"ttft_s": None, "latency_s": 0.01,
                       "partial": True, "finish": "length"})
        out2 = slo.compliance_from_traces(traces)
        assert out2["latency"]["compliance"] == 0.5
        assert out2["latency"]["total"] == 2


# ---------------------------------------------------------------------------
# CLIs (in-process — tier-1 lean rule) + lint + bench fields
# ---------------------------------------------------------------------------

def _run_serving_workload(event_dir, monkeypatch):
    monkeypatch.setenv("SPARKDL_EVENT_DIR", str(event_dir))
    events.reset()
    eng = GenerationEngine(StubBackend(2, 64, step_s=0.001,
                                       prefill_s=0.004),
                           prefill_chunk=8)
    hs = [eng.submit([1 + i, 2, 3], max_new_tokens=8)
          for i in range(8)]
    _drain(eng, hs)
    events.reset()  # close the stream
    monkeypatch.delenv("SPARKDL_EVENT_DIR")


class TestReportClis:
    def test_request_report_cli(self, tmp_path, monkeypatch, capsys):
        _run_serving_workload(tmp_path, monkeypatch)
        monkeypatch.setenv("SPARKDL_SLO_TTFT_S", "5.0")
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "request_report",
            os.path.join(_REPO, "scripts", "request_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main([str(tmp_path), "--top", "3"]) == 0
        out = capsys.readouterr().out
        assert "8 completed" in out
        assert "dominant cause" in out
        assert "SLO compliance" in out and "ttft" in out
        # JSON mode round-trips
        assert mod.main([str(tmp_path), "--json"]) == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["completed"] == 8
        assert rec["tail_dominant_phase"] in rec["tail_phase_frac"]
        assert rec["max_unattributed_frac"] <= 0.05
        assert rec["slo"]["ttft"]["met"] is True
        # empty dir -> exit 2
        empty = tmp_path / "empty"
        empty.mkdir()
        assert mod.main([str(empty)]) == 2

    def test_bottleneck_report_appends_request_block(
            self, tmp_path, monkeypatch, capsys):
        """Satellite: with serve_* spans in the event dir the existing
        stage report gains the SLO-compliance block and the
        phase-attributed slowest-requests table."""
        _run_serving_workload(tmp_path, monkeypatch)
        monkeypatch.setenv("SPARKDL_SLO_LATENCY_S", "10.0")
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bottleneck_report",
            os.path.join(_REPO, "scripts", "bottleneck_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "dominant stage" in out      # the PR 6 stage report
        assert "request traces:" in out     # the ISSUE 13 block
        assert "SLO compliance" in out
        assert "latency" in out
        assert mod.main([str(tmp_path), "--json"]) == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["requests"]["completed"] == 8
        assert rec["report"] is not None

    def test_check_metric_docs_lint(self, tmp_path):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_metric_docs",
            os.path.join(_REPO, "scripts", "check_metric_docs.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        # the repo itself must be clean
        assert mod.missing_metrics() == []
        # synthetic drift is caught
        pkg = tmp_path / "sparkdl_tpu"
        pkg.mkdir()
        (pkg / "x.py").write_text(
            'reg.counter("totally_new_metric_total").inc()\n'
            '_metric("gauge", "another_new_gauge", 1)\n')
        (tmp_path / "README.md").write_text("nothing documented\n")
        missing = mod.missing_metrics(root=str(tmp_path),
                                      readme=str(tmp_path / "README.md"))
        assert missing == ["another_new_gauge",
                           "totally_new_metric_total"]

    def test_serve_bench_leg_records_slo_and_slowest_trace(self):
        """Satellite: run_engine_leg's record carries the SLO
        compliance numbers, the slowest-trace phase breakdown, and the
        attribution residual — the fields _serve_headline forwards into
        BOTH the healthy and backend_unavailable bench records."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "serve_bench",
            os.path.join(_REPO, "scripts", "serve_bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        workload = [([1 + i, 2, 3], 6) for i in range(12)]
        leg = mod.run_engine_leg(
            lambda: GenerationEngine(StubBackend(2, 64,
                                                 step_s=0.0005),
                                     prefill_chunk=8),
            workload, concurrency=4)
        assert leg["completed"] == 12
        assert leg["slo"]["ttft_compliance"] >= 0.99
        assert leg["slo"]["latency_compliance"] >= 0.99
        assert leg["trace_attribution"]["within_5pct"] is True
        st = leg["slowest_trace"]
        assert st["dominant_phase"] in (
            "queue", "prefill", "prefill_wait", "block_stall", "draft",
            "decode", "unattributed")
        # ... and the headline forwards them
        sys.path.insert(0, _REPO)
        import bench
        head = bench._serve_headline({"engine": {"4": leg}})
        assert head["serve_slo_ttft_compliance"] == \
            leg["slo"]["ttft_compliance"]
        assert head["serve_slowest_trace"] == st
        assert head["serve_trace_max_unattributed_frac"] == \
            leg["trace_attribution"]["max_unattributed_frac"]

    def test_serve_bench_survivability_leg_and_gating(self):
        """ISSUE 19 satellite: the survivability leg reports one
        injected failover's recovery latency + the exactly-once
        token-identity float, _serve_headline forwards both (riding
        healthy AND backend_unavailable records), and bench_trend's
        name-shape rules gate them in the right direction."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "serve_bench",
            os.path.join(_REPO, "scripts", "serve_bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        surv = mod.run_survivability_comparison(n_requests=8,
                                                concurrency=4)
        assert surv["failovers"] == 1
        assert surv["token_identical"] == 1.0  # float, NOT bool
        assert not isinstance(surv["token_identical"], bool)
        assert surv["recovery_s"] is not None and surv["recovery_s"] > 0
        assert surv["clean"]["completed"] == 8
        assert surv["faulted"]["completed"] == 8
        sys.path.insert(0, _REPO)
        import bench
        head = bench._serve_headline({"survivability": surv})
        assert head["serve_recovery_s"] == surv["recovery_s"]
        assert head["serve_failover_token_identical"] == 1.0
        bt_spec = importlib.util.spec_from_file_location(
            "bench_trend",
            os.path.join(_REPO, "scripts", "bench_trend.py"))
        bt = importlib.util.module_from_spec(bt_spec)
        bt_spec.loader.exec_module(bt)
        assert bt._LOWER_IS_BETTER.search("serve_recovery_s")
        assert not bt._LOWER_IS_BETTER.search(
            "serve_failover_token_identical")
        # a slower recovery OR a broken identity must trip the gate
        recs = [{"n": i, "parsed": {"metric": "m", "value": 1.0,
                                    "extra": e}}
                for i, e in ((1, {"serve_recovery_s": 0.05,
                                  "serve_failover_token_identical": 1.0}),
                             (2, {"serve_recovery_s": 0.12,
                                  "serve_failover_token_identical": 0.0}))]
        rep = bt.trend(recs)
        assert {"serve_recovery_s", "serve_failover_token_identical"} \
            <= set(rep["regressions"])

    def test_serve_bench_fleet_leg_and_gating(self):
        """ISSUE 20 satellite: the fleet leg reports the radix-vs-
        round-robin routing comparison plus one unclean replica kill's
        recovery latency and the cross-replica exactly-once float;
        _serve_headline forwards them (riding healthy AND
        backend_unavailable records) and bench_trend's name-shape rules
        gate fleet_recovery_s lower-is-better and fleet_token_identical
        higher-is-better."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "serve_bench",
            os.path.join(_REPO, "scripts", "serve_bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        flt = mod.run_fleet_comparison(n_requests=12, step_s=0.001)
        assert flt["token_identical"] == 1.0  # float, NOT bool
        assert not isinstance(flt["token_identical"], bool)
        assert flt["recovery_s"] is not None and flt["recovery_s"] > 0
        assert flt["readmissions"] >= 1
        for leg in (flt["radix"], flt["round_robin"]):
            assert leg["completed"] == flt["requests"]
            assert leg["reused_tokens"] >= 0
        sys.path.insert(0, _REPO)
        import bench
        head = bench._serve_headline({"fleet": flt})
        assert head["fleet_recovery_s"] == flt["recovery_s"]
        assert head["fleet_token_identical"] == 1.0
        assert head["fleet_prefix_reuse_ratio"] == flt["reuse_ratio"]
        bt_spec = importlib.util.spec_from_file_location(
            "bench_trend",
            os.path.join(_REPO, "scripts", "bench_trend.py"))
        bt = importlib.util.module_from_spec(bt_spec)
        bt_spec.loader.exec_module(bt)
        assert bt._LOWER_IS_BETTER.search("fleet_recovery_s")
        assert not bt._LOWER_IS_BETTER.search("fleet_token_identical")
        # slower fleet recovery OR a broken identity trips the gate
        recs = [{"n": i, "parsed": {"metric": "m", "value": 1.0,
                                    "extra": e}}
                for i, e in ((1, {"fleet_recovery_s": 0.05,
                                  "fleet_token_identical": 1.0}),
                             (2, {"fleet_recovery_s": 0.12,
                                  "fleet_token_identical": 0.0}))]
        rep = bt.trend(recs)
        assert {"fleet_recovery_s", "fleet_token_identical"} \
            <= set(rep["regressions"])

    def test_gang_aggregation_merges_trace_blocks(self, tmp_path):
        """aggregate_snapshots re-ranks the per-rank slowest lists into
        one gang tail."""
        for rank, lat in ((0, 1.0), (1, 9.0)):
            snap = {"t": 1.0, "rank": rank, "elapsed_s": 1.0,
                    "stages": {}, "request_traces": {
                        "completed": 2, "open": 0,
                        "slowest": [{"request": rank * 10,
                                     "latency_s": lat}]}}
            (tmp_path / f"metrics_rank{rank}.json").write_text(
                json.dumps(snap))
        agg = telemetry.aggregate_snapshots(str(tmp_path))
        tb = agg["request_traces"]
        assert tb["completed"] == 4
        assert tb["slowest"][0]["request"] == 10  # rank 1's 9.0s leads

    def test_gang_aggregation_honors_slowest_knob(self, tmp_path,
                                                  monkeypatch):
        """The gang re-rank trims to SPARKDL_TRACE_SLOWEST — the same
        bound each rank's export honors, not the compile-time
        default."""
        monkeypatch.setenv("SPARKDL_TRACE_SLOWEST", "2")
        for rank in (0, 1):
            snap = {"t": 1.0, "rank": rank, "elapsed_s": 1.0,
                    "stages": {}, "request_traces": {
                        "completed": 2, "open": 0,
                        "slowest": [{"request": rank * 10 + i,
                                     "latency_s": float(i)}
                                    for i in range(2)]}}
            (tmp_path / f"metrics_rank{rank}.json").write_text(
                json.dumps(snap))
        agg = telemetry.aggregate_snapshots(str(tmp_path))
        assert len(agg["request_traces"]["slowest"]) == 2


class TestEngineInspectorIntegrity:
    def test_introspect_registry_is_weak(self):
        import gc
        import weakref
        eng = GenerationEngine(StubBackend(1, 32))
        assert eng in introspect.live_engines()
        wr = weakref.ref(eng)
        del eng
        gc.collect()
        # the registry holds no strong ref: the engine is collectable
        # and therefore gone from the live list
        assert wr() is None
        assert all(wr() is not e for e in introspect.live_engines())

    def test_serving_snapshot_degrades_per_engine(self):
        eng = GenerationEngine(StubBackend(1, 32))
        eng.backend.pool_stats = None  # not callable -> fine
        snap = introspect.serving_snapshot()
        assert snap["n_engines"] >= 1
        assert all("slots" in e or "error" in e
                   for e in snap["engines"])

    def test_debug_state_exposes_failover_and_delivery_cursors(self):
        """ISSUE 19: the /serving view carries the failover state
        machine block, and each occupied slot row shows the exactly-once
        audit fields (delivery cursor + per-request failover count)."""
        eng = GenerationEngine(StubBackend(1, 32, vocab_size=997))
        eng.submit([5], max_new_tokens=8)
        for _ in range(3):
            eng.step()
        state = introspect.engine_debug_state(eng)
        fo = state["failover"]
        assert fo["state"] == "healthy"
        assert fo["count"] == 0 and fo["quarantined_total"] == 0
        row = state["slots"][0]
        assert row["state"] == "running"
        # the delivery cursor must sit exactly at the emitted frontier
        # at every iteration boundary — that equality IS exactly-once
        assert row["delivered"] == row["tokens_out"] > 0
        assert row["failovers"] == 0
        # snapshot() (the aggregate-counters view) carries it too
        assert eng.snapshot()["failover"]["state"] == "healthy"
