"""Worker program for the multi-process chaos tests (launched by
runner.launcher.supervise — NOT collected by pytest).

Each process: rendezvous via the launcher's SPARKDL_* env, then train a tiny
linear classifier through ``ctx.fit`` — which runs the chaos hooks
(``SPARKDL_CHAOS`` from the supervisor's FaultPlan) and the heartbeat touch
(``SPARKDL_HEARTBEAT_DIR``). A plan that SIGKILLs one rank mid-run exercises
the supervisor's prompt dead-rank detection + gang relaunch; the worker
needs no chaos awareness at all — that is the point.

Usage: chaos_mp_worker.py <out_dir>
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402


def main():
    out_dir = sys.argv[1]
    import numpy as np
    import optax

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from sparkdl_tpu.runner import XlaRunner, softmax_cross_entropy_loss

    runner = XlaRunner(np=2)
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()

    rng = np.random.RandomState(0)
    params = {"w": rng.randn(4, 3).astype(np.float32)}

    def data():
        r = np.random.RandomState(1)
        while True:
            x = r.randn(8, 4).astype(np.float32)
            yield {"image": x, "label": r.randint(0, 3, (8,))}

    def train(ctx):
        return ctx.fit(loss_fn=softmax_cross_entropy_loss(), params=params,
                       tx=optax.sgd(0.1), apply_fn=lambda p, x: x @ p["w"],
                       data=data(), num_steps=4, log_every=100)

    res = runner.run(train)
    assert int(res["state"].step) == 4
    with open(os.path.join(out_dir, f"rank{rank}.ok"), "a") as f:
        f.write("ok\n")
    print(f"rank {rank} ok", flush=True)


if __name__ == "__main__":
    main()
