"""parallel/ tests on the 8-device CPU mesh: sequence-parallel attention must
match dense single-device attention; sharding rules must produce the intended
PartitionSpecs and actually place shards."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from sparkdl_tpu.core import runtime
from sparkdl_tpu.parallel import (dense_attention, describe, lora_rules,
                                  make_rules, ring_attention, shard_params,
                                  transformer_tp_rules, ulysses_attention)


@pytest.fixture(scope="module")
def mesh():
    return runtime.make_mesh({"sp": 8})


def _qkv(seed=0, B=2, H=8, S=64, D=16, dtype=np.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D).astype(dtype) * 0.3)
    return mk(), mk(), mk()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, mesh, causal):
        q, k, v = _qkv()
        expected = dense_attention(q, k, v, causal=causal)
        got = ring_attention(q, k, v, mesh, axis="sp", causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)

    def test_inside_jit_with_grad(self, mesh):
        """Ring attention must compose into larger jitted programs and
        differentiate (it sits inside training steps)."""
        q, k, v = _qkv(seed=1, S=32)

        @jax.jit
        def loss(q, k, v):
            return ring_attention(q, k, v, mesh, causal=True).sum()

        g = jax.grad(loss)(q, k, v)
        assert g.shape == q.shape
        assert np.isfinite(np.asarray(g)).all()

        def dense_loss(q, k, v):
            return dense_attention(q, k, v, causal=True).sum()

        g_ref = jax.grad(dense_loss)(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=2e-3, atol=2e-4)

    def test_bf16(self, mesh):
        q, k, v = _qkv(seed=2, dtype=np.float32)
        q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
        got = ring_attention(q, k, v, mesh, causal=True)
        assert got.dtype == jnp.bfloat16
        exp = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), causal=True)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(exp), rtol=0.1, atol=0.05)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, mesh, causal):
        q, k, v = _qkv(seed=3)
        expected = dense_attention(q, k, v, causal=causal)
        got = ulysses_attention(q, k, v, mesh, axis="sp", causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)

    def test_head_divisibility_check(self, mesh):
        q, k, v = _qkv(H=6)
        with pytest.raises(ValueError):
            ulysses_attention(q, k, v, mesh)


class TestShardingRules:
    def _params(self):
        return {
            "layer0": {
                "q_proj": {"kernel": np.zeros((64, 64)),
                           "bias": np.zeros((64,))},
                "o_proj": {"kernel": np.zeros((64, 64))},
                "up_proj": {"kernel": np.zeros((64, 256))},
                "down_proj": {"kernel": np.zeros((256, 64))},
                "norm": {"scale": np.zeros((64,))},
            },
            "embed_tokens": {"embedding": np.zeros((1000, 64))},
        }

    def test_tp_rules_specs(self):
        rules = transformer_tp_rules()
        desc = describe(self._params(), rules)
        assert desc["layer0/q_proj/kernel"] == str(P(None, "model"))
        assert desc["layer0/o_proj/kernel"] == str(P("model", None))
        assert desc["layer0/up_proj/kernel"] == str(P(None, "model"))
        assert desc["layer0/down_proj/kernel"] == str(P("model", None))
        assert desc["embed_tokens/embedding"] == str(P(None, "model"))
        assert desc["layer0/norm/scale"] == str(P())
        # bias: the kernel rules don't match it → replicated default
        assert desc["layer0/q_proj/bias"] == str(P())

    def test_shard_params_places_shards(self):
        mesh = runtime.make_mesh({"data": 4, "model": 2})
        placed = shard_params(self._params(), mesh,
                              transformer_tp_rules())
        k = placed["layer0"]["q_proj"]["kernel"]
        # output dim split over model axis (2) → shards are (64, 32)
        assert {s.data.shape for s in k.addressable_shards} == {(64, 32)}
        n = placed["layer0"]["norm"]["scale"]
        assert {s.data.shape for s in n.addressable_shards} == {(64,)}

    def test_lora_rules_inherit(self):
        params = {
            "layer0": {"q_proj": {
                "kernel": np.zeros((64, 64)),
                "lora_a": {"kernel": np.zeros((64, 8))},
                "lora_b": {"kernel": np.zeros((8, 64))},
            }}}
        rules = lora_rules(transformer_tp_rules())
        desc = describe(params, rules)
        # base q_proj is output-sharded → A replicated-in (in-dim of base is
        # None), B inherits output sharding
        assert desc["layer0/q_proj/lora_a/kernel"] == str(P(None, None))
        assert desc["layer0/q_proj/lora_b/kernel"] == str(P(None, "model"))

    def test_custom_rules_first_match_wins(self):
        rules = make_rules([(r"special", P("data")), (r".*", P())])
        desc = describe({"special": np.zeros((8, 2)),
                         "other": np.zeros((8,))}, rules)
        assert desc["special"] == str(P("data"))
        assert desc["other"] == str(P())


class TestPipelineParallel:
    """GPipe schedule vs sequential stage application (parallel/pipeline.py)."""

    P_STAGES = 8
    D = 16

    def _stages(self):
        rng = np.random.RandomState(0)
        return [{"w": jnp.asarray(rng.randn(self.D, self.D)
                                  .astype(np.float32) * 0.3),
                 "b": jnp.asarray(rng.randn(self.D)
                                  .astype(np.float32) * 0.1)}
                for _ in range(self.P_STAGES)]

    @staticmethod
    def _stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    def _reference(self, stages, x):
        h = x.reshape(-1, self.D)
        for p in stages:
            h = self._stage_fn(p, h)
        return h.reshape(x.shape)

    def test_forward_matches_sequential(self):
        from sparkdl_tpu.parallel import (gpipe, stack_stage_params,
                                          stage_sharding)
        mesh = runtime.make_mesh({"pp": self.P_STAGES})
        stages = self._stages()
        stacked = stage_sharding(mesh, stack_stage_params(stages), "pp")
        apply = gpipe(self._stage_fn, mesh, "pp")
        x = jnp.asarray(np.random.RandomState(1)
                        .randn(4, 2, self.D).astype(np.float32))
        y = jax.jit(apply)(stacked, x)
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(self._reference(stages, x)),
                                   atol=1e-6)

    def test_backward_through_schedule(self):
        from sparkdl_tpu.parallel import (gpipe, stack_stage_params,
                                          stage_sharding)
        mesh = runtime.make_mesh({"pp": self.P_STAGES})
        stages = self._stages()
        stacked = stage_sharding(mesh, stack_stage_params(stages), "pp")
        apply = gpipe(self._stage_fn, mesh, "pp")
        x = jnp.asarray(np.random.RandomState(2)
                        .randn(2, 2, self.D).astype(np.float32))

        def loss_pp(params):
            return (apply(params, x) ** 2).sum()

        def loss_ref(params_list):
            h = x.reshape(-1, self.D)
            for i in range(self.P_STAGES):
                h = self._stage_fn(
                    jax.tree_util.tree_map(lambda l: l[i], params_list), h)
            return (h ** 2).sum()

        g_pp = jax.jit(jax.grad(loss_pp))(stacked)
        g_ref = jax.grad(loss_ref)(stack_stage_params(stages))
        for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)

    def test_microbatch_helper(self):
        from sparkdl_tpu.parallel import microbatch
        assert microbatch(np.zeros((8, 3)), 4).shape == (4, 2, 3)
        with pytest.raises(ValueError, match="not divisible"):
            microbatch(np.zeros((7, 3)), 4)


class TestSwitchMoE:
    """Expert parallelism (parallel/moe.py): GShard dispatch einsums vs a
    per-token reference; ep-axis sharding; capacity drops; aux loss."""

    def _build(self, capacity_factor=4.0, seed=0):
        from sparkdl_tpu.parallel import SwitchMoE
        rng = np.random.RandomState(seed)
        x = jnp.asarray(rng.randn(2, 16, 8).astype(np.float32))
        moe = SwitchMoE(num_experts=4, d_ff=32,
                        capacity_factor=capacity_factor)
        variables = moe.init(jax.random.PRNGKey(0), x)
        return moe, variables, x

    def test_matches_per_token_reference(self):
        moe, variables, x = self._build()
        out = moe.apply(variables, x)
        params = variables["params"]
        xf = np.asarray(x.reshape(-1, x.shape[-1]))
        logits = xf @ np.asarray(params["router"]["kernel"]) + \
            np.asarray(params["router"]["bias"])
        probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        idx, g = np.argmax(probs, -1), np.max(probs, -1)
        ref = np.zeros_like(xf)
        for n in range(len(xf)):
            e = int(idx[n])
            h = np.asarray(jax.nn.gelu(jnp.asarray(
                xf[n] @ np.asarray(params["experts"]["wi"]["kernel"])[e]
                + np.asarray(params["experts"]["wi"]["bias"])[e])))
            ref[n] = g[n] * (
                h @ np.asarray(params["experts"]["wo"]["kernel"])[e]
                + np.asarray(params["experts"]["wo"]["bias"])[e])
        np.testing.assert_allclose(np.asarray(out).reshape(ref.shape), ref,
                                   atol=1e-5)

    def test_capacity_drops_tokens(self):
        # capacity 1 token/expert: most tokens dropped → output zeros there
        moe, variables, x = self._build(capacity_factor=4.0)
        out_full = np.asarray(moe.apply(variables, x))
        from sparkdl_tpu.parallel import SwitchMoE
        tight = SwitchMoE(num_experts=4, d_ff=32, capacity_factor=0.125)
        out_tight = np.asarray(tight.apply(variables, x))
        zeros_tight = (np.abs(out_tight.reshape(-1, 8)).sum(-1) == 0).sum()
        zeros_full = (np.abs(out_full.reshape(-1, 8)).sum(-1) == 0).sum()
        assert zeros_tight > zeros_full

    def test_aux_loss_bounds(self):
        from sparkdl_tpu.parallel import moe_aux_loss
        moe, variables, x = self._build()
        _, state = moe.apply(variables, x, mutable=["intermediates"])
        aux = float(moe_aux_loss(state["intermediates"]))
        # E * sum_e(f_e * p_e) lies in (0, E]: each factor is a
        # distribution over experts; near-uniform routing gives ~1
        assert 0.0 < aux <= moe.num_experts

    def test_ep_sharding_and_grads(self):
        from sparkdl_tpu.parallel import moe_rules, shard_params
        moe, variables, x = self._build()
        mesh = runtime.make_mesh({"ep": 4, "data": 2})
        placed = {"params": shard_params(variables["params"], mesh,
                                         moe_rules(ep_axis="ep"))}
        spec = placed["params"]["experts"]["wi"]["kernel"].sharding.spec
        assert spec[0] == "ep"
        router_spec = placed["params"]["router"]["kernel"].sharding.spec
        assert all(s is None for s in router_spec)
        grads = jax.jit(jax.grad(
            lambda v: (moe.apply(v, x) ** 2).sum()))(placed)
        assert all(bool(jnp.isfinite(g).all())
                   for g in jax.tree_util.tree_leaves(grads))


def test_ulysses_flash_local_attention():
    """Ulysses with the flash kernel as the local attention (the long-
    context composition of SURVEY §5.7) matches dense-local Ulysses and
    single-device dense attention."""
    import functools
    from sparkdl_tpu.ops import flash_attention
    mesh = runtime.make_mesh({"sp": 4}, devices_=jax.devices()[:4])
    rng = np.random.RandomState(5)
    q, k, v = [jnp.asarray(rng.randn(2, 4, 64, 16).astype(np.float32) * 0.3)
               for _ in range(3)]
    ref = dense_attention(q, k, v, causal=True)
    got = ulysses_attention(
        q, k, v, mesh, axis="sp", causal=True,
        local_attn=functools.partial(flash_attention,
                                     block_q=16, block_k=16))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_ring_attention_composes_with_dp_tp_axes():
    """ring_attention(batch_axis=, head_axis=) on a 3-D data×model×sp
    mesh: B and H ride their already-sharded axes (no all-gather undoing
    DP/TP around attention) and the result still matches single-device
    dense attention — composition is layout, not math."""
    mesh = runtime.make_mesh({"data": 2, "model": 2, "sp": 2})
    rng = np.random.RandomState(9)
    q, k, v = [jnp.asarray(rng.randn(4, 4, 32, 16).astype(np.float32) * 0.3)
               for _ in range(3)]
    ref = dense_attention(q, k, v, causal=True)
    composed = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, axis="sp", causal=True,
        batch_axis="data", head_axis="model"))
    np.testing.assert_allclose(np.asarray(composed(q, k, v)),
                               np.asarray(ref), atol=2e-5)
    # sharded inputs (the composed-training layout) give the same answer
    spec = jax.sharding.NamedSharding(mesh, P("data", "model", "sp", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    np.testing.assert_allclose(np.asarray(composed(qs, ks, vs)),
                               np.asarray(ref), atol=2e-5)


def test_ulysses_composes_with_dp_tp_axes():
    """Ulysses on the 3-D data×model×sp mesh (the DeepSpeed Ulysses+TP
    layout): the all_to_all scatters the TP-local head set over sp, B
    rides the data axis, and the answer matches single-device dense."""
    mesh = runtime.make_mesh({"data": 2, "model": 2, "sp": 2})
    rng = np.random.RandomState(11)
    q, k, v = [jnp.asarray(rng.randn(4, 4, 32, 16).astype(np.float32) * 0.3)
               for _ in range(3)]
    ref = dense_attention(q, k, v, causal=True)
    composed = jax.jit(lambda a, b, c: ulysses_attention(
        a, b, c, mesh, axis="sp", causal=True,
        batch_axis="data", head_axis="model"))
    np.testing.assert_allclose(np.asarray(composed(q, k, v)),
                               np.asarray(ref), atol=2e-5)
    spec = jax.sharding.NamedSharding(mesh, P("data", "model", "sp", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    np.testing.assert_allclose(np.asarray(composed(qs, ks, vs)),
                               np.asarray(ref), atol=2e-5)
    # per-TP-shard divisibility is the enforced contract: 4 heads / tp 2
    # = 2 local heads over sp 2 is exactly divisible; 1 local head is not
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(q[:, :2], k[:, :2], v[:, :2], mesh, axis="sp",
                          batch_axis="data", head_axis="model")


class TestFSDP:
    """fsdp_rules: ZeRO-3-style param sharding over the data axis,
    GSPMD-idiomatic (all-gather at use / reduce-scatter on grads come
    from the layout, not a wrapper)."""

    def test_specs_compose_with_tp(self):
        rules = transformer_tp_rules(data_axis="data")
        params = {
            "l0": {"q_proj": {"kernel": np.zeros((64, 64)),
                              "bias": np.zeros((64,))},
                   "o_proj": {"kernel": np.zeros((64, 64))},
                   "norm": {"scale": np.zeros((64,))}},
            "embed_tokens": {"embedding": np.zeros((512, 64))},
        }
        desc = describe(params, rules)
        # TP dim kept, first free dim goes to data
        assert desc["l0/q_proj/kernel"] == str(P("data", "model"))
        assert desc["l0/o_proj/kernel"] == str(P("model", "data"))
        assert desc["embed_tokens/embedding"] == str(P("data", "model"))
        # 1-D leaves stay on the base layout
        assert desc["l0/q_proj/bias"] == str(P())
        assert desc["l0/norm/scale"] == str(P())

    def test_fsdp_tp_train_step_matches_single_device(self):
        """A 2-D FSDP×TP step (params sharded over data AND model) must
        produce the same updated params as an unsharded single-device
        step — the sharding is residency layout, not math."""
        import optax
        from sparkdl_tpu.models.llama import (LlamaConfig, LlamaModel,
                                              causal_lm_loss_fn)
        from sparkdl_tpu.runner import TrainState, make_train_step

        mesh = runtime.make_mesh({"data": 4, "model": 2})
        cfg = LlamaConfig.tiny()
        rng = np.random.RandomState(13)
        ids = rng.randint(0, cfg.vocab_size, size=(8, 16))
        model = LlamaModel(cfg)
        variables = jax.tree_util.tree_map(
            np.asarray,
            model.init(jax.random.PRNGKey(0), jnp.asarray(ids[:1])))
        loss_fn = causal_lm_loss_fn()

        placed = shard_params(variables, mesh,
                              transformer_tp_rules(data_axis="data"))
        # the FSDP layout actually landed: q_proj kernel has both axes
        qk = placed["params"]["layer_0"]["attn"]["q_proj"]["base"]["kernel"]
        assert {s.data.shape for s in qk.addressable_shards} == \
            {(qk.shape[0] // 4, qk.shape[1] // 2)}

        state = TrainState.create(model.apply, placed, optax.sgd(1e-2))
        step = make_train_step(loss_fn, mesh, data_axis="data")
        new_state, m = step(state, {"input_ids": jnp.asarray(ids)})
        jax.block_until_ready(new_state.params)
        assert np.isfinite(float(m["loss"]))

        ref_state = TrainState.create(model.apply, variables,
                                      optax.sgd(1e-2))
        ref_step = jax.jit(lambda s, b: s.apply_gradients(jax.grad(
            lambda p: loss_fn(p, model.apply, b)[0])(s.params)))
        ref_new = ref_step(ref_state, {"input_ids": jnp.asarray(ids)})
        flat_ref = dict(jax.tree_util.tree_leaves_with_path(ref_new.params))
        for path, leaf in jax.tree_util.tree_leaves_with_path(
                new_state.params):
            np.testing.assert_allclose(np.asarray(leaf),
                                       np.asarray(flat_ref[path]),
                                       rtol=5e-4, atol=5e-5)


def test_fsdp_skips_indivisible_dims_with_mesh():
    """Advisor (round 5) regression: with the mesh given, fsdp_rules must
    only put the data axis on a dim divisible by mesh.shape[data_axis] —
    a 50257-vocab embedding on data=4 splits unevenly and GSPMD would
    pad-and-reshard it on every use. Later free dims are tried; with none
    divisible the leaf falls back to the base spec."""
    from sparkdl_tpu.parallel import fsdp_rules
    mesh = runtime.make_mesh({"data": 4, "model": 2})
    rules = transformer_tp_rules(data_axis="data", mesh=mesh)
    params = {
        # vocab 50257 % 4 != 0, hidden dim taken by TP -> base spec only
        "embed_tokens": {"embedding": np.zeros((50257, 64))},
        # first dim indivisible, SECOND free dim divisible -> data lands
        # there (try-later-free-dims, not give-up-at-first)
        "odd_head": {"kernel": np.zeros((7, 64))},
        # the normal case keeps its FSDP sharding
        "l0": {"q_proj": {"kernel": np.zeros((64, 64))}},
    }
    desc = describe(params, rules)
    assert desc["embed_tokens/embedding"] == str(P(None, "model"))
    assert desc["odd_head/kernel"] == str(P(None, "data"))
    assert desc["l0/q_proj/kernel"] == str(P("data", "model"))
    # documented limitation: WITHOUT the mesh the extent is unknown and
    # the first free dim is taken unchecked (pre-fix behavior)
    no_mesh = describe(params, transformer_tp_rules(data_axis="data"))
    assert no_mesh["embed_tokens/embedding"] == str(P("data", "model"))
    # bare fsdp_rules (no TP base) honors the mesh too
    bare = fsdp_rules(data_axis="data", mesh=mesh)
    assert describe({"t": {"kernel": np.zeros((50257, 7))}},
                    bare)["t/kernel"] == str(P())


def test_fsdp_lora_and_idempotence():
    """lora_rules composes over the FSDP wrapper (adapters inherit the
    BASE TP layout, deliberately unsharded on data), and re-applying
    fsdp_rules never produces a duplicate mesh axis."""
    from sparkdl_tpu.parallel import fsdp_rules
    params = {"l0": {"q_proj": {
        "base": {"kernel": np.zeros((64, 64))},
        "lora_a": {"kernel": np.zeros((64, 8))},
        "lora_b": {"kernel": np.zeros((8, 64))},
    }, "custom_head": {"kernel": np.zeros((64, 32))}}}
    rules = lora_rules(transformer_tp_rules(data_axis="data"))
    desc = describe(params, rules)
    assert desc["l0/q_proj/base/kernel"] == str(P("data", "model"))
    # adapters: TP inheritance preserved, NOT data-sharded
    assert desc["l0/q_proj/lora_a/kernel"] == str(P(None, None))
    assert desc["l0/q_proj/lora_b/kernel"] == str(P(None, "model"))
    # double application is idempotent (no P("data", "data"))
    twice = fsdp_rules(transformer_tp_rules(data_axis="data"),
                       data_axis="data")
    d2 = describe(params, twice)
    assert d2["l0/custom_head/kernel"] == str(P("data", None))


def test_train_step_batch_spec_rank_truncation():
    """A multi-axis batch_spec applies per leaf truncated to the leaf's
    rank: a [B] leaf under P('data', 'sp') constrains as P('data')
    instead of crashing, and accum microbatches keep the spec."""
    import optax
    from sparkdl_tpu.runner import TrainState, make_train_step

    mesh = runtime.make_mesh({"data": 4, "sp": 2})

    def loss_fn(params, apply_fn, batch):
        per_tok = (batch["x"] * params["w"]).mean(axis=1)
        return (per_tok * batch["weight"]).mean(), {}

    params = {"w": np.float32(2.0)}
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(8, 4).astype(np.float32),
             "weight": rng.rand(8).astype(np.float32)}
    for accum in (1, 2):
        # fresh state each round: the step donates its state argument
        state = TrainState.create(None, params, optax.sgd(0.1))
        step = make_train_step(loss_fn, mesh, data_axis="data",
                               batch_spec=P("data", "sp"),
                               accum_steps=accum)
        new_state, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))
        ref = (batch["x"].mean(axis=1) * batch["weight"]).mean()
        np.testing.assert_allclose(float(m["loss"]), ref * 2.0, rtol=1e-5)
