"""Model zoo tests: shapes, feature dims, determinism, weight round-trips.

Runs on the virtual CPU mesh with small batches; full 299x299 InceptionV3
forward is exercised once (it is the flagship featurizer). Heavier archs are
shape-checked at reduced spatial size where the architecture allows.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu.models import registry
from sparkdl_tpu.models.registry import get_model


def test_registry_contents_match_reference_surface():
    # The reference's SUPPORTED_MODELS: InceptionV3, Xception, ResNet50,
    # VGG16, VGG19 (SURVEY.md §2.1). Extras are allowed, absences are not.
    for name in ["InceptionV3", "Xception", "ResNet50", "VGG16", "VGG19"]:
        m = get_model(name)
        assert m.feature_dim in (2048, 4096)
    with pytest.raises(ValueError, match="Unknown model"):
        get_model("NopeNet")


def test_preprocess_functions():
    x = jnp.full((1, 2, 2, 3), 255.0)
    np.testing.assert_allclose(registry.preprocess_tf(x), 1.0)
    caffe = registry.preprocess_caffe(jnp.zeros((1, 1, 1, 3)))
    np.testing.assert_allclose(
        np.asarray(caffe)[0, 0, 0], [-103.939, -116.779, -123.68], rtol=1e-5)
    t = registry.preprocess_torch(jnp.full((1, 1, 1, 3), 255.0))
    np.testing.assert_allclose(
        np.asarray(t)[0, 0, 0],
        (1.0 - np.array([0.485, 0.456, 0.406])) / np.array([0.229, 0.224, 0.225]),
        rtol=1e-5)


def test_resnet50_shapes_and_feature_dim():
    # eval_shape end to end (the ISSUE 8/10 headroom pattern): shapes
    # and feature dims need no parameter compute and no XLA compile —
    # this was ~16s of real 224x224 ResNet50 forwards for a shape
    # assertion. Real-forward numerics for the image models are pinned
    # by the DeepImageFeaturizer equivalence test (ResNet18).
    m = get_model("ResNet50")
    variables = jax.eval_shape(lambda: m.init_params(seed=0))
    x = jax.ShapeDtypeStruct((2, 224, 224, 3), np.float32)
    feats = jax.eval_shape(m.apply_fn(features_only=True), variables, x)
    logits = jax.eval_shape(m.apply_fn(features_only=False), variables, x)
    assert feats.shape == (2, 2048)
    assert logits.shape == (2, 1000)


@pytest.mark.slow
def test_inception_v3_full_size_bottleneck():
    # Full-size 299x299 InceptionV3 forward: ~30s of tier-1 budget for a
    # numerical-sanity proof — behind the slow marker (ISSUE 8 headroom
    # satellite); the architecture itself is covered by the shape and
    # param-count tests.
    m = get_model("InceptionV3")
    variables = m.init_params(seed=0)
    fn = jax.jit(m.apply_fn(features_only=True))
    x = np.random.default_rng(1).uniform(0, 255, (1, 299, 299, 3)).astype(np.float32)
    feats = fn(variables, x)
    assert feats.shape == (1, 2048)
    assert np.isfinite(np.asarray(feats)).all()


def test_param_counts_sane():
    # ResNet50 ≈ 25.6M params; InceptionV3 ≈ 23.9M (with heads).
    # Shape-only: eval_shape traces init without computing a single
    # weight (the old full inits cost ~37s of tier-1 budget for a
    # number that only depends on shapes).
    def count(model):
        shapes = jax.eval_shape(lambda: model.init_params(seed=0))
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(shapes["params"]))

    rn = count(get_model("ResNet50"))
    assert 25_000_000 < rn < 26_500_000, rn
    iv = count(get_model("InceptionV3"))
    assert 23_000_000 < iv < 24_500_000, iv


def test_bf16_compute_fp32_params():
    # dtype policy is a trace-level property — eval_shape carries dtypes
    # without a ~6s real 224x224 forward (ISSUE 10 headroom satellite);
    # bf16 NUMERICS are pinned by the featurizer bfloat16-close-to-f32
    # test in test_transformers.
    m = get_model("ResNet18")
    variables = jax.eval_shape(
        lambda: m.init_params(seed=0, dtype=jnp.bfloat16))
    p0 = jax.tree_util.tree_leaves(variables["params"])[0]
    assert p0.dtype == jnp.float32  # params stay fp32
    x = jax.ShapeDtypeStruct((1, 224, 224, 3), np.float32)
    out = jax.eval_shape(m.apply_fn(dtype=jnp.bfloat16,
                                    features_only=True), variables, x)
    assert out.dtype == jnp.float32  # features cast back at the boundary
    assert out.shape == (1, 512)


def test_weight_roundtrip_msgpack_and_safetensors(tmp_path):
    m = get_model("ResNet18")
    variables = m.init_params(seed=42)
    p1 = str(tmp_path / "w.msgpack")
    registry.save_weights(variables, p1)
    loaded = registry.load_weights(variables, p1)
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(loaded)[0]),
        np.asarray(jax.tree_util.tree_leaves(variables)[0]))

    p2 = str(tmp_path / "w.safetensors")
    registry.save_safetensors(variables, p2)
    loaded2 = registry.load_safetensors(variables, p2)
    for a, b in zip(jax.tree_util.tree_leaves(loaded2),
                    jax.tree_util.tree_leaves(variables)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    registry.save_safetensors({"params": {"w": jnp.ones((2,))}},
                              str(tmp_path / "bad.safetensors"))
    with pytest.raises(ValueError, match="missing"):
        registry.load_safetensors(variables, str(tmp_path / "bad.safetensors"))


def test_decode_predictions():
    logits = np.array([[0.0, 3.0, 1.0]])
    out = registry.decodePredictions(logits, top=2)
    assert out[0][0]["class"] == 1 and out[0][1]["class"] == 2
    assert 0 < out[0][0]["score"] <= 1
    assert out[0][0]["label"] == "class_1"


def test_preprocess_accepts_uint8_wire_batches():
    """uint8 batches (the 4x-cheaper wire format) must preprocess
    identically to their f32 equivalents — caffe's mean subtraction in
    particular must not wrap in uint8 arithmetic."""
    import numpy as np
    import jax.numpy as jnp
    from sparkdl_tpu.models.registry import (preprocess_caffe,
                                             preprocess_tf,
                                             preprocess_torch)
    rng = np.random.RandomState(0)
    u8 = rng.randint(0, 256, size=(2, 8, 8, 3), dtype=np.uint8)
    f32 = u8.astype(np.float32)
    for fn in (preprocess_tf, preprocess_caffe, preprocess_torch):
        a = np.asarray(fn(jnp.asarray(u8)))
        b = np.asarray(fn(jnp.asarray(f32)))
        assert a.dtype == np.float32, fn.__name__
        np.testing.assert_allclose(a, b, atol=1e-6, err_msg=fn.__name__)
