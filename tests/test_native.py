"""Native batch-packer tests (C++ lib vs numpy/jax references)."""

import numpy as np
import pytest

import jax

from sparkdl_tpu import native


@pytest.fixture(scope="module", autouse=True)
def built():
    if not native.ensure_built():
        pytest.skip("native toolchain unavailable")


def test_abi_available():
    assert native.available()


def test_pack_batch_exact_no_resize():
    rng = np.random.RandomState(0)
    b = rng.randint(0, 256, (4, 5, 6, 3)).astype(np.uint8)
    out = native.pack_batch(b, flip_bgr=True, scale=1 / 127.5, offset=-1.0)
    assert out.dtype == np.float32
    want = b[..., ::-1].astype(np.float32) / 127.5 - 1.0
    assert np.allclose(out, want, atol=1e-6)


def test_pack_batch_matches_jax_resize():
    rng = np.random.RandomState(1)
    for (h, w), (oh, ow) in [((10, 12), (8, 8)), ((7, 5), (16, 16)),
                             ((20, 20), (8, 14))]:
        src = rng.randint(0, 256, (2, h, w, 3)).astype(np.uint8)
        nat = native.pack_batch(src, oh, ow)
        ref = np.asarray(jax.image.resize(
            src.astype(np.float32), (2, oh, ow, 3), method="bilinear"))
        assert np.abs(nat - ref).max() < 1e-3, ((h, w), (oh, ow))


def test_pack_images_variable_sizes():
    rng = np.random.RandomState(2)
    hs, ws = [9, 17, 8], [11, 6, 8]
    bufs = [rng.randint(0, 256, (h, w, 3)).astype(np.uint8).tobytes()
            for h, w in zip(hs, ws)]
    out = native.pack_images(bufs, hs, ws, 3, 8, 8, flip_bgr=True)
    assert out.shape == (3, 8, 8, 3)
    for i, (h, w) in enumerate(zip(hs, ws)):
        src = np.frombuffer(bufs[i], np.uint8).reshape(h, w, 3)
        ref = np.asarray(jax.image.resize(
            src[..., ::-1].astype(np.float32), (8, 8, 3), method="bilinear"))
        assert np.abs(out[i] - ref).max() < 1e-3


def test_bgra_flip_native_and_python_paths_agree():
    """c=4 flip must be BGRA→RGBA (alpha preserved) on EVERY path."""
    from sparkdl_tpu.image import imageIO

    rng = np.random.RandomState(9)
    arr = rng.randint(0, 256, (5, 5, 4)).astype(np.uint8)
    structs = [imageIO.imageArrayToStruct(arr)]
    nat = imageIO.structsToNHWC(structs)  # native path (float32 + uint8)
    py = imageIO.structsToNHWC(structs, dtype=np.float64).astype(np.float32)
    np.testing.assert_allclose(nat, py)
    assert np.allclose(nat[0][..., 3], arr[..., 3])   # alpha stays channel 3
    assert np.allclose(nat[0][..., 0], arr[..., 2])   # B<->R swapped
    # round-trip: NHWC (RGBA) → structs (BGRA) → NHWC
    back = imageIO.structsToNHWC(imageIO.nhwcToStructs(
        nat.astype(np.uint8)))
    np.testing.assert_allclose(back, nat)


def test_pack_images_bgra_alpha_preserved():
    rng = np.random.RandomState(3)
    b = rng.randint(0, 256, (2, 4, 4, 4)).astype(np.uint8)
    out = native.pack_batch(b, flip_bgr=True)
    assert np.allclose(out[..., 3], b[..., 3])
    assert np.allclose(out[..., 0], b[..., 2])
    assert np.allclose(out[..., 2], b[..., 0])


def test_pack_images_grayscale():
    rng = np.random.RandomState(4)
    b = rng.randint(0, 256, (3, 6, 6, 1)).astype(np.uint8)
    out = native.pack_batch(b, flip_bgr=True)  # flip is a no-op for c=1
    assert np.allclose(out, b.astype(np.float32))


def test_bad_buffer_size_raises():
    with pytest.raises(ValueError, match="expected"):
        native.pack_images([b"abc"], [4], [4], 3, 4, 4)


def test_empty_batch():
    out = native.pack_images([], [], [], 3, 4, 4)
    assert out.shape == (0, 4, 4, 3)


def test_numpy_fallback_agrees_uniform():
    rng = np.random.RandomState(5)
    b = rng.randint(0, 256, (3, 5, 5, 3)).astype(np.uint8)
    nat = native.pack_batch(b, flip_bgr=True, scale=2.0, offset=1.0)
    ref = np.empty_like(nat)
    native._pack_images_numpy([b[i] for i in range(3)], [5] * 3, [5] * 3, 3,
                              ref, True, 2.0, 1.0)
    assert np.allclose(nat, ref, atol=1e-5)


def test_image_column_uses_native_path(monkeypatch):
    """imageColumnToNHWC's output must agree with the pure-python path."""
    import pyarrow as pa

    from sparkdl_tpu.image import imageIO

    rng = np.random.RandomState(6)
    structs = [imageIO.imageArrayToStruct(
        rng.randint(0, 256, (7, 7, 3)).astype(np.uint8)) for _ in range(4)]
    col = pa.array(structs, type=imageIO.imageSchema)
    monkeypatch.setenv("SPARKDL_TPU_NATIVE", "1")
    fast = imageIO.imageColumnToNHWC(col)
    monkeypatch.setenv("SPARKDL_TPU_NATIVE", "0")
    slow = imageIO.imageColumnToNHWC(col)
    assert np.allclose(fast, slow, atol=1e-5)


def test_pack_images_rejects_nonuint8_arrays():
    with pytest.raises(TypeError, match="uint8"):
        native.pack_images([np.ones((4, 4, 3), np.float32)], [4], [4],
                           3, 4, 4)


def test_pack_images_u8_output_exact_and_rounds():
    """dtype=uint8 output: exact passthrough when no resize; rounded (<=0.5
    level) match of the float path when resizing — the u8 feed ships 4x
    fewer bytes to the device (round-3 perf fix)."""
    rng = np.random.RandomState(3)
    img = rng.randint(0, 256, size=(20, 30, 3)).astype(np.uint8)
    same = native.pack_images([img.tobytes()], [20], [30], 3, 20, 30,
                              flip_bgr=True, dtype=np.uint8)
    assert same.dtype == np.uint8
    np.testing.assert_array_equal(same[0], img[:, :, ::-1])

    f32 = native.pack_images([img.tobytes()], [20], [30], 3, 11, 17,
                             flip_bgr=True)
    u8 = native.pack_images([img.tobytes()], [20], [30], 3, 11, 17,
                            flip_bgr=True, dtype=np.uint8)
    assert np.abs(f32[0] - u8[0].astype(np.float32)).max() <= 0.5 + 1e-3


def test_pack_images_rejects_bad_dtype():
    with pytest.raises(TypeError):
        native.pack_images([b"\x00" * 3], [1], [1], 3, 1, 1,
                           dtype=np.float64)


def test_ensure_built_thread_safe_single_make(monkeypatch, tmp_path):
    """Concurrent first-use must run at most one build, and a make that
    produces no .so must be reported as a failure (ADVICE r1 item 2)."""
    import threading
    import sparkdl_tpu.native as nat

    calls = []
    lock_probe = threading.Barrier(4, timeout=10)

    def fake_run(*a, **kw):
        calls.append(a)
        class R:
            returncode = 0
        return R()

    monkeypatch.setattr(nat, "_SO_PATH", str(tmp_path / "never_built.so"))
    monkeypatch.setattr(nat, "_build_failed", False)
    monkeypatch.setattr(nat.subprocess, "run", fake_run)

    results = []

    def worker():
        lock_probe.wait()
        results.append(nat.ensure_built())

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # make "succeeded" but produced no .so -> failure, and only ONE make ran
    # (the rest short-circuited on _build_failed under the lock).
    assert results == [False] * 4
    assert len(calls) == 1
