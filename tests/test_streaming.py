"""Streaming inference engine tests (ISSUE 3): cross-partition pipelining,
parallel decode overlap, per-stage flight-recorder spans, score smoke.

The runtime-level window mechanics are pinned in test_runtime.py
(run_stream meta threading, the no-drain dispatch count); this file pins
the TRANSFORMER-level engine: the StreamScorer that chunks partitions,
decodes on the pool, feeds one continuous device stream, and reassembles
partition outputs with the encode on an overlap worker.
"""

import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest

import sparkdl_tpu as sdl
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.runner import events

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def vector_df(n, parts, d=3):
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(n, d)).astype(np.float32)
    return sdl.DataFrame.fromPydict({"x": vals.tolist()},
                                    numPartitions=parts), vals


def test_stream_scorer_cross_partition_equivalence():
    """Many partitions (including filter-emptied ones mid-stream) through
    ONE continuous device stream: outputs land on the right partitions in
    the right order, identical to the single-partition path."""
    df, vals = vector_df(37, parts=9)
    fn = lambda b: b * 2.0 + 1.0
    t = sdl.XlaTransformer(inputCol="x", outputCol="y", fn=fn, batchSize=4)
    got = np.asarray([r.y for r in t.transform(df).collect()], np.float32)
    np.testing.assert_allclose(got, vals * 2.0 + 1.0, rtol=1e-6)

    single = sdl.DataFrame.fromPydict({"x": vals.tolist()}, numPartitions=1)
    got1 = np.asarray([r.y for r in t.transform(single).collect()],
                      np.float32)
    np.testing.assert_allclose(got, got1)

    # empty partitions interleaved: partition granularity preserved
    emptied = df.filter(lambda r: abs(r.x[0]) < 0.7)
    kept = [v for v in vals if abs(v[0]) < 0.7]
    out = t.transform(emptied)
    rows = out.collect()
    assert len(rows) == len(kept)
    np.testing.assert_allclose(
        np.asarray([r.y for r in rows], np.float32),
        np.asarray(kept, np.float32) * 2.0 + 1.0, rtol=1e-6)


def test_transformer_no_drain_at_partition_boundaries():
    """Dispatch-counting acceptance pin, transformer level: after the first
    partition's output is materialized, the engine has already dispatched
    chunks from LATER partitions — the in-flight window crossed the
    boundary instead of draining (the old per-partition mapBatches op
    dispatched exactly its own partition's chunks)."""
    df, _ = vector_df(24, parts=6)  # 6 partitions x 4 rows
    t = sdl.XlaTransformer(inputCol="x", outputCol="y",
                           fn=lambda b: b * 3.0, batchSize=4)
    runner = t._get_runner()
    dispatched = []
    inner = runner._jitted
    runner._jitted = lambda b: (dispatched.append(1), inner(b))[1]
    try:
        parts = t.transform(df).iterPartitions()
        first = next(parts)
        assert first.num_rows == 4
        # prefetch=2 window: >= 3 chunks (partitions 0,1,2) dispatched
        # before partition 0's output batch was even assembled
        assert len(dispatched) >= 3, dispatched
        rest = list(parts)
        assert len(rest) == 5
        assert len(dispatched) == 6
    finally:
        runner._jitted = inner


def test_pipelined_overlap_beats_serial_sum(monkeypatch):
    """ISSUE 3 acceptance: deliberately slow decode + slow fn — pipelined
    scoring wall-clock must beat the serial sum with generous margin
    (< 0.8x). Sleeps, not compute, so the bound is load-stable."""
    from sparkdl_tpu.transformers import tensor as tensor_mod

    n_chunks, decode_s, fn_s = 8, 0.08, 0.04
    monkeypatch.setenv("SPARKDL_DECODE_WORKERS", "2")
    orig_decode = tensor_mod.columnToNdarray

    def slow_decode(col, shape, **kw):
        time.sleep(decode_s)
        return orig_decode(col, shape, **kw)

    monkeypatch.setattr(tensor_mod, "columnToNdarray", slow_decode)

    def slow_fn(b):
        def cb(x):
            time.sleep(fn_s)
            return np.asarray(x) * 2.0
        return jax.pure_callback(
            cb, jax.ShapeDtypeStruct(b.shape, b.dtype), b)

    df, vals = vector_df(n_chunks * 4, parts=n_chunks)  # 1 chunk/partition
    t = sdl.XlaTransformer(inputCol="x", outputCol="y", fn=slow_fn,
                           batchSize=4)
    # compile + warm outside the timed window (serial sum has no compile
    # either); schema probe also lands here
    t.transform(df.limit(4)).collect()

    t0 = time.perf_counter()
    rows = t.transform(df).collect()
    wall = time.perf_counter() - t0
    assert len(rows) == n_chunks * 4
    np.testing.assert_allclose(
        np.asarray([r.y for r in rows], np.float32), vals * 2.0, rtol=1e-5)

    serial_sum = n_chunks * (decode_s + fn_s)  # 0.96s
    assert wall < 0.8 * serial_sum, \
        f"pipelined wall {wall:.3f}s vs serial sum {serial_sum:.3f}s"


def test_all_scoring_stages_emit_spans():
    """Every stage of the scoring pipeline lands in the flight recorder:
    decode/pad/put/dispatch/fetch on the feed side, encode on the overlap
    worker — the breakdown scripts/score_smoke.py prints."""
    rec = events.reset()
    try:
        df, _ = vector_df(12, parts=3)
        t = sdl.XlaTransformer(inputCol="x", outputCol="y",
                               fn=lambda b: b + 1.0, batchSize=4)
        assert len(t.transform(df).collect()) == 12
        evs = rec.tail()
        for stage in ("decode", "pad", "put", "dispatch", "fetch",
                      "encode"):
            ends = [e for e in evs
                    if e["name"] == stage and e["ph"] == "E"]
            assert len(ends) >= 3, f"missing spans for stage {stage}"
            assert all("dur_s" in e for e in ends)
    finally:
        events.reset()


def test_image_transformer_streams_across_partitions():
    """The image path (uint8 feed, image-mode output) through the
    cross-partition engine: struct outputs land on the right rows."""
    # constant-valued rows so output pixel values pin row ORDER across the
    # partition reassembly (model-output structs carry no origin)
    imgs = [np.full((8, 8, 3), i * 20, np.uint8) for i in range(10)]
    structs = [imageIO.imageArrayToStruct(im, origin=f"mem://{i}")
               for i, im in enumerate(imgs)]
    df = sdl.DataFrame.fromArrow(
        pa.table({"image": pa.array(structs, type=imageIO.imageSchema)}),
        numPartitions=5)
    t = sdl.XlaImageTransformer(
        inputCol="image", outputCol="out", fn=lambda b: b * 0.5,
        inputSize=(8, 8), batchSize=2, outputMode="image")
    rows = t.transform(df).collect()
    assert len(rows) == 10
    assert all(r.out["height"] == 8 for r in rows)
    got = [np.frombuffer(r.out["data"], np.uint8)[0] for r in rows]
    assert got == [i * 10 for i in range(10)]


@pytest.mark.slow
def test_score_smoke_script():
    """scripts/score_smoke.py end-to-end: streaming scoring + per-stage
    breakdown + a persistent compile-cache HIT in the second process."""
    import json
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "score_smoke.py")],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, \
        f"stdout={proc.stdout[-2000:]}\nstderr={proc.stderr[-2000:]}"
    line = [ln for ln in proc.stdout.strip().splitlines()
            if ln.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["ok"] is True
    assert rec["second_run"]["compile_cache"]["hits"] > 0
    assert set(rec["first_run"]["stages"]) >= {
        "decode", "pad", "put", "dispatch", "fetch", "encode"}


def test_encode_backpressure_bounds_raw_output_backlog():
    """A slow encode must throttle the consumer loop: fetched-but-not-
    encoded RAW float32 chunks are bounded by the backlog window, never a
    whole partition (the O(window·batchSize) host-memory contract)."""
    from sparkdl_tpu.transformers.streaming import StreamScorer

    pulled = []

    class StubRunner:
        prefetch = 2

        def run_stream(self, stream):
            for i, (arr, entry) in enumerate(stream):
                pulled.append(i)
                yield np.asarray([[float(i)]], np.float32), entry

    encode_backlog_seen = []
    done = [0]

    def slow_encode(result):
        # raw backlog at encode start = chunks pulled - chunks encoded
        encode_backlog_seen.append(len(pulled) - done[0])
        time.sleep(0.02)
        done[0] += 1
        return pa.array([float(result[0][0])])

    n_chunks = 12
    batch = pa.RecordBatch.from_arrays(
        [pa.array([float(i) for i in range(n_chunks)])], ["x"])
    scorer = StreamScorer(
        StubRunner(), "y",
        make_decoder=lambda rb: (
            lambda start, length: np.asarray([[float(start)]], np.float32)),
        encode=slow_encode,
        empty_array=lambda: pa.array([], type=pa.float64()),
        chunk_rows=1, decode_workers=0)
    [out] = list(scorer(iter([batch])))
    assert out.column(out.schema.get_field_index("y")).to_pylist() \
        == [float(i) for i in range(n_chunks)]
    # without backpressure the stub's instant fetches would pile all 12
    # raw chunks behind the first sleeping encode (backlog ≈ n_chunks)
    assert max(encode_backlog_seen) <= StubRunner.prefetch + 2, \
        encode_backlog_seen


# ---------------------------------------------------------------------------
# Fault-tolerant data plane (ISSUE 4): quarantine + dead letters + breaker
# ---------------------------------------------------------------------------

def ragged_df(parts=4, bad_rows=(5, 11), n=16):
    """3-wide float vectors; rows in ``bad_rows`` are ragged (fail the
    inputShape=(3,) reshape at decode time)."""
    rows = [[float(i), float(i + 1), float(i + 2)] for i in range(n)]
    for b in bad_rows:
        rows[b] = [1.0]
    df = sdl.DataFrame.fromArrow(
        pa.table({"x": pa.array(rows, type=pa.list_(pa.float32()))}),
        numPartitions=parts)
    return df, rows


def quarantining_transformer(**kw):
    kw.setdefault("batchSize", 4)
    return sdl.XlaTransformer(inputCol="x", outputCol="y",
                              fn=lambda b: b * 2.0, inputShape=(3,),
                              onError="quarantine", **kw)


def test_quarantine_routes_bad_rows_to_dead_letters():
    """Bad rows dead-letter with error classes; every surviving row is
    bit-identical to a clean run; counts agree across sink, run_stats,
    and input-minus-output."""
    from sparkdl_tpu.runner import metrics
    metrics.run_stats.reset()
    df, rows = ragged_df()
    t = quarantining_transformer()
    out = t.transform(df).collect()
    assert len(out) == 14
    dead = t.deadLetters()
    assert dead.num_rows == 2
    assert set(dead.column("error_class").to_pylist()) == {"ValueError"}
    assert all(m for m in dead.column("error").to_pylist())
    # dead letters carry the ORIGINAL payloads of exactly the bad rows
    assert sorted(len(v) for v in dead.column("x").to_pylist()) == [1, 1]
    assert metrics.run_stats.rows_quarantined == 2
    good = [r for i, r in enumerate(rows) if i not in (5, 11)]
    clean = sdl.XlaTransformer(inputCol="x", outputCol="y",
                               fn=lambda b: b * 2.0, inputShape=(3,),
                               batchSize=4).transform(
        sdl.DataFrame.fromPydict({"x": good})).collect()
    np.testing.assert_array_equal(
        np.asarray([r.y for r in out], np.float32),
        np.asarray([r.y for r in clean], np.float32))
    metrics.run_stats.reset()


def test_quarantine_default_is_raise():
    df, _ = ragged_df()
    t = sdl.XlaTransformer(inputCol="x", outputCol="y",
                           fn=lambda b: b * 2.0, inputShape=(3,),
                           batchSize=4)
    assert t.getOnError() == "raise"
    with pytest.raises(ValueError):
        t.transform(df).collect()
    with pytest.raises(ValueError, match="onError"):
        t.setOnError("ignore")


def test_quarantine_schema_stable_across_edges():
    """Satellite: scored + dead-letter batches round-trip through Arrow
    with stable column types on the empty-quarantine and
    all-rows-quarantined edges."""
    # all rows of one partition bad (partition 2 of 4 = rows 8..11)
    df, _ = ragged_df(bad_rows=(8, 9, 10, 11))
    t = quarantining_transformer(batchSize=2)
    scored = t.transform(df)
    table = scored.toArrow()
    assert table.num_rows == 12
    dead_all = t.deadLetters()
    assert dead_all.num_rows == 4

    # empty quarantine: same stable schema, zero rows
    clean_df, _ = ragged_df(bad_rows=())
    t2 = quarantining_transformer(batchSize=2)
    assert len(t2.transform(clean_df).collect()) == 16
    dead_none = t2.deadLetters()
    assert dead_none.num_rows == 0
    assert dead_none.schema.equals(dead_all.schema)
    assert dead_none.schema.names[-2:] == ["error_class", "error"]
    # both round-trip through Arrow IPC with types intact
    import pyarrow.ipc as ipc
    for tbl in (dead_all, dead_none):
        sink = pa.BufferOutputStream()
        with ipc.new_stream(sink, tbl.schema) as w:
            w.write_table(tbl)
        back = ipc.open_stream(sink.getvalue()).read_all()
        assert back.schema.equals(tbl.schema)
        assert back.num_rows == tbl.num_rows
    from sparkdl_tpu.runner import metrics
    metrics.run_stats.reset()


def test_quarantine_circuit_breaker_trips_fatal():
    from sparkdl_tpu.runner.failures import (QuarantineOverflowError,
                                             classify_exception)
    df, _ = ragged_df(bad_rows=tuple(range(16)))  # every row bad
    t = quarantining_transformer()
    with pytest.raises(QuarantineOverflowError) as ei:
        t.transform(df).collect()
    assert classify_exception(ei.value) == "fatal"
    from sparkdl_tpu.runner import metrics
    metrics.run_stats.reset()


def test_quarantine_image_payloads():
    """Image path: a row whose pixel buffer is truncated dead-letters;
    the rest score normally (chunk decode fails -> row fallback)."""
    imgs = [np.full((6, 6, 3), i * 10, np.uint8) for i in range(8)]
    structs = [imageIO.imageArrayToStruct(im, origin=f"m{i}")
               for i, im in enumerate(imgs)]
    structs[3] = dict(structs[3], data=structs[3]["data"][:17])  # truncated
    df = sdl.DataFrame.fromArrow(
        pa.table({"image": pa.array(structs, type=imageIO.imageSchema)}),
        numPartitions=2)
    t = sdl.XlaImageTransformer(
        inputCol="image", outputCol="out", fn=lambda b: b.mean(axis=(1, 2)),
        inputSize=(6, 6), batchSize=4, onError="quarantine")
    rows = t.transform(df).collect()
    assert [r.image["origin"] for r in rows] == \
        [f"m{i}" for i in range(8) if i != 3]
    dead = t.deadLetters()
    assert dead.num_rows == 1
    assert dead.column("image").to_pylist()[0]["origin"] == "m3"
    from sparkdl_tpu.runner import metrics
    metrics.run_stats.reset()


@pytest.mark.slow
@pytest.mark.chaos
def test_score_chaos_smoke_script():
    """scripts/score_chaos_smoke.py end-to-end (ISSUE 4 acceptance):
    injected decode faults -> job completes, quarantine counts agree,
    survivors bit-identical; injected dispatch preemption -> retried."""
    import json
    proc = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "scripts", "score_chaos_smoke.py")],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, \
        f"stdout={proc.stdout[-2000:]}\nstderr={proc.stderr[-2000:]}"
    rec = json.loads([ln for ln in proc.stdout.strip().splitlines()
                      if ln.startswith("{")][-1])
    assert rec["ok"] is True
    assert rec["survivors_bit_identical"] is True
    assert rec["quarantine_counts_agree"] is True
    assert rec["quarantined"] > 0
    assert rec["dispatch_retry_events"] >= 1


def test_schema_probe_preserves_dead_letters():
    """Review regression: DataFrame.schema re-invokes the stream op on a
    1-row probe; that clean pass must not wipe the dead letters of the
    real materialization."""
    df, _ = ragged_df()
    t = quarantining_transformer()
    out = t.transform(df)
    assert len(out.collect()) == 14
    assert t.deadLetters().num_rows == 2
    _ = out.schema          # 1-row clean probe
    _ = out.columns
    assert t.deadLetters().num_rows == 2  # ledger intact
    from sparkdl_tpu.runner import metrics
    metrics.run_stats.reset()


def test_circuit_breaker_has_min_rows_floor():
    """Review regression: a corrupt cluster at the HEAD of the stream
    must not trip the breaker when the whole-input fraction is tiny."""
    n = 120
    rows = [[float(i), 1.0, 2.0] for i in range(n)]
    for b in range(4):          # first chunk: 100% bad
        rows[b] = [0.5]
    df = sdl.DataFrame.fromArrow(
        pa.table({"x": pa.array(rows, type=pa.list_(pa.float32()))}),
        numPartitions=6)
    t = quarantining_transformer()
    out = t.transform(df).collect()   # completes: 4/120 << 0.5 overall
    assert len(out) == n - 4
    assert t.deadLetters().num_rows == 4
    from sparkdl_tpu.runner import metrics
    metrics.run_stats.reset()


# ---------------------------------------------------------------------------
# Process decode backend (ISSUE 7): SPARKDL_DECODE_BACKEND=process
# ---------------------------------------------------------------------------

def test_process_backend_vector_equivalence(monkeypatch):
    """The process decode pool is a drop-in for threads: same outputs,
    same order, across many partitions including filter-emptied ones."""
    df, vals = vector_df(37, parts=9)
    t = sdl.XlaTransformer(inputCol="x", outputCol="y",
                           fn=lambda b: b * 2.0 + 1.0, batchSize=4)
    thread = np.asarray([r.y for r in t.transform(df).collect()],
                        np.float32)
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", "process")
    got = np.asarray([r.y for r in t.transform(df).collect()], np.float32)
    np.testing.assert_array_equal(got, thread)

    emptied = df.filter(lambda r: abs(r.x[0]) < 0.7)
    rows = t.transform(emptied).collect()
    assert len(rows) == sum(1 for v in vals if abs(v[0]) < 0.7)


def test_process_backend_image_equivalence(monkeypatch):
    """Image path (compacted Arrow chunk payloads over the pickle
    boundary): bit-identical to the thread backend."""
    rng = np.random.default_rng(3)
    imgs = [rng.integers(0, 256, (8, 8, 3), np.uint8) for _ in range(10)]
    structs = [imageIO.imageArrayToStruct(im, origin=f"m{i}")
               for i, im in enumerate(imgs)]
    df = sdl.DataFrame.fromArrow(
        pa.table({"image": pa.array(structs, type=imageIO.imageSchema)}),
        numPartitions=3)
    t = sdl.XlaImageTransformer(
        inputCol="image", outputCol="out", fn=lambda b: b.mean(axis=(1, 2)),
        inputSize=(8, 8), batchSize=4)
    thread = np.asarray([r.out for r in t.transform(df).collect()])
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", "process")
    got = np.asarray([r.out for r in t.transform(df).collect()])
    np.testing.assert_array_equal(got, thread)


def test_process_backend_quarantine_equivalence(monkeypatch):
    """PR 4 fault tolerance on the process backend: the row-fallback runs
    in the pool child, dead-letter rows re-base onto the partition, and
    counts/classes/survivors match the thread backend exactly."""
    from sparkdl_tpu.runner import metrics
    metrics.run_stats.reset()
    df, rows = ragged_df()
    t = quarantining_transformer()
    thread_out = t.transform(df).collect()
    thread_dead = t.deadLetters()
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", "process")
    out = t.transform(df).collect()
    dead = t.deadLetters()
    assert len(out) == len(thread_out) == 14
    assert dead.num_rows == thread_dead.num_rows == 2
    assert dead.column("error_class").to_pylist() == \
        thread_dead.column("error_class").to_pylist()
    # dead letters carry the ORIGINAL payloads of exactly the bad rows
    assert sorted(len(v) for v in dead.column("x").to_pylist()) == [1, 1]
    np.testing.assert_array_equal(
        np.asarray([r.y for r in out], np.float32),
        np.asarray([r.y for r in thread_out], np.float32))
    metrics.run_stats.reset()


def test_process_backend_chaos_decode_all_rows_dead(monkeypatch):
    """Chaos ``decode`` fires IN THE POOL CHILD (the plan ships with each
    task): prob=1/once=False fails every chunk and every row-fallback
    attempt, so the whole input quarantines and the circuit breaker
    trips — deterministic proof the site is live across the process
    boundary."""
    from sparkdl_tpu.runner import chaos, metrics
    from sparkdl_tpu.runner.failures import QuarantineOverflowError
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", "process")
    df, _ = ragged_df(bad_rows=())
    t = quarantining_transformer()
    chaos.install(chaos.FaultPlan(
        [chaos.Fault("decode", "fatal", prob=1.0, once=False)]))
    try:
        with pytest.raises(QuarantineOverflowError):
            t.transform(df).collect()
    finally:
        chaos.uninstall()
        metrics.run_stats.reset()


def test_process_backend_chaos_once_semantics(tmp_path, monkeypatch):
    """once=True with a plan ``state_dir`` holds ACROSS pool children
    (marker files, exactly like supervised gang restarts): one chunk
    fails and row-recovers, everything else decodes clean — full output,
    zero dead letters."""
    from sparkdl_tpu.runner import chaos, metrics
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", "process")
    df, rows = ragged_df(bad_rows=())
    t = quarantining_transformer()
    chaos.install(chaos.FaultPlan(
        [chaos.Fault("decode", "fatal", prob=1.0, once=True)],
        state_dir=str(tmp_path)))
    try:
        out = t.transform(df).collect()
        assert len(out) == 16
        assert t.deadLetters().num_rows == 0
        # the once-marker landed exactly once, from whichever child fired
        assert [f for f in os.listdir(tmp_path) if f.endswith(".fired")]
    finally:
        chaos.uninstall()
        metrics.run_stats.reset()


def test_process_backend_workers0_inline(monkeypatch):
    """workers=0 under SPARKDL_DECODE_BACKEND=process still maps inline
    on the consumer thread (no pool of either kind) with correct output."""
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", "process")
    monkeypatch.setenv("SPARKDL_DECODE_WORKERS", "0")
    df, vals = vector_df(11, parts=3)
    t = sdl.XlaTransformer(inputCol="x", outputCol="y",
                           fn=lambda b: b * 2.0 + 1.0, batchSize=4)
    got = np.asarray([r.y for r in t.transform(df).collect()], np.float32)
    np.testing.assert_allclose(got, vals * 2.0 + 1.0, rtol=1e-6)


def test_process_backend_without_spec_degrades_to_threads(
        monkeypatch, caplog):
    """A scorer with no decoder_spec (decoder closes over un-picklable
    state) must WARN and decode on threads, not crash the stream."""
    import logging

    from sparkdl_tpu.transformers import streaming as streaming_mod
    orig_init = streaming_mod.StreamScorer.__init__

    def no_spec_init(self, *a, **kw):
        kw.pop("decoder_spec", None)
        orig_init(self, *a, **kw)

    monkeypatch.setattr(streaming_mod.StreamScorer, "__init__",
                        no_spec_init)
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", "process")
    df, vals = vector_df(9, parts=2)
    t = sdl.XlaTransformer(inputCol="x", outputCol="y",
                           fn=lambda b: b + 1.0, batchSize=4)
    with caplog.at_level(logging.WARNING, logger="sparkdl_tpu.streaming"):
        got = np.asarray([r.y for r in t.transform(df).collect()],
                         np.float32)
    np.testing.assert_allclose(got, vals + 1.0, rtol=1e-6)
    assert any("decoder_spec" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# Fused-feed policy regressions (ISSUE 7 review round)
# ---------------------------------------------------------------------------

def _image_df(imgs, parts=1):
    structs = [imageIO.imageArrayToStruct(im, origin=f"m{i}")
               for i, im in enumerate(imgs)]
    return sdl.DataFrame.fromArrow(
        pa.table({"image": pa.array(structs, type=imageIO.imageSchema)}),
        numPartitions=parts)


def test_fused_feed_requires_static_input_size():
    """No ``inputSize`` → target pinned per partition at decode time,
    which the once-traced prologue cannot know: fused mode must stand
    down to the host pack path. Regression: a mixed-size partition whose
    later chunk is uniformly SMALLER than the pinned target used to ship
    at native size with nothing ever resizing it."""
    rng = np.random.default_rng(5)
    imgs = [rng.integers(0, 256, (16, 16, 3), np.uint8) for _ in range(8)]
    imgs += [rng.integers(0, 256, (8, 8, 3), np.uint8) for _ in range(4)]
    df = _image_df(imgs, parts=1)
    t = sdl.XlaImageTransformer(inputCol="image", outputCol="f",
                                fn=lambda b: b.mean(axis=(1, 2)),
                                batchSize=4)  # chunk 2 is uniform 8x8
    got = np.asarray([r.f for r in t.transform(df).collect()])
    assert got.shape == (12, 3)
    # reference: every row host-packed to the partition-pinned 16x16
    expect = imageIO.imageColumnToNHWC(
        pa.array([imageIO.imageArrayToStruct(im) for im in imgs],
                 type=imageIO.imageSchema), 16, 16, dtype=np.uint8,
        channelOrder="RGB").astype(np.float32).mean(axis=(1, 2))
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-4)


@pytest.mark.parametrize("backend", ["thread", "process"])
def test_fused_row_fallback_keeps_mixed_size_rows(monkeypatch, backend):
    """Quarantine row-fallback under the fused feed: a chunk mixing
    stored sizes (all <= target) plus ONE corrupt row must dead-letter
    exactly the corrupt row — the 1-row re-decodes pack at target, so
    valid minority-size rows can't deviate from the modal shape."""
    from sparkdl_tpu.runner import metrics
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", backend)
    rng = np.random.default_rng(6)
    structs = []
    for i in range(8):
        edge = 8 if i % 2 else 6  # mixed sizes -> no zero-copy view
        structs.append(imageIO.imageArrayToStruct(
            rng.integers(0, 256, (edge, edge, 3), np.uint8),
            origin=f"m{i}"))
    structs[3] = dict(structs[3], data=b"\x00" * 5)  # corrupt payload
    df = sdl.DataFrame.fromArrow(
        pa.table({"image": pa.array(structs, type=imageIO.imageSchema)}),
        numPartitions=1)
    t = sdl.XlaImageTransformer(inputCol="image", outputCol="f",
                                fn=lambda b: b.mean(axis=(1, 2)),
                                inputSize=(16, 16), batchSize=8,
                                onError="quarantine")
    out = t.transform(df).collect()
    dead = t.deadLetters()
    assert len(out) == 7
    assert dead.num_rows == 1
    assert [r["origin"] for r in dead.column("image").to_pylist()] == ["m3"]
    metrics.run_stats.reset()


@pytest.mark.parametrize("backend", ["thread", "process"])
def test_wire_shape_cap_bounds_native_sizes(monkeypatch, backend):
    """SPARKDL_MAX_WIRE_SHAPES: each distinct native size a fused stage
    ships is one XLA compilation, so past the cap chunks must pack at the
    target shape. Cap=1 + three uniform-size runs → exactly one native
    size on the wire, correct outputs for all rows."""
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", backend)
    monkeypatch.setenv("SPARKDL_MAX_WIRE_SHAPES", "1")
    rng = np.random.default_rng(9)
    imgs = [rng.integers(0, 256, (e, e, 3), np.uint8)
            for e in (6, 6, 8, 8, 10, 10)]  # 3 uniform-size chunk runs
    df = _image_df(imgs, parts=1)
    t = sdl.XlaImageTransformer(inputCol="image", outputCol="f",
                                fn=lambda b: b.mean(axis=(1, 2)),
                                inputSize=(16, 16), batchSize=2)
    events.reset()
    got = np.asarray([r.f for r in t.transform(df).collect()])
    assert got.shape == (6, 3)
    # the wire evidence is the put spans' byte ledger: u8 feeds of
    # (2,6,6,3)/(2,8,8,3)/(2,10,10,3) vs packed target (2,16,16,3)
    put_bytes = sorted(e["bytes"] for e in events.get_recorder().ring
                       if e["name"] == "put" and e["ph"] == "E")
    native = [b for b in put_bytes if b < 2 * 16 * 16 * 3]
    assert len(native) == 1, put_bytes  # only the FIRST size went native


@pytest.mark.parametrize("backend", ["thread", "process"])
def test_wire_budget_not_stranded_on_undeliverable_chunk(monkeypatch,
                                                         backend):
    """A chunk that is metadata-uniform but whose zero-copy view DECLINES
    (truncated payload fails the offsets check) must not consume a
    wire-shape budget slot: with cap=1, a later legitimately shippable
    size still goes native instead of finding the budget stranded on a
    shape that only ever packs."""
    from sparkdl_tpu.runner import metrics
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", backend)
    monkeypatch.setenv("SPARKDL_MAX_WIRE_SHAPES", "1")
    rng = np.random.default_rng(11)
    structs = [imageIO.imageArrayToStruct(
        rng.integers(0, 256, (8, 8, 3), np.uint8), origin=f"a{i}")
        for i in range(4)]
    # metadata says (8, 8, 3) but the payload is truncated: uniform-size
    # scan passes, the view's row-bytes check declines, the pack raises
    # -> row-fallback dead-letters exactly this row
    structs[1] = dict(structs[1], data=b"\x00" * 5)
    structs += [imageIO.imageArrayToStruct(
        rng.integers(0, 256, (6, 6, 3), np.uint8), origin=f"b{i}")
        for i in range(4)]
    df = sdl.DataFrame.fromArrow(
        pa.table({"image": pa.array(structs, type=imageIO.imageSchema)}),
        numPartitions=1)
    t = sdl.XlaImageTransformer(inputCol="image", outputCol="f",
                                fn=lambda b: b.mean(axis=(1, 2)),
                                inputSize=(16, 16), batchSize=4,
                                onError="quarantine")
    events.reset()
    out = t.transform(df).collect()
    assert len(out) == 7
    assert [r["origin"] for r in
            t.deadLetters().column("image").to_pylist()] == ["a1"]
    # the clean 6x6 chunk must hold the one budget slot: its put ships
    # the native (4, 6, 6, 3) u8 view, not the packed (4, 16, 16, 3)
    put_bytes = sorted(e["bytes"] for e in events.get_recorder().ring
                       if e["name"] == "put" and e["ph"] == "E")
    assert 4 * 6 * 6 * 3 in put_bytes, put_bytes
    metrics.run_stats.reset()
