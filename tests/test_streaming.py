"""Streaming inference engine tests (ISSUE 3): cross-partition pipelining,
parallel decode overlap, per-stage flight-recorder spans, score smoke.

The runtime-level window mechanics are pinned in test_runtime.py
(run_stream meta threading, the no-drain dispatch count); this file pins
the TRANSFORMER-level engine: the StreamScorer that chunks partitions,
decodes on the pool, feeds one continuous device stream, and reassembles
partition outputs with the encode on an overlap worker.
"""

import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest

import sparkdl_tpu as sdl
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.runner import events

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def vector_df(n, parts, d=3):
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(n, d)).astype(np.float32)
    return sdl.DataFrame.fromPydict({"x": vals.tolist()},
                                    numPartitions=parts), vals


def test_stream_scorer_cross_partition_equivalence():
    """Many partitions (including filter-emptied ones mid-stream) through
    ONE continuous device stream: outputs land on the right partitions in
    the right order, identical to the single-partition path."""
    df, vals = vector_df(37, parts=9)
    fn = lambda b: b * 2.0 + 1.0
    t = sdl.XlaTransformer(inputCol="x", outputCol="y", fn=fn, batchSize=4)
    got = np.asarray([r.y for r in t.transform(df).collect()], np.float32)
    np.testing.assert_allclose(got, vals * 2.0 + 1.0, rtol=1e-6)

    single = sdl.DataFrame.fromPydict({"x": vals.tolist()}, numPartitions=1)
    got1 = np.asarray([r.y for r in t.transform(single).collect()],
                      np.float32)
    np.testing.assert_allclose(got, got1)

    # empty partitions interleaved: partition granularity preserved
    emptied = df.filter(lambda r: abs(r.x[0]) < 0.7)
    kept = [v for v in vals if abs(v[0]) < 0.7]
    out = t.transform(emptied)
    rows = out.collect()
    assert len(rows) == len(kept)
    np.testing.assert_allclose(
        np.asarray([r.y for r in rows], np.float32),
        np.asarray(kept, np.float32) * 2.0 + 1.0, rtol=1e-6)


def test_transformer_no_drain_at_partition_boundaries():
    """Dispatch-counting acceptance pin, transformer level: after the first
    partition's output is materialized, the engine has already dispatched
    chunks from LATER partitions — the in-flight window crossed the
    boundary instead of draining (the old per-partition mapBatches op
    dispatched exactly its own partition's chunks)."""
    df, _ = vector_df(24, parts=6)  # 6 partitions x 4 rows
    t = sdl.XlaTransformer(inputCol="x", outputCol="y",
                           fn=lambda b: b * 3.0, batchSize=4)
    runner = t._get_runner()
    dispatched = []
    inner = runner._jitted
    runner._jitted = lambda b: (dispatched.append(1), inner(b))[1]
    try:
        parts = t.transform(df).iterPartitions()
        first = next(parts)
        assert first.num_rows == 4
        # prefetch=2 window: >= 3 chunks (partitions 0,1,2) dispatched
        # before partition 0's output batch was even assembled
        assert len(dispatched) >= 3, dispatched
        rest = list(parts)
        assert len(rest) == 5
        assert len(dispatched) == 6
    finally:
        runner._jitted = inner


def test_pipelined_overlap_beats_serial_sum(monkeypatch):
    """ISSUE 3 acceptance: deliberately slow decode + slow fn — pipelined
    scoring wall-clock must beat the serial sum with generous margin
    (< 0.8x). Sleeps, not compute, so the bound is load-stable."""
    from sparkdl_tpu.transformers import tensor as tensor_mod

    n_chunks, decode_s, fn_s = 8, 0.08, 0.04
    monkeypatch.setenv("SPARKDL_DECODE_WORKERS", "2")
    orig_decode = tensor_mod.columnToNdarray

    def slow_decode(col, shape, **kw):
        time.sleep(decode_s)
        return orig_decode(col, shape, **kw)

    monkeypatch.setattr(tensor_mod, "columnToNdarray", slow_decode)

    def slow_fn(b):
        def cb(x):
            time.sleep(fn_s)
            return np.asarray(x) * 2.0
        return jax.pure_callback(
            cb, jax.ShapeDtypeStruct(b.shape, b.dtype), b)

    df, vals = vector_df(n_chunks * 4, parts=n_chunks)  # 1 chunk/partition
    t = sdl.XlaTransformer(inputCol="x", outputCol="y", fn=slow_fn,
                           batchSize=4)
    # compile + warm outside the timed window (serial sum has no compile
    # either); schema probe also lands here
    t.transform(df.limit(4)).collect()

    t0 = time.perf_counter()
    rows = t.transform(df).collect()
    wall = time.perf_counter() - t0
    assert len(rows) == n_chunks * 4
    np.testing.assert_allclose(
        np.asarray([r.y for r in rows], np.float32), vals * 2.0, rtol=1e-5)

    serial_sum = n_chunks * (decode_s + fn_s)  # 0.96s
    assert wall < 0.8 * serial_sum, \
        f"pipelined wall {wall:.3f}s vs serial sum {serial_sum:.3f}s"


def test_all_scoring_stages_emit_spans():
    """Every stage of the scoring pipeline lands in the flight recorder:
    decode/pad/put/dispatch/fetch on the feed side, encode on the overlap
    worker — the breakdown scripts/score_smoke.py prints."""
    rec = events.reset()
    try:
        df, _ = vector_df(12, parts=3)
        t = sdl.XlaTransformer(inputCol="x", outputCol="y",
                               fn=lambda b: b + 1.0, batchSize=4)
        assert len(t.transform(df).collect()) == 12
        evs = rec.tail()
        for stage in ("decode", "pad", "put", "dispatch", "fetch",
                      "encode"):
            ends = [e for e in evs
                    if e["name"] == stage and e["ph"] == "E"]
            assert len(ends) >= 3, f"missing spans for stage {stage}"
            assert all("dur_s" in e for e in ends)
    finally:
        events.reset()


def test_image_transformer_streams_across_partitions():
    """The image path (uint8 feed, image-mode output) through the
    cross-partition engine: struct outputs land on the right rows."""
    # constant-valued rows so output pixel values pin row ORDER across the
    # partition reassembly (model-output structs carry no origin)
    imgs = [np.full((8, 8, 3), i * 20, np.uint8) for i in range(10)]
    structs = [imageIO.imageArrayToStruct(im, origin=f"mem://{i}")
               for i, im in enumerate(imgs)]
    df = sdl.DataFrame.fromArrow(
        pa.table({"image": pa.array(structs, type=imageIO.imageSchema)}),
        numPartitions=5)
    t = sdl.XlaImageTransformer(
        inputCol="image", outputCol="out", fn=lambda b: b * 0.5,
        inputSize=(8, 8), batchSize=2, outputMode="image")
    rows = t.transform(df).collect()
    assert len(rows) == 10
    assert all(r.out["height"] == 8 for r in rows)
    got = [np.frombuffer(r.out["data"], np.uint8)[0] for r in rows]
    assert got == [i * 10 for i in range(10)]


@pytest.mark.slow
def test_score_smoke_script():
    """scripts/score_smoke.py end-to-end: streaming scoring + per-stage
    breakdown + a persistent compile-cache HIT in the second process."""
    import json
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "score_smoke.py")],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, \
        f"stdout={proc.stdout[-2000:]}\nstderr={proc.stderr[-2000:]}"
    line = [ln for ln in proc.stdout.strip().splitlines()
            if ln.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["ok"] is True
    assert rec["second_run"]["compile_cache"]["hits"] > 0
    assert set(rec["first_run"]["stages"]) >= {
        "decode", "pad", "put", "dispatch", "fetch", "encode"}


def test_encode_backpressure_bounds_raw_output_backlog():
    """A slow encode must throttle the consumer loop: fetched-but-not-
    encoded RAW float32 chunks are bounded by the backlog window, never a
    whole partition (the O(window·batchSize) host-memory contract)."""
    from sparkdl_tpu.transformers.streaming import StreamScorer

    pulled = []

    class StubRunner:
        prefetch = 2

        def run_stream(self, stream):
            for i, (arr, entry) in enumerate(stream):
                pulled.append(i)
                yield np.asarray([[float(i)]], np.float32), entry

    encode_backlog_seen = []
    done = [0]

    def slow_encode(result):
        # raw backlog at encode start = chunks pulled - chunks encoded
        encode_backlog_seen.append(len(pulled) - done[0])
        time.sleep(0.02)
        done[0] += 1
        return pa.array([float(result[0][0])])

    n_chunks = 12
    batch = pa.RecordBatch.from_arrays(
        [pa.array([float(i) for i in range(n_chunks)])], ["x"])
    scorer = StreamScorer(
        StubRunner(), "y",
        chunk_thunks=lambda rb: [
            lambda i=i: np.asarray([[float(i)]], np.float32)
            for i in range(rb.num_rows)],
        encode=slow_encode,
        empty_array=lambda: pa.array([], type=pa.float64()),
        decode_workers=0)
    [out] = list(scorer(iter([batch])))
    assert out.column(out.schema.get_field_index("y")).to_pylist() \
        == [float(i) for i in range(n_chunks)]
    # without backpressure the stub's instant fetches would pile all 12
    # raw chunks behind the first sleeping encode (backlog ≈ n_chunks)
    assert max(encode_backlog_seen) <= StubRunner.prefetch + 2, \
        encode_backlog_seen
