"""Serving fleet front door (ISSUE 20): health-gated routing, cross-
engine drain/resume failover, shadow re-admission after unclean replica
death, hedged requests, load shedding, and the fleet chaos/telemetry/
introspection surfaces — all jax-free (StubBackend replicas).

The heavy end-to-end proof (≥3 replicas, Llama backends, paged + un-
paged, injected replica_dead + DOOMED drain under concurrent load,
radix-vs-round-robin hit-rate) lives in ``scripts/fleet_chaos_smoke.py``
behind the ``slow`` marker; these tests keep each mechanism pinned
individually and cheap.
"""

import time

import pytest

from sparkdl_tpu.runner import chaos, failures, telemetry
from sparkdl_tpu.serving import (DEAD, DEGRADED, DOOMED, HEALTHY,
                                 SNAPSHOT_VERSION, EngineFleet,
                                 FleetDegradedError, FleetRequest,
                                 FleetRoutingError, GenerationEngine,
                                 RequestShedError,
                                 SnapshotIncompatibleError, StubBackend,
                                 fleet_debug_state, serving_snapshot)
from sparkdl_tpu.serving.prefix import (DIGEST_GRANULE, PrefixCache,
                                        RadixPrefixCache,
                                        prompt_digest_chain)


def _mk(slots=2, max_len=128, *, paged=False, pool_blocks=80, **kw):
    if paged:
        kw.setdefault("block_size", 4)
        kw.setdefault("pool_blocks", pool_blocks)
    be = StubBackend(slots, max_len, vocab_size=997, **kw)
    return GenerationEngine(be, queue_capacity=32)


def _reference(prompt, max_new):
    eng = _mk()
    r = eng.submit(prompt, max_new_tokens=max_new, block=False)
    eng.run_until_idle()
    return r.tokens


# ---------------------------------------------------------------------------
# routing: radix-aware placement, round-robin comparator, affinity, shed
# ---------------------------------------------------------------------------

class TestFleetRouting:
    def test_radix_routes_prefix_family_to_resident_replica(self):
        """The second request of a prefix family follows the first to
        the replica whose residency shadow holds the family head —
        co-location is what makes the fleet-wide hit-rate beat
        round-robin."""
        fleet = EngineFleet([_mk() for _ in range(3)], routing="radix")
        head = list(range(1, 1 + 2 * DIGEST_GRANULE))
        a1 = fleet.submit(head + [500], max_new_tokens=2)
        home = a1.replica
        a2 = fleet.submit(head + [600, 601], max_new_tokens=2)
        assert a2.replica == home
        fleet.run_until_idle()
        assert a1.result(1) and a2.result(1)

    def test_round_robin_comparator_rotates(self):
        fleet = EngineFleet([_mk() for _ in range(2)],
                            routing="round_robin")
        seen = [fleet.submit([i + 1] * 4, max_new_tokens=1).replica
                for i in range(4)]
        fleet.run_until_idle()
        assert seen[0] != seen[1] and seen[:2] == seen[2:]

    def test_session_affinity_pins_replica(self):
        fleet = EngineFleet([_mk() for _ in range(3)])
        first = fleet.submit([1, 2, 3], max_new_tokens=1, session="s1")
        for prompt in ([50, 60], [70, 80, 90]):
            fr = fleet.submit(prompt, max_new_tokens=1, session="s1")
            assert fr.replica == first.replica
        fleet.run_until_idle()

    def test_shed_past_queue_depth_under_burn_is_classified(self):
        """Overload shedding: queue past SPARKDL_FLEET_SHED_QUEUE while
        the replica burns ≥1x → RequestShedError (retryable), counted,
        never enqueued."""
        fleet = EngineFleet([_mk(slots=1)], shed_queue=1, min_replicas=1)
        for i in range(3):  # 1 in slot, 2 queued — past the depth
            fleet.submit([i + 1, 2], max_new_tokens=4)
        rep = fleet._replicas["replica0"]
        rep.burn.record_outcome(False)  # error budget torched → burn >> 1
        with pytest.raises(RequestShedError) as ei:
            fleet.submit([9, 9], max_new_tokens=2)
        assert failures.classify_exception(ei.value) == "retryable"
        assert fleet.stats["shed"] == 1
        fleet.run_until_idle()
        assert fleet.stats["completed"] == 3

    def test_unknown_routing_policy_rejected(self):
        with pytest.raises(ValueError):
            EngineFleet([_mk()], routing="random")


# ---------------------------------------------------------------------------
# failover: DOOMED drain → cross-engine resume; DEAD → shadow re-admit
# ---------------------------------------------------------------------------

class TestFleetFailover:
    @pytest.mark.parametrize("paged", [False, True])
    def test_doom_drain_readmits_token_identical(self, paged):
        """Drain replica A mid-stream, re-admit on survivor B: the
        greedy stream is bit-identical to an uninterrupted single-
        engine run and the client-streamed sequence has zero duplicated
        and zero lost tokens (delivery-cursor audit)."""
        fleet = EngineFleet([_mk(paged=paged) for _ in range(2)],
                            min_replicas=1)
        prompt = list(range(1, 20))
        streamed = []
        fr = fleet.submit(prompt, max_new_tokens=12,
                          stream_cb=lambda fr, t: streamed.append(t))
        for _ in range(3):
            fleet.step()
        pre = list(streamed)
        assert pre, "expected tokens streamed before the drain"
        victim = fr.replica
        fleet.doom_replica(victim, "test")
        fleet.run_until_idle()
        assert fr.result(1) == _reference(prompt, 12)
        assert streamed == fr.tokens  # zero dup, zero loss
        assert streamed[:len(pre)] == pre
        assert fr.hops == 1 and fr.replica != victim
        assert fleet.replica_state(victim) in (DOOMED, DEAD)
        assert fleet.stats["readmissions"] == 1

    def test_unclean_death_readmits_from_shadow_state(self):
        """A replica that dies WITHOUT draining: the router re-admits
        from its own shadow (prompt + delivery cursor) — undelivered
        tokens regrow by greedy determinism, delivered ones never
        repeat."""
        fleet = EngineFleet([_mk() for _ in range(3)])
        prompt = list(range(5, 40))
        streamed = []
        fr = fleet.submit(prompt, max_new_tokens=10,
                          stream_cb=lambda fr, t: streamed.append(t))
        for _ in range(4):
            fleet.step()
        assert streamed
        victim = fr.replica
        fleet.kill_replica(victim)
        fleet.run_until_idle()
        assert fr.result(1) == _reference(prompt, 10)
        assert streamed == fr.tokens
        assert fleet.replica_state(victim) == DEAD
        assert fleet.stats["replica_deaths"] == 1
        assert fleet.stats["readmissions"] == 1

    def test_min_replicas_floor_fails_closed_classified(self):
        fleet = EngineFleet([_mk() for _ in range(2)], min_replicas=2)
        fleet.kill_replica("replica0")
        with pytest.raises(FleetDegradedError) as ei:
            fleet.submit([1, 2], max_new_tokens=2)
        assert "SPARKDL_FLEET_MIN_REPLICAS" in str(ei.value)
        assert failures.classify_exception(ei.value) == "retryable"
        assert failures.classify_text(
            f"FleetDegradedError: {ei.value}") == "retryable"

    def test_double_drain_and_empty_fleet_idempotent(self):
        fleet = EngineFleet([_mk() for _ in range(2)], min_replicas=0)
        fr = fleet.submit([1, 2, 3], max_new_tokens=4)
        assert fleet.drain() == 2
        assert fleet.drain() == 0  # second drain: nothing left to drain
        fleet.doom_replica("replica0")  # doom-after-drain: no-op
        assert fr.state == "failed"  # no survivor existed to re-admit on
        assert isinstance(fr.error, FleetDegradedError)
        empty = EngineFleet([], min_replicas=0)
        assert empty.drain() == 0 and empty.drain() == 0

    def test_readmission_cascade_respects_floor(self):
        """Survivor drains re-admit onto remaining replicas while any
        exist; work still in flight when the LAST replica drains fails
        closed with the classified error, never hangs."""
        fleet = EngineFleet([_mk(slots=1) for _ in range(2)],
                            min_replicas=1)
        frs = [fleet.submit([i + 1, 3], max_new_tokens=64)
               for i in range(3)]
        fleet.step()
        fleet.doom_replica("replica0")
        fleet.doom_replica("replica1")
        fleet.run_until_idle()
        for fr in frs:
            assert fr.done and fr.state == "failed"
            assert isinstance(fr.error, FleetDegradedError)


# ---------------------------------------------------------------------------
# snapshot portability (satellite: self-contained version-tagged resume)
# ---------------------------------------------------------------------------

class TestSnapshotPortability:
    def test_snapshot_dict_resumes_on_foreign_engine(self):
        eng = _mk()
        r = eng.submit([3, 1, 4, 1, 5], max_new_tokens=10, block=False)
        eng.run_until_idle()
        half = r.snapshot()
        half["tokens"] = half["tokens"][:4]
        half["delivered"] = 4
        other = _mk()
        r2 = other.resume(half)
        other.run_until_idle()
        assert r2.tokens == r.tokens  # regrown tail identical
        assert r2.delivered == 10

    def test_stale_version_rejected_classified(self):
        eng = _mk()
        r = eng.submit([1, 2], max_new_tokens=2, block=False)
        eng.run_until_idle()
        snap = r.snapshot()
        snap["version"] = SNAPSHOT_VERSION + 1
        other = _mk()
        with pytest.raises(SnapshotIncompatibleError) as ei:
            other.resume(snap)
        assert failures.classify_exception(ei.value) == "fatal"

    @pytest.mark.parametrize("mutate", [
        lambda s: s.pop("prompt"),
        lambda s: s.update(prompt=[]),
        lambda s: s.update(delivered=10 ** 6),
        lambda s: s.update(delivered=-1),
    ])
    def test_foreign_or_corrupt_snapshot_rejected(self, mutate):
        eng = _mk()
        r = eng.submit([1, 2], max_new_tokens=2, block=False)
        eng.run_until_idle()
        snap = r.snapshot()
        mutate(snap)
        with pytest.raises(SnapshotIncompatibleError):
            _mk().resume(snap)

    def test_resume_onto_small_pool_waits_fifo_not_reject(self):
        """A drained snapshot re-admitted to a paged replica whose pool
        is coverable but currently FULL queues FIFO behind the running
        work instead of being rejected."""
        src = _mk(paged=True)
        r = src.submit(list(range(1, 10)), max_new_tokens=8, block=False)
        for _ in range(4):
            src.step()
        snaps = src.drain(timeout=5)
        assert any(s is r for s in snaps)
        # 9 usable blocks of 4: a 17-token hog pins 5(+1 frontier);
        # the resumed request needs more than what's left RIGHT NOW but
        # well under the pool — must wait, not reject
        dst = GenerationEngine(StubBackend(2, 64, vocab_size=997,
                                           block_size=4, pool_blocks=10),
                               queue_capacity=8)
        hog = dst.submit(list(range(40, 57)), max_new_tokens=6,
                         block=False)
        dst.step()
        r2 = dst.resume(r)
        assert r2.state == "queued"  # admitted, not RequestRejected
        dst.run_until_idle()
        assert hog.result(1)
        assert r2.result(1) == _reference(list(range(1, 10)), 8)


# ---------------------------------------------------------------------------
# hedged requests
# ---------------------------------------------------------------------------

class TestHedging:
    def test_hedge_fires_on_degraded_primary_loser_cancelled(self):
        """A first-token-starved request on a DEGRADED replica grows a
        speculative twin; first token wins, the loser is CANCELLED
        (never quarantined, never an error), and the delivery cursor
        admits no duplicate tokens."""
        fleet = EngineFleet([_mk() for _ in range(2)],
                            hedge_ttft_s=0.01)
        prompt = [7, 7, 7]
        fr = fleet.submit(prompt, max_new_tokens=6)
        primary = fr.replica
        fleet._replicas[primary].burn.record_outcome(False)  # DEGRADED
        time.sleep(0.03)
        fleet._tick()  # health transition + hedge arm
        assert fleet.stats["hedges_fired"] == 1
        assert fr.hedges == 1
        fleet.run_until_idle()
        assert fr.result(1) == _reference(prompt, 6)
        assert fr.delivered == len(fr.tokens) == 6  # cursor audit
        stats = [fleet.engine(n).stats for n in fleet.replica_names()]
        assert sum(s["quarantined"] for s in stats) == 0
        assert sum(s["cancelled"] for s in stats) == 1  # the loser
        assert fleet.stats["failed"] == 0

    def test_no_hedge_when_disabled_or_healthy(self):
        fleet = EngineFleet([_mk() for _ in range(2)], hedge_ttft_s=0.0)
        fr = fleet.submit([1, 2], max_new_tokens=4)
        time.sleep(0.02)
        fleet._tick()
        assert fleet.stats["hedges_fired"] == 0
        fleet.run_until_idle()
        assert fr.result(1)


# ---------------------------------------------------------------------------
# health assessment
# ---------------------------------------------------------------------------

class TestHealthStates:
    def test_burn_degrades_then_cooldown_recovers(self):
        fleet = EngineFleet([_mk() for _ in range(2)])
        rep = fleet._replicas["replica0"]
        rep.burn.record_outcome(False)
        fleet._tick()
        assert rep.state == DEGRADED
        # decay the burn window and the cooldown clock, then re-assess
        rep.burn.window_s = 0.001
        rep.t_state -= 10.0
        time.sleep(0.005)
        fleet._tick()
        assert rep.state == HEALTHY

    def test_circuit_breaker_dooms_after_consecutive_failures(self):
        fleet = EngineFleet([_mk() for _ in range(2)],
                            breaker_failures=2, min_replicas=1)
        rep = fleet._replicas["replica0"]
        rep.consecutive_failures = 2
        fleet._tick()
        assert rep.state in (DOOMED, DEAD) or rep.drained
        assert fleet.replicas_healthy == 1

    def test_fatal_engine_goes_dead(self):
        fleet = EngineFleet([_mk() for _ in range(2)])
        fleet.engine("replica1")._fatal = RuntimeError("device gone")
        fleet._tick()
        assert fleet.replica_state("replica1") == DEAD
        assert fleet.replicas_healthy == 1


# ---------------------------------------------------------------------------
# chaos plumbing
# ---------------------------------------------------------------------------

class TestFleetChaos:
    def test_replica_dead_requires_fleet_site(self):
        with pytest.raises(ValueError):
            chaos.Fault(site="serve_prefill", kind="replica_dead",
                        at_step=1)
        f = chaos.Fault(site="fleet_route", kind="replica_dead",
                        at_step=1)
        assert f.site in chaos.FLEET_SITES

    def test_injected_replica_dead_at_route_kills_chosen_replica(self):
        """A replica_dead fault at fleet_route kills the replica the
        router WOULD have used; the submission itself still succeeds on
        a survivor and classification calls the injection retryable."""
        chaos.install(chaos.FaultPlan([
            chaos.Fault(site="fleet_route", kind="replica_dead",
                        at_step=2)]))
        try:
            fleet = EngineFleet([_mk() for _ in range(3)])
            a = fleet.submit([1, 2], max_new_tokens=2)
            b = fleet.submit([3, 4], max_new_tokens=2)  # fires here
            fleet.run_until_idle()
            assert a.result(1) and b.result(1)
            assert fleet.stats["replica_deaths"] == 1
            assert fleet.replicas_healthy == 2
            assert failures.classify_exception(
                chaos.InjectedReplicaDead("x")) == "retryable"
        finally:
            chaos.uninstall()


# ---------------------------------------------------------------------------
# residency digests (prefix.py)
# ---------------------------------------------------------------------------

class TestResidencyDigest:
    def test_lru_cache_digest_matches_prompt_chain(self):
        pc = PrefixCache(budget_bytes=1 << 20)
        prompt = list(range(1, 50))
        pc.put(tuple(prompt[:32]), payload=None, nbytes=64)
        dig = pc.residency_digest()
        assert dig["granule"] == DIGEST_GRANULE
        chain = prompt_digest_chain(prompt, dig["granule"])
        hits = [n for n, h in chain if h in dig["heads"]]
        assert hits == [16, 32]  # both whole granules of the entry

    def test_radix_digest_walks_trie(self):
        from sparkdl_tpu.serving import BlockAllocator
        alloc = BlockAllocator(64)
        rx = RadixPrefixCache(alloc, block_size=4)
        toks = tuple(range(1, 13))
        blocks = alloc.allocate(3)
        rx.insert(toks, blocks)
        dig = rx.residency_digest()
        assert dig["granule"] == 4
        chain = prompt_digest_chain(list(toks) + [99], 4)
        assert [n for n, h in chain if h in dig["heads"]] == [4, 8, 12]

    def test_engine_exposes_backend_digest(self):
        eng = _mk()  # unpaged stub carries a PrefixCache
        r = eng.submit(list(range(1, 40)), max_new_tokens=2, block=False)
        eng.run_until_idle()
        assert r.result(1)
        dig = eng.residency_digest()
        assert dig is not None and dig["heads"]


# ---------------------------------------------------------------------------
# telemetry + introspection
# ---------------------------------------------------------------------------

class TestFleetObservability:
    def test_fleet_metrics_reach_registry(self):
        telemetry.reset()
        telemetry.start()
        try:
            fleet = EngineFleet([_mk() for _ in range(2)])
            fr = fleet.submit(list(range(1, 12)), max_new_tokens=8)
            fleet.step()
            fleet.kill_replica(fr.replica)
            fleet.run_until_idle()
            assert fr.result(1)
            snap = telemetry.registry().snapshot()
            assert snap["gauges"]["fleet_replicas_healthy"]["value"] >= 1
            assert snap["counters"]["fleet_readmissions_total"] >= 1
        finally:
            telemetry.reset()

    def test_serving_snapshot_carries_fleet_view(self):
        fleet = EngineFleet([_mk() for _ in range(2)])
        fr = fleet.submit([1, 2, 3], max_new_tokens=2)
        fleet.run_until_idle()
        assert fr.result(1)
        state = fleet_debug_state(fleet)
        assert set(state["replicas"]) == {"replica0", "replica1"}
        for row in state["replicas"].values():
            assert row["state"] == HEALTHY
            assert "shadow_heads" in row and "burn" in row
        snap = serving_snapshot()
        assert snap["n_fleets"] >= 1
        assert any(f.get("stats", {}).get("completed", 0) >= 1
                   for f in snap["fleets"] if "error" not in f)

    def test_fleet_request_repr_and_cancel(self):
        fleet = EngineFleet([_mk()], min_replicas=1)
        fr = fleet.submit([1, 2, 3], max_new_tokens=50)
        assert "FleetRequest" in repr(fr)
        fr.cancel()
        fleet.run_until_idle()
        assert fr.done and fr.state == "failed"
        assert fleet.stats["cancelled"] == 1
        assert fleet.stats["failed"] == 0
        assert fleet.engine("replica0").stats["quarantined"] == 0
